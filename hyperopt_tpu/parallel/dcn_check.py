"""Two-process DCN execution check for the sharded suggest program.

The multihost story (SURVEY.md SS5 'distributed communication backend')
promises that :func:`hyperopt_tpu.parallel.sharded.sharded_suggest` spans
hosts: all processes join one ``jax.distributed`` runtime, the candidate
sweep shards over every device of every host, and the EI argmax-allgather
rides DCN between processes.  This module EXECUTES that path the way the
reference tests multi-node -- by running the real thing small: launched as
one worker per process (``python -m hyperopt_tpu.parallel.dcn_check <pid>
<port>``), each worker forces ``--n-local`` virtual CPU devices, joins a
2-process runtime (2 x n-local global devices), and runs the REAL APIs
over the global mesh on identical seeded histories:

* ``sharded_suggest`` on a continuous space (stage A) and on a MIXED
  space (stage B -- the categorical sweep's hit-mask contraction and
  argmax-allgather cross DCN too, VERDICT r3 weak #2);
* a population-sharded ``device_loop.compile_fmin`` whose per-step
  trial axis spans both processes (stage C) -- suggest batch, objective
  evaluation and history scatter all cross DCN every scan step;
* a fused ``hyperband.compile_sha`` ladder whose rung populations shard
  over both processes (stage D) -- the survivor gathers between rungs
  move state across the process boundary, the replicated ranking drives
  identical promotions on every process, and the result must match the
  single-process ladder exactly (round 5);
* a fused ``pbt.compile_pbt`` schedule whose population shards over
  both processes (stage E) -- every exploit event's rank + bottom-
  quantile-copies-top gather moves member state across the process
  boundary, and the run must match the single-process schedule exactly
  (round 5: the second scheduler-family collective over DCN).

Process 0 checks winner distributions against the single-process
unsharded path at equal total candidate count (two-sample KS per dim)
and loop determinism.

Used by ``__graft_entry__.dryrun_multichip`` (stage 5) and
``tests/test_sharding.py`` -- both spawn the two workers and assert on
the ``DCN RESULT`` line this prints.
"""

from __future__ import annotations

import os
import sys


def _force_local_cpu_devices(n_local):
    """CPU platform + n_local virtual devices, before backend init.

    Any inherited ``xla_force_host_platform_device_count`` is replaced
    (the parent may run under a different virtual-device count), and a
    pre-latched TPU-tunnel plugin is scrubbed (see tests/conftest.py).
    """
    flags = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={int(n_local)}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    try:  # pragma: no cover - environment dependent
        from jax._src import xla_bridge as xb

        xb._backend_factories.pop("axon", None)
    except Exception:
        pass
    jax.config.update("jax_platforms", "cpu")


def _complete_history(space, fn, n_obs, seed):
    """Identical completed-trial history on every process."""
    from ..base import Domain, JOB_STATE_DONE, Trials
    from .. import rand

    domain = Domain(fn, space)
    trials = Trials()
    docs = rand.suggest(trials.new_trial_ids(n_obs), domain, trials, seed=seed)
    for d in docs:
        cfg = {k: v[0] for k, v in d["misc"]["vals"].items()}
        d["state"] = JOB_STATE_DONE
        d["result"] = {"status": "ok", "loss": float(fn(cfg))}
    trials.insert_trial_docs(docs)
    trials.refresh()
    return domain, trials


def _seeded_history(n_obs=40, seed=0):
    import numpy as np

    from .. import hp

    space = {
        "x": hp.uniform("x", -5.0, 5.0),
        "y": hp.loguniform("y", float(np.log(1e-3)), float(np.log(10.0))),
    }

    def fn(cfg):
        return (cfg["x"] - 1.0) ** 2 + (np.log(cfg["y"]) + 1.0) ** 2

    return _complete_history(space, fn, n_obs, seed)


def _seeded_history_mixed(n_obs=40, seed=0):
    """Categorical-bearing space: ``ei_sweep_cat`` (the [S, K] hit-mask
    contraction + per-option llr argmax) must cross the process boundary
    too, not just the continuous sweep (VERDICT r3 weak #2: the DCN path
    previously executed only the continuous, categorical-free slice)."""
    from .. import hp

    space = {
        "x": hp.uniform("x", -5.0, 5.0),
        "k": hp.choice("k", [0.1, 0.5, 1.0, 2.0, 4.0]),
        "r": hp.randint("r", 4),
    }

    def fn(cfg):
        return (cfg["x"] - 1.0) ** 2 + cfg["k"] + 0.25 * cfg["r"]

    return _complete_history(space, fn, n_obs, seed)


def _ks_distance(a, b):
    import numpy as np

    grid = np.sort(np.concatenate([a, b]))

    def ecdf(x):
        return np.searchsorted(np.sort(x), grid, side="right") / len(x)

    return float(np.abs(ecdf(a) - ecdf(b)).max())


def launch(n_local=4, timeout=600):
    """Spawn the two workers and return process-0's output.

    Raises ``RuntimeError`` (with both workers' tails) if either exits
    nonzero.  The coordinator port is bound-then-released on loopback.
    """
    import socket
    import subprocess

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "hyperopt_tpu.parallel.dcn_check",
             str(pid), str(port), str(n_local)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=timeout)[0] for p in procs]
    finally:  # never orphan a worker holding the coordinator port
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(p.returncode != 0 for p in procs):
        raise RuntimeError(
            "dcn_check worker failed:\n"
            + "\n---\n".join(out[-2000:] for out in outs)
        )
    return outs[0]


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    pid, port = int(argv[0]), argv[1]
    n_local = int(argv[2]) if len(argv) > 2 else 4
    _force_local_cpu_devices(n_local)

    import numpy as np
    import jax

    from . import multihost
    from .mesh import CAND_AXIS, default_mesh
    from .sharded import sharded_suggest

    multihost.initialize(f"127.0.0.1:{port}", num_processes=2, process_id=pid)
    assert multihost.is_multihost(), "expected a 2-process runtime"
    n_global = len(jax.devices())
    assert n_global == 2 * n_local, (n_global, n_local)
    mesh = default_mesh()  # 1-D cand mesh over BOTH processes' devices

    domain, trials = _seeded_history()
    B = 256
    n_per_dev = 32
    docs = sharded_suggest(
        trials.new_trial_ids(B), domain, trials, seed=5,
        mesh=mesh, n_EI_per_device=n_per_dev,
    )
    assert len(docs) == B
    sh_vals = {
        lab: np.array([d["misc"]["vals"][lab][0] for d in docs])
        for lab in ("x", "y")
    }

    # --- stage B: mixed space -- the CATEGORICAL sweep crosses DCN too --
    domain_m, trials_m = _seeded_history_mixed()
    docs_m = sharded_suggest(
        trials_m.new_trial_ids(B), domain_m, trials_m, seed=11,
        mesh=mesh, n_EI_per_device=n_per_dev,
    )
    assert len(docs_m) == B
    sh_vals_m = {
        lab: np.array([d["misc"]["vals"][lab][0] for d in docs_m])
        for lab in ("x", "k", "r")
    }

    # --- stage C: population-sharded device loop SPANNING processes -----
    # The trial axis of compile_fmin's per-step batch shards over a mesh
    # covering both processes' devices: the suggest batch, the objective
    # evaluation, and the history scatter all cross DCN every scan step.
    from jax.sharding import Mesh

    from .. import hp
    from ..device_loop import compile_fmin

    pop_mesh = Mesh(np.array(jax.devices()), ("trial",))
    import jax.numpy as jnp

    loop_space = {
        "x": hp.uniform("x", -5.0, 5.0),
        "y": hp.loguniform("y", float(np.log(1e-3)), float(np.log(10.0))),
    }
    runner = compile_fmin(
        lambda cfg: (cfg["x"] - 1.0) ** 2 + (jnp.log(cfg["y"]) + 1.0) ** 2,
        loop_space, max_evals=64, batch_size=n_global,
        mesh=pop_mesh, trial_axis="trial",
    )
    loop_a = runner(seed=2)
    loop_b = runner(seed=2)
    assert np.array_equal(loop_a["losses"], loop_b["losses"]), (
        "population-sharded loop nondeterministic across DCN"
    )
    assert np.isfinite(loop_a["best_loss"])

    # --- stage D: fused successive halving SPANNING processes -----------
    # compile_sha with its trial axis over the 2-process mesh: each rung
    # trains a population sharded across BOTH processes, the replicated
    # ranking drives identical promotions everywhere, and the survivor
    # gathers (state[keep] with a cross-process-sharded state) ride DCN
    # between rungs (VERDICT r4 weak/next #7).  The member train math is
    # elementwise per member, so the sharded ladder must match the
    # single-process unsharded ladder EXACTLY, and repeat runs must be
    # deterministic.
    from ..hyperband import compile_sha

    def sha_train_fn(state, hypers, key):
        theta = state["theta"] - hypers["lr"] * 2.0 * (state["theta"] - 0.7)
        return {"theta": theta}, (theta - 0.7) ** 2

    P_sha = n_global  # one member per global device at rung 0
    sha_sharded = compile_sha(
        sha_train_fn, {"theta": jnp.full((P_sha,), 5.0)},
        {"lr": (1e-3, 1.0)}, n_configs=P_sha, eta=2, steps_per_rung=2,
        mesh=pop_mesh, trial_axis="trial",
    )
    sha_a = sha_sharded(seed=9)
    sha_b = sha_sharded(seed=9)
    assert sha_a["best_loss"] == sha_b["best_loss"], (
        "sha-over-DCN nondeterministic"
    )
    assert sha_a["rungs"] == sha_b["rungs"]
    sha_plain = compile_sha(
        sha_train_fn, {"theta": jnp.full((P_sha,), 5.0)},
        {"lr": (1e-3, 1.0)}, n_configs=P_sha, eta=2, steps_per_rung=2,
    )(seed=9)
    assert sha_a["best_loss"] == sha_plain["best_loss"], (
        "sha-over-DCN diverges from the single-process ladder",
        sha_a["best_loss"], sha_plain["best_loss"],
    )
    assert [r["best_loss"] for r in sha_a["rungs"]] == [
        r["best_loss"] for r in sha_plain["rungs"]
    ]
    assert np.isfinite(sha_a["best_loss"])

    # --- stage E: population-based training SPANNING processes ----------
    # compile_pbt with its population axis over the 2-process mesh: each
    # exploit event ranks the (replicated) losses and copies the top
    # quantile's member state into the bottom quantile -- gathers whose
    # source and destination members live on DIFFERENT processes, riding
    # DCN.  Per-member train math is elementwise, so the sharded schedule
    # must match the single-process one exactly and repeats must be
    # deterministic.
    from ..pbt import compile_pbt

    def pbt_train_fn(state, hypers, key):
        theta = state["theta"] - hypers["lr"] * 2.0 * (state["theta"] - 0.7)
        return {"theta": theta}, (theta - 0.7) ** 2

    P_pbt = n_global  # one member per global device
    pbt_kw = dict(
        hyper_bounds={"lr": (1e-3, 1.0)}, pop_size=P_pbt,
        exploit_every=2, n_rounds=4,
    )
    pbt_sharded = compile_pbt(
        pbt_train_fn, {"theta": jnp.full((P_pbt,), 5.0)},
        mesh=pop_mesh, trial_axis="trial", **pbt_kw,
    )
    pbt_a = pbt_sharded(seed=13)
    pbt_b = pbt_sharded(seed=13)
    assert pbt_a["best_loss"] == pbt_b["best_loss"], (
        "pbt-over-DCN nondeterministic"
    )
    assert np.array_equal(pbt_a["loss_history"], pbt_b["loss_history"])
    pbt_plain = compile_pbt(
        pbt_train_fn, {"theta": jnp.full((P_pbt,), 5.0)}, **pbt_kw,
    )(seed=13)
    assert pbt_a["best_loss"] == pbt_plain["best_loss"], (
        "pbt-over-DCN diverges from the single-process schedule",
        pbt_a["best_loss"], pbt_plain["best_loss"],
    )
    assert np.array_equal(
        np.asarray(pbt_a["loss_history"]),
        np.asarray(pbt_plain["loss_history"]),
    ), "pbt-over-DCN loss history diverges from single-process"
    assert np.isfinite(pbt_a["best_loss"])

    if pid == 0:
        # agreement vs the single-process path at equal TOTAL candidates
        # (local single-device jit -- no collectives, runs on pid 0 only)
        from ..tpe_jax import suggest_batch

        _, un_vals = suggest_batch(
            trials.new_trial_ids(B), domain, trials, seed=6,
            n_EI_candidates=n_per_dev * n_global,
            n_EI_candidates_cat=None,
        )
        ks = {
            lab: round(_ks_distance(sh_vals[lab], np.asarray(un_vals[lab])), 4)
            for lab in ("x", "y")
        }
        # KS critical value at alpha=0.001 for n=m=256 is ~0.172; 0.2
        # allows f32 jitter while failing any real divergence (wrong
        # slab gather, biased per-device folds, broken DCN allgather)
        for lab, v in ks.items():
            assert v < 0.2, (lab, v)

        # mixed-space twin at the sharded path's EXECUTED categorical
        # total: per-device counts round up from the n_EI_cat_total
        # default, so the executed total is ceil(default/n)*n -- derive
        # it instead of hardcoding n_global=8's value
        from . import sharded as sharded_mod

        cat_exec_total = (
            -(-int(sharded_mod._default_n_EI_cat_total) // n_global)
            * n_global
        )
        _, un_vals_m = suggest_batch(
            trials_m.new_trial_ids(B), domain_m, trials_m, seed=12,
            n_EI_candidates=n_per_dev * n_global,
            n_EI_candidates_cat=cat_exec_total,
        )
        ks_m = {
            lab: round(
                _ks_distance(
                    np.asarray(sh_vals_m[lab], dtype=float),
                    np.asarray(un_vals_m[lab], dtype=float),
                ),
                4,
            )
            for lab in ("x", "k", "r")
        }
        for lab, v in ks_m.items():
            assert v < 0.2, (lab, v)
        print(
            f"DCN RESULT procs=2 devices={n_global} "
            f"mesh={{{CAND_AXIS}: {int(mesh.shape[CAND_AXIS])}}} ks={ks} "
            f"mixed_ks={ks_m} "
            f"pop_sharded_loop={{trial: {n_global}}} "
            f"best={loop_a['best_loss']:.5f} deterministic=True "
            f"sha_dcn={{trial: {n_global}, n_configs: {P_sha}}} "
            f"sha_best={sha_a['best_loss']:.5f} "
            f"sha_matches_unsharded=True sha_deterministic=True "
            f"pbt_dcn={{trial: {n_global}, pop: {P_pbt}}} "
            f"pbt_best={pbt_a['best_loss']:.5f} "
            f"pbt_matches_unsharded=True pbt_deterministic=True",
            flush=True,
        )
    else:
        print(f"DCN RESULT pid=1 ok n={B}", flush=True)


if __name__ == "__main__":
    main()
