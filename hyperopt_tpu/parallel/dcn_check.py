"""Two-process DCN execution check for the sharded suggest program.

The multihost story (SURVEY.md SS5 'distributed communication backend')
promises that :func:`hyperopt_tpu.parallel.sharded.sharded_suggest` spans
hosts: all processes join one ``jax.distributed`` runtime, the candidate
sweep shards over every device of every host, and the EI argmax-allgather
rides DCN between processes.  This module EXECUTES that path the way the
reference tests multi-node -- by running the real thing small: launched as
one worker per process (``python -m hyperopt_tpu.parallel.dcn_check <pid>
<port>``), each worker forces ``--n-local`` virtual CPU devices, joins a
2-process runtime (2 x n-local global devices), runs the REAL
``sharded_suggest`` API over the global mesh on an identical seeded
history, and process 0 checks the winner distribution against the
single-process unsharded path at equal total candidate count
(two-sample KS per dim).

Used by ``__graft_entry__.dryrun_multichip`` (stage 5) and
``tests/test_sharding.py`` -- both spawn the two workers and assert on
the ``DCN RESULT`` line this prints.
"""

from __future__ import annotations

import os
import sys


def _force_local_cpu_devices(n_local):
    """CPU platform + n_local virtual devices, before backend init.

    Any inherited ``xla_force_host_platform_device_count`` is replaced
    (the parent may run under a different virtual-device count), and a
    pre-latched TPU-tunnel plugin is scrubbed (see tests/conftest.py).
    """
    flags = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={int(n_local)}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    try:  # pragma: no cover - environment dependent
        from jax._src import xla_bridge as xb

        xb._backend_factories.pop("axon", None)
    except Exception:
        pass
    jax.config.update("jax_platforms", "cpu")


def _seeded_history(n_obs=40, seed=0):
    """Identical completed-trial history on every process."""
    import numpy as np

    from ..base import Domain, JOB_STATE_DONE, Trials
    from .. import hp, rand

    space = {
        "x": hp.uniform("x", -5.0, 5.0),
        "y": hp.loguniform("y", float(np.log(1e-3)), float(np.log(10.0))),
    }

    def fn(cfg):
        return (cfg["x"] - 1.0) ** 2 + (np.log(cfg["y"]) + 1.0) ** 2

    domain = Domain(fn, space)
    trials = Trials()
    docs = rand.suggest(trials.new_trial_ids(n_obs), domain, trials, seed=seed)
    for d in docs:
        cfg = {k: v[0] for k, v in d["misc"]["vals"].items()}
        d["state"] = JOB_STATE_DONE
        d["result"] = {"status": "ok", "loss": float(fn(cfg))}
    trials.insert_trial_docs(docs)
    trials.refresh()
    return domain, trials


def _ks_distance(a, b):
    import numpy as np

    grid = np.sort(np.concatenate([a, b]))

    def ecdf(x):
        return np.searchsorted(np.sort(x), grid, side="right") / len(x)

    return float(np.abs(ecdf(a) - ecdf(b)).max())


def launch(n_local=4, timeout=300):
    """Spawn the two workers and return process-0's output.

    Raises ``RuntimeError`` (with both workers' tails) if either exits
    nonzero.  The coordinator port is bound-then-released on loopback.
    """
    import socket
    import subprocess

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "hyperopt_tpu.parallel.dcn_check",
             str(pid), str(port), str(n_local)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=timeout)[0] for p in procs]
    finally:  # never orphan a worker holding the coordinator port
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(p.returncode != 0 for p in procs):
        raise RuntimeError(
            "dcn_check worker failed:\n"
            + "\n---\n".join(out[-2000:] for out in outs)
        )
    return outs[0]


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    pid, port = int(argv[0]), argv[1]
    n_local = int(argv[2]) if len(argv) > 2 else 4
    _force_local_cpu_devices(n_local)

    import numpy as np
    import jax

    from . import multihost
    from .mesh import CAND_AXIS, default_mesh
    from .sharded import sharded_suggest

    multihost.initialize(f"127.0.0.1:{port}", num_processes=2, process_id=pid)
    assert multihost.is_multihost(), "expected a 2-process runtime"
    n_global = len(jax.devices())
    assert n_global == 2 * n_local, (n_global, n_local)
    mesh = default_mesh()  # 1-D cand mesh over BOTH processes' devices

    domain, trials = _seeded_history()
    B = 256
    n_per_dev = 32
    docs = sharded_suggest(
        trials.new_trial_ids(B), domain, trials, seed=5,
        mesh=mesh, n_EI_per_device=n_per_dev,
    )
    assert len(docs) == B
    sh_vals = {
        lab: np.array([d["misc"]["vals"][lab][0] for d in docs])
        for lab in ("x", "y")
    }

    if pid == 0:
        # agreement vs the single-process path at equal TOTAL candidates
        # (local single-device jit -- no collectives, runs on pid 0 only)
        from ..tpe_jax import suggest_batch

        _, un_vals = suggest_batch(
            trials.new_trial_ids(B), domain, trials, seed=6,
            n_EI_candidates=n_per_dev * n_global,
            n_EI_candidates_cat=None,
        )
        ks = {
            lab: round(_ks_distance(sh_vals[lab], np.asarray(un_vals[lab])), 4)
            for lab in ("x", "y")
        }
        # KS critical value at alpha=0.001 for n=m=256 is ~0.172; 0.2
        # allows f32 jitter while failing any real divergence (wrong
        # slab gather, biased per-device folds, broken DCN allgather)
        for lab, v in ks.items():
            assert v < 0.2, (lab, v)
        print(
            f"DCN RESULT procs=2 devices={n_global} "
            f"mesh={{{CAND_AXIS}: {int(mesh.shape[CAND_AXIS])}}} ks={ks}",
            flush=True,
        )
    else:
        print(f"DCN RESULT pid=1 ok n={B}", flush=True)


if __name__ == "__main__":
    main()
