"""``hp.*`` search-space constructors.

Capability parity with the reference's ``hyperopt/hp.py`` (SURVEY.md SS2):
each ``hp.X(label, ...)`` wraps a stochastic node in
``hyperopt_param(label, ...)``; ``hp.choice`` is ``switch(randint(n), *opts)``;
``hp.pchoice`` is ``switch(categorical(p), *opts)``.

The same graphs serve both execution paths: host-interpreted via
``pyll.rec_eval`` (oracle / parity) and compiled to one jitted JAX sampler
via :mod:`hyperopt_tpu.ops.compile` (TPU path).
"""

from __future__ import annotations

from .exceptions import InvalidAnnotatedParameter
from .pyll.base import scope
from .pyll_utils import validate_label

__all__ = [
    "choice",
    "pchoice",
    "randint",
    "uniform",
    "quniform",
    "uniformint",
    "loguniform",
    "qloguniform",
    "normal",
    "qnormal",
    "lognormal",
    "qlognormal",
]


def choice(label, options):
    """Choose one of ``options`` uniformly; conditional subspaces allowed."""
    validate_label(label)
    options = list(options)
    if not options:
        raise InvalidAnnotatedParameter(f"hp.choice({label!r}): empty options")
    ch = scope.hyperopt_param(label, scope.randint(len(options)))
    return scope.switch(ch, *options)


def pchoice(label, p_options):
    """Choose one of ``options`` with explicit probabilities.

    ``p_options`` is a list of ``(prob, option)`` pairs.
    """
    validate_label(label)
    p_options = list(p_options)
    if not p_options:
        raise InvalidAnnotatedParameter(f"hp.pchoice({label!r}): empty options")
    probs, options = [], []
    for item in p_options:
        try:
            p, opt = item
        except (TypeError, ValueError):
            raise InvalidAnnotatedParameter(
                f"hp.pchoice({label!r}): expected (prob, option) pairs"
            )
        probs.append(float(p))
        options.append(opt)
    total = sum(probs)
    if total <= 0:
        raise InvalidAnnotatedParameter(f"hp.pchoice({label!r}): probs sum <= 0")
    probs = [p / total for p in probs]
    ch = scope.hyperopt_param(label, scope.categorical(probs))
    return scope.switch(ch, *options)


def randint(label, *args):
    """``randint(label, upper)`` -> [0, upper); ``randint(label, low, high)``."""
    validate_label(label)
    if len(args) not in (1, 2):
        raise InvalidAnnotatedParameter(
            f"hp.randint({label!r}): takes (upper,) or (low, high)"
        )
    return scope.hyperopt_param(label, scope.randint(*args))


def uniform(label, low, high):
    validate_label(label)
    return scope.float(scope.hyperopt_param(label, scope.uniform(low, high)))


def quniform(label, low, high, q):
    validate_label(label)
    return scope.float(scope.hyperopt_param(label, scope.quniform(low, high, q)))


def uniformint(label, low, high, q=1.0):
    """Uniform integer in [low, high] (inclusive), via quantized uniform."""
    validate_label(label)
    if q != 1.0:
        raise InvalidAnnotatedParameter(
            f"hp.uniformint({label!r}): q must be 1.0 (use quniform for q != 1)"
        )
    return scope.int(scope.hyperopt_param(label, scope.quniform(low, high, q)))


def loguniform(label, low, high):
    """exp(uniform(low, high)) -- low/high are bounds in log space."""
    validate_label(label)
    return scope.float(scope.hyperopt_param(label, scope.loguniform(low, high)))


def qloguniform(label, low, high, q):
    validate_label(label)
    return scope.float(scope.hyperopt_param(label, scope.qloguniform(low, high, q)))


def normal(label, mu, sigma):
    validate_label(label)
    return scope.float(scope.hyperopt_param(label, scope.normal(mu, sigma)))


def qnormal(label, mu, sigma, q):
    validate_label(label)
    return scope.float(scope.hyperopt_param(label, scope.qnormal(mu, sigma, q)))


def lognormal(label, mu, sigma):
    validate_label(label)
    return scope.float(scope.hyperopt_param(label, scope.lognormal(mu, sigma)))


def qlognormal(label, mu, sigma, q):
    validate_label(label)
    return scope.float(scope.hyperopt_param(label, scope.qlognormal(mu, sigma, q)))
