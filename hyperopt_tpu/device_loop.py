"""The whole optimization loop on-device: ``fmin_on_device``.

The reference's fmin (SURVEY.md SS3.1) alternates host-side suggest and
host-side evaluate; even this repo's jitted ``tpe_jax.suggest`` pays one
device dispatch + host round-trip per batch.  For objectives that are
themselves JAX-traceable (surrogates, analytic benchmarks, small neural
nets -- anything a TPU can evaluate), the entire ask-evaluate-append
history loop compiles to ONE XLA program: a ``lax.scan`` whose carry is
the dense observation buffers, with the TPE (or annealing/random) suggest
kernels and the vmapped objective fused into each step.  Zero host
round-trips until the final result -- this is the fully pipelined
suggest<->evaluate path of SURVEY.md SS7/M4, and the execution model the
reference cannot express.

    from hyperopt_tpu import hp
    from hyperopt_tpu.device_loop import fmin_on_device

    out = fmin_on_device(
        lambda cfg: (cfg["x"] - 1.0) ** 2,   # jnp math, vmapped by us
        {"x": hp.uniform("x", -5.0, 5.0)},
        max_evals=512,
    )
    out["best"]["x"], out["best_loss"], out["losses"]

The objective receives a dict of ``[batch]`` value arrays (natural
space; categorical/randint dims as float indices -- round/cast inside)
plus, for conditional spaces, an ``active`` dict of ``[batch]`` masks
under the keyword ``active`` if the callable accepts it.  It must return
``[batch]`` losses (jnp).  Non-finite losses are masked out of the
posterior, matching the host driver's error handling (SURVEY.md SS5).
"""

from __future__ import annotations

import inspect

import numpy as np

from .ops.compile import compile_space

__all__ = [
    "TrainableObjective",
    "fmin_on_device",
    "compile_fmin",
    "history_from_trials",
]


class TrainableObjective:
    """A *stateful* on-device objective: per-trial training inside the scan.

    The plain-fn seam evaluates a stateless ``fn(cfg) -> [B] losses``;
    real JAX workloads carry state -- params and optimizer moments
    trained over device-resident data.  A ``TrainableObjective`` gives
    the device loop that shape as three jit-traceable pieces, vmapped
    across the trial batch by :func:`compile_fmin`:

    * ``init_fn(key, cfg) -> state`` -- build one trial's carried state
      (params/opt-state pytree) from a per-trial PRNG key and its
      hyperparameter dict (scalars, natural space; categorical dims as
      float indices).
    * ``step_fn(state, cfg, epoch) -> state`` -- one training epoch,
      run ``n_epochs`` times under an inner ``lax.fori_loop`` INSIDE
      the experiment scan step.
    * ``loss_fn(state, cfg) -> scalar`` -- the trial's reported loss.

    Training data lives in closures (device-resident after the first
    dispatch).  Per-trial keys derive from the experiment key stream
    (fold + split), so results are seed-deterministic and independent
    of batch size placement.  The suggest key stream is untouched --
    a trainable objective sees the exact suggestion sequence a plain
    objective with the same algo/seed would.
    """

    def __init__(self, init_fn, step_fn, loss_fn, n_epochs=1):
        if int(n_epochs) < 1:
            raise ValueError("n_epochs must be a positive integer")
        self.init_fn = init_fn
        self.step_fn = step_fn
        self.loss_fn = loss_fn
        self.n_epochs = int(n_epochs)

    def __repr__(self):
        return f"TrainableObjective(n_epochs={self.n_epochs})"


def history_from_trials(space, trials):
    """Convert a host ``Trials`` store into a ``runner(init=...)`` dict.

    The bridge from the host-driven world to the on-device loop: run (or
    resume) an experiment through ``fmin`` / an async backend, then
    continue it on-device --

        hist = history_from_trials(space, trials)
        runner = compile_fmin(fn, space, max_evals=1000,
                              warm_capacity=hist["losses"].shape[0])
        out = runner(init=hist)

    Ingestion IS the suggest paths' dense mirror
    (:class:`hyperopt_tpu.jax_trials.ObsBuffer`): only posterior-
    eligible trials enter (completed, status-ok, finite loss), in tid
    order -- one implementation, so warm-started device runs can never
    see a different posterior than the suggest paths.  ``space`` may be
    an ``hp.*`` space or a ``PackedSpace``.
    """
    from .jax_trials import ObsBuffer
    from .ops.compile import PackedSpace

    ps = space if isinstance(space, PackedSpace) else compile_space(space)
    buf = ObsBuffer(ps)
    buf.sync(trials)
    n = buf.count
    return {
        "values": buf.values[:, :n].copy(),
        "active": buf.active[:, :n].copy(),
        "losses": buf.losses[:n].copy(),
    }


def _round_up(n, m):
    return (n + m - 1) // m * m


def compile_fmin(
    fn,
    space,
    max_evals,
    batch_size=1,
    algo="tpe",
    n_startup_jobs=20,
    n_EI_candidates=24,
    n_EI_candidates_cat=None,
    gamma=0.25,
    prior_weight=1.0,
    linear_forgetting=25,
    joint_ei=False,
    avg_best_idx=2.0,
    shrink_coef=0.1,
    mesh=None,
    trial_axis="trial",
    cand_axis=None,
    loss_threshold=None,
    no_progress_steps=None,
    warm_capacity=0,
    chunk_size=None,
    progress_callback=None,
    progress_every=1,
    checkpoint_path=None,
    checkpoint_every=1,
    resume=False,
    fs=None,
    metrics_registry=None,
    asha=None,
    artifact_callback=None,
):
    """Compile a full HPO experiment into one reusable device program.

    Returns ``runner(seed=0, return_trials=False) -> result dict``; the
    seed is a traced input, so repeated runs (seed sweeps, CV repeats)
    reuse the compilation.  ``runner(seed=[s0, s1, ...])`` runs a
    VECTORIZED seed sweep -- the whole experiment scan ``vmap``-ed over
    the seed axis, S independent loops (own histories/key streams)
    advancing in lockstep in one program -- and returns a LIST of
    per-seed result dicts.  At B=1, where fixed per-step cost dominates
    (ROOFLINE.md round 5), S seeds cost ~one seed's wall-clock: the
    median-of-seeds study collapses to a single call (measured --
    BASELINE.md round-5 seed-sweep row).

    Args:
      fn: JAX-traceable objective over a dict of [batch] value arrays.
      space: an ``hp.*`` space (pytree of pyll graphs).
      max_evals: total evaluations (rounded up to a batch multiple).
      batch_size: trials suggested + evaluated per step (population mode
        when > 1 -- all members of a step share the same posterior).
      algo: 'tpe' | 'anneal' | 'rand' | 'atpe' (adaptive TPE: per-step
        gamma/prior-weight/restart decisions + converged-parameter
        locking as traced functions of the history carry -- see
        :func:`hyperopt_tpu.atpe_jax.build_atpe_device_fn`.  The
        adaptive layer DERIVES gamma and prior-weight per step, so the
        ``gamma`` argument is ignored under atpe; ``prior_weight`` is
        its base, ``n_EI_candidates`` its anchor (adaptation only
        raises it); ``joint_ei`` raises).
      joint_ei: TPE only -- whole-configuration scoring (see tpe_jax).
      mesh: optional ``jax.sharding.Mesh``; the population axis of every
        step (suggest batch + objective evaluation) is sharded over
        ``trial_axis`` with GSPMD sharding constraints -- the history
        buffers stay replicated (every device needs the full posterior).
        ``batch_size`` must be a multiple of the axis size when
        ``trial_axis`` is an axis of the mesh.
      cand_axis: optional mesh axis to shard the TPE EI candidate sweep
        over, INSIDE the scan (shard_map per-device slabs + argmax-
        allgather, exactly :func:`parallel.sharded.build_sharded_suggest_fn`).
        This is how multi-chip accelerates the flagship SEQUENTIAL
        ``batch_size=1`` mode, whose per-step cost is the candidate
        sweep itself -- population sharding cannot apply there (round-3
        verdict weak #1).  ``n_EI_candidates`` stays the TOTAL sweep
        width: each device draws ``ceil(total / n_dev)`` so the executed
        total rounds up to a device multiple.  Composes with
        ``trial_axis`` on a 2-D mesh (population sharded, sweep
        sharded); requires ``algo='tpe'`` or ``'atpe'`` (the sweep is
        what shards) and factorized EI.
      loss_threshold: stop as soon as a trial reaches this loss (fmin's
        stopping-rule parity) -- the scan becomes a ``lax.while_loop``,
        so a threshold hit early really does cut device wall-clock.
        Untouched tail slots stay invalid; ``n_evals`` in the result is
        the count actually run.
      no_progress_steps: stop after this many consecutive *steps* (each
        ``batch_size`` trials) without improving the best loss -- the
        on-device counterpart of ``early_stop.no_progress_loss``.
        Composes with ``loss_threshold``.
      warm_capacity: reserve history slots for warm starts; ``runner(...,
        init=prev_out)`` resumes from a previous result dict's history
        (checkpoint/resume for the on-device path). Warm trials feed the
        posterior and count toward the startup threshold but not toward
        this run's ``max_evals``.
      chunk_size: restructure the experiment scan into CHUNKED scans of
        ``ceil(chunk_size / batch_size)`` steps each (trials per chunk,
        rounded up to a batch multiple; the tail chunk is padded with
        masked no-op steps).  One compiled chunk program is dispatched
        ``n_chunks`` times by a host loop -- the per-step key stream
        folds the GLOBAL step index, so the trial stream is identical
        to the unchunked scan -- and each chunk boundary is a progress/
        checkpoint/resume point.  Does not compose with the early-stop
        ``while_loop`` path (``loss_threshold``/``no_progress_steps``)
        or vectorized seed sweeps.
      progress_callback: host callable receiving ``{"chunk", "trials_
        done", "best_loss"}`` rows streamed out of the running chunk
        program via ``jax.experimental.io_callback`` (ordered) -- live
        observability without leaving the compiled regime.  Rows fire
        on every ``progress_every``-th chunk plus the final one; the
        callback variant is a separate compiled twin, so cadence-off
        dispatches pay zero callback overhead, and the result stream
        is bitwise identical either way.
      checkpoint_path: publish the scan carry as a durable bundle
        (tmp+fsync+rename; :func:`hyperopt_tpu.utils.checkpoint.
        save_device_chunk`) every ``checkpoint_every`` chunks and after
        the final one.  ``resume=True`` (or ``runner(resume=True)``)
        loads the bundle and dispatches only the remaining chunks --
        bitwise identical to the uninterrupted run; a bundle from a
        different experiment (space/objective/algo/geometry guard) or
        seed is refused with ``CheckpointError``.
      fs: PR-3 fault-injection seam for the chunk loop (crash points
        ``device_loop_after_chunk_before_ckpt`` /
        ``device_loop_after_ckpt_before_next_chunk`` plus the durable
        saver's torn-publish window).
      asha: graftrung -- fuse rung-based successive-halving early
        stopping (ASHA, Li et al.) INSIDE the compiled scan.  A dict
        ``{"eta": 2, "rung_epochs": 1, "n_rungs": None}``: each scan
        step runs one BRACKET of ``batch_size`` fresh configs (so
        ``batch_size`` must be a power of ``eta``); rung ``r`` trains
        the live lanes ``rung_epochs * eta**r`` further epochs, then an
        on-device promotion (:func:`hyperopt_tpu.hyperband.rung_rank`)
        keeps the best ``1/eta`` and the survivors compact into a
        statically narrower vmap width -- no host round trip between
        rungs, and the ladder supersedes the objective's ``n_epochs``.
        Requires a :class:`TrainableObjective`; composes with
        ``chunk_size`` (rung/bracket boundaries align to chunk
        boundaries, so checkpoints/resume stay bitwise -- the promotion
        record ``rung_of`` rides the carry and the durable bundle) and
        with ``mesh``/``trial_axis`` (rung training shard_maps over the
        gcd-sized sub-mesh, :func:`hyperopt_tpu.parallel.mesh.
        rung_submesh`; a 1-device sub-mesh is bitwise the unsharded
        program); refuses ``loss_threshold``/``no_progress_steps``/
        ``cand_axis``/vectorized seed sweeps.  ``best``/``best_loss``
        rank FULL-FIDELITY trials only; the result dict gains
        ``rung_of`` [N] and an ``asha`` ladder-metadata dict.
      artifact_callback: host callable receiving one dict per bracket
        (``{"bracket", "slot", "loss", "params"}`` -- the full-fidelity
        winner's slot, loss, and trained params pytree as host numpy),
        streamed through the same declared-``io_callback`` seam as
        progress rows.  Requires ``asha=`` and ``chunk_size=``; when
        unset, dispatches use the callback-free twin and never even
        stack the winner rows (zero extra dispatches, zero overhead).

    ``fn`` may also be a :class:`TrainableObjective` -- a stateful
    per-trial training loop (``init_fn``/``step_fn``/``loss_fn``,
    ``n_epochs`` inner ``fori_loop`` epochs) vmapped across the trial
    batch, so "optimize a JAX model end-to-end" runs ask-evaluate-tell
    entirely on device.

    The result dict has ``best`` ({label: python value}, the same
    index-form encoding ``fmin`` returns -- ``space_eval(space, best)``
    resolves it to a concrete config), ``best_loss``,
    ``losses`` [N], ``values`` [D, N], ``active`` [D, N] and, when
    ``return_trials=True``, a rebuilt host ``Trials`` store (one
    device->host copy per array plus list-of-docs assembly).
    """
    import jax
    import jax.numpy as jnp

    if algo not in ("tpe", "anneal", "rand", "atpe"):
        raise ValueError(
            f"unknown algo {algo!r}: expected tpe|anneal|rand|atpe"
        )
    if algo == "atpe" and joint_ei:
        raise ValueError(
            "algo='atpe' supports only the factorized EI argmax "
            "(the adaptive layer has no joint-scoring path); drop "
            "joint_ei or use algo='tpe'"
        )
    from .fmin import validate_loss_threshold

    validate_loss_threshold(loss_threshold)
    if no_progress_steps is not None and (
        not isinstance(no_progress_steps, (int, np.integer))
        or no_progress_steps < 1
    ):
        raise ValueError("no_progress_steps must be a positive integer")
    if metrics_registry is not None and progress_callback is None:
        # graftscope: land the declared per-chunk progress rows on a
        # metrics registry (gauges + obs_device_events_total) instead
        # of a hand-rolled callback -- same io_callback seam, same
        # chunked-path requirement below
        from .obs.device import progress_to_registry

        progress_callback = progress_to_registry(metrics_registry)
    chunked = chunk_size is not None
    if not chunked and (
        progress_callback is not None
        or checkpoint_path is not None
        or resume
    ):
        raise ValueError(
            "progress_callback/checkpoint_path/resume ride the chunked "
            "scan path; pass chunk_size= to enable it"
        )
    if chunked:
        if loss_threshold is not None or no_progress_steps is not None:
            raise ValueError(
                "chunk_size does not compose with loss_threshold/"
                "no_progress_steps (the early-stop while_loop path is "
                "unchunked); drop one"
            )
        if int(chunk_size) < 1:
            raise ValueError("chunk_size must be a positive integer")
        if int(progress_every) < 1 or int(checkpoint_every) < 1:
            raise ValueError(
                "progress_every/checkpoint_every must be positive"
            )
        if resume and checkpoint_path is None:
            raise ValueError("resume=True needs checkpoint_path")
    ps = compile_space(space)
    _ = ps._consts  # materialize device constants outside the trace
    D = ps.n_dims
    B = int(batch_size)
    assert B >= 1
    n_steps = -(-int(max_evals) // B)
    N = n_steps * B
    W = int(warm_capacity)
    cap = _round_up(W + N, 128)
    n_cand = int(n_EI_candidates)
    n_cand_cat = (
        None if n_EI_candidates_cat is None else int(n_EI_candidates_cat)
    )
    gamma_f = float(gamma)
    lf_f = float(linear_forgetting)
    pw = float(prior_weight)

    if cand_axis is not None and mesh is None:
        raise ValueError("cand_axis requires a mesh")
    shard_trials = False
    if mesh is not None:
        if cand_axis is not None:
            if cand_axis not in mesh.shape:
                raise ValueError(
                    f"cand_axis {cand_axis!r} is not an axis of the mesh "
                    f"(axes: {tuple(mesh.shape)})"
                )
            if algo not in ("tpe", "atpe"):
                raise ValueError(
                    "cand_axis shards the (adaptive) TPE candidate "
                    f"sweep; algo={algo!r} has no candidate sweep to shard"
                )
            if joint_ei:
                raise ValueError(
                    "cand_axis supports only the factorized EI argmax "
                    "(joint_ei scores whole configurations on one device)"
                )
        if cand_axis is not None and B == 1:
            # sequential mode: a 1-wide population cannot shard, so the
            # trial axis is irrelevant (the cand axis carries the mesh)
            pass
        elif trial_axis is None:
            # explicit population-sharding opt-out; only meaningful when
            # the cand axis is doing the sharding
            if cand_axis is None:
                raise ValueError(
                    "mesh given with trial_axis=None and no cand_axis: "
                    "nothing to shard"
                )
        elif trial_axis in mesh.shape:
            shard_trials = True
            n_dev = int(mesh.shape[trial_axis])
            # asha= rung evaluation shard_maps over a gcd-sized sub-mesh
            # (rung_submesh), so shrinking rung widths need not divide
            # the axis; only the plain GSPMD population path requires it
            if B % n_dev and asha is None:
                raise ValueError(
                    f"batch_size={B} must be a multiple of mesh axis "
                    f"{trial_axis!r} size {n_dev}"
                )
        else:
            # a NAMED trial axis missing from the mesh is an error even
            # with cand sharding active -- a typo must never silently
            # unshard the population
            raise ValueError(
                f"trial_axis {trial_axis!r} is not an axis of the mesh "
                f"(axes: {tuple(mesh.shape)}); pass trial_axis=None to "
                "opt out of population sharding"
            )

    trainable = isinstance(fn, TrainableObjective)
    accepts_active = (
        not trainable and "active" in inspect.signature(fn).parameters
    )
    init_accepts_active = trainable and (
        "active" in inspect.signature(fn.init_fn).parameters
    )

    # ---- graftrung (asha=): fused rung-based early stopping --------------
    asha_mode = asha is not None
    a_eta = a_rung_epochs = a_n_rungs = None
    asha_ladder = None
    if asha_mode:
        if not isinstance(asha, dict):
            raise ValueError(
                "asha= takes a dict of rung options "
                '({"eta", "rung_epochs", "n_rungs"})'
            )
        unknown = set(asha) - {"eta", "rung_epochs", "n_rungs"}
        if unknown:
            raise ValueError(
                f"unknown asha option(s) {sorted(unknown)}; expected "
                "eta|rung_epochs|n_rungs"
            )
        if not trainable:
            raise ValueError(
                "asha= fuses rung-based early stopping into the "
                "per-trial training loop; fn must be a TrainableObjective"
            )
        if loss_threshold is not None or no_progress_steps is not None:
            raise ValueError(
                "asha= does not compose with loss_threshold/"
                "no_progress_steps (rung promotion IS the early "
                "stopping); drop one"
            )
        if cand_axis is not None:
            raise ValueError(
                "asha= does not compose with cand_axis (bracket "
                "populations shard over trial_axis; there is no "
                "sequential candidate sweep to shard)"
            )
        a_eta = int(asha.get("eta", 2))
        a_rung_epochs = int(asha.get("rung_epochs", 1))
        from .hyperband import rung_schedule

        try:
            asha_ladder = rung_schedule(
                B, a_eta, asha.get("n_rungs"), a_rung_epochs
            )
        except ValueError as e:
            raise ValueError(
                f"asha bracket geometry: {e} (batch_size is the "
                "bracket population)"
            ) from None
        a_n_rungs = len(asha_ladder)
    if artifact_callback is not None:
        if not asha_mode:
            raise ValueError(
                "artifact_callback streams rung-winner params; it "
                "requires asha="
            )
        if not chunked:
            raise ValueError(
                "artifact_callback rides the chunked scan path; pass "
                "chunk_size= to enable it"
            )
    # the rung seam shard_maps explicit device blocks (compile_sha's
    # graftmesh idiom) instead of GSPMD constraints on the suggest batch
    asha_shard = False
    if asha_mode and shard_trials:
        asha_shard = True
        shard_trials = False

    def eval_batch(values, active):
        """values/active [D, B] -> losses [B] via the user objective."""
        cfg = {label: values[d] for d, label in enumerate(ps.labels)}
        if accepts_active:
            return fn(cfg, active={
                label: active[d] for d, label in enumerate(ps.labels)
            })
        return fn(cfg)

    def _trial_cfg(vcol, acol):
        """One trial's hyperparameter dict with inactive-branch dims
        MASKED to 0.0.  The suggest kernels sample every dim and leave
        unsampled-branch values in place; the host driver's domain memo
        simply omits inactive labels, but a scalar dict cannot -- so
        conditional-space trainables pin them to 0.0 instead of training
        on another branch's garbage (PR-10 residue)."""
        return {
            label: jnp.where(acol[d], vcol[d], 0.0)
            for d, label in enumerate(ps.labels)
        }

    def _init_one(k, vcol, acol):
        """Build one trial's carried state; ``init_fn`` may accept the
        per-dim ``active`` mask (keyword, like plain objectives) to
        size/shape conditional branches itself."""
        cfg = _trial_cfg(vcol, acol)
        if init_accepts_active:
            return fn.init_fn(k, cfg, active={
                label: acol[d] for d, label in enumerate(ps.labels)
            })
        return fn.init_fn(k, cfg)

    def eval_batch_trainable(key, values, active):
        """The stateful seam: per-trial init -> n_epochs inner
        ``fori_loop`` training -> loss, vmapped over the trial batch.
        Keys fold a fixed tag off the step key, so the SUGGEST stream
        is untouched and the training stream is seed-deterministic."""
        ekeys = jax.random.split(jax.random.fold_in(key, 0x7EA1), B)

        def one(vcol, acol, k):
            cfg = _trial_cfg(vcol, acol)
            state = _init_one(k, vcol, acol)
            state = jax.lax.fori_loop(
                0, fn.n_epochs,
                lambda e, s: fn.step_fn(s, cfg, e),
                state,
            )
            return fn.loss_fn(state, cfg)

        return jax.vmap(one, in_axes=(1, 1, 0))(values, active, ekeys)

    def suggest(key, values, active, losses, valid):
        if algo == "rand":
            return ps.sample_prior_fn(key, B)

        def prior(_):
            return ps.sample_prior_fn(key, B)

        def model(_):
            if algo == "anneal":
                return _anneal_step(key, values, active, losses, valid)
            if algo == "atpe":
                return _atpe_step(key, values, active, losses, valid)
            return _tpe_step(key, values, active, losses, valid)

        # startup on history size; every evaluated trial counts, failed
        # or not, matching the reference driver (len(trials) gates
        # startup there; failures only mask out of the posterior)
        n_hist = jnp.sum(valid.astype(jnp.int32))
        return jax.lax.cond(n_hist < n_startup_jobs, prior, model, None)

    def _tpe_step(key, values, active, losses, valid):
        # the returned fns are jitted; nested jit inlines under the scan
        if cand_axis is not None:
            from .parallel.sharded import (
                build_sharded_suggest_fn,
                per_device_count,
            )

            n_dev_c = int(mesh.shape[cand_axis])
            # n_EI_candidates is the TOTAL sweep width in every mode;
            # per-device counts round up (executed total may exceed the
            # request by < n_dev per dim -- per_device_count pins the
            # contract once for every sharded entry point)
            cat_total = n_cand if n_cand_cat is None else n_cand_cat
            fn_ = build_sharded_suggest_fn(
                ps, mesh, per_device_count(n_cand, n_dev_c), gamma_f,
                lf_f, pw, axis=cand_axis,
                n_cand_cat_per_device=per_device_count(cat_total, n_dev_c),
            )
        else:
            from .tpe_jax import build_suggest_fn

            fn_ = build_suggest_fn(ps, n_cand, gamma_f, lf_f, pw,
                                   joint_ei=joint_ei, n_cand_cat=n_cand_cat)
        return fn_(key, values, active, losses, valid, batch=B)

    def _anneal_step(key, values, active, losses, valid):
        from .anneal_jax import build_anneal_fn

        fn_ = build_anneal_fn(ps, avg_best_idx, shrink_coef)
        return fn_(key, values, active, losses, valid, batch=B)

    def _atpe_step(key, values, active, losses, valid):
        from .atpe_jax import build_atpe_device_fn

        # adaptive settings are traced scalars of the history carry; the
        # candidate counts derive from n_EI_candidates as the base (the
        # host adaptive layer's anchor semantics: adaptation only raises)
        fn_ = build_atpe_device_fn(
            ps, lf_f, prior_weight=pw, base_n_ei=n_cand,
            n_cand_cat=n_cand_cat,
            mesh=mesh if cand_axis is not None else None,
            cand_axis=cand_axis,
        )
        return fn_(key, values, active, losses, valid, batch=B)

    def _shard_batch(x, spec_tail):
        """Pin the population axis of a per-step array onto the mesh."""
        if mesh is None or not shard_trials:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(*spec_tail))
        )

    # the scan carry is the SAME state container the resident ObsBuffer
    # mirror and the fused tell+ask programs thread (one shape for "the
    # history as a unit" across every execution model)
    from .ops.kernels import HistoryState

    def step(base_key, c0, carry, i):
        values, active, losses, valid = carry
        # fold the warm offset too: a resumed run must not replay the
        # original run's per-step key stream
        key = jax.random.fold_in(jax.random.fold_in(base_key, c0), i)
        new_vals, new_act = suggest(key, values, active, losses, valid)
        new_vals = _shard_batch(new_vals, (None, trial_axis))
        new_act = _shard_batch(new_act, (None, trial_axis))
        if trainable:
            new_losses = eval_batch_trainable(
                key, new_vals, new_act
            ).astype(jnp.float32)
        else:
            new_losses = eval_batch(new_vals, new_act).astype(jnp.float32)
        new_losses = _shard_batch(new_losses, (trial_axis,))
        idx = c0 + i * B + jnp.arange(B)
        values = values.at[:, idx].set(new_vals)
        active = active.at[:, idx].set(new_act)
        losses = losses.at[idx].set(new_losses)
        valid = valid.at[idx].set(True)
        return HistoryState(values, active, losses, valid), new_losses

    @jax.jit
    def run(seed_arr, values, active, losses, valid, c0, best0):
        base_key = jax.random.key(seed_arr)
        if loss_threshold is None and no_progress_steps is None:
            (values, active, losses, valid), _ = jax.lax.scan(
                lambda carry, i: step(base_key, c0, carry, i),
                HistoryState(values, active, losses, valid),
                jnp.arange(n_steps),
            )
            n_done = jnp.int32(n_steps)
        else:
            thr = jnp.float32(
                loss_threshold if loss_threshold is not None else -jnp.inf
            )
            stale_cap = (
                jnp.int32(no_progress_steps)
                if no_progress_steps is not None
                else jnp.int32(n_steps + 1)
            )

            def cond(state):
                i, stop, _, _, _ = state
                return (i < n_steps) & ~stop

            def body(state):
                i, stop, best, stale, carry = state
                carry, new_losses = step(base_key, c0, carry, i)
                fin = jnp.isfinite(new_losses)
                batch_best = jnp.min(jnp.where(fin, new_losses, jnp.inf))
                improved = batch_best < best
                best = jnp.minimum(best, batch_best)
                # no_progress_loss parity: the stale counter only runs
                # once SOME finite best exists -- all-failed startup
                # batches must not stop the experiment
                stale = jnp.where(
                    improved | ~jnp.isfinite(best), 0, stale + 1
                )
                stop = (best <= thr) | (stale >= stale_cap)
                return i + 1, stop, best, stale, carry

            n_done, _, _, _, (values, active, losses, valid) = (
                jax.lax.while_loop(
                    cond, body,
                    (jnp.int32(0), best0 <= thr, best0,
                     jnp.int32(0),
                     HistoryState(values, active, losses, valid)),
                )
            )
        ok = valid & jnp.isfinite(losses)
        keyed = jnp.where(ok, losses, jnp.inf)
        best_i = jnp.argmin(keyed)
        return values, active, losses, valid, best_i, n_done

    # ---- graftrung bracket machinery (asha=) -----------------------------
    # One scan step = one BRACKET: B fresh configs; rung 0 trains every
    # lane ``rung_epochs`` epochs; an on-device promotion (shared
    # ``hyperband.rung_rank``: stable argsort, non-finite last) keeps the
    # best B/eta, and the survivors COMPACT into a statically narrower
    # vmap width to train eta x deeper -- rung by rung, unrolled at trace
    # time.  Masking dead lanes would save nothing under vmap (every lane
    # still computes); compaction is where the early-stopping compute win
    # comes from.  The promotion record (``rung_of``: the highest rung
    # each history slot reached, -1 for warm/untouched slots) rides the
    # scan carry next to the history, so chunk checkpoints capture it and
    # kill-and-resume stays bitwise; suggest keys fold the same GLOBAL
    # bracket index as the plain scan's step index, so chunked == flat.
    run_asha = None
    _asha_sub = None
    _asha_k = 1
    if asha_mode:
        from .hyperband import rung_rank

        if asha_shard:
            from .parallel.mesh import rung_submesh

            # ONE sub-mesh for the whole program, sized by the SMALLEST
            # rung (every wider rung width is a power-of-eta multiple of
            # it, so one gcd covers the whole ladder; per-rung sub-mesh
            # shrinking would put multiple device sets in one program).
            # k == 1 degenerates to the unsharded body: the bitwise-
            # parity anchor.
            _asha_sub, _asha_k = rung_submesh(
                mesh, trial_axis, asha_ladder[-1][0]
            )

        def _build_rung_train(width, n_ep, e0):
            """The rung-``r`` trainer at STATIC width: every live lane
            advances ``n_ep`` epochs from cumulative offset ``e0`` (the
            epoch counter a survivor sees is continuous across rungs,
            exactly ``compile_sha``'s ladder), then reports its loss."""

            def unsharded(states, vals, act):
                def one(s, vcol, acol):
                    cfg = _trial_cfg(vcol, acol)
                    s = jax.lax.fori_loop(
                        e0, e0 + n_ep,
                        lambda e, ss: fn.step_fn(ss, cfg, e),
                        s,
                    )
                    return s, fn.loss_fn(s, cfg)

                return jax.vmap(one, in_axes=(0, 1, 1))(states, vals, act)

            if not asha_shard or _asha_k == 1:
                return unsharded
            from jax.sharding import PartitionSpec as Pspec

            from .parallel.sharded import _shard_map

            def block(states, vals, act):
                # each device trains its member block collective-free;
                # the rung boundary pays ONE loss all_gather so the
                # (replicated) promotion ranking sees every member
                st, ls = unsharded(states, vals, act)
                return st, jax.lax.all_gather(ls, trial_axis, tiled=True)

            return _shard_map()(
                block, mesh=_asha_sub,
                in_specs=(Pspec(trial_axis), Pspec(None, trial_axis),
                          Pspec(None, trial_axis)),
                out_specs=(Pspec(trial_axis), Pspec()),
                check_vma=False,
            )

        _rung_train_fns = [
            _build_rung_train(width, n_ep, e0)
            for width, n_ep, e0 in asha_ladder
        ]

        def asha_bracket(base_key, c0, carry, i, collect=False):
            """One bracket: suggest B, write the slots, then the unrolled
            compacting rung ladder.  ``collect=True`` additionally
            returns the full-fidelity winner's (slot, loss, trained
            params) for the artifact io_callback seam."""
            hist, rung_of = carry
            values, active, losses, valid = hist
            key = jax.random.fold_in(jax.random.fold_in(base_key, c0), i)
            new_vals, new_act = suggest(key, values, active, losses, valid)
            ekeys = jax.random.split(jax.random.fold_in(key, 0x7EA1), B)
            cur_states = jax.vmap(_init_one, in_axes=(0, 1, 1))(
                ekeys, new_vals, new_act
            )
            # every bracket member owns its history slot up front;
            # per-rung losses and the promotion record overwrite in place
            idx = c0 + i * B + jnp.arange(B)
            values = values.at[:, idx].set(new_vals)
            active = active.at[:, idx].set(new_act)
            valid = valid.at[idx].set(True)
            cur_slots = idx
            cur_vals, cur_act = new_vals, new_act
            win = None
            for r, (width, n_ep, e0) in enumerate(asha_ladder):
                cur_states, cur_losses = _rung_train_fns[r](
                    cur_states, cur_vals, cur_act
                )
                cur_losses = cur_losses.astype(jnp.float32)
                losses = losses.at[cur_slots].set(cur_losses)
                rung_of = rung_of.at[cur_slots].set(jnp.int32(r))
                order = rung_rank(cur_losses, 1, width)[0]
                if r + 1 < a_n_rungs:
                    keep = asha_ladder[r + 1][0]
                    sel = order[:keep]
                    cur_states = jax.tree_util.tree_map(
                        lambda x: x[sel], cur_states
                    )
                    cur_vals = cur_vals[:, sel]
                    cur_act = cur_act[:, sel]
                    cur_slots = cur_slots[sel]
                elif collect:
                    w = order[0]
                    win = {
                        "slot": cur_slots[w].astype(jnp.int32),
                        "loss": cur_losses[w],
                        "params": jax.tree_util.tree_map(
                            lambda x: x[w], cur_states
                        ),
                    }
            new_carry = (
                HistoryState(values, active, losses, valid), rung_of
            )
            return (new_carry, win) if collect else new_carry

        def _asha_summary(hist, rung_of):
            """Progress 'best' = best among FULL-FIDELITY trials only: a
            rung-0 loss after one epoch is not comparable to a survivor's
            (the host-ASHA runners report the same way)."""
            ok = hist.valid & jnp.isfinite(hist.losses) & (
                rung_of == jnp.int32(a_n_rungs - 1)
            )
            best = jnp.min(jnp.where(ok, hist.losses, jnp.inf))
            done = jnp.sum(hist.valid.astype(jnp.int32))
            return best, done

        def _asha_best_host(losses_np, valid_np, rung_np):
            ok = (
                valid_np & np.isfinite(losses_np)
                & (rung_np == a_n_rungs - 1)
            )
            keyed = np.where(ok, losses_np, np.inf)
            if not np.isfinite(keyed).any():
                # degenerate fallback (every full-fidelity trial failed):
                # best finite loss at any rung, so _package_result can
                # still name a config before raising on the all-failed case
                keyed = np.where(
                    valid_np & np.isfinite(losses_np), losses_np, np.inf
                )
            return int(np.argmin(keyed))

        @jax.jit
        def run_asha(seed_arr, values, active, losses, valid, rung_of, c0):
            base_key = jax.random.key(seed_arr)

            def body(carry, i):
                return asha_bracket(base_key, c0, carry, i), None

            (hist, rung_of), _ = jax.lax.scan(
                body,
                (HistoryState(values, active, losses, valid), rung_of),
                jnp.arange(n_steps),
            )
            return (*tuple(hist), rung_of)

    # ---- chunked-scan machinery (chunk_size=) ----------------------------
    # the flat scan above dispatches once; the chunked twin dispatches one
    # compiled chunk program per chunk so every boundary is a progress /
    # checkpoint / resume point.  The per-step key folds the GLOBAL step
    # index, so the executed trial stream is bitwise the flat scan's.
    chunk_steps = n_chunks = None
    run_chunk = run_chunk_cb = None
    ck_guard = None
    resume_default = bool(resume)
    if chunked and asha_mode:
        from jax.experimental import io_callback

        chunk_steps = -(-int(chunk_size) // B)
        n_chunks = -(-n_steps // chunk_steps)

        def _asha_chunk_impl(seed_arr, values, active, losses, valid,
                             rung_of, c0, chunk_idx, collect=False):
            base_key = jax.random.key(seed_arr)

            def body(carry, j):
                i = chunk_idx * chunk_steps + j
                if collect:
                    # tail-padded steps emit the zero winner row; the
                    # artifact sink drops them by count on the host
                    return jax.lax.cond(
                        i < n_steps,
                        lambda c: asha_bracket(
                            base_key, c0, c, i, collect=True
                        ),
                        lambda c: (c, _winner_zeros()),
                        carry,
                    )
                return jax.lax.cond(
                    i < n_steps,
                    lambda c: asha_bracket(base_key, c0, c, i),
                    lambda c: c,
                    carry,
                ), None

            (hist, rung_of), ys = jax.lax.scan(
                body,
                (HistoryState(values, active, losses, valid), rung_of),
                jnp.arange(chunk_steps),
            )
            best, done = _asha_summary(hist, rung_of)
            out = (*tuple(hist), rung_of, best, done)
            return (out, ys) if collect else out

        run_chunk = jax.jit(_asha_chunk_impl)

        if artifact_callback is not None:
            # abstract one-trial state pytree: the zero template the
            # padded tail steps emit in place of a winner row
            _state_struct = jax.eval_shape(
                lambda s, v, a: _init_one(jax.random.key(s), v, a),
                jax.ShapeDtypeStruct((), np.uint32),
                jax.ShapeDtypeStruct((D,), jnp.float32),
                jax.ShapeDtypeStruct((D,), jnp.bool_),
            )

            def _winner_zeros():
                return {
                    "loss": jnp.float32(0),
                    "params": jax.tree_util.tree_map(
                        lambda t: jnp.zeros(t.shape, t.dtype),
                        _state_struct,
                    ),
                    "slot": jnp.int32(0),
                }

        if progress_callback is not None or artifact_callback is not None:
            if progress_callback is not None:
                def _progress_sink(best, done, chunk_idx):
                    progress_callback({
                        "chunk": int(chunk_idx),
                        "trials_done": int(done),
                        "best_loss": float(best),
                    })

            if artifact_callback is not None:
                def _artifact_sink(slots, wlosses, params, chunk_idx):
                    done_prev = int(chunk_idx) * int(chunk_steps)
                    n_real = min(int(chunk_steps), n_steps - done_prev)
                    for j in range(n_real):
                        artifact_callback({
                            "bracket": done_prev + j,
                            "slot": int(slots[j]),
                            "loss": float(wlosses[j]),
                            "params": jax.tree_util.tree_map(
                                lambda x: np.asarray(x)[j], params
                            ),
                        })

            def _asha_cb_impl(seed_arr, values, active, losses, valid,
                              rung_of, c0, chunk_idx):
                if artifact_callback is not None:
                    out, ys = _asha_chunk_impl(
                        seed_arr, values, active, losses, valid,
                        rung_of, c0, chunk_idx, collect=True,
                    )
                    # rung winners stream through the SAME declared
                    # io_callback seam as progress rows (GL401's
                    # per-program escape hatch): one ordered callback
                    # per chunk carrying every bracket winner's trained
                    # params -- cadence-off dispatches never build ys
                    io_callback(
                        _artifact_sink, None, ys["slot"], ys["loss"],
                        ys["params"], chunk_idx, ordered=True,
                    )
                else:
                    out = _asha_chunk_impl(
                        seed_arr, values, active, losses, valid,
                        rung_of, c0, chunk_idx,
                    )
                if progress_callback is not None:
                    io_callback(
                        _progress_sink, None, out[5], out[6], chunk_idx,
                        ordered=True,
                    )
                return out

            run_chunk_cb = jax.jit(_asha_cb_impl)

        if checkpoint_path is not None:
            from .hyperband import _algo_identity, _space_fingerprint
            from .pyll.base import as_apply

            ck_guard = [
                "device-loop-chunk", 1, str(algo),
                _space_fingerprint(as_apply(space)), _algo_identity(fn),
                int(n_steps), int(B), int(chunk_steps), int(cap),
                # the asha ladder is part of the experiment identity: a
                # bundle from a different rung geometry must refuse
                "asha", a_eta, a_rung_epochs, a_n_rungs,
            ]

    elif chunked:
        from jax.experimental import io_callback

        from .ops.kernels import history_summary

        chunk_steps = -(-int(chunk_size) // B)
        n_chunks = -(-n_steps // chunk_steps)

        def _chunk_step(base_key, c0, carry, i):
            # tail-chunk padding: steps past n_steps are masked no-ops
            return jax.lax.cond(
                i < n_steps,
                lambda c: step(base_key, c0, c, i)[0],
                lambda c: c,
                carry,
            )

        def _chunk_impl(seed_arr, values, active, losses, valid, c0,
                        chunk_idx):
            base_key = jax.random.key(seed_arr)

            def body(carry, j):
                i = chunk_idx * chunk_steps + j
                return _chunk_step(base_key, c0, carry, i), None

            carry, _ = jax.lax.scan(
                body, HistoryState(values, active, losses, valid),
                jnp.arange(chunk_steps),
            )
            best, done = history_summary(carry)
            return (*tuple(carry), best, done)

        run_chunk = jax.jit(_chunk_impl)

        if progress_callback is not None:
            def _progress_sink(best, done, chunk_idx):
                progress_callback({
                    "chunk": int(chunk_idx),
                    "trials_done": int(done),
                    "best_loss": float(best),
                })

            def _chunk_cb_impl(seed_arr, values, active, losses, valid,
                               c0, chunk_idx):
                out = _chunk_impl(seed_arr, values, active, losses,
                                  valid, c0, chunk_idx)
                # the ONLY sanctioned host hop inside a compiled program
                # family: declared in the graftir registration's
                # allowed_callbacks (GL401's explicit escape hatch)
                io_callback(
                    _progress_sink, None, out[4], out[5], chunk_idx,
                    ordered=True,
                )
                return out

            run_chunk_cb = jax.jit(_chunk_cb_impl)

        if checkpoint_path is not None:
            from .hyperband import _algo_identity, _space_fingerprint
            from .pyll.base import as_apply

            ck_guard = [
                "device-loop-chunk", 1, str(algo),
                _space_fingerprint(as_apply(space)), _algo_identity(fn),
                int(n_steps), int(B), int(chunk_steps), int(cap),
            ]

    def _runner_chunked(seed, return_trials, init, resume_now):
        from .distributed.faults import REAL_FS

        fs_ = REAL_FS if fs is None else fs
        seed_u = int(seed) % (2**32)
        state = None
        c0 = 0
        start_chunk = 0
        init_state = init_c0 = None
        if init is not None:
            iv, ia, il, ivd, init_c0, _ = _unpack_init(init)
            init_state = (iv, ia, il, ivd)
            if asha_mode:
                # warm trials predate this run's brackets: no rung record
                init_state += (np.full(cap, -1, dtype=np.int32),)
        if resume_now:
            if checkpoint_path is None:
                raise ValueError("resume=True needs checkpoint_path")
            from .exceptions import CheckpointError
            from .utils.checkpoint import load_device_chunk

            if fs_.exists(checkpoint_path):
                bundle = load_device_chunk(
                    checkpoint_path, guard=ck_guard, fs=fs_
                )
                if int(bundle["seed"]) != seed_u:
                    raise CheckpointError(
                        f"chunk checkpoint {checkpoint_path!r} was "
                        f"written by seed {bundle['seed']}; this run "
                        f"uses seed {seed_u} -- the resumed stream "
                        "would diverge; refusing to resume"
                    )
                if init_c0 is not None and int(bundle["c0"]) != init_c0:
                    raise CheckpointError(
                        f"chunk checkpoint {checkpoint_path!r} records "
                        f"a warm offset of {bundle['c0']} trials but "
                        f"init= holds {init_c0}; refusing to resume"
                    )
                c0 = int(bundle["c0"])
                start_chunk = int(bundle["chunk_next"])
                state = (bundle["values"], bundle["active"],
                         bundle["losses"], bundle["valid"])
                if asha_mode:
                    state += (bundle["rung_of"],)
        if state is None:
            if init_state is not None:
                state, c0 = init_state, init_c0
            else:
                state = _zero_state()
        out = None
        n_state = 5 if asha_mode else 4
        for ci in range(start_chunk, n_chunks):
            # artifact streaming is per-bracket, not cadenced: every
            # chunk dispatches the cb twin when it is armed
            use_cb = run_chunk_cb is not None and (
                artifact_callback is not None
                or (ci + 1) % int(progress_every) == 0
                or ci == n_chunks - 1
            )
            prog = run_chunk_cb if use_cb else run_chunk
            out = prog(
                np.uint32(seed_u), *state, np.int32(c0), np.int32(ci)
            )
            state = out[:n_state]
            fs_.crashpoint("device_loop_after_chunk_before_ckpt")
            if checkpoint_path is not None and (
                (ci + 1) % int(checkpoint_every) == 0
                or ci == n_chunks - 1
            ):
                from .utils.checkpoint import save_device_chunk

                host = jax.device_get(state)  # one batched fetch
                bundle = {
                    "guard": ck_guard, "seed": seed_u, "c0": int(c0),
                    "chunk_next": ci + 1, "n_chunks": int(n_chunks),
                    "values": np.asarray(host[0]),
                    "active": np.asarray(host[1]),
                    "losses": np.asarray(host[2]),
                    "valid": np.asarray(host[3]),
                }
                if asha_mode:
                    bundle["rung_of"] = np.asarray(host[4])
                save_device_chunk(checkpoint_path, bundle, fs=fs_)
                fs_.crashpoint(
                    "device_loop_after_ckpt_before_next_chunk"
                )
        host = [np.asarray(a) for a in jax.device_get(state)]
        values, active, losses, valid = host[:4]
        rung_np = host[4] if asha_mode else None
        n_ran = n_steps * B
        total = c0 + n_ran
        if asha_mode:
            best_i = _asha_best_host(losses, valid, rung_np)
        else:
            keyed = np.where(
                valid & np.isfinite(losses), losses, np.inf
            )
            best_i = int(np.argmin(keyed))
        return _package_result(
            values[:, :total], active[:, :total], losses[:total],
            best_i, n_ran, total, return_trials,
            rung_of_np=None if rung_np is None else rung_np[:total],
        )

    cat_dims = set(ps.cat_idx.tolist())

    zero_buffers = []  # device-resident, reused by every cold run
    run_vmapped = []  # lazily-built vmap-over-seeds twin of `run`

    def _runner_seeds(seeds, return_trials):
        """Vectorized seed sweep: the WHOLE experiment scan vmapped over
        a seed axis -- S independent sequential loops (own histories,
        own key streams) advance in lockstep inside one XLA program, so
        the fixed per-step cost that dominates the B=1 flagship mode
        (bench_artifacts/ROOFLINE.md round 5) is paid once for all S.
        A median-of-5-seeds study costs ~one seed's wall-clock.

        Semantics per seed are the single-seed program's (same suggest
        math on the same key stream derived from each seed); under
        early stopping the vmapped ``while_loop`` runs until every seed
        stops, freezing finished seeds -- results are unchanged, only
        the finished seeds' slack compute differs.  Returns a list of
        per-seed result dicts (exactly the single-seed shape).
        """
        S = len(seeds)
        if not run_vmapped:
            run_vmapped.append(jax.jit(jax.vmap(
                run, in_axes=(0, 0, 0, 0, 0, None, None)
            )))
        seeds_arr = np.asarray(
            [int(s) % (2**32) for s in seeds], dtype=np.uint32
        )
        zeros = (
            np.zeros((S, D, cap), dtype=np.float32),
            np.zeros((S, D, cap), dtype=bool),
            np.zeros((S, cap), dtype=np.float32),
            np.zeros((S, cap), dtype=bool),
        )
        out_dev = run_vmapped[0](
            seeds_arr, *zeros, np.int32(0), np.float32(np.inf)
        )
        values, active, losses, valid, best_i, n_done = jax.device_get(
            out_dev
        )
        outs = []
        for i in range(S):
            n_ran = int(n_done[i]) * B
            outs.append(_package_result(
                values[i][:, :n_ran], active[i][:, :n_ran],
                losses[i][:n_ran], int(best_i[i]), n_ran, n_ran,
                return_trials,
            ))
        return outs

    def _zero_state():
        zeros = (
            np.zeros((D, cap), dtype=np.float32),
            np.zeros((D, cap), dtype=bool),
            np.zeros(cap, dtype=np.float32),
            np.zeros(cap, dtype=bool),
        )
        if asha_mode:  # promotion record: -1 = no rung reached yet
            zeros += (np.full(cap, -1, dtype=np.int32),)
        if jax.process_count() > 1:
            # multi-process (jax.distributed) runtime: inputs
            # committed to one local device cannot feed a global-mesh
            # computation; hand jit host numpy instead -- uncommitted
            # inputs are placed by jit as fully-replicated over the
            # global mesh (same contract as
            # parallel.sharded._history_inputs)
            return zeros
        if not zero_buffers:  # non-donated, so safely reusable
            zero_buffers.append(jax.device_put(zeros))
        return zero_buffers[0]

    def _unpack_init(init):
        iv = np.asarray(init["values"], dtype=np.float32)
        ia = np.asarray(init["active"], dtype=bool)
        il = np.asarray(init["losses"], dtype=np.float32)
        c0 = il.shape[0]
        if c0 > W:
            raise ValueError(
                f"init history has {c0} trials but warm_capacity={W}; "
                "recompile with a larger warm_capacity"
            )
        values0 = np.zeros((D, cap), dtype=np.float32)
        active0 = np.zeros((D, cap), dtype=bool)
        losses0 = np.zeros(cap, dtype=np.float32)
        valid0 = np.zeros(cap, dtype=bool)
        values0[:, :c0] = iv
        active0[:, :c0] = ia
        losses0[:c0] = il
        valid0[:c0] = True
        best0 = np.float32(np.inf)
        fin = il[np.isfinite(il)]
        if fin.size:  # early-stop rules see the warm best
            best0 = np.float32(fin.min())
        return values0, active0, losses0, valid0, c0, best0

    def runner(seed=0, return_trials=False, init=None, resume=None):
        if chunked:
            if isinstance(seed, (list, tuple)) or (
                isinstance(seed, np.ndarray) and seed.ndim > 0
            ):
                raise ValueError(
                    "chunk_size does not compose with vectorized seed "
                    "sweeps; run seeds individually"
                )
            resume_now = bool(
                resume_default if resume is None else resume
            )
            return _runner_chunked(seed, return_trials, init, resume_now)
        if resume:
            raise ValueError(
                "resume rides the chunked path; pass chunk_size= (and "
                "checkpoint_path=) to compile_fmin"
            )
        if isinstance(seed, (list, tuple)) or (
            isinstance(seed, np.ndarray) and seed.ndim > 0
        ):
            if asha_mode:
                raise ValueError(
                    "asha= does not compose with vectorized seed "
                    "sweeps; run seeds individually"
                )
            if init is not None:
                raise ValueError(
                    "init= resume is single-seed; run the seed sweep "
                    "fresh or resume seeds individually"
                )
            return _runner_seeds(list(seed), return_trials)
        if asha_mode:
            if init is None:
                c0 = 0
                state0 = _zero_state()
            else:
                values0, active0, losses0, valid0, c0, _ = (
                    _unpack_init(init)
                )
                state0 = (values0, active0, losses0, valid0,
                          np.full(cap, -1, dtype=np.int32))
            out_dev = run_asha(
                np.uint32(int(seed) % (2**32)), *state0, np.int32(c0)
            )
            values, active, losses, valid, rung_np = (
                np.asarray(a) for a in jax.device_get(out_dev)
            )
            n_ran = n_steps * B
            total = c0 + n_ran
            best_i = _asha_best_host(losses, valid, rung_np)
            return _package_result(
                values[:, :total], active[:, :total], losses[:total],
                best_i, n_ran, total, return_trials,
                rung_of_np=rung_np[:total],
            )
        if init is None:
            c0 = 0
            best0 = np.float32(np.inf)
            values0, active0, losses0, valid0 = _zero_state()
        else:
            values0, active0, losses0, valid0, c0, best0 = (
                _unpack_init(init)
            )
        # scalars as host numpy (uncommitted) for the same multi-process
        # placement reason as the zero buffers above
        out_dev = run(
            np.uint32(int(seed) % (2**32)),
            values0, active0, losses0, valid0, np.int32(c0),
            np.float32(best0),
        )
        # ONE batched device->host fetch for every result (values/active/
        # losses/valid/best_i/n_done): per-array np.asarray fetches paid
        # one tunnel round-trip EACH and were 63% of a 1k-trial B=1
        # runner call (measured, bench_artifacts/ROOFLINE.md round 5);
        # device_get also forces completion (block_until_ready is a
        # no-op on remote-attached platforms)
        values, active, losses, valid, best_i, n_done = jax.device_get(
            out_dev
        )
        n_ran = int(n_done) * B
        total = c0 + n_ran
        return _package_result(
            np.asarray(values)[:, :total], np.asarray(active)[:, :total],
            np.asarray(losses)[:total], int(best_i), n_ran, total,
            return_trials,
        )

    def _package_result(values_np, active_np, losses_np, bi, n_ran, total,
                        return_trials, rung_of_np=None):
        if not np.isfinite(losses_np).any():
            from .exceptions import AllTrialsFailed

            raise AllTrialsFailed(
                "every on-device trial returned a non-finite loss"
            )

        best = {}
        for d, label in enumerate(ps.labels):
            if not active_np[d, bi]:
                continue
            v = float(values_np[d, bi])
            best[label] = int(round(v)) if d in cat_dims else v

        out = {
            "best": best,
            "best_loss": float(losses_np[bi]),
            "best_index": bi,
            # full experiment history (warm prefix + this run) -- feed
            # straight back in as ``init=`` to resume again
            "losses": losses_np,
            "values": values_np,
            "active": active_np,
            "n_evals": n_ran,
            "n_total": total,
        }
        if rung_of_np is not None:
            # graftrung promotion record: highest rung each slot reached
            # (-1 = warm/untouched); full fidelity is rung n_rungs-1
            out["rung_of"] = rung_of_np
            out["asha"] = {
                "eta": a_eta,
                "rung_epochs": a_rung_epochs,
                "n_rungs": a_n_rungs,
                "ladder": [tuple(row) for row in asha_ladder],
            }
        if return_trials:
            out["trials"] = _to_trials(ps, values_np, active_np, losses_np)
        return out

    # the jitted experiment program itself, exposed for the graftir
    # registry (analysis/ir.py traces it over abstract inputs) -- the
    # runner closure is the only other holder
    runner._compiled_run = run_asha if asha_mode else run
    runner._history_capacity = cap
    runner._packed_space = ps
    runner._compiled_chunk = run_chunk
    runner._compiled_chunk_cb = run_chunk_cb
    if asha_mode:
        runner._asha_ladder = list(asha_ladder)
        runner._asha_submesh_devices = _asha_k
    if chunked:
        runner._chunk_geometry = {
            "chunk_steps": chunk_steps,
            "n_chunks": n_chunks,
            "n_steps": n_steps,
            "batch_size": B,
        }
    return runner


def fmin_on_device(fn, space, max_evals, seed=0, return_trials=False, **kw):
    """One-shot convenience over :func:`compile_fmin` (compiles every
    call; use compile_fmin directly for seed sweeps)."""
    return compile_fmin(fn, space, max_evals, **kw)(
        seed=seed, return_trials=return_trials
    )


# ---------------------------------------------------------------------------
# graftir registration (hyperopt-tpu-lint --ir)
# ---------------------------------------------------------------------------

from .ops.compile import ProgramCapture, register_program  # noqa: E402


def _registry_quadratic(cfg):
    """The registry's reference objective (sum of squared offsets)."""
    import jax.numpy as jnp

    t = jnp.zeros((), jnp.float32)
    for label in sorted(cfg):
        t = t + (cfg[label] - 1.0) ** 2
    return t


def _history_args(runner, tail_dtypes):
    """Abstract input specs shared by every compile_fmin program: seed +
    the four history-carry arrays + per-family scalar tail."""
    import jax
    import jax.numpy as jnp

    cap = runner._history_capacity
    D = runner._packed_space.n_dims
    return (
        jax.ShapeDtypeStruct((), np.uint32),           # seed
        jax.ShapeDtypeStruct((D, cap), jnp.float32),   # values
        jax.ShapeDtypeStruct((D, cap), jnp.bool_),     # active
        jax.ShapeDtypeStruct((cap,), jnp.float32),     # losses
        jax.ShapeDtypeStruct((cap,), jnp.bool_),       # valid
    ) + tuple(jax.ShapeDtypeStruct((), dt) for dt in tail_dtypes)


def _scan_args(runner):
    """(..., c0, best0): the flat ``run`` program's tail."""
    import jax.numpy as jnp

    return _history_args(runner, (jnp.int32, jnp.float32))


def _chunk_args(runner):
    """(..., c0, chunk_idx): the chunk program's tail."""
    import jax.numpy as jnp

    return _history_args(runner, (jnp.int32, jnp.int32))


@register_program(
    "device_loop.scan",
    families=("hyperopt_tpu.device_loop:compile_fmin",),
)
def _registry_device_loop(p):
    """The whole-experiment scan (``compile_fmin``'s jitted ``run``):
    the suggest kernels, the vmapped objective, and the history carry
    fused into one program.  Traced over abstract zero-history inputs
    at a small step count -- the IR shape is step-count-scaled but
    structurally identical to production runs."""
    from .ops.compile import reference_space

    runner = compile_fmin(
        _registry_quadratic, reference_space(), max_evals=4, batch_size=1,
        algo="tpe", n_startup_jobs=2, n_EI_candidates=24,
    )
    return ProgramCapture(fn=runner._compiled_run, args=_scan_args(runner))


@register_program(
    "device_loop.chunked_scan",
    families=("hyperopt_tpu.device_loop:compile_fmin",),
)
def _registry_chunked_scan(p):
    """One chunk of the chunked experiment scan (``chunk_size=``): the
    same step math as ``device_loop.scan`` over ``chunk_steps`` global
    step indices, plus the chunk-boundary summary reductions.  No host
    callback -- the cadence-off dispatches must stay callback-free."""
    from .ops.compile import reference_space

    runner = compile_fmin(
        _registry_quadratic, reference_space(), max_evals=8, batch_size=1,
        algo="tpe", n_startup_jobs=2, n_EI_candidates=24, chunk_size=4,
    )
    return ProgramCapture(
        fn=runner._compiled_chunk, args=_chunk_args(runner)
    )


@register_program(
    "device_loop.chunked_scan_cb",
    families=("hyperopt_tpu.device_loop:compile_fmin",),
)
def _registry_chunked_scan_cb(p):
    """The progress-streaming twin of ``device_loop.chunked_scan``: the
    identical chunk body plus ONE ordered ``io_callback`` emitting the
    (trials done, best-so-far) row.  The callback is DECLARED via
    ``allowed_callbacks`` -- GL401's explicit per-program escape hatch;
    an undeclared callback anywhere else still fails the gate."""
    from .ops.compile import reference_space

    runner = compile_fmin(
        _registry_quadratic, reference_space(), max_evals=8, batch_size=1,
        algo="tpe", n_startup_jobs=2, n_EI_candidates=24, chunk_size=4,
        progress_callback=lambda row: None,
    )
    return ProgramCapture(
        fn=runner._compiled_chunk_cb, args=_chunk_args(runner),
        allowed_callbacks=("io_callback",),
        # shares the chunk closure with device_loop.chunked_scan (same
        # build, callback appended): promotion behavior already pinned
        x64_check=False,
    )


@register_program(
    "device_loop.train_step",
    families=("hyperopt_tpu.device_loop:compile_fmin",),
)
def _registry_train_step(p):
    """The stateful-objective experiment scan: a ``TrainableObjective``
    (per-trial MLP training -- init, inner ``fori_loop`` epochs, loss)
    vmapped across the trial batch inside the scan step.  Pins the
    train-inside-the-scan IR: no callbacks, no f64 creep from the
    grad/opt math, contract-stable cost."""
    from .models.synthetic import mlp_tune_objective, mlp_tune_space

    runner = compile_fmin(
        mlp_tune_objective(n_epochs=2, n_train=32, in_dim=4, hidden=8),
        mlp_tune_space(), max_evals=4, batch_size=2,
        algo="tpe", n_startup_jobs=2, n_EI_candidates=8,
    )
    return ProgramCapture(fn=runner._compiled_run, args=_scan_args(runner))


def _asha_args(runner, tail_dtypes):
    """The asha program families' abstract inputs: seed + the FIVE
    carry arrays (history + ``rung_of`` promotion record) + tail."""
    import jax
    import jax.numpy as jnp

    cap = runner._history_capacity
    D = runner._packed_space.n_dims
    return (
        jax.ShapeDtypeStruct((), np.uint32),           # seed
        jax.ShapeDtypeStruct((D, cap), jnp.float32),   # values
        jax.ShapeDtypeStruct((D, cap), jnp.bool_),     # active
        jax.ShapeDtypeStruct((cap,), jnp.float32),     # losses
        jax.ShapeDtypeStruct((cap,), jnp.bool_),       # valid
        jax.ShapeDtypeStruct((cap,), jnp.int32),       # rung_of
    ) + tuple(jax.ShapeDtypeStruct((), dt) for dt in tail_dtypes)


def _asha_registry_runner(**kw):
    """One shared build for the graftrung registry family: a tiny
    mlp-tune bracket (B=4, eta=2, two rungs) -- small enough to trace
    fast, structurally identical to production ladders."""
    from .models.synthetic import mlp_tune_objective, mlp_tune_space

    return compile_fmin(
        mlp_tune_objective(n_epochs=1, n_train=32, in_dim=4, hidden=8),
        mlp_tune_space(), max_evals=8, batch_size=4,
        algo="tpe", n_startup_jobs=2, n_EI_candidates=8,
        asha={"eta": 2, "rung_epochs": 1, "n_rungs": 2}, **kw,
    )


@register_program(
    "device_loop.asha_scan",
    families=("hyperopt_tpu.device_loop:compile_fmin",),
)
def _registry_asha_scan(p):
    """The fused-ASHA experiment scan (``asha=``): per-bracket suggest,
    the unrolled compacting rung ladder (train -> rank -> gather
    survivors) and the ``rung_of`` promotion record, all inside one
    program -- the graftrung tentpole's flat anchor."""
    import jax.numpy as jnp

    runner = _asha_registry_runner()
    return ProgramCapture(
        fn=runner._compiled_run, args=_asha_args(runner, (jnp.int32,))
    )


@register_program(
    "device_loop.asha_chunked_scan",
    families=("hyperopt_tpu.device_loop:compile_fmin",),
)
def _registry_asha_chunked_scan(p):
    """One chunk of the fused-ASHA scan: the same bracket math over
    ``chunk_steps`` global bracket indices plus the full-fidelity
    summary reductions.  Callback-free -- cadence-off dispatches must
    stay that way."""
    import jax.numpy as jnp

    runner = _asha_registry_runner(chunk_size=4)
    return ProgramCapture(
        fn=runner._compiled_chunk,
        args=_asha_args(runner, (jnp.int32, jnp.int32)),
    )


@register_program(
    "device_loop.asha_chunked_scan_cb",
    families=("hyperopt_tpu.device_loop:compile_fmin",),
)
def _registry_asha_chunked_scan_cb(p):
    """The streaming twin of ``device_loop.asha_chunked_scan``: the
    identical chunk body plus the DECLARED ordered ``io_callback``\\ s
    -- the progress row and the per-bracket rung-winner artifact rows
    (trained params out of the running program).  GL401's explicit
    per-program escape hatch covers both."""
    import jax.numpy as jnp

    runner = _asha_registry_runner(
        chunk_size=4,
        progress_callback=lambda row: None,
        artifact_callback=lambda row: None,
    )
    return ProgramCapture(
        fn=runner._compiled_chunk_cb,
        args=_asha_args(runner, (jnp.int32, jnp.int32)),
        allowed_callbacks=("io_callback",),
        # shares the bracket closure with device_loop.asha_chunked_scan
        # (same build, callbacks appended): promotion already pinned
        x64_check=False,
    )


def _to_trials(ps, values, active, losses, trials=None):
    """Rebuild a host ``Trials`` store from the device history (into
    ``trials`` when given -- the ``fmin(compiled=True)`` route fills
    the caller's store; a fresh one otherwise)."""
    from .base import JOB_STATE_DONE, STATUS_FAIL, STATUS_OK, Trials

    if trials is None:
        trials = Trials()
    n = values.shape[1]
    ids = trials.new_trial_ids(n)
    cat = set(ps.cat_idx.tolist())
    miscs = []
    for i, tid in enumerate(ids):
        t_idxs, t_vals = {}, {}
        for d, label in enumerate(ps.labels):
            if active[d, i]:
                v = float(values[d, i])
                t_idxs[label] = [tid]
                t_vals[label] = [int(round(v)) if d in cat else v]
            else:
                t_idxs[label] = []
                t_vals[label] = []
        miscs.append({
            "tid": tid,
            "cmd": ("domain_attachment", "FMinIter_Domain"),
            "workdir": None,
            "idxs": t_idxs,
            "vals": t_vals,
        })
    results = [
        {"status": STATUS_OK, "loss": float(losses[i])}
        if np.isfinite(losses[i])
        else {"status": STATUS_FAIL, "loss": None}
        for i in range(n)
    ]
    docs = trials.new_trial_docs(ids, [None] * n, results, miscs)
    for doc in docs:
        doc["state"] = JOB_STATE_DONE
    trials.insert_trial_docs(docs)
    trials.refresh()
    return trials
