"""Probabilistic mixture over suggest algorithms.

Capability parity with the reference's ``hyperopt/mix.py`` (SURVEY.md SS2).
"""

from __future__ import annotations

import numpy as np

from .pyll.stochastic import ensure_rng

__all__ = ["suggest"]


def suggest(new_ids, domain, trials, seed, p_suggest):
    """Call one of several suggest functions, chosen with probability p.

    ``p_suggest``: list of (probability, suggest_fn) pairs.  Use with e.g.
    ``partial(mix.suggest, p_suggest=[(0.8, tpe.suggest), (0.2, rand.suggest)])``.
    """
    rng = ensure_rng(seed)
    ps, suggests = zip(*p_suggest)
    ps = np.asarray(ps, dtype=float)
    if abs(ps.sum() - 1.0) > 1e-5:
        raise ValueError(f"p_suggest probabilities must sum to 1.0, got {ps.sum()}")
    idx = int(rng.choice(len(ps), p=ps / ps.sum()))
    return suggests[idx](
        new_ids, domain, trials, seed=int(rng.integers(2**31 - 1))
    )
