"""Exception types for hyperopt_tpu.

Capability parity with the reference's ``hyperopt/exceptions.py`` (see
SURVEY.md SS2: AllTrialsFailed, DuplicateLabel, InvalidTrial,
InvalidResultStatus, InvalidLoss).  Reference mount was empty; spec of
record is SURVEY.md.
"""


class HyperoptTpuError(Exception):
    """Base class for all hyperopt_tpu errors."""


class PyllImportError(HyperoptTpuError):
    """A pyll graph references an unknown scope symbol."""


class DuplicateLabel(HyperoptTpuError):
    """The same hyperparameter label was used for two different nodes."""


class InvalidTrial(HyperoptTpuError, ValueError):
    """A trial document failed validation."""


class InvalidResultStatus(HyperoptTpuError, ValueError):
    """An objective returned a result dict with a bad ``status``."""


class InvalidLoss(HyperoptTpuError, ValueError):
    """An objective returned a loss that is not a finite float (or None)."""


class InvalidAnnotatedParameter(HyperoptTpuError, ValueError):
    """An ``hp.*`` call was malformed (bad label or arguments)."""


class AllTrialsFailed(HyperoptTpuError):
    """Every trial in the experiment errored; there is no argmin."""


class CompileError(HyperoptTpuError):
    """The space compiler could not lower a search space to a JAX sampler."""


class CheckpointError(HyperoptTpuError):
    """A checkpoint / write-ahead-log artifact could not be used for
    resume: truncated or corrupt pickle, torn mid-file WAL record, or a
    guard-fingerprint mismatch (the snapshot belongs to a different
    space/algo/objective).  The message names the offending file and,
    when one exists, the last-good artifact to fall back to."""


class TrialTimeout(HyperoptTpuError):
    """A single objective evaluation exceeded the driver's per-trial
    deadline (``fmin(trial_timeout=...)``); recorded as a STATUS_FAIL
    trial, never propagated."""


class BackendError(HyperoptTpuError):
    """A distributed-transport (filequeue / mongo) operation failed.

    The transient-vs-fatal split below is the contract
    ``distributed._common.with_retries`` classifies by: transient
    failures (mount blips, reconnects) are retried with exponential
    backoff, fatal ones surface immediately."""


class TransientBackendError(BackendError):
    """A retryable transport failure (the ESTALE/EIO/AutoReconnect
    class): raise this to ask the retry scaffold for another attempt."""


class FatalBackendError(BackendError):
    """A non-retryable transport failure (corruption, permission,
    protocol violation): never retried, always surfaced."""


class ClaimLost(BackendError):
    """A worker's reservation was reaped (and possibly re-claimed)
    while it was still evaluating -- detected at completion time so the
    stale worker drops its result instead of racing the re-run into a
    duplicate DONE doc."""


class ServeError(HyperoptTpuError):
    """Base of the suggestion service's runtime-protection (graftguard)
    errors.  Every one is a *structured refusal*: the service stays
    healthy, the client gets a typed reason and (where it makes sense)
    a hint about what to do next."""


class Overloaded(ServeError):
    """The service refused to admit an ask: the bounded queue is at its
    high-water mark, the study hit its fairness cap, the batcher's
    circuit breaker is open, or the service is draining for a rolling
    restart.  ``retry_after`` (seconds, may be None while draining) is
    computed from current queue occupancy and the p50 ask latency --
    back off that long and resubmit.  ``reason`` is one of
    ``queue_full`` / ``study_queue_cap`` / ``circuit_open`` /
    ``draining``."""

    def __init__(self, message, retry_after=None, reason="queue_full"):
        super().__init__(message)
        self.retry_after = retry_after
        self.reason = reason


class DeadlineExpired(ServeError):
    """An ask's client deadline passed before the service could serve
    it -- shed at submit (already expired) or dropped from the queue
    (expired while waiting) instead of wasting a dispatch slot on an
    answer nobody is waiting for."""


class StudyPoisoned(ServeError):
    """The fused finite-check caught non-finite values in this study's
    slot (its resident history or this round's suggestion): the ask is
    failed back to this client only, the slot re-materializes from
    host truth, and sibling slots are untouched."""


class StudyQuarantined(StudyPoisoned):
    """The study tripped the finite-check K consecutive times and was
    evicted from the slotted batch (its host truth itself is poisoned,
    e.g. a told NaN loss).  Asks and tells are refused until the study
    is closed; sibling studies are unaffected."""


class DispatchTimeout(ServeError):
    """A device dispatch exceeded the scheduler's watchdog deadline.
    Treated as transient: the round retries once against a freshly
    re-materialized stacked state before failing the picked asks."""


class OwnershipLost(ServeError):
    """The serve-fleet twin of :class:`ClaimLost`: this replica's
    per-study claim/epoch token was taken over (failover or planned
    migration bumped the epoch), so the replica must drop the operation
    instead of double-serving a study it no longer owns.  A partitioned
    or zombie replica surfaces this on its next fenced ask/tell; the
    client retries through the router, which routes to the new owner."""


class ReplicaDead(ServeError):
    """A fleet replica marked dead (killed, crashed, or partitioned
    away from the router) was asked to serve: the router converts this
    into failover -- re-materializing the dead replica's studies on
    survivors -- and retries against the new owner."""


class NetworkTimeout(ServeError):
    """A socket read or write missed its deadline: the peer is
    connected but silent (black-hole partition, hung handler, or a
    slow-loris writer slower than the budget).  Raised instead of
    blocking a handler or client thread forever; routed into the same
    failover/retry machinery as a connection error -- the router marks
    the backend suspect and re-routes, the client resubmits with the
    exactly-once recover/re-tell discipline."""


class PeerUnreachable(ServeError):
    """A connection could not be established (refused, no route, DNS,
    or connect deadline) or was exhausted after bounded retries: the
    peer is gone rather than slow.  The terminal transport error a
    client surfaces when every retry budget is spent -- always typed,
    never a raw :class:`OSError`."""
