"""Successive halving and Hyperband, host-driven and fused on-device.

Budget-aware HPO schedulers (Li et al., 2018) absent from the reference:
evaluate many configurations at a small budget, keep the top ``1/eta``
at each rung, and spend the saved budget deepening the survivors.

Two execution modes, matching the rest of the framework:

* :func:`successive_halving` / :func:`hyperband` -- host drivers over an
  arbitrary budget-aware objective ``fn(config, budget) -> loss`` (any
  Python), suggesting rung-0 configurations through the standard algo
  seam (``rand.suggest`` / ``tpe_jax.suggest`` / ...) and recording
  every evaluation in a ``Trials`` store (``result["budget"]`` carries
  the rung budget).
* :func:`compile_sha` -- successive halving over TRAINING, fused: the
  population trains ``steps_per_rung`` under a ``lax.scan``, survivors'
  states/hypers are gathered on-device, and the next (smaller) rung is
  its own jitted program -- compute really shrinks by ``eta`` per rung,
  and partially-trained survivors CONTINUE from their state (learning-
  curve halving, not re-evaluation).  Same train-fn contract as
  :mod:`hyperopt_tpu.pbt`: ``train_fn(state, hypers, key) -> (state,
  losses[P])`` with population-leading pytrees.
"""

from __future__ import annotations

import functools
import logging
import math
import os

import numpy as np

logger = logging.getLogger(__name__)

__all__ = [
    "successive_halving",
    "hyperband",
    "asha",
    "compile_sha",
    "compile_hyperband",
    "budget_aware",
    "rung_schedule",
    "rung_rank",
]


def _budgets_integral(max_budget, min_budget):
    """The shared integral-budget rule: fn sees ints whenever
    ``max_budget`` is integral (python int OR any ``numbers.Integral``,
    e.g. ``np.int64`` -- an epoch-count objective asserting ints must
    not see 9.0 because the budget came through numpy) and
    ``min_budget`` is a whole number (so epoch-count objectives survive
    hyperband's whole-float bracket minimums).  One definition for
    every driver."""
    import numbers

    return (
        isinstance(max_budget, numbers.Integral)
        and float(min_budget) == round(float(min_budget))
    )


def _algo_identity(algo):
    """Checkpoint-guard identity of a suggest algo: resuming a run
    under a different algorithm silently changes the experiment.
    ``functools.partial`` unwraps (fully -- wrappers stack) to its base
    fn; tuned kwargs are not fingerprintable in general."""
    a = algo
    while isinstance(a, functools.partial):
        a = a.func
    return (
        f"{getattr(a, '__module__', '?')}."
        f"{getattr(a, '__qualname__', type(a).__name__)}"
    )


def _check_evaluator_arity(evaluator):
    """Fail fast on a mismatched evaluator (e.g. one written against an
    older ``(vals, budget)`` seam): inside the failure-tolerant worker
    the TypeError would burn every job as a failed trial instead.

    ``inspect.signature`` itself raises ValueError (TypeError on some
    older CPythons) for C-implemented callables without introspectable
    signatures -- those are ACCEPTED, not rejected: a valid evaluator
    without a signature must not crash the driver with an unrelated
    error (ADVICE r5), and a genuinely mismatched one still surfaces at
    its first call."""
    import inspect

    try:
        sig = inspect.signature(evaluator)
    except (ValueError, TypeError):
        return
    try:
        sig.bind({}, {}, 1)
    except TypeError:
        raise TypeError(
            f"evaluator must accept (vals, cfg, budget); got signature {sig}"
        )


def _rstate_fingerprint(rstate):
    """Checkpoint-guard identity of a generator's CURRENT position:
    stale snapshot files from a run with a different seed (or a
    different point in a shared stream) must be refused, not silently
    resurrected -- while a re-run with the identical seed may resume,
    because it would recompute the identical result.

    The state is serialized canonically (sorted-key json, arrays via
    tolist) -- ``repr`` would truncate array-state generators
    (MT19937's 624-word state) under small ``np.printoptions``
    thresholds, refusing valid same-seed resumes and colliding
    genuinely different states."""
    import hashlib
    import json

    def norm(v):
        if isinstance(v, dict):
            return {k: norm(v[k]) for k in sorted(v)}
        if isinstance(v, np.ndarray):
            return v.tolist()
        if isinstance(v, np.generic):
            return v.item()
        return v

    blob = json.dumps(norm(rstate.bit_generator.state), sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _rung_budget(min_budget, eta, r, integral):
    """Rung ``r``'s budget under the shared integral rule -- ONE
    definition for every host driver (sha/hyperband/asha), so their
    budget materialization cannot drift."""
    b = float(min_budget) * eta**r
    return int(round(b)) if integral else b


def _vals_of(doc):
    """Index-form config of a suggested trial doc (single-valued labels
    only -- inactive conditional branches have empty vals lists)."""
    return {
        k: v[0] for k, v in doc["misc"]["vals"].items() if len(v) == 1
    }


def _int_log(ratio, eta):
    """Largest integer k with eta**k <= ratio (float-tolerant: exact
    eta-powers like 243/1 with eta=3 must count fully -- math.log gives
    4.9999... there and floor silently drops the max-budget rung)."""
    k = 0
    b = 1.0
    while b * eta <= ratio * (1 + 1e-9):
        b *= eta
        k += 1
    return k


def rung_schedule(n_configs, eta, n_rungs=None, steps_per_rung=1):
    """The shared SHA rung ladder: ``[(width, steps, offset), ...]``.

    ONE definition of the successive-halving geometry for every
    on-device runner (:func:`compile_sha`'s per-rung programs and the
    compiled-ASHA device loop, :func:`hyperopt_tpu.device_loop.
    compile_fmin` with ``asha=``), so the two regimes cannot drift:
    rung ``r`` runs its surviving ``n_configs // eta**r`` members for
    ``steps_per_rung * eta**r`` INCREMENTAL steps (budgets continue
    from the trained state -- learning-curve halving), starting at
    cumulative step ``offset``.  ``n_configs`` must be a power of
    ``eta`` so every promotion keeps an exact ``1/eta``;``n_rungs``
    defaults to halving down to a single survivor.
    """
    p0 = int(n_configs)
    eta = int(eta)
    if eta < 2:
        raise ValueError(f"eta={eta} must be >= 2")
    max_rungs = _int_log(p0, eta)
    if eta**max_rungs != p0:
        raise ValueError(
            f"n_configs={p0} must be a power of eta={eta}"
        )
    if n_rungs is None:
        n_rungs = max_rungs + 1
    if not 1 <= int(n_rungs) <= max_rungs + 1:
        raise ValueError(
            f"n_rungs={n_rungs} must be in [1, {max_rungs + 1}] for "
            f"n_configs={p0}, eta={eta}"
        )
    ladder = []
    offset = 0
    for r in range(int(n_rungs)):
        steps = int(steps_per_rung) * eta**r
        ladder.append((p0 // eta**r, steps, offset))
        offset += steps
    return ladder


def rung_rank(losses, replicas, p_live):
    """Shared on-device promotion ranking: ``[R * p_live]`` losses ->
    ``[R, p_live]`` GLOBAL member indices, best first within each
    bracket.  Non-finite losses rank last (inf-keyed); ties break by
    member order (stable argsort) -- the single promotion rule both
    :func:`compile_sha` rung programs and the compiled-ASHA scan use,
    so a rung's survivors are the same members under every execution
    model."""
    import jax.numpy as jnp

    keyed = jnp.where(jnp.isfinite(losses), losses, jnp.inf)
    by_rep = keyed.reshape(replicas, p_live)
    order = jnp.argsort(by_rep, axis=1)  # [R, p_live]
    return order + (
        jnp.arange(replicas, dtype=order.dtype)[:, None] * p_live
    )


def successive_halving(
    fn,
    space,
    max_budget,
    eta=3,
    n_configs=None,
    min_budget=1,
    algo=None,
    trials=None,
    rstate=None,
    checkpoint=None,
    checkpoint_every=1,
):
    """One successive-halving bracket over a budget-aware objective.

    Args:
      fn: ``fn(config, budget) -> loss`` (or a dict with ``"loss"``).
      space: an ``hp.*`` search space.
      max_budget / min_budget: budget of the last / first rung; rung
        budgets grow by ``eta`` (kept integral -- fn sees ints -- when
        ``max_budget`` is an int and ``min_budget`` is a whole number,
        so epoch-count objectives work through :func:`hyperband` too,
        whose bracket min-budgets arrive as whole floats).
      eta: keep the top ``1/eta`` configurations per rung.
      n_configs: rung-0 population (default: ``eta ** (n_rungs - 1)`` so
        one configuration survives to ``max_budget``).
      algo: suggest function for rung-0 configs (default random search).
      trials: optional ``Trials`` store; every evaluation is recorded as
        a completed trial whose ``result["budget"]`` is its rung budget.
      rstate: ``np.random.Generator`` (reproducibility contract).
      checkpoint: optional path for durable kill/resume (the driver is
        a serial loop over (rung, member), so the snapshot -- trials
        store, rung bookkeeping, survivor tids, via the atomic-rename
        pickle -- is written every ``checkpoint_every`` evaluations,
        plus at every rung boundary, and resuming reproduces the
        uninterrupted run bitwise).  A snapshot from a different
        ladder/space/algo/seed is refused; the restored trials REPLACE
        the ``trials=`` argument.  Raise ``checkpoint_every`` when
        pickling a large shared trials store every evaluation measures
        as the bottleneck (cheap objectives under :func:`hyperband`).

    Returns ``{"best": config, "best_loss": loss, "rungs": [...]}``.
    """
    from .base import Domain, Trials
    from . import rand as rand_mod
    from .fmin import space_eval

    if rstate is None:
        rstate = np.random.default_rng()
    if algo is None:
        algo = rand_mod.suggest
    if trials is None:
        trials = Trials()
    n_rungs = _int_log(max_budget / min_budget, eta) + 1
    if n_configs is None:
        n_configs = eta ** (n_rungs - 1)
    domain = Domain(fn, space, pass_expr_memo_ctrl=False)
    integral = _budgets_integral(max_budget, min_budget)

    # generator position BEFORE the seed draw: the guard must identify
    # the run (a stale snapshot from a different seed is refused; the
    # identical seed would recompute the identical result, so resuming
    # it is sound -- which also requires fingerprinting fn and algo:
    # an edited objective resumed at the same seed would otherwise
    # silently return the OLD objective's answer)
    rs_fp = _rstate_fingerprint(rstate)
    snap = None
    ck_guard = None
    if checkpoint is not None:
        if int(checkpoint_every) < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got {checkpoint_every!r}"
            )
        ck_guard = (
            "sha", n_rungs, float(max_budget), float(min_budget),
            float(eta), int(n_configs), _algo_identity(algo),
            _algo_identity(fn), _space_fingerprint(domain.expr), rs_fp,
        )
        if os.path.exists(checkpoint):
            # refuse BEFORE the seed draw: a refused resume must not
            # mutate the caller's generator as a side effect
            from .utils.checkpoint import load_guarded

            snap = load_guarded(checkpoint, ck_guard)
    # ALWAYS drawn, resuming or not: a caller sharing one rstate across
    # brackets (hyperband) must see the same stream either way
    seed = int(rstate.integers(0, 2**31 - 1))

    def config_of(doc):
        return space_eval(space, _vals_of(doc))

    def _docs_by_tid(wanted):
        m = {t["tid"]: t for t in trials._dynamic_trials}
        return [m[t] for t in wanted]

    if snap is None:
        ids = trials.new_trial_ids(n_configs)
        docs = algo(ids, domain, trials, seed)
        trials.insert_trial_docs(docs)
        trials.refresh()
        # mutate the STORED docs (insert may copy) so results land in
        # the trials store, not in dead suggestion copies
        tids = {d["tid"] for d in docs}
        live = [t for t in trials._dynamic_trials if t["tid"] in tids]
        r0, j0, rungs, new_ids, scored_tids = 0, 0, [], None, []
    else:
        trials = snap["trials"]
        live = _docs_by_tid(snap["live_tids"])
        r0, j0 = snap["r"], snap["j"]
        rungs = snap["rungs"]
        new_ids = snap["new_ids"]
        scored_tids = snap["scored"]  # [(loss, tid)] of the partial rung

    def _write(r, j, scored, live, rungs, new_ids):
        from .utils.checkpoint import save_trials

        save_trials({
            "guard": ck_guard,
            "trials": trials,
            "r": r,
            "j": j,
            "scored": [(l, d["tid"]) for l, d in scored],
            "live_tids": [d["tid"] for d in live],
            "rungs": rungs,
            "new_ids": new_ids,
        }, checkpoint)

    import copy as _copy

    scored = None  # stays None when resuming an already-finished run
    for r in range(r0, n_rungs):
        b = _rung_budget(min_budget, eta, r, integral)
        if r > 0 and new_ids is None:
            new_ids = trials.new_trial_ids(len(live))
        if r == r0 and scored_tids:
            restored = _docs_by_tid([t for _, t in scored_tids])
            scored = [
                (l, d) for (l, _), d in zip(scored_tids, restored)
            ]
        else:
            scored = []
        for j in range(j0 if r == r0 else 0, len(live)):
            doc = live[j]
            loss = fn(config_of(doc), b)
            if isinstance(loss, dict):
                loss = loss["loss"]
            result = {"status": "ok", "loss": float(loss), "budget": b}
            if r == 0:
                # rung 0 completes the suggested trials themselves
                doc["result"] = result
                doc["state"] = 2  # JOB_STATE_DONE
                rec = doc
            else:
                # promotions append a NEW trial per (config, budget):
                # lower-rung results stay in the store (learning-curve
                # history), never overwritten
                tid = new_ids[j]
                misc = _copy.deepcopy(doc["misc"])
                misc["tid"] = tid
                misc["idxs"] = {
                    k: ([tid] if v else []) for k, v in misc["idxs"].items()
                }
                (rec,) = trials.new_trial_docs(
                    [tid], [None], [result], [misc]
                )
                rec["state"] = 2
                trials.insert_trial_docs([rec])
                # the STORED copy is the record scored/promoted from;
                # insert appends, so scan from the END (O(1) here, not
                # O(store) per evaluation under a shared hyperband store)
                for t in reversed(trials._dynamic_trials):
                    if t["tid"] == tid:
                        rec = t
                        break
            scored.append((float(loss), rec))
            if (
                checkpoint is not None
                and (j + 1) % int(checkpoint_every) == 0
                and j + 1 < len(live)  # the rung-boundary write is
                # about to supersede a last-evaluation snapshot
            ):
                _write(r, j + 1, scored, live, rungs, new_ids)
        trials.refresh()
        scored.sort(key=lambda t: (not np.isfinite(t[0]), t[0]))
        rungs.append({
            "budget": b,
            "n": len(scored),
            "best_loss": scored[0][0],
        })
        n_keep = max(1, len(scored) // eta)
        live = [doc for _, doc in scored[:n_keep]]
        new_ids = None
        if checkpoint is not None:
            _write(r + 1, 0, [], live, rungs, None)
    if scored is None:
        # resumed a checkpoint written at the FINAL rung boundary: the
        # run had already finished; its answer is the last rung's best
        best_loss, best_doc = rungs[-1]["best_loss"], live[0]
    else:
        best_loss, best_doc = scored[0]
    return {
        "best": config_of(best_doc),
        "best_loss": best_loss,
        "rungs": rungs,
        "trials": trials,
    }


def hyperband(fn, space, max_budget, eta=3, min_budget=1, algo=None,
              rstate=None, trials=None, checkpoint=None,
              checkpoint_every=1):
    """Full Hyperband: every bracket of successive halving from the most
    exploratory (many configs, tiny budget) to a single full-budget
    bracket, sharing one ``Trials`` store.  Returns the overall best.

    Brackets run serially HERE because the objective is arbitrary host
    Python (each evaluation is its own call, as in the reference).  For
    JAX-traceable training the fused path packs brackets instead:
    ``compile_sha(replicas=K)`` trains K independent brackets inside
    every rung program, so K bracket results cost roughly one bracket's
    wall-clock on an underutilized chip (measured -- BASELINE.md SHA
    row).

    ``checkpoint`` makes the spread durable (the
    ``compile_hyperband``-shaped contract): a bracket-boundary snapshot
    at ``checkpoint`` (trials, generator state, completed brackets,
    incumbent) plus per-bracket :func:`successive_halving` snapshots at
    ``checkpoint + ".s<s>"``; resuming skips completed brackets,
    continues the in-flight one mid-rung, and reproduces the
    uninterrupted run bitwise.
    """
    from .base import Trials

    if rstate is None:
        rstate = np.random.default_rng()
    if trials is None:
        trials = Trials()
    s_max = _int_log(max_budget / min_budget, eta)
    best = None
    brackets = []
    s0 = s_max
    ck_guard = None
    if checkpoint is not None:
        from .base import Domain
        from . import rand as rand_mod

        algo_id = _algo_identity(
            algo if algo is not None else rand_mod.suggest
        )
        ck_guard = (
            "hyperband", s_max, float(max_budget), float(min_budget),
            float(eta), type(rstate.bit_generator).__name__, algo_id,
            _algo_identity(fn),
            _space_fingerprint(
                Domain(fn, space, pass_expr_memo_ctrl=False).expr
            ),
            # run identity: the generator's ENTRY position -- a
            # completed snapshot resumed under a different seed must be
            # refused, not silently returned as the old run's answer
            _rstate_fingerprint(rstate),
        )
        if os.path.exists(checkpoint):
            from .utils.checkpoint import load_guarded

            snap = load_guarded(checkpoint, ck_guard)
            trials = snap["trials"]
            brackets = snap["brackets"]
            best = snap["best"]
            s0 = snap["next_s"]
            rstate = np.random.Generator(type(rstate.bit_generator)())
            rstate.bit_generator.state = snap["rstate"]
            # sweep .s files of brackets the main snapshot already
            # subsumes: a kill between the main write and the .s
            # removal must not leave a stale file that blocks a later
            # fresh run at this path
            for s in range(s_max, s0, -1):
                try:
                    os.remove(f"{checkpoint}.s{s}")
                except FileNotFoundError:
                    pass
    for s in range(s0, -1, -1):
        n = int(math.ceil((s_max + 1) * eta**s / (s + 1)))
        out = successive_halving(
            fn, space,
            max_budget=max_budget,
            min_budget=max_budget / eta**s,
            eta=eta,
            n_configs=n,
            algo=algo,
            trials=trials,
            rstate=rstate,
            checkpoint=(
                None if checkpoint is None else f"{checkpoint}.s{s}"
            ),
            checkpoint_every=checkpoint_every,
        )
        trials = out["trials"]  # a resumed bracket restored its own store
        brackets.append({"s": s, **{k: out[k] for k in ("rungs",)}})
        if best is None or out["best_loss"] < best["best_loss"]:
            best = {"best": out["best"], "best_loss": out["best_loss"]}
        if checkpoint is not None:
            from .utils.checkpoint import save_trials

            save_trials({
                "guard": ck_guard,
                "trials": trials,
                "brackets": brackets,
                "best": best,
                "next_s": s - 1,
                "rstate": rstate.bit_generator.state,
            }, checkpoint)
            # the bracket is fully subsumed by the main snapshot now;
            # leaving its .s file would permanently block a FRESH run
            # at this path after the main checkpoint is removed (the
            # stale guard mismatches and refuses)
            try:
                os.remove(f"{checkpoint}.s{s}")
            except FileNotFoundError:
                pass
    return {
        "best": best["best"],
        "best_loss": best["best_loss"],
        "brackets": brackets,
        "trials": trials,
    }


def budget_aware(base_algo=None, min_obs=8):
    """BOHB-style model fitting for rung-0 suggestions.

    Losses evaluated at different budgets are not comparable (a cheap
    noisy rung's losses would pollute the posterior), so the wrapped
    algo fits its model ONLY on observations from the highest budget
    with at least ``min_obs`` completed trials (falling back to the
    most-populated budget, then to everything, while data is scarce) --
    the model-fitting rule of BOHB (Falkner et al., 2018) on top of any
    suggest algo at the standard plugin seam.

        hyperband(fn, space, max_budget=81,
                  algo=budget_aware(tpe_jax.suggest))
    """
    from collections import Counter

    from .base import trials_from_docs

    def algo(new_ids, domain, trials, seed, **kw):
        nonlocal base_algo
        if base_algo is None:
            from . import tpe_jax

            base_algo = tpe_jax.suggest
        counts = Counter(
            t["result"]["budget"]
            for t in trials.trials
            if t.get("result")
            and t["result"].get("loss") is not None
            and t["result"].get("budget") is not None
        )
        if counts:
            eligible = [b for b, c in counts.items() if c >= min_obs]
            target = max(eligible) if eligible else max(
                counts, key=lambda b: (counts[b], b)
            )
            docs = [
                t for t in trials.trials
                if t.get("result") is not None
                and t["result"].get("budget") == target
            ]
            filtered = trials_from_docs(docs, validate=False)
            return base_algo(new_ids, domain, filtered, seed, **kw)
        return base_algo(new_ids, domain, trials, seed, **kw)

    return algo


def compile_sha(
    train_fn,
    init_state,
    hyper_bounds,
    n_configs,
    eta=2,
    steps_per_rung=5,
    n_rungs=None,
    mesh=None,
    trial_axis="trial",
    replicas=1,
    shard_mode=None,
):
    """Successive halving over TRAINING, on-device.

    Rung r trains its (shrinking) population ``steps_per_rung * eta**r``
    steps under one jitted scan, then the top ``1/eta`` survivors'
    states AND hyperparameters are gathered on-device into the next
    rung's (statically smaller) program -- per-rung compute genuinely
    shrinks, and survivors continue from their trained state rather
    than restarting (learning-curve halving).  Hyperparameters sample
    log-uniformly from ``hyper_bounds`` at rung 0, as in
    :func:`hyperopt_tpu.pbt.compile_pbt` (same ``train_fn`` contract).

    ``replicas=K`` packs K INDEPENDENT brackets into every rung program
    (bracket-packing, VERDICT r3 weak #4): rung r trains all K brackets'
    populations stacked on the member axis (width ``K * P_r``), and
    promotion ranks WITHIN each bracket.  Late rungs -- where a lone
    bracket's population (P <= eta) underutilizes the chip -- run K
    members wide instead, so K bracket results cost roughly one
    bracket's wall-clock.  ``init_state`` leaves must then carry
    ``K * n_configs`` on the leading axis.  The dispatch chain is
    asynchronous: rung programs enqueue back-to-back and the host
    fetches bookkeeping ONCE at the end, so the tunnel round-trip is
    paid once per run, not per rung.

    ``n_configs`` must be a power of ``eta`` (every rung's population
    stays mesh-divisible); ``n_rungs`` defaults to halving down to one
    survivor per bracket.  ``shard_mode="shard_map"`` (graftmesh)
    shards every rung's member axis with ``shard_map`` over a per-rung
    sub-mesh of ``gcd(members, mesh size)`` devices: member blocks
    train collective-free and the only mesh-wide work is ONE loss
    all_gather per rung boundary (the replicated ranking) -- late tiny
    rungs shrink their sub-mesh instead of breaking divisibility, and
    the ladder is bitwise the unsharded one (same contract as
    :func:`hyperopt_tpu.pbt.compile_pbt`'s shard_map mode).  Returns ``runner(seed=0, checkpoint=None) ->
    {"best_loss", "best_hypers", "rungs": [{"n", "steps",
    "best_loss"}...], "state", "replica_bests"}`` (``best_*`` is the
    best across brackets; ``n`` counts ONE bracket's rung population).

    ``checkpoint=path`` makes the run DURABLE (VERDICT r4 weak #3): an
    atomic snapshot (state, hypers, per-rung bookkeeping, schedule
    guard) is written at every rung boundary, and a later
    ``runner(seed, checkpoint=path)`` against an existing file resumes
    from the last completed rung and bitwise-reproduces the
    uninterrupted result (a completed snapshot reassembles the result
    with no device work at all).  The cost of durability: the rung
    chain synchronizes per rung (one state fetch each) instead of
    dispatching asynchronously with a single end-of-run fetch, so use
    it where kills hurt -- the cold-compile regime -- and skip it for
    steady-state seed sweeps.  A snapshot from a different seed or
    ladder schedule is rejected, never silently resumed.  Durable mode
    is single-process: over a multi-process mesh the trial-sharded
    state is not host-addressable (fetch survivors with
    ``multihost_utils.process_allgather`` instead).
    """
    import jax
    import jax.numpy as jnp

    from .pbt import _hypers_dict, _log_bounds, _make_constrain

    P0 = int(n_configs)
    R = int(replicas)
    if R < 1:
        raise ValueError(f"replicas={R} must be >= 1")
    # the shared SHA geometry (also the compiled-ASHA device loop's):
    # validates the power-of-eta population and rung count in one place
    ladder = rung_schedule(P0, eta, n_rungs, steps_per_rung)
    n_rungs = len(ladder)
    def _validate_leading(state):
        leading = {x.shape[0] for x in jax.tree.leaves(state)}
        if leading != {R * P0}:
            raise ValueError(
                f"init_state leaves must have leading dim replicas * "
                f"n_configs = {R * P0}; got {sorted(leading)}"
            )
        return state

    # init_state may be a callable: materialized per run and released
    # after it, so schedulers holding MANY compile_sha programs
    # (compile_hyperband's brackets) don't pin every bracket's full
    # population in memory for the runner's lifetime.  A one-arg
    # callable receives the runner's seed, so seed sweeps can vary the
    # initial population too (advisor r4).
    init_takes_seed = False
    if callable(init_state):
        import inspect as _inspect

        # seed-taking ONLY on a required positional parameter: a
        # zero-required-arg callable (default-capture lambdas, **kwargs,
        # non-introspectable C callables) keeps the zero-arg contract --
        # passing the seed into a default-bound parameter would silently
        # override the captured value
        try:
            init_takes_seed = any(
                p.default is _inspect.Parameter.empty
                and p.kind in (
                    _inspect.Parameter.POSITIONAL_ONLY,
                    _inspect.Parameter.POSITIONAL_OR_KEYWORD,
                )
                for p in _inspect.signature(
                    init_state
                ).parameters.values()
            )
        except (TypeError, ValueError):
            init_takes_seed = False
    else:
        _validate_leading(init_state)
    names, log_lo, log_hi = _log_bounds(hyper_bounds)
    from .pbt import _resolve_shard_mode

    mode = _resolve_shard_mode(shard_mode, mesh)
    # shard_map lays the member axis out itself; GSPMD constraints
    # inside its per-shard bodies would be wrong
    constrain = _make_constrain(
        mesh if mode == "constraint" else None, trial_axis
    )

    @jax.jit
    def init_hypers(key):
        u = jax.random.uniform(key, (R * P0, len(names)),
                               dtype=jnp.float32)
        return log_lo + u * (log_hi - log_lo)

    # one jitted program per rung, built ONCE (the schedule is static);
    # rebuilding inside runner would re-jit every rung on every call.
    # p_live is static per rung, so the per-bracket ranking reshape is
    # shape-static too.
    def make_rung(n_steps, p_live):
        def rung(state, log_h, key):
            keys = jax.random.split(key, n_steps)

            def step(state, k):
                state, losses = train_fn(state, _hypers_dict(log_h, names), k)
                return constrain(state), losses

            state, losses_seq = jax.lax.scan(step, state, keys)
            losses = losses_seq[-1]  # [R * p_live]
            if mode == "constraint":
                # replicate the bookkeeping outputs: with the population
                # sharded over a multi-PROCESS mesh, trial-sharded
                # losses/order would not be host-addressable and the
                # runner's device_get would fail -- and every process
                # needs the full ranking to drive identical promotions
                from jax.sharding import NamedSharding, PartitionSpec

                losses = jax.lax.with_sharding_constraint(
                    losses, NamedSharding(mesh, PartitionSpec())
                )
            # rank WITHIN each bracket; emit global member indices
            order = rung_rank(losses, R, p_live)
            if mode == "constraint":
                from jax.sharding import NamedSharding, PartitionSpec

                order = jax.lax.with_sharding_constraint(
                    order, NamedSharding(mesh, PartitionSpec())
                )
            return state, losses, order

        return jax.jit(rung)

    def make_rung_sharded(n_steps, p_live):
        """The graftmesh rung (shard_map over a per-rung sub-mesh):
        each device trains its member block collective-free; the rung
        boundary pays ONE loss all_gather and the ranking runs
        replicated (bitwise :func:`make_rung`'s, per member).
        Returns ``(jitted_fn, member_sharding)`` -- the runner places
        rung inputs with the sharding before each call, since sub-mesh
        device sets shrink with the rung population."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as Pspec

        from .parallel.mesh import rung_submesh
        from .parallel.sharded import _shard_map

        m = R * p_live
        sub, k = rung_submesh(mesh, trial_axis, m)
        p_loc = m // k

        def body(state, log_h, key):
            lo = jax.lax.axis_index(trial_axis) * p_loc
            # exp over the FULL replicated table, block sliced after
            # (CPU libm vectorizes transcendentals differently at
            # narrow widths -- exp-then-slice keeps hypers bitwise)
            hyp = {
                n: jax.lax.dynamic_slice_in_dim(v, lo, p_loc)
                for n, v in _hypers_dict(log_h, names).items()
            }
            keys = jax.random.split(key, n_steps)

            def step(state, kk):
                state, losses = train_fn(state, hyp, kk)
                return state, losses

            state, losses_seq = jax.lax.scan(step, state, keys)
            losses = jax.lax.all_gather(
                losses_seq[-1], trial_axis, tiled=True
            )
            order = rung_rank(losses, R, p_live)
            return state, losses, order

        fn = jax.jit(_shard_map()(
            body, mesh=sub,
            in_specs=(Pspec(trial_axis), Pspec(), Pspec()),
            out_specs=(Pspec(trial_axis), Pspec(), Pspec()),
            check_vma=False,
        ))
        return fn, NamedSharding(sub, Pspec(trial_axis))

    rung_fns = []
    rung_shardings = []  # shard_map mode: per-rung member placement
    for p, n_steps_r, _ in ladder:
        if mode == "shard_map":
            fn, sharding = make_rung_sharded(n_steps_r, p)
            rung_fns.append(fn)
            rung_shardings.append(sharding)
        else:
            rung_fns.append(make_rung(n_steps_r, p))
            rung_shardings.append(None)

    # -- durable-mode snapshot machinery (rung-boundary checkpoints) ------
    sched_guard = (P0, R, int(eta), int(n_rungs), int(steps_per_rung))
    _template_cache = []

    def _state_template():
        """Abstract rung-0 state pytree for checkpoint reconstruction
        (``jax.eval_shape`` keeps a callable ``init_state`` cheap)."""
        if not _template_cache:
            if callable(init_state):
                fn0 = (
                    (lambda: init_state(0)) if init_takes_seed
                    else init_state
                )
                _template_cache.append(jax.eval_shape(fn0))
            else:
                _template_cache.append(jax.eval_shape(lambda: init_state))
        return _template_cache[0]

    def _pop_after(rung):
        """Members on the leading axis after ``rung`` completed rungs
        (the final rung has no promotion)."""
        return R * (P0 // eta ** min(rung, n_rungs - 1))

    def _snapshot_target(rung):
        """Zero pytree matching a snapshot with ``rung`` completed rungs
        (``load_pytree`` validates leaf shapes/dtypes against it)."""
        m = _pop_after(rung)
        state_t = jax.tree.map(
            lambda l: np.zeros(
                (m,) + tuple(l.shape[1:]), np.dtype(l.dtype)
            ),
            _state_template(),
        )
        return {
            "meta": np.zeros(2 + len(sched_guard), np.int64),
            "log_h": np.zeros((m, len(names)), np.float32),
            "state": state_t,
            "rungs": {
                "losses": [
                    np.zeros((R * (P0 // eta**i),), np.float32)
                    for i in range(rung)
                ],
                "order": [
                    np.zeros((R, P0 // eta**i), np.int32)
                    for i in range(rung)
                ],
            },
        }

    def _write_snapshot(path, rung, seed, log_h_np, state_np, per_rung):
        from .utils.checkpoint import save_pytree

        save_pytree({
            "meta": np.asarray(
                [int(rung), int(seed), *sched_guard], np.int64
            ),
            "log_h": log_h_np,
            "state": state_np,
            "rungs": {
                "losses": [l for l, _ in per_rung],
                "order": [o for _, o in per_rung],
            },
        }, path)

    def _read_snapshot(path, seed):
        from .utils.checkpoint import load_pytree

        with np.load(path) as d:
            meta = np.asarray(d["['meta']"])
        rung = int(meta[0])
        if int(meta[1]) != int(seed) or (
            tuple(int(x) for x in meta[2:]) != sched_guard
        ):
            raise ValueError(
                f"checkpoint {path!r} was written by seed={int(meta[1])}, "
                f"schedule={tuple(int(x) for x in meta[2:])}; refusing to "
                f"resume seed={int(seed)}, schedule={sched_guard}"
            )
        snap = load_pytree(_snapshot_target(rung), path)
        return rung, snap["log_h"], snap["state"], list(
            zip(snap["rungs"]["losses"], snap["rungs"]["order"])
        )

    def runner(seed=0, checkpoint=None):
        base = jax.random.key(int(seed) % 2**32)
        k_init, *rung_keys = jax.random.split(base, n_rungs + 1)
        start = 0
        per_rung_host = []  # numpy bookkeeping (durable mode / resume)
        if checkpoint is not None and os.path.exists(checkpoint):
            start, log_h, state, per_rung_host = _read_snapshot(
                checkpoint, seed
            )
            state = constrain(state)
        else:
            log_h = init_hypers(k_init)
            if callable(init_state):
                raw = (
                    init_state(int(seed)) if init_takes_seed
                    else init_state()
                )
                state = constrain(_validate_leading(raw))
            else:
                state = constrain(init_state)
        n_live = P0 // eta ** min(start, n_rungs - 1)
        per_rung_dev = []  # device arrays; fetched ONCE after the last rung
        for r in range(start, n_rungs):
            key_r = rung_keys[r]
            if rung_shardings[r] is not None:
                # graftmesh: sub-mesh device sets shrink with the rung
                # population, so each rung's inputs are explicitly
                # placed (members sharded, bookkeeping replicated) --
                # device-to-device moves, no host round-trip
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as Pspec

                repl = NamedSharding(rung_shardings[r].mesh, Pspec())
                state = jax.device_put(state, rung_shardings[r])
                log_h = jax.device_put(log_h, repl)
                key_r = jax.device_put(key_r, repl)
            state, losses, order = rung_fns[r](state, log_h, key_r)
            if r < n_rungs - 1:
                keep = order[:, : n_live // eta].reshape(-1)
                state = jax.tree.map(lambda x: x[keep], state)
                log_h = log_h[keep]
                n_live //= eta
            if checkpoint is not None:
                # durable mode: synchronize + persist at the boundary.
                # The fetched arrays feed the next rung unchanged
                # (device->host->device is bitwise exact), so a resumed
                # run reproduces the uninterrupted one exactly.
                losses_np, order_np, state, log_h = jax.device_get(
                    (losses, order, state, log_h)
                )
                per_rung_host.append((losses_np, order_np))
                _write_snapshot(
                    checkpoint, r + 1, seed, log_h, state, per_rung_host
                )
            else:
                per_rung_dev.append((losses, order))
        # ONE host synchronization for the whole ladder in the default
        # (non-durable) mode: the rung chain is dispatched asynchronously
        # (device-side gathers), so the tunnel round-trip is paid once
        fetched = per_rung_host + (
            jax.device_get(per_rung_dev) if per_rung_dev else []
        )
        sched = [
            {"n": P0 // eta**r, "steps": int(steps_per_rung) * eta**r}
            for r in range(n_rungs)
        ]
        log_h_np = np.asarray(log_h)

        def rung_best(losses_np, order_np):
            # best across brackets at this rung (non-finite excluded)
            cand = losses_np[order_np[:, 0]]
            return float(np.min(np.where(np.isfinite(cand), cand, np.inf)))

        rungs = [
            {**s, "best_loss": rung_best(losses_np, order_np)}
            for s, (losses_np, order_np) in zip(sched, fetched)
        ]
        last_losses, last_order = fetched[-1]
        rep_best_idx = last_order[:, 0]  # [R] global member indices
        rep_bests = last_losses[rep_best_idx]
        r_win = int(np.argmin(
            np.where(np.isfinite(rep_bests), rep_bests, np.inf)
        ))
        best_i = int(rep_best_idx[r_win])
        return {
            "best_loss": float(last_losses[best_i]),
            "best_hypers": {
                n: float(np.exp(log_h_np[best_i, i]))
                for i, n in enumerate(names)
            },
            "rungs": rungs,
            "state": state,
            "best_index": best_i,
            "replica_bests": [float(b) for b in rep_bests],
        }

    # the graftir seam: per-rung jitted programs + their placements
    runner._rung_fns = rung_fns
    runner._rung_shardings = rung_shardings
    runner._shard_mode = mode
    return runner


def compile_hyperband(
    train_fn,
    init_state_fn,
    hyper_bounds,
    s_max,
    eta=2,
    steps_per_rung=5,
    replicas=1,
    mesh=None,
    trial_axis="trial",
):
    """Full Hyperband over TRAINING, on-device: every bracket from the
    most exploratory (``eta**s_max`` configs at the smallest rung-0
    budget) to a single full-budget one, as chained ``compile_sha``
    ladders.

    The one-survivor bracket variant: bracket ``s`` runs ``eta**s``
    configurations through ``s + 1`` rungs with per-rung base budget
    ``steps_per_rung * eta**(s_max - s)`` training steps, so every
    bracket's survivor retires at the same maximum budget while the
    brackets trade configurations against rung-0 depth -- the Hyperband
    exploration/exploitation spread (Li et al., 2018) with populations
    kept ``eta``-powers for the fused ladders.  ``replicas=K``
    bracket-packs every ladder (K independent instances of EACH
    bracket).

    Each bracket's rung chain dispatches asynchronously with one host
    fetch at its end, so the device runs bracket-to-bracket back to
    back; total wall-clock is the sum of bracket compute plus one
    round-trip per bracket (vs the host driver
    :func:`hyperband`, which must synchronize every evaluation of an
    arbitrary Python objective).

    Args:
      train_fn: the :func:`compile_sha` / :func:`hyperopt_tpu.pbt`
        population train-fn contract.
      init_state_fn: ``(key, n) -> state pytree`` with leading dim
        ``n`` on every leaf (e.g. ``transformer.init_population``
        wrapped).  Deliberately LAZY: invoked once per bracket on every
        ``runner()`` call (not at build time), so peak memory is one
        bracket's population, released after its ladder runs.  The key
        folds the bracket id with the runner seed, so ``runner(seed=0)``
        and ``runner(seed=1)`` start every bracket from DIFFERENT
        initial populations (advisor r4).
      s_max: bracket count - 1; the widest bracket has ``eta**s_max``
        configs per replica.

    Returns ``runner(seed=0, checkpoint=None) -> {"best_loss",
    "best_hypers", "brackets": [{"s", "n_configs", "rungs", "best_loss",
    "replica_bests"}...], "best_bracket"}``.

    ``checkpoint=directory`` makes the whole spread durable: each
    bracket's ladder writes rung-boundary snapshots to
    ``<directory>/bracket_<s>.npz`` (see :func:`compile_sha`), so a
    kill anywhere in the spread loses at most the current rung --
    completed brackets replay from their snapshots with NO device work
    and the interrupted one resumes mid-ladder, bitwise-reproducing the
    uninterrupted result.  This is the answer to the cold-compile
    regime (BASELINE.md: ~400 s cold for the 5-bracket spread), where a
    kill used to lose every bracket.
    """
    import jax

    if s_max < 0:
        raise ValueError(f"s_max={s_max} must be >= 0")
    bracket_runners = []
    for s in range(int(s_max), -1, -1):
        n_s = eta**s
        bracket_runners.append((s, compile_sha(
            train_fn,
            # lazy: each bracket's population materializes when ITS
            # ladder runs and is released after, so peak memory is one
            # bracket, not the sum of all of them.  The one-arg form
            # receives the ladder's seed: folding it into the bracket
            # key makes seed sweeps vary initial populations too.
            (lambda seed_, s_=s, n_=n_s: init_state_fn(
                jax.random.fold_in(jax.random.key(s_), seed_ % 2**31),
                int(replicas) * n_,
            )),
            hyper_bounds,
            n_configs=n_s,
            eta=eta,
            steps_per_rung=int(steps_per_rung) * eta ** (int(s_max) - s),
            replicas=replicas,
            mesh=mesh,
            trial_axis=trial_axis,
        )))

    def runner(seed=0, checkpoint=None):
        if checkpoint is not None:
            os.makedirs(checkpoint, exist_ok=True)
        brackets = []
        outs = []
        for s, run_s in bracket_runners:
            # distinct per-bracket seeds: fold the bracket id
            out = run_s(
                seed=(int(seed) * 1_000_003 + s) % 2**31,
                checkpoint=(
                    None if checkpoint is None
                    else os.path.join(checkpoint, f"bracket_{s}.npz")
                ),
            )
            outs.append(out)
            brackets.append({
                "s": s,
                "n_configs": eta**s,
                "rungs": out["rungs"],
                "best_loss": out["best_loss"],
                "replica_bests": out["replica_bests"],
            })
        # NaN-safe winner: a diverged bracket (non-finite best) must
        # never poison the result; all-diverged keeps bracket 0's NaN
        keyed = [
            b["best_loss"] if np.isfinite(b["best_loss"]) else np.inf
            for b in brackets
        ]
        win = int(np.argmin(keyed))
        return {
            "best_loss": outs[win]["best_loss"],
            "best_hypers": outs[win]["best_hypers"],
            "brackets": brackets,
            "best_bracket": win,
        }

    return runner


def _space_fingerprint(expr):
    """Stable structural hash of a pyll space graph, for checkpoint
    guards: distributions, bounds, labels, and choice-option ORDER all
    change it; process identity does not.  ``str(expr)`` is NOT usable
    here -- it embeds ``repr()`` of literal objects, and a space with
    callables/objects as choice options (a standard pattern) would
    print per-process memory addresses, refusing every real
    cross-process resume.  Non-primitive literal values are therefore
    normalized to their type name (their index in the graph still
    participates, so reordering options changes the hash)."""
    import hashlib

    from .pyll.base import Literal, dfs

    def norm(v):
        if isinstance(v, (str, int, float, bool, type(None))):
            return repr(v)
        if isinstance(v, np.generic):  # numpy scalars: not python
            # int/float instances, but value+dtype reprs are stable
            return f"np.{type(v).__name__}({v!r})"
        if isinstance(v, np.ndarray):
            if v.dtype == object:
                return f"nd.object{norm(v.tolist())}"
            return (
                f"nd({v.dtype},{v.shape},"
                f"{hashlib.sha256(np.ascontiguousarray(v).tobytes()).hexdigest()})"
            )
        if isinstance(v, (list, tuple)):
            return f"{type(v).__name__}({','.join(norm(x) for x in v)})"
        if isinstance(v, dict):
            items = ",".join(
                f"{norm(k)}:{norm(v[k])}" for k in sorted(v, key=repr)
            )
            return f"dict({items})"
        return f"<{type(v).__module__}.{type(v).__qualname__}>"

    parts = []
    for node in dfs(expr):
        if isinstance(node, Literal):
            parts.append(f"L:{norm(node.obj)}")
        else:
            kw = ",".join(sorted(k for k, _ in node.named_args))
            parts.append(f"A:{node.name}/{len(node.pos_args)}/{kw}")
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


def asha(
    fn,
    space,
    max_budget,
    eta=3,
    min_budget=1,
    max_jobs=81,
    workers=4,
    algo=None,
    trials=None,
    rstate=None,
    checkpoint=None,
    checkpoint_every=1,
    evaluator=None,
):
    """Asynchronous successive halving (ASHA, Li et al., 2020).

    The synchronous :func:`successive_halving` waits for a whole rung
    before promoting, so stragglers idle every worker; ASHA promotes a
    configuration the moment it is in the top ``1/eta`` of COMPLETED
    results at its rung, and otherwise starts a fresh rung-0
    configuration -- workers never wait.  This is the scheduler shape
    that fits this framework's asynchronous execution backends (the
    filequeue/Mongo worker model): here it runs on an in-process thread
    pool with the scheduler state under one lock, the same concurrency
    discipline as ``distributed.threads.ThreadTrials``.

    Args:
      fn: ``fn(config, budget) -> loss`` (or dict with ``"loss"``);
        called concurrently from ``workers`` threads -- it must be
        thread-safe (pure functions and most surrogates are).
      max_budget / min_budget / eta: the rung ladder, as in
        :func:`successive_halving` (ints kept integral the same way).
      max_jobs: total evaluations across all rungs (the stop rule).
      workers: concurrent evaluator threads.
      algo: suggest fn for rung-0 configurations (default random); asked
        one configuration at a time, under the scheduler lock.
      trials: optional ``Trials``; every evaluation is recorded with
        ``result["budget"]`` (same contract as the sync drivers, so
        ``budget_aware`` model fitting composes).
      checkpoint: optional path for durable kill/resume, completing the
        resume family (``device_loop``/``pbt``/``compile_sha``/
        ``compile_hyperband`` all have one).  The scheduler state is a
        host-object graph -- per-rung sorted results, the config table,
        the generator state, the trials store -- so the snapshot is an
        atomic-rename pickle (the ``save_trials`` mechanism), written
        under the scheduler lock every ``checkpoint_every`` recorded
        evaluations.  If ``checkpoint`` exists, it is resumed: the
        restored trials/rstate REPLACE the ``trials=``/``rstate=``
        arguments (the snapshot is the source of truth of the
        interrupted run), in-flight evaluations at kill time are
        re-run -- a rung-0 suggestion re-runs its exact suggested
        config (the snapshot carries it), a promotion becomes eligible
        again -- and the run continues to ``max_jobs`` total recorded
        evaluations.  With ``workers=1`` the resumed run
        reproduces the uninterrupted one bitwise (the snapshot's
        generator state predates the in-flight job's suggestion, so the
        re-suggestion replays it); with ``workers>1`` completion order
        is scheduling-dependent either way, so resume preserves the
        invariants, not the stream.  The file is kept on success.
      checkpoint_every: snapshot cadence in recorded evaluations
        (default 1: every record; raise it if pickling a large trials
        store every record measures as the bottleneck).
      evaluator: optional transport seam, ``evaluator(vals, cfg,
        budget) -> loss`` where ``vals`` is the INDEX-form config dict
        (the encoding trial docs carry) and ``cfg`` its decoded form --
        lets the scheduler dispatch evaluations somewhere other than
        this process while the worker threads become in-flight-job
        slots.  The decode happens OUTSIDE the failure-tolerant region
        for every path, so a deterministic space bug surfaces at the
        first job instead of burning ``max_jobs`` failed trials.
        :func:`hyperopt_tpu.distributed.asha_filequeue` /
        ``asha_mongo`` / ``asha_spark`` use it to farm evaluations to
        worker processes / Spark tasks.  Default: evaluate
        ``fn(cfg, budget)`` inline.

    Returns ``{"best": config, "best_loss", "rungs": [{"budget", "n"}],
    "trials"}`` where ``best`` is the best completed evaluation at the
    HIGHEST budget reached (ASHA's answer is its deepest survivor).
    """
    import bisect
    import threading
    from concurrent.futures import ThreadPoolExecutor

    from .base import Domain, Trials
    from . import rand as rand_mod
    from .fmin import space_eval

    if rstate is None:
        rstate = np.random.default_rng()
    if algo is None:
        algo = rand_mod.suggest
    if trials is None:
        trials = Trials()
    n_rungs = _int_log(max_budget / min_budget, eta) + 1
    integral = _budgets_integral(max_budget, min_budget)
    if evaluator is not None:
        _check_evaluator_arity(evaluator)

    def rung_budget(r):
        return _rung_budget(min_budget, eta, r, integral)

    domain = Domain(fn, space, pass_expr_memo_ctrl=False)
    lock = threading.Lock()
    # rung r -> SORTED list of (loss, config_key) (bisect.insort in
    # _record), so the scheduler's promotable-set scan needs no per-call
    # sort under the lock every worker contends on
    done = [[] for _ in range(n_rungs)]
    promoted = [set() for _ in range(n_rungs)]
    configs = {}  # config_key -> config dict (index-form vals)
    pending = {}  # config_key -> suggested doc, completed at its rung-0 record
    started = 0
    recorded = 0  # completed _record calls (incl. failed evals): the
    # durable progress measure -- ``started`` counts assignments, which
    # include in-flight work a kill would lose
    # promoted[] marks claims at ASSIGNMENT time (so two workers cannot
    # promote the same key); attempted[] marks them at RECORD time.  The
    # snapshot persists attempted, not promoted: a claim whose
    # evaluation died in flight must be re-runnable after resume, while
    # a recorded attempt (even a failed one) must not repeat -- exactly
    # the uninterrupted run's behavior
    attempted = [set() for _ in range(n_rungs)]
    # ladder + budget + space identity; a snapshot from a different
    # schedule (or a different space: index-form vals would be silently
    # decoded against the wrong labels/options/ranges) must be refused.
    # Guard built only when checkpointing (it is the only consumer)
    if checkpoint is not None:
        if int(checkpoint_every) < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got {checkpoint_every!r}"
            )
        # algo AND objective identity ride the guard: resuming a
        # TPE-driven run with the defaulted (random) algo, or an asha
        # snapshot with an EDITED objective, would silently change the
        # experiment -- the latter mixing the old objective's recorded
        # losses with new evaluations of the new one (ADVICE r5; sha/
        # hyperband already fingerprint fn).  No rstate fingerprint here
        # (unlike sha/hyperband): asha RESTORES the generator state from
        # the snapshot, so resuming under any entry rstate is sound
        ckpt_guard = (
            "asha", n_rungs, float(max_budget), float(min_budget),
            float(eta), int(max_jobs),
            type(rstate.bit_generator).__name__,
            _algo_identity(algo),
            _algo_identity(fn),
            _space_fingerprint(domain.expr),
        )
    requeue = []  # restored in-flight rung-0 keys, re-assigned first

    def _write_ckpt():
        """Snapshot the full scheduler state (the ``save_trials``
        atomic-rename pickle); called under the lock (every mutated
        structure is lock-guarded).  ``pending`` rides along so a
        rung-0 suggestion whose evaluation was in flight at kill time
        is re-run on resume, not dropped -- its doc and the tid the
        store already allocated for it are pickled together, keeping
        the tid sequence contiguous."""
        from .utils.checkpoint import save_trials

        save_trials({
            "guard": ckpt_guard,
            "configs": configs,
            "done": done,
            "attempted": [sorted(s) for s in attempted],
            "pending": pending,
            "recorded": recorded,
            "rstate": rstate.bit_generator.state,
            "trials": trials,
        }, checkpoint)

    if checkpoint is not None and os.path.exists(checkpoint):
        from .utils.checkpoint import load_guarded

        snap = load_guarded(checkpoint, ckpt_guard)
        configs = snap["configs"]
        done = snap["done"]
        # attempted (record-time marks), not assignment-time claims: a
        # promotion whose evaluation died in flight must re-run
        promoted = [set(s) for s in snap["attempted"]]
        attempted = [set(s) for s in snap["attempted"]]
        pending = snap["pending"]
        requeue = sorted(pending)
        recorded = snap["recorded"]
        started = recorded  # in-flight-at-kill assignments are re-run
        # fresh generator of the guarded type -- restoring must not
        # clobber the caller's rstate object as a side effect
        rstate = np.random.Generator(type(rstate.bit_generator)())
        rstate.bit_generator.state = snap["rstate"]
        trials = snap["trials"]

    def _suggest_one():
        """One new rung-0 configuration through the algo seam.  The
        suggested doc itself is kept (``pending``) and completed by the
        rung-0 ``_record``, reusing its tid -- allocating a second tid
        for the stored doc would leave the suggestion's tid orphaned and
        the store's tid sequence non-contiguous (advisor r4)."""
        seed = int(rstate.integers(0, 2**31 - 1))
        (tid,) = trials.new_trial_ids(1)
        (doc,) = algo([tid], domain, trials, seed)
        return doc

    def _next_job():
        """Scheduler core, called under the lock: the highest-rung
        eligible promotion, else a fresh rung-0 config."""
        nonlocal started
        if started >= max_jobs:
            return None
        if requeue:  # restored in-flight suggestions resume first
            key = requeue.pop(0)
            started += 1
            return key, 0
        for r in range(n_rungs - 2, -1, -1):
            n_promotable = len(done[r]) // eta
            for loss, key in done[r][:n_promotable]:
                if key not in promoted[r]:
                    promoted[r].add(key)
                    started += 1
                    return key, r + 1
        key = len(configs)
        doc = _suggest_one()
        configs[key] = _vals_of(doc)
        pending[key] = doc
        started += 1
        return key, 0

    def _record(key, r, loss):
        nonlocal recorded
        from .base import JOB_STATE_DONE

        b = rung_budget(r)
        result = {
            "status": "ok",
            "loss": float(loss) if np.isfinite(loss) else None,
            "budget": b,
        }
        if result["loss"] is None:
            result["status"] = "fail"
        doc = pending.pop(key, None)
        if doc is not None:
            # rung 0 completes the SUGGESTED doc itself (tid reuse)
            doc["result"] = result
        else:
            # promotions append a NEW trial per (config, budget):
            # lower-rung results stay as learning-curve history
            (tid,) = trials.new_trial_ids(1)
            misc = {
                "tid": tid,
                "cmd": ("domain_attachment", "FMinIter_Domain"),
                "workdir": None,
                "idxs": {k: [tid] for k in configs[key]},
                "vals": {k: [v] for k, v in configs[key].items()},
            }
            (doc,) = trials.new_trial_docs([tid], [None], [result], [misc])
        doc["state"] = JOB_STATE_DONE
        trials.insert_trial_docs([doc])
        # refresh under the lock so a model-based rung-0 algo (tpe_jax,
        # budget_aware) sees every completed evaluation, not an empty
        # stale view -- trials.trials reads the refresh-synced list
        trials.refresh()
        if np.isfinite(loss):
            bisect.insort(done[r], (float(loss), key))
        if r > 0:
            attempted[r - 1].add(key)
        recorded += 1
        if checkpoint is not None and recorded % int(checkpoint_every) == 0:
            _write_ckpt()

    def worker():
        while True:
            with lock:
                job = _next_job()
            if job is None:
                return
            key, r = job
            # decode OUTSIDE the try, for BOTH paths: a space_eval
            # failure is a deterministic framework/space bug that must
            # surface immediately, not burn max_jobs NaN trials
            cfg = space_eval(space, configs[key])
            try:
                if evaluator is not None:
                    loss = evaluator(
                        dict(configs[key]), cfg, rung_budget(r)
                    )
                else:
                    loss = fn(cfg, rung_budget(r))
                if isinstance(loss, dict):
                    loss = loss["loss"]
                loss = float(loss)
            except Exception:
                logger.exception("asha evaluation failed")
                loss = float("nan")
            with lock:
                _record(key, r, loss)

    with ThreadPoolExecutor(max_workers=int(workers)) as pool:
        futures = [pool.submit(worker) for _ in range(int(workers))]
        for f in futures:
            f.result()  # surface worker crashes
    trials.refresh()
    if checkpoint is not None:
        with lock:
            _write_ckpt()  # final state, whatever the cadence left off

    populated = [r for r in range(n_rungs) if done[r]]
    if not populated:
        from .exceptions import AllTrialsFailed

        raise AllTrialsFailed(
            f"every asha evaluation failed ({max_jobs} jobs, all "
            "non-finite or raising); the recorded trials are in the "
            "trials= store if one was passed"
        )
    deepest = populated[-1]
    best_loss, best_key = done[deepest][0]  # sorted: first is best
    return {
        "best": space_eval(space, configs[best_key]),
        "best_loss": best_loss,
        "rungs": [
            {"budget": rung_budget(r), "n": len(done[r])}
            for r in range(n_rungs)
        ],
        "trials": trials,
    }


# ---------------------------------------------------------------------------
# graftir registration (hyperopt-tpu-lint --ir)
# ---------------------------------------------------------------------------

from .ops.compile import ProgramCapture, register_program  # noqa: E402


@register_program(
    "hyperband.sha_rung_mesh",
    families=("hyperopt_tpu.hyperband:compile_sha",),
)
def _registry_sha_rung_mesh(p):
    """The graftmesh device-ASHA rung: member blocks training
    collective-free under shard_map with ONE loss all_gather at the
    rung boundary, traced over the forced 4-virtual-CPU-device trial
    mesh (rung 0 of an 8-config ladder; later rungs shrink their
    sub-mesh but share the body's family)."""
    import jax
    import jax.numpy as jnp

    from .parallel.mesh import TRIAL_AXIS, registry_cpu_mesh

    mesh = registry_cpu_mesh(axis=TRIAL_AXIS)
    n_cfg = 8

    def train_fn(state, hypers, key):
        theta = state["theta"] - hypers["lr"] * 2.0 * (
            state["theta"] - 0.7
        )
        return {"theta": theta}, (theta - 0.7) ** 2

    runner = compile_sha(
        train_fn, {"theta": jnp.zeros((n_cfg,), jnp.float32)},
        {"lr": (1e-3, 1.0)}, n_configs=n_cfg, eta=2, steps_per_rung=2,
        mesh=mesh, trial_axis=TRIAL_AXIS, shard_mode="shard_map",
    )
    from jax.sharding import NamedSharding, PartitionSpec as Pspec

    sharding = runner._rung_shardings[0]
    repl = NamedSharding(sharding.mesh, Pspec())
    key_aval = jax.eval_shape(lambda: jax.random.key(0))
    return ProgramCapture(
        fn=runner._rung_fns[0],
        args=(
            {"theta": jax.ShapeDtypeStruct(
                (n_cfg,), jnp.float32, sharding=sharding
            )},
            jax.ShapeDtypeStruct((n_cfg, 1), jnp.float32, sharding=repl),
            jax.ShapeDtypeStruct(
                key_aval.shape, key_aval.dtype, sharding=repl
            ),
        ),
    )
