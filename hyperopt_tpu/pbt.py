"""Population-Based Training, fused on-device.

PBT (Jaderberg et al., 2017) tunes hyperparameters *during* training:
a population of P members trains in parallel; every ``exploit_every``
steps the bottom quantile copies the parameters of a top-quantile member
(exploit) and perturbs its hyperparameters (explore).  The reference
cannot express this at all (its trials are independent black-box
evaluations); here the whole schedule -- P models training, periodic
rank/copy/perturb -- compiles to ONE XLA program over the population
``vmap``, with the population axis optionally sharded over a mesh
(the same GSPMD shape as :mod:`hyperopt_tpu.models.resnet` /
``models.transformer`` population training).

Contract: the user supplies a *vmapped* population train function
``train_fn(state, hypers, key) -> (state, losses[P])`` (one gradient
step for every member; ``state`` is any pytree with leading population
axis P on every leaf; ``hypers`` a dict of ``[P]`` arrays) plus per-
hyperparameter log-space bounds.  :func:`compile_pbt` returns a runner
executing ``n_rounds x exploit_every`` total steps.

    from hyperopt_tpu.pbt import compile_pbt

    runner = compile_pbt(train_fn, init_state, {"lr": (1e-4, 1.0)},
                         pop_size=8, exploit_every=5, n_rounds=20)
    out = runner(seed=0)
    out["best_loss"], out["hypers"], out["loss_history"]  # [rounds, P]
"""

from __future__ import annotations

import numpy as np

__all__ = ["compile_pbt"]


def _log_bounds(hyper_bounds):
    """Validate ``{name: (low, high)}`` and return (names, log_lo, log_hi)
    as device arrays -- shared by every population-scheduler module
    (:mod:`hyperopt_tpu.pbt`, :mod:`hyperopt_tpu.hyperband`)."""
    import jax.numpy as jnp

    names = sorted(hyper_bounds)
    lo = np.array([float(hyper_bounds[n][0]) for n in names])
    hi = np.array([float(hyper_bounds[n][1]) for n in names])
    if not (lo > 0).all() or not (hi > lo).all():
        raise ValueError("hyper_bounds must satisfy 0 < low < high")
    return (
        names,
        jnp.asarray(np.log(lo), jnp.float32),
        jnp.asarray(np.log(hi), jnp.float32),
    )


def _hypers_dict(log_h, names):
    import jax.numpy as jnp

    return {n: jnp.exp(log_h[:, i]) for i, n in enumerate(names)}


def _make_constrain(mesh, trial_axis):
    """Population-axis sharding constraint (identity without a mesh)."""
    import jax

    if mesh is None:
        return lambda state: state
    from jax.sharding import NamedSharding, PartitionSpec as Pspec

    sharding = NamedSharding(mesh, Pspec(trial_axis))

    def constrain(state):
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, sharding), state
        )

    return constrain


def _resolve_shard_mode(shard_mode, mesh):
    """The population-sharding regime: ``None`` keeps the historical
    default (GSPMD sharding constraints when a mesh is given),
    ``"shard_map"`` selects the graftmesh collective-explicit path --
    per-shard member blocks train with ZERO collectives and the only
    mesh-wide communication is the loss/state all_gather at exploit
    (or rung) boundaries."""
    if shard_mode is None:
        return "constraint" if mesh is not None else None
    mode = str(shard_mode)
    if mode not in ("constraint", "shard_map"):
        raise ValueError(
            f"shard_mode={shard_mode!r}; expected 'constraint' or "
            "'shard_map'"
        )
    if mesh is None:
        raise ValueError(f"shard_mode={mode!r} requires mesh=")
    return mode


def _check_divisible(pop, mesh, trial_axis, what):
    n_dev = int(mesh.shape[trial_axis])
    if pop % n_dev:
        raise ValueError(
            f"{what}={pop} must divide by the {trial_axis!r} mesh axis "
            f"size {n_dev} for shard_map population sharding"
        )
    return n_dev


def _place_population(state, mesh, trial_axis):
    """DCN-aware population placement for the shard_map path.

    Single-process: commit the leaves sharded over the trial axis so
    the jitted schedule never reshards them.  Multi-process (a
    ``jax.distributed`` mesh spanning hosts): a host-committed array
    cannot feed a global-mesh computation, so leaves pass through as
    host arrays and jit itself places them over the global mesh --
    the :func:`hyperopt_tpu.parallel.sharded._history_inputs`
    placement contract, population-shaped."""
    import jax

    if jax.process_count() > 1:
        import numpy as np_

        return jax.tree.map(np_.asarray, state)
    from jax.sharding import NamedSharding, PartitionSpec as Pspec

    sharding = NamedSharding(mesh, Pspec(trial_axis))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), state)


def compile_pbt(
    train_fn,
    init_state,
    hyper_bounds,
    pop_size,
    exploit_every=5,
    n_rounds=20,
    exploit_quantile=0.25,
    perturb_factors=(0.8, 1.25),
    mesh=None,
    trial_axis="trial",
    shard_mode=None,
):
    """Compile a PBT schedule into one reusable device program.

    Args:
      train_fn: ``(state, hypers, key) -> (state, losses[P])`` -- one
        vmapped training step for the whole population.  ``losses`` is
        the ranking signal (lower is better).
      init_state: population state pytree (leading axis P on every leaf).
      hyper_bounds: ``{name: (low, high)}`` -- positive bounds; hypers
        live and perturb in log space (the PBT-natural scale for
        lr/wd-like knobs) and are sampled log-uniformly at start.
      pop_size: P.
      exploit_every: training steps between exploit/explore events.
      n_rounds: number of exploit/explore events; total steps =
        ``n_rounds * exploit_every``.
      exploit_quantile: fraction of the population replaced each event
        (bottom q copies params from the top q).
      perturb_factors: multiplicative explore range (log-uniform within).
      mesh / trial_axis: optional population sharding, as in
        :func:`hyperopt_tpu.device_loop.compile_fmin`.
      shard_mode: ``"constraint"`` (the default with a mesh: GSPMD
        sharding constraints) or ``"shard_map"`` (graftmesh): the
        population splits into per-device member blocks that train
        with ZERO collectives -- the only mesh-wide communication is
        ONE loss all_gather plus ONE member-state all_gather per
        exploit boundary, so populations of thousands scale with chip
        count.  Requires ``pop_size`` divisible by the mesh size; the
        schedule is bitwise the unsharded one for any ``train_fn``
        whose per-member math does not depend on its position in the
        batch (the vmapped-contract norm).  ``train_fn`` receives its
        shard's member block (``P / n_devices`` leading axis).

    Returns ``runner(seed=0, init=None) -> dict`` with ``best_loss``,
    ``best_hypers`` ({name: float} of the best final member),
    ``hypers`` ({name: [P]} final), ``loss_history`` [n_rounds, P]
    (each round's last-step losses), and ``state`` (final population
    pytree, device arrays).  ``runner(init=prev_out)`` RESUMES a
    previous result's population (state + hypers) for another
    ``n_rounds`` -- checkpoint/resume for the PBT path; persist/restore
    the dict's ``state``/``hypers`` across processes with
    ``utils.checkpoint.save_pytree``/``load_pytree``.
    """
    import jax
    import jax.numpy as jnp

    P = int(pop_size)
    names, log_lo, log_hi = _log_bounds(hyper_bounds)
    n_replace = max(1, int(round(P * float(exploit_quantile))))
    if 2 * n_replace > P:
        raise ValueError(
            f"exploit_quantile={exploit_quantile} replaces {n_replace} of "
            f"{P} members; top and bottom quantiles must not overlap"
        )
    log_pf = (float(np.log(perturb_factors[0])),
              float(np.log(perturb_factors[1])))
    mode = _resolve_shard_mode(shard_mode, mesh)
    if mode == "shard_map":
        n_dev = _check_divisible(P, mesh, trial_axis, "pop_size")
        p_local = P // n_dev
    # the shard_map path lays the population out itself; GSPMD
    # constraints inside its per-shard body would be wrong
    constrain = _make_constrain(
        mesh if mode == "constraint" else None, trial_axis
    )

    def hypers_dict(log_h):
        return _hypers_dict(log_h, names)

    def train_rounds(carry, key):
        """exploit_every train steps, then one exploit/explore event."""
        state, log_h = carry
        k_steps, k_perturb = jax.random.split(key)

        def step(state, k):
            state, losses = train_fn(state, hypers_dict(log_h), k)
            return constrain(state), losses

        state, losses_seq = jax.lax.scan(
            step, state, jax.random.split(k_steps, exploit_every)
        )
        losses = losses_seq[-1]  # rank on the window's final step

        # exploit: bottom n_replace member i copies params of the
        # rank-matched top member; explore: its (copied) hypers perturb
        # by a log-uniform factor, clipped into bounds
        order = jnp.argsort(losses)  # ascending: best first
        top = order[:n_replace]
        bottom = order[P - n_replace:]
        src = jnp.arange(P).at[bottom].set(top)  # identity elsewhere
        state = jax.tree.map(lambda x: x[src], state)
        state = constrain(state)

        factors = jax.random.uniform(
            k_perturb, (n_replace, log_h.shape[1]),
            minval=log_pf[0], maxval=log_pf[1], dtype=jnp.float32,
        )
        new_rows = jnp.clip(log_h[top] + factors, log_lo, log_hi)
        log_h = log_h.at[bottom].set(new_rows)
        return (state, log_h), losses

    def train_rounds_sharded(carry, key):
        """The graftmesh round body, run INSIDE shard_map: this shard's
        member block trains ``exploit_every`` steps collective-free
        (``log_h`` is replicated -- the block slices its hyper rows by
        axis index), then the exploit boundary pays the run's ONLY
        collectives: one loss all_gather for the replicated ranking,
        one member-state all_gather for the bottom-quantile copy.
        Per-member math is bitwise :func:`train_rounds`'s."""
        state, log_h = carry
        k_steps, k_perturb = jax.random.split(key)
        lo = jax.lax.axis_index(trial_axis) * p_local
        # exp over the FULL replicated table, block sliced after: the
        # unsharded path exponentiates at width P, and CPU libm
        # vectorizes transcendentals differently at narrow widths --
        # exp-then-slice keeps every member's hypers bitwise
        blk_hypers = {
            n: jax.lax.dynamic_slice_in_dim(v, lo, p_local)
            for n, v in hypers_dict(log_h).items()
        }

        def step(state, k):
            state, losses = train_fn(state, blk_hypers, k)
            return state, losses

        state, losses_seq = jax.lax.scan(
            step, state, jax.random.split(k_steps, exploit_every)
        )
        losses = jax.lax.all_gather(
            losses_seq[-1], trial_axis, tiled=True
        )
        order = jnp.argsort(losses)  # replicated: identical everywhere
        top = order[:n_replace]
        bottom = order[P - n_replace:]
        src = jnp.arange(P).at[bottom].set(top)
        full = jax.tree.map(
            lambda x: jax.lax.all_gather(x, trial_axis, tiled=True),
            state,
        )
        src_blk = jax.lax.dynamic_slice_in_dim(src, lo, p_local)
        state = jax.tree.map(lambda x: x[src_blk], full)
        factors = jax.random.uniform(
            k_perturb, (n_replace, log_h.shape[1]),
            minval=log_pf[0], maxval=log_pf[1], dtype=jnp.float32,
        )
        new_rows = jnp.clip(log_h[top] + factors, log_lo, log_hi)
        log_h = log_h.at[bottom].set(new_rows)
        return (state, log_h), losses

    def _finish(state, log_h, loss_hist):
        final = loss_hist[-1]
        # NaN-safe: a member perturbed into divergence in the last round
        # must not win the argmin (argsort during training already sends
        # NaNs to the replaced bottom quantile)
        best_i = jnp.argmin(jnp.where(jnp.isfinite(final), final, jnp.inf))
        return state, log_h, loss_hist, best_i

    @jax.jit
    def run(seed_arr):
        base = jax.random.key(seed_arr)
        k_init, k_rounds = jax.random.split(base)
        u = jax.random.uniform(k_init, (P, len(names)), dtype=jnp.float32)
        log_h0 = log_lo + u * (log_hi - log_lo)  # log-uniform start
        (state, log_h), loss_hist = jax.lax.scan(
            train_rounds,
            (constrain(init_state), log_h0),
            jax.random.split(k_rounds, n_rounds),
        )
        return _finish(state, log_h, loss_hist)

    @jax.jit
    def run_resume(seed_arr, state0, log_h0):
        # fold a resume marker so runner(init=...) at the SAME seed does
        # not replay the original segment's perturbation key stream --
        # exploration across segments must be independent, as if these
        # were rounds n..2n of one longer run
        base = jax.random.fold_in(jax.random.key(seed_arr), 1)
        _, k_rounds = jax.random.split(base)
        (state, log_h), loss_hist = jax.lax.scan(
            train_rounds,
            (constrain(state0), log_h0),
            jax.random.split(k_rounds, n_rounds),
        )
        return _finish(state, log_h, loss_hist)

    if mode == "shard_map":
        from jax.sharding import PartitionSpec as Pspec

        from .parallel.sharded import _shard_map

        def _schedule(state0, log_h0, round_keys):
            (state, log_h), loss_hist = jax.lax.scan(
                train_rounds_sharded, (state0, log_h0), round_keys
            )
            return state, log_h, loss_hist

        sharded_schedule = _shard_map()(
            _schedule, mesh=mesh,
            in_specs=(Pspec(trial_axis), Pspec(), Pspec()),
            out_specs=(Pspec(trial_axis), Pspec(), Pspec()),
            check_vma=False,
        )

        @jax.jit
        def run_sharded(seed_arr, state0):
            base = jax.random.key(seed_arr)
            k_init, k_rounds = jax.random.split(base)
            u = jax.random.uniform(
                k_init, (P, len(names)), dtype=jnp.float32
            )
            log_h0 = log_lo + u * (log_hi - log_lo)
            state, log_h, loss_hist = sharded_schedule(
                state0, log_h0, jax.random.split(k_rounds, n_rounds)
            )
            return _finish(state, log_h, loss_hist)

        @jax.jit
        def run_resume_sharded(seed_arr, state0, log_h0):
            base = jax.random.fold_in(jax.random.key(seed_arr), 1)
            _, k_rounds = jax.random.split(base)
            state, log_h, loss_hist = sharded_schedule(
                state0, log_h0, jax.random.split(k_rounds, n_rounds)
            )
            return _finish(state, log_h, loss_hist)

    def runner(seed=0, init=None):
        """``init=prev_out`` resumes: the population state AND hypers of
        a previous result dict (or one rebuilt via
        ``utils.checkpoint.load_pytree``) continue for another
        ``n_rounds`` -- checkpoint/resume for the on-device PBT path,
        matching ``device_loop``'s ``runner(init=...)`` contract."""
        if init is not None:
            missing = [n for n in names if n not in init["hypers"]]
            if missing:
                raise ValueError(
                    f"init hypers missing {missing}; expected {names}"
                )
            bad = {
                n: np.shape(init["hypers"][n])
                for n in names if np.shape(init["hypers"][n]) != (P,)
            }
            if bad:
                raise ValueError(
                    f"init hypers must cover {P} members x {names}; "
                    f"got shapes {bad}"
                )
            log_h0 = jnp.log(jnp.stack(
                [jnp.asarray(init["hypers"][n], jnp.float32) for n in names],
                axis=1,
            ))
            if mode == "shard_map":
                state, log_h, loss_hist, best_i = run_resume_sharded(
                    np.uint32(int(seed) % 2**32),
                    _place_population(init["state"], mesh, trial_axis),
                    log_h0,
                )
            else:
                state, log_h, loss_hist, best_i = run_resume(
                    np.uint32(int(seed) % 2**32), init["state"], log_h0
                )
            return _package(state, log_h, loss_hist, best_i)
        if mode == "shard_map":
            state, log_h, loss_hist, best_i = run_sharded(
                np.uint32(int(seed) % 2**32),
                _place_population(init_state, mesh, trial_axis),
            )
        else:
            state, log_h, loss_hist, best_i = run(
                np.uint32(int(seed) % 2**32)
            )
        return _package(state, log_h, loss_hist, best_i)

    def _package(state, log_h, loss_hist, best_i):
        # multi-host population: loss_hist/log_h shard over processes
        # and need the allgather fetch; single-process this is asarray
        from .parallel.multihost import fetch_global

        loss_hist, log_h = fetch_global((loss_hist, log_h))
        bi = int(best_i)
        hypers = {n: np.exp(log_h[:, i]) for i, n in enumerate(names)}
        return {
            "best_loss": float(loss_hist[-1, bi]),
            "best_index": bi,
            "best_hypers": {n: float(v[bi]) for n, v in hypers.items()},
            "hypers": hypers,
            "loss_history": loss_hist,
            "state": state,
            "n_steps": int(n_rounds * exploit_every),
        }

    # the graftir seam (like device_loop's runner._compiled_run): the
    # jitted schedule itself, traceable over abstract inputs
    runner._compiled_run = run_sharded if mode == "shard_map" else run
    runner._shard_mode = mode
    return runner


# ---------------------------------------------------------------------------
# graftir registration (hyperopt-tpu-lint --ir)
# ---------------------------------------------------------------------------

from .ops.compile import ProgramCapture, register_program  # noqa: E402


@register_program(
    "pbt.sharded_schedule",
    families=("hyperopt_tpu.pbt:compile_pbt",),
)
def _registry_pbt_sharded(p):
    """The graftmesh PBT schedule: per-shard member blocks training
    collective-free with the loss/state all_gathers only at exploit
    boundaries, traced over the forced 4-virtual-CPU-device trial
    mesh (whole schedule = one program, no donation)."""
    import jax
    import jax.numpy as jnp

    from .parallel.mesh import TRIAL_AXIS, registry_cpu_mesh

    mesh = registry_cpu_mesh(axis=TRIAL_AXIS)
    pop = 8

    def train_fn(state, hypers, key):
        theta = state["theta"] - hypers["lr"] * 2.0 * (
            state["theta"] - 0.7
        )
        return {"theta": theta}, (theta - 0.7) ** 2

    runner = compile_pbt(
        train_fn, {"theta": jnp.zeros((pop,), jnp.float32)},
        {"lr": (1e-3, 1.0)}, pop_size=pop, exploit_every=2, n_rounds=3,
        mesh=mesh, trial_axis=TRIAL_AXIS, shard_mode="shard_map",
    )
    from jax.sharding import NamedSharding, PartitionSpec as Pspec

    sharding = NamedSharding(mesh, Pspec(TRIAL_AXIS))
    return ProgramCapture(
        fn=runner._compiled_run,
        args=(
            jax.ShapeDtypeStruct((), np.uint32),
            {"theta": jax.ShapeDtypeStruct(
                (pop,), jnp.float32, sharding=sharding
            )},
        ),
    )
