"""Population-Based Training, fused on-device.

PBT (Jaderberg et al., 2017) tunes hyperparameters *during* training:
a population of P members trains in parallel; every ``exploit_every``
steps the bottom quantile copies the parameters of a top-quantile member
(exploit) and perturbs its hyperparameters (explore).  The reference
cannot express this at all (its trials are independent black-box
evaluations); here the whole schedule -- P models training, periodic
rank/copy/perturb -- compiles to ONE XLA program over the population
``vmap``, with the population axis optionally sharded over a mesh
(the same GSPMD shape as :mod:`hyperopt_tpu.models.resnet` /
``models.transformer`` population training).

Contract: the user supplies a *vmapped* population train function
``train_fn(state, hypers, key) -> (state, losses[P])`` (one gradient
step for every member; ``state`` is any pytree with leading population
axis P on every leaf; ``hypers`` a dict of ``[P]`` arrays) plus per-
hyperparameter log-space bounds.  :func:`compile_pbt` returns a runner
executing ``n_rounds x exploit_every`` total steps.

    from hyperopt_tpu.pbt import compile_pbt

    runner = compile_pbt(train_fn, init_state, {"lr": (1e-4, 1.0)},
                         pop_size=8, exploit_every=5, n_rounds=20)
    out = runner(seed=0)
    out["best_loss"], out["hypers"], out["loss_history"]  # [rounds, P]
"""

from __future__ import annotations

import numpy as np

__all__ = ["compile_pbt"]


def _log_bounds(hyper_bounds):
    """Validate ``{name: (low, high)}`` and return (names, log_lo, log_hi)
    as device arrays -- shared by every population-scheduler module
    (:mod:`hyperopt_tpu.pbt`, :mod:`hyperopt_tpu.hyperband`)."""
    import jax.numpy as jnp

    names = sorted(hyper_bounds)
    lo = np.array([float(hyper_bounds[n][0]) for n in names])
    hi = np.array([float(hyper_bounds[n][1]) for n in names])
    if not (lo > 0).all() or not (hi > lo).all():
        raise ValueError("hyper_bounds must satisfy 0 < low < high")
    return (
        names,
        jnp.asarray(np.log(lo), jnp.float32),
        jnp.asarray(np.log(hi), jnp.float32),
    )


def _hypers_dict(log_h, names):
    import jax.numpy as jnp

    return {n: jnp.exp(log_h[:, i]) for i, n in enumerate(names)}


def _make_constrain(mesh, trial_axis):
    """Population-axis sharding constraint (identity without a mesh)."""
    import jax

    if mesh is None:
        return lambda state: state
    from jax.sharding import NamedSharding, PartitionSpec as Pspec

    sharding = NamedSharding(mesh, Pspec(trial_axis))

    def constrain(state):
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, sharding), state
        )

    return constrain


def compile_pbt(
    train_fn,
    init_state,
    hyper_bounds,
    pop_size,
    exploit_every=5,
    n_rounds=20,
    exploit_quantile=0.25,
    perturb_factors=(0.8, 1.25),
    mesh=None,
    trial_axis="trial",
):
    """Compile a PBT schedule into one reusable device program.

    Args:
      train_fn: ``(state, hypers, key) -> (state, losses[P])`` -- one
        vmapped training step for the whole population.  ``losses`` is
        the ranking signal (lower is better).
      init_state: population state pytree (leading axis P on every leaf).
      hyper_bounds: ``{name: (low, high)}`` -- positive bounds; hypers
        live and perturb in log space (the PBT-natural scale for
        lr/wd-like knobs) and are sampled log-uniformly at start.
      pop_size: P.
      exploit_every: training steps between exploit/explore events.
      n_rounds: number of exploit/explore events; total steps =
        ``n_rounds * exploit_every``.
      exploit_quantile: fraction of the population replaced each event
        (bottom q copies params from the top q).
      perturb_factors: multiplicative explore range (log-uniform within).
      mesh / trial_axis: optional population sharding, as in
        :func:`hyperopt_tpu.device_loop.compile_fmin`.

    Returns ``runner(seed=0, init=None) -> dict`` with ``best_loss``,
    ``best_hypers`` ({name: float} of the best final member),
    ``hypers`` ({name: [P]} final), ``loss_history`` [n_rounds, P]
    (each round's last-step losses), and ``state`` (final population
    pytree, device arrays).  ``runner(init=prev_out)`` RESUMES a
    previous result's population (state + hypers) for another
    ``n_rounds`` -- checkpoint/resume for the PBT path; persist/restore
    the dict's ``state``/``hypers`` across processes with
    ``utils.checkpoint.save_pytree``/``load_pytree``.
    """
    import jax
    import jax.numpy as jnp

    P = int(pop_size)
    names, log_lo, log_hi = _log_bounds(hyper_bounds)
    n_replace = max(1, int(round(P * float(exploit_quantile))))
    if 2 * n_replace > P:
        raise ValueError(
            f"exploit_quantile={exploit_quantile} replaces {n_replace} of "
            f"{P} members; top and bottom quantiles must not overlap"
        )
    log_pf = (float(np.log(perturb_factors[0])),
              float(np.log(perturb_factors[1])))
    constrain = _make_constrain(mesh, trial_axis)

    def hypers_dict(log_h):
        return _hypers_dict(log_h, names)

    def train_rounds(carry, key):
        """exploit_every train steps, then one exploit/explore event."""
        state, log_h = carry
        k_steps, k_perturb = jax.random.split(key)

        def step(state, k):
            state, losses = train_fn(state, hypers_dict(log_h), k)
            return constrain(state), losses

        state, losses_seq = jax.lax.scan(
            step, state, jax.random.split(k_steps, exploit_every)
        )
        losses = losses_seq[-1]  # rank on the window's final step

        # exploit: bottom n_replace member i copies params of the
        # rank-matched top member; explore: its (copied) hypers perturb
        # by a log-uniform factor, clipped into bounds
        order = jnp.argsort(losses)  # ascending: best first
        top = order[:n_replace]
        bottom = order[P - n_replace:]
        src = jnp.arange(P).at[bottom].set(top)  # identity elsewhere
        state = jax.tree.map(lambda x: x[src], state)
        state = constrain(state)

        factors = jax.random.uniform(
            k_perturb, (n_replace, log_h.shape[1]),
            minval=log_pf[0], maxval=log_pf[1],
        )
        new_rows = jnp.clip(log_h[top] + factors, log_lo, log_hi)
        log_h = log_h.at[bottom].set(new_rows)
        return (state, log_h), losses

    def _finish(state, log_h, loss_hist):
        final = loss_hist[-1]
        # NaN-safe: a member perturbed into divergence in the last round
        # must not win the argmin (argsort during training already sends
        # NaNs to the replaced bottom quantile)
        best_i = jnp.argmin(jnp.where(jnp.isfinite(final), final, jnp.inf))
        return state, log_h, loss_hist, best_i

    @jax.jit
    def run(seed_arr):
        base = jax.random.key(seed_arr)
        k_init, k_rounds = jax.random.split(base)
        u = jax.random.uniform(k_init, (P, len(names)))
        log_h0 = log_lo + u * (log_hi - log_lo)  # log-uniform start
        (state, log_h), loss_hist = jax.lax.scan(
            train_rounds,
            (constrain(init_state), log_h0),
            jax.random.split(k_rounds, n_rounds),
        )
        return _finish(state, log_h, loss_hist)

    @jax.jit
    def run_resume(seed_arr, state0, log_h0):
        # fold a resume marker so runner(init=...) at the SAME seed does
        # not replay the original segment's perturbation key stream --
        # exploration across segments must be independent, as if these
        # were rounds n..2n of one longer run
        base = jax.random.fold_in(jax.random.key(seed_arr), 1)
        _, k_rounds = jax.random.split(base)
        (state, log_h), loss_hist = jax.lax.scan(
            train_rounds,
            (constrain(state0), log_h0),
            jax.random.split(k_rounds, n_rounds),
        )
        return _finish(state, log_h, loss_hist)

    def runner(seed=0, init=None):
        """``init=prev_out`` resumes: the population state AND hypers of
        a previous result dict (or one rebuilt via
        ``utils.checkpoint.load_pytree``) continue for another
        ``n_rounds`` -- checkpoint/resume for the on-device PBT path,
        matching ``device_loop``'s ``runner(init=...)`` contract."""
        if init is not None:
            missing = [n for n in names if n not in init["hypers"]]
            if missing:
                raise ValueError(
                    f"init hypers missing {missing}; expected {names}"
                )
            bad = {
                n: np.shape(init["hypers"][n])
                for n in names if np.shape(init["hypers"][n]) != (P,)
            }
            if bad:
                raise ValueError(
                    f"init hypers must cover {P} members x {names}; "
                    f"got shapes {bad}"
                )
            log_h0 = jnp.log(jnp.stack(
                [jnp.asarray(init["hypers"][n], jnp.float32) for n in names],
                axis=1,
            ))
            state, log_h, loss_hist, best_i = run_resume(
                np.uint32(int(seed) % 2**32), init["state"], log_h0
            )
            return _package(state, log_h, loss_hist, best_i)
        state, log_h, loss_hist, best_i = run(np.uint32(int(seed) % 2**32))
        return _package(state, log_h, loss_hist, best_i)

    def _package(state, log_h, loss_hist, best_i):
        # multi-host population: loss_hist/log_h shard over processes
        # and need the allgather fetch; single-process this is asarray
        from .parallel.multihost import fetch_global

        loss_hist, log_h = fetch_global((loss_hist, log_h))
        bi = int(best_i)
        hypers = {n: np.exp(log_h[:, i]) for i, n in enumerate(names)}
        return {
            "best_loss": float(loss_hist[-1, bi]),
            "best_index": bi,
            "best_hypers": {n: float(v[bi]) for n, v in hypers.items()},
            "hypers": hypers,
            "loss_history": loss_hist,
            "state": state,
            "n_steps": int(n_rounds * exploit_every),
        }

    return runner
