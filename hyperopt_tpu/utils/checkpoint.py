"""Experiment checkpoint/resume helpers.

The reference checkpoints by pickling the whole Trials after every round
(``fmin(trials_save_file=...)``, SURVEY.md SS5) -- that path works here
unchanged.  This module adds the TPU-side story promised in SURVEY.md SS5:
array-native serialization of the dense observation history (ObsBuffer /
JaxTrials) -- npz always, orbax when available -- so resuming reloads
arrays straight to device without replaying the doc list.
"""

from __future__ import annotations

import logging
import os

import numpy as np

logger = logging.getLogger(__name__)

__all__ = ["save_obs_buffer", "load_obs_buffer", "save_trials", "load_trials"]


def save_obs_buffer(buf, path):
    """Serialize an ObsBuffer's arrays + cursors to ``path`` (.npz)."""
    tmp = f"{path}.tmp.{os.getpid()}.npz"
    with open(tmp, "wb") as f:
        np.savez_compressed(
            f,
            values=buf.values,
            active=buf.active,
            losses=buf.losses,
            valid=buf.valid,
            tids=buf.tids,
            count=np.int64(buf.count),
            n_scanned=np.int64(buf._n_scanned),
            pending=np.asarray(buf._pending, dtype=np.int64),
            labels=np.asarray(buf.space.labels, dtype=object),
        )
    os.replace(tmp, path)
    return path


def load_obs_buffer(space, path):
    """Rebuild an ObsBuffer for ``space`` from a saved .npz."""
    from ..jax_trials import ObsBuffer

    with np.load(path, allow_pickle=True) as data:
        labels = list(data["labels"])
        if labels != list(space.labels):
            raise ValueError(
                f"checkpoint labels {labels} do not match space "
                f"{list(space.labels)}"
            )
        buf = ObsBuffer(space, capacity=int(data["values"].shape[1]))
        buf.values[:] = data["values"]
        buf.active[:] = data["active"]
        buf.losses[:] = data["losses"]
        buf.valid[:] = data["valid"]
        if "tids" in data:  # absent in pre-round-2 checkpoints
            buf.tids[:] = data["tids"]
        else:
            # legacy checkpoint: synthesized contiguous tids are only an
            # approximation (failed/NaN trials interleave tids in real
            # runs) -- mark the buffer so its first sync() against a
            # trials store rebuilds from the doc list (source of truth)
            # instead of trusting this guess for late-completion inserts
            buf.tids[: int(data["count"])] = np.arange(int(data["count"]))
            buf._legacy_tids = True
        buf.count = int(data["count"])
        buf._n_scanned = int(data["n_scanned"])
        # docs scanned while in flight must survive resume, else the
        # checkpoint path reintroduces async posterior starvation
        buf._pending = (
            [int(i) for i in data["pending"]] if "pending" in data else []
        )
    return buf


def save_trials(trials, path):
    """Checkpoint a Trials store.

    Uses orbax-checkpoint when importable (TPU-native array handling,
    async-friendly), else the stdlib pickle the reference uses.
    """
    try:
        import orbax.checkpoint  # noqa: F401

        # orbax manages directories of array trees; trial docs are
        # JSON-ish so pickle inside the managed dir keeps one mechanism
    except ImportError:
        pass
    import pickle

    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        pickle.dump(trials, f, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, path)
    return path


def load_trials(path):
    import pickle

    with open(path, "rb") as f:
        return pickle.load(f)
