"""Experiment checkpoint/resume helpers.

The reference checkpoints by pickling the whole Trials after every round
(``fmin(trials_save_file=...)``, SURVEY.md SS5) -- that path works here
unchanged.  This module adds the TPU-side story promised in SURVEY.md SS5:
array-native serialization of the dense observation history (ObsBuffer /
JaxTrials) -- npz always, orbax when available -- so resuming reloads
arrays straight to device without replaying the doc list.
"""

from __future__ import annotations

import logging
import os

import numpy as np

logger = logging.getLogger(__name__)

__all__ = [
    "save_obs_buffer",
    "load_obs_buffer",
    "save_obs_buffer_orbax",
    "load_obs_buffer_orbax",
    "save_trials",
    "load_trials",
    "load_guarded",
    "save_pytree",
    "load_pytree",
]


def save_obs_buffer(buf, path):
    """Serialize an ObsBuffer's arrays + cursors to ``path`` (.npz)."""
    tmp = f"{path}.tmp.{os.getpid()}.npz"
    with open(tmp, "wb") as f:
        np.savez_compressed(
            f,
            values=buf.values,
            active=buf.active,
            losses=buf.losses,
            valid=buf.valid,
            tids=buf.tids,
            count=np.int64(buf.count),
            n_scanned=np.int64(buf._n_scanned),
            pending=np.asarray(buf._pending, dtype=np.int64),
            labels=np.asarray(buf.space.labels, dtype=object),
        )
        # fsync before the rename (GL301): without it a crash after the
        # replace can publish a truncated checkpoint under the real name
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def load_obs_buffer(space, path):
    """Rebuild an ObsBuffer for ``space`` from a saved .npz."""
    from ..jax_trials import ObsBuffer

    with np.load(path, allow_pickle=True) as data:
        labels = list(data["labels"])
        if labels != list(space.labels):
            raise ValueError(
                f"checkpoint labels {labels} do not match space "
                f"{list(space.labels)}"
            )
        buf = ObsBuffer(space, capacity=int(data["values"].shape[1]))
        buf.values[:] = data["values"]
        buf.active[:] = data["active"]
        buf.losses[:] = data["losses"]
        buf.valid[:] = data["valid"]
        if "tids" in data:  # absent in pre-round-2 checkpoints
            buf.tids[:] = data["tids"]
        else:
            # legacy checkpoint: synthesized contiguous tids are only an
            # approximation (failed/NaN trials interleave tids in real
            # runs) -- mark the buffer so its first sync() against a
            # trials store rebuilds from the doc list (source of truth)
            # instead of trusting this guess for late-completion inserts
            buf.tids[: int(data["count"])] = np.arange(int(data["count"]))
            buf._legacy_tids = True
        buf.count = int(data["count"])
        buf._n_scanned = int(data["n_scanned"])
        # docs scanned while in flight must survive resume, else the
        # checkpoint path reintroduces async posterior starvation
        buf._pending = (
            [int(i) for i in data["pending"]] if "pending" in data else []
        )
    return buf


def _obs_buffer_tree(buf):
    return {
        "values": buf.values,
        "active": buf.active,
        "losses": buf.losses,
        "valid": buf.valid,
        "tids": buf.tids,
        # 0-d ndarrays, not np scalars: orbax's standard handler only
        # accepts array types
        "count": np.asarray(buf.count, dtype=np.int64),
        "n_scanned": np.asarray(buf._n_scanned, dtype=np.int64),
        # leading -1 sentinel: orbax cannot save zero-size arrays, and
        # the pending list is empty in the common (no-in-flight) case
        "pending": np.asarray([-1] + list(buf._pending), dtype=np.int64),
    }


def save_obs_buffer_orbax(buf, directory):
    """Serialize an ObsBuffer with orbax-checkpoint (TPU-native array
    handling: async-friendly, sharded-array aware, atomic directories).

    Layout: ``<directory>/arrays`` is the orbax tree (arrays + cursors;
    orbax's standard handler is arrays-only), ``<directory>/labels.json``
    the space-identity sidecar used for validation on load.  The npz
    path (:func:`save_obs_buffer`) remains the dependency-free default;
    this is the orbax story promised in SURVEY.md SS5 for deployments
    already standardized on orbax checkpoint trees.
    """
    import json

    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        ckptr.save(
            os.path.join(directory, "arrays"), _obs_buffer_tree(buf),
            force=True,
        )
    tmp = os.path.join(directory, f".labels.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        # space-identity sidecar only: all SHAPE information lives in
        # the orbax tree itself (restore builds its abstract target from
        # orbax metadata), so a crash between the two writes cannot make
        # the checkpoint unloadable -- a stale labels.json only matters
        # if the same directory is reused for a different space, which
        # load rejects either way
        json.dump({"labels": list(buf.space.labels)}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(directory, "labels.json"))
    return directory


def load_obs_buffer_orbax(space, directory):
    """Rebuild an ObsBuffer for ``space`` from an orbax checkpoint dir."""
    import json

    import orbax.checkpoint as ocp

    from ..jax_trials import ObsBuffer

    directory = os.path.abspath(directory)
    with open(os.path.join(directory, "labels.json")) as f:
        meta = json.load(f)
    if list(meta["labels"]) != list(space.labels):
        raise ValueError(
            f"checkpoint labels {meta['labels']} do not match space "
            f"{list(space.labels)}"
        )
    # restore against an abstract target (restoring target-less is
    # documented as unsafe under shardings different from save time);
    # shapes/dtypes come from the orbax tree's own metadata, so the
    # target always matches what was actually saved
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        arrays_dir = os.path.join(directory, "arrays")
        meta_obj = ckptr.metadata(arrays_dir)
        # orbax <= 0.7 returns the metadata tree (a dict) directly;
        # newer releases wrap it in CheckpointMetadata.item_metadata
        tree_meta = (
            meta_obj
            if isinstance(meta_obj, dict)
            else meta_obj.item_metadata.tree
        )
        target = {
            k: np.zeros(m.shape, np.dtype(m.dtype))
            for k, m in tree_meta.items()
        }
        data = ckptr.restore(
            arrays_dir, args=ocp.args.StandardRestore(target)
        )
    buf = ObsBuffer(space, capacity=int(np.asarray(data["values"]).shape[1]))
    buf.values[:] = data["values"]
    buf.active[:] = data["active"]
    buf.losses[:] = data["losses"]
    buf.valid[:] = data["valid"]
    buf.tids[:] = data["tids"]
    buf.count = int(data["count"])
    buf._n_scanned = int(data["n_scanned"])
    buf._pending = [int(i) for i in np.asarray(data["pending"])[1:]]
    return buf


def save_pytree(tree, path):
    """Checkpoint an arbitrary array pytree (population-scheduler state:
    ``compile_pbt``/``compile_sha`` ``out["state"]``, model params, ...)
    to one .npz, keyed by tree path.  Dependency-free counterpart of an
    orbax tree save; pairs with :func:`load_pytree` and the schedulers'
    ``runner(init=...)`` resume."""
    import jax

    leaves = jax.tree_util.tree_leaves_with_path(tree)
    arrays = {
        jax.tree_util.keystr(kp): np.asarray(v) for kp, v in leaves
    }
    tmp = f"{path}.tmp.{os.getpid()}.npz"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def load_pytree(target, path):
    """Rebuild a pytree with ``target``'s structure from a saved .npz;
    shapes and dtypes are validated leaf by leaf (``target`` may be the
    live pytree or an abstract one of zeros)."""
    import jax

    with np.load(path) as data:
        def fill(kp, leaf):
            key = jax.tree_util.keystr(kp)
            if key not in data:
                raise ValueError(f"checkpoint is missing leaf {key!r}")
            arr = data[key]
            # shape/dtype attributes only -- np.asarray on a live device
            # pytree would pull every array to host just to validate
            want_shape = tuple(np.shape(leaf))
            want_dtype = np.dtype(getattr(leaf, "dtype", type(leaf)))
            if arr.shape != want_shape or arr.dtype != want_dtype:
                raise ValueError(
                    f"leaf {key!r}: checkpoint {arr.shape}/{arr.dtype} "
                    f"does not match target {want_shape}/{want_dtype}"
                )
            return arr

        return jax.tree_util.tree_map_with_path(fill, target)


def save_trials(trials, path):
    """Checkpoint a Trials store.

    Trial docs are JSON-ish host objects, so this is the stdlib pickle
    the reference uses; the dense ARRAY state has the orbax-native path
    (:func:`save_obs_buffer_orbax`) for deployments standardized on
    orbax checkpoint trees.
    """
    import pickle

    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        pickle.dump(trials, f, protocol=pickle.HIGHEST_PROTOCOL)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def load_trials(path):
    import pickle

    with open(path, "rb") as f:
        return pickle.load(f)


def load_guarded(path, guard):
    """Load a pickled scheduler snapshot and refuse one whose recorded
    ``guard`` differs -- the shared contract of every host scheduler's
    checkpoint (asha / successive_halving / hyperband): a snapshot from
    a different schedule, space, algo, or seed must be REFUSED, never
    silently reinterpreted."""
    snap = load_trials(path)
    if snap.get("guard") != guard:
        raise ValueError(
            f"checkpoint {path!r} was written by schedule "
            f"{snap.get('guard')}; refusing to resume {guard}"
        )
    return snap
