"""Experiment checkpoint/resume helpers.

The reference checkpoints by pickling the whole Trials after every round
(``fmin(trials_save_file=...)``, SURVEY.md SS5) -- that path works here
unchanged.  This module adds the TPU-side story promised in SURVEY.md SS5:
array-native serialization of the dense observation history (ObsBuffer /
JaxTrials) -- npz always, orbax when available -- so resuming reloads
arrays straight to device without replaying the doc list.
"""

from __future__ import annotations

import logging
import os

import numpy as np

logger = logging.getLogger(__name__)

__all__ = [
    "save_obs_buffer",
    "load_obs_buffer",
    "save_obs_buffer_orbax",
    "load_obs_buffer_orbax",
    "save_trials",
    "load_trials",
    "load_guarded",
    "save_pytree",
    "load_pytree",
    "save_device_chunk",
    "load_device_chunk",
]


def save_obs_buffer(buf, path):
    """Serialize an ObsBuffer's arrays + cursors to ``path`` (.npz)."""
    tmp = f"{path}.tmp.{os.getpid()}.npz"
    with open(tmp, "wb") as f:
        f.write(obs_buffer_npz_bytes(buf))
        # fsync before the rename (GL301): without it a crash after the
        # replace can publish a truncated checkpoint under the real name
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def _fill_obs_buffer(space, data):
    """Rebuild an ObsBuffer for ``space`` from a loaded npz mapping --
    the shared core of :func:`load_obs_buffer` (file path) and
    :func:`load_obs_buffer_bytes` (in-bundle blob)."""
    from ..jax_trials import ObsBuffer

    labels = list(data["labels"])
    if labels != list(space.labels):
        raise ValueError(
            f"checkpoint labels {labels} do not match space "
            f"{list(space.labels)}"
        )
    buf = ObsBuffer(space, capacity=int(data["values"].shape[1]))
    buf.values[:] = data["values"]
    buf.active[:] = data["active"]
    buf.losses[:] = data["losses"]
    buf.valid[:] = data["valid"]
    if "tids" in data:  # absent in pre-round-2 checkpoints
        buf.tids[:] = data["tids"]
    else:
        # legacy checkpoint: synthesized contiguous tids are only an
        # approximation (failed/NaN trials interleave tids in real
        # runs) -- mark the buffer so its first sync() against a
        # trials store rebuilds from the doc list (source of truth)
        # instead of trusting this guess for late-completion inserts
        buf.tids[: int(data["count"])] = np.arange(int(data["count"]))
        buf._legacy_tids = True
    buf.count = int(data["count"])
    buf._n_scanned = int(data["n_scanned"])
    # docs scanned while in flight must survive resume, else the
    # checkpoint path reintroduces async posterior starvation
    buf._pending = (
        [int(i) for i in data["pending"]] if "pending" in data else []
    )
    return buf


def load_obs_buffer(space, path):
    """Rebuild an ObsBuffer for ``space`` from a saved .npz."""
    with np.load(path, allow_pickle=True) as data:
        return _fill_obs_buffer(space, data)


def obs_buffer_npz_bytes(buf):
    """The :func:`save_obs_buffer` npz payload as in-memory bytes --
    what :class:`DriverRecovery` embeds in its checkpoint bundle so a
    resumed resident mirror re-materializes without re-scanning the
    whole doc list."""
    import io

    bio = io.BytesIO()
    np.savez_compressed(
        bio,
        values=buf.values,
        active=buf.active,
        losses=buf.losses,
        valid=buf.valid,
        tids=buf.tids,
        count=np.int64(buf.count),
        n_scanned=np.int64(buf._n_scanned),
        pending=np.asarray(buf._pending, dtype=np.int64),
        labels=np.asarray(buf.space.labels, dtype=object),
    )
    return bio.getvalue()


def load_obs_buffer_bytes(space, blob):
    """Inverse of :func:`obs_buffer_npz_bytes`; raises ValueError on a
    space/label mismatch (the caller treats that as 'not my blob')."""
    import io

    with np.load(io.BytesIO(blob), allow_pickle=True) as data:
        return _fill_obs_buffer(space, data)


def _obs_buffer_tree(buf):
    return {
        "values": buf.values,
        "active": buf.active,
        "losses": buf.losses,
        "valid": buf.valid,
        "tids": buf.tids,
        # 0-d ndarrays, not np scalars: orbax's standard handler only
        # accepts array types
        "count": np.asarray(buf.count, dtype=np.int64),
        "n_scanned": np.asarray(buf._n_scanned, dtype=np.int64),
        # leading -1 sentinel: orbax cannot save zero-size arrays, and
        # the pending list is empty in the common (no-in-flight) case
        "pending": np.asarray([-1] + list(buf._pending), dtype=np.int64),
    }


def save_obs_buffer_orbax(buf, directory):
    """Serialize an ObsBuffer with orbax-checkpoint (TPU-native array
    handling: async-friendly, sharded-array aware, atomic directories).

    Layout: ``<directory>/arrays`` is the orbax tree (arrays + cursors;
    orbax's standard handler is arrays-only), ``<directory>/labels.json``
    the space-identity sidecar used for validation on load.  The npz
    path (:func:`save_obs_buffer`) remains the dependency-free default;
    this is the orbax story promised in SURVEY.md SS5 for deployments
    already standardized on orbax checkpoint trees.
    """
    import json

    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        ckptr.save(
            os.path.join(directory, "arrays"), _obs_buffer_tree(buf),
            force=True,
        )
    tmp = os.path.join(directory, f".labels.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        # space-identity sidecar only: all SHAPE information lives in
        # the orbax tree itself (restore builds its abstract target from
        # orbax metadata), so a crash between the two writes cannot make
        # the checkpoint unloadable -- a stale labels.json only matters
        # if the same directory is reused for a different space, which
        # load rejects either way
        json.dump({"labels": list(buf.space.labels)}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(directory, "labels.json"))
    return directory


def load_obs_buffer_orbax(space, directory):
    """Rebuild an ObsBuffer for ``space`` from an orbax checkpoint dir."""
    import json

    import orbax.checkpoint as ocp

    from ..jax_trials import ObsBuffer

    directory = os.path.abspath(directory)
    with open(os.path.join(directory, "labels.json")) as f:
        meta = json.load(f)
    if list(meta["labels"]) != list(space.labels):
        raise ValueError(
            f"checkpoint labels {meta['labels']} do not match space "
            f"{list(space.labels)}"
        )
    # restore against an abstract target (restoring target-less is
    # documented as unsafe under shardings different from save time);
    # shapes/dtypes come from the orbax tree's own metadata, so the
    # target always matches what was actually saved
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        arrays_dir = os.path.join(directory, "arrays")
        meta_obj = ckptr.metadata(arrays_dir)
        # orbax <= 0.7 returns the metadata tree (a dict) directly;
        # newer releases wrap it in CheckpointMetadata.item_metadata
        tree_meta = (
            meta_obj
            if isinstance(meta_obj, dict)
            else meta_obj.item_metadata.tree
        )
        target = {
            k: np.zeros(m.shape, np.dtype(m.dtype))
            for k, m in tree_meta.items()
        }
        data = ckptr.restore(
            arrays_dir, args=ocp.args.StandardRestore(target)
        )
    buf = ObsBuffer(space, capacity=int(np.asarray(data["values"]).shape[1]))
    buf.values[:] = data["values"]
    buf.active[:] = data["active"]
    buf.losses[:] = data["losses"]
    buf.valid[:] = data["valid"]
    buf.tids[:] = data["tids"]
    buf.count = int(data["count"])
    buf._n_scanned = int(data["n_scanned"])
    buf._pending = [int(i) for i in np.asarray(data["pending"])[1:]]
    return buf


def save_pytree(tree, path):
    """Checkpoint an arbitrary array pytree (population-scheduler state:
    ``compile_pbt``/``compile_sha`` ``out["state"]``, model params, ...)
    to one .npz, keyed by tree path.  Dependency-free counterpart of an
    orbax tree save; pairs with :func:`load_pytree` and the schedulers'
    ``runner(init=...)`` resume."""
    import jax

    leaves = jax.tree_util.tree_leaves_with_path(tree)
    arrays = {
        jax.tree_util.keystr(kp): np.asarray(v) for kp, v in leaves
    }
    tmp = f"{path}.tmp.{os.getpid()}.npz"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def load_pytree(target, path):
    """Rebuild a pytree with ``target``'s structure from a saved .npz;
    shapes and dtypes are validated leaf by leaf (``target`` may be the
    live pytree or an abstract one of zeros)."""
    import jax

    with np.load(path) as data:
        def fill(kp, leaf):
            key = jax.tree_util.keystr(kp)
            if key not in data:
                raise ValueError(f"checkpoint is missing leaf {key!r}")
            arr = data[key]
            # shape/dtype attributes only -- np.asarray on a live device
            # pytree would pull every array to host just to validate
            want_shape = tuple(np.shape(leaf))
            want_dtype = np.dtype(getattr(leaf, "dtype", type(leaf)))
            if arr.shape != want_shape or arr.dtype != want_dtype:
                raise ValueError(
                    f"leaf {key!r}: checkpoint {arr.shape}/{arr.dtype} "
                    f"does not match target {want_shape}/{want_dtype}"
                )
            return arr

        return jax.tree_util.tree_map_with_path(fill, target)


def durable_pickle(obj, path, fs=None, crash_between=None):
    """THE durable saver for pickled state: tmp + fsync + atomic
    rename.  Every checkpoint/WAL-adjacent pickle write must route
    through here (or fsync+rename itself) -- graftlint GL305 flags the
    bare-``pickle.dump`` shortcut.  ``fs`` is the PR-3 injection seam;
    ``crash_between`` names a crash point fired between the fsync and
    the publishing rename (the torn-publish window chaos tests kill
    in)."""
    import pickle

    from ..distributed.faults import REAL_FS

    fs = REAL_FS if fs is None else fs
    tmp = f"{path}.tmp.{os.getpid()}"
    with fs.open(tmp, "wb") as f:
        f.write(pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))
        fs.fsync(f)
        if crash_between:
            fs.crashpoint(crash_between)
    fs.rename(tmp, path)
    return path


def save_trials(trials, path, fs=None):
    """Checkpoint a Trials store.

    Trial docs are JSON-ish host objects, so this is the stdlib pickle
    the reference uses; the dense ARRAY state has the orbax-native path
    (:func:`save_obs_buffer_orbax`) for deployments standardized on
    orbax checkpoint trees.
    """
    return durable_pickle(trials, path, fs=fs)


def load_trials(path):
    import pickle

    with open(path, "rb") as f:
        return pickle.load(f)


def load_pickle_guarded(path, fs=None, what="checkpoint"):
    """Load a pickle, converting the raw truncation/corruption zoo
    (EOFError, UnpicklingError, ...) into a :class:`~hyperopt_tpu.
    exceptions.CheckpointError` that names the file and the recovery
    options -- a resumed driver must never greet its operator with a
    bare ``pickle`` traceback."""
    import pickle

    from ..distributed.faults import REAL_FS
    from ..exceptions import CheckpointError

    from ..distributed import _common

    fs = REAL_FS if fs is None else fs

    def _read():
        with fs.open(path, "rb") as f:
            return f.read()

    try:
        return pickle.loads(
            _common.with_retries(_read, label="checkpoint read")
        )
    except (
        EOFError, pickle.UnpicklingError, AttributeError, ImportError,
        IndexError, MemoryError, ValueError,
    ) as e:
        hints = [
            f"{sib} exists"
            for sib in (f"{path}.meta", f"{path}.wal")
            if fs.exists(sib)
        ]
        hint = (
            f" (last-good recovery artifacts: {', '.join(hints)}; run "
            f"`hyperopt-tpu-fsck --driver {path}` to audit)"
            if hints
            else " (no sidecar recovery artifacts found; the study must "
            "be restarted from scratch)"
        )
        raise CheckpointError(
            f"{what} {path!r} is truncated or corrupt "
            f"({type(e).__name__}: {e}){hint}"
        ) from e


# ---------------------------------------------------------------------------
# chunked device-loop carry bundles (device_loop.compile_fmin chunk_size=)
# ---------------------------------------------------------------------------


DEVICE_CHUNK_FORMAT = 1


def save_device_chunk(path, bundle, fs=None):
    """Durably publish one chunk-boundary carry bundle of the chunked
    device loop: the full scan carry (values/active/losses/valid as
    host numpy), the seed, the warm offset, and ``chunk_next`` -- the
    first chunk a resumed run must dispatch.  Rides
    :func:`durable_pickle` (tmp + fsync + atomic rename through the
    PR-3 ``fs=`` seam), with the shared ``after_ckpt_tmp_before_rename``
    torn-publish crash window armed for the chaos tests."""
    bundle = dict(bundle, format=DEVICE_CHUNK_FORMAT)
    return durable_pickle(
        bundle, path, fs=fs, crash_between="after_ckpt_tmp_before_rename"
    )


def load_device_chunk(path, guard=None, fs=None):
    """Load a chunk bundle, refusing (CheckpointError) corruption and
    -- when ``guard`` is given -- a bundle written by a different
    experiment (space/objective/algo/geometry fingerprint): resuming a
    foreign chunk stream would silently change the experiment."""
    from ..exceptions import CheckpointError

    bundle = load_pickle_guarded(
        path, fs=fs, what="device-loop chunk checkpoint"
    )
    if bundle.get("format") != DEVICE_CHUNK_FORMAT:
        raise CheckpointError(
            f"device-loop chunk checkpoint {path!r} has format "
            f"{bundle.get('format')!r}; this loader reads format "
            f"{DEVICE_CHUNK_FORMAT}"
        )
    if (
        guard is not None
        and bundle.get("guard") is not None
        and list(bundle["guard"]) != list(guard)
    ):
        raise CheckpointError(
            f"device-loop chunk checkpoint {path!r} was written by a "
            f"different experiment (guard {bundle['guard']!r} != "
            f"{list(guard)!r}); refusing to resume"
        )
    return bundle


# ---------------------------------------------------------------------------
# rstate serialization (JSON-able, for WAL records and bundle metadata)
# ---------------------------------------------------------------------------


def _jsonify_state(v):
    if isinstance(v, np.ndarray):
        return {"__ndarray__": [v.dtype.str, v.tolist()]}
    if isinstance(v, dict):
        return {k: _jsonify_state(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonify_state(x) for x in v]
    if isinstance(v, np.generic):
        return v.item()
    return v


def _dejsonify_state(v):
    if isinstance(v, dict):
        if set(v) == {"__ndarray__"}:
            dtype, data = v["__ndarray__"]
            return np.asarray(data, dtype=np.dtype(dtype))
        return {k: _dejsonify_state(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_dejsonify_state(x) for x in v]
    return v


def encode_rstate(rstate):
    """The bit-generator cursor of an ``np.random.Generator`` (or
    legacy ``RandomState``) as a JSON-able dict -- the per-record
    rstate cursor of the write-ahead log.  Restoring it and re-drawing
    reproduces the exact seed stream, which is what makes a resumed
    suggestion stream bitwise-identical to the uninterrupted run."""
    if hasattr(rstate, "bit_generator"):
        return {
            "kind": "generator",
            "state": _jsonify_state(rstate.bit_generator.state),
        }
    state = rstate.get_state()
    return {"kind": "randomstate", "state": _jsonify_state(list(state))}


def decode_rstate(encoded):
    """Inverse of :func:`encode_rstate`: a fresh generator positioned
    at the recorded cursor."""
    state = _dejsonify_state(encoded["state"])
    if encoded["kind"] == "generator":
        bitgen_cls = getattr(np.random, state["bit_generator"])
        rstate = np.random.Generator(bitgen_cls())
        rstate.bit_generator.state = state
        return rstate
    rstate = np.random.RandomState()
    state = list(state)
    state[1] = np.asarray(state[1], dtype=np.uint32)
    rstate.set_state(tuple(state))
    return rstate


# ---------------------------------------------------------------------------
# DriverRecovery: the sequential driver's crash-recovery coordinator
# ---------------------------------------------------------------------------


class RestoredDriverState:
    """What :meth:`DriverRecovery.load` hands back to ``fmin``."""

    def __init__(self, trials, rstate, ask_ahead_seed, n_replayed_tells,
                 n_replayed_asks):
        self.trials = trials
        self.rstate = rstate
        self.ask_ahead_seed = ask_ahead_seed
        self.n_replayed_tells = n_replayed_tells
        self.n_replayed_asks = n_replayed_asks


class DriverRecovery:
    """Write-ahead log + durable checkpoint bundles for ``fmin``'s
    sequential driver (the FAILURES.md driver recovery matrix).

    Artifacts, all rooted at ``path``:

    * ``path``       -- the pickled Trials store (durable tmp+fsync+
      rename; stays loadable by plain ``pickle.load`` for backward
      compatibility with the bare ``trials_save_file`` contract).
    * ``path.meta``  -- the bundle metadata: guard fingerprint, numpy
      bit-generator state, ask-ahead seam seed, WAL watermark, and the
      resident ObsBuffer npz blobs (``obs_buffer_npz_bytes``).
    * ``path.wal``   -- the :class:`~hyperopt_tpu.utils.wal.TellWAL`:
      one ``ask`` record per algo call (docs + rstate cursor), one
      ``tell`` record per applied result, each durable BEFORE the
      corresponding in-memory mutation.

    Exactly-once semantics: a tell present in the WAL is never
    re-evaluated (replay marks its doc DONE before the driver runs) and
    never double-applied (replay skips docs already terminal); an ask
    that never reached the WAL is re-issued from the restored rstate
    cursor and draws the identical seed.

    ``fs`` is the PR-3 fault-injection seam; the chaos suite arms the
    :data:`~hyperopt_tpu.distributed.faults.DRIVER_CRASH_POINTS` on it.
    ``cadence`` is how many tells ride on the WAL between full bundle
    publishes (replay length is bounded by it).
    """

    META_FORMAT = 1

    def __init__(self, path, fs=None, cadence=25, guard=None):
        from ..distributed.faults import REAL_FS
        from .wal import TellWAL

        self.path = str(path)
        self.meta_path = self.path + ".meta"
        self.fs = REAL_FS if fs is None else fs
        self.cadence = max(1, int(cadence))
        self.guard = None if guard is None else list(guard)
        self.wal = TellWAL(self.path + ".wal", fs=self.fs, guard=self.guard)
        self._tells_since_ckpt = 0
        #: accumulated wall-clock spent on durability (WAL appends +
        #: bundle publishes) -- bench.py's ``resume_overhead_per_trial``
        self.seconds_spent = 0.0

    def set_guard(self, guard):
        """Attach the study fingerprint (space/algo/objective identity;
        ``fmin`` builds it) -- checked against every artifact on load
        and stamped into everything written."""
        self.guard = None if guard is None else list(guard)
        self.wal.guard = self.guard

    def exists(self):
        from ..distributed import _common

        return _common.with_retries(
            lambda: self.fs.exists(self.path), label="ckpt exists"
        )

    # -- write-ahead logging ----------------------------------------------
    def log_ask(self, docs, rstate):
        """Durably record an algo call's new trial docs plus the rstate
        cursor AFTER its seed draw, before the docs are inserted."""
        import time as _time

        t0 = _time.perf_counter()
        self.fs.crashpoint("before_wal_append")
        # flush-only (no fsync barrier): a lost ask re-derives bitwise
        # from the restored cursor; the tell's fsync covers it -- one
        # disk barrier per trial, not two
        self.wal.append("ask", {
            "docs": docs,
            "rstate": encode_rstate(rstate),
        }, sync=False)
        self.fs.crashpoint("after_wal_append_before_tell")
        self.seconds_spent += _time.perf_counter() - t0

    def log_tell(self, tid, state, result=None, error=None, tb=None):
        """Durably record one completed (or errored) evaluation before
        its result is applied to the Trials store."""
        import time as _time

        t0 = _time.perf_counter()
        self.fs.crashpoint("before_wal_append")
        rec = {"tid": int(tid), "state": int(state)}
        if result is not None:
            rec["result"] = result
        if error is not None:
            rec["error"] = error
        if tb is not None:
            rec["traceback"] = tb
        self.wal.append("tell", rec)
        self.fs.crashpoint("after_wal_append_before_tell")
        self.seconds_spent += _time.perf_counter() - t0
        self._tells_since_ckpt += 1

    # -- checkpoint bundles ------------------------------------------------
    def maybe_checkpoint(self, trials, rstate, ask_ahead_seed=None,
                         force=False):
        """Publish a bundle when the cadence (or ``force``) says so."""
        if not force and self._tells_since_ckpt < self.cadence:
            return False
        self.checkpoint(trials, rstate, ask_ahead_seed=ask_ahead_seed)
        return True

    def checkpoint(self, trials, rstate, ask_ahead_seed=None):
        """Atomically publish the full driver state: trials pickle,
        then the metadata bundle, then compact the WAL.  Every crash
        window in between is covered: a stale artifact is always
        superseded by the WAL records that outlived it, and replay
        deduplicates by tid."""
        import time as _time

        t0 = _time.perf_counter()
        obs_npz = []
        for buf in getattr(trials, "_buffers", {}).values():
            try:
                obs_npz.append(obs_buffer_npz_bytes(buf))
            except Exception:  # graftlint: disable=GL302 the blob is an optimization; resume falls back to a doc-list rescan
                logger.exception("obs-buffer snapshot failed; resume "
                                 "will rebuild from the doc list")
        meta = {
            "format": self.META_FORMAT,
            "guard": self.guard,
            "n_trials": len(trials._dynamic_trials),
            "wal_seq": self.wal.next_seq,
            "total_tells": self.wal.total_tells,
            "rstate": encode_rstate(rstate),
            "ask_ahead_seed": (
                None if ask_ahead_seed is None else int(ask_ahead_seed)
            ),
            "obs_npz": obs_npz,
        }
        from ..distributed import _common

        # each publish retries whole on a transient fault (the tmp file
        # is rewritten from scratch, so retry is idempotent); a crash
        # point firing inside is a BaseException and propagates
        _common.with_retries(
            lambda: durable_pickle(
                trials, self.path, fs=self.fs,
                crash_between="after_ckpt_tmp_before_rename",
            ),
            label="trials publish",
        )
        _common.with_retries(
            lambda: durable_pickle(
                meta, self.meta_path, fs=self.fs,
                crash_between="after_ckpt_tmp_before_rename",
            ),
            label="bundle publish",
        )
        self.fs.crashpoint("after_ckpt_publish_before_wal_reset")
        _common.with_retries(self.wal.reset, label="wal reset")
        self._tells_since_ckpt = 0
        self.seconds_spent += _time.perf_counter() - t0
        return self.path

    # -- restore -----------------------------------------------------------
    def load(self):
        """Load + WAL-replay the durable driver state, or None when no
        trials artifact exists yet.  Refuses (CheckpointError) guard
        mismatches and mid-file WAL corruption; merely-torn WAL tails
        are truncated and survive."""
        from ..exceptions import CheckpointError

        if not self.exists():
            return None
        trials = load_pickle_guarded(
            self.path, fs=self.fs, what="trials checkpoint"
        )
        meta = None
        if self.fs.exists(self.meta_path):
            meta = load_pickle_guarded(
                self.meta_path, fs=self.fs, what="checkpoint bundle"
            )
            if (
                self.guard is not None
                and meta.get("guard") is not None
                and list(meta["guard"]) != list(self.guard)
            ):
                raise CheckpointError(
                    f"checkpoint bundle {self.meta_path!r} was written "
                    f"by a different study (guard {meta['guard']!r} != "
                    f"{self.guard!r}); refusing to resume"
                )
        records = self.wal.replay() if self.wal.exists() else []
        watermark = meta["wal_seq"] if meta else 0
        suffix = [r for r in records if int(r["seq"]) >= watermark]
        n_asks, n_tells, last_cursor = self._apply_records(trials, suffix)
        if last_cursor is not None:
            rstate, seed = decode_rstate(last_cursor), None
        elif meta is not None:
            rstate = decode_rstate(meta["rstate"])
            seed = meta.get("ask_ahead_seed")
        else:
            rstate, seed = None, None
            logger.warning(
                "resuming %r without recovery metadata (legacy "
                "checkpoint): trials are restored but the suggestion "
                "stream will not match the uninterrupted run",
                self.path,
            )
        if meta is not None and meta.get("obs_npz"):
            # stashed for JaxTrials.obs_buffer: the resident mirror
            # re-materializes from these instead of re-scanning docs
            trials._stashed_obs_npz = list(meta["obs_npz"])
        return RestoredDriverState(trials, rstate, seed, n_tells, n_asks)

    @staticmethod
    def _apply_records(trials, records):
        """Replay a WAL suffix into ``trials`` exactly once: asks
        insert docs not yet present (in record order -- tid order), and
        tells finalize docs that are not already terminal."""
        from ..base import (
            JOB_STATE_DONE,
            JOB_STATE_ERROR,
            validate_trial,
        )

        by_tid = {t["tid"]: t for t in trials._dynamic_trials}
        n_asks = n_tells = 0
        last_cursor = None
        for rec in records:
            if rec.get("kind") == "ask":
                last_cursor = rec["rstate"]
                fresh = [
                    validate_trial(d)
                    for d in rec["docs"]
                    if d["tid"] not in by_tid
                ]
                if fresh:
                    trials._insert_trial_docs(fresh)
                    for doc in fresh:
                        by_tid[doc["tid"]] = doc
                    n_asks += 1
            elif rec.get("kind") == "tell":
                doc = by_tid.get(rec["tid"])
                if doc is not None and doc["state"] not in (
                    JOB_STATE_DONE, JOB_STATE_ERROR,
                ):
                    doc["state"] = rec["state"]
                    if "result" in rec:
                        doc["result"] = rec["result"]
                    if "error" in rec:
                        doc["misc"]["error"] = rec["error"]
                    if "traceback" in rec:
                        doc["misc"]["traceback"] = rec["traceback"]
                    n_tells += 1
        trials.refresh()
        return n_asks, n_tells, last_cursor


def load_guarded(path, guard):
    """Load a pickled scheduler snapshot and refuse one whose recorded
    ``guard`` differs -- the shared contract of every host scheduler's
    checkpoint (asha / successive_halving / hyperband): a snapshot from
    a different schedule, space, algo, or seed must be REFUSED, never
    silently reinterpreted."""
    snap = load_trials(path)
    if snap.get("guard") != guard:
        raise ValueError(
            f"checkpoint {path!r} was written by schedule "
            f"{snap.get('guard')}; refusing to resume {guard}"
        )
    return snap
