"""Small shared helpers (parity: reference ``hyperopt/utils.py``, SURVEY.md SS2)."""

from __future__ import annotations

import datetime
import os
import tempfile

import numpy as np

__all__ = [
    "enable_compilation_cache",
    "coarse_utcnow",
    "fast_isin",
    "get_most_recent_inds",
    "temp_dir",
    "working_dir",
    "path_split_all",
    "get_closest_dir",
]


def enable_compilation_cache(cache_dir=None, force_cpu=False):
    """Turn on JAX's persistent compilation cache.

    Every (space, capacity-bucket, batch) combination costs an XLA
    compile on first use (~seconds on TPU); the persistent cache reuses
    compilations across processes and runs, which dominates wall-clock
    for short fmin experiments.  Defaults to
    ``$JAX_COMPILATION_CACHE_DIR`` or ``~/.cache/hyperopt_tpu_xla``.

    On the CPU backend this is a NO-OP (returns None) unless
    ``force_cpu=True``: jaxlib 0.4.36's CPU runtime intermittently
    corrupts the heap while deserializing cached executables -- a
    warm-cache process dies minutes later with SIGSEGV/glibc abort at
    an unrelated allocation (see FAILURES.md "Known test debt").
    Compile seconds only dominate on accelerators anyway; a CPU run
    paying them keeps its heap.
    """
    import jax

    if jax.default_backend() == "cpu" and not force_cpu:
        import logging

        logging.getLogger(__name__).info(
            "persistent compilation cache left OFF on the CPU backend "
            "(jaxlib 0.4.36 warm-cache deserialization heap-corrupts; "
            "FAILURES.md); pass force_cpu=True to override"
        )
        return None
    if cache_dir is None:
        cache_dir = os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.join(
                os.path.expanduser("~"), ".cache", "hyperopt_tpu_xla"
            ),
        )
        # partition by backend: entries AOT-compiled through a
        # remote-attachment platform can carry host-machine features the
        # local CPU lacks (XLA warns of potential SIGILL on load), so a
        # cpu run must never read an accelerator run's entries
        cache_dir = os.path.join(cache_dir, jax.default_backend())
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache every compilation, however small/fast
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    return cache_dir


def coarse_utcnow():
    """UTC now, truncated to milliseconds (stable across (de)serialization)."""
    now = datetime.datetime.now(datetime.timezone.utc).replace(tzinfo=None)
    return now.replace(microsecond=(now.microsecond // 1000) * 1000)


def fast_isin(X, Y):
    """Boolean mask: which elements of X are in (sorted or unsorted) Y."""
    X = np.asarray(X)
    Y = np.asarray(Y)
    if Y.size == 0:
        return np.zeros(len(X), dtype=bool)
    return np.isin(X, Y)


def get_most_recent_inds(obj):
    """Indices of docs that are the latest version per ``_id``.

    ``obj`` is a list of dicts with ``_id`` and ``version`` keys.
    """
    ids = np.array([o["_id"] for o in obj])
    versions = np.array([o.get("version", 0) for o in obj])
    order = np.lexsort((versions, ids))
    ids_sorted = ids[order]
    last_of_id = np.ones(len(ids), dtype=bool)
    last_of_id[:-1] = ids_sorted[1:] != ids_sorted[:-1]
    return np.sort(order[last_of_id])


class temp_dir:
    """Context manager: mkdir (tempfile if needed), yield path, keep dir."""

    def __init__(self, suffix=""):
        self.suffix = suffix

    def __enter__(self):
        self.path = tempfile.mkdtemp(suffix=self.suffix)
        return self.path

    def __exit__(self, *exc):
        return False


class working_dir:
    """Context manager: chdir into ``path`` (creating it), restore on exit."""

    def __init__(self, path):
        self.path = path

    def __enter__(self):
        os.makedirs(self.path, exist_ok=True)
        self._prev = os.getcwd()
        os.chdir(self.path)
        return self.path

    def __exit__(self, *exc):
        os.chdir(self._prev)
        return False


def path_split_all(path):
    """Split a path into all of its components."""
    parts = []
    while True:
        path, tail = os.path.split(path)
        if tail:
            parts.append(tail)
        else:
            if path:
                parts.append(path)
            break
    return list(reversed(parts))


def get_closest_dir(workdir):
    """Deepest existing ancestor of ``workdir`` plus the first missing part."""
    closest_dir = ""
    for part in path_split_all(workdir):
        candidate = os.path.join(closest_dir, part) if closest_dir else part
        if os.path.isdir(candidate):
            closest_dir = candidate
        else:
            return closest_dir, part
    return closest_dir, ""
