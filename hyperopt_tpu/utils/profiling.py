"""Tracing / profiling hooks.

The reference has only stdlib logging (SURVEY.md SS5 'tracing: none');
the TPU equivalent promised there: ``jax.profiler`` trace capture (XLA
timeline -> Perfetto/TensorBoard) plus cheap per-suggest-step wall-clock
metrics that work on any backend.
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import defaultdict

logger = logging.getLogger(__name__)

__all__ = ["StepTimer", "instrument_algo", "device_trace"]


class StepTimer:
    """Accumulates wall-clock stats per named step.

    >>> timer = StepTimer()
    >>> with timer.measure("suggest"):
    ...     pass
    >>> timer.summary()["suggest"]["count"]
    1
    """

    def __init__(self):
        self._records = defaultdict(list)

    @contextlib.contextmanager
    def measure(self, name):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._records[name].append(time.perf_counter() - t0)

    def record(self, name, seconds):
        self._records[name].append(float(seconds))

    def summary(self):
        out = {}
        for name, xs in self._records.items():
            n = len(xs)
            total = sum(xs)
            out[name] = {
                "count": n,
                "total_s": total,
                "mean_s": total / n,
                "min_s": min(xs),
                "max_s": max(xs),
            }
        return out

    def log_summary(self, level=logging.INFO):
        for name, s in sorted(self.summary().items()):
            logger.log(
                level,
                "%s: n=%d mean=%.4fs total=%.2fs",
                name, s["count"], s["mean_s"], s["total_s"],
            )


def instrument_algo(algo, timer, name=None):
    """Wrap a suggest function so every call is timed.

    >>> timed = instrument_algo(tpe_jax.suggest, timer)
    >>> fmin(fn, space, algo=timed, ...)
    """
    label = name or getattr(algo, "__name__", "suggest")

    def timed(new_ids, domain, trials, seed, *args, **kwargs):
        with timer.measure(label):
            return algo(new_ids, domain, trials, seed, *args, **kwargs)

    timed.__name__ = f"timed_{label}"
    return timed


@contextlib.contextmanager
def device_trace(logdir, create_perfetto_link=False):
    """Capture an XLA device trace (view in TensorBoard / Perfetto).

    No-op fallback when the profiler is unavailable on the backend.
    """
    import jax

    started = False
    try:
        jax.profiler.start_trace(
            logdir, create_perfetto_link=create_perfetto_link
        )
        started = True
    except Exception as e:  # pragma: no cover - backend dependent
        logger.warning("device_trace unavailable: %s", e)
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # pragma: no cover
                logger.warning("stop_trace failed: %s", e)
