"""Write-ahead tell log for the sequential driver.

The crash-recovery contract (FAILURES.md "driver" rows): every ask the
driver issues and every tell it applies is appended to this log,
fsync-durable, *before* the corresponding in-memory mutation -- so a
driver killed at any instruction boundary can be resumed with zero lost
and zero duplicated tells, and with the numpy bit-generator cursor each
ask record carries, the resumed suggestion stream is bitwise identical
to the run that never crashed.

Record format (one line per record, inspectable with ``cat``)::

    <crc32 of the json, 8 hex chars> <json body>\n

where the body is ``{"seq": n, ...payload}``.  The first record of
every file is a header (``{"seq": -1, "magic": ..., "guard": ...,
"base_seq": N, "base_tells": M}``); ``base_seq``/``base_tells`` carry
the monotone counters across :meth:`TellWAL.reset` compactions, so
"total tells ever logged" survives checkpoint absorption (the zero-
lost/zero-duplicate assertion of the chaos suite reads it).

Torn-tail rule: a crash (or torn write) mid-append leaves a final line
that is truncated or fails its checksum.  :meth:`TellWAL.recover`
truncates exactly that tail -- atomically, via tmp+fsync+rename -- and
replay proceeds from the valid prefix.  A checksum failure *before* the
final record is corruption the protocol cannot have produced on its
own; it raises :class:`~hyperopt_tpu.exceptions.CheckpointError` and is
``fsck --driver``'s job to quarantine.

All filesystem access goes through the PR-3 ``fs`` seam
(:mod:`hyperopt_tpu.distributed.faults`), so the chaos suite injects
transient errors, partial writes, and the driver crash points without
monkeypatching.
"""

from __future__ import annotations

import json
import logging
import os
import zlib

from ..distributed.faults import REAL_FS
from ..exceptions import CheckpointError

logger = logging.getLogger(__name__)

__all__ = ["TellWAL", "WAL_MAGIC"]

WAL_MAGIC = "hyperopt-tpu-wal-1"


def _encode_record(body):
    data = json.dumps(body, sort_keys=True, separators=(",", ":"))
    crc = zlib.crc32(data.encode("utf-8")) & 0xFFFFFFFF
    return f"{crc:08x} {data}\n"


def _decode_line(line):
    """The parsed body, or None for a torn/garbled line."""
    if not line.endswith("\n"):
        return None
    try:
        crc_hex, data = line[:-1].split(" ", 1)
        if int(crc_hex, 16) != zlib.crc32(data.encode("utf-8")) & 0xFFFFFFFF:
            return None
        body = json.loads(data)
    except (ValueError, json.JSONDecodeError):
        return None
    return body if isinstance(body, dict) else None


class TellWAL:
    """Append-only, checksummed, fsync-durable record log at ``path``.

    ``append`` assigns monotone sequence numbers that survive
    :meth:`reset` compaction (the checkpoint absorbs a prefix; the
    header of the rewritten file carries the base counters forward).
    ``guard`` is the study fingerprint stamped into the header --
    replaying a log written by a different space/algo must be refused,
    never silently reinterpreted.
    """

    def __init__(self, path, fs=REAL_FS, guard=None):
        self.path = path
        self.fs = fs
        self.guard = list(guard) if guard is not None else None
        self._f = None  # persistent append handle
        self._next_seq = None  # lazily established from the file
        self._base_tells = 0
        self._n_tells = 0  # tells appended since the last header
        #: fsync barriers this WAL has issued (append sync, group-commit
        #: barrier, header publish, compaction, torn-tail truncation) --
        #: the numerator of the bench's ``wal_fsyncs_per_tell``
        self.fsyncs = 0
        self._unbarriered = False  # flush-only records since the last fsync

    # -- scanning ----------------------------------------------------------
    def exists(self):
        from ..distributed import _common

        return _common.with_retries(
            lambda: self.fs.exists(self.path), label="wal exists"
        )

    def scan(self):
        """Parse the log: ``(header, records, good_bytes, torn_bytes)``.

        ``records`` excludes the header; ``torn_bytes`` > 0 means the
        tail is torn (crash mid-append) and :meth:`recover` will
        truncate it.  A checksum failure before the final line is
        mid-file corruption and raises :class:`CheckpointError`.
        """
        if not self.exists():
            return None, [], 0, 0
        from ..distributed import _common

        def _read():
            with self.fs.open(self.path, "rb") as f:
                return f.read()

        raw = _common.with_retries(_read, label="wal scan")
        # split at the byte level: records are ascii json (ensure_ascii),
        # so any undecodable line is torn garbage, and byte offsets --
        # what truncation needs -- stay exact
        lines = raw.splitlines(keepends=True)
        header, records, good = None, [], 0
        seen_seqs = set()
        for i, bline in enumerate(lines):
            try:
                line = bline.decode("utf-8")
            except UnicodeDecodeError:
                line = ""  # undecodable: treated as torn below
            body = _decode_line(line)
            if body is None:
                if i != len(lines) - 1:
                    raise CheckpointError(
                        f"WAL {self.path!r}: corrupt record at line "
                        f"{i + 1} is not the final line -- this is not "
                        "a torn tail; run fsck --driver to quarantine"
                    )
                break
            if body.get("seq") == -1:
                if header is None:
                    header = body
                    if (
                        self.guard is not None
                        and body.get("guard") is not None
                        and list(body["guard"]) != list(self.guard)
                    ):
                        raise CheckpointError(
                            f"WAL {self.path!r} was written by a "
                            f"different study (guard {body.get('guard')!r}"
                            f" != {self.guard!r}); refusing to replay"
                        )
            elif body["seq"] not in seen_seqs:
                # a retried append whose first attempt landed despite
                # its fsync error writes the same (seq, payload) twice;
                # one logical record, counted and replayed once
                seen_seqs.add(body["seq"])
                records.append(body)
            good += len(bline)
        return header, records, good, len(raw) - good

    def recover(self):
        """Truncate a torn tail (atomic rewrite); returns bytes dropped."""
        header, records, good, torn = self.scan()
        if torn:
            from ..distributed import _common

            def _truncate():
                with self.fs.open(self.path, "rb") as f:
                    raw = f.read()
                tmp = f"{self.path}.tmp.{os.getpid()}"
                with self.fs.open(tmp, "wb") as f:
                    f.write(raw[:good])
                    self.fs.fsync(f)
                    self.fsyncs += 1
                self.fs.rename(tmp, self.path)

            _common.with_retries(_truncate, label="wal truncate")
            logger.warning(
                "WAL %s: truncated %d torn tail byte(s)", self.path, torn
            )
        self._load_counters(header, records)
        return torn

    def replay(self):
        """Valid records after torn-tail recovery (establishes counters)."""
        self.recover()
        _header, records, _good, _torn = self.scan()
        return records

    def _load_counters(self, header, records):
        base = int(header.get("base_seq", 0)) if header else 0
        self._base_tells = int(header.get("base_tells", 0)) if header else 0
        self._next_seq = max(
            [base] + [int(r["seq"]) + 1 for r in records]
        )
        self._n_tells = sum(1 for r in records if r.get("kind") == "tell")

    # -- appending ---------------------------------------------------------
    def _header_body(self, base_seq, base_tells):
        return {
            "seq": -1,
            "magic": WAL_MAGIC,
            "guard": self.guard,
            "base_seq": int(base_seq),
            "base_tells": int(base_tells),
        }

    def _ensure_open(self):
        if self._f is not None:
            return
        if self._next_seq is None:
            if self.exists():
                self.recover()
            else:
                self._next_seq = 0
                self._n_tells = 0
                self._base_tells = 0
        if not self.exists():
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with self.fs.open(tmp, "w") as f:
                f.write(_encode_record(self._header_body(self._next_seq, 0)))
                self.fs.fsync(f)
                self.fsyncs += 1
            self.fs.rename(tmp, self.path)
        self._f = self.fs.open(self.path, "a")

    def append(self, kind, payload, sync=True):
        """Durably append one record; returns its sequence number.

        With ``sync=True`` (the default -- every tell) the record is on
        disk (written + fsynced) before this returns: the caller may
        apply the corresponding in-memory mutation only after -- that
        ordering IS the write-ahead contract.

        ``sync=False`` writes + flushes without the fsync barrier: the
        record is kernel-visible immediately (it survives process
        death; only a machine crash can tear it, which the torn-tail
        rule absorbs) and the NEXT synced append's fsync makes it
        durable.  Ask records ride this: a lost ask is re-derived
        bitwise from the restored rstate cursor, so asks need ordering,
        not their own disk barrier -- halving the per-trial fsync cost.

        Transient fs faults (the ESTALE/EIO class) retry through the
        PR-3 scaffold; a failed attempt's torn partial record is
        truncated away before the retry, so a mount blip can never
        manufacture the mid-file corruption the scanner refuses.
        """
        from ..distributed import _common

        _common.with_retries(self._ensure_open, label="wal open")
        seq = self._next_seq
        body = dict(payload)
        body["seq"] = seq
        body["kind"] = kind
        line = _encode_record(body)

        healed = [False]

        def attempt():
            try:
                self._ensure_open()
                self._f.write(line)
                if sync:
                    self.fs.fsync(self._f)
                    self.fsyncs += 1
                    self._unbarriered = False
                else:
                    self._f.flush()
                    self._unbarriered = True
            except OSError:
                # drop the handle and any torn partial record so the
                # retry appends onto a valid prefix
                self.close()
                try:
                    self.recover()
                    healed[0] = True
                except OSError:
                    pass
                raise

        _common.with_retries(attempt, label="wal append")
        if healed[0]:
            # a failed attempt may have landed its record anyway (fsync
            # error after a durable write): reload the counters from
            # the file truth (scan deduplicates by seq) instead of
            # double-counting in memory
            self.close()
            self.recover()
        else:
            self._next_seq = seq + 1
            if kind == "tell":
                self._n_tells += 1
        return seq

    def barrier(self):
        """Group-commit barrier: one fsync covering every flush-only
        record appended since the last fsync.  Returns True iff a sync
        was actually issued (no-op when nothing is unbarriered -- safe
        to call after :meth:`reset` absorbed the records, or twice).

        This is the other half of the ``sync=False`` idiom documented
        on :meth:`append`: a scheduler round flushes all of its tells
        per study, then one barrier per touched WAL establishes the
        same durability point N per-tell fsyncs would have.  A machine
        crash inside the flush-to-barrier window tears at most the
        unbarriered suffix, which the torn-tail rule truncates on
        replay; a process kill in the window loses nothing (flushed
        records are kernel-visible).
        """
        if not self._unbarriered:
            return False
        from ..distributed import _common

        def attempt():
            try:
                self._ensure_open()
                self.fs.fsync(self._f)
            except OSError:
                # same healing discipline as append: drop the handle so
                # the retry fsyncs a freshly opened descriptor
                self.close()
                raise

        _common.with_retries(attempt, label="wal barrier")
        self.fsyncs += 1
        self._unbarriered = False
        return True

    def close(self):
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None

    @property
    def next_seq(self):
        if self._next_seq is None:
            self.recover()
        return self._next_seq

    @property
    def total_tells(self):
        """Tells ever logged, across compactions (the zero-lost /
        zero-duplicate counter the chaos suite checks against the
        trials count)."""
        if self._next_seq is None:
            self.recover()
        return self._base_tells + self._n_tells

    # -- compaction --------------------------------------------------------
    def reset(self):
        """Compact: atomically rewrite the log as header-only, carrying
        the monotone counters forward.  Called after a checkpoint
        bundle has absorbed every record; a crash before the rename
        leaves the old log, whose records replay idempotently (tells
        are deduplicated by tid at apply time)."""
        if self._next_seq is None:
            if self.exists():
                self.recover()
            else:
                self._next_seq = 0
        self.close()
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with self.fs.open(tmp, "w") as f:
            f.write(_encode_record(
                self._header_body(self._next_seq, self.total_tells)
            ))
            self.fs.fsync(f)
            self.fsyncs += 1
        self.fs.rename(tmp, self.path)
        self._base_tells = self.total_tells
        self._n_tells = 0
        # every pre-compaction record is in the bundle; nothing left to
        # barrier
        self._unbarriered = False
