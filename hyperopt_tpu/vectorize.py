"""Batch sampling of search spaces -> sparse ``idxs/vals`` encoding.

Capability parity with the reference's ``hyperopt/vectorize.py``
(SURVEY.md SS2): ``VectorizeHelper`` turns one space into a sampler that
draws values for a *batch* of trial ids, emitting ``{label: [tids]}`` /
``{label: [values]}`` where a trial only appears under labels active on its
``hp.choice`` branch (SURVEY.md SS3.3).

Design departure from the reference (SURVEY.md SS7 stance #1): instead of
rewriting the graph into a vectorized pyll program, the host path evaluates
the space once per trial id with lazy ``switch`` (only active params are
drawn) and an observer recording labeled draws.  The *fast* batch sampler
is not here at all -- :mod:`hyperopt_tpu.ops.compile` lowers the space to a
single jitted JAX program emitting dense ``[n]`` arrays + active-masks, and
:func:`dense_to_idxs_vals` converts back to this sparse encoding at the API
boundary.
"""

from __future__ import annotations

import numpy as np

from .pyll.base import Literal, as_apply, clone, dfs, rec_eval
from .pyll.stochastic import STOCHASTIC_NAMES, ensure_rng
from .pyll_utils import expr_to_config

__all__ = [
    "VectorizeHelper",
    "pretty_names",
    "sample_config",
    "dense_to_idxs_vals",
    "idxs_vals_to_dense",
]


class VectorizeHelper:
    """Samples a batch of trials from an hp-annotated space.

    ``idxs_by_label()`` / ``vals_by_label()`` return the sparse encoding of
    the most recent batch (names kept for reference-API familiarity).
    """

    def __init__(self, expr, s_new_ids=None):
        self.expr = as_apply(expr)
        self.s_new_ids = s_new_ids
        self.hps = expr_to_config(self.expr)
        self.labels = sorted(self.hps)

        # Clone once; per-trial RNG is injected by swapping one Literal's
        # payload (avoids re-cloning the graph every draw).
        self._rng_literal = Literal(None)
        self._sampling_expr = clone(self.expr)
        for node in dfs(self._sampling_expr):
            if node.name in STOCHASTIC_NAMES:
                named = dict(node.named_args)
                if "rng" not in named:
                    node.named_args.append(("rng", self._rng_literal))
                    node.named_args.sort()
        self._last_idxs = None
        self._last_vals = None

    def sample_one(self, rng):
        """Draw one trial's config; returns {label: raw value} for the
        *active* labels only."""
        rng = ensure_rng(rng)
        self._rng_literal._obj = rng
        vals = {}

        def observer(node, value):
            if node.name == "hyperopt_param":
                label = node.pos_args[0].obj
                vals[label] = value

        rec_eval(self._sampling_expr, observer=observer)
        return vals

    def sample_batch(self, new_ids, rng):
        """Draw one config per trial id -> sparse (idxs, vals) dicts."""
        rng = ensure_rng(rng)
        idxs = {label: [] for label in self.labels}
        vals = {label: [] for label in self.labels}
        for tid in new_ids:
            config = self.sample_one(rng)
            for label, value in config.items():
                idxs[label].append(tid)
                vals[label].append(value)
        self._last_idxs, self._last_vals = idxs, vals
        return idxs, vals

    def idxs_by_label(self):
        if self._last_idxs is None:
            raise RuntimeError("no batch sampled yet")
        return self._last_idxs

    def vals_by_label(self):
        if self._last_vals is None:
            raise RuntimeError("no batch sampled yet")
        return self._last_vals


def sample_config(expr, rng):
    """One-shot convenience: {label: value} for one draw of ``expr``."""
    return VectorizeHelper(expr).sample_one(rng)


def pretty_names(expr, prefix=None):
    """{node: dotted-name} map for labeled params (diagnostic aid; parity
    with reference ``vectorize.pretty_names``)."""
    hps = expr_to_config(as_apply(expr))
    rval = {}
    for label, info in sorted(hps.items()):
        name = label if prefix is None else f"{prefix}.{label}"
        rval[info.node] = name
    return rval


# ---------------------------------------------------------------------------
# dense <-> sparse bridges (used by the JAX samplers at the API boundary)
# ---------------------------------------------------------------------------


def dense_to_idxs_vals(new_ids, labels, values, active):
    """Convert dense per-label arrays + active-mask to sparse idxs/vals.

    Args:
      new_ids: sequence of trial ids, length n.
      labels: list of D label strings.
      values: [D, n] array-like of drawn values (garbage where inactive).
      active: [D, n] boolean mask.
    """
    idxs = {}
    vals = {}
    new_ids = list(new_ids)
    values = np.asarray(values)
    active = np.asarray(active, dtype=bool)  # int masks must not fancy-index
    for d, label in enumerate(labels):
        mask = active[d]
        if mask.all():
            idxs[label] = list(new_ids)
        else:
            idxs[label] = [tid for tid, m in zip(new_ids, mask) if m]
        vals[label] = values[d][mask].tolist()
    return idxs, vals


def idxs_vals_to_dense(tids, labels, idxs, vals, fill=0.0):
    """Convert sparse idxs/vals to dense [D, n] values + active mask."""
    tid_pos = {tid: i for i, tid in enumerate(tids)}
    n = len(tids)
    D = len(labels)
    values = np.full((D, n), fill, dtype=np.float64)
    active = np.zeros((D, n), dtype=bool)
    for d, label in enumerate(labels):
        for tid, v in zip(idxs.get(label, []), vals.get(label, [])):
            if tid in tid_pos:
                i = tid_pos[tid]
                values[d, i] = v
                active[d, i] = True
    return values, active
