"""hyperopt_tpu: a TPU-native hyperparameter-optimization framework.

Capabilities of the reference (``mvanveen/hyperopt``; see SURVEY.md), built
idiomatically on JAX/XLA: the ``fmin`` driver, ``hp.*`` search-space DSL
(including conditional ``hp.choice`` spaces), a ``Trials`` store, and the
``suggest``-function plugin boundary -- plus jitted/vmapped TPE kernels
(``tpe_jax``), a compiled space sampler, an on-device ``JaxTrials`` history
and mesh-sharded candidate scoring (``hyperopt_tpu.parallel``).

Quick start::

    from hyperopt_tpu import fmin, hp, tpe_jax

    best = fmin(lambda x: (x - 3) ** 2, hp.uniform("x", -10, 10),
                algo=tpe_jax.suggest, max_evals=100)
"""

from . import (
    anneal,
    base,
    early_stop,
    exceptions,
    hp,
    mix,
    pyll,
    rand,
    tpe,
)
from .base import (
    Ctrl,
    Domain,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    JOB_STATES,
    STATUS_FAIL,
    STATUS_NEW,
    STATUS_OK,
    STATUS_RUNNING,
    STATUS_STRINGS,
    STATUS_SUSPENDED,
    Trials,
    trials_from_docs,
)
from .exceptions import (
    AllTrialsFailed,
    DuplicateLabel,
    HyperoptTpuError,
    InvalidLoss,
    InvalidResultStatus,
    InvalidTrial,
)
from .fmin import (
    FMinIter,
    fmin,
    fmin_pass_expr_memo_ctrl,
    generate_trials_to_calculate,
    partial,
    space_eval,
)
from .early_stop import no_progress_loss

__version__ = "0.1.0"

__all__ = [
    "__version__",
    "anneal",
    "anneal_jax",
    "atpe_jax",
    "device_loop",
    "base",
    "early_stop",
    "exceptions",
    "fmin",
    "FMinIter",
    "fmin_pass_expr_memo_ctrl",
    "generate_trials_to_calculate",
    "hp",
    "hyperband",
    "mix",
    "no_progress_loss",
    "partial",
    "pbt",
    "pyll",
    "rand",
    "space_eval",
    "tpe",
    "Ctrl",
    "Domain",
    "Trials",
    "trials_from_docs",
    "AllTrialsFailed",
    "DuplicateLabel",
    "HyperoptTpuError",
    "InvalidLoss",
    "InvalidResultStatus",
    "InvalidTrial",
    "JOB_STATES",
    "JOB_STATE_DONE",
    "JOB_STATE_ERROR",
    "JOB_STATE_NEW",
    "JOB_STATE_RUNNING",
    "STATUS_FAIL",
    "STATUS_NEW",
    "STATUS_OK",
    "STATUS_RUNNING",
    "STATUS_STRINGS",
    "STATUS_SUSPENDED",
]


def __getattr__(name):
    # heavier JAX-facing modules load lazily so `import hyperopt_tpu` stays
    # cheap on hosts without an accelerator
    lazy = {
        "tpe_jax",
        "rand_jax",
        "anneal_jax",
        "atpe_jax",
        "device_loop",
        "jax_trials",
        "ops",
        "parallel",
        "distributed",
        "models",
        "hyperband",
        "pbt",
        # progress/utils resolve today via eager siblings' transitive
        # imports; listing them makes the attribute a guarantee, not an
        # accident of import order
        "progress",
        "utils",
        "atpe",
        "criteria",
        "plotting",
        "graphviz",
        "vectorize",
        "pyll_utils",
    }
    if name in lazy:
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
