"""Stock early-stopping functions for ``fmin(early_stop_fn=...)``.

Capability parity with the reference's ``hyperopt/early_stop.py``
(SURVEY.md SS2): ``no_progress_loss``.
"""

from __future__ import annotations

import numpy as np

__all__ = ["no_progress_loss"]


def no_progress_loss(iteration_stop_count=20, percent_increase=0.0):
    """Stop if the best loss has not improved for ``iteration_stop_count``
    iterations (improvement must exceed ``percent_increase`` percent).
    """

    def stop_fn(trials, best_loss=None, iteration_no_progress=0):
        new_loss = trials.trials[len(trials.trials) - 1]["result"].get("loss")
        if new_loss is None:
            return False, [best_loss, iteration_no_progress + 1]
        if best_loss is None:
            return False, [new_loss, 0]
        best_loss_threshold = best_loss - abs(best_loss * (percent_increase / 100.0))
        if new_loss is not None and new_loss < best_loss_threshold:
            best_loss = new_loss
            iteration_no_progress = 0
        else:
            iteration_no_progress += 1
        return (
            iteration_no_progress >= iteration_stop_count,
            [best_loss, iteration_no_progress],
        )

    return stop_fn
