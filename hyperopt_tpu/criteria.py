"""Closed-form acquisition criteria over Gaussian predictions.

Capability parity with the reference's ``hyperopt/criteria.py``
(SURVEY.md SS2): analytic EI / logEI / UCB utility functions.  Not wired
into TPE (same as the reference); useful for GP-flavored extensions.
Implemented with scipy on host and mirrored as jnp-compatible math (the
functions accept numpy or jax arrays).
"""

from __future__ import annotations

import numpy as np

__all__ = ["EI_empirical", "EI_gaussian", "logEI_gaussian", "UCB"]


def _np_mod(x):
    try:
        import jax.numpy as jnp

        if isinstance(x, jnp.ndarray):
            return jnp
    except Exception:
        pass
    return np


def _norm_pdf(x, xp):
    return xp.exp(-0.5 * x * x) / xp.sqrt(2 * xp.pi)


def _norm_cdf(x, xp):
    if xp is np:
        from scipy.special import erf
    else:
        from jax.scipy.special import erf
    return 0.5 * (1.0 + erf(x / xp.sqrt(2.0)))


def EI_empirical(samples, thresh):
    """Expected improvement over ``thresh`` from empirical samples."""
    xp = _np_mod(samples)
    samples = xp.asarray(samples)
    return xp.maximum(samples - thresh, 0.0).mean()


def EI_gaussian(mean, var, thresh):
    """Expected improvement over ``thresh`` of N(mean, var)."""
    xp = _np_mod(mean)
    mean = xp.asarray(mean, dtype=float)
    var = xp.asarray(var, dtype=float)
    sigma = xp.sqrt(var)
    score = (mean - thresh) / sigma
    return sigma * (score * _norm_cdf(score, xp) + _norm_pdf(score, xp))


def logEI_gaussian(mean, var, thresh):
    """log(EI_gaussian), numerically stable deep into the tail.

    For score << 0 uses the asymptotic expansion
    ``EI ~ pdf(s) * sigma / s^2`` so the log stays finite where the naive
    formula underflows.
    """
    xp = _np_mod(mean)
    mean = xp.asarray(mean, dtype=float)
    var = xp.asarray(var, dtype=float)
    sigma = xp.sqrt(var)
    score = (mean - thresh) / sigma

    naive_inner = score * _norm_cdf(score, xp) + _norm_pdf(score, xp)
    naive = xp.log(xp.maximum(naive_inner, 1e-300)) + xp.log(sigma)
    # tail: log(pdf(s)/s^2 * (1 - 2/s^2)) + log(sigma)
    s2 = xp.maximum(score * score, 1e-12)
    tail = (
        -0.5 * s2
        - 0.5 * xp.log(2 * xp.pi)
        - xp.log(s2)
        + xp.log1p(xp.maximum(-2.0 / s2, -0.999))
        + xp.log(sigma)
    )
    use_tail = score < -6.0
    return xp.where(use_tail, tail, naive)


def UCB(mean, var, zscore):
    """Upper confidence bound: mean + zscore * std."""
    xp = _np_mod(mean)
    return xp.asarray(mean, dtype=float) + xp.sqrt(
        xp.asarray(var, dtype=float)
    ) * zscore
