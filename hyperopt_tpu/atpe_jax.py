"""Adaptive TPE on the TPU path.

Couples :class:`hyperopt_tpu.atpe.ATPEOptimizer`'s online decisions --
per-step TPE hyperparameters (gamma / n_EI_candidates / prior_weight)
and converged-parameter locking -- with the jitted suggest program of
:mod:`hyperopt_tpu.tpe_jax` (via its shared :func:`tpe_jax.suggest_dense`
engine). The decision layer is cheap host statistics over the trial
history (exactly :mod:`hyperopt_tpu.atpe`); the candidate sweep runs
on-device. Locked hyperparameters are overwritten in the dense draw and
conditional activity is re-derived, so locking an ``hp.choice`` arm
consistently re-routes its subtree. Lock decisions roll per suggestion,
matching the host path's ``lock_fraction`` semantics for batched calls.
"""

from __future__ import annotations

import numpy as np

from .atpe import ATPEOptimizer
from .jax_trials import obs_buffer_for, packed_space_for
from .pyll.stochastic import ensure_rng
from .rand import _domain_helper, docs_from_idxs_vals
from .vectorize import dense_to_idxs_vals

__all__ = ["suggest"]


def _optimizer_for(domain, lock_fraction, elite_count):
    from . import tpe_jax

    opt = getattr(domain, "_atpe_jax_optimizer", None)
    if (opt is None or opt.lock_fraction != lock_fraction
            or opt.elite_count != elite_count):
        # anchor the adaptive candidate count at the TPU path's default:
        # adaptation may only raise it
        opt = ATPEOptimizer(lock_fraction=lock_fraction,
                            elite_count=elite_count,
                            base_n_ei=tpe_jax._default_n_EI_candidates)
        domain._atpe_jax_optimizer = opt
    return opt


def _dense_draw(domain, trials, opt, rng, batch, n_startup_jobs,
                linear_forgetting):
    """The adaptive draw for a batch: device sweep under the optimizer's
    per-step settings, then per-column restart/lock rolls."""
    from . import tpe_jax

    ps = packed_space_for(domain)
    buf = obs_buffer_for(domain, trials)
    warm = buf.count >= n_startup_jobs

    kw = {}
    explore_fraction = 0.0
    if warm:
        kw = dict(opt.tpe_settings(domain, trials))
        # consumed here, never forwarded to the jitted engine
        explore_fraction = kw.pop("explore_fraction", 0.0)
    values, active = tpe_jax.suggest_dense(
        domain, trials, int(rng.integers(0, 2**31 - 1)), batch,
        n_startup_jobs=n_startup_jobs,
        linear_forgetting=linear_forgetting,
        **kw,
    )
    values = np.array(values)
    active = np.asarray(active)

    if warm:
        pos = {label: d for d, label in enumerate(ps.labels)}
        cands = opt.lock_candidates(domain, trials)  # invariant per call
        helper = _domain_helper(domain) if explore_fraction else None
        rerouted = False
        for j in range(batch):  # per-suggestion rolls (host-path parity)
            if explore_fraction and rng.uniform() < explore_fraction:
                # stall-triggered restart: overwrite this column with a
                # pure prior draw (host sampler, no device dispatch);
                # locking is skipped -- a restart that keeps converged
                # values is not a restart
                for label, v in helper.sample_one(rng).items():
                    values[pos[label], j] = float(v)
                rerouted = True
                continue
            if not cands or rng.uniform() > opt.lock_fraction:
                continue
            for label, v in cands.items():
                d = pos.get(label)
                if d is not None:
                    values[d, j] = float(v)
                    rerouted = True
        if rerouted:
            # restarts/locks may re-route choice subtrees: recompute
            active = np.asarray(ps.active_fn(values))
    return values, active


def suggest(
    new_ids,
    domain,
    trials,
    seed,
    n_startup_jobs=20,
    linear_forgetting=25,
    lock_fraction=0.5,
    elite_count=8,
    speculative=0,
    max_stale=None,
):
    """``algo=atpe_jax.suggest``: adaptive TPE with the device sweep.

    ``speculative=k`` serves k sequential asks from one k-wide draw
    (same cache/staleness semantics as :func:`tpe_jax.suggest`; the
    adaptive settings and lock set refresh on every redraw, matching
    the accepted ``max_queue_len=k`` staleness profile).  The
    saturated-pure-categorical auto-guard applies, judged at the
    adaptive layer's fixed categorical candidate count.
    """
    from . import tpe_jax

    rng = ensure_rng(seed)
    opt = _optimizer_for(domain, lock_fraction, elite_count)
    ps = packed_space_for(domain)
    B = len(new_ids)

    if speculative and B == 1:
        # pure-categorical saturation: same trap as tpe_jax, judged at
        # the adaptive layer's pinned categorical candidate count
        if tpe_jax._saturated_categorical(
            ps, tpe_jax._default_n_EI_candidates_cat
        ):
            tpe_jax._warn_saturated(
                domain, speculative,
                advice="the adaptive layer pins the categorical "
                "candidate count, so speculation stays off on this "
                "space; use plain tpe_jax.suggest with a lowered "
                "n_EI_candidates_cat to re-enable it.",
            )
            speculative = 0

    if speculative and B == 1:
        params = (
            "atpe", float(lock_fraction), int(elite_count),
            int(n_startup_jobs), int(linear_forgetting), id(trials),
            int(speculative),
            int(speculative) - 1 if max_stale is None else int(max_stale),
        )
        values, active = tpe_jax._speculative_cols(
            domain, trials, seed, int(speculative), max_stale, params,
            n_startup_jobs,
            lambda s, k: _dense_draw(
                domain, trials, opt, ensure_rng(s), k, n_startup_jobs,
                linear_forgetting,
            ),
        )
    else:
        values, active = _dense_draw(
            domain, trials, opt, rng, B, n_startup_jobs, linear_forgetting
        )

    idxs, vals = dense_to_idxs_vals(new_ids, ps.labels, values, active)
    idxs, vals = tpe_jax._cast_vals(ps, idxs, vals)
    return docs_from_idxs_vals(new_ids, domain, trials, idxs, vals)
