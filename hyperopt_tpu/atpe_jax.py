"""Adaptive TPE on the TPU path.

Couples :class:`hyperopt_tpu.atpe.ATPEOptimizer`'s online decisions --
per-step TPE hyperparameters (gamma / n_EI_candidates / prior_weight)
and converged-parameter locking -- with the jitted suggest program of
:mod:`hyperopt_tpu.tpe_jax` (via its shared :func:`tpe_jax.suggest_dense`
engine). The decision layer is cheap host statistics over the trial
history (exactly :mod:`hyperopt_tpu.atpe`); the candidate sweep runs
on-device. Locked hyperparameters are overwritten in the dense draw and
conditional activity is re-derived, so locking an ``hp.choice`` arm
consistently re-routes its subtree. Lock decisions roll per suggestion,
matching the host path's ``lock_fraction`` semantics for batched calls.
"""

from __future__ import annotations

import numpy as np

from .atpe import ATPEOptimizer
from .jax_trials import obs_buffer_for, packed_space_for
from .pyll.stochastic import ensure_rng
from .rand import _domain_helper, docs_from_idxs_vals
from .vectorize import dense_to_idxs_vals

__all__ = ["suggest", "build_atpe_device_fn"]

# the largest gamma the traced adaptive schedule can produce -- the
# static below-buffer pad bound handed to kernels.fit_all_dims
_MAX_ADAPTIVE_GAMMA = 0.35


def build_atpe_device_fn(ps, lf, prior_weight=1.0, elite_count=8,
                         lock_fraction=0.5, base_n_ei=None, n_cand_cat=None,
                         mesh=None, cand_axis=None, above_cap=None):
    """Compile the ADAPTIVE TPE suggest step for a PackedSpace -- the
    on-device counterpart of :class:`hyperopt_tpu.atpe.ATPEOptimizer`,
    traceable under ``device_loop.compile_fmin``'s scan (VERDICT r3
    weak #5: the adaptive settings are scalar statistics of the history
    carry, so nothing forces them onto the host).

    Returns jitted ``fn(key, values, active, losses, valid, batch) ->
    (new_values [D, B], new_active [D, B])`` with ``batch`` static.

    The host decision layer maps onto the trace as:

    * static (space-shape) decisions stay host-side at build: the
      candidate count ``n_ei = clip(base * (1 + D/20), base,
      max(256, 2*base))`` (shapes cannot be traced), the base gamma
      ``clip(0.20 + 0.01 D, 0.15, 0.35)``, and the pure-categorical
      regime (plain-TPE settings, no locking -- measured
      neutral-to-harmful there, BASELINE.md ATPE table);
    * per-step decisions become traced scalars of the carry: the
      round-3 stall detector (best-loss gain over the last
      ``min(15, n//2)`` trials <= 2% of total gain) drives
      ``prior_weight`` to the absolute value 1.5 (host parity -- NOT a
      multiple of the base) + a 25% pure-prior restart fraction when
      stalled, and sharpens ``gamma`` by 0.05 when improving;
    * parameter locking becomes a masked reduction: the elite set's
      per-dim spread (latent std vs 5% of prior width; categorical
      modal share >= 0.8) yields a lock mask + values, capped at D//2
      keeping the most-converged, applied per suggestion column with
      probability ``lock_fraction`` (restart columns skip locks), then
      conditional activity is re-derived so locked choice arms re-route
      their subtrees -- exactly the host path's semantics.

    ``above_cap`` follows :func:`tpe_jax.build_suggest_fn`'s knob (None
    = the framework default cap, 0 = full-width scoring): the adaptive
    path shares the compacted above model, so its suggest cost is also
    flat past the cap.

    ``mesh``/``cand_axis`` shard the EI candidate sweep over the mesh
    (per-device slabs + argmax-allgather via
    :func:`hyperopt_tpu.parallel.sharded.build_sharded_sweep`); the
    adapted candidate count stays the TOTAL sweep width (per-device
    counts round up).  The traced settings and lock logic are
    device-count-independent, so the sharded and unsharded programs
    differ only in the sweep's key folding.
    """
    import jax
    import jax.numpy as jnp

    from . import tpe_jax
    from .ops import kernels as K

    K.check_prior_weight(prior_weight)
    if base_n_ei is None:
        base_n_ei = tpe_jax._default_n_EI_candidates
    if n_cand_cat is None:
        n_cand_cat = tpe_jax._default_n_EI_candidates_cat
    a_cap = tpe_jax._resolve_above_cap(above_cap)
    c = ps._consts
    D = ps.n_dims
    Dc = len(ps.cont_idx)
    Dk = len(ps.cat_idx)
    pure_categorical = D > 0 and Dk == D
    lf_f = float(lf)
    pw0 = float(prior_weight)
    E = int(elite_count)
    lock_fraction = float(lock_fraction)

    # -- static (space-shape) settings, host formulas verbatim ------------
    if pure_categorical:
        base_gamma = 0.25
        n_ei = int(base_n_ei)
    else:
        base_gamma = float(np.clip(0.20 + 0.01 * D, 0.15, _MAX_ADAPTIVE_GAMMA))
        n_ei = int(np.clip(
            base_n_ei * (1 + D / 20), base_n_ei, max(256, 2 * base_n_ei)
        ))
    n_cat = max(1, int(n_cand_cat))

    # per-cont-dim latent prior width for lock convergence (bounded dims:
    # high - low; unbounded: 2 sigma -- host atpe.lock_candidates)
    if Dc:
        width_np = np.where(
            np.isfinite(ps.low) & np.isfinite(ps.high),
            ps.high - ps.low,
            2.0 * ps.prior_sigma,
        ).astype(np.float32)
    m_min = max(3, E // 2)  # min elite observations per dim to judge
    max_lock = D // 2

    sharded_sweep = None
    if cand_axis is not None:
        if mesh is None:
            raise ValueError("cand_axis requires a mesh")
        from .parallel.sharded import build_sharded_sweep, per_device_count

        n_dev_c = int(mesh.shape[cand_axis])
        sharded_sweep = build_sharded_sweep(
            ps, mesh, per_device_count(n_ei, n_dev_c), axis=cand_axis,
            n_cand_cat_per_device=per_device_count(n_cat, n_dev_c),
        )

    def settings(losses, valid):
        """Traced per-step (gamma, prior_weight, explore_fraction)."""
        ok = valid & jnp.isfinite(losses)
        n = jnp.sum(ok.astype(jnp.int32))
        best_first = jax.lax.cummin(jnp.where(ok, losses, jnp.inf))
        cnt = jnp.cumsum(ok.astype(jnp.int32))

        def at_ok(k):  # best-so-far after the k-th ok trial (1-indexed)
            slot = jnp.clip(
                jnp.searchsorted(cnt, k), 0, losses.shape[0] - 1
            )
            return best_first[slot]

        w = jnp.minimum(15, jnp.maximum(2, n // 2))
        # host parity: best_first[-w] is the best AFTER the (n-w+1)-th ok
        # trial (1-indexed), so the gain spans w-1 trials, not w
        recent_gain = at_ok(n - w + 1) - at_ok(n)
        total_gain = at_ok(jnp.int32(1)) - at_ok(n)
        judged = n >= 20
        stalled = judged & (recent_gain <= 0.02 * (total_gain + 1e-12))
        improving = judged & ~stalled
        gamma = jnp.where(
            improving, jnp.maximum(0.15, base_gamma - 0.05), base_gamma
        )
        # host parity: ATPEOptimizer sets the ABSOLUTE value 1.5 when
        # stalled (atpe.py tpe_settings), not a multiple of the base --
        # the two agree only at prior_weight=1.0
        pw = jnp.where(stalled, jnp.float32(1.5), pw0)
        explore = jnp.where(stalled, 0.25, 0.0)
        return gamma, pw, explore, ok, n

    def lock_set(values, active, losses, ok, n):
        """Traced (lock_mask [D], lock_vals [D]) over the elite set."""
        keyed = jnp.where(ok, losses, jnp.inf)
        order = jnp.argsort(keyed, stable=True)
        elite = jnp.zeros_like(ok).at[order[:E]].set(True) & ok

        scores = jnp.full((D,), -jnp.inf, dtype=jnp.float32)
        lock_vals = jnp.zeros((D,), dtype=jnp.float32)

        if Dc:
            cont_idx = c["cont_idx"]
            obs = values[cont_idx]
            lat = jnp.where(c["logspace"][:, None], _safe_log(obs), obs)
            elig = active[cont_idx] & elite[None, :]
            w = elig.astype(jnp.float32)
            m = jnp.sum(w, axis=1)
            m_safe = jnp.maximum(m, 1.0)
            mean = jnp.sum(lat * w, axis=1) / m_safe
            var = jnp.sum((lat - mean[:, None]) ** 2 * w, axis=1) / m_safe
            std = jnp.sqrt(jnp.maximum(var, 0.0))
            width = jnp.asarray(width_np)
            thr = 0.05 * width
            # masked median, matching np.median (mean of middles)
            svals = jnp.sort(jnp.where(elig, lat, jnp.inf), axis=1)
            mi = jnp.maximum(m.astype(jnp.int32) - 1, 0)
            lo = jnp.take_along_axis(svals, (mi // 2)[:, None], axis=1)[:, 0]
            hi = jnp.take_along_axis(
                svals, ((mi + 1) // 2)[:, None], axis=1
            )[:, 0]
            med_lat = 0.5 * (lo + hi)
            nat = jnp.where(c["logspace"], jnp.exp(med_lat), med_lat)
            nat = K.quantize_nat(
                nat, c["q"], c["low"], c["high"], c["logspace"]
            )
            locked = (
                (m >= m_min) & (width > 0) & (std < thr) & (n >= 20)
            )
            score = jnp.where(locked, 1.0 - std / jnp.maximum(thr, 1e-30),
                              -jnp.inf)
            scores = scores.at[cont_idx].set(score)
            lock_vals = lock_vals.at[cont_idx].set(nat)

        if Dk:
            cat_idx = c["cat_idx"]
            obs_k = values[cat_idx] - c["int_low"][:, None]
            elig = active[cat_idx] & elite[None, :]
            w = elig.astype(jnp.float32)
            m = jnp.sum(w, axis=1)
            k_max = int(ps.k_max)
            onehot = (
                obs_k[:, :, None]
                == jnp.arange(k_max, dtype=obs_k.dtype)[None, None, :]
            ).astype(jnp.float32)
            counts = jnp.sum(onehot * w[:, :, None], axis=1)  # [Dk, K]
            share = jnp.max(counts, axis=1) / jnp.maximum(m, 1.0)
            mode = jnp.argmax(counts, axis=1).astype(jnp.float32)
            locked = (m >= m_min) & (share >= 0.8) & (n >= 20)
            score = jnp.where(locked, (share - 0.8) / 0.2, -jnp.inf)
            scores = scores.at[cat_idx].set(score)
            lock_vals = lock_vals.at[cat_idx].set(
                mode + c["int_low"].astype(jnp.float32)
            )

        if max_lock == 0:  # 1-dim spaces never lock
            return jnp.zeros((D,), dtype=bool), lock_vals
        # cap at D//2, keeping the most-converged (host: sort by score)
        rank = jnp.zeros((D,), jnp.int32).at[
            jnp.argsort(-scores, stable=True)
        ].set(jnp.arange(D, dtype=jnp.int32))
        lock_mask = jnp.isfinite(scores) & (rank < max_lock)
        return lock_mask, lock_vals

    _safe_log = K._safe_log  # one latent transform everywhere

    def fn(key, values, active, losses, valid, batch):
        k_tpe, k_prior, k_roll = jax.random.split(key, 3)
        if pure_categorical:
            # HOST PARITY: pure-categorical spaces pin plain-TPE
            # settings statically -- no stall-adapted gamma or boosted
            # prior may reach the fits (the boosted prior flattens the
            # posterior that IS the exploitation mechanism there,
            # measured harmful -- BASELINE.md ATPE table)
            gamma, pw = base_gamma, pw0
        else:
            gamma, pw, explore_frac, ok, n = settings(losses, valid)
        fits = K.fit_all_dims(
            c, values, active, losses, valid, gamma, lf_f, pw,
            pad_gamma=_MAX_ADAPTIVE_GAMMA, above_cap=a_cap,
        )

        if sharded_sweep is not None:
            new_values, _ = sharded_sweep(k_tpe, fits, batch)
        else:
            new_values = jnp.zeros((D, batch), dtype=jnp.float32)
            keys = jax.random.split(k_tpe, max(batch * (Dc + Dk), 1))
            if fits["cont"] is not None:
                cont_keys = keys[: batch * Dc].reshape(batch, Dc)
                cont_vals, _ = K.ei_sweep_cont(
                    ps.q, c, cont_keys, fits["cont"], n_ei
                )
                new_values = new_values.at[c["cont_idx"]].set(cont_vals.T)
            if fits["cat"] is not None:
                pb, pa = fits["cat"]
                cat_keys = (
                    keys[batch * Dc: batch * (Dc + Dk)].reshape(batch, Dk)
                )
                cat_vals, _ = K.ei_sweep_cat(cat_keys, pb, pa, n_cat)
                new_values = new_values.at[c["cat_idx"]].set(
                    cat_vals.T + c["int_low"][:, None]
                )

        if pure_categorical:
            # plain-TPE behavior: no restarts, no locking (measured
            # neutral-to-harmful -- the posterior IS the mechanism)
            return new_values, ps.active_fn(new_values)

        # stall-triggered restarts: whole columns become pure prior
        # draws (the posterior's argmax cannot leave its basin)
        prior_vals, _ = ps.sample_prior_fn(k_prior, batch)
        k_explore, k_lock = jax.random.split(k_roll)
        explore_col = (
            jax.random.uniform(k_explore, (batch,), dtype=jnp.float32)
            < explore_frac
        )
        new_values = jnp.where(explore_col[None, :], prior_vals, new_values)

        # converged-parameter locking, rolled per suggestion column;
        # restart columns skip locks (a restart keeping converged
        # values is not a restart)
        lock_mask, lock_vals = lock_set(values, active, losses, ok, n)
        lock_col = (
            jax.random.uniform(k_lock, (batch,), dtype=jnp.float32)
            < lock_fraction
        ) & ~explore_col
        apply = lock_mask[:, None] & lock_col[None, :]
        new_values = jnp.where(apply, lock_vals[:, None], new_values)

        # locks/restarts may re-route choice subtrees: re-derive activity
        return new_values, ps.active_fn(new_values)

    return jax.jit(fn, static_argnames=("batch",))


def _optimizer_for(domain, lock_fraction, elite_count):
    from . import tpe_jax

    opt = getattr(domain, "_atpe_jax_optimizer", None)
    if (opt is None or opt.lock_fraction != lock_fraction
            or opt.elite_count != elite_count):
        # anchor the adaptive candidate count at the TPU path's default:
        # adaptation may only raise it
        opt = ATPEOptimizer(lock_fraction=lock_fraction,
                            elite_count=elite_count,
                            base_n_ei=tpe_jax._default_n_EI_candidates)
        domain._atpe_jax_optimizer = opt
    return opt


def _sharded_dense(domain, trials, seed, batch, mesh, kw, linear_forgetting):
    """Warm-path adaptive draw with the candidate sweep mesh-sharded:
    the optimizer's per-step settings feed
    :func:`parallel.sharded.build_sharded_suggest_fn` (cached per
    settings tuple -- gamma/prior-weight each take two adaptive values,
    so at most four builds per mesh)."""
    from .jax_trials import host_key
    from .parallel.mesh import CAND_AXIS
    from .parallel.sharded import per_device_count, sharded_draw

    buf = obs_buffer_for(domain, trials)
    key = host_key(int(seed) % (2**31 - 1))
    n_dev = int(mesh.shape[CAND_AXIS])
    return sharded_draw(
        domain, buf, mesh, "_atpe_sharded_cache",
        per_device_count(kw["n_EI_candidates"], n_dev),
        kw["gamma"], linear_forgetting, kw["prior_weight"],
        per_device_count(kw["n_EI_candidates_cat"], n_dev),
        key, batch, above_cap=kw.get("above_cap"),
    )


def _dense_draw(domain, trials, opt, rng, batch, n_startup_jobs,
                linear_forgetting, mesh=None):
    """The adaptive draw for a batch: device sweep under the optimizer's
    per-step settings, then per-column restart/lock rolls."""
    from . import tpe_jax

    ps = packed_space_for(domain)
    buf = obs_buffer_for(domain, trials)
    warm = buf.count >= n_startup_jobs

    kw = {}
    explore_fraction = 0.0
    if warm:
        kw = dict(opt.tpe_settings(domain, trials))
        # consumed here, never forwarded to the jitted engine
        explore_fraction = kw.pop("explore_fraction", 0.0)
    if warm and mesh is not None:
        values, active = _sharded_dense(
            domain, trials, int(rng.integers(0, 2**31 - 1)), batch, mesh,
            kw, linear_forgetting,
        )
    else:
        values, active = tpe_jax.suggest_dense(
            domain, trials, int(rng.integers(0, 2**31 - 1)), batch,
            n_startup_jobs=n_startup_jobs,
            linear_forgetting=linear_forgetting,
            **kw,
        )
    values = np.array(values)
    active = np.asarray(active)

    if warm:
        pos = {label: d for d, label in enumerate(ps.labels)}
        cands = opt.lock_candidates(domain, trials)  # invariant per call
        helper = _domain_helper(domain) if explore_fraction else None
        rerouted = False
        for j in range(batch):  # per-suggestion rolls (host-path parity)
            if explore_fraction and rng.uniform() < explore_fraction:
                # stall-triggered restart: overwrite this column with a
                # pure prior draw (host sampler, no device dispatch);
                # locking is skipped -- a restart that keeps converged
                # values is not a restart
                for label, v in helper.sample_one(rng).items():
                    values[pos[label], j] = float(v)
                rerouted = True
                continue
            if not cands or rng.uniform() > opt.lock_fraction:
                continue
            for label, v in cands.items():
                d = pos.get(label)
                if d is not None:
                    values[d, j] = float(v)
                    rerouted = True
        if rerouted:
            # restarts/locks may re-route choice subtrees: recompute
            active = np.asarray(ps.active_fn(values))
    return values, active


def suggest(
    new_ids,
    domain,
    trials,
    seed,
    n_startup_jobs=20,
    linear_forgetting=25,
    lock_fraction=0.5,
    elite_count=8,
    speculative=0,
    max_stale=None,
    mesh=None,
    resident=None,
):
    """``algo=atpe_jax.suggest``: adaptive TPE with the device sweep.

    ``speculative=k`` serves k sequential asks from one k-wide draw
    (same cache/staleness semantics as :func:`tpe_jax.suggest`; the
    adaptive settings and lock set refresh on every redraw, matching
    the accepted ``max_queue_len=k`` staleness profile).  The
    saturated-pure-categorical auto-guard applies, judged at the
    adaptive layer's fixed categorical candidate count.

    ``mesh`` shards the warm-path candidate sweep over every device of
    the mesh's ``cand`` axis (the adaptive candidate count becomes the
    TOTAL across devices), like
    :func:`hyperopt_tpu.parallel.sharded.sharded_suggest` for plain TPE.

    ``resident=True`` flips the observation mirror to device-resident
    mode: the adaptive layer's device sweep runs through
    ``tpe_jax.suggest_dense``, so its warm draws inherit the O(D)
    delta-tell / fused-dispatch state engine unchanged (the host-side
    restart/lock rolls are posterior-independent and unaffected).

    COMPATIBILITY STATUS (round 20, graftclient): under
    ``fmin(engine=True)`` / ``ask_ahead=k`` this adaptive driver is
    served as a per-study ``host_algo`` hook inside the serve
    engine's rounds (the host decision layer cannot vmap across
    studies; the hook runs :func:`_dense_draw` verbatim, so the
    stream is bitwise this solo path's) -- with the serve tier's
    admission control, WAL durability, and tracing on top.
    """
    from . import tpe_jax

    rng = ensure_rng(seed)
    opt = _optimizer_for(domain, lock_fraction, elite_count)
    ps = packed_space_for(domain)
    if resident is not None:
        obs_buffer_for(domain, trials, resident=bool(resident))
    B = len(new_ids)

    if speculative and B == 1:
        # pure-categorical saturation: same trap as tpe_jax, judged at
        # the adaptive layer's pinned categorical candidate count
        if tpe_jax._saturated_categorical(
            ps, tpe_jax._default_n_EI_candidates_cat
        ):
            tpe_jax._warn_saturated(
                domain, speculative,
                advice="the adaptive layer pins the categorical "
                "candidate count, so speculation stays off on this "
                "space; use plain tpe_jax.suggest with a lowered "
                "n_EI_candidates_cat to re-enable it.",
            )
            speculative = 0

    if speculative and B == 1:
        params = (
            "atpe", float(lock_fraction), int(elite_count),
            int(n_startup_jobs), int(linear_forgetting), id(trials),
            int(speculative),
            int(speculative) - 1 if max_stale is None else int(max_stale),
            0 if mesh is None else id(mesh),
        )
        values, active = tpe_jax._speculative_cols(
            domain, trials, seed, int(speculative), max_stale, params,
            n_startup_jobs,
            lambda s, k: _dense_draw(
                domain, trials, opt, ensure_rng(s), k, n_startup_jobs,
                linear_forgetting, mesh=mesh,
            ),
        )
    else:
        values, active = _dense_draw(
            domain, trials, opt, rng, B, n_startup_jobs, linear_forgetting,
            mesh=mesh,
        )

    idxs, vals = dense_to_idxs_vals(new_ids, ps.labels, values, active)
    idxs, vals = tpe_jax._cast_vals(ps, idxs, vals)
    return docs_from_idxs_vals(new_ids, domain, trials, idxs, vals)


# ---------------------------------------------------------------------------
# graftir registrations (hyperopt-tpu-lint --ir)
# ---------------------------------------------------------------------------

from .ops.compile import ProgramCapture, register_program  # noqa: E402


@register_program(
    "atpe_jax.device_step",
    families=("hyperopt_tpu.atpe_jax:build_atpe_device_fn",),
)
def _registry_atpe_device(p):
    """The adaptive on-device suggest step (traced settings + locking),
    the ``algo='atpe'`` body of ``device_loop.compile_fmin``'s scan."""
    _ = p.space._consts
    fn = build_atpe_device_fn(p.space, 25.0)
    return ProgramCapture(
        fn=fn, args=(p.key_spec(),) + p.history_specs(),
        kwargs={"batch": p.batch},
    )
