"""graftstorm: seeded, deterministic NETWORK fault injection.

The socket twin of :mod:`.faults`: where ``FaultPlan`` corrupts the
filesystem seam, ``NetFaultPlan`` corrupts the wire.  It is injected at
every connection-creation point (client transport, router backend
conns, probes, the negotiating server fronts) as a file-object wrapper
around the socket's ``makefile`` handle, and injects the failure modes
that dominate a multi-host fleet in production:

* **reset** -- the peer resets the connection mid-frame: a write puts
  only a prefix on the wire, then ``ConnectionResetError``; a read
  raises it immediately (RST while blocked in ``recv``).
* **latency** -- bounded read/write delay (capped at 50 ms, the chaos
  suites' no-real-sleeps budget).
* **truncate-then-close** -- a prefix of the frame reaches the peer and
  the socket is then hard-closed: the reader sees a torn frame
  (``FrameError`` mid-read), the writer ``BrokenPipeError``.
* **black-hole partition** -- connected but silent: writes are
  swallowed, reads time out.  Keyed and healable at runtime
  (:meth:`NetFaultPlan.partition` / :meth:`NetFaultPlan.heal`) so a
  *partitioned-but-alive* replica is a first-class chaos shape,
  distinct from ``die()``.
* **slow-loris** -- byte-at-a-time writes with per-byte delays for the
  frame prefix, modeling the classic slow client that starves an
  unbounded accept loop.

Determinism: fault schedules are a pure function of ``(seed, conn key,
conn ordinal, that connection's own op sequence)``.  Each wrapped
connection draws from its own crc32-derived RNG stream, so decisions
do not depend on how threads interleave *across* connections -- the
same property ``FaultPlan.split`` gives simulated workers.  Every
decision lands in ``plan.log`` for trace-equality assertions and in
``plan.stats`` for live counters.

Fault streaks are burst-bounded per (op, connection) exactly like
``FaultPlan``: a retry loop of ``burst + 1`` attempts always converges.

``NET_CRASH_POINTS`` bracket the client's send/ack window -- the two
instants where a lost ack forces the exactly-once resubmission
machinery (rid correlation + WAL tid-dedup) to prove itself:

``net_client_after_send_before_reply``
    the request bytes are on the wire but no reply arrived: a
    restarted client must resubmit (asks with ``recover=True``, tells
    with explicit ``vals``) and the service must dedup.

``net_client_after_reply_before_deliver``
    the reply bytes arrived but the client died before acting on them:
    the ack is lost *after* the service committed -- resubmission must
    be absorbed exactly once (WAL tid-dedup), never double-applied.

Imports are lazy both ways: :mod:`.faults` imports this module to
re-export the plan and extend ``ALL_CRASH_POINTS``; this module pulls
``SimulatedCrash``/``ALL_CRASH_POINTS`` from :mod:`.faults` only
inside methods.
"""

import logging
import random
import socket
import threading
import time
import zlib

import collections

logger = logging.getLogger(__name__)

__all__ = [
    "NET_CRASH_POINTS", "NetFaultPlan", "FaultyWire",
]

#: crash points bracketing the client's send/ack window (see module
#: docstring) -- merged into ``faults.ALL_CRASH_POINTS`` so the chaos
#: suites' registration pin covers them.
NET_CRASH_POINTS = (
    "net_client_after_send_before_reply",
    "net_client_after_reply_before_deliver",
)

#: injected latency is capped here (matches ``FaultPlan``): chaos
#: suites must not acquire real multi-second sleeps.
_LATENCY_CAP = 0.05

#: slow-loris shape: this many leading bytes of each write go out
#: one at a time with a per-byte delay; the remainder is written
#: normally so the total injected stall stays inside the cap.
_LORIS_PREFIX = 24
_LORIS_BYTE_DELAY = 0.002


class NetFaultPlan:
    """A seeded, deterministic schedule of network faults.

    One plan = one family of per-connection RNG streams: with a fixed
    seed and a fixed per-connection op sequence, the injected faults
    are identical run to run regardless of thread interleaving across
    connections.

    Parameters:
      seed:          RNG seed (determinism anchor).
      reset_rate:    probability a read/write dies with
                     ``ConnectionResetError`` (writes put a prefix on
                     the wire first -- the mid-frame case).
      latency:       max injected delay per socket op, seconds (capped
                     at 50 ms).
      truncate_rate: probability a write sends only a prefix and then
                     hard-closes the socket (torn frame on the peer).
      burst:         max *consecutive* injected faults per (op, conn);
                     bounds the adversary so ``burst + 1`` retries
                     always converge.  ``None`` = unbounded.
    """

    def __init__(self, seed=0, reset_rate=0.0, latency=0.0,
                 truncate_rate=0.0, burst=2):
        self.seed = seed
        self.reset_rate = float(reset_rate)
        self.latency = min(float(latency), _LATENCY_CAP)
        self.truncate_rate = float(truncate_rate)
        self.burst = burst
        self._lock = threading.RLock()
        self._ordinals = {}        # key -> next conn ordinal
        self._partitioned = set()  # keys currently black-holed
        self._loris = set()        # keys writing byte-at-a-time
        self._crash = {}
        self.log = []
        self.stats = collections.Counter()

    # -- derivation --------------------------------------------------------
    def split(self, name):
        """A derived plan with the same fault profile and a stably
        derived seed (crc32, not ``hash()`` -- PYTHONHASHSEED must not
        leak into the schedule).  Crash points and partition/loris
        marks are NOT inherited."""
        child_seed = zlib.crc32(f"{self.seed}/{name}".encode())
        return NetFaultPlan(
            seed=child_seed, reset_rate=self.reset_rate,
            latency=self.latency, truncate_rate=self.truncate_rate,
            burst=self.burst,
        )

    # -- chaos shapes ------------------------------------------------------
    def partition(self, key):
        """Black-hole every connection under ``key`` from now on:
        connected but silent (writes swallowed, reads time out).  The
        partitioned-but-alive shape -- the process keeps running and
        is fenced by claim epochs, not failover-killed."""
        with self._lock:
            self._partitioned.add(key)
            self.log.append(("partition", key, "on"))
            self.stats["net:partition"] += 1
        return self

    def heal(self, key=None):
        """Lift the partition for ``key`` (or all keys): live
        connections resume passing bytes on their next op -- no
        reconnect required, exactly like a switch port coming back."""
        with self._lock:
            healed = [key] if key is not None else sorted(self._partitioned)
            if key is None:
                self._partitioned.clear()
            else:
                self._partitioned.discard(key)
            for k in healed:
                self.log.append(("partition", k, "healed"))
        return self

    def is_partitioned(self, key):
        with self._lock:
            return key in self._partitioned

    def slow_loris(self, key):
        """Mark ``key``'s connections as slow-loris writers: the first
        bytes of every write trickle out one at a time."""
        with self._lock:
            self._loris.add(key)
            self.log.append(("slow_loris", key, "on"))
        return self

    def is_loris(self, key):
        with self._lock:
            return key in self._loris

    # -- crash points ------------------------------------------------------
    def arm(self, point, at=1):
        """Arm a one-shot crash at the ``at``-th hit of ``point``."""
        from .faults import ALL_CRASH_POINTS
        if point not in ALL_CRASH_POINTS:
            raise ValueError(f"unknown crash point {point!r}")
        with self._lock:
            self._crash[point] = int(at)
        return self

    def fire_crashpoint(self, name):
        from .faults import SimulatedCrash
        with self._lock:
            if name not in self._crash:
                return
            self._crash[name] -= 1
            if self._crash[name] > 0:
                return
            del self._crash[name]
            self.log.append(("crash", name, "fired"))
            self.stats[f"crash:{name}"] += 1
        raise SimulatedCrash(name)

    # -- wrapping ----------------------------------------------------------
    def _conn_state(self, key):
        with self._lock:
            ordinal = self._ordinals.get(key, 0)
            self._ordinals[key] = ordinal + 1
        conn_seed = zlib.crc32(f"{self.seed}/{key}/{ordinal}".encode())
        return _ConnState(self, key, ordinal, random.Random(conn_seed))

    def wrap(self, f, sock=None, key=None):
        """Wrap one ``makefile('rwb')`` handle in the fault seam."""
        return FaultyWire(f, self._conn_state(key or "conn"), sock=sock)

    def wrap_pair(self, rfile, wfile, sock=None, key=None):
        """Wrap a server handler's (rfile, wfile) pair: one connection
        ordinal, one RNG stream shared by both directions -- the fault
        schedule stays a function of the connection's op sequence."""
        st = self._conn_state(key or "conn")
        return FaultyWire(rfile, st, sock=sock), FaultyWire(wfile, st, sock=sock)

    # -- decision engine (called by FaultyWire) ----------------------------
    def _decide(self, st, op):
        """One burst-bounded draw on ``st``'s own RNG stream: ``None``
        or the fault to inject (``"reset"``; writes may also draw
        ``"truncate"``).  A single streak key per (op, conn) keeps the
        ``burst + 1``-retries-converge guarantee even with both rates
        set."""
        with self._lock:
            trunc = self.truncate_rate if op == "write" else 0.0
            total = self.reset_rate + trunc
            if not total:
                return None
            streak = st.streaks.get(op, 0)
            allowed = self.burst is None or streak < self.burst
            r = st.rng.random()
            if allowed and r < total:
                st.streaks[op] = streak + 1
                fault = "reset" if r < self.reset_rate else "truncate"
                self.log.append((op, st.tag, fault))
                self.stats[f"net:{fault}"] += 1
                return fault
            st.streaks[op] = 0
            self.log.append((op, st.tag, "ok"))
            return None

    def _decide_latency(self, st):
        if not self.latency:
            return 0.0
        with self._lock:
            return st.rng.uniform(0.0, self.latency)


class _ConnState:
    """Per-connection fault state: own RNG stream, own burst streaks,
    shared (under the plan lock) by both directions of a server pair."""

    __slots__ = ("plan", "key", "ordinal", "rng", "streaks", "tag")

    def __init__(self, plan, key, ordinal, rng):
        self.plan = plan
        self.key = key
        self.ordinal = ordinal
        self.rng = rng
        self.streaks = {}
        self.tag = f"{key}#{ordinal}"


class FaultyWire:
    """File-object proxy that injects the plan's network faults.

    Wraps a socket ``makefile`` handle (or a handler's rfile/wfile):
    reads and writes consult the plan first, then delegate.  Unknown
    attributes pass through, so it is drop-in wherever the raw handle
    was (``FrameConn``, ``StreamRequestHandler``).
    """

    def __init__(self, f, state, sock=None):
        self._f = f
        self._st = state
        self._sock = sock
        self._plan = state.plan

    # -- read side ---------------------------------------------------------
    def _pre_read(self):
        plan, st = self._plan, self._st
        if plan.is_partitioned(st.key):
            # connected but silent: block for the latency budget, then
            # miss the deadline the way a real black hole does
            time.sleep(plan.latency or 0.01)
            plan.stats["net:blackhole_read"] += 1
            raise socket.timeout(f"black hole: {st.tag}")
        if plan._decide(st, "read") == "reset":
            raise ConnectionResetError(f"injected reset (read): {st.tag}")
        lat = plan._decide_latency(st)
        if lat:
            time.sleep(lat)

    def read(self, n=-1):
        self._pre_read()
        data = self._f.read(n)
        if data:
            self._plan.fire_crashpoint("net_client_after_reply_before_deliver")
        return data

    def readline(self, limit=-1):
        self._pre_read()
        data = self._f.readline(limit)
        if data:
            self._plan.fire_crashpoint("net_client_after_reply_before_deliver")
        return data

    # -- write side --------------------------------------------------------
    def write(self, b):
        plan, st = self._plan, self._st
        if plan.is_partitioned(st.key):
            # swallowed by the black hole: locally "successful"
            plan.stats["net:blackhole_write"] += 1
            return len(b)
        fault = plan._decide(st, "write")
        if fault == "reset":
            self._tear(b)
            raise ConnectionResetError(f"injected reset (write): {st.tag}")
        if fault == "truncate":
            self._tear(b)
            self._hard_close()
            raise BrokenPipeError(f"injected truncate-then-close: {st.tag}")
        lat = plan._decide_latency(st)
        if lat:
            time.sleep(lat)
        if plan.is_loris(st.key) and len(b) > 1:
            head = b[:_LORIS_PREFIX]
            for i in range(len(head)):
                self._f.write(head[i:i + 1])
                self._f.flush()
                time.sleep(_LORIS_BYTE_DELAY)
            self._f.write(b[_LORIS_PREFIX:])
            return len(b)
        return self._f.write(b)

    def _tear(self, b):
        """Put a prefix on the wire before dying: the mid-frame case
        (the peer's ``_read_exact`` sees a torn frame, not clean EOF)."""
        st = self._st
        cut = st.rng.randrange(0, max(len(b), 1))
        if cut:
            try:
                self._f.write(b[:cut])
                self._f.flush()
            except OSError:
                pass

    def _hard_close(self):
        try:
            if self._sock is not None:
                self._sock.close()
            self._f.close()
        except OSError:
            pass

    def flush(self):
        if self._plan.is_partitioned(self._st.key):
            return
        self._f.flush()
        self._plan.fire_crashpoint("net_client_after_send_before_reply")

    # -- passthrough -------------------------------------------------------
    def close(self):
        self._f.close()

    @property
    def closed(self):
        return self._f.closed

    def __getattr__(self, name):
        return getattr(self._f, name)
