"""``hyperopt-tpu-worker``: evaluate queued trials from a FileJobQueue.

The worker-process role of the reference's ``hyperopt-mongo-worker`` CLI
(SURVEY.md SS3.4): reserve (atomic CAS) -> unpickle the shipped Domain ->
evaluate -> publish DONE/ERROR, in a loop, with reserve-timeout reaping,
an idle exit, optional workdir isolation and a max-jobs budget.

Hardening (FAILURES.md has the full recovery matrix):

* transient mount blips (ESTALE/EIO class) in reserve/heartbeat/
  complete/reap are retried by the shared scaffold
  (``_common.with_retries``); persistent ones back the loop off instead
  of crashing it;
* a crash-loop guard exits loudly (rc 2) after ``--max-crash-loop``
  consecutive unexpected errors, so a supervisor restart-loop on a
  poisoned environment cannot silently spin forever;
* SIGTERM drains gracefully: the in-flight job finishes (or is given
  back), then the worker exits 0;
* lost claims are detected at completion time: a job reaped (and
  possibly re-run) while this worker evaluated it is dropped with a
  warning, never published as a duplicate DONE doc.

Usage::

    python -m hyperopt_tpu.distributed.worker --dir /shared/exp1 \
        [--exp-key K] [--max-jobs N] [--poll-interval S] \
        [--reserve-timeout S] [--last-job-timeout S] [--workdir D] \
        [--max-crash-loop N]
"""

from __future__ import annotations

import argparse
import collections
import logging
import os
import pickle
import signal
import sys
import time
import traceback

from ..base import (
    Ctrl,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    SONify,
    spec_from_misc,
)
from ..utils import working_dir
from . import _common
from .filequeue import FileJobQueue, FileTrials, worker_owner

logger = logging.getLogger(__name__)

__all__ = ["main", "run_one", "WorkerExit", "GracefulDrain"]


class WorkerExit(Exception):
    pass


class GracefulDrain:
    """SIGTERM -> finish (or give back) the in-flight job, then exit 0.

    The handler only flips a flag: evaluation is never interrupted
    mid-flight, so a drained worker leaves either a published result or
    an intact claim for the reaper -- never a half-written doc.
    ``install()`` is a no-op outside the main thread (signal.signal
    would raise), which keeps in-process/threaded harnesses working.
    """

    def __init__(self):
        self.requested = False

    def _handle(self, signum, frame):
        self.requested = True
        logger.info("SIGTERM received: draining (finishing in-flight job)")

    def install(self):
        try:
            signal.signal(signal.SIGTERM, self._handle)
        except ValueError:  # not the main thread
            pass
        return self


def _load_domain(queue, blob_key="FMinIter_Domain",
                 cache=collections.OrderedDict()):
    """``blob_key`` comes from the job doc's cmd (the reference's
    contract): drivers with different objectives (an fmin and an
    asha_filequeue, say) share one queue directory, each doc naming the
    Domain to evaluate with."""
    if blob_key not in queue.attachments:
        raise WorkerExit(
            f"no pickled Domain {blob_key!r} at {queue.root}/attachments -- "
            "is a driver running against this queue?"
        )
    # cache keyed by the attachment file's identity, not forever: a new
    # driver reusing the directory (e.g. asha_filequeue after an fmin
    # run) RE-publishes the Domain, and a long-lived worker must pick
    # the new objective up rather than silently evaluating the stale
    # one.  Every publish is tmp+rename = a NEW inode, so st_ino moves
    # even on mounts with coarse timestamps where two publishes can
    # land inside one mtime tick; mtime+size ride along as backstops.
    path = queue.attachments._path(blob_key)
    try:
        st = _common.with_retries(
            lambda: queue.fs.stat(path), label="domain stat"
        )
    except FileNotFoundError:  # raced a re-publish; next loop retries
        raise WorkerExit(f"domain attachment vanished under {queue.root}")
    ident = (st.st_ino, st.st_mtime_ns, st.st_size)
    return _common.lru_get(
        cache, (queue.root, blob_key), ident,
        lambda: pickle.loads(queue.attachments[blob_key]),
    )


class _ClaimBeat:
    """The heartbeat callable for a filequeue claim: refresh the
    running-file's mtime each tick; stop (return False) and remember
    the loss once the claim is gone (completed/reaped underneath us).
    Transient mount blips (ESTALE/EIO class) are retried by the shared
    scaffold here; if they persist the tick raises, and
    ``claim_heartbeat`` logs it and keeps beating."""

    def __init__(self, path, fs):
        self.path = path
        self.fs = fs
        self.lost = False

    def __call__(self):
        try:
            _common.with_retries(
                lambda: self.fs.utime(self.path), label="claim heartbeat"
            )
            return True
        except FileNotFoundError:
            self.lost = True
            return False


def run_one(queue, owner, exp_key=None, workdir=None, trials=None,
            heartbeat=None, exclude_tids=()):
    """Reserve and evaluate a single job; False if the queue was empty.

    ``heartbeat`` (seconds) keeps the reserved job's claim fresh during
    evaluation -- the worker CLI passes ``reserve_timeout / 3``.  None
    disables it (unit-test mode / instant objectives).  ``exclude_tids``
    skips jobs this worker already failed to load a Domain for (the CLI
    maintains the cooldown set).
    """
    doc = queue.reserve(owner, exp_key=exp_key, exclude_tids=exclude_tids)
    if doc is None:
        return False
    blob_key = _common.blob_key_from_doc(doc)
    try:
        domain = _load_domain(queue, blob_key)
    except Exception as e:
        # give the job back (the reap transition) and surface the
        # error: a worker that cannot load the Domain must neither
        # strand the reserved job in running/ nor mark it failed --
        # another worker (or this one, once the attachment appears)
        # can still evaluate it.  The tid rides the exception so the
        # CLI loop can cool the job down instead of re-reserving it
        queue.unreserve(doc)  # the queue owns the RUNNING->NEW machine
        e.failed_tid = doc.get("tid")
        raise
    if trials is None:
        trials = FileTrials(queue.root, exp_key=exp_key, refresh=False)
    ctrl = Ctrl(trials, current_trial=doc)
    # Ctrl.checkpoint asserts membership of the live store
    trials._dynamic_trials.append(doc)
    spec = spec_from_misc(doc["misc"])
    running_path = os.path.join(queue.root, "running", f"{doc['tid']}.json")
    beat = _ClaimBeat(running_path, queue.fs)
    with _common.claim_heartbeat(beat, heartbeat):
        try:
            if workdir:
                with working_dir(os.path.join(workdir, str(doc["tid"]))):
                    result = domain.evaluate(spec, ctrl)
            else:
                result = domain.evaluate(spec, ctrl)
        except Exception as e:  # graftlint: disable=GL302 objective errors become ERROR docs
            logger.error("job %s failed: %s", doc["tid"], e)
            doc["state"] = JOB_STATE_ERROR
            doc["misc"]["error"] = (str(type(e)), str(e))
            doc["misc"]["traceback"] = traceback.format_exc()
        else:
            doc["state"] = JOB_STATE_DONE
            doc["result"] = SONify(result)
    queue.fs.crashpoint("before_complete")
    # completion-time lost-claim detection: claim_is_live (inside
    # complete) re-reads the running file and compares claim tokens --
    # the authoritative check; beat.lost is only the early-stop hint
    # that let the heartbeat thread exit cleanly
    if not queue.complete(doc, require_claim=True):
        # the claim was reaped mid-evaluation (heartbeat lost / running
        # file re-owned): the job is already back in new/ or re-running
        # elsewhere -- publishing now would race the re-run into a
        # duplicate DONE doc, so drop this result and move on
        logger.warning(
            "job %s: claim lost mid-evaluation (reaped); dropping result "
            "to defer to the re-run", doc.get("tid"),
        )
    return True


def main_worker_helper(options, drain=None):
    # options.fs (optional) injects the filesystem seam -- the chaos
    # harness drives the REAL CLI loop under a FaultPlan this way
    fs = getattr(options, "fs", None)
    queue = FileJobQueue(options.dir, fs=fs)
    owner = worker_owner()
    n_done = 0
    idle_since = time.time()
    drain = (drain or GracefulDrain()).install()
    # jobs whose Domain failed to load are skipped on cooldown so one
    # dangling-attachment job cannot monopolize the sorted reserve scan
    # (other jobs and other drivers keep being served; the TTL retries
    # eventually in case the failure was transient)
    bad_tids = _common.TTLSet()
    # crash-loop guard: consecutive unexpected errors (not per-job
    # Domain failures) back off, then exit LOUDLY -- a worker under a
    # process supervisor must not silently restart-spin on a poisoned
    # environment, and a transient mount outage that outlives the
    # per-op retries should cost backoff, not the process
    consecutive_errors = 0
    max_crash_loop = getattr(options, "max_crash_loop", 5)
    trials = FileTrials(
        options.dir, exp_key=options.exp_key, refresh=False,
        reserve_timeout=options.reserve_timeout, fs=fs,
    )
    logger.info("worker %s serving %s", owner, queue.root)
    while options.max_jobs is None or n_done < options.max_jobs:
        if drain.requested:
            logger.info("drained after %d job(s), exiting 0", n_done)
            return 0
        # backoff decisions are made IN the handler, the sleep happens
        # at loop level on the shared with_retries schedule
        # (_common.retry_delay) -- one backoff curve for the whole
        # fault domain, no hand-rolled sleep-in-except retry (GL303)
        backoff = None
        try:
            queue.reap(options.reserve_timeout)
            ran = run_one(
                queue, owner, exp_key=options.exp_key,
                workdir=options.workdir, trials=trials,
                heartbeat=(
                    options.reserve_timeout / 3.0
                    if options.reserve_timeout else None
                ),
                exclude_tids=bad_tids.current(),
            )
        except Exception as e:
            # ANY Domain-load failure carries the job's tid (run_one
            # gave the job back) -- WorkerExit for a missing
            # attachment, but also UnpicklingError/ImportError from
            # version skew: all cool the tid down instead of crashing
            # the worker into a supervisor restart loop on the same
            # lowest-tid job.  A misconfigured queue (jobs but never a
            # Domain) thus drains into the cooldown set, run_one starts
            # returning False, and the normal idle path applies the
            # last_job_timeout give-up
            tid = getattr(e, "failed_tid", None)
            if tid is not None:
                logger.error("job %s returned to queue: %s", tid, e)
                bad_tids.add(tid)
                consecutive_errors = 0  # per-job failure, not a crash loop
                backoff = options.poll_interval
            else:
                consecutive_errors += 1
                if consecutive_errors >= max_crash_loop:
                    logger.critical(
                        "%d consecutive unexpected errors (last: %s); "
                        "exiting loudly", consecutive_errors, e,
                        exc_info=True,
                    )
                    return 2
                level = (
                    logging.WARNING if _common.is_transient(e)
                    else logging.ERROR
                )
                logger.log(
                    level, "unexpected worker error (%d/%d): %s",
                    consecutive_errors, max_crash_loop, e, exc_info=True,
                )
                backoff = _common.retry_delay(
                    consecutive_errors,
                    base_delay=options.poll_interval, max_delay=2.0,
                )
        if backoff is not None:
            time.sleep(backoff)
            continue
        consecutive_errors = 0
        if ran:
            n_done += 1
            idle_since = time.time()
        else:
            if (
                options.last_job_timeout is not None
                and time.time() - idle_since > options.last_job_timeout  # graftlint: disable=GL307 idle-timeout protocol arithmetic (exit decision), not a metric accumulation
            ):
                logger.info("idle for %.0fs, exiting", options.last_job_timeout)
                break
            time.sleep(options.poll_interval)
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(prog="hyperopt-tpu-worker")
    parser.add_argument("--dir", required=True, help="FileJobQueue directory")
    parser.add_argument("--exp-key", default=None)
    parser.add_argument("--max-jobs", type=int, default=None)
    parser.add_argument("--poll-interval", type=float, default=0.2)
    parser.add_argument("--reserve-timeout", type=float, default=120.0)
    parser.add_argument(
        "--last-job-timeout", type=float, default=None,
        help="exit after this many seconds without work",
    )
    parser.add_argument("--workdir", default=None)
    parser.add_argument(
        "--max-crash-loop", type=int, default=5,
        help="consecutive unexpected errors before a loud exit (rc 2)",
    )
    parser.add_argument("-v", "--verbose", action="count", default=0)
    options = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if options.verbose else logging.INFO,
        stream=sys.stderr,
    )
    return main_worker_helper(options)


if __name__ == "__main__":
    sys.exit(main())
