"""``hyperopt-tpu-worker``: evaluate queued trials from a FileJobQueue.

The worker-process role of the reference's ``hyperopt-mongo-worker`` CLI
(SURVEY.md SS3.4): reserve (atomic CAS) -> unpickle the shipped Domain ->
evaluate -> publish DONE/ERROR, in a loop, with reserve-timeout reaping,
an idle exit, optional workdir isolation and a max-jobs budget.

Usage::

    python -m hyperopt_tpu.distributed.worker --dir /shared/exp1 \
        [--exp-key K] [--max-jobs N] [--poll-interval S] \
        [--reserve-timeout S] [--last-job-timeout S] [--workdir D]
"""

from __future__ import annotations

import argparse
import logging
import os
import pickle
import sys
import time
import traceback

from ..base import Ctrl, JOB_STATE_DONE, JOB_STATE_ERROR, SONify, spec_from_misc
from ..utils import working_dir
from .filequeue import FileJobQueue, FileTrials, worker_owner

logger = logging.getLogger(__name__)

__all__ = ["main", "run_one", "WorkerExit"]


class WorkerExit(Exception):
    pass


def _load_domain(queue, cache={}):
    blob_key = "FMinIter_Domain"
    if blob_key not in queue.attachments:
        raise WorkerExit(
            f"no pickled Domain at {queue.root}/attachments -- is fmin running "
            "against this queue with an async FileTrials?"
        )
    # cache keyed by the attachment file's identity, not forever: a new
    # driver reusing the directory (e.g. asha_filequeue after an fmin
    # run) RE-publishes the Domain, and a long-lived worker must pick
    # the new objective up rather than silently evaluating the stale
    # one.  Every publish is tmp+rename = a NEW inode, so st_ino moves
    # even on mounts with coarse timestamps where two publishes can
    # land inside one mtime tick; mtime+size ride along as backstops.
    path = queue.attachments._path(blob_key)
    try:
        st = os.stat(path)
    except FileNotFoundError:  # raced a re-publish; next loop retries
        raise WorkerExit(f"domain attachment vanished under {queue.root}")
    ident = (st.st_ino, st.st_mtime_ns, st.st_size)
    hit = cache.get(queue.root)
    if hit is not None and hit[0] == ident:
        return hit[1]
    domain = pickle.loads(queue.attachments[blob_key])
    cache[queue.root] = (ident, domain)
    return domain


def _heartbeat(path, interval, stop):
    """Refresh a running-file's mtime until ``stop`` is set: the claim
    stays visibly alive through evaluations LONGER than the reserve
    timeout, so reapers only recycle jobs whose worker actually died
    (an untouched claim means a crashed/wedged process, not a long
    objective)."""
    while not stop.wait(interval):
        try:
            os.utime(path)
        except FileNotFoundError:  # completed/reaped underneath us
            return
        except OSError as e:  # transient mount blip (ESTALE/EIO class):
            # keep beating -- permanently exiting would freeze the
            # mtime and get a LIVE job reaped and duplicated
            logger.warning("heartbeat on %s failed transiently: %s", path, e)


def run_one(queue, owner, exp_key=None, workdir=None, trials=None,
            heartbeat=None):
    """Reserve and evaluate a single job; False if the queue was empty.

    ``heartbeat`` (seconds) keeps the reserved job's claim fresh during
    evaluation -- the worker CLI passes ``reserve_timeout / 3``.  None
    disables it (unit-test mode / instant objectives).
    """
    import threading

    doc = queue.reserve(owner, exp_key=exp_key)
    if doc is None:
        return False
    domain = _load_domain(queue)
    if trials is None:
        trials = FileTrials(queue.root, exp_key=exp_key, refresh=False)
    ctrl = Ctrl(trials, current_trial=doc)
    # Ctrl.checkpoint asserts membership of the live store
    trials._dynamic_trials.append(doc)
    spec = spec_from_misc(doc["misc"])
    stop = threading.Event()
    beat = None
    if heartbeat is not None:
        running_path = os.path.join(
            queue.root, "running", f"{doc['tid']}.json"
        )
        beat = threading.Thread(
            target=_heartbeat, args=(running_path, float(heartbeat), stop),
            daemon=True,
        )
        beat.start()
    try:
        if workdir:
            with working_dir(os.path.join(workdir, str(doc["tid"]))):
                result = domain.evaluate(spec, ctrl)
        else:
            result = domain.evaluate(spec, ctrl)
    except Exception as e:
        logger.error("job %s failed: %s", doc["tid"], e)
        doc["state"] = JOB_STATE_ERROR
        doc["misc"]["error"] = (str(type(e)), str(e))
        doc["misc"]["traceback"] = traceback.format_exc()
    else:
        doc["state"] = JOB_STATE_DONE
        doc["result"] = SONify(result)
    finally:
        stop.set()
        if beat is not None:
            beat.join(timeout=5)
    queue.complete(doc)
    return True


def main_worker_helper(options):
    queue = FileJobQueue(options.dir)
    owner = worker_owner()
    n_done = 0
    idle_since = time.time()
    trials = FileTrials(
        options.dir, exp_key=options.exp_key, refresh=False,
        reserve_timeout=options.reserve_timeout,
    )
    logger.info("worker %s serving %s", owner, queue.root)
    while options.max_jobs is None or n_done < options.max_jobs:
        queue.reap(options.reserve_timeout)
        try:
            ran = run_one(
                queue, owner, exp_key=options.exp_key,
                workdir=options.workdir, trials=trials,
                heartbeat=(
                    options.reserve_timeout / 3.0
                    if options.reserve_timeout else None
                ),
            )
        except WorkerExit as e:
            logger.info("worker exit: %s", e)
            if time.time() - idle_since > (options.last_job_timeout or 30.0):
                return 1
            time.sleep(options.poll_interval)
            continue
        if ran:
            n_done += 1
            idle_since = time.time()
        else:
            if (
                options.last_job_timeout is not None
                and time.time() - idle_since > options.last_job_timeout
            ):
                logger.info("idle for %.0fs, exiting", options.last_job_timeout)
                break
            time.sleep(options.poll_interval)
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(prog="hyperopt-tpu-worker")
    parser.add_argument("--dir", required=True, help="FileJobQueue directory")
    parser.add_argument("--exp-key", default=None)
    parser.add_argument("--max-jobs", type=int, default=None)
    parser.add_argument("--poll-interval", type=float, default=0.2)
    parser.add_argument("--reserve-timeout", type=float, default=120.0)
    parser.add_argument(
        "--last-job-timeout", type=float, default=None,
        help="exit after this many seconds without work",
    )
    parser.add_argument("--workdir", default=None)
    parser.add_argument("-v", "--verbose", action="count", default=0)
    options = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if options.verbose else logging.INFO,
        stream=sys.stderr,
    )
    return main_worker_helper(options)


if __name__ == "__main__":
    sys.exit(main())
