"""FileTrials: a shared-filesystem job queue with atomic reservation.

The MongoDB-backend role of the reference (SURVEY.md SS3.4) rebuilt on the
substrate TPU pods actually share -- a common filesystem (NFS / GCS FUSE):

* the queue is a directory; a trial is one JSON file;
* reservation NEW -> RUNNING is an atomic ``os.rename`` into ``running/``
  (exactly one worker wins; the loser gets ENOENT) -- the CAS;
* the ``Domain`` ships to workers as a pickled attachment file;
* dead workers are reaped by mtime: ``running/`` entries older than
  ``reserve_timeout`` are renamed back into ``new/`` (the
  ``--reserve-timeout`` story, SURVEY.md SS5 failure detection);
* results land in ``done/`` via write-tmp-then-rename (atomic publish);
  exceptions produce ERROR-state docs with the traceback attached.

Run workers with ``python -m hyperopt_tpu.distributed.worker --dir DIR``
(or the ``hyperopt-tpu-worker`` console script).
"""

from __future__ import annotations

import datetime
import json
import logging
import os
import socket
import time

from ..base import JOB_STATE_DONE, JOB_STATE_ERROR, JOB_STATE_NEW, JOB_STATE_RUNNING, Trials
from ..utils import coarse_utcnow

logger = logging.getLogger(__name__)

__all__ = ["FileJobQueue", "FileTrials", "FileAttachments"]


def _encode(obj):
    if isinstance(obj, datetime.datetime):
        return {"__dt__": obj.isoformat()}
    raise TypeError(f"not JSON serializable: {type(obj)}")


def _decode(d):
    if "__dt__" in d:
        return datetime.datetime.fromisoformat(d["__dt__"])
    return d


def _write_atomic(path, payload):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, default=_encode)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, path)


def _read_json(path):
    with open(path) as f:
        return json.load(f, object_hook=_decode)


class FileAttachments:
    """Dict-like binary attachment store backed by a directory."""

    def __init__(self, root):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key):
        safe = "".join(c if c.isalnum() or c in "._-" else "_" for c in str(key))
        return os.path.join(self.root, safe)

    def __contains__(self, key):
        return os.path.exists(self._path(key))

    def __getitem__(self, key):
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise KeyError(key)

    def __setitem__(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(value)
        os.rename(tmp, path)

    def __delitem__(self, key):
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            raise KeyError(key)

    def keys(self):
        return os.listdir(self.root)


class FileJobQueue:
    """The queue protocol: reserve / complete / reap over a directory."""

    def __init__(self, root):
        self.root = os.path.abspath(root)
        for sub in ("new", "running", "done"):
            os.makedirs(os.path.join(self.root, sub), exist_ok=True)
        self.attachments = FileAttachments(os.path.join(self.root, "attachments"))

    def _p(self, sub, name=""):
        return os.path.join(self.root, sub, name)

    # -- driver side -------------------------------------------------------
    def publish(self, doc):
        _write_atomic(self._p("new", f"{doc['tid']}.json"), doc)

    def done_docs(self):
        out = {}
        for name in os.listdir(self._p("done")):
            if not name.endswith(".json"):
                continue
            try:
                doc = _read_json(self._p("done", name))
            except (json.JSONDecodeError, OSError):
                continue  # mid-write by a worker on a non-atomic FS
            out[doc["tid"]] = doc
        return out

    def counts(self):
        return {
            sub: len([n for n in os.listdir(self._p(sub)) if n.endswith(".json")])
            for sub in ("new", "running", "done")
        }

    # -- worker side -------------------------------------------------------
    def reserve(self, owner, exp_key=None, exclude_tids=()):
        """Atomically claim one NEW job; None if queue empty/raced away.

        ``exclude_tids`` lets a worker skip jobs it has already proven
        it cannot process (e.g. a dangling Domain attachment) -- the
        sorted scan would otherwise hand the same poisoned job back on
        every call and starve everything behind it."""
        names = sorted(n for n in os.listdir(self._p("new")) if n.endswith(".json"))
        for name in names:
            src = self._p("new", name)
            dst = self._p("running", name)
            try:
                doc = _read_json(src)
            except (FileNotFoundError, json.JSONDecodeError):
                continue
            if exp_key is not None and doc.get("exp_key") != exp_key:
                continue
            if doc.get("tid") in exclude_tids:
                continue
            try:
                # refresh the mtime BEFORE the CAS rename: a job that
                # waited in new/ longer than reserve_timeout would carry
                # its stale mtime into running/ and be reap-eligible
                # until _write_atomic below rewrites it -- a concurrent
                # reaper in that window could move it back to new/ while
                # this worker recreates the running file, duplicating
                # the evaluation (mirrors the utime-before-rename fix in
                # reap()/unreserve(); ADVICE r5).  Touching src is safe
                # under contention: whoever wins the rename gets a fresh
                # claim timestamp either way.
                os.utime(src)
                os.rename(src, dst)  # the CAS: exactly one winner
            except FileNotFoundError:
                continue  # another worker won this job
            doc["state"] = JOB_STATE_RUNNING
            doc["owner"] = owner
            doc["book_time"] = coarse_utcnow()
            _write_atomic(dst, doc)
            return doc
        return None

    def unreserve(self, doc):
        """Return a reserved job to NEW (the reap transition) -- used by
        a worker that cannot process it.  One atomic rename, content
        untouched: the directory is the state (``refresh`` reads only
        done/, ``reserve`` normalizes the doc when it claims).  The
        mtime is refreshed first so the job does not reappear in new/
        already looking reap-stale."""
        name = f"{doc['tid']}.json"
        path = self._p("running", name)
        try:
            os.utime(path)
            os.rename(path, self._p("new", name))
        except FileNotFoundError:
            pass  # completed or reaped underneath us

    def complete(self, doc):
        """Publish a finished (DONE or ERROR) doc and release the claim."""
        doc["refresh_time"] = coarse_utcnow()
        _write_atomic(self._p("done", f"{doc['tid']}.json"), doc)
        try:
            os.unlink(self._p("running", f"{doc['tid']}.json"))
        except FileNotFoundError:
            pass

    def reap(self, reserve_timeout):
        """Return RUNNING jobs older than reserve_timeout to NEW (crashed
        or wedged workers lose their claim)."""
        if reserve_timeout is None:
            return 0
        now = time.time()
        reaped = 0
        for name in os.listdir(self._p("running")):
            if not name.endswith(".json"):
                continue
            path = self._p("running", name)
            try:
                age = now - os.path.getmtime(path)
            except FileNotFoundError:
                continue
            if age < reserve_timeout:
                continue
            try:
                _read_json(path)  # validity gate: don't recycle a
                # mid-write/truncated claim into unreservable garbage
            except (FileNotFoundError, json.JSONDecodeError):
                continue
            try:
                # refresh the mtime BEFORE the rename: the recycled job
                # must not reappear in new/ still carrying its expired
                # timestamp, or the next reserver's claim would be
                # instantly reap-stale (a second reaper could recycle
                # the LIVE claim mid-reservation -- duplicated job).
                # Then ONE atomic rename, no content rewrite: the
                # directory IS the state (refresh reads only done/;
                # reserve normalizes state/owner/book_time when it
                # claims), and a rewrite here could race a reserver
                # into a duplicate or recreate a completed job's file
                os.utime(path)
                os.rename(path, self._p("new", name))
            except FileNotFoundError:
                continue
            reaped += 1
            logger.warning("reaped stale job %s (age %.0fs)", name, age)
        return reaped


class FileTrials(Trials):
    """Async Trials over a :class:`FileJobQueue` directory.

    Use with fmin exactly like MongoTrials in the reference::

        trials = FileTrials("/shared/exp1", exp_key="exp1")
        fmin(fn, space, algo=tpe_jax.suggest, max_evals=500, trials=trials)

    while N workers run ``hyperopt-tpu-worker --dir /shared/exp1``.
    """

    asynchronous = True

    def __init__(self, dirpath, exp_key=None, reserve_timeout=120.0, refresh=True):
        self.queue = FileJobQueue(dirpath)
        self.reserve_timeout = reserve_timeout
        super().__init__(exp_key=exp_key, refresh=False)
        self.attachments = self.queue.attachments
        if refresh:
            self.refresh()

    def _insert_trial_docs(self, docs):
        tids = super()._insert_trial_docs(docs)
        for doc in docs:
            self.queue.publish(doc)
        return tids

    def refresh(self):
        done = self.queue.done_docs()
        for trial in self._dynamic_trials:
            upd = done.get(trial["tid"])
            if upd is not None and trial["state"] not in (
                JOB_STATE_DONE, JOB_STATE_ERROR,
            ):
                trial.update(upd)
        self.queue.reap(self.reserve_timeout)
        super().refresh()

    def count_by_state_unsynced(self, arg):
        self.refresh()
        return super().count_by_state_unsynced(arg)

    def __getstate__(self):
        state = self.__dict__.copy()
        state["queue"] = self.queue.root
        state["attachments"] = None
        return state

    def __setstate__(self, state):
        root = state.pop("queue")
        self.__dict__.update(state)
        self.queue = FileJobQueue(root)
        self.attachments = self.queue.attachments


def worker_owner():
    return f"{socket.gethostname()}:{os.getpid()}"
