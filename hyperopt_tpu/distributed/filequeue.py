"""FileTrials: a shared-filesystem job queue with atomic reservation.

The MongoDB-backend role of the reference (SURVEY.md SS3.4) rebuilt on the
substrate TPU pods actually share -- a common filesystem (NFS / GCS FUSE):

* the queue is a directory; a trial is one JSON file;
* reservation NEW -> RUNNING is an atomic ``os.rename`` into ``running/``
  (exactly one worker wins; the loser gets ENOENT) -- the CAS;
* the ``Domain`` ships to workers as a pickled attachment file;
* dead workers are reaped by mtime: ``running/`` entries older than
  ``reserve_timeout`` are renamed back into ``new/`` (the
  ``--reserve-timeout`` story, SURVEY.md SS5 failure detection);
* results land in ``done/`` via write-tmp-then-rename (atomic publish);
  exceptions produce ERROR-state docs with the traceback attached.

Failure semantics (see FAILURES.md for the full recovery matrix):

* every filesystem primitive goes through an injectable ``fs`` seam
  (:mod:`.faults`), and every queue operation retries transient mount
  blips (ESTALE/EIO class) with bounded exponential backoff through
  :func:`._common.with_retries`;
* each claim carries a unique token; ``complete(doc, require_claim=True)``
  publishes only if the claim is still this worker's (a reaped-and-rerun
  job must not produce a duplicate DONE doc);
* ``reap`` releases -- rather than recycles -- claims whose DONE doc is
  already published (a worker that crashed between publishing and
  releasing must not cause a re-evaluation);
* ``python -m hyperopt_tpu.distributed.fsck --dir D [--repair]`` audits
  and repairs a corrupted queue directory.

Run workers with ``python -m hyperopt_tpu.distributed.worker --dir DIR``
(or the ``hyperopt-tpu-worker`` console script).
"""

from __future__ import annotations

import datetime
import json
import logging
import os
import socket
import time
import uuid

from ..base import JOB_STATE_DONE, JOB_STATE_ERROR, JOB_STATE_NEW, JOB_STATE_RUNNING, Trials
from ..utils import coarse_utcnow
from . import _common
from .faults import REAL_FS

logger = logging.getLogger(__name__)

__all__ = ["FileJobQueue", "FileTrials", "FileAttachments"]


def _encode(obj):
    if isinstance(obj, datetime.datetime):
        return {"__dt__": obj.isoformat()}
    raise TypeError(f"not JSON serializable: {type(obj)}")


def _decode(d):
    if "__dt__" in d:
        return datetime.datetime.fromisoformat(d["__dt__"])
    return d


def _write_atomic(path, payload, fs=REAL_FS, crash_before_rename=None):
    tmp = f"{path}.tmp.{os.getpid()}"
    with fs.open(tmp, "w") as f:
        json.dump(payload, f, default=_encode)
        fs.fsync(f)
    if crash_before_rename is not None:
        fs.crashpoint(crash_before_rename)
    fs.rename(tmp, path)


def _read_json(path, fs=REAL_FS):
    with fs.open(path) as f:
        return json.load(f, object_hook=_decode)


class FileAttachments:
    """Dict-like binary attachment store backed by a directory."""

    def __init__(self, root, fs=REAL_FS):
        self.root = root
        self.fs = fs
        fs.makedirs(root, exist_ok=True)

    def _path(self, key):
        safe = "".join(c if c.isalnum() or c in "._-" else "_" for c in str(key))
        return os.path.join(self.root, safe)

    def __contains__(self, key):
        return self.fs.exists(self._path(key))

    def __getitem__(self, key):
        def read():
            with self.fs.open(self._path(key), "rb") as f:
                return f.read()
        try:
            return _common.with_retries(read, label="attachment read")
        except FileNotFoundError:
            raise KeyError(key)

    def __setitem__(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}"

        def write():
            # fsync BEFORE the rename, like _write_atomic: without it a
            # crash shortly after the rename can publish an empty or
            # truncated blob (the rename metadata may reach disk before
            # the data does) -- and a truncated Domain pickle poisons
            # every worker that loads it
            with self.fs.open(tmp, "wb") as f:
                f.write(value)
                self.fs.fsync(f)
            self.fs.crashpoint("after_attach_fsync_before_rename")
            self.fs.rename(tmp, path)

        _common.with_retries(write, label="attachment write")

    def __delitem__(self, key):
        try:
            _common.with_retries(
                lambda: self.fs.unlink(self._path(key)),
                label="attachment delete",
            )
        except FileNotFoundError:
            raise KeyError(key)

    def keys(self):
        return _common.with_retries(
            lambda: self.fs.listdir(self.root), label="attachment list"
        )


class FileJobQueue:
    """The queue protocol: reserve / complete / reap over a directory.

    ``fs`` injects the filesystem seam (default: the real ``os``); pass
    ``faults.FaultPlan(...).fs()`` to run the protocol under seeded
    chaos (tests/test_chaos.py).
    """

    def __init__(self, root, fs=None):
        self.root = os.path.abspath(root)
        self.fs = fs if fs is not None else REAL_FS
        for sub in ("new", "running", "done"):
            self.fs.makedirs(os.path.join(self.root, sub), exist_ok=True)
        self.attachments = FileAttachments(
            os.path.join(self.root, "attachments"), fs=self.fs
        )

    def _p(self, sub, name=""):
        return os.path.join(self.root, sub, name)

    # -- driver side -------------------------------------------------------
    def publish(self, doc):
        _common.with_retries(
            lambda: _write_atomic(
                self._p("new", f"{doc['tid']}.json"), doc, fs=self.fs,
                crash_before_rename="after_publish_tmp_before_rename",
            ),
            label="publish",
        )

    def done_docs(self):
        out = {}
        names = _common.with_retries(
            lambda: self.fs.listdir(self._p("done")), label="done scan"
        )
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                doc = _common.with_retries(
                    lambda: _read_json(self._p("done", name), fs=self.fs),
                    label="done read",
                )
            except (json.JSONDecodeError, OSError):
                continue  # mid-write by a worker on a non-atomic FS
            out[doc["tid"]] = doc
        return out

    def counts(self):
        return {
            sub: len([
                n
                for n in _common.with_retries(
                    lambda: self.fs.listdir(self._p(sub)), label="counts"
                )
                if n.endswith(".json")
            ])
            for sub in ("new", "running", "done")
        }

    # -- worker side -------------------------------------------------------
    def reserve(self, owner, exp_key=None, exclude_tids=()):
        """Atomically claim one NEW job; None if queue empty/raced away.

        ``exclude_tids`` lets a worker skip jobs it has already proven
        it cannot process (e.g. a dangling Domain attachment) -- the
        sorted scan would otherwise hand the same poisoned job back on
        every call and starve everything behind it."""
        names = sorted(
            n
            for n in _common.with_retries(
                lambda: self.fs.listdir(self._p("new")), label="reserve scan"
            )
            if n.endswith(".json")
        )
        for name in names:
            src = self._p("new", name)
            dst = self._p("running", name)
            try:
                doc = _common.with_retries(
                    lambda: _read_json(src, fs=self.fs), label="reserve read"
                )
            except (FileNotFoundError, json.JSONDecodeError):
                continue
            except OSError:
                continue  # transient blip outlasted the retries: the
                # job stays in new/, a later pass picks it up
            if exp_key is not None and doc.get("exp_key") != exp_key:
                continue
            if doc.get("tid") in exclude_tids:
                continue
            try:
                already_done = self._already_done(name)
            except OSError:
                continue  # can't prove it's not completed: skip this
                # candidate for now rather than risk a duplicate
            if already_done:
                # a crash between complete()'s DONE publish and its
                # claim release, reaped by a pre-fix reaper (or fsck
                # fixture corruption), can leave a completed job back
                # in new/ -- re-evaluating it would duplicate the DONE
                # doc, so retire the leftover instead of claiming it
                try:
                    self.fs.unlink(src)
                except OSError:
                    pass
                continue
            try:
                def claim():
                    # refresh the mtime BEFORE the CAS rename: a job that
                    # waited in new/ longer than reserve_timeout would carry
                    # its stale mtime into running/ and be reap-eligible
                    # until _write_atomic below rewrites it -- a concurrent
                    # reaper in that window could move it back to new/ while
                    # this worker recreates the running file, duplicating
                    # the evaluation (mirrors the utime-before-rename fix in
                    # reap()/unreserve(); ADVICE r5).  Touching src is safe
                    # under contention: whoever wins the rename gets a fresh
                    # claim timestamp either way.
                    self.fs.utime(src)
                    self.fs.crashpoint("after_claim_utime_before_rename")
                    self.fs.rename(src, dst)  # the CAS: exactly one winner
                _common.with_retries(claim, label="reserve claim")
            except FileNotFoundError:
                continue  # another worker won this job
            except OSError:
                continue  # transient blip outlasted the retries; if the
                # rename did land server-side the claim sits in running/
                # with a fresh mtime and the reaper recycles it later --
                # delayed, never lost
            doc["state"] = JOB_STATE_RUNNING
            doc["owner"] = owner
            doc["book_time"] = coarse_utcnow()
            # unique claim token: lost-claim detection at completion
            # time must distinguish *this* reservation from a
            # reaped-and-re-claimed one, even when both claimants share
            # an owner string (two worker threads in one process)
            doc["claim"] = uuid.uuid4().hex
            self.fs.crashpoint("after_claim_rename_before_write")
            _common.with_retries(
                lambda: _write_atomic(dst, doc, fs=self.fs),
                label="reserve write",
            )
            return doc
        return None

    def _already_done(self, name):
        """Whether a valid DONE doc exists for ``name``.  Transient
        read failures are retried; if they persist, the OSError
        propagates so each caller can fail toward ITS safe side
        (reserve/reap skip the entry for this pass)."""
        try:
            _common.with_retries(
                lambda: _read_json(self._p("done", name), fs=self.fs),
                label="done check",
            )
            return True
        except (FileNotFoundError, json.JSONDecodeError):
            return False

    def unreserve(self, doc):
        """Return a reserved job to NEW (the reap transition) -- used by
        a worker that cannot process it.  One atomic rename, content
        untouched: the directory is the state (``refresh`` reads only
        done/, ``reserve`` normalizes the doc when it claims).  The
        mtime is refreshed first so the job does not reappear in new/
        already looking reap-stale."""
        name = f"{doc['tid']}.json"
        path = self._p("running", name)

        def give_back():
            self.fs.utime(path)
            self.fs.crashpoint("after_unreserve_utime_before_rename")
            self.fs.rename(path, self._p("new", name))

        try:
            _common.with_retries(give_back, label="unreserve")
        except FileNotFoundError:
            pass  # completed or reaped underneath us

    def claim_is_live(self, doc):
        """Whether ``doc``'s reservation still belongs to its claimant:
        the running file exists and carries the same claim token.  A
        False answer means the claim was reaped (and possibly handed to
        a re-run) -- the claimant must not publish."""
        path = self._p("running", f"{doc['tid']}.json")
        try:
            current = _common.with_retries(
                lambda: _read_json(path, fs=self.fs), label="claim check"
            )
        except FileNotFoundError:
            return False
        except (OSError, json.JSONDecodeError):
            # unreadable after retries: cannot prove the claim lost, and
            # a decode error here can only be a reaped-then-mid-rewrite
            # race; err toward keeping the result unless DONE exists
            try:
                return not self._already_done(f"{doc['tid']}.json")
            except OSError:
                return True  # doubly ambiguous: publishing (at worst an
                # overwrite with an equivalent doc) beats losing a result
        token = doc.get("claim")
        return token is None or current.get("claim") == token

    def complete(self, doc, require_claim=False):
        """Publish a finished (DONE or ERROR) doc and release the claim.

        With ``require_claim=True`` the publish happens only if the
        reservation is still this claimant's (:meth:`claim_is_live`);
        returns False -- publishing nothing -- when the claim was
        reaped mid-evaluation, so a stale worker cannot race the job's
        re-run into a duplicate DONE doc."""
        if require_claim and not self.claim_is_live(doc):
            return False
        doc["refresh_time"] = coarse_utcnow()
        _common.with_retries(
            lambda: _write_atomic(
                self._p("done", f"{doc['tid']}.json"), doc, fs=self.fs,
                crash_before_rename="after_done_tmp_before_rename",
            ),
            label="complete publish",
        )
        self.fs.crashpoint("after_done_rename_before_unlink")
        try:
            _common.with_retries(
                lambda: self.fs.unlink(self._p("running", f"{doc['tid']}.json")),
                label="complete release",
            )
        except (FileNotFoundError, OSError):
            pass  # reaped underneath us, or a blip outlasted the
            # retries -- either way reap() releases DONE-backed claims
        return True

    def reap(self, reserve_timeout):
        """Return RUNNING jobs older than reserve_timeout to NEW (crashed
        or wedged workers lose their claim).  A stale claim whose DONE
        doc is already published is *released* instead of recycled: the
        worker died between publishing and releasing, and re-running it
        would duplicate the DONE doc."""
        if reserve_timeout is None:
            return 0
        now = time.time()
        reaped = 0
        try:
            names = _common.with_retries(
                lambda: self.fs.listdir(self._p("running")), label="reap scan"
            )
        except OSError:
            return 0  # transient blip outlasted the retries: reaping is
            # periodic, the next cycle sees the same stale claims
        for name in names:
            if not name.endswith(".json"):
                continue
            path = self._p("running", name)
            try:
                age = now - _common.with_retries(
                    lambda: self.fs.getmtime(path), label="reap stat"
                )
            except (FileNotFoundError, OSError):
                continue
            if age < reserve_timeout:
                continue
            try:
                _common.with_retries(
                    lambda: _read_json(path, fs=self.fs), label="reap read"
                )  # validity gate: don't recycle a
                # mid-write/truncated claim into unreservable garbage
            except (FileNotFoundError, json.JSONDecodeError, OSError):
                continue
            try:
                already_done = self._already_done(name)
            except OSError:
                continue  # undecidable this cycle; reaping is periodic
            if already_done:
                # the claimant crashed AFTER publishing its DONE doc but
                # before releasing the claim: finish the release for it
                try:
                    _common.with_retries(
                        lambda: self.fs.unlink(path), label="reap release"
                    )
                    logger.warning(
                        "released completed stale claim %s (age %.0fs)",
                        name, age,
                    )
                except (FileNotFoundError, OSError):
                    pass
                continue
            try:
                def recycle():
                    # refresh the mtime BEFORE the rename: the recycled job
                    # must not reappear in new/ still carrying its expired
                    # timestamp, or the next reserver's claim would be
                    # instantly reap-stale (a second reaper could recycle
                    # the LIVE claim mid-reservation -- duplicated job).
                    # Then ONE atomic rename, no content rewrite: the
                    # directory IS the state (refresh reads only done/;
                    # reserve normalizes state/owner/book_time when it
                    # claims), and a rewrite here could race a reserver
                    # into a duplicate or recreate a completed job's file
                    self.fs.utime(path)
                    self.fs.crashpoint("after_reap_utime_before_rename")
                    self.fs.rename(path, self._p("new", name))
                _common.with_retries(recycle, label="reap recycle")
            except (FileNotFoundError, OSError):
                continue
            reaped += 1
            logger.warning("reaped stale job %s (age %.0fs)", name, age)
        return reaped


class FileTrials(Trials):
    """Async Trials over a :class:`FileJobQueue` directory.

    Use with fmin exactly like MongoTrials in the reference::

        trials = FileTrials("/shared/exp1", exp_key="exp1")
        fmin(fn, space, algo=tpe_jax.suggest, max_evals=500, trials=trials)

    while N workers run ``hyperopt-tpu-worker --dir /shared/exp1``.
    """

    asynchronous = True

    def __init__(self, dirpath, exp_key=None, reserve_timeout=120.0,
                 refresh=True, fs=None):
        self.queue = FileJobQueue(dirpath, fs=fs)
        self.reserve_timeout = reserve_timeout
        super().__init__(exp_key=exp_key, refresh=False)
        self.attachments = self.queue.attachments
        if refresh:
            self.refresh()

    def _insert_trial_docs(self, docs):
        tids = super()._insert_trial_docs(docs)
        for doc in docs:
            self.queue.publish(doc)
        return tids

    def refresh(self):
        done = self.queue.done_docs()
        for trial in self._dynamic_trials:
            upd = done.get(trial["tid"])
            if upd is not None and trial["state"] not in (
                JOB_STATE_DONE, JOB_STATE_ERROR,
            ):
                trial.update(upd)
        self.queue.reap(self.reserve_timeout)
        super().refresh()

    def count_by_state_unsynced(self, arg):
        self.refresh()
        return super().count_by_state_unsynced(arg)

    def __getstate__(self):
        state = self.__dict__.copy()
        state["queue"] = self.queue.root
        state["attachments"] = None
        return state

    def __setstate__(self, state):
        root = state.pop("queue")
        self.__dict__.update(state)
        self.queue = FileJobQueue(root)
        self.attachments = self.queue.attachments


def worker_owner():
    return f"{socket.gethostname()}:{os.getpid()}"
