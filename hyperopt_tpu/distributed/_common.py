"""Shared mechanics of the worker backends (filequeue + Mongo).

Both workers implement the same three contracts -- which Domain a job
doc names, a cooldown set for jobs whose Domain would not load, and a
small identity-validated Domain cache -- so the logic lives once here
and cannot drift between transports.
"""

from __future__ import annotations

import collections
import contextlib
import logging
import threading
import time

logger = logging.getLogger(__name__)

__all__ = ["blob_key_from_doc", "TTLSet", "lru_get", "claim_heartbeat"]

DEFAULT_DOMAIN_KEY = "FMinIter_Domain"


def blob_key_from_doc(doc):
    """The Domain attachment a trial doc names (the reference's cmd
    contract); drivers with different objectives share one queue, each
    doc resolving its own."""
    cmd = (doc.get("misc") or {}).get("cmd") or (None, None)
    return cmd[1] if cmd[0] == "domain_attachment" else DEFAULT_DOMAIN_KEY


class TTLSet:
    """Keys on cooldown: ``add`` starts a member's TTL, ``current()``
    prunes and returns the live members.  Used for poisoned-job tids --
    excluded from reservation long enough to stop a livelock on the
    lowest-tid job, retried afterwards in case the failure (a network
    blip misread as a missing attachment) was transient."""

    def __init__(self, ttl=300.0, clock=time.monotonic):
        self.ttl = float(ttl)
        self._clock = clock
        self._seen = {}

    def add(self, key):
        self._seen[key] = self._clock()

    def current(self):
        now = self._clock()
        self._seen = {
            k: ts for k, ts in self._seen.items() if now - ts < self.ttl
        }
        return list(self._seen)


@contextlib.contextmanager
def claim_heartbeat(beat, interval):
    """Run ``beat()`` every ``interval`` seconds on a daemon thread for
    the duration of the with-block -- the shared scaffold keeping a
    reserved job's claim visibly alive through evaluations LONGER than
    the reserve timeout, so reapers only recycle jobs whose worker
    actually died.  ``beat`` returns False to stop early (the claim is
    gone: completed/reaped underneath us); exceptions are logged and
    beating continues (a transient transport blip must not freeze the
    claim and get a LIVE job reaped and duplicated).  ``interval=None``
    disables the heartbeat entirely.
    """
    if interval is None:
        yield
        return
    stop = threading.Event()

    def loop():
        while not stop.wait(float(interval)):
            try:
                if beat() is False:
                    return
            except Exception as e:
                logger.warning("claim heartbeat failed transiently: %s", e)

    th = threading.Thread(target=loop, daemon=True)
    th.start()
    try:
        yield
    finally:
        stop.set()
        th.join(timeout=5)


def lru_get(cache, key, ident, load, cap=8):
    """Identity-validated LRU lookup: return ``cache[key]``'s value if
    its recorded identity equals ``ident``, else ``load()`` and store
    ``(ident, value)``.  Evicts least-recently-used entries beyond
    ``cap`` -- a long-lived worker serving many successive driver runs
    (one unique attachment key each) must not hold every run's
    unpickled Domain until OOM.

    ``cache`` must be a ``collections.OrderedDict``.
    """
    assert isinstance(cache, collections.OrderedDict)
    hit = cache.get(key)
    if hit is None or hit[0] != ident:
        hit = (ident, load())
        cache[key] = hit
    cache.move_to_end(key)
    while len(cache) > cap:
        cache.popitem(last=False)
    return hit[1]
