"""Shared mechanics of the worker backends (filequeue + Mongo).

Both workers implement the same three contracts -- which Domain a job
doc names, a cooldown set for jobs whose Domain would not load, and a
small identity-validated Domain cache -- so the logic lives once here
and cannot drift between transports.
"""

from __future__ import annotations

import collections
import contextlib
import errno
import logging
import threading
import time

logger = logging.getLogger(__name__)

__all__ = [
    "blob_key_from_doc", "TTLSet", "lru_get", "claim_heartbeat",
    "retry_delay", "with_retries", "is_transient", "TRANSIENT_ERRNOS",
]

DEFAULT_DOMAIN_KEY = "FMinIter_Domain"

# The errno classes a flaky network mount (NFS / GCS FUSE) emits for
# operations that are perfectly retryable: the handle went stale under
# a server restart (ESTALE), the transport hiccuped (EIO/ETIMEDOUT/
# ECONNRESET), or the kernel asked us to try again (EAGAIN/EINTR/EBUSY).
# ENOENT is deliberately ABSENT: FileNotFoundError is a protocol signal
# in the queue (a lost CAS race, a reaped claim), never a blip.
TRANSIENT_ERRNOS = frozenset({
    errno.ESTALE, errno.EIO, errno.EAGAIN, errno.EINTR, errno.EBUSY,
    errno.ETIMEDOUT, errno.ECONNRESET, errno.ENOBUFS, errno.EREMOTEIO,
})

# pymongo's retryable family, matched by mro NAME because pymongo is an
# optional (import-gated) dependency: AutoReconnect covers primary
# stepdowns and dropped sockets, NetworkTimeout subclasses it, and the
# test doubles can participate by naming an exception class the same.
_TRANSIENT_MONGO_NAMES = frozenset({
    "AutoReconnect", "NetworkTimeout", "NotPrimaryError",
})


def is_transient(exc):
    """Classify an exception as a retryable transport blip vs a real
    failure -- the transient-vs-fatal contract of
    :class:`hyperopt_tpu.exceptions.BackendError`."""
    from ..exceptions import FatalBackendError, TransientBackendError

    if isinstance(exc, FatalBackendError):
        return False
    if isinstance(exc, TransientBackendError):
        return True
    if isinstance(exc, OSError):
        return exc.errno in TRANSIENT_ERRNOS
    return any(
        c.__name__ in _TRANSIENT_MONGO_NAMES for c in type(exc).__mro__
    )


def retry_delay(attempt, base_delay=0.005, max_delay=0.05):
    """THE backoff schedule: ``min(max_delay, base_delay * 2**attempt)``.

    One definition shared by :func:`with_retries` and the worker CLIs'
    crash-loop guards, so every sleep-on-error in the fault domain backs
    off on the same bounded exponential curve (GL303's contract: no
    hand-rolled retry schedules)."""
    return min(float(max_delay), float(base_delay) * (2 ** int(attempt)))


def with_retries(fn, attempts=10, base_delay=0.005, max_delay=0.05,
                 sleep=time.sleep, classify=is_transient, label=None):
    """Call ``fn()``; on a transient failure (per ``classify``) retry
    with exponential backoff, up to ``attempts`` total calls.

    ``attempts=10`` covers the worst compound case a burst-bounded
    fault schedule can produce: a 4-primitive composite (open + write +
    fsync + rename) with up to 2 consecutive failures per primitive
    needs 9 calls to converge.

    The shared hardening scaffold both queue backends thread through
    reserve/complete/reap/refresh/heartbeat: an ESTALE from a bounced
    NFS server or an AutoReconnect from a mongo stepdown costs a few
    milliseconds of backoff instead of a dead worker.  Non-transient
    exceptions (FileNotFoundError CAS losses, JSON decode errors,
    FatalBackendError) propagate immediately -- retrying a protocol
    signal would only mask bugs.  Delays are capped at ``max_delay``
    (50 ms default) so the deterministic chaos suite never waits on a
    real-world backoff schedule.
    """
    for attempt in range(attempts):
        try:
            return fn()
        except Exception as e:
            if attempt == attempts - 1 or not classify(e):
                raise
            delay = retry_delay(attempt, base_delay, max_delay)
            logger.debug(
                "transient failure in %s (attempt %d/%d), retrying in "
                "%.0f ms: %s", label or getattr(fn, "__name__", "op"),
                attempt + 1, attempts, delay * 1e3, e,
            )
            sleep(delay)


def blob_key_from_doc(doc):
    """The Domain attachment a trial doc names (the reference's cmd
    contract); drivers with different objectives share one queue, each
    doc resolving its own."""
    cmd = (doc.get("misc") or {}).get("cmd") or (None, None)
    return cmd[1] if cmd[0] == "domain_attachment" else DEFAULT_DOMAIN_KEY


class TTLSet:
    """Keys on cooldown: ``add`` starts a member's TTL, ``current()``
    prunes and returns the live members.  Used for poisoned-job tids --
    excluded from reservation long enough to stop a livelock on the
    lowest-tid job, retried afterwards in case the failure (a network
    blip misread as a missing attachment) was transient."""

    def __init__(self, ttl=300.0, clock=time.monotonic):
        self.ttl = float(ttl)
        self._clock = clock
        self._seen = {}

    def add(self, key):
        self._seen[key] = self._clock()

    def current(self):
        now = self._clock()
        self._seen = {
            k: ts for k, ts in self._seen.items() if now - ts < self.ttl
        }
        return list(self._seen)


@contextlib.contextmanager
def claim_heartbeat(beat, interval):
    """Run ``beat()`` every ``interval`` seconds on a daemon thread for
    the duration of the with-block -- the shared scaffold keeping a
    reserved job's claim visibly alive through evaluations LONGER than
    the reserve timeout, so reapers only recycle jobs whose worker
    actually died.  ``beat`` returns False to stop early (the claim is
    gone: completed/reaped underneath us); exceptions are logged and
    beating continues (a transient transport blip must not freeze the
    claim and get a LIVE job reaped and duplicated).  ``interval=None``
    disables the heartbeat entirely.
    """
    if interval is None:
        yield
        return
    stop = threading.Event()

    def loop():
        while not stop.wait(float(interval)):
            try:
                if beat() is False:
                    return
            # a frozen beat gets a LIVE job reaped and duplicated, so the
            # heartbeat must outlive ANY transport error; non-transient
            # failures surface on the next (classified) queue operation
            except Exception as e:  # graftlint: disable=GL302 beat must outlive any error
                logger.warning("claim heartbeat failed transiently: %s", e)

    th = threading.Thread(target=loop, daemon=True)
    th.start()
    try:
        yield
    finally:
        stop.set()
        th.join(timeout=5)


def lru_get(cache, key, ident, load, cap=8):
    """Identity-validated LRU lookup: return ``cache[key]``'s value if
    its recorded identity equals ``ident``, else ``load()`` and store
    ``(ident, value)``.  Evicts least-recently-used entries beyond
    ``cap`` -- a long-lived worker serving many successive driver runs
    (one unique attachment key each) must not hold every run's
    unpickled Domain until OOM.

    ``cache`` must be a ``collections.OrderedDict``.
    """
    assert isinstance(cache, collections.OrderedDict)
    hit = cache.get(key)
    if hit is None or hit[0] != ident:
        hit = (ident, load())
        cache[key] = hit
    cache.move_to_end(key)
    while len(cache) > cap:
        cache.popitem(last=False)
    return hit[1]
