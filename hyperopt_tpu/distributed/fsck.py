"""Recovery audit for a FileJobQueue directory.

``python -m hyperopt_tpu.distributed.fsck --dir D [--repair]`` detects
(and, with ``--repair``, fixes) the residue every crash mode of the
queue protocol can leave behind -- the operational complement of the
worker-side hardening (FAILURES.md has the full recovery matrix):

==================  ==============================================  ===========================
issue               how it happens                                   repair
==================  ==============================================  ===========================
stale_tmp           crash between tmp write and rename               unlink (never referenced)
half_written        torn write on a non-atomic FS / fault fixture    quarantine the doc
orphaned_claim      worker died holding a claim (no heartbeat)       recycle to new/ (the reap)
completed_claim     crash between DONE publish and claim release     release (unlink the claim)
duplicate_tid       completed job recycled back into new/running     retire the shadowed copy
==================  ==============================================  ===========================

After ``--repair`` a fresh worker drains the directory completely: no
job lost, no DONE doc duplicated.  The tool only moves or deletes files
the protocol can prove are residue; half-written docs go to
``quarantine/`` (with a uniquifying suffix), never silently destroyed.

Exit codes: 0 clean (or fully repaired), 1 issues found (audit-only)
or unrepaired issues remain.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time

from . import _common
from .faults import REAL_FS
from .filequeue import _read_json

logger = logging.getLogger(__name__)

__all__ = ["Issue", "audit", "repair", "main"]

_SUBS = ("new", "running", "done")


class Issue:
    """One detected problem: ``kind`` (table above), the offending
    ``path``, and a human-readable ``detail``."""

    def __init__(self, kind, path, detail=""):
        self.kind = kind
        self.path = path
        self.detail = detail

    def __repr__(self):
        return f"Issue({self.kind}, {self.path!r}, {self.detail!r})"

    def __eq__(self, other):
        return (
            isinstance(other, Issue)
            and (self.kind, self.path) == (other.kind, other.path)
        )

    def __hash__(self):
        return hash((self.kind, self.path))


def _valid_doc(path, fs):
    try:
        doc = _common.with_retries(
            lambda: _read_json(path, fs=fs), label="fsck read"
        )
        return doc if isinstance(doc, dict) else None
    except (OSError, json.JSONDecodeError, ValueError):
        return None


def audit(root, fs=REAL_FS, reserve_timeout=None, tmp_grace=60.0):
    """Scan a queue directory and return the list of :class:`Issue`.

    ``reserve_timeout`` enables orphaned-claim detection (claims in
    running/ older than it); None skips that check (a live worker's
    claim is indistinguishable from an orphan without an age bound).
    ``tmp_grace`` is how old a ``*.tmp.*`` file must be before it
    counts as stale -- in-flight writers keep theirs younger.
    """
    root = os.path.abspath(root)
    issues = []
    now = time.time()
    docs = {}  # sub -> {name: doc or None}
    for sub in _SUBS + ("attachments",):
        subdir = os.path.join(root, sub)
        try:
            names = fs.listdir(subdir)
        except FileNotFoundError:
            continue
        for name in sorted(names):
            path = os.path.join(subdir, name)
            if ".tmp." in name:
                try:
                    age = now - fs.getmtime(path)
                except OSError:
                    continue
                if age >= tmp_grace:
                    issues.append(Issue(
                        "stale_tmp", path, f"age {age:.0f}s"
                    ))
                continue
            if sub == "attachments" or not name.endswith(".json"):
                continue
            doc = _valid_doc(path, fs)
            docs.setdefault(sub, {})[name] = doc
            if doc is None:
                issues.append(Issue(
                    "half_written", path, "unparseable job doc"
                ))
    # duplicate tids: the same job file present in more than one state
    # directory (a completed job recycled into new/ or running/, or a
    # claim that was both recycled and re-claimed)
    for name in sorted(
        set(docs.get("new", {})) | set(docs.get("running", {}))
    ):
        in_done = docs.get("done", {}).get(name) is not None
        in_new = name in docs.get("new", {})
        in_running = name in docs.get("running", {})
        if in_done:
            for sub in ("new", "running"):
                if name in docs.get(sub, {}):
                    kind = (
                        "completed_claim" if sub == "running"
                        else "duplicate_tid"
                    )
                    issues.append(Issue(
                        kind, os.path.join(root, sub, name),
                        "DONE doc already published",
                    ))
        elif in_new and in_running:
            issues.append(Issue(
                "duplicate_tid", os.path.join(root, "new", name),
                "also claimed in running/",
            ))
    # orphaned claims: running/ entries older than the reserve timeout
    # with no DONE doc (those are completed_claim above)
    if reserve_timeout is not None:
        for name, doc in sorted(docs.get("running", {}).items()):
            if doc is None or docs.get("done", {}).get(name) is not None:
                continue
            path = os.path.join(root, "running", name)
            try:
                age = now - fs.getmtime(path)
            except OSError:
                continue
            if age >= reserve_timeout:
                issues.append(Issue(
                    "orphaned_claim", path, f"age {age:.0f}s"
                ))
    return issues


def repair(root, issues, fs=REAL_FS):
    """Fix every repairable :class:`Issue`; returns the repaired count.

    Order matters: shadowed duplicates are retired before orphaned
    claims are recycled, so a completed job can never be resurrected
    through the reap transition."""
    root = os.path.abspath(root)
    quarantine = os.path.join(root, "quarantine")
    repaired = 0
    order = {
        "stale_tmp": 0, "half_written": 1, "completed_claim": 2,
        "duplicate_tid": 3, "orphaned_claim": 4,
    }
    for issue in sorted(issues, key=lambda i: (order.get(i.kind, 9), i.path)):
        try:
            if issue.kind == "stale_tmp":
                fs.unlink(issue.path)
            elif issue.kind == "half_written":
                fs.makedirs(quarantine, exist_ok=True)
                dst = os.path.join(
                    quarantine,
                    f"{os.path.basename(os.path.dirname(issue.path))}."
                    f"{os.path.basename(issue.path)}",
                )
                fs.rename(issue.path, dst)
                logger.warning("quarantined %s -> %s", issue.path, dst)
            elif issue.kind in ("completed_claim", "duplicate_tid"):
                # DONE already published, or the job is claimed in
                # running/: this copy is the resurrection hazard
                fs.unlink(issue.path)
            elif issue.kind == "orphaned_claim":
                # the reap transition: refresh the mtime first so the
                # recycled job does not reappear in new/ reap-stale
                name = os.path.basename(issue.path)
                fs.utime(issue.path)
                fs.rename(issue.path, os.path.join(root, "new", name))
            else:
                continue
            repaired += 1
        except FileNotFoundError:
            repaired += 1  # a live worker fixed it first
        except OSError as e:
            logger.error("could not repair %r: %s", issue, e)
    return repaired


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m hyperopt_tpu.distributed.fsck",
        description="Audit (and repair) a FileJobQueue directory.",
    )
    parser.add_argument("--dir", required=True, help="queue directory")
    parser.add_argument(
        "--repair", action="store_true",
        help="fix repairable issues instead of only reporting them",
    )
    parser.add_argument(
        "--reserve-timeout", type=float, default=120.0,
        help="claim age that counts as orphaned (seconds); the worker "
        "default.  Pass a negative value to skip orphan detection.",
    )
    parser.add_argument(
        "--tmp-grace", type=float, default=60.0,
        help="tmp-file age that counts as stale (seconds)",
    )
    parser.add_argument("-v", "--verbose", action="count", default=0)
    options = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if options.verbose else logging.INFO,
        stream=sys.stderr,
    )
    reserve_timeout = (
        None if options.reserve_timeout < 0 else options.reserve_timeout
    )
    issues = audit(
        options.dir, reserve_timeout=reserve_timeout,
        tmp_grace=options.tmp_grace,
    )
    for issue in issues:
        print(f"{issue.kind}: {issue.path} ({issue.detail})")
    if not issues:
        print(f"{options.dir}: clean")
        return 0
    if not options.repair:
        print(f"{len(issues)} issue(s) found (re-run with --repair to fix)")
        return 1
    n = repair(options.dir, issues)
    remaining = audit(
        options.dir, reserve_timeout=reserve_timeout,
        tmp_grace=options.tmp_grace,
    )
    print(f"repaired {n}/{len(issues)} issue(s); {len(remaining)} remain")
    return 0 if not remaining else 1


if __name__ == "__main__":
    sys.exit(main())
