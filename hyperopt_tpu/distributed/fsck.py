"""Recovery audit for a FileJobQueue directory -- and for the
sequential driver's crash-recovery artifacts.

``python -m hyperopt_tpu.distributed.fsck --dir D [--repair]`` detects
(and, with ``--repair``, fixes) the residue every crash mode of the
queue protocol can leave behind -- the operational complement of the
worker-side hardening (FAILURES.md has the full recovery matrix):

==================  ==============================================  ===========================
issue               how it happens                                   repair
==================  ==============================================  ===========================
stale_tmp           crash between tmp write and rename               unlink (never referenced)
half_written        torn write on a non-atomic FS / fault fixture    quarantine the doc
orphaned_claim      worker died holding a claim (no heartbeat)       recycle to new/ (the reap)
completed_claim     crash between DONE publish and claim release     release (unlink the claim)
duplicate_tid       completed job recycled back into new/running     retire the shadowed copy
==================  ==============================================  ===========================

ROLES under the round-20 unified durability layout: ``--serve`` is
the audit for everything the serve persistence writes -- fleet study
roots AND the engine-routed ``fmin`` client's
``trials_save_file``/``resume_from`` directory (``<root>/fmin.wal`` +
``fmin.snap``; graftclient rides the same per-study WAL/snapshot
machinery).  ``--driver`` remains for LEGACY solo-driver checkpoint
FILES only (``fmin(engine=False, trials_save_file="ckpt")``'s
``PATH``/``.meta``/``.wal`` family).

``--serve ROOT`` audits a SERVE study root -- the shared directory a
fleet of ``SuggestService`` replicas keeps one ``<name>.wal`` /
``<name>.snap`` / ``<name>.claim`` family per study in.  Every family
gets the driver-family checks below (torn WAL tails truncated,
mid-file corruption and foreign-guard snapshots quarantined, orphaned
snapshot tmps unlinked), plus ``claim_orphaned`` -- a claim token
whose study artifacts are gone (unlink).  After ``--serve ROOT
--repair`` the root is restorable: every surviving study family loads
via ``SuggestService(root=ROOT).create_study(name)`` -- the same
contract ``--driver`` gives ``fmin(resume_from=...)``.

``--driver PATH`` audits a driver checkpoint family instead (``PATH``,
``PATH.meta``, ``PATH.wal`` -- ``fmin(trials_save_file=)``'s recovery
artifacts):

=========================  =========================================  ===========================
issue                      how it happens                             repair
=========================  =========================================  ===========================
wal_torn_tail              driver died mid-append (torn record)       truncate to the valid prefix
wal_corrupt                mid-file checksum failure (not a tail)     quarantine the log
ckpt_fingerprint_mismatch  bundle belongs to a different study        quarantine the bundle
orphaned_snapshot_tmp      crash between snapshot tmp and rename      unlink (never referenced)
=========================  =========================================  ===========================

After ``--driver PATH --repair`` the checkpoint family is resumable:
``fmin(resume_from=PATH)`` loads the trials pickle, replays the valid
WAL prefix, and continues.  The tool only moves or deletes files the
protocol can prove are residue; anything ambiguous is quarantined
(``*.quarantined.*`` suffix), never silently destroyed.

Exit codes: 0 clean (or fully repaired), 1 issues found (audit-only)
or unrepaired issues remain.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time

from . import _common
from .faults import REAL_FS
from .filequeue import _read_json

logger = logging.getLogger(__name__)

__all__ = [
    "Issue", "audit", "repair", "audit_driver", "repair_driver",
    "audit_serve", "repair_serve", "audit_obs", "repair_obs", "main",
]

_SUBS = ("new", "running", "done")


class Issue:
    """One detected problem: ``kind`` (table above), the offending
    ``path``, and a human-readable ``detail``."""

    def __init__(self, kind, path, detail=""):
        self.kind = kind
        self.path = path
        self.detail = detail

    def __repr__(self):
        return f"Issue({self.kind}, {self.path!r}, {self.detail!r})"

    def __eq__(self, other):
        return (
            isinstance(other, Issue)
            and (self.kind, self.path) == (other.kind, other.path)
        )

    def __hash__(self):
        return hash((self.kind, self.path))


def _valid_doc(path, fs):
    try:
        doc = _common.with_retries(
            lambda: _read_json(path, fs=fs), label="fsck read"
        )
        return doc if isinstance(doc, dict) else None
    except (OSError, json.JSONDecodeError, ValueError):
        return None


def audit(root, fs=REAL_FS, reserve_timeout=None, tmp_grace=60.0):
    """Scan a queue directory and return the list of :class:`Issue`.

    ``reserve_timeout`` enables orphaned-claim detection (claims in
    running/ older than it); None skips that check (a live worker's
    claim is indistinguishable from an orphan without an age bound).
    ``tmp_grace`` is how old a ``*.tmp.*`` file must be before it
    counts as stale -- in-flight writers keep theirs younger.
    """
    root = os.path.abspath(root)
    issues = []
    now = time.time()
    docs = {}  # sub -> {name: doc or None}
    for sub in _SUBS + ("attachments",):
        subdir = os.path.join(root, sub)
        try:
            names = fs.listdir(subdir)
        except FileNotFoundError:
            continue
        for name in sorted(names):
            path = os.path.join(subdir, name)
            if ".tmp." in name:
                try:
                    age = now - fs.getmtime(path)
                except OSError:
                    continue
                if age >= tmp_grace:
                    issues.append(Issue(
                        "stale_tmp", path, f"age {age:.0f}s"
                    ))
                continue
            if sub == "attachments" or not name.endswith(".json"):
                continue
            doc = _valid_doc(path, fs)
            docs.setdefault(sub, {})[name] = doc
            if doc is None:
                issues.append(Issue(
                    "half_written", path, "unparseable job doc"
                ))
    # duplicate tids: the same job file present in more than one state
    # directory (a completed job recycled into new/ or running/, or a
    # claim that was both recycled and re-claimed)
    for name in sorted(
        set(docs.get("new", {})) | set(docs.get("running", {}))
    ):
        in_done = docs.get("done", {}).get(name) is not None
        in_new = name in docs.get("new", {})
        in_running = name in docs.get("running", {})
        if in_done:
            for sub in ("new", "running"):
                if name in docs.get(sub, {}):
                    kind = (
                        "completed_claim" if sub == "running"
                        else "duplicate_tid"
                    )
                    issues.append(Issue(
                        kind, os.path.join(root, sub, name),
                        "DONE doc already published",
                    ))
        elif in_new and in_running:
            issues.append(Issue(
                "duplicate_tid", os.path.join(root, "new", name),
                "also claimed in running/",
            ))
    # orphaned claims: running/ entries older than the reserve timeout
    # with no DONE doc (those are completed_claim above)
    if reserve_timeout is not None:
        for name, doc in sorted(docs.get("running", {}).items()):
            if doc is None or docs.get("done", {}).get(name) is not None:
                continue
            path = os.path.join(root, "running", name)
            try:
                age = now - fs.getmtime(path)
            except OSError:
                continue
            if age >= reserve_timeout:
                issues.append(Issue(
                    "orphaned_claim", path, f"age {age:.0f}s"
                ))
    return issues


def repair(root, issues, fs=REAL_FS):  # graftlint: disable=GL605 fsck IS the post-crash repair path: every rename here is idempotent and the chaos suites re-run fsck after injected kills, so a crash mid-repair is just another crash fsck heals
    """Fix every repairable :class:`Issue`; returns the repaired count.

    Order matters: shadowed duplicates are retired before orphaned
    claims are recycled, so a completed job can never be resurrected
    through the reap transition."""
    root = os.path.abspath(root)
    quarantine = os.path.join(root, "quarantine")
    repaired = 0
    order = {
        "stale_tmp": 0, "half_written": 1, "completed_claim": 2,
        "duplicate_tid": 3, "orphaned_claim": 4,
    }
    for issue in sorted(issues, key=lambda i: (order.get(i.kind, 9), i.path)):
        try:
            if issue.kind == "stale_tmp":
                fs.unlink(issue.path)
            elif issue.kind == "half_written":
                fs.makedirs(quarantine, exist_ok=True)
                dst = os.path.join(
                    quarantine,
                    f"{os.path.basename(os.path.dirname(issue.path))}."
                    f"{os.path.basename(issue.path)}",
                )
                fs.rename(issue.path, dst)
                logger.warning("quarantined %s -> %s", issue.path, dst)
            elif issue.kind in ("completed_claim", "duplicate_tid"):
                # DONE already published, or the job is claimed in
                # running/: this copy is the resurrection hazard
                fs.unlink(issue.path)
            elif issue.kind == "orphaned_claim":
                # the reap transition: refresh the mtime first so the
                # recycled job does not reappear in new/ reap-stale
                name = os.path.basename(issue.path)
                fs.utime(issue.path)
                fs.rename(issue.path, os.path.join(root, "new", name))
            else:
                continue
            repaired += 1
        except FileNotFoundError:
            repaired += 1  # a live worker fixed it first
        except OSError as e:
            logger.error("could not repair %r: %s", issue, e)
    return repaired


# ---------------------------------------------------------------------------
# serve study root (a fleet's shared WAL/snapshot/claim families)
# ---------------------------------------------------------------------------


def audit_serve(root, fs=REAL_FS, tmp_grace=60.0, claim_grace=None,
                live_owners=None):
    """Audit a serve study root: one ``<name>.wal`` / ``<name>.snap``
    / ``<name>.claim`` family per study, every crash mode a killed or
    failed-over replica can leave.  Returns the list of
    :class:`Issue` (kinds shared with :func:`audit_driver`, plus
    ``claim_orphaned`` and the cross-host kinds below).

    Cross-host checks (graftpilot): a shared NFS-style root is written
    by replicas on MANY hosts, so fsck must also catch the residue one
    host's crash leaves for another to trip over:

    * ``claim_stale_foreign`` -- a LIVE claim held by an owner not in
      ``live_owners`` (the operator-supplied set of replica ids that
      are actually up).  Only checked when ``live_owners`` is given;
      ``claim_grace`` (seconds) additionally requires the claim file
      to be at least that old before it counts, absorbing another
      host's skewed clock mid-handoff.  Repair tombstones the claim
      with a monotone epoch bump so any survivor can adopt without
      ``takeover``.
    * ``study_half_migrated`` -- a handoff-marked tombstone whose
      study was never adopted (the source released mid-migration and
      the coordinator died before the target restored).  The
      artifacts restore in place; repair clears the marker.
    * ``wal_snap_divergent`` -- the snapshot bundle counts more tells
      than the WAL has ever logged (``base_tells`` + records): the
      log was replaced or rolled back relative to the bundle by a
      host that had not seen its history.  Repair quarantines the
      WAL; the bundle holds the superset.
    """
    import pickle

    from ..exceptions import CheckpointError
    from ..utils.wal import TellWAL

    root = os.path.abspath(root)
    issues = []
    now = time.time()
    try:
        names = sorted(fs.listdir(root))
    except FileNotFoundError:
        return issues
    families = {}
    for name in names:
        full = os.path.join(root, name)
        if ".tmp." in name:
            try:
                age = now - fs.getmtime(full)
            except OSError:
                continue
            if age >= tmp_grace:
                issues.append(Issue(
                    "orphaned_snapshot_tmp", full, f"age {age:.0f}s"
                ))
            continue
        for suffix in (".wal", ".snap", ".claim"):
            if name.endswith(suffix):
                families.setdefault(
                    name[: -len(suffix)], set()
                ).add(suffix)
    for fam in sorted(families):
        kinds = families[fam]
        base = os.path.join(root, fam)
        wal_guard = None
        wal_total = None
        if ".wal" in kinds:
            wal = TellWAL(base + ".wal", fs=fs)
            try:
                header, records, _good, torn = wal.scan()
                wal_guard = (header or {}).get("guard")
                wal_total = (
                    int((header or {}).get("base_tells", 0))
                    + sum(1 for r in records if r.get("kind") == "tell")
                )
                if torn:
                    issues.append(Issue(
                        "wal_torn_tail", wal.path, f"{torn} torn byte(s)"
                    ))
            except CheckpointError as e:
                issues.append(Issue("wal_corrupt", wal.path, str(e)))
        if ".snap" in kinds:
            snap = base + ".snap"
            snap_guard = None
            snap_total = None
            try:
                with fs.open(snap, "rb") as f:
                    bundle = pickle.loads(f.read())
                snap_guard = bundle.get("guard")
                if bundle.get("total_tells") is not None:
                    snap_total = int(bundle["total_tells"])
            except Exception:  # graftlint: disable=GL302 an unreadable bundle is reported as an issue, not retried
                issues.append(Issue(
                    "ckpt_fingerprint_mismatch", snap, "bundle unreadable"
                ))
            if (
                snap_guard is not None
                and wal_guard is not None
                and list(snap_guard) != list(wal_guard)
            ):
                issues.append(Issue(
                    "ckpt_fingerprint_mismatch", snap,
                    f"bundle guard {snap_guard!r} != WAL guard "
                    f"{wal_guard!r}",
                ))
            elif (
                snap_total is not None
                and wal_total is not None
                and snap_total > wal_total
            ):
                issues.append(Issue(
                    "wal_snap_divergent", base + ".wal",
                    f"snapshot counts {snap_total} tell(s) but the WAL "
                    f"has only ever logged {wal_total} -- the log was "
                    "replaced or rolled back relative to the bundle",
                ))
        if kinds == {".claim"}:
            issues.append(Issue(
                "claim_orphaned", base + ".claim",
                "claim token with no WAL or snapshot",
            ))
            continue
        if ".claim" in kinds:
            doc = _valid_doc(base + ".claim", fs)
            if (
                doc is not None
                and not doc.get("released")
                and live_owners is not None
                and doc.get("replica") not in set(live_owners)
            ):
                try:
                    age = now - fs.getmtime(base + ".claim")
                except OSError:
                    age = None
                if claim_grace is None or age is None or age >= claim_grace:
                    issues.append(Issue(
                        "claim_stale_foreign", base + ".claim",
                        f"held by {doc.get('replica')!r} (epoch "
                        f"{doc.get('epoch')}), not in the live owner set",
                    ))
            if (
                doc is not None
                and doc.get("released")
                and doc.get("handoff")
            ):
                issues.append(Issue(
                    "study_half_migrated", base + ".claim",
                    f"handoff tombstone (epoch {doc.get('epoch')}) "
                    "never adopted: the source released, no owner "
                    "restored",
                ))
    return issues


def _republish_tombstone(path, fs):  # graftlint: disable=GL605 fsck repair primitive: the tombstone publish is idempotent (monotone epoch bump), and a crash between fsync and rename leaves the old claim visible for the NEXT fsck pass to tombstone again
    """Overwrite a claim file with a released tombstone, epoch bumped
    past whatever is on disk (the fsck repair for stale foreign claims
    and unacknowledged handoffs): monotone for every observer, and any
    survivor can then adopt the study without ``takeover``."""
    doc = _valid_doc(path, fs) or {}
    body = {
        "replica": None, "token": None,
        "epoch": int(doc.get("epoch", -1)) + 1, "released": True,
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with fs.open(tmp, "w") as f:
        f.write(json.dumps(body, sort_keys=True))
        fs.fsync(f)
    fs.rename(tmp, path)


def repair_serve(root, issues, fs=REAL_FS):  # graftlint: disable=GL605 fsck IS the post-crash repair path: tombstones and quarantine renames are idempotent, and chaos suites re-run fsck after injected kills
    """Fix every repairable serve-root :class:`Issue`; returns the
    repaired count.  Family kinds delegate to :func:`repair_driver`
    (truncate / quarantine / unlink are path-local); orphaned claims
    are unlinked -- nothing references them.  Cross-host kinds: stale
    foreign claims and half-migrated handoffs are tombstoned with a
    monotone epoch bump (never unlinked -- the epoch history is the
    fence); a divergent WAL is quarantined, its bundle holds the
    superset history."""
    repaired = 0
    rest = []
    for issue in issues:
        try:
            if issue.kind == "claim_orphaned":
                fs.unlink(issue.path)
            elif issue.kind in ("claim_stale_foreign",
                                "study_half_migrated"):
                _republish_tombstone(issue.path, fs)
            elif issue.kind == "wal_snap_divergent":
                dst = f"{issue.path}.quarantined.{os.getpid()}"
                fs.rename(issue.path, dst)
                logger.warning("quarantined %s -> %s", issue.path, dst)
            else:
                rest.append(issue)
                continue
            repaired += 1
        except FileNotFoundError:
            repaired += 1
        except OSError as e:
            logger.error("could not repair %r: %s", issue, e)
    return repaired + repair_driver(root, rest, fs=fs)


# ---------------------------------------------------------------------------
# driver checkpoint family (fmin's WAL + bundle artifacts)
# ---------------------------------------------------------------------------


def audit_driver(path, fs=REAL_FS, tmp_grace=60.0):
    """Audit a driver checkpoint family (``path`` / ``path.meta`` /
    ``path.wal``) for the corruption classes a killed driver can leave:
    a torn WAL tail, mid-file WAL corruption, a bundle whose guard
    fingerprint disagrees with the WAL header (a foreign study's
    artifact under this name), and orphaned ``*.tmp.*`` snapshots."""
    import pickle

    from ..exceptions import CheckpointError
    from ..utils.wal import TellWAL

    path = os.path.abspath(path)
    issues = []
    now = time.time()
    # orphaned snapshot tmp files: <family member>.tmp.<pid> residue of
    # a crash inside a durable publish (the rename never happened)
    dirname, base = os.path.split(path)
    try:
        names = fs.listdir(dirname)
    except FileNotFoundError:
        names = []
    for name in sorted(names):
        if not name.startswith(base) or ".tmp." not in name:
            continue
        full = os.path.join(dirname, name)
        try:
            age = now - fs.getmtime(full)
        except OSError:
            continue
        if age >= tmp_grace:
            issues.append(Issue(
                "orphaned_snapshot_tmp", full, f"age {age:.0f}s"
            ))
    # WAL integrity: a torn tail is normal crash residue (repairable by
    # truncation); a mid-file checksum failure is not ours to truncate.
    # Under graftburst group-commit the window widens: a machine crash
    # between a round's flushes and its barrier fsync can tear (or drop)
    # the whole unbarriered suffix, not just one record -- the same
    # truncate-to-valid-prefix repair covers it, and the barriered
    # prefix is exactly what replay restores
    wal = TellWAL(path + ".wal", fs=fs)
    wal_guard = None
    if wal.exists():
        try:
            header, _records, _good, torn = wal.scan()
            wal_guard = (header or {}).get("guard")
            if torn:
                issues.append(Issue(
                    "wal_torn_tail", wal.path, f"{torn} torn byte(s)"
                ))
        except CheckpointError as e:
            issues.append(Issue("wal_corrupt", wal.path, str(e)))
    # bundle fingerprint: the meta guard and the WAL header guard were
    # stamped by the same study -- disagreement means one of them is a
    # foreign artifact parked under this family's name
    meta_path = path + ".meta"
    if fs.exists(meta_path) and wal_guard is not None:
        try:
            with fs.open(meta_path, "rb") as f:
                meta = pickle.loads(f.read())
            meta_guard = meta.get("guard")
        except Exception:  # graftlint: disable=GL302 an unreadable bundle is reported as an issue, not retried
            meta_guard = None
            issues.append(Issue(
                "ckpt_fingerprint_mismatch", meta_path,
                "bundle unreadable",
            ))
        if meta_guard is not None and list(meta_guard) != list(wal_guard):
            issues.append(Issue(
                "ckpt_fingerprint_mismatch", meta_path,
                f"bundle guard {meta_guard!r} != WAL guard {wal_guard!r}",
            ))
    return issues


def repair_driver(path, issues, fs=REAL_FS):  # graftlint: disable=GL605 fsck IS the post-crash repair path: quarantine renames are idempotent and re-runnable, so a crash mid-repair is just another crash the next fsck pass heals
    """Fix every repairable driver-family :class:`Issue`; returns the
    repaired count.  Quarantined artifacts get a ``.quarantined.<pid>``
    suffix next to the family -- resume then falls back to the
    surviving artifacts (trials pickle + valid WAL prefix)."""
    from ..utils.wal import TellWAL

    repaired = 0
    for issue in sorted(issues, key=lambda i: (i.kind, i.path)):
        try:
            if issue.kind == "orphaned_snapshot_tmp":
                fs.unlink(issue.path)
            elif issue.kind == "wal_torn_tail":
                TellWAL(issue.path, fs=fs).recover()
            elif issue.kind in ("wal_corrupt", "ckpt_fingerprint_mismatch"):
                dst = f"{issue.path}.quarantined.{os.getpid()}"
                fs.rename(issue.path, dst)
                logger.warning("quarantined %s -> %s", issue.path, dst)
            else:
                continue
            repaired += 1
        except FileNotFoundError:
            repaired += 1  # a live driver fixed it first
        except OSError as e:
            logger.error("could not repair %r: %s", issue, e)
    return repaired


def audit_obs(path, fs=REAL_FS):
    """Audit a graftscope flight-recorder log (``--obs PATH``): a torn
    tail (crash mid-export) is repairable by truncation; mid-file
    corruption is reported but left in place -- the span scanner
    already skips it, and telemetry never warrants quarantine."""
    from ..obs.flightrec import audit_flight_log

    return [
        Issue(kind, p, detail)
        for kind, p, detail in audit_flight_log(path, fs=fs)
    ]


def repair_obs(path, issues, fs=REAL_FS):
    """Truncate a flight log's torn tail (tmp + fsync + rename);
    returns the repaired count."""
    from ..obs.flightrec import repair_flight_log

    repaired = 0
    for issue in issues:
        if issue.kind != "obs_torn_tail":
            continue
        dropped = repair_flight_log(issue.path, fs=fs)
        if dropped:
            logger.info(
                "flight log %s: truncated %d torn byte(s)",
                issue.path, dropped,
            )
            repaired += 1
    return repaired


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m hyperopt_tpu.distributed.fsck",
        description="Audit (and repair) a FileJobQueue directory, or a "
        "driver checkpoint family (--driver).",
    )
    parser.add_argument("--dir", help="queue directory")
    parser.add_argument(
        "--driver", metavar="PATH",
        help="audit fmin's driver checkpoint family (PATH, PATH.meta, "
        "PATH.wal) instead of a queue directory",
    )
    parser.add_argument(
        "--serve", metavar="ROOT",
        help="audit a serve study root (a fleet's shared directory of "
        "per-study <name>.wal/.snap/.claim families) instead",
    )
    parser.add_argument(
        "--obs", metavar="PATH",
        help="audit a graftscope flight-recorder span log (torn export "
        "tails are truncated under --repair) instead",
    )
    parser.add_argument(
        "--repair", action="store_true",
        help="fix repairable issues instead of only reporting them",
    )
    parser.add_argument(
        "--reserve-timeout", type=float, default=120.0,
        help="claim age that counts as orphaned (seconds); the worker "
        "default.  Pass a negative value to skip orphan detection.",
    )
    parser.add_argument(
        "--tmp-grace", type=float, default=60.0,
        help="tmp-file age that counts as stale (seconds)",
    )
    parser.add_argument(
        "--live-owner", action="append", metavar="RID",
        help="(--serve) a replica id known to be up (repeatable); "
        "enables the cross-host claim_stale_foreign check -- a live "
        "claim held by any OTHER owner is reported and, under "
        "--repair, tombstoned with a monotone epoch bump",
    )
    parser.add_argument(
        "--claim-grace", type=float, default=None,
        help="(--serve) minimum claim-file age (seconds) before a "
        "foreign claim counts as stale -- absorbs another host's "
        "skewed clock mid-handoff; default: no age requirement",
    )
    parser.add_argument("-v", "--verbose", action="count", default=0)
    options = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if options.verbose else logging.INFO,
        stream=sys.stderr,
    )
    n_targets = sum(
        1 for t in (
            options.dir, options.driver, options.serve, options.obs
        ) if t
    )
    if n_targets != 1:
        parser.error(
            "exactly one of --dir, --driver, --serve or --obs is required"
        )
    if options.obs:
        target = options.obs
        do_audit = lambda: audit_obs(options.obs)  # noqa: E731
        do_repair = lambda issues: repair_obs(  # noqa: E731
            options.obs, issues
        )
    elif options.serve:
        target = options.serve
        do_audit = lambda: audit_serve(  # noqa: E731
            options.serve, tmp_grace=options.tmp_grace,
            claim_grace=options.claim_grace,
            live_owners=options.live_owner,
        )
        do_repair = lambda issues: repair_serve(  # noqa: E731
            options.serve, issues
        )
    elif options.driver:
        target = options.driver
        do_audit = lambda: audit_driver(  # noqa: E731
            options.driver, tmp_grace=options.tmp_grace
        )
        do_repair = lambda issues: repair_driver(  # noqa: E731
            options.driver, issues
        )
    else:
        target = options.dir
        reserve_timeout = (
            None if options.reserve_timeout < 0 else options.reserve_timeout
        )
        do_audit = lambda: audit(  # noqa: E731
            options.dir, reserve_timeout=reserve_timeout,
            tmp_grace=options.tmp_grace,
        )
        do_repair = lambda issues: repair(options.dir, issues)  # noqa: E731
    issues = do_audit()
    for issue in issues:
        print(f"{issue.kind}: {issue.path} ({issue.detail})")
    if not issues:
        print(f"{target}: clean")
        return 0
    if not options.repair:
        print(f"{len(issues)} issue(s) found (re-run with --repair to fix)")
        return 1
    n = do_repair(issues)
    remaining = do_audit()
    print(f"repaired {n}/{len(issues)} issue(s); {len(remaining)} remain")
    return 0 if not remaining else 1


if __name__ == "__main__":
    sys.exit(main())
