"""ASHA over the shared-filesystem work queue: the async scheduler
driving the async execution backend.

The reference pairs its only async scheduler shape (the fmin driver's
``asynchronous=True`` loop) with MongoDB task farming (SURVEY.md SS3.4);
this module pairs the modern async scheduler -- :func:`hyperband.asha`'s
promote-on-completion rule -- with the same farming model on the
substrate TPU pods actually share (``filequeue``, the Mongo role over
NFS/GCS-FUSE).  The division of labor:

* the DRIVER runs the ASHA scheduler; each of its in-flight slots
  publishes one ``(config, budget)`` job to the queue and blocks until
  that job's ``done/<tid>.json`` appears -- promotion decisions never
  wait for a rung barrier, exactly as in-process ASHA;
* ``hyperopt-tpu-worker`` PROCESSES (any number, any host sharing the
  mount) reserve jobs via the atomic-rename CAS and evaluate them
  through the pickled :class:`BudgetedDomainFn` domain, which hands the
  user objective the trial's ``budget`` alongside its decoded config;
* crashed workers are reaped by mtime (``reserve_timeout``) and their
  jobs re-reserved; a worker ERROR doc (traceback attached) records as
  a failed evaluation that can never promote -- the same failure
  contract as the in-process path.

``asha(checkpoint=...)`` composes: the scheduler snapshot lives with
the driver, the queue directory is the transport record.
"""

from __future__ import annotations

import itertools
import logging
import os
import pickle
import threading
import time
import uuid

from ..base import Domain, JOB_STATE_DONE, JOB_STATE_NEW, SONify, STATUS_OK
from .filequeue import FileJobQueue, _read_json

logger = logging.getLogger(__name__)

__all__ = ["BudgetedDomainFn", "asha_filequeue"]


class BudgetedDomainFn:
    """Picklable worker-side objective adapter: evaluates a budget-aware
    ``fn(config, budget)`` from a queued trial doc.

    Shipped to workers inside the pickled ``Domain`` (so ``fn`` must be
    picklable, same contract as the reference's Domain shipping).  Uses
    the ``pass_expr_memo_ctrl`` seam: the ``Ctrl``'s current trial doc
    carries the rung budget in ``misc["budget"]``, and the config is
    recovered by evaluating the space expression under the doc's pinned
    parameter memo -- identical decoding to the sync driver's
    ``space_eval``.
    """

    fmin_pass_expr_memo_ctrl = True

    def __init__(self, fn):
        self.fn = fn

    def __call__(self, expr, memo, ctrl):
        from ..pyll.base import rec_eval

        budget = ctrl.current_trial["misc"]["budget"]
        cfg = rec_eval(expr, memo=memo)
        return self.fn(cfg, budget)




def asha_filequeue(
    fn,
    space,
    max_budget,
    dirpath,
    eta=3,
    min_budget=1,
    max_jobs=81,
    inflight=8,
    algo=None,
    trials=None,
    rstate=None,
    checkpoint=None,
    checkpoint_every=1,
    exp_key=None,
    poll_interval=0.05,
    eval_timeout=None,
    reserve_timeout=120.0,
):
    """Run ASHA with evaluations farmed to ``hyperopt-tpu-worker``
    processes over a :class:`FileJobQueue` directory.

    Args are :func:`hyperopt_tpu.hyperband.asha`'s, plus:

      dirpath: the queue directory workers serve (``python -m
        hyperopt_tpu.distributed.worker --dir DIR``).  The budget-aware
        ``Domain`` is (re)published to its attachments at entry.
      inflight: concurrent jobs in the queue (the driver's slot count;
        actual parallelism is however many workers serve the mount).
      poll_interval: driver's BASE done-file poll cadence per slot;
        each slot backs off proportionally to its job's elapsed time
        (~10%, capped at >= 1 s), so short jobs are detected within
        ~poll_interval while long evaluations do not hammer the mount.
      eval_timeout: per-evaluation wall-clock bound; an expired job
        records as a failed evaluation (it keeps its queue files for
        post-mortem, but can never promote).
      reserve_timeout: stale-claim reaping age, as in the worker CLI --
        the driver reaps while polling, so a crashed worker's job
        returns to ``new/`` even if every other worker is busy.

    Returns the :func:`hyperband.asha` result dict; the scheduler's
    trial store is driver-side, the queue directory holds the transport
    record (every job's doc with owner/timings/tracebacks).
    """
    from ..hyperband import asha

    if trials is not None and hasattr(trials, "queue"):
        # a queue-backed store (FileTrials) would RE-publish every
        # scheduler-recorded doc into new/ as a job -- workers would
        # churn on budget-less garbage.  The scheduler store is
        # driver-side bookkeeping; the queue directory is the transport
        raise ValueError(
            "asha_filequeue needs an in-memory Trials (or None) for "
            "trials=; queue-backed stores like FileTrials re-publish "
            "recorded docs as jobs"
        )
    queue = FileJobQueue(dirpath)
    domain = Domain(BudgetedDomainFn(fn), space)
    queue.attachments["FMinIter_Domain"] = pickle.dumps(domain)
    # queue tids are namespaced per driver run: a resumed driver must
    # never collide with the killed run's leftover files
    run_tag = uuid.uuid4().hex[:8]
    counter = itertools.count()
    counter_lock = threading.Lock()
    # reaping only matters on the reserve_timeout scale; one shared
    # rate limit keeps ``inflight`` polling slots from issuing
    # listdir+getmtime scans of running/ every tick on a network mount
    reap_period = max(1.0, float(reserve_timeout or 0) / 10.0)
    last_reap = [0.0]

    def _maybe_reap():
        with counter_lock:
            now = time.monotonic()
            if now - last_reap[0] < reap_period:
                return
            last_reap[0] = now
        queue.reap(reserve_timeout)

    def evaluator(vals, budget):
        with counter_lock:
            tid = f"{run_tag}-{next(counter)}"
        doc = {
            "tid": tid,
            "state": JOB_STATE_NEW,
            "spec": None,
            "result": {"status": "new"},
            "misc": {
                "tid": tid,
                "cmd": ("domain_attachment", "FMinIter_Domain"),
                "workdir": None,
                "idxs": {k: [tid] for k in vals},
                # SONify: doc vals may be numpy scalars/0-d arrays and
                # the queue serializes docs as JSON
                "vals": SONify({k: [v] for k, v in vals.items()}),
                "budget": SONify(budget),
            },
            "exp_key": exp_key,
            "owner": None,
            "version": 0,
            "book_time": None,
            "refresh_time": None,
        }
        queue.publish(doc)
        done_path = os.path.join(queue.root, "done", f"{tid}.json")
        deadline = (
            None if eval_timeout is None else time.monotonic() + eval_timeout
        )
        # proportional backoff per slot: poll at ~10% of the job's
        # elapsed time, floored at the responsive base cadence and
        # capped at 1 Hz -- short evaluations pay ~poll_interval of
        # detection latency while long (TPU-training-scale) ones stop
        # hammering the mount's metadata path (total polls grow
        # logarithmically, then linearly at 1/s)
        published = time.monotonic()
        while True:
            out = None
            if os.path.exists(done_path):
                try:
                    out = _read_json(done_path)
                except (ValueError, OSError):
                    out = None  # mid-write on a non-atomic FS: retry,
                    # but fall through to the deadline check -- a file
                    # left permanently truncated by a killed worker
                    # must not bypass eval_timeout
            if out is not None:
                result = out.get("result") or {}
                if (
                    out.get("state") == JOB_STATE_DONE
                    and result.get("status") == STATUS_OK
                ):
                    return float(result["loss"])
                logger.warning(
                    "queued asha job %s failed: %s", tid,
                    out.get("misc", {}).get("error"),
                )
                return float("nan")
            if deadline is not None and time.monotonic() > deadline:
                logger.warning("queued asha job %s timed out", tid)
                return float("nan")
            _maybe_reap()
            elapsed = time.monotonic() - published
            time.sleep(min(
                max(float(poll_interval), 0.1 * elapsed),
                max(float(poll_interval), 1.0),
            ))

    return asha(
        fn,
        space,
        max_budget,
        eta=eta,
        min_budget=min_budget,
        max_jobs=max_jobs,
        workers=inflight,
        algo=algo,
        trials=trials,
        rstate=rstate,
        checkpoint=checkpoint,
        checkpoint_every=checkpoint_every,
        evaluator=evaluator,
    )
