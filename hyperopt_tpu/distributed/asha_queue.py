"""ASHA over the shared-filesystem work queue: the async scheduler
driving the async execution backend.

The reference pairs its only async scheduler shape (the fmin driver's
``asynchronous=True`` loop) with MongoDB task farming (SURVEY.md SS3.4);
this module pairs the modern async scheduler -- :func:`hyperband.asha`'s
promote-on-completion rule -- with the same farming model on the
substrate TPU pods actually share (``filequeue``, the Mongo role over
NFS/GCS-FUSE).  The division of labor:

* the DRIVER runs the ASHA scheduler; each of its in-flight slots
  publishes one ``(config, budget)`` job to the queue and blocks until
  that job's ``done/<tid>.json`` appears -- promotion decisions never
  wait for a rung barrier, exactly as in-process ASHA;
* ``hyperopt-tpu-worker`` PROCESSES (any number, any host sharing the
  mount) reserve jobs via the atomic-rename CAS and evaluate them
  through the pickled :class:`BudgetedDomainFn` domain, which hands the
  user objective the trial's ``budget`` alongside its decoded config;
* crashed workers are reaped by mtime (``reserve_timeout``) and their
  jobs re-reserved; a worker ERROR doc (traceback attached) records as
  a failed evaluation that can never promote -- the same failure
  contract as the in-process path.

``asha(checkpoint=...)`` composes: the scheduler snapshot lives with
the driver, the queue directory is the transport record.

:func:`asha_mongo` is the same driver/worker split over the MongoDB
protocol (``hyperopt-tpu-mongo-worker`` processes, GridFS Domain
shipping) -- both share :class:`_TransportDriver`, so transport
behavior (tid namespacing, proportional-backoff polling, rate-limited
reaping, timeout-as-failed-trial) is identical.
"""

from __future__ import annotations

import itertools
import logging
import os
import pickle
import threading
import time
import uuid

from ..base import Domain, JOB_STATE_DONE, JOB_STATE_NEW, SONify, STATUS_OK
from ..obs.registry import CounterAttr, MetricsRegistry
from . import _common
from .filequeue import FileJobQueue, _read_json

logger = logging.getLogger(__name__)

__all__ = [
    "BudgetedDomainFn", "asha_filequeue", "asha_mongo", "asha_spark",
]


class BudgetedDomainFn:
    """Picklable worker-side objective adapter: evaluates a budget-aware
    ``fn(config, budget)`` from a queued trial doc.

    Shipped to workers inside the pickled ``Domain`` (so ``fn`` must be
    picklable, same contract as the reference's Domain shipping).  Uses
    the ``pass_expr_memo_ctrl`` seam: the ``Ctrl``'s current trial doc
    carries the rung budget in ``misc["budget"]``, and the config is
    recovered by evaluating the space expression under the doc's pinned
    parameter memo -- identical decoding to the sync driver's
    ``space_eval``.
    """

    fmin_pass_expr_memo_ctrl = True

    def __init__(self, fn):
        self.fn = fn

    def __call__(self, expr, memo, ctrl):
        from ..pyll.base import rec_eval

        budget = ctrl.current_trial["misc"]["budget"]
        cfg = rec_eval(expr, memo=memo)
        return self.fn(cfg, budget)




def asha_filequeue(
    fn,
    space,
    max_budget,
    dirpath,
    eta=3,
    min_budget=1,
    max_jobs=81,
    inflight=8,
    algo=None,
    trials=None,
    rstate=None,
    checkpoint=None,
    checkpoint_every=1,
    exp_key=None,
    poll_interval=0.05,
    eval_timeout=None,
    reserve_timeout=120.0,
    fs=None,
):
    """Run ASHA with evaluations farmed to ``hyperopt-tpu-worker``
    processes over a :class:`FileJobQueue` directory.

    Args are :func:`hyperopt_tpu.hyperband.asha`'s, plus:

      dirpath: the queue directory workers serve (``python -m
        hyperopt_tpu.distributed.worker --dir DIR``).  The budget-aware
        ``Domain`` is (re)published to its attachments at entry.
      fs: injectable filesystem seam for the DRIVER side (see
        :mod:`.faults`); None uses the real ``os``.  Workers inject
        their own.
      inflight: concurrent jobs in the queue (the driver's slot count;
        actual parallelism is however many workers serve the mount).
      poll_interval: driver's BASE done-file poll cadence per slot;
        each slot backs off proportionally to its job's elapsed time
        (~10%, capped at >= 1 s), so short jobs are detected within
        ~poll_interval while long evaluations do not hammer the mount.
      eval_timeout: per-evaluation wall-clock bound; an expired job
        records as a failed evaluation (it keeps its queue files for
        post-mortem, but can never promote).
      reserve_timeout: stale-claim reaping age, as in the worker CLI --
        the driver reaps while polling, so a crashed worker's job
        returns to ``new/`` even if every other worker is busy.

    Returns the :func:`hyperband.asha` result dict; the scheduler's
    trial store is driver-side, the queue directory holds the transport
    record (every job's doc with owner/timings/tracebacks).
    """
    _reject_queue_backed_trials(trials, "asha_filequeue")
    queue = FileJobQueue(dirpath, fs=fs)
    # per-run attachment key: a queue directory shared with a live fmin
    # (or a previous asha run) keeps every driver's Domain intact --
    # each job doc's cmd names the one to evaluate with
    attachment_key = f"FMinIter_Domain.asha-{uuid.uuid4().hex[:8]}"
    domain = Domain(BudgetedDomainFn(fn), space)
    queue.attachments[attachment_key] = pickle.dumps(domain)

    def fetch(tid):
        done_path = os.path.join(queue.root, "done", f"{tid}.json")
        try:
            if not queue.fs.exists(done_path):
                return None
            return _read_json(done_path, fs=queue.fs)
        except (ValueError, OSError):
            return None  # mid-write on a non-atomic FS, or a transient
            # mount blip: retry next poll, but the driver's deadline
            # check still runs -- a file left permanently truncated by
            # a killed worker must not bypass eval_timeout

    transport = _TransportDriver(
        publish=queue.publish,
        fetch=fetch,
        reap=queue.reap,
        exp_key=exp_key,
        poll_interval=poll_interval,
        eval_timeout=eval_timeout,
        reserve_timeout=reserve_timeout,
        attachment_key=attachment_key,
    )
    try:
        return _run_asha(
            transport.evaluator, fn, space, max_budget, eta, min_budget,
            max_jobs, inflight, algo, trials, rstate, checkpoint,
            checkpoint_every,
        )
    finally:
        _cleanup_attachment(
            transport, lambda: queue.attachments.__delitem__(attachment_key)
        )


def _cleanup_attachment(transport, delete):
    """Run-scoped Domain blobs must not accumulate forever (one per
    asha run on a shared queue/database) -- delete on the way out,
    UNLESS any of this run's jobs may still be evaluated later: a
    timed-out job's queue entry, or jobs left published-but-uncollected
    by an aborted driver.  Deleting under those would turn them into
    dangling-attachment poison pills; such runs keep their blob."""
    if transport.expired or transport.collected < transport.published:
        logger.warning(
            "keeping Domain attachment %s: %d timed-out and %d "
            "uncollected job(s) may still be evaluated",
            transport.attachment_key, transport.expired,
            transport.published - transport.collected,
        )
        return
    try:
        delete()
    except KeyError:
        pass
    except Exception as e:  # graftlint: disable=GL302 cleanup must never mask the run's result
        logger.warning(
            "could not delete Domain attachment %s: %s",
            transport.attachment_key, e,
        )


def _reject_queue_backed_trials(trials, caller):
    """Both drivers need an IN-MEMORY scheduler store: an asynchronous
    Trials (FileTrials, MongoTrials, ThreadTrials, SparkTrials -- every
    store whose insert publishes or evaluates docs marks itself
    ``asynchronous``) would re-process each scheduler-recorded doc as a
    job, and workers would churn on budget-less garbage."""
    if trials is not None and getattr(trials, "asynchronous", False):
        raise ValueError(
            f"{caller} needs an in-memory Trials (or None) for trials=; "
            "queue-backed stores re-publish recorded docs as jobs"
        )


def _run_asha(evaluator, fn, space, max_budget, eta, min_budget,
              max_jobs, inflight, algo, trials, rstate, checkpoint,
              checkpoint_every):
    """One shared asha() invocation for every transport driver -- a new
    asha parameter threads through here once, not per transport."""
    from ..hyperband import asha

    return asha(
        fn,
        space,
        max_budget,
        eta=eta,
        min_budget=min_budget,
        max_jobs=max_jobs,
        workers=inflight,
        algo=algo,
        trials=trials,
        rstate=rstate,
        checkpoint=checkpoint,
        checkpoint_every=checkpoint_every,
        evaluator=evaluator,
    )


class _TransportDriver:
    """Driver-side transport shared by the filequeue and Mongo ASHA
    drivers: per-run tid namespacing (a resumed driver must never
    collide with a killed run's leftover jobs), trial-doc building,
    result polling with proportional backoff, and rate-limited reaping.

    ``publish(doc)`` enqueues a NEW job doc; ``fetch(tid)`` returns the
    completed doc (state DONE or ERROR) or None while in flight --
    transient read failures should surface as None so the deadline
    check still runs; ``reap(reserve_timeout)`` recycles stale claims.
    """

    # graftscope: publish/collect/expire accounting behind the
    # historic attribute names (asha_filequeue's cleanup decision and
    # its uncollected-jobs warning read these)
    published = CounterAttr(
        "asha_published_total", "jobs enqueued by this run")
    collected = CounterAttr(
        "asha_collected_total", "completed job docs collected")
    expired = CounterAttr(
        "asha_expired_total",
        "jobs that outran eval_timeout (may still be evaluated later)")

    def __init__(self, publish, fetch, reap, exp_key, poll_interval,
                 eval_timeout, reserve_timeout,
                 attachment_key="FMinIter_Domain"):
        self.metrics = MetricsRegistry("asha_queue")
        self._publish = publish
        self._fetch = fetch
        self._reap = reap
        self.exp_key = exp_key
        self.attachment_key = attachment_key
        self.poll_interval = float(poll_interval)
        self.eval_timeout = eval_timeout
        self.reserve_timeout = reserve_timeout
        self._run_tag = uuid.uuid4().hex[:8]
        self._counter = itertools.count()
        self._lock = threading.Lock()
        # reaping only matters on the reserve_timeout scale; one shared
        # rate limit keeps the polling slots from issuing full queue
        # scans every tick on a network mount / remote database
        self._reap_period = max(1.0, float(reserve_timeout or 0) / 10.0)
        self._last_reap = 0.0

    def _maybe_reap(self):
        with self._lock:
            now = time.monotonic()
            if now - self._last_reap < self._reap_period:
                return
            self._last_reap = now
        try:
            self._reap(self.reserve_timeout)
        except Exception as e:
            # reaping is periodic best-effort: a transient transport
            # failure that outlives the backend's own retries must not
            # kill a polling slot (and with it the whole run) -- the
            # next reap cycle sees the same stale claims.  Anything
            # non-transient is a real bug and surfaces.
            if not _common.is_transient(e):
                raise
            logger.warning("reap cycle skipped on transient failure: %s", e)

    def evaluator(self, vals, cfg, budget):
        """The :func:`hyperband.asha` ``evaluator=`` seam: one queued
        job per call, blocking until its result lands (or expires).
        ``cfg`` (the decoded config) is unused here -- workers decode
        from the doc's index-form vals themselves."""
        del cfg
        with self._lock:
            tid = f"{self._run_tag}-{next(self._counter)}"
            self.published += 1
        self._publish({
            "tid": tid,
            "state": JOB_STATE_NEW,
            "spec": None,
            "result": {"status": "new"},
            "misc": {
                "tid": tid,
                # the doc NAMES its Domain attachment (the reference's
                # cmd contract): drivers with different objectives can
                # share one queue/database without clobbering each other
                "cmd": ("domain_attachment", self.attachment_key),
                "workdir": None,
                "idxs": {k: [tid] for k in vals},
                # SONify: doc vals may be numpy scalars/0-d arrays and
                # transports serialize docs (JSON files / BSON)
                "vals": SONify({k: [v] for k, v in vals.items()}),
                "budget": SONify(budget),
            },
            "exp_key": self.exp_key,
            "owner": None,
            "version": 0,
            "book_time": None,
            "refresh_time": None,
        })
        deadline = (
            None if self.eval_timeout is None
            else time.monotonic() + self.eval_timeout
        )
        # proportional backoff per slot: poll at ~10% of the job's
        # elapsed time, floored at the responsive base cadence and
        # capped at 1 Hz -- short evaluations pay ~poll_interval of
        # detection latency while long (TPU-training-scale) ones stop
        # hammering the transport (total polls grow logarithmically,
        # then linearly at 1/s)
        published = time.monotonic()
        while True:
            out = self._fetch(tid)
            if out is not None:
                with self._lock:
                    self.collected += 1
                result = out.get("result") or {}
                if (
                    out.get("state") == JOB_STATE_DONE
                    and result.get("status") == STATUS_OK
                ):
                    return float(result["loss"])
                logger.warning(
                    "queued asha job %s failed: %s", tid,
                    (out.get("misc") or {}).get("error"),
                )
                return float("nan")
            if deadline is not None and time.monotonic() > deadline:
                logger.warning("queued asha job %s timed out", tid)
                with self._lock:
                    self.expired += 1
                return float("nan")
            self._maybe_reap()
            elapsed = time.monotonic() - published
            time.sleep(min(
                max(self.poll_interval, 0.1 * elapsed),
                max(self.poll_interval, 1.0),
            ))


def asha_mongo(
    fn,
    space,
    max_budget,
    mongo,
    eta=3,
    min_budget=1,
    max_jobs=81,
    inflight=8,
    algo=None,
    trials=None,
    rstate=None,
    checkpoint=None,
    checkpoint_every=1,
    exp_key=None,
    poll_interval=0.05,
    eval_timeout=None,
    reserve_timeout=120.0,
):
    """Run ASHA with evaluations farmed to ``hyperopt-tpu-mongo-worker``
    processes over the MongoDB protocol -- the same driver/worker split
    as :func:`asha_filequeue` on the reference's own transport
    (SURVEY.md SS3.4: CAS reservation via ``find_one_and_update``,
    GridFS Domain shipping).

    ``mongo`` is a connection string (``host:port/db``) or a live
    ``MongoJobs``.  The budget-aware ``Domain`` is (re)published to
    GridFS at entry; completed jobs are polled with ``find_one`` by
    tid.  All other args as :func:`asha_filequeue`.
    """
    from ..base import JOB_STATE_ERROR
    from .mongo import MongoJobs

    _reject_queue_backed_trials(trials, "asha_mongo")
    jobs = (
        mongo if isinstance(mongo, MongoJobs)
        else MongoJobs.new_from_connection_str(mongo)
    )
    try:
        # each poll is a find_one({tid, state}); on a real mongod only
        # _id is indexed by default, so every poll (and reserve's tid
        # sort) would scan the collection.  Doubles without
        # create_index just skip this.
        jobs.coll.create_index([("tid", 1), ("state", 1)])
    except AttributeError:
        pass
    # per-run attachment key (see asha_filequeue): a shared database's
    # concurrent fmin keeps ITS Domain; docs name which one to load
    attachment_key = f"FMinIter_Domain.asha-{uuid.uuid4().hex[:8]}"
    domain = Domain(BudgetedDomainFn(fn), space)
    jobs.set_attachment(attachment_key, pickle.dumps(domain))

    def fetch(tid):
        return jobs.coll.find_one({
            "tid": tid,
            "state": {"$in": [JOB_STATE_DONE, JOB_STATE_ERROR]},
        })

    transport = _TransportDriver(
        publish=jobs.publish,
        fetch=fetch,
        reap=jobs.reap,
        exp_key=exp_key,
        poll_interval=poll_interval,
        eval_timeout=eval_timeout,
        reserve_timeout=reserve_timeout,
        attachment_key=attachment_key,
    )
    try:
        return _run_asha(
            transport.evaluator, fn, space, max_budget, eta, min_budget,
            max_jobs, inflight, algo, trials, rstate, checkpoint,
            checkpoint_every,
        )
    finally:
        _cleanup_attachment(
            transport, lambda: jobs.delete_attachment(attachment_key)
        )


def asha_spark(
    fn,
    space,
    max_budget,
    spark=None,
    eta=3,
    min_budget=1,
    max_jobs=81,
    inflight=4,
    algo=None,
    trials=None,
    rstate=None,
    checkpoint=None,
    checkpoint_every=1,
):
    """Run ASHA with each evaluation dispatched as a 1-task Spark job --
    the :class:`~.spark.SparkTrials` execution model (SURVEY.md SS3.5)
    driven by the async scheduler.  Each in-flight slot submits
    ``fn(config, budget)`` through ``sc.parallelize([...], 1)`` under
    its own job group and blocks on ``collect``; promotion decisions
    never wait at a rung barrier, and up to ``inflight`` Spark jobs run
    concurrently (cluster parallelism is Spark's to schedule).

    Args as :func:`hyperband.asha`, plus ``spark``: a ``SparkSession``
    (default ``SparkSession.builder.getOrCreate()``).  ``fn`` ships to
    executors via Spark's closure serialization, the same contract as
    ``SparkTrials`` objectives; a task exception records as a failed
    evaluation that can never promote.  There is no ``eval_timeout``
    here -- bound task time with Spark's own scheduler configs, as the
    reference's SparkTrials users do.
    """
    from .spark import _require_pyspark, submit_one_task

    _reject_queue_backed_trials(trials, "asha_spark")
    if spark is None:
        pyspark = _require_pyspark()  # curated error names alternatives
        spark = pyspark.sql.SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    run_tag = uuid.uuid4().hex[:8]
    counter = itertools.count()
    counter_lock = threading.Lock()

    def evaluator(vals, cfg, budget):
        del vals  # the decoded cfg ships in the task closure
        with counter_lock:
            i = next(counter)

        def task(_):
            return fn(cfg, budget)

        # per-evaluation job group (observable in the Spark UI;
        # reliably cancellable under pinned threads -- see
        # submit_one_task), through the dispatch SparkTrials shares
        return submit_one_task(
            sc, task, f"hyperopt_tpu-asha-{run_tag}-{i}",
            f"asha eval {i} (budget {budget})",
        )  # float or {"loss": ...}; asha normalizes

    return _run_asha(
        evaluator, fn, space, max_budget, eta, min_budget, max_jobs,
        inflight, algo, trials, rstate, checkpoint, checkpoint_every,
    )
