"""Distributed / asynchronous execution backends (layer L5).

Capability parity with the reference's trial-level task farming
(SURVEY.md SS2 rows 'Mongo backend' / 'Spark backend', SS3.4-3.5), built
for the environments a TPU framework actually runs in:

``threads``   -- ``ThreadTrials``: in-process thread-pool evaluation with a
                 parallelism cap, timeout and cancellation (the SparkTrials
                 control-flow without a Spark dependency).
``filequeue`` -- ``FileTrials`` + ``hyperopt-tpu-worker``: a shared-
                 filesystem job queue with atomic (rename-based) job
                 reservation, reserve-timeout reaping, pickled-Domain
                 shipping and ERROR-state capture -- the MongoDB work-queue
                 role on the NFS/GCS-FUSE mounts TPU pods already have.
``asha_queue``-- the ASHA scheduler driving every execution backend:
                 ``asha_filequeue`` (jobs to ``hyperopt-tpu-worker``
                 processes over the shared-FS queue), ``asha_mongo``
                 (the MongoDB protocol itself), and ``asha_spark``
                 (each evaluation a 1-task Spark job).  Budget rides
                 the trial doc; the pickled ``BudgetedDomainFn`` hands
                 it to the objective; per-run Domain attachment keys
                 let the drivers share a queue/database with fmin.
``mongo``     -- ``MongoTrials``: the reference's MongoDB protocol (CAS
                 reservation via find_one_and_modify, GridFS attachments);
                 requires pymongo, import-gated.
``spark``     -- ``SparkTrials``: dispatcher-thread + one-task Spark jobs;
                 requires pyspark, import-gated.
``faults``    -- ``FaultPlan``: seeded deterministic fault injection for
                 the filesystem seam the queue/worker stack runs on
                 (transient errno faults, latency, partial writes, named
                 crash points) -- the chaos suite's substrate.
``fsck``      -- recovery audit/repair for a queue directory
                 (``python -m hyperopt_tpu.distributed.fsck --dir D``).
"""

from .threads import ThreadTrials
from .filequeue import FileTrials, FileJobQueue
from .faults import FaultPlan, REAL_FS

__all__ = [
    "ThreadTrials", "FileTrials", "FileJobQueue", "FaultPlan", "REAL_FS",
    "asha_filequeue", "asha_mongo", "asha_spark", "BudgetedDomainFn",
]


def __getattr__(name):
    import importlib

    if name in ("fsck",):
        mod = importlib.import_module(".fsck", __name__)
        globals()["fsck"] = mod
        return mod
    if name in (
        "asha_queue", "asha_filequeue", "asha_mongo", "asha_spark",
        "BudgetedDomainFn",
    ):
        # lazy: pulls in hyperband (and its numpy graph machinery) only
        # when the ASHA-over-queue driver is actually used
        mod = importlib.import_module(".asha_queue", __name__)
        globals()["asha_queue"] = mod
        return mod if name == "asha_queue" else getattr(mod, name)
    if name in ("mongo", "MongoTrials"):
        mod = importlib.import_module(".mongo", __name__)
        globals()["mongo"] = mod
        return mod if name == "mongo" else mod.MongoTrials
    if name in ("spark", "SparkTrials"):
        mod = importlib.import_module(".spark", __name__)
        globals()["spark"] = mod
        return mod if name == "spark" else mod.SparkTrials
    raise AttributeError(name)
