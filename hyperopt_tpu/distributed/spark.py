"""SparkTrials: one Spark task per trial.

Capability parity with the reference's ``hyperopt/spark.py`` (SURVEY.md
SS3.5): an fmin dispatcher launches each trial as a 1-task Spark job in
its own thread (<= ``parallelism`` in flight), cancels via job groups on
timeout, and posts results back into the driver-side store under a lock.
Requires ``pyspark`` (not bundled in the TPU image) -- import-gated; the
same dispatch control-flow runs dependency-free in
:class:`hyperopt_tpu.distributed.ThreadTrials`.  Executed coverage:
``tests/test_mongo_spark.py`` drives THIS module end-to-end (dispatcher
threads, 1-task jobs, timeout cancellation via job groups, error
writeback) over an in-memory SparkSession double.
"""

from __future__ import annotations

import logging
import threading
import timeit

from ..base import (
    Ctrl,
    JOB_STATE_CANCEL,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    Trials,
    spec_from_misc,
)
from ..utils import coarse_utcnow

logger = logging.getLogger(__name__)

__all__ = ["SparkTrials"]


def _require_pyspark():
    try:
        import pyspark

        return pyspark
    except ImportError as e:
        raise ImportError(
            "SparkTrials requires pyspark, which is not installed in this "
            "environment. ThreadTrials provides the same dispatch semantics "
            "in-process; FileTrials + hyperopt-tpu-worker scales across "
            "hosts on a shared filesystem."
        ) from e


def _spark_supports_job_cancelling(sc):
    return hasattr(sc, "cancelJobGroup")


def submit_one_task(sc, task, group, description, interrupt=True):
    """Run ``task`` as a single-task Spark job under ``group`` and
    return its result -- the 1-trial dispatch idiom shared by
    ``SparkTrials`` and ``asha_spark`` (one definition, so fixes to the
    dispatch cannot drift).  The job group is set only when the context
    supports cancellation (the same capability gate SparkTrials uses).

    NOTE on concurrency: job groups are per-JVM-thread; without
    PySpark pinned-thread mode (``PYSPARK_PIN_THREAD``, default on
    since Spark 3.2) concurrent driver threads can attach a group to
    the wrong job, so external per-group cancellation is only reliable
    under pinned threads."""
    if _spark_supports_job_cancelling(sc):
        sc.setJobGroup(group, description, interrupt)
    [result] = sc.parallelize([0], 1).map(task).collect()
    return result


class SparkTrials(Trials):
    """Trials whose evaluation fans out as single-task Spark jobs."""

    asynchronous = True

    def __init__(self, parallelism=None, timeout=None, spark_session=None,
                 exp_key=None, refresh=True):
        pyspark = _require_pyspark()
        if spark_session is None:
            spark_session = pyspark.sql.SparkSession.builder.getOrCreate()
        self._spark = spark_session
        self._sc = spark_session.sparkContext
        default_par = max(self._sc.defaultParallelism, 1)
        self.parallelism = int(parallelism) if parallelism else default_par
        self.timeout = timeout
        self._lock = threading.RLock()
        self._inflight = {}
        self._fmin_cancelled = False
        self._fmin_cancelled_reason = None
        self._start_time = None
        self._supports_cancel = _spark_supports_job_cancelling(self._sc)
        super().__init__(exp_key=exp_key, refresh=refresh)

    # -- bookkeeping under the lock (SS3.5: 'results posted back under a
    # lock; refresh() on driver') ------------------------------------------
    def refresh(self):
        with self._lock:
            super().refresh()

    def insert_trial_docs(self, docs):
        with self._lock:
            return super().insert_trial_docs(docs)

    def _timed_out(self):
        return (
            self.timeout is not None
            and self._start_time is not None
            and timeit.default_timer() - self._start_time >= self.timeout
        )

    def _job_group(self, trial):
        return f"hyperopt_tpu-trial-{trial['tid']}"

    def _run_trial_async(self, trial, domain):
        """One dispatcher thread: run the trial as a 1-task Spark job."""
        sc = self._sc
        group = self._job_group(trial)
        spec = spec_from_misc(trial["misc"])

        def task(_):
            ctrl = Ctrl(None, current_trial=None)
            return domain.evaluate(spec, ctrl, attach_attachments=False)

        try:
            result = submit_one_task(
                sc, task, group, f"trial {trial['tid']}", True
            )
        except Exception as e:  # graftlint: disable=GL302 task failure becomes an ERROR doc
            with self._lock:
                if trial["state"] == JOB_STATE_RUNNING:
                    trial["state"] = JOB_STATE_ERROR
                    trial["misc"]["error"] = (str(type(e)), str(e))
                    trial["refresh_time"] = coarse_utcnow()
        else:
            with self._lock:
                trial["state"] = JOB_STATE_DONE
                trial["result"] = result
                trial["refresh_time"] = coarse_utcnow()
        finally:
            with self._lock:
                self._inflight.pop(trial["tid"], None)

    def _dispatch_new(self, domain):
        with self._lock:
            if self._timed_out():
                if not self._fmin_cancelled:
                    self._fmin_cancelled = True
                    self._fmin_cancelled_reason = "fmin run timeout"
                for tid, (th, trial) in list(self._inflight.items()):
                    if self._supports_cancel:
                        self._sc.cancelJobGroup(self._job_group(trial))
                for t in self._dynamic_trials:
                    if t["state"] == JOB_STATE_NEW:
                        t["state"] = JOB_STATE_CANCEL
                return
            for t in self._dynamic_trials:
                if len(self._inflight) >= self.parallelism:
                    break
                if t["state"] != JOB_STATE_NEW:
                    continue
                t["state"] = JOB_STATE_RUNNING
                t["book_time"] = coarse_utcnow()
                t["owner"] = "spark"
                th = threading.Thread(
                    target=self._run_trial_async, args=(t, domain), daemon=True
                )
                self._inflight[t["tid"]] = (th, t)
                th.start()

    def count_by_state_unsynced(self, arg):
        domain = getattr(self, "_domain", None)
        if domain is not None:
            self._dispatch_new(domain)
        with self._lock:
            return super().count_by_state_unsynced(arg)

    def fmin(self, fn, space, algo=None, max_evals=None, **kwargs):
        from ..base import Domain
        from ..fmin import fmin as _fmin

        kwargs.pop("allow_trials_fmin", None)
        timeout = kwargs.pop("timeout", None)
        if timeout is not None:
            self.timeout = timeout
        # under the lock (GL501): the dispatcher threads' lock domain
        # owns the cancellation flag (same fix as ThreadTrials.fmin)
        with self._lock:
            self._start_time = timeit.default_timer()
            self._fmin_cancelled = False
        pass_expr_memo_ctrl = kwargs.pop("pass_expr_memo_ctrl", None)
        self._domain = Domain(fn, space, pass_expr_memo_ctrl=pass_expr_memo_ctrl)
        kwargs.setdefault("max_queue_len", self.parallelism)
        return _fmin(
            fn, space, algo=algo, max_evals=max_evals, trials=self,
            allow_trials_fmin=False, timeout=self.timeout,
            pass_expr_memo_ctrl=pass_expr_memo_ctrl, **kwargs,
        )
