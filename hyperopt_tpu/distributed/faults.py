"""Deterministic fault injection for the distributed layer.

The filequeue/worker stack promises graceful degradation under flaky
NFS mounts, mid-CAS crashes and SIGTERM -- this module makes those
promises *testable* without monkeypatching.  Every filesystem primitive
the queue touches (open/read/write, rename, utime, stat, listdir,
unlink, fsync) goes through an injectable ``fs`` seam:

* :data:`REAL_FS` -- the default, a thin ``os`` passthrough whose named
  crash points are no-ops (zero overhead in production);
* :class:`FaultPlan` + :meth:`FaultPlan.fs` -- a *seeded, deterministic*
  injector: transient errno faults (ESTALE/EIO/... at a configurable
  rate, burst-bounded so retries always converge), bounded latency,
  partial writes, and one-shot **named crash points** -- e.g.
  ``after_claim_utime_before_rename`` -- that raise
  :class:`SimulatedCrash` at exactly the protocol step a real worker
  could die at.

``FileJobQueue(root, fs=plan.fs())`` (and ``FileTrials(..., fs=...)``,
``asha_filequeue(..., fs=...)``) inject it; ``tests/test_chaos.py``
replays seeded plans against live queue+worker stacks and asserts no
job is ever lost or double-completed.

Named crash points wired into the queue/worker protocol::

    after_publish_tmp_before_rename    publish():   tmp written, not yet in new/
    after_claim_utime_before_rename    reserve():   mtime refreshed, CAS rename pending
    after_claim_rename_before_write    reserve():   claim renamed, doc not yet normalized
    after_done_tmp_before_rename       complete():  result tmp fsynced, not yet in done/
    after_done_rename_before_unlink    complete():  DONE published, claim not yet released
    after_unreserve_utime_before_rename unreserve(): give-back rename pending
    after_reap_utime_before_rename     reap():      recycle rename pending
    after_attach_fsync_before_rename   attachments: blob tmp fsynced, not yet visible
    before_complete                    worker:      evaluated, result not yet published
"""

from __future__ import annotations

import collections
import errno
import logging
import os
import random
import threading
import time
import zlib

logger = logging.getLogger(__name__)

__all__ = [
    "REAL_FS", "RealFS", "FaultPlan", "FaultyFS", "SimulatedCrash",
    "DeviceFaultPlan", "NetFaultPlan", "FaultyWire",
    "CRASH_POINTS", "DRIVER_CRASH_POINTS", "SERVE_CRASH_POINTS",
    "DEVICE_LOOP_CRASH_POINTS", "FLEET_CRASH_POINTS",
    "OBS_CRASH_POINTS", "PILOT_CRASH_POINTS", "NET_CRASH_POINTS",
    "ALL_CRASH_POINTS",
]

#: every named crash point the QUEUE protocol code declares (see module
#: docstring) -- the chaos suite iterates this so a new crash point
#: cannot be added without being exercised.
CRASH_POINTS = (
    "after_publish_tmp_before_rename",
    "after_claim_utime_before_rename",
    "after_claim_rename_before_write",
    "after_done_tmp_before_rename",
    "after_done_rename_before_unlink",
    "after_unreserve_utime_before_rename",
    "after_reap_utime_before_rename",
    "after_attach_fsync_before_rename",
    "before_complete",
)

#: crash points of the sequential DRIVER's recovery protocol (fmin's
#: write-ahead tell log + checkpoint bundles -- utils/wal.py,
#: utils/checkpoint.DriverRecovery).  The resume-parity suite
#: (tests/test_resume_parity.py) iterates this tuple the same way the
#: distributed chaos suite iterates :data:`CRASH_POINTS`::
#:
#:     before_wal_append            evaluated/asked, record not yet durable
#:     after_wal_append_before_tell record durable, tell not yet applied
#:     after_tell_before_ask_ahead  tell applied, pre-dispatch handoff pending
#:     after_ckpt_tmp_before_rename bundle tmp fsynced, not yet published
#:     after_ckpt_publish_before_wal_reset  bundle live, WAL not compacted
DRIVER_CRASH_POINTS = (
    "before_wal_append",
    "after_wal_append_before_tell",
    "after_tell_before_ask_ahead",
    "after_ckpt_tmp_before_rename",
    "after_ckpt_publish_before_wal_reset",
)

#: crash points of the multi-tenant suggestion SERVICE's batching loop
#: (hyperopt_tpu/serve): the scheduler coalesces many studies' tells and
#: asks into one device dispatch, so its crash windows sit between the
#: per-study WAL appends and the shared batch.  The serve chaos suite
#: (tests/test_serve_chaos.py) iterates this tuple the way the driver
#: suite iterates :data:`DRIVER_CRASH_POINTS`::
#:
#:     serve_after_wal_before_dispatch  tell durable in the study WAL,
#:                                      batch not yet dispatched
#:     serve_mid_batch                  batch assembled, device program
#:                                      not yet dispatched
#:     serve_after_dispatch_before_ack  device state committed, clients
#:                                      not yet acked / served records
#:                                      not yet logged
#:     serve_group_commit_after_flush_before_barrier
#:                                      the round's tells are flushed
#:                                      (kernel-visible, process-crash
#:                                      safe) but the group-commit
#:                                      round barrier has not fsynced
#:                                      yet -- a kill here loses only
#:                                      what a machine crash could tear,
#:                                      and replay restores exactly the
#:                                      flushed prefix with zero dupes
SERVE_CRASH_POINTS = (
    "serve_after_wal_before_dispatch",
    "serve_mid_batch",
    "serve_after_dispatch_before_ack",
    "serve_group_commit_after_flush_before_barrier",
)

#: crash points of the CHUNKED device loop's host loop
#: (``device_loop.compile_fmin(chunk_size=..., checkpoint_path=...)``):
#: the on-device experiment dispatches chunk by chunk and publishes a
#: durable carry bundle at the checkpoint cadence, so its crash windows
#: sit between a finished chunk and its bundle.  The device-loop resume
#: suite (tests/test_device_loop_chunked.py) iterates this tuple at
#: EVERY chunk boundary::
#:
#:     device_loop_after_chunk_before_ckpt   chunk dispatched, carry not
#:                                           yet durable (resume replays
#:                                           the chunk from the previous
#:                                           bundle)
#:     device_loop_after_ckpt_before_next_chunk  bundle published, next
#:                                           chunk not yet dispatched
#:
#: (the bundle publish itself rides ``durable_pickle``'s existing
#: ``after_ckpt_tmp_before_rename`` torn-publish window.)
DEVICE_LOOP_CRASH_POINTS = (
    "device_loop_after_chunk_before_ckpt",
    "device_loop_after_ckpt_before_next_chunk",
)

#: crash points of the horizontal serve FLEET (hyperopt_tpu/serve/
#: fleet.py + router.py): replica death rides the existing
#: ``serve_mid_batch`` point armed on THAT replica's plan; the fleet
#: adds the windows the single-process serve stack cannot have.  The
#: fleet chaos suite (``tests/test_fleet_chaos.py``) iterates these::
#:
#:     fleet_router_after_forward_before_ack   the replica executed the
#:                                             op (tell durable / ask
#:                                             served), the router died
#:                                             before acking the client
#:                                             -- retried idempotently
#:                                             (tid-dedup / recover-ask)
#:     fleet_migrate_after_snapshot_before_handoff  drain migration:
#:                                             snapshot published, the
#:                                             source still owns the
#:                                             study (migration aborts,
#:                                             source keeps serving)
#:     fleet_migrate_after_handoff_before_restore   drain migration:
#:                                             source released its
#:                                             claim, target not yet
#:                                             restored (the router
#:                                             lazily adopts on the
#:                                             ring owner)
#:     fleet_claim_tmp_before_rename           claim publish: the temp
#:                                             claim doc is fsynced but
#:                                             the rename never landed
#:                                             -- the old claim (or no
#:                                             claim) stays visible and
#:                                             a re-acquire wins cleanly;
#:                                             the orphan ``.tmp.<pid>``
#:                                             is fsck's to sweep
FLEET_CRASH_POINTS = (
    "fleet_router_after_forward_before_ack",
    "fleet_migrate_after_snapshot_before_handoff",
    "fleet_migrate_after_handoff_before_restore",
    "fleet_claim_tmp_before_rename",
)

#: crash point of graftscope's flight-recorder export (hyperopt_tpu/
#: obs/flightrec.py): fires MID-RECORD, leaving a torn final line --
#: exactly the state a machine crash produces -- which
#: ``hyperopt-tpu-fsck --obs`` truncates and the recorder's scan rule
#: skips.  tests/test_obs.py proves the log recoverable and the spans
#: before the tear intact.
OBS_CRASH_POINTS = (
    "obs_flight_export_mid_append",
)

#: crash points of the graftpilot autoscaler (hyperopt_tpu/serve/
#: pilot.py): the controller is just another process that can die, and
#: both windows must leave the fleet in a state the ordinary heal
#: paths repair.  tests/test_pilot_chaos.py iterates these::
#:
#:     pilot_after_decision_before_actuate     the decision span is
#:                                             recorded, no fleet
#:                                             primitive has run -- a
#:                                             restarted pilot simply
#:                                             re-scrapes and re-decides
#:                                             (decisions are stateless
#:                                             functions of the metrics)
#:     pilot_mid_scale_out                     fired on the FLEET's
#:                                             plan inside
#:                                             ``add_replica``'s
#:                                             migration loop: the ring
#:                                             already includes the new
#:                                             replica but only some
#:                                             remapped studies moved --
#:                                             the rest heal via the
#:                                             lazy-adoption path
#:                                             (``create_study(
#:                                             takeover=True)`` on first
#:                                             routed request)
PILOT_CRASH_POINTS = (
    "pilot_after_decision_before_actuate",
    "pilot_mid_scale_out",
)

from .netfaults import (  # noqa: E402 -- re-exported alongside FaultPlan
    NET_CRASH_POINTS, NetFaultPlan, FaultyWire,
)

ALL_CRASH_POINTS = (
    CRASH_POINTS + DRIVER_CRASH_POINTS + SERVE_CRASH_POINTS
    + DEVICE_LOOP_CRASH_POINTS + FLEET_CRASH_POINTS + OBS_CRASH_POINTS
    + PILOT_CRASH_POINTS + NET_CRASH_POINTS
)

#: the transient errno mix a flaky mount produces; FileNotFoundError
#: (ENOENT) may be added to a plan's ``errors`` to simulate NFS
#: visibility lag -- the protocol treats it as a lost race and retries
#: at the job level, so nothing is ever deleted on its account.
DEFAULT_ERRORS = (errno.ESTALE, errno.EIO)


class SimulatedCrash(BaseException):
    """Simulated process death at a named crash point.

    A ``BaseException`` deliberately: the worker's evaluation-error
    capture (``except Exception``) must not swallow a simulated crash
    into an ERROR doc -- a dead process publishes nothing.  Chaos
    harnesses catch it at the top of their worker loop and carry on as
    a restarted worker would.
    """

    def __init__(self, point):
        super().__init__(f"simulated crash at {point}")
        self.point = point


class RealFS:
    """The default seam: ``os`` passthrough, no-op crash points."""

    def open(self, path, mode="r"):
        return open(path, mode)

    def rename(self, src, dst):
        os.rename(src, dst)

    def utime(self, path, times=None):
        os.utime(path, times)

    def stat(self, path):
        return os.stat(path)

    def getmtime(self, path):
        return os.path.getmtime(path)

    def listdir(self, path):
        return os.listdir(path)

    def unlink(self, path):
        os.unlink(path)

    def exists(self, path):
        return os.path.exists(path)

    def makedirs(self, path, exist_ok=True):
        os.makedirs(path, exist_ok=exist_ok)

    def fsync(self, f):
        f.flush()
        os.fsync(f.fileno())

    def crashpoint(self, name):
        pass


REAL_FS = RealFS()


class DeviceFaultPlan:
    """A seeded, deterministic schedule of DEVICE faults for the serve
    dispatch path -- the accelerator-side twin of the fs primitives
    below, injected through the scheduler's ``fs=`` seam (``FaultPlan(
    device=DeviceFaultPlan(...))``), never by monkeypatching.

    Three fault classes, all keyed to the scheduler's own dispatch
    ordinal so a same-seed replay injects identically:

    * **NaN corruption** (``nan_study`` + ``nan_at`` / ``nan_count``):
      from the ``nan_at``-th dispatch on, the named tenant's batched
      step output columns are overwritten with NaN -- the poisoned-slot
      signal graftguard's fused finite-check must catch without
      disturbing sibling slots.  ``nan_count=None`` poisons every
      dispatch (a deterministically bad tenant, driving K-trip
      eviction); ``nan_count=n`` poisons only the first ``n`` hits (a
      transient device fault the re-materialization path absorbs).
    * **Hang** (``hang_at`` + ``hang_s``): the ``hang_at``-th dispatch
      sleeps ``hang_s`` seconds inside the dispatch closure -- armed
      past the scheduler's watchdog deadline it simulates a wedged
      device; one-shot.
    * **Raises** (``raise_rate`` + ``burst``): each dispatch raises
      :class:`~hyperopt_tpu.exceptions.TransientBackendError` with the
      given probability, burst-bounded to ``burst`` CONSECUTIVE raises
      so the watchdog's retry-once always converges at ``burst=1``.
      ``fatal_at`` instead raises a plain ``RuntimeError`` at that
      ordinal -- the deterministic-program-bug case ``is_transient``
      must classify as NOT worth retrying.
    """

    def __init__(self, seed=0, nan_study=None, nan_at=1, nan_count=None,
                 hang_at=None, hang_s=0.2, raise_rate=0.0, burst=1,
                 fatal_at=None):
        self.seed = int(seed)
        self.nan_study = nan_study
        self.nan_at = int(nan_at)
        self.nan_count = None if nan_count is None else int(nan_count)
        self.hang_at = None if hang_at is None else int(hang_at)
        self.hang_s = min(float(hang_s), 0.5)  # chaos-suite time budget
        self.raise_rate = float(raise_rate)
        self.burst = int(burst)
        self.fatal_at = None if fatal_at is None else int(fatal_at)
        self._rng = random.Random(self.seed)
        self._lock = threading.RLock()
        self._ordinal = 0
        self._raise_streak = 0
        self._nan_hits = 0
        self.stats = collections.Counter()
        self.log = []

    def on_dispatch(self):
        """Called inside the dispatch closure, before the device
        program runs: may sleep (hang) or raise (injected dispatch
        fault).  One RNG draw per call when ``raise_rate`` is set, so
        the schedule is a pure function of the dispatch sequence."""
        from ..exceptions import TransientBackendError

        with self._lock:
            self._ordinal += 1
            ordinal = self._ordinal
            hang = self.hang_at is not None and ordinal == self.hang_at
            fatal = self.fatal_at is not None and ordinal == self.fatal_at
            raise_now = False
            if self.raise_rate:
                roll = self._rng.random() < self.raise_rate
                if roll and self._raise_streak < self.burst:
                    self._raise_streak += 1
                    raise_now = True
                else:
                    self._raise_streak = 0
            if hang:
                self.stats["device:hang"] += 1
                self.log.append(("dispatch", ordinal, "hang"))
            elif fatal:
                self.stats["device:fatal"] += 1
                self.log.append(("dispatch", ordinal, "fatal"))
            elif raise_now:
                self.stats["device:raise"] += 1
                self.log.append(("dispatch", ordinal, "raise"))
            else:
                self.log.append(("dispatch", ordinal, "ok"))
        if hang:
            time.sleep(self.hang_s)
        if fatal:
            raise RuntimeError(
                f"injected deterministic program bug at dispatch {ordinal}"
            )
        if raise_now:
            raise TransientBackendError(
                f"injected transient device fault at dispatch {ordinal}"
            )

    def corrupt_outputs(self, new_v, slot_of):
        """NaN-poison the named tenant's suggestion columns in the
        fetched batched-step output (``new_v`` is the host ``[S, D,
        batch]`` array, ``slot_of`` maps study name -> slot index).
        Mutates in place; sibling slots are never touched."""
        if self.nan_study is None or self.nan_study not in slot_of:
            return
        with self._lock:
            if self._ordinal < self.nan_at:
                return
            if self.nan_count is not None and self._nan_hits >= self.nan_count:
                return
            self._nan_hits += 1
            self.stats["device:nan"] += 1
            self.log.append(("corrupt", self._ordinal, self.nan_study))
        new_v[slot_of[self.nan_study]] = float("nan")


class FaultPlan:
    """A seeded, deterministic schedule of faults.

    One plan = one RNG stream: with a fixed seed and a fixed sequence
    of filesystem operations, the injected faults are identical run to
    run (``self.log`` records every decision for trace-equality
    assertions).  Concurrency note: give each simulated worker its own
    :meth:`split` plan -- decisions then depend only on that worker's
    own operation sequence, not on thread interleaving.

    Parameters:
      seed:    RNG seed (determinism anchor).
      rate:    probability of injecting a transient error per fs call.
      errors:  errno pool drawn from (``OSError(errno, ...)`` picks the
               matching subclass, so ENOENT raises FileNotFoundError).
      latency: max injected delay per call, seconds (capped at 50 ms --
               the chaos suite's no-real-sleeps budget).
      partial_rate: probability a file opened for writing fails midway
               with EIO after writing only a prefix (the torn-write
               case tmp+rename protocols must survive).
      burst:   max *consecutive* injected failures per (op, file) key;
               bounds the adversary so a retry loop of ``burst + 1``
               attempts always converges.  ``None`` = unbounded.
      ops:     restrict error injection to these op names (None = all).
      device:  an optional :class:`DeviceFaultPlan` riding along -- the
               serve scheduler discovers it through its ``fs=`` seam
               (``fs.plan.device``) and injects the device-side faults
               at dispatch time.
    """

    def __init__(self, seed=0, rate=0.0, errors=DEFAULT_ERRORS,
                 latency=0.0, partial_rate=0.0, burst=2, ops=None,
                 device=None):
        self.seed = seed
        self.rate = float(rate)
        self.errors = tuple(errors)
        self.latency = min(float(latency), 0.05)
        self.partial_rate = float(partial_rate)
        self.burst = burst
        self.ops = None if ops is None else frozenset(ops)
        self.device = device
        self._rng = random.Random(seed)
        self._lock = threading.RLock()
        self._consecutive = {}
        self._crash = {}
        self.log = []
        self.stats = collections.Counter()

    def split(self, name):
        """A derived plan with the same fault profile and a stably
        derived seed (crc32, not ``hash()`` -- PYTHONHASHSEED must not
        leak into the schedule).  Crash points and the device-fault
        plan are NOT inherited: arm them on exactly the plan whose
        actor should die (or whose dispatches should misbehave)."""
        child_seed = zlib.crc32(f"{self.seed}/{name}".encode())
        return FaultPlan(
            seed=child_seed, rate=self.rate, errors=self.errors,
            latency=self.latency, partial_rate=self.partial_rate,
            burst=self.burst, ops=self.ops,
        )

    def fs(self):
        """An injectable filesystem bound to this plan."""
        return FaultyFS(self)

    def arm(self, point, at=1):
        """Arm a one-shot crash at the ``at``-th hit of ``point``."""
        if point not in ALL_CRASH_POINTS:
            raise ValueError(f"unknown crash point {point!r}")
        with self._lock:
            self._crash[point] = int(at)
        return self

    # -- decision engine (called by FaultyFS) ------------------------------
    def _decide_error(self, op, key):
        with self._lock:
            if not self.rate or (self.ops is not None and op not in self.ops):
                self.log.append((op, key, "ok"))
                return None
            k = (op, key)
            streak = self._consecutive.get(k, 0)
            allowed = self.burst is None or streak < self.burst
            if allowed and self._rng.random() < self.rate:
                self._consecutive[k] = streak + 1
                err = self._rng.choice(self.errors)
                self.log.append((op, key, f"errno={err}"))
                self.stats[f"error:{op}"] += 1
                return err
            self._consecutive[k] = 0
            self.log.append((op, key, "ok"))
            return None

    def _decide_partial(self, key, size_hint=256):
        """None, or the byte offset at which a write handle dies."""
        with self._lock:
            if not self.partial_rate:
                return None
            k = ("write", key)
            streak = self._consecutive.get(k, 0)
            allowed = self.burst is None or streak < self.burst
            if allowed and self._rng.random() < self.partial_rate:
                self._consecutive[k] = streak + 1
                cut = self._rng.randrange(0, size_hint)
                self.log.append(("write", key, f"partial@{cut}"))
                self.stats["error:partial_write"] += 1
                return cut
            self._consecutive[k] = 0
            return None

    def _decide_latency(self):
        if not self.latency:
            return 0.0
        with self._lock:
            return self._rng.uniform(0.0, self.latency)

    def fire_crashpoint(self, name):
        with self._lock:
            if name not in self._crash:
                return
            self._crash[name] -= 1
            if self._crash[name] > 0:
                return
            del self._crash[name]
            self.log.append(("crash", name, "fired"))
            self.stats[f"crash:{name}"] += 1
        raise SimulatedCrash(name)


class _FaultyWriteFile:
    """Write-handle proxy that may die mid-stream: writes a prefix up
    to the plan-chosen offset, then raises EIO -- exactly the torn
    write the tmp+fsync+rename protocol exists to survive."""

    def __init__(self, f, fail_at):
        self._f = f
        self._fail_at = fail_at
        self._written = 0

    def write(self, data):
        if self._fail_at is not None:
            budget = self._fail_at - self._written
            if len(data) >= budget:
                if budget > 0:
                    self._f.write(data[:budget])
                    self._written += budget
                self._fail_at = None  # one torn write per handle
                raise OSError(errno.EIO, "injected partial write")
        self._f.write(data)
        self._written += len(data)
        return len(data)

    def flush(self):
        self._f.flush()

    def fileno(self):
        return self._f.fileno()

    def close(self):
        self._f.close()

    @property
    def name(self):
        return self._f.name

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._f.close()
        return False


class FaultyFS:
    """The injectable filesystem: every primitive consults the plan for
    an error / latency decision before delegating to ``os``; crash
    points raise :class:`SimulatedCrash` when armed.  API-compatible
    with :class:`RealFS`, so product code is injection-agnostic."""

    def __init__(self, plan):
        self.plan = plan

    def _gate(self, op, path):
        delay = self.plan._decide_latency()
        if delay:
            time.sleep(delay)
        err = self.plan._decide_error(op, os.path.basename(str(path)))
        if err is not None:
            raise OSError(err, f"injected {errno.errorcode.get(err, err)}",
                          str(path))

    def open(self, path, mode="r"):
        self._gate("open", path)
        f = open(path, mode)
        if any(c in mode for c in "wxa+"):
            fail_at = self.plan._decide_partial(os.path.basename(str(path)))
            if fail_at is not None:
                return _FaultyWriteFile(f, fail_at)
        return f

    def rename(self, src, dst):
        self._gate("rename", src)
        os.rename(src, dst)

    def utime(self, path, times=None):
        self._gate("utime", path)
        os.utime(path, times)

    def stat(self, path):
        self._gate("stat", path)
        return os.stat(path)

    def getmtime(self, path):
        self._gate("stat", path)
        return os.path.getmtime(path)

    def listdir(self, path):
        self._gate("listdir", path)
        return os.listdir(path)

    def unlink(self, path):
        self._gate("unlink", path)
        os.unlink(path)

    def exists(self, path):
        self._gate("stat", path)
        return os.path.exists(path)

    def makedirs(self, path, exist_ok=True):
        os.makedirs(path, exist_ok=exist_ok)

    def fsync(self, f):
        self._gate("fsync", getattr(f, "name", "?"))
        f.flush()
        os.fsync(f.fileno())

    def crashpoint(self, name):
        self.plan.fire_crashpoint(name)
