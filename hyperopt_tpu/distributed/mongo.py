"""MongoTrials: the reference's MongoDB work-queue protocol.

Capability parity with ``hyperopt/mongoexp.py`` (SURVEY.md SS2/SS3.4):
trials collection as the queue, atomic NEW->RUNNING reservation via a
compare-and-swap ``find_one_and_update`` on ``owner``, pickled Domain in
GridFS, DONE/ERROR result writeback, reserve-timeout reaping and exp_key
namespacing.  Requires ``pymongo`` (not bundled in the TPU image) -- all
imports are gated; :class:`hyperopt_tpu.distributed.FileTrials` provides
the same role on a shared filesystem without extra dependencies and is the
recommended backend on TPU pods.

Executed coverage: ``tests/test_mongo_spark.py`` runs this module's real
protocol code (reserve CAS under thread contention AND across real
worker PROCESSES, reaping, GridFS domain shipping, full async fmin with
worker threads and with ``main_worker`` subprocesses, the CLI loop)
against pymongo/gridfs doubles implementing exactly the client surface
used here -- in-memory for thread-level tests, file-backed (O_EXCL lock
+ atomic replace) for cross-process contention -- plus an import-gated
real-mongod test that activates wherever ``mongod`` exists: the
reference's real-mongod strategy (SURVEY.md SS4) adapted to this image.
"""

from __future__ import annotations

import logging
import pickle
import uuid

from ..base import (
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    Ctrl,
    SONify,
    Trials,
    spec_from_misc,
)
from ..utils import coarse_utcnow
from . import _common

logger = logging.getLogger(__name__)

__all__ = ["MongoTrials", "MongoJobs", "MongoWorker", "as_mongo_str", "main_worker"]


def _require_pymongo():
    try:
        import pymongo  # noqa: F401
        import gridfs  # noqa: F401

        return pymongo
    except ImportError as e:
        raise ImportError(
            "MongoTrials requires pymongo, which is not installed in this "
            "environment. Use hyperopt_tpu.distributed.FileTrials (shared-"
            "filesystem queue) for distributed evaluation on TPU pods."
        ) from e


def as_mongo_str(host_port_db):
    """'host:port/dbname' -> mongodb:// connection string."""
    if host_port_db.startswith("mongodb://"):
        return host_port_db
    return f"mongodb://{host_port_db}"


class MongoJobs:
    """Thin collection wrapper: publish / reserve (CAS) / complete / reap."""

    def __init__(self, db, jobs_collection="jobs"):
        _require_pymongo()
        self.db = db
        self.coll = db[jobs_collection]
        import gridfs

        self.gfs = gridfs.GridFS(db, collection="fs")

    @classmethod
    def new_from_connection_str(cls, conn_str, dbname=None):
        pymongo = _require_pymongo()
        conn_str = as_mongo_str(conn_str)
        if dbname is None:
            dbname = conn_str.rsplit("/", 1)[-1]
            conn_str = conn_str.rsplit("/", 1)[0]
        client = pymongo.MongoClient(conn_str)
        return cls(client[dbname])

    def publish(self, doc):
        doc = SONify(doc)
        _common.with_retries(
            lambda: self.coll.insert_one(doc), label="mongo publish"
        )
        return doc

    def reserve(self, owner, exp_key=None, exclude_tids=()):
        """The CAS: atomically flip one NEW job to RUNNING with our owner.

        Ordered by ``_id`` (insertion order), NOT by tid: BSON sorts all
        numbers before all strings, so a tid sort would starve
        ``asha_mongo``'s string tids ('<runtag>-<n>') behind any
        concurrent fmin's numeric tids on a shared collection (ADVICE
        r5).  ``_id`` is type-neutral and insertion-ordered for both the
        real ObjectId and the test doubles' counters; for a single
        driver publishing in tid order the two orderings coincide.

        ``exclude_tids`` lets a worker skip jobs it has already proven
        it cannot process (e.g. a dangling Domain attachment) -- without
        it, the stable ordering would hand the same poisoned job back
        on every iteration and starve everything behind it."""
        query = {"state": JOB_STATE_NEW}
        if exp_key is not None:
            query["exp_key"] = exp_key
        if exclude_tids:
            query["tid"] = {"$nin": list(exclude_tids)}
        # unique claim token: completion-time lost-claim detection must
        # distinguish THIS reservation from a reaped-and-re-claimed one
        # even when both claimants share an owner string
        token = uuid.uuid4().hex
        return _common.with_retries(
            lambda: self.coll.find_one_and_update(
                query,
                {
                    "$set": {
                        "state": JOB_STATE_RUNNING,
                        "owner": owner,
                        "book_time": coarse_utcnow(),
                        "claim": token,
                    }
                },
                sort=[("_id", 1)],
                return_document=True,
            ),
            label="mongo reserve",
        )

    def complete(self, doc, result=None, error=None, require_claim=False):
        """Write the finished state back.  With ``require_claim=True``
        the writeback is a CAS on the reservation's claim token: it
        succeeds (returns True) only if the job is still RUNNING under
        THIS claim -- a job reaped (and possibly re-run) mid-evaluation
        matches nothing, returns False, and the stale worker's result
        is dropped instead of racing the re-run into a duplicate DONE
        doc."""
        update = {"refresh_time": coarse_utcnow()}
        if error is not None:
            update["state"] = JOB_STATE_ERROR
            update["misc.error"] = error
        else:
            update["state"] = JOB_STATE_DONE
            update["result"] = SONify(result)
        query = {"_id": doc["_id"]}
        if require_claim:
            query["state"] = JOB_STATE_RUNNING
            query["claim"] = doc.get("claim")
        res = _common.with_retries(
            lambda: self.coll.update_one(query, {"$set": update}),
            label="mongo complete",
        )
        return res.matched_count == 1

    def unreserve(self, doc):
        """Return a reserved job to NEW (the reap transition) -- used by
        a worker that cannot process it; the queue owns this state
        machine so reap/give-back semantics cannot drift apart."""
        _common.with_retries(
            lambda: self.coll.update_one(
                {"_id": doc["_id"]},
                {"$set": {"state": JOB_STATE_NEW, "owner": None,
                          "book_time": None, "claim": None}},
            ),
            label="mongo unreserve",
        )

    def reap(self, reserve_timeout):
        if reserve_timeout is None:
            return 0
        import datetime

        cutoff = coarse_utcnow() - datetime.timedelta(seconds=reserve_timeout)
        res = _common.with_retries(
            lambda: self.coll.update_many(
                {"state": JOB_STATE_RUNNING, "book_time": {"$lt": cutoff}},
                {"$set": {"state": JOB_STATE_NEW, "owner": None,
                          "book_time": None, "claim": None}},
            ),
            label="mongo reap",
        )
        return res.modified_count

    # attachments (GridFS) --------------------------------------------------
    def _newest_file(self, key):
        """Newest GridFS file for ``key``: real gridfs ``find_one`` has
        NO ordering guarantee (natural order -- oldest first in
        practice), so a replacement must be looked up via
        ``get_last_version``; the in-memory double only has a
        newest-first ``find_one``."""
        try:
            return self.gfs.get_last_version(key)
        except AttributeError:  # double without get_last_version
            return self.gfs.find_one({"filename": key})
        except KeyError:  # the double's stand-in for NoFile
            return None
        except Exception as e:
            # ONLY gridfs.NoFile means "missing"; a connection error
            # must surface as itself, not masquerade as deleted data
            # (callers put tids on cooldown / raise KeyError for None)
            if type(e).__name__ == "NoFile":
                return None
            raise

    def set_attachment(self, key, blob):
        # put-then-sweep: the replacement window must never be EMPTY (a
        # worker loading the Domain mid-republish would fail on a
        # healthy queue); afterwards every file under the name EXCEPT
        # the new one is deleted, so a crash between put and sweep
        # leaves duplicates a later set_attachment cleans up, and
        # readers (newest-first) converge immediately either way
        new_id = self.gfs.put(blob, filename=key)
        for obj in self.gfs.find({"filename": key}):
            # sweep only files OLDER than ours (_ids are time-ordered):
            # two concurrent writers must not delete each other's new
            # file and leave the key empty -- the newest always survives
            if obj._id != new_id and obj._id < new_id:
                self.gfs.delete(obj._id)

    def get_attachment(self, key):
        obj = self._newest_file(key)
        if obj is None:
            raise KeyError(key)
        return obj.read()

    def delete_attachment(self, key):
        """Remove every GridFS file under ``key`` (run-scoped Domain
        cleanup); missing keys are a no-op."""
        for obj in self.gfs.find({"filename": key}):
            self.gfs.delete(obj._id)

    def has_attachment(self, key):
        return self.gfs.find_one({"filename": key}) is not None


class _GfsAttachments:
    def __init__(self, jobs):
        self.jobs = jobs

    def __contains__(self, key):
        return self.jobs.has_attachment(key)

    def __getitem__(self, key):
        return self.jobs.get_attachment(key)

    def __setitem__(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        self.jobs.set_attachment(key, value)


class MongoTrials(Trials):
    """Async Trials over a MongoDB jobs collection (reference-compatible
    ``MongoTrials('mongo://host:port/db/jobs', exp_key=...)`` shape)."""

    asynchronous = True

    def __init__(self, arg, exp_key=None, refresh=True, reserve_timeout=None):
        _require_pymongo()
        if isinstance(arg, MongoJobs):
            self.handle = arg
        else:
            conn = str(arg)
            for prefix in ("mongo://", "mongodb://"):
                if conn.startswith(prefix):
                    conn = conn[len(prefix):]
            conn = conn.rstrip("/")
            if conn.endswith("/jobs"):
                conn = conn[: -len("/jobs")]
            self.handle = MongoJobs.new_from_connection_str(conn)
        self.reserve_timeout = reserve_timeout
        super().__init__(exp_key=exp_key, refresh=False)
        self.attachments = _GfsAttachments(self.handle)
        if refresh:
            self.refresh()

    def _insert_trial_docs(self, docs):
        for doc in docs:
            self.handle.publish(doc)
        return [d["tid"] for d in docs]

    def refresh(self):
        query = {} if self._exp_key is None else {"exp_key": self._exp_key}
        docs = list(_common.with_retries(
            lambda: self.handle.coll.find(query, sort=[("tid", 1)]),
            label="mongo refresh",
        ))
        for d in docs:
            d.pop("_id", None)
        self._dynamic_trials = docs
        if self.reserve_timeout:
            self.handle.reap(self.reserve_timeout)
        super().refresh()

    def new_trial_ids(self, n):
        # ids must be unique across every driver using the collection.
        # Max over NUMERIC tids only, server-side: asha_mongo's
        # transport jobs carry string tids ("<runtag>-<n>"), which BSON
        # sorts above every number -- an unfiltered sort would hand
        # back a string and `+ 1` would crash on a legitimately shared
        # db.  The $type filter keeps this one indexed find_one instead
        # of an O(collection) client-side scan.
        last = self.handle.coll.find_one(
            {"tid": {"$type": "number"}}, sort=[("tid", -1)]
        )
        base = (int(last["tid"]) + 1) if last else 0
        local_floor = max(self._ids, default=-1) + 1
        start = max(base, local_floor)
        rval = list(range(start, start + n))
        self._ids.update(rval)
        return rval

    def delete_all(self):
        query = {} if self._exp_key is None else {"exp_key": self._exp_key}
        self.handle.coll.delete_many(query)
        super().delete_all()


class MongoWorker:
    """Evaluate reserved jobs (the ``hyperopt-mongo-worker`` role)."""

    def __init__(self, jobs, exp_key=None, workdir=None, heartbeat=None):
        self.jobs = jobs
        self.exp_key = exp_key
        self.workdir = workdir
        self.heartbeat = heartbeat
        import collections

        # attachment key -> (gridfs _id, Domain); identity-validated
        # LRU (shared contract with the filequeue worker, _common)
        self._domains = collections.OrderedDict()
        # poisoned-job cooldown (shared TTLSet contract): a tid whose
        # Domain failed to load is excluded from this worker's
        # reservations for the TTL, then retried -- neither a livelock
        # on the lowest tid nor a permanent exclusion on a transient
        # failure
        self._bad_tids = _common.TTLSet()

    def _load_domain(self, doc):
        # the doc's cmd names its Domain attachment (the reference's
        # contract), so drivers with DIFFERENT objectives can share one
        # database -- asha_mongo publishes under a per-run key and a
        # concurrent fmin's jobs keep resolving their own.  Cache keyed
        # by the GridFS file's _id: a re-publish under the same key
        # (set_attachment puts a NEW file) invalidates, the same
        # contract as the filequeue worker's inode check.
        key = _common.blob_key_from_doc(doc)
        obj = self.jobs._newest_file(key)
        if obj is None:
            raise KeyError(key)
        return _common.lru_get(
            self._domains, key, obj._id, lambda: pickle.loads(obj.read())
        )

    def run_one(self, owner):
        doc = self.jobs.reserve(
            owner, exp_key=self.exp_key,
            exclude_tids=self._bad_tids.current(),
        )
        if doc is None:
            return False
        try:
            domain = self._load_domain(doc)
        except Exception as e:
            # give the job back and surface the error: a worker that
            # cannot load the Domain (version skew, missing attachment)
            # must not mark jobs failed -- healthy workers can run
            # them.  The tid joins this worker's cooldown set so its
            # next reserve moves PAST the poisoned job instead of
            # re-reserving it forever
            self._bad_tids.add(doc.get("tid"))
            self.jobs.unreserve(doc)
            e.failed_tid = doc.get("tid")
            raise
        trials = Trials()
        trials._dynamic_trials.append(doc)
        ctrl = Ctrl(trials, current_trial=doc)

        def _beat():
            # refresh book_time so reapers (driver-side asha_mongo,
            # other workers' reap calls) never recycle a LIVE job whose
            # evaluation outlives reserve_timeout -- the mtime-heartbeat
            # contract of the filequeue worker, via the shared scaffold.
            # CAS on the claim token: a reaped-and-re-claimed job must
            # not have its NEW claimant's book_time refreshed by the old
            # worker, and a matched_count of 0 (claim gone) stops the
            # beat thread cleanly (the scaffold's False contract)
            res = _common.with_retries(
                lambda: self.jobs.coll.update_one(
                    {"_id": doc["_id"], "state": JOB_STATE_RUNNING,
                     "claim": doc.get("claim")},
                    {"$set": {"book_time": coarse_utcnow()}},
                ),
                label="mongo heartbeat",
            )
            return res.matched_count == 1

        with _common.claim_heartbeat(_beat, self.heartbeat):
            try:
                result = domain.evaluate(spec_from_misc(doc["misc"]), ctrl)
            except Exception as e:  # graftlint: disable=GL302 objective errors become ERROR docs
                logger.error("job %s failed: %s", doc.get("tid"), e)
                published = self.jobs.complete(
                    doc, error=(str(type(e)), str(e)), require_claim=True
                )
            else:
                published = self.jobs.complete(
                    doc, result=result, require_claim=True
                )
        if not published:
            # completion-time lost-claim detection (the filequeue
            # worker's contract): the claim was reaped mid-evaluation
            # and the job re-queued -- drop this result rather than
            # racing the re-run into a duplicate DONE doc
            logger.warning(
                "job %s: claim lost mid-evaluation (reaped); dropping "
                "result to defer to the re-run", doc.get("tid"),
            )
        return True


def main_worker(argv=None):
    """CLI: ``hyperopt-tpu-mongo-worker --mongo=host:port/db``."""
    import argparse
    import socket
    import os
    import time

    parser = argparse.ArgumentParser(prog="hyperopt-tpu-mongo-worker")
    parser.add_argument("--mongo", required=True)
    parser.add_argument("--exp-key", default=None)
    parser.add_argument("--max-jobs", type=int, default=None)
    parser.add_argument("--poll-interval", type=float, default=1.0)
    parser.add_argument("--reserve-timeout", type=float, default=120.0)
    parser.add_argument("--workdir", default=None)
    parser.add_argument(
        "--max-crash-loop", type=int, default=5,
        help="consecutive unexpected errors before a loud exit (rc 2)",
    )
    options = parser.parse_args(argv)

    from .worker import GracefulDrain

    jobs = MongoJobs.new_from_connection_str(options.mongo)
    worker = MongoWorker(
        jobs, exp_key=options.exp_key, workdir=options.workdir,
        heartbeat=(
            options.reserve_timeout / 3.0
            if options.reserve_timeout else None
        ),
    )
    owner = f"{socket.gethostname()}:{os.getpid()}"
    drain = GracefulDrain().install()
    n = 0
    consecutive_errors = 0
    while options.max_jobs is None or n < options.max_jobs:
        if drain.requested:
            logger.info("drained after %d job(s), exiting 0", n)
            return 0
        # backoff computed in-handler, slept at loop level on the shared
        # with_retries schedule (_common.retry_delay) -- no hand-rolled
        # sleep-in-except retry loop (GL303)
        backoff = None
        try:
            jobs.reap(options.reserve_timeout)
            ran = worker.run_one(owner)
        except Exception as e:  # graftlint: disable=GL302 crash-loop guard: bounded backoff then exit 2
            if getattr(e, "failed_tid", None) is not None:
                # a job naming an unloadable Domain: run_one gave it
                # back and put the tid on cooldown; cool off instead of
                # crash-looping the process on the same lowest-tid doc
                logger.error("job %s returned to queue: %s", e.failed_tid, e)
                consecutive_errors = 0
                backoff = options.poll_interval
            else:
                # crash-loop guard (the filequeue worker's contract):
                # back off on unexpected errors -- an AutoReconnect
                # storm that outlives the per-op retries costs backoff,
                # not the process -- then exit loudly so a supervisor
                # restart loop cannot silently spin forever
                consecutive_errors += 1
                if consecutive_errors >= options.max_crash_loop:
                    logger.critical(
                        "%d consecutive unexpected errors (last: %s); "
                        "exiting loudly", consecutive_errors, e,
                        exc_info=True,
                    )
                    return 2
                logger.error(
                    "unexpected worker error (%d/%d): %s",
                    consecutive_errors, options.max_crash_loop, e,
                )
                backoff = _common.retry_delay(
                    consecutive_errors,
                    base_delay=options.poll_interval, max_delay=2.0,
                )
        if backoff is not None:
            time.sleep(backoff)
            continue
        consecutive_errors = 0
        if ran:
            n += 1
        else:
            time.sleep(options.poll_interval)
    return 0
