"""ThreadTrials: asynchronous in-process evaluation with a parallelism cap.

The control-flow of the reference's ``SparkTrials`` (SURVEY.md SS3.5:
dispatcher loop, <= parallelism trials in flight, timeout cancellation,
results posted back under a lock) with a thread pool instead of 1-task
Spark jobs.  Useful whenever the objective releases the GIL (device calls,
subprocesses, IO) -- which a TPU objective does.
"""

from __future__ import annotations

import logging
import threading
import timeit

from ..base import (
    Ctrl,
    JOB_STATE_CANCEL,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    Trials,
    spec_from_misc,
)
from ..utils import coarse_utcnow

logger = logging.getLogger(__name__)

__all__ = ["ThreadTrials"]


class ThreadTrials(Trials):
    """Trials whose NEW jobs are evaluated by a pool of worker threads.

    Args:
      parallelism: max trials in flight at once.
      timeout: per-experiment wall-clock budget (seconds); when exceeded,
        queued trials are cancelled (running ones finish -- Python threads
        are not preemptible, matching Spark's cancel-at-boundary behavior).
    """

    asynchronous = True

    def __init__(self, parallelism=4, timeout=None, exp_key=None, refresh=True):
        self.parallelism = max(1, int(parallelism))
        self.timeout = timeout
        self._lock = threading.RLock()
        self._inflight = {}
        self._fmin_cancelled = False
        self._fmin_cancelled_reason = None
        self._start_time = None
        super().__init__(exp_key=exp_key, refresh=refresh)

    # -- hooks -------------------------------------------------------------
    def refresh(self):
        with self._lock:
            super().refresh()

    def insert_trial_docs(self, docs):
        with self._lock:
            return super().insert_trial_docs(docs)

    # -- dispatch ----------------------------------------------------------
    def _timed_out(self):
        return (
            self.timeout is not None
            and self._start_time is not None
            and timeit.default_timer() - self._start_time >= self.timeout
        )

    def _run_trial(self, trial, domain):
        ctrl = Ctrl(self, current_trial=trial)
        spec = spec_from_misc(trial["misc"])
        try:
            result = domain.evaluate(spec, ctrl)
        except Exception as e:  # graftlint: disable=GL302 objective errors become ERROR docs
            logger.error("trial %s exception: %s", trial["tid"], e)
            with self._lock:
                trial["state"] = JOB_STATE_ERROR
                trial["misc"]["error"] = (str(type(e)), str(e))
                trial["refresh_time"] = coarse_utcnow()
        else:
            with self._lock:
                trial["state"] = JOB_STATE_DONE
                trial["result"] = result
                trial["refresh_time"] = coarse_utcnow()
        finally:
            with self._lock:
                self._inflight.pop(trial["tid"], None)

    def _dispatch_new(self, domain):
        """Launch threads for NEW trials up to the parallelism cap."""
        with self._lock:
            if self._timed_out():
                if not self._fmin_cancelled:
                    self._fmin_cancelled = True
                    self._fmin_cancelled_reason = "fmin run timeout"
                    logger.warning("ThreadTrials: timeout, cancelling queue")
                for t in self._dynamic_trials:
                    if t["state"] == JOB_STATE_NEW:
                        t["state"] = JOB_STATE_CANCEL
                        t["refresh_time"] = coarse_utcnow()
                return
            for t in self._dynamic_trials:
                if len(self._inflight) >= self.parallelism:
                    break
                if t["state"] != JOB_STATE_NEW:
                    continue
                t["state"] = JOB_STATE_RUNNING
                t["book_time"] = coarse_utcnow()
                t["owner"] = f"thread:{len(self._inflight)}"
                th = threading.Thread(
                    target=self._run_trial, args=(t, domain), daemon=True
                )
                self._inflight[t["tid"]] = th
                th.start()

    # -- fmin entry point --------------------------------------------------
    def fmin(self, fn, space, algo=None, max_evals=None, **kwargs):
        """Dispatching fmin: suggest on the driver, evaluate in threads."""
        from ..base import Domain
        from ..fmin import fmin as _fmin

        kwargs.pop("allow_trials_fmin", None)
        timeout = kwargs.pop("timeout", None)
        if timeout is not None:
            self.timeout = timeout
        # under the lock (GL501): _fmin_cancelled is read/written by
        # the worker threads' lock domain, and a racing re-entrant
        # fmin must not tear the previous run's cancellation state
        with self._lock:
            self._start_time = timeit.default_timer()
            self._fmin_cancelled = False

        pass_expr_memo_ctrl = kwargs.pop("pass_expr_memo_ctrl", None)
        domain = Domain(fn, space, pass_expr_memo_ctrl=pass_expr_memo_ctrl)
        self._domain = domain

        # whole rounds of `parallelism` trials are suggested, dispatched to
        # the pool, then awaited (the SparkTrials dispatch shape)
        kwargs.setdefault("max_queue_len", self.parallelism)
        rval = _fmin(
            fn,
            space,
            algo=algo,
            max_evals=max_evals,
            trials=self,
            allow_trials_fmin=False,
            timeout=self.timeout,
            pass_expr_memo_ctrl=pass_expr_memo_ctrl,
            **kwargs,
        )
        return rval

    def count_by_state_unsynced(self, arg):
        # every poll from FMinIter.block_until_done doubles as the pump
        domain = getattr(self, "_domain", None)
        if domain is not None:
            self._dispatch_new(domain)
        with self._lock:
            return super().count_by_state_unsynced(arg)