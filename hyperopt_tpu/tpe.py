"""Tree-structured Parzen Estimator -- host/numpy parity path.

Capability parity with the reference's ``hyperopt/tpe.py`` (SURVEY.md SS2,
SS3.2): adaptive-Parzen 1-D GMM fitting (neighbor-difference sigmas, sigma
clipping, prior component, linear forgetting), truncated/quantized GMM
sampling + lpdfs (``GMM1``/``LGMM1`` families), categorical posteriors via
weighted counts, good/bad split at ``n_below = min(ceil(gamma*sqrt(n)), LF)``
and factorized per-hyperparameter EI argmax over ``n_EI_candidates`` draws.

This numpy implementation is the *oracle*: the production TPU path
(:mod:`hyperopt_tpu.tpe_jax`) re-derives the same math as shape-static
vmapped JAX kernels (inverse-CDF truncation instead of rejection, masked
padding instead of ragged obs) and is validated statistically against this
module (SURVEY.md SS7 design stance #2).

One deliberate design departure: sampling uses inverse-CDF truncation here
too (never rejection loops), so oracle and kernel share identical
truncation semantics.
"""

from __future__ import annotations

import logging

import numpy as np
from scipy.special import ndtri  # inverse normal CDF

from .base import JOB_STATE_DONE, STATUS_OK, posterior_state
from .pyll.base import rec_eval, scope
from .pyll.stochastic import ensure_rng
from .rand import docs_from_idxs_vals, _domain_helper

logger = logging.getLogger(__name__)


def _native():
    """The optional C++ host-math library (None when unavailable)."""
    from . import native as _native_mod

    return _native_mod if _native_mod.available() else None


__all__ = [
    "suggest",
    "suggest_batch",
    "adaptive_parzen_normal",
    "adaptive_parzen_normal_orig",
    "linear_forgetting_weights",
    "normal_cdf",
    "GMM1",
    "GMM1_lpdf",
    "LGMM1",
    "LGMM1_lpdf",
    "ap_split_trials",
    "ap_filter_trials",
    "broadcast_best",
    "adaptive_parzen_samplers",
]

# -- defaults (reference tpe.py module constants, SURVEY.md SS2) -----------
_default_prior_weight = 1.0
_default_n_EI_candidates = 24
_default_gamma = 0.25
_default_n_startup_jobs = 20
_default_linear_forgetting = 25

EPS = 1e-12


# ---------------------------------------------------------------------------
# weights / parzen fitting
# ---------------------------------------------------------------------------


def linear_forgetting_weights(N, LF):
    """Weights over N time-ordered observations: newest LF get weight 1,
    older ones ramp linearly down toward 1/N (oldest first in the array)."""
    if N == 0:
        return np.asarray([], dtype=float)
    if N < LF:
        return np.ones(N)
    ramp = np.linspace(1.0 / N, 1.0, num=N - LF)
    flat = np.ones(LF)
    return np.concatenate([ramp, flat])


def adaptive_parzen_normal(mus, prior_weight, prior_mu, prior_sigma, LF=None):
    """Fit a 1-D GMM over observed values ``mus`` (time order).

    Components: one per observation plus a prior component at
    ``(prior_mu, prior_sigma)`` inserted in sorted position.  Sigmas are
    neighbor differences (max of left/right gap), clipped to
    ``[prior_sigma / min(100, 1 + n), prior_sigma]``.  Weights carry linear
    forgetting beyond ``LF`` observations; the prior gets ``prior_weight``.

    Returns (weights, mus, sigmas) sorted by mu, weights normalized.
    """
    if LF is None:
        LF = _default_linear_forgetting
    nat = _native()
    if nat is not None:
        fit = nat.adaptive_parzen(mus, prior_weight, prior_mu, prior_sigma, LF)
        if fit is not None:
            return fit
    return adaptive_parzen_normal_numpy(mus, prior_weight, prior_mu,
                                        prior_sigma, LF)


def adaptive_parzen_normal_numpy(mus, prior_weight, prior_mu, prior_sigma,
                                 LF=None):
    """Pure-numpy adaptive-Parzen fit (the oracle the native/JAX paths are
    validated against)."""
    if LF is None:
        LF = _default_linear_forgetting
    mus = np.asarray(mus, dtype=float)
    n = len(mus)

    if n == 0:
        srtd_mus = np.asarray([prior_mu], dtype=float)
        sigma = np.asarray([prior_sigma], dtype=float)
        prior_pos = 0
        srtd_weights = np.asarray([1.0])
    else:
        order = np.argsort(mus)
        prior_pos = int(np.searchsorted(mus[order], prior_mu))
        srtd_mus = np.insert(mus[order], prior_pos, prior_mu)
        m = len(srtd_mus)
        sigma = np.zeros(m)
        if m == 1:
            sigma[:] = prior_sigma
        elif m == 2:
            gap = abs(srtd_mus[1] - srtd_mus[0])
            sigma[:] = np.maximum(gap, EPS)
        else:
            left_gap = srtd_mus[1:-1] - srtd_mus[:-2]
            right_gap = srtd_mus[2:] - srtd_mus[1:-1]
            sigma[1:-1] = np.maximum(left_gap, right_gap)
            sigma[0] = srtd_mus[1] - srtd_mus[0]
            sigma[-1] = srtd_mus[-1] - srtd_mus[-2]
        # clip, then pin the prior component's sigma
        maxsigma = prior_sigma
        minsigma = prior_sigma / min(100.0, 1.0 + n)
        sigma = np.clip(sigma, minsigma, maxsigma)
        sigma[prior_pos] = prior_sigma

        if LF and LF < n:
            unsrtd_weights = linear_forgetting_weights(n, LF)
        else:
            unsrtd_weights = np.ones(n)
        srtd_weights = np.insert(unsrtd_weights[order], prior_pos, prior_weight)

    srtd_weights = srtd_weights / srtd_weights.sum()
    return srtd_weights, srtd_mus, sigma


def adaptive_parzen_normal_orig(mus, prior_weight, prior_mu, prior_sigma):
    """Variant without linear forgetting (parity with the reference's
    ``adaptive_parzen_normal_orig``)."""
    return adaptive_parzen_normal(mus, prior_weight, prior_mu, prior_sigma, LF=0)


# ---------------------------------------------------------------------------
# normal helpers
# ---------------------------------------------------------------------------

_SQRT2 = np.sqrt(2.0)


def normal_cdf(x, mu, sigma):
    from scipy.special import erf

    z = (np.asarray(x, dtype=float) - mu) / (np.maximum(sigma, EPS) * _SQRT2)
    return 0.5 * (1.0 + erf(z))


def _normal_logpdf(x, mu, sigma):
    sigma = np.maximum(sigma, EPS)
    z = (x - mu) / sigma
    return -0.5 * z * z - np.log(sigma) - 0.5 * np.log(2 * np.pi)


def _logsumexp(a, axis=None):
    amax = np.max(a, axis=axis, keepdims=True)
    amax = np.where(np.isfinite(amax), amax, 0.0)
    out = np.log(np.sum(np.exp(a - amax), axis=axis)) + np.squeeze(amax, axis=axis)
    return out


def _trunc_normal_sample(rng, mu, sigma, low, high, size):
    """Truncated normal via inverse CDF -- rejection-free by design
    (SURVEY.md SS7 hard-parts list)."""
    mu = np.broadcast_to(mu, size).astype(float)
    sigma = np.maximum(np.broadcast_to(sigma, size).astype(float), EPS)
    if low is None and high is None:
        return rng.normal(mu, sigma)
    a = 0.0 if low is None else normal_cdf(low, mu, sigma)
    b = 1.0 if high is None else normal_cdf(high, mu, sigma)
    u = rng.uniform(size=size)
    p = np.clip(a + u * (b - a), EPS, 1 - EPS)
    x = mu + sigma * ndtri(p)
    if low is not None:
        x = np.maximum(x, low)
    if high is not None:
        x = np.minimum(x, high)
    return x


# ---------------------------------------------------------------------------
# GMM sample / lpdf ops (registered into the pyll scope for parity)
# ---------------------------------------------------------------------------


@scope.define
def GMM1(weights, mus, sigmas, low=None, high=None, q=None, rng=None, size=()):
    """Sample from a (truncated, optionally quantized) 1-D GMM."""
    rng = ensure_rng(rng)
    weights = np.asarray(weights, dtype=float)
    mus = np.asarray(mus, dtype=float)
    sigmas = np.asarray(sigmas, dtype=float)
    size = (size,) if isinstance(size, (int, np.integer)) else tuple(size)
    n = int(np.prod(size)) if size else 1

    ks = rng.choice(len(weights), size=n, p=weights / weights.sum())
    draws = _trunc_normal_sample(rng, mus[ks], sigmas[ks], low, high, (n,))
    if q is not None:
        draws = np.round(draws / q) * q
        if low is not None:
            draws = np.maximum(draws, np.round(low / q) * q)
        if high is not None:
            draws = np.minimum(draws, np.round(high / q) * q)
    if not size:
        return float(draws[0])
    return draws.reshape(size)


@scope.define
def GMM1_lpdf(samples, weights, mus, sigmas, low=None, high=None, q=None):
    """log-density of ``samples`` under a truncated/quantized 1-D GMM."""
    samples = np.asarray(samples, dtype=float)
    nat = _native()
    if nat is not None:
        out = nat.gmm_lpdf(samples.ravel(), weights, mus, sigmas,
                           low=low, high=high, q=q, logspace=False)
        if out is not None:
            return out.reshape(samples.shape)
    return GMM1_lpdf_numpy(samples, weights, mus, sigmas, low=low, high=high,
                           q=q)


def GMM1_lpdf_numpy(samples, weights, mus, sigmas, low=None, high=None, q=None):
    """Pure-numpy GMM1_lpdf (oracle for native/JAX paths)."""
    samples = np.asarray(samples, dtype=float)
    weights = np.asarray(weights, dtype=float)
    mus = np.asarray(mus, dtype=float)
    sigmas = np.maximum(np.asarray(sigmas, dtype=float), EPS)
    x = samples.reshape(-1, 1)  # [S, 1] vs components [K]

    # per-component truncation mass
    a = normal_cdf(low, mus, sigmas) if low is not None else 0.0
    b = normal_cdf(high, mus, sigmas) if high is not None else 1.0
    log_mass = np.log(np.maximum(b - a, EPS))
    logw = np.log(np.maximum(weights / weights.sum(), EPS))

    if q is None:
        ll = logw + _normal_logpdf(x, mus, sigmas) - log_mass
    else:
        ub = x + q / 2.0
        lb = x - q / 2.0
        if low is not None:
            lb = np.maximum(lb, low)
        if high is not None:
            ub = np.minimum(ub, high)
        bin_mass = normal_cdf(ub, mus, sigmas) - normal_cdf(lb, mus, sigmas)
        ll = logw + np.log(np.maximum(bin_mass, EPS)) - log_mass
    rval = _logsumexp(ll, axis=1)
    return rval.reshape(samples.shape)


@scope.define
def LGMM1(weights, mus, sigmas, low=None, high=None, q=None, rng=None, size=()):
    """Sample from a lognormal mixture: ``exp(GMM1-in-log-space)``.

    ``low``/``high`` are bounds in *log* space (matching the reference's
    use for ``loguniform`` priors, SURVEY.md SS2 TPE row (b)).
    """
    rng = ensure_rng(rng)
    weights = np.asarray(weights, dtype=float)
    mus = np.asarray(mus, dtype=float)
    sigmas = np.asarray(sigmas, dtype=float)
    size = (size,) if isinstance(size, (int, np.integer)) else tuple(size)
    n = int(np.prod(size)) if size else 1

    ks = rng.choice(len(weights), size=n, p=weights / weights.sum())
    draws = np.exp(_trunc_normal_sample(rng, mus[ks], sigmas[ks], low, high, (n,)))
    if q is not None:
        draws = np.round(draws / q) * q
    if not size:
        return float(draws[0])
    return draws.reshape(size)


@scope.define
def LGMM1_lpdf(samples, weights, mus, sigmas, low=None, high=None, q=None):
    """log-density under a (truncated in log space, optionally quantized)
    lognormal mixture; ``samples`` are in natural space."""
    samples = np.asarray(samples, dtype=float)
    nat = _native()
    if nat is not None:
        out = nat.gmm_lpdf(samples.ravel(), weights, mus, sigmas,
                           low=low, high=high, q=q, logspace=True)
        if out is not None:
            return out.reshape(samples.shape)
    return LGMM1_lpdf_numpy(samples, weights, mus, sigmas, low=low, high=high,
                            q=q)


def LGMM1_lpdf_numpy(samples, weights, mus, sigmas, low=None, high=None,
                     q=None):
    """Pure-numpy LGMM1_lpdf (oracle for native/JAX paths)."""
    samples = np.asarray(samples, dtype=float)
    weights = np.asarray(weights, dtype=float)
    mus = np.asarray(mus, dtype=float)
    sigmas = np.maximum(np.asarray(sigmas, dtype=float), EPS)
    x = samples.reshape(-1, 1)

    a = normal_cdf(low, mus, sigmas) if low is not None else 0.0
    b = normal_cdf(high, mus, sigmas) if high is not None else 1.0
    log_mass = np.log(np.maximum(b - a, EPS))
    logw = np.log(np.maximum(weights / weights.sum(), EPS))

    if q is None:
        logx = np.log(np.maximum(x, EPS))
        ll = logw + _normal_logpdf(logx, mus, sigmas) - logx - log_mass
    else:
        ub = np.log(np.maximum(x + q / 2.0, EPS))
        lb = np.log(np.maximum(x - q / 2.0, EPS))
        if low is not None:
            lb = np.maximum(lb, low)
        if high is not None:
            ub = np.minimum(ub, high)
        bin_mass = normal_cdf(ub, mus, sigmas) - normal_cdf(lb, mus, sigmas)
        ll = logw + np.log(np.maximum(bin_mass, EPS)) - log_mass
    rval = _logsumexp(ll, axis=1)
    return rval.reshape(samples.shape)


def broadcast_best(samples, ll_below, ll_above):
    """Factorized EI argmax: pick the candidate maximizing
    ``log l(x) - log g(x)`` (independently per hyperparameter)."""
    samples = np.asarray(samples)
    score = np.asarray(ll_below) - np.asarray(ll_above)
    return samples[int(np.argmax(score))]


# ---------------------------------------------------------------------------
# categorical posterior
# ---------------------------------------------------------------------------


def categorical_posterior(obs, prior_p, prior_weight, LF):
    """Posterior pmf over categories from weighted counts + prior
    pseudocounts (parity: reference ``ap_categorical_sampler``)."""
    prior_p = np.asarray(prior_p, dtype=float)
    n_options = len(prior_p)
    obs = np.asarray(obs, dtype=int)
    w = linear_forgetting_weights(len(obs), LF)
    counts = np.bincount(obs, weights=w, minlength=n_options)
    pseudocounts = counts + prior_weight * prior_p * n_options
    return pseudocounts / pseudocounts.sum()


# ---------------------------------------------------------------------------
# per-distribution posterior draw (the factorized TPE inner step)
# ---------------------------------------------------------------------------


def _prior_gmm_params(info):
    """Map a ParamInfo to (prior_mu, prior_sigma, low, high, logspace, q)."""
    p = info.params
    d = info.dist
    if d in ("uniform", "quniform"):
        low, high = float(p["low"]), float(p["high"])
        return 0.5 * (low + high), high - low, low, high, False, p.get("q")
    if d in ("loguniform", "qloguniform"):
        low, high = float(p["low"]), float(p["high"])
        return 0.5 * (low + high), high - low, low, high, True, p.get("q")
    if d in ("normal", "qnormal"):
        return float(p["mu"]), float(p["sigma"]), None, None, False, p.get("q")
    if d in ("lognormal", "qlognormal"):
        return float(p["mu"]), float(p["sigma"]), None, None, True, p.get("q")
    raise NotImplementedError(d)


def posterior_draw(info, obs_below, obs_above, rng, prior_weight, n_EI_candidates, LF):
    """Draw the EI-argmax value for one hyperparameter."""
    d = info.dist
    p = info.params

    if d in ("randint", "categorical", "randint_via_categorical"):
        if d == "randint":
            low = int(p["low"])
            n_options = int(p["high"]) - low
            prior_p = np.full(n_options, 1.0 / n_options)
            ob = np.asarray(obs_below, dtype=int) - low
            oa = np.asarray(obs_above, dtype=int) - low
        else:
            low = 0
            prior_p = np.asarray(p["p"], dtype=float)
            ob = np.asarray(obs_below, dtype=int)
            oa = np.asarray(obs_above, dtype=int)
        p_below = categorical_posterior(ob, prior_p, prior_weight, LF)
        p_above = categorical_posterior(oa, prior_p, prior_weight, LF)
        candidates = rng.choice(len(prior_p), size=n_EI_candidates, p=p_below)
        llr = np.log(p_below[candidates]) - np.log(p_above[candidates])
        return int(candidates[int(np.argmax(llr))]) + low

    prior_mu, prior_sigma, low, high, logspace, q = _prior_gmm_params(info)
    q = None if q is None else float(q)
    obs_below = np.asarray(obs_below, dtype=float)
    obs_above = np.asarray(obs_above, dtype=float)
    if logspace:
        fit_below = np.log(np.maximum(obs_below, EPS)) if len(obs_below) else obs_below
        fit_above = np.log(np.maximum(obs_above, EPS)) if len(obs_above) else obs_above
    else:
        fit_below, fit_above = obs_below, obs_above

    wb, mb, sb = adaptive_parzen_normal(fit_below, prior_weight, prior_mu, prior_sigma, LF)
    wa, ma, sa = adaptive_parzen_normal(fit_above, prior_weight, prior_mu, prior_sigma, LF)

    if logspace:
        samples = LGMM1(wb, mb, sb, low=low, high=high, q=q, rng=rng,
                        size=(n_EI_candidates,))
        ll_below = LGMM1_lpdf(samples, wb, mb, sb, low=low, high=high, q=q)
        ll_above = LGMM1_lpdf(samples, wa, ma, sa, low=low, high=high, q=q)
    else:
        samples = GMM1(wb, mb, sb, low=low, high=high, q=q, rng=rng,
                       size=(n_EI_candidates,))
        ll_below = GMM1_lpdf(samples, wb, mb, sb, low=low, high=high, q=q)
        ll_above = GMM1_lpdf(samples, wa, ma, sa, low=low, high=high, q=q)
    return float(broadcast_best(samples, ll_below, ll_above))


# Registry {dist name -> posterior draw}: the plugin surface the reference
# exposes as ``adaptive_parzen_samplers`` (SURVEY.md SS2 TPE row).
adaptive_parzen_samplers = {
    name: posterior_draw
    for name in (
        "uniform", "quniform", "loguniform", "qloguniform",
        "normal", "qnormal", "lognormal", "qlognormal",
        "randint", "categorical", "randint_via_categorical",
    )
}


# ---------------------------------------------------------------------------
# good/bad split
# ---------------------------------------------------------------------------


def ap_filter_trials(trials, gamma, LF):
    """Completed ok-trials sorted by (loss, tid) -> (below_docs, above_docs).

    ``n_below = min(ceil(gamma * sqrt(n)), LF)`` (SURVEY.md SS3.2).
    """
    ok = [t for t in trials.trials if posterior_state(t) == "ok"]
    ok.sort(key=lambda t: (float(t["result"]["loss"]), t["tid"]))
    n_below = min(int(np.ceil(gamma * np.sqrt(len(ok)))), LF)
    below = ok[:n_below]
    above = ok[n_below:]
    # time order within each side (parzen weights are time-indexed)
    below.sort(key=lambda t: t["tid"])
    above.sort(key=lambda t: t["tid"])
    return below, above


ap_split_trials = ap_filter_trials  # reference exposes both names


def _obs_by_label(docs, labels):
    obs = {label: [] for label in labels}
    for t in docs:
        vals = t["misc"]["vals"]
        for label in labels:
            v = vals.get(label, [])
            if len(v) == 1:
                obs[label].append(v[0])
    return obs


class _ObsIndex:
    """Incremental columnar mirror of completed-ok trials (host path).

    Profiling showed ~40% of a host suggest in re-extracting per-label
    observation lists from every trial doc (``ap_filter_trials`` +
    ``_obs_by_label``); this index scans each doc once and answers the
    (loss, tid)-sorted below/above split with numpy selections, with
    EXACTLY the reference semantics (same split, per-side tid order).
    Docs scanned while pending (the shared
    :func:`hyperopt_tpu.base.posterior_state` classification) are
    revisited -- a late completion (the async-backend pattern) is simply
    appended, since :meth:`split_obs` derives every ordering from
    (loss, tid) sorts and row order is irrelevant.
    """

    def __init__(self, labels):
        self.labels = tuple(labels)
        self.reset()

    def reset(self):
        self.n_scanned = 0
        self.pending = []
        self.tids = []
        self.losses = []
        self.label_pos = {lb: [] for lb in self.labels}
        self.label_vals = {lb: [] for lb in self.labels}
        self._frozen = None

    def _add(self, t):
        pos = len(self.tids)
        self.tids.append(int(t["tid"]))
        self.losses.append(float(t["result"]["loss"]))
        vals = t["misc"]["vals"]
        for lb in self.labels:
            v = vals.get(lb, [])
            if len(v) == 1:
                self.label_pos[lb].append(pos)
                self.label_vals[lb].append(v[0])

    def sync(self, trials):
        docs = trials.trials
        if len(docs) < self.n_scanned:
            self.reset()
        grew = False
        still = []
        for i in self.pending:
            t = docs[i]
            ps = posterior_state(t)
            if ps == "ok":
                # late completion: APPEND is enough -- split_obs derives
                # every ordering from (loss, tid) sorts, so row order in
                # the columnar store is irrelevant
                self._add(t)
                grew = True
            elif ps == "pending":
                still.append(i)
        self.pending = still
        for i in range(self.n_scanned, len(docs)):
            t = docs[i]
            ps = posterior_state(t)
            if ps == "ok":
                self._add(t)
                grew = True
            elif ps == "pending":
                self.pending.append(i)
        self.n_scanned = len(docs)
        if grew:
            self._frozen = None
        return self

    def arrays(self):
        if self._frozen is None:
            self._frozen = (
                np.asarray(self.tids, dtype=np.int64),
                np.asarray(self.losses, dtype=np.float64),
                {
                    lb: np.asarray(p, dtype=np.int64)
                    for lb, p in self.label_pos.items()
                },
            )
        return self._frozen

    def split_obs(self, gamma, LF):
        """(obs_below, obs_above) per label -- reference-exact:
        (loss, tid)-sorted split, each side's observations in tid order."""
        tids, losses, label_pos = self.arrays()
        n_ok = len(tids)
        n_below = min(int(np.ceil(gamma * np.sqrt(n_ok))), int(LF))
        order = np.lexsort((tids, losses))  # by loss, ties by tid
        sides = []
        for pos in (order[:n_below], order[n_below:]):
            pos = pos[np.argsort(tids[pos], kind="stable")]  # tid order
            rank = np.full(n_ok, -1, dtype=np.int64)
            rank[pos] = np.arange(len(pos), dtype=np.int64)
            side = {}
            for lb in self.labels:
                lp = label_pos[lb]
                r = rank[lp] if len(lp) else np.empty(0, dtype=np.int64)
                sel = np.flatnonzero(r >= 0)
                sel = sel[np.argsort(r[sel], kind="stable")]
                vals = self.label_vals[lb]
                side[lb] = [vals[int(j)] for j in sel]
            sides.append(side)
        return sides[0], sides[1]


def _obs_index_for(domain, trials, labels):
    """Per-(domain, trials-store) cached index: a Domain reused across
    two Trials stores must never serve one store's observations for the
    other (the stateless pre-index host path was immune by construction,
    so the cache keys on the store's identity via a weakref)."""
    import weakref

    cache = getattr(domain, "_host_obs_index", None)
    idx = None
    if cache is not None:
        ref, idx_cached = cache
        if ref() is trials and idx_cached.labels == tuple(labels):
            idx = idx_cached
    if idx is None:
        idx = _ObsIndex(labels)
        domain._host_obs_index = (weakref.ref(trials), idx)
    return idx.sync(trials)


# ---------------------------------------------------------------------------
# suggest
# ---------------------------------------------------------------------------


def _posterior_draws(domain, trials, rng, prior_weight, n_EI_candidates, gamma, LF):
    """Unrouted per-label posterior EI-argmax draws (every label, whether
    or not it ends up active)."""
    helper = _domain_helper(domain)
    hps = helper.hps
    labels = sorted(hps)

    obs_below, obs_above = _obs_index_for(domain, trials, labels).split_obs(
        gamma, LF
    )

    return {
        label: posterior_draw(
            hps[label],
            obs_below[label],
            obs_above[label],
            rng,
            prior_weight,
            n_EI_candidates,
            LF,
        )
        for label in labels
    }


def _route_draws(domain, draws):
    """Route draws through the space graph: only labels on the chosen
    branches survive into the trial's active config."""
    helper = _domain_helper(domain)
    memo = {info.node: draws[label] for label, info in helper.hps.items()}
    active = {}

    def observer(node, value):
        if node.name == "hyperopt_param":
            active[node.pos_args[0].obj] = value

    rec_eval(domain.expr, memo=memo, observer=observer)
    return active


def _suggest_config(domain, trials, rng, prior_weight, n_EI_candidates, gamma, LF):
    """One new config: posterior EI-argmax per hyperparameter, activity
    routed through the space graph (factorized TPE, SURVEY.md SS3.2)."""
    draws = _posterior_draws(
        domain, trials, rng, prior_weight, n_EI_candidates, gamma, LF
    )
    return _route_draws(domain, draws)


def suggest_batch(
    new_ids,
    domain,
    trials,
    seed,
    prior_weight=_default_prior_weight,
    n_startup_jobs=_default_n_startup_jobs,
    n_EI_candidates=_default_n_EI_candidates,
    gamma=_default_gamma,
    linear_forgetting=_default_linear_forgetting,
):
    """Sparse (idxs, vals) for a batch of new ids via TPE."""
    rng = ensure_rng(seed)
    helper = _domain_helper(domain)
    labels = sorted(helper.hps)
    idxs = {label: [] for label in labels}
    vals = {label: [] for label in labels}

    n_ok = len(
        [
            t
            for t in trials.trials
            if t["state"] == JOB_STATE_DONE and t["result"].get("status") == STATUS_OK
        ]
    )
    for tid in new_ids:
        if n_ok < n_startup_jobs:
            config = helper.sample_one(rng)
        else:
            config = _suggest_config(
                domain, trials, rng, prior_weight, n_EI_candidates, gamma,
                linear_forgetting,
            )
        for label, value in config.items():
            idxs[label].append(tid)
            vals[label].append(value)
    return idxs, vals


def suggest(
    new_ids,
    domain,
    trials,
    seed,
    prior_weight=_default_prior_weight,
    n_startup_jobs=_default_n_startup_jobs,
    n_EI_candidates=_default_n_EI_candidates,
    gamma=_default_gamma,
    linear_forgetting=_default_linear_forgetting,
    verbose=True,
):
    """The algo plugin-boundary entry point: ``algo=tpe.suggest``."""
    idxs, vals = suggest_batch(
        new_ids,
        domain,
        trials,
        seed,
        prior_weight=prior_weight,
        n_startup_jobs=n_startup_jobs,
        n_EI_candidates=n_EI_candidates,
        gamma=gamma,
        linear_forgetting=linear_forgetting,
    )
    return docs_from_idxs_vals(new_ids, domain, trials, idxs, vals)
