"""pyll: a tiny lazy symbolic-expression DAG.

Capability parity with the reference's ``hyperopt/pyll/base.py`` (SURVEY.md
SS2 L0): ``Apply``/``Literal`` nodes, a ``scope`` symbol table with
``@scope.define``, ``as_apply`` literal lifting, an iterative ``rec_eval``
with memoization and lazy ``switch``, and graph utilities (``dfs``,
``toposort``, ``clone``, ``clone_merge``).

This is a fresh implementation designed as the *host-side* symbolic layer of
a TPU-native framework: pyll graphs describe search spaces and are either
interpreted on host (parity path) or *compiled* by
:mod:`hyperopt_tpu.ops.compile` into a single jitted stochastic program
(the TPU path).  The interpreter is deliberately small; nothing
performance-critical lives here.
"""

from __future__ import annotations

import copy
import operator

import numpy as np

from ..exceptions import PyllImportError

__all__ = [
    "Apply",
    "Literal",
    "Lambda",
    "SymbolTable",
    "scope",
    "as_apply",
    "rec_eval",
    "dfs",
    "toposort",
    "clone",
    "clone_merge",
    "stochastic_nodes",
]


class Apply:
    """A node in a pyll expression graph: a deferred call ``name(*pos_args,
    **named_args)``.

    Nodes are identity-hashed (graphs are DAGs of *objects*, two
    structurally equal nodes are distinct unless merged via
    :func:`clone_merge`).
    """

    def __init__(self, name, pos_args=(), named_args=(), o_len=None, pure=False):
        self.name = name
        self.pos_args = list(pos_args)
        # named_args kept sorted for deterministic traversal / printing
        self.named_args = sorted((str(k), v) for k, v in dict(named_args).items())
        self.o_len = o_len  # advertised len() of the result, if known
        self.pure = pure  # no side effects / no rng: safe to merge & memo
        for a in self.pos_args:
            assert isinstance(a, Apply), a
        for _, a in self.named_args:
            assert isinstance(a, Apply), a

    # -- graph structure ---------------------------------------------------
    def inputs(self):
        """All child nodes, positional then named (deterministic order)."""
        return self.pos_args + [v for _, v in self.named_args]

    @property
    def arg(self):
        """Named-argument view including positional args bound to the
        implementation's signature where possible (dict label -> node)."""
        binding = {}
        for i, a in enumerate(self.pos_args):
            binding[i] = a
        for k, v in self.named_args:
            binding[k] = v
        return binding

    def clone_from_inputs(self, inputs, o_len="same"):
        if len(inputs) != len(self.pos_args) + len(self.named_args):
            raise ValueError("clone_from_inputs: arity mismatch")
        npos = len(self.pos_args)
        new_pos = list(inputs[:npos])
        new_named = [(k, inputs[npos + i]) for i, (k, _) in enumerate(self.named_args)]
        if o_len == "same":
            o_len = self.o_len
        return self.__class__(self.name, new_pos, new_named, o_len, self.pure)

    def replace_input(self, old_node, new_node):
        """In-place substitution of a direct child; returns positions hit."""
        rval = []
        for i, a in enumerate(self.pos_args):
            if a is old_node:
                self.pos_args[i] = new_node
                rval.append(i)
        for i, (k, a) in enumerate(self.named_args):
            if a is old_node:
                self.named_args[i] = (k, new_node)
                rval.append(k)
        return rval

    # -- syntactic sugar ---------------------------------------------------
    def __getitem__(self, idx):
        if self.o_len is not None and isinstance(idx, int) and idx >= self.o_len:
            raise IndexError(idx)
        return scope.getitem(self, as_apply(idx))

    def __len__(self):
        if self.o_len is None:
            return object.__len__(self)
        return self.o_len

    def __add__(self, other):
        return scope.add(self, as_apply(other))

    def __radd__(self, other):
        return scope.add(as_apply(other), self)

    def __sub__(self, other):
        return scope.sub(self, as_apply(other))

    def __rsub__(self, other):
        return scope.sub(as_apply(other), self)

    def __mul__(self, other):
        return scope.mul(self, as_apply(other))

    def __rmul__(self, other):
        return scope.mul(as_apply(other), self)

    def __truediv__(self, other):
        return scope.truediv(self, as_apply(other))

    def __rtruediv__(self, other):
        return scope.truediv(as_apply(other), self)

    def __floordiv__(self, other):
        return scope.floordiv(self, as_apply(other))

    def __pow__(self, other):
        return scope.pow(self, as_apply(other))

    def __neg__(self):
        return scope.neg(self)

    def __gt__(self, other):
        return scope.gt(self, as_apply(other))

    def __ge__(self, other):
        return scope.ge(self, as_apply(other))

    def __lt__(self, other):
        return scope.lt(self, as_apply(other))

    def __le__(self, other):
        return scope.le(self, as_apply(other))

    def __call__(self, *args, **kwargs):
        return scope.call(self, as_apply(args), as_apply(kwargs))

    # -- printing ----------------------------------------------------------
    def pprint(self, memo=None, depth=0):
        if memo is None:
            memo = {}
        if self in memo:
            return "  " * depth + memo[self]
        memo[self] = f"<node_{len(memo)} {self.name}>"
        lines = ["  " * depth + self.name]
        for a in self.pos_args:
            lines.append(a.pprint(memo, depth + 1))
        for k, v in self.named_args:
            lines.append("  " * (depth + 1) + k + " =")
            lines.append(v.pprint(memo, depth + 2))
        return "\n".join(lines)

    def __repr__(self):
        return f"<pyll.Apply {self.name} @{hex(id(self))}>"

    def __str__(self):
        return self.pprint()


class Literal(Apply):
    """A leaf node wrapping a concrete Python object."""

    def __init__(self, obj=None):
        try:
            o_len = len(obj)
        except TypeError:
            o_len = None
        Apply.__init__(self, "literal", [], {}, o_len, pure=True)
        self._obj = obj

    @property
    def obj(self):
        return self._obj

    def clone_from_inputs(self, inputs, o_len="same"):
        return self.__class__(self._obj)

    def replace_input(self, old_node, new_node):
        return []

    def pprint(self, memo=None, depth=0):
        if memo is None:
            memo = {}
        if self in memo:
            return "  " * depth + memo[self]
        memo[self] = f"<lit_{len(memo)}>"
        return "  " * depth + f"Literal{{{self._obj!r}}}"

    def __repr__(self):
        return f"<pyll.Literal {self._obj!r}>"


class Lambda:
    """A deferred function over pyll graphs.

    ``Lambda('f', [('x', x_node)], expr)`` substitutes call arguments for
    the parameter placeholder nodes and returns a cloned body.  Parity with
    the reference's ``pyll.base.Lambda`` (SURVEY.md SS2, pyll core row).
    """

    def __init__(self, name, params, expr):
        self.__name__ = name
        self.params = list(params)  # list of (name, placeholder Apply)
        self.expr = as_apply(expr)

    def __call__(self, *args, **kwargs):
        memo = {}
        for arg, (pname, pnode) in zip(args, self.params):
            memo[pnode] = as_apply(arg)
        if len(args) < len(self.params):
            for pname, pnode in self.params[len(args):]:
                if pname in kwargs:
                    memo[pnode] = as_apply(kwargs.pop(pname))
        if kwargs:
            raise TypeError(f"unexpected keyword args {sorted(kwargs)}")
        return clone(self.expr, memo)


class UndefinedValue:
    """Sentinel for 'not yet computed' in rec_eval memos."""

    def __repr__(self):
        return "<undefined>"


_undefined = UndefinedValue()


def as_apply(obj):
    """Lift a Python object into a pyll graph.

    dicts -> ``scope.dict`` (sorted keys), lists/tuples -> ``pos_args``,
    Apply passes through, everything else wraps in :class:`Literal`.
    """
    if isinstance(obj, Apply):
        return obj
    if isinstance(obj, tuple):
        return Apply("pos_args", [as_apply(a) for a in obj], {}, len(obj), pure=True)
    if isinstance(obj, list):
        return Apply("pos_args", [as_apply(a) for a in obj], {}, len(obj), pure=True)
    if isinstance(obj, dict):
        items = sorted(obj.items(), key=lambda kv: str(kv[0]))
        named = {str(k): as_apply(v) for k, v in items}
        return Apply("dict", [], named, len(named), pure=True)
    return Literal(obj)


class SymbolTable:
    """Registry of named functions available to pyll graphs.

    ``scope.foo(*args)`` builds an ``Apply('foo', ...)`` node;
    ``@scope.define`` registers the implementation used by ``rec_eval``.
    """

    def __init__(self):
        self._impls = {}
        self._pure = set()

    # define / lookup ------------------------------------------------------
    def define_impl(self, name, f, pure=False, o_len=None):
        if name in self._impls:
            raise ValueError(f"Cannot override symbol {name!r}")
        self._impls[name] = f
        if pure:
            self._pure.add(name)
        return f

    def define(self, f):
        self.define_impl(f.__name__, f, pure=False)
        return f

    def define_pure(self, f):
        self.define_impl(f.__name__, f, pure=True)
        return f

    def define_info(self, o_len=None, pure=False):
        def deco(f):
            self.define_impl(f.__name__, f, pure=pure, o_len=o_len)
            return f

        return deco

    def undefine(self, name):
        self._impls.pop(name, None)
        self._pure.discard(name)

    def impl(self, name):
        try:
            return self._impls[name]
        except KeyError:
            raise PyllImportError(f"Undefined pyll symbol: {name!r}")

    def is_pure(self, name):
        return name in self._pure

    def __contains__(self, name):
        return name in self._impls

    def __getattr__(self, name):
        if name.startswith("__"):
            raise AttributeError(name)
        if name not in self._impls:
            raise AttributeError(f"scope has no symbol {name!r}")

        pure = name in self._pure

        def apply_builder(*args, **kwargs):
            pos = [as_apply(a) for a in args]
            named = {k: as_apply(v) for k, v in kwargs.items()}
            return Apply(name, pos, named, None, pure=pure)

        apply_builder.__name__ = name
        return apply_builder


scope = SymbolTable()


# ---------------------------------------------------------------------------
# graph utilities
# ---------------------------------------------------------------------------


def dfs(expr, seq=None, seen=None):
    """Depth-first post-order traversal (children before parents)."""
    if seq is None:
        assert seen is None
        seq, seen = [], set()
    if id(expr) in seen:
        return seq
    seen.add(id(expr))
    for a in expr.inputs():
        dfs(a, seq, seen)
    seq.append(expr)
    return seq


def toposort(expr):
    """Topological order of the DAG rooted at ``expr`` (inputs first)."""
    return dfs(expr)


def clone(expr, memo=None):
    """Deep-copy a graph; ``memo`` maps old nodes -> replacement nodes."""
    if memo is None:
        memo = {}
    nodes = dfs(expr)
    for node in nodes:
        if node not in memo:
            new_inputs = [memo[a] for a in node.inputs()]
            memo[node] = node.clone_from_inputs(new_inputs)
    return memo[expr]


def _node_key(node, memo):
    """Structural signature used by clone_merge."""
    if isinstance(node, Literal):
        try:
            hash(node.obj)
        except TypeError:
            return ("literal-id", id(node))
        return ("literal", node.obj)
    return (
        node.name,
        tuple(id(memo[a]) for a in node.pos_args),
        tuple((k, id(memo[a])) for k, a in node.named_args),
    )


def clone_merge(expr, memo=None, merge_literals=False):
    """Clone while merging structurally identical *pure* subgraphs."""
    if memo is None:
        memo = {}
    canon = {}
    for node in dfs(expr):
        if node in memo:
            continue
        new_inputs = [memo[a] for a in node.inputs()]
        mergeable = node.pure and (merge_literals or not isinstance(node, Literal))
        if mergeable:
            key = _node_key(node, memo)
            if key in canon:
                memo[node] = canon[key]
                continue
            new_node = node.clone_from_inputs(new_inputs)
            canon[key] = new_node
            memo[node] = new_node
        else:
            memo[node] = node.clone_from_inputs(new_inputs)
    return memo[expr]


def stochastic_nodes(expr, stoch_names):
    """All nodes in ``expr`` whose name is in ``stoch_names``."""
    return [n for n in dfs(expr) if n.name in stoch_names]


# ---------------------------------------------------------------------------
# evaluation
# ---------------------------------------------------------------------------


def _eval_apply(node, evaluated):
    """Call a node's implementation on already-evaluated inputs."""
    if isinstance(node, Literal):
        return node.obj
    f = scope.impl(node.name)
    args = [evaluated[id(a)] for a in node.pos_args]
    kwargs = {k: evaluated[id(a)] for k, a in node.named_args}
    return f(*args, **kwargs)


def rec_eval(
    expr, memo=None, max_program_len=100_000, deepcopy_inputs=False, observer=None
):
    """Evaluate a pyll graph.

    Iterative work-stack evaluator (no Python recursion limit) with:

    * ``memo``: dict node -> value overriding evaluation of those nodes
      (this is how Domain substitutes sampled hyperparameter values);
    * lazy ``switch``: only the selected branch is evaluated -- this is what
      makes conditional (``hp.choice``) spaces cheap on host;
    * cycle / runaway-program detection via ``max_program_len``;
    * optional ``observer(node, value)`` hook fired as each Apply node
      resolves -- used by the batch sampler to record which labeled
      hyperparameters were active for a trial.

    Parity: reference ``pyll/base.py rec_eval`` (SURVEY.md SS3.1/SS3.3).
    """
    node = as_apply(expr)
    evaluated = {}  # id(node) -> value
    if memo:
        for k, v in memo.items():
            evaluated[id(k)] = v

    stack = [node]
    steps = 0
    while stack:
        steps += 1
        if steps > max_program_len:
            raise RuntimeError("rec_eval: max program length exceeded")
        current = stack[-1]
        if id(current) in evaluated:
            stack.pop()
            continue

        if isinstance(current, Literal):
            evaluated[id(current)] = current.obj
            stack.pop()
            continue

        if current.name == "switch":
            # lazily evaluate: index first, then only the chosen branch
            idx_node = current.pos_args[0]
            if id(idx_node) not in evaluated:
                stack.append(idx_node)
                continue
            idx = int(evaluated[id(idx_node)])
            options = current.pos_args[1:]
            if not 0 <= idx < len(options):
                raise IndexError(
                    f"switch index {idx} out of range for {len(options)} options"
                )
            chosen = options[idx]
            if id(chosen) not in evaluated:
                stack.append(chosen)
                continue
            evaluated[id(current)] = evaluated[id(chosen)]
            if observer is not None:
                observer(current, evaluated[id(current)])
            stack.pop()
            continue

        waiting = [a for a in current.inputs() if id(a) not in evaluated]
        if waiting:
            stack.extend(waiting)
            continue

        rval = _eval_apply(current, evaluated)
        if deepcopy_inputs:
            rval = copy.deepcopy(rval)
        evaluated[id(current)] = rval
        if observer is not None:
            observer(current, rval)
        stack.pop()

    return evaluated[id(node)]


# ---------------------------------------------------------------------------
# built-in scope symbols
# ---------------------------------------------------------------------------


@scope.define_pure
def pos_args(*args):
    return list(args)


def _dict_impl(**kwargs):
    return kwargs


# registered via define_impl so the module namespace keeps the builtins
scope.define_impl("dict", _dict_impl, pure=True)
scope.define_impl("int", int, pure=True)
scope.define_impl("float", float, pure=True)
scope.define_impl("len", len, pure=True)


@scope.define_pure
def getitem(obj, idx):
    return obj[idx]


@scope.define_pure
def identity(obj):
    return obj


@scope.define
def call(f, args, kwargs):
    return f(*args, **kwargs)


@scope.define_pure
def switch(idx, *options):
    # Only reached when rec_eval's lazy special-case is bypassed
    # (e.g. a user calls the impl directly); semantics identical.
    return options[int(idx)]


@scope.define_pure
def hyperopt_param(label, obj):
    """Marker wrapping a stochastic node with a user-facing label."""
    return obj


# arithmetic ---------------------------------------------------------------

for _name, _f in [
    ("add", operator.add),
    ("sub", operator.sub),
    ("mul", operator.mul),
    ("truediv", operator.truediv),
    ("floordiv", operator.floordiv),
    ("pow", operator.pow),
    ("mod", operator.mod),
    ("gt", operator.gt),
    ("ge", operator.ge),
    ("lt", operator.lt),
    ("le", operator.le),
    ("eq", operator.eq),
    ("neg", operator.neg),
]:
    scope.define_impl(_name, _f, pure=True)

scope.define_impl("div", operator.truediv, pure=True)


@scope.define_pure
def exp(x):
    return np.exp(x)


@scope.define_pure
def log(x):
    return np.log(x)


@scope.define_pure
def sqrt(x):
    return np.sqrt(x)


@scope.define_pure
def floor(x):
    return np.floor(x)


@scope.define_pure
def ceil(x):
    return np.ceil(x)


@scope.define_pure
def maximum(x, y):
    return np.maximum(x, y)


@scope.define_pure
def minimum(x, y):
    return np.minimum(x, y)


scope.define_impl("max", max, pure=True)
scope.define_impl("min", min, pure=True)
scope.define_impl("abs", abs, pure=True)


@scope.define_pure
def array_union(a, b):
    return np.union1d(a, b)


@scope.define_pure
def asarray(a, dtype=None):
    if dtype is None:
        return np.asarray(a)
    return np.asarray(a, dtype=dtype)


@scope.define_pure
def str_join(sep, seq):
    return sep.join(seq)


@scope.define
def partial(name, *args, **kwargs):
    """Return a callable applying scope symbol ``name`` with bound args."""
    f = scope.impl(name)

    def caller(*more, **kwmore):
        kw = {**kwargs, **kwmore}
        return f(*(args + more), **kw)

    caller.__name__ = f"partial({name})"
    return caller
