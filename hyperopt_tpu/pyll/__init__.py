"""pyll: symbolic expression graphs for search spaces (host-side layer L0)."""

from . import base, stochastic
from .base import (
    Apply,
    Lambda,
    Literal,
    SymbolTable,
    as_apply,
    clone,
    clone_merge,
    dfs,
    rec_eval,
    scope,
    stochastic_nodes,
    toposort,
)
from .stochastic import sample, recursive_set_rng_kwarg, STOCHASTIC_NAMES

__all__ = [
    "Apply",
    "Lambda",
    "Literal",
    "SymbolTable",
    "as_apply",
    "base",
    "clone",
    "clone_merge",
    "dfs",
    "rec_eval",
    "recursive_set_rng_kwarg",
    "sample",
    "scope",
    "stochastic",
    "stochastic_nodes",
    "toposort",
    "STOCHASTIC_NAMES",
]
