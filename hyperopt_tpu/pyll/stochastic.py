"""Stochastic pyll ops and the host-side sampling driver.

Capability parity with the reference's ``hyperopt/pyll/stochastic.py``
(SURVEY.md SS2): distribution ops registered into ``scope``, RNG threading
via ``recursive_set_rng_kwarg``, and ``sample(expr, rng)``.

These numpy implementations are the *oracle* path.  The TPU path does not
interpret these nodes at all -- :mod:`hyperopt_tpu.ops.compile` lowers the
same graph to one jitted JAX program (SURVEY.md SS7 design stance #1).
"""

from __future__ import annotations

import numpy as np

from .base import Apply, as_apply, clone, dfs, rec_eval, scope

__all__ = [
    "STOCHASTIC_NAMES",
    "sample",
    "recursive_set_rng_kwarg",
    "replace_repeat_stochastic",
    "ensure_rng",
]


def ensure_rng(rng):
    """Accept a seed, ``np.random.Generator``, ``RandomState`` or None."""
    if rng is None:
        return np.random.default_rng()
    if isinstance(rng, (int, np.integer)):
        return np.random.default_rng(int(rng))
    return rng


def _size_tuple(size):
    if size == () or size is None:
        return ()
    if isinstance(size, (int, np.integer)):
        return (int(size),)
    return tuple(int(s) for s in size)


# ---------------------------------------------------------------------------
# distribution implementations
# ---------------------------------------------------------------------------


@scope.define
def uniform(low, high, rng=None, size=()):
    rng = ensure_rng(rng)
    return rng.uniform(low, high, size=_size_tuple(size))


@scope.define
def loguniform(low, high, rng=None, size=()):
    rng = ensure_rng(rng)
    return np.exp(rng.uniform(low, high, size=_size_tuple(size)))


@scope.define
def quniform(low, high, q, rng=None, size=()):
    rng = ensure_rng(rng)
    draw = rng.uniform(low, high, size=_size_tuple(size))
    return np.round(draw / q) * q


@scope.define
def qloguniform(low, high, q, rng=None, size=()):
    rng = ensure_rng(rng)
    draw = np.exp(rng.uniform(low, high, size=_size_tuple(size)))
    return np.round(draw / q) * q


@scope.define
def normal(mu, sigma, rng=None, size=()):
    rng = ensure_rng(rng)
    return rng.normal(mu, sigma, size=_size_tuple(size))


@scope.define
def qnormal(mu, sigma, q, rng=None, size=()):
    rng = ensure_rng(rng)
    return np.round(rng.normal(mu, sigma, size=_size_tuple(size)) / q) * q


@scope.define
def lognormal(mu, sigma, rng=None, size=()):
    rng = ensure_rng(rng)
    return np.exp(rng.normal(mu, sigma, size=_size_tuple(size)))


@scope.define
def qlognormal(mu, sigma, q, rng=None, size=()):
    rng = ensure_rng(rng)
    draw = np.exp(rng.normal(mu, sigma, size=_size_tuple(size)))
    return np.round(draw / q) * q


@scope.define
def randint(low, high=None, rng=None, size=()):
    """``randint(upper)`` -> [0, upper); ``randint(low, high)`` -> [low, high)."""
    rng = ensure_rng(rng)
    if high is None:
        low, high = 0, low
    return rng.integers(int(low), int(high), size=_size_tuple(size))


@scope.define
def categorical(p, rng=None, size=()):
    """Draw index ~ Categorical(p)."""
    rng = ensure_rng(rng)
    p = np.asarray(p, dtype=float)
    p = p / p.sum()
    size = _size_tuple(size)
    n = int(np.prod(size)) if size else 1
    draws = rng.choice(len(p), size=n, p=p)
    if not size:
        return draws[0]
    return draws.reshape(size)


@scope.define
def randint_via_categorical(p, rng=None, size=()):
    """Categorical draw standing in for a randint node; used by the TPE
    posterior over integer hyperparameters (SURVEY.md SS2 TPE row (b))."""
    return categorical(p, rng=rng, size=size)


@scope.define
def repeat(n_times, obj):
    return [obj] * int(n_times)


STOCHASTIC_NAMES = (
    "uniform",
    "loguniform",
    "quniform",
    "qloguniform",
    "normal",
    "qnormal",
    "lognormal",
    "qlognormal",
    "randint",
    "categorical",
    "randint_via_categorical",
    # TPE posterior mixture draws are stochastic too (defined in tpe.py):
    "GMM1",
    "LGMM1",
)


def recursive_set_rng_kwarg(expr, rng_node=None):
    """Attach ``rng=rng_node`` to every stochastic node lacking one.

    Mutates the graph in place (matches reference semantics) and returns it.
    """
    if rng_node is None:
        rng_node = as_apply(np.random.default_rng())
    rng_node = as_apply(rng_node)
    for node in dfs(as_apply(expr)):
        if node.name in STOCHASTIC_NAMES:
            if "rng" not in [k for k, _ in node.named_args]:
                node.named_args.append(("rng", rng_node))
                node.named_args.sort()
    return expr


def sample(expr, rng=None, **kwargs):
    """Draw one sample from a stochastic pyll graph."""
    rng = ensure_rng(rng)
    cloned = clone(as_apply(expr))
    recursive_set_rng_kwarg(cloned, as_apply(rng))
    return rec_eval(cloned, **kwargs)


def replace_repeat_stochastic(expr, return_memo=False):
    """Rewrite ``repeat(n, stochastic(...))`` into a single vector draw
    ``stochastic(..., size=n)`` -- the batch-vectorization primitive used by
    :mod:`hyperopt_tpu.vectorize` (parity: reference
    ``pyll/stochastic.py replace_repeat_stochastic``)."""
    nodes = dfs(as_apply(expr))
    memo = {}
    for node in nodes:
        if node.name != "repeat":
            continue
        n_times, inner = node.pos_args
        if inner.name not in STOCHASTIC_NAMES:
            continue
        named = dict(inner.named_args)
        if "size" in named:
            continue  # already vectorized
        named["size"] = n_times
        vnode = Apply(inner.name, list(inner.pos_args), named, None, pure=False)
        memo[node] = vnode
        # splice into parents
        for parent in nodes:
            parent.replace_input(node, vnode)
    new_expr = memo.get(expr, expr)
    if return_memo:
        return new_expr, memo
    return new_expr
