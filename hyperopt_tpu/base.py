"""Experiment core: trial documents, ``Trials`` history store, ``Domain``.

Capability parity with the reference's ``hyperopt/base.py`` (SURVEY.md SS2):
``Trials`` (refresh / new_trial_ids / new_trial_docs / insert / losses /
statuses / best_trial / argmin / average_best_error / attachments),
``trials_from_docs``, ``miscs_to_idxs_vals``, ``miscs_update_idxs_vals``,
``spec_from_misc``, ``SONify``, ``Domain`` (the objective wrapper) and
``Ctrl`` (async job handle).

Trial documents are JSON-ish dicts::

    {tid, state, spec, result{status, loss, ...},
     misc{tid, cmd, idxs, vals, workdir}, exp_key, owner, version,
     book_time, refresh_time}

The sparse ``idxs/vals`` encoding: ``misc['vals'][label]`` is ``[value]`` if
the hyperparameter was active for this trial and ``[]`` if not (conditional
``hp.choice`` branches) -- SURVEY.md SS3.3.  The on-device mirror of this
store lives in :mod:`hyperopt_tpu.jax_trials` (dense arrays + masks).
"""

from __future__ import annotations

import logging

import numpy as np

from .exceptions import (
    AllTrialsFailed,
    InvalidLoss,
    InvalidResultStatus,
    InvalidTrial,
)
from .pyll.base import as_apply, rec_eval
from .pyll_utils import expr_to_config
from .utils import coarse_utcnow

logger = logging.getLogger(__name__)

__all__ = [
    "JOB_STATE_NEW",
    "JOB_STATE_RUNNING",
    "JOB_STATE_DONE",
    "JOB_STATE_ERROR",
    "JOB_STATE_CANCEL",
    "JOB_STATES",
    "JOB_VALID_STATES",
    "STATUS_NEW",
    "STATUS_RUNNING",
    "STATUS_SUSPENDED",
    "STATUS_OK",
    "STATUS_FAIL",
    "STATUS_STRINGS",
    "Trials",
    "trials_from_docs",
    "Domain",
    "Ctrl",
    "miscs_to_idxs_vals",
    "miscs_update_idxs_vals",
    "spec_from_misc",
    "SONify",
]

# -- job states (trial lifecycle) ------------------------------------------
JOB_STATE_NEW = 0
JOB_STATE_RUNNING = 1
JOB_STATE_DONE = 2
JOB_STATE_ERROR = 3
JOB_STATE_CANCEL = 4
JOB_STATES = (
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_CANCEL,
)
JOB_VALID_STATES = JOB_STATES

# -- result statuses (objective-reported) ----------------------------------
STATUS_NEW = "new"
STATUS_RUNNING = "running"
STATUS_SUSPENDED = "suspended"
STATUS_OK = "ok"
STATUS_FAIL = "fail"
STATUS_STRINGS = (STATUS_NEW, STATUS_RUNNING, STATUS_SUSPENDED, STATUS_OK, STATUS_FAIL)


def posterior_state(trial):
    """Classify a trial doc for posterior ingestion -- THE shared
    predicate of every observation mirror (host ``tpe._ObsIndex``, device
    ``jax_trials.ObsBuffer``, and the reference-shaped filters):

      * ``"ok"``      -- completed, status ok, finite loss: ingest.
      * ``"pending"`` -- may still become ok: NEW/RUNNING state, or a
        DONE state whose result still reads new/running (an async worker
        writes ``state`` and ``result`` as two plain stores; a reader in
        that window must keep waiting, not evict the trial).
      * ``"dead"``    -- will never produce an observation (ERROR,
        CANCEL, failed/suspended status, missing or non-finite loss).
    """
    state = trial["state"]
    if state in (JOB_STATE_NEW, JOB_STATE_RUNNING):
        return "pending"
    if state == JOB_STATE_DONE:
        status = trial["result"].get("status")
        if status == STATUS_OK:
            loss = trial["result"].get("loss")
            if loss is not None and np.isfinite(float(loss)):
                return "ok"
            return "dead"
        if status in (STATUS_NEW, STATUS_RUNNING):
            return "pending"  # mid-write race window
        return "dead"
    return "dead"

TRIAL_KEYS = frozenset(
    [
        "tid",
        "spec",
        "result",
        "misc",
        "state",
        "owner",
        "book_time",
        "refresh_time",
        "exp_key",
        "version",
    ]
)
TRIAL_MISC_KEYS = frozenset(["tid", "cmd", "idxs", "vals"])


def SONify(arg):
    """Recursively convert numpy scalars/arrays to plain JSON-able Python."""
    if isinstance(arg, dict):
        return {SONify(k): SONify(v) for k, v in arg.items()}
    if isinstance(arg, (list, tuple)):
        return [SONify(a) for a in arg]
    if isinstance(arg, np.ndarray):
        return [SONify(a) for a in arg.tolist()] if arg.ndim else SONify(arg.item())
    if isinstance(arg, np.integer):
        return int(arg)
    if isinstance(arg, np.floating):
        return float(arg)
    if isinstance(arg, np.bool_):
        return bool(arg)
    if isinstance(arg, (str, bytes, int, float, bool)) or arg is None:
        return arg
    if hasattr(arg, "item"):  # 0-d jax arrays etc.
        return SONify(arg.item())
    return arg


def miscs_to_idxs_vals(miscs, keys=None):
    """Aggregate per-trial sparse encodings into {label: [tids]}, {label: [vals]}."""
    if keys is None:
        if len(miscs) == 0:
            raise ValueError("cannot infer keys from empty miscs")
        keys = list(miscs[0]["idxs"].keys())
    idxs = {k: [] for k in keys}
    vals = {k: [] for k in keys}
    for misc in miscs:
        for k in keys:
            t_idxs = misc["idxs"].get(k, [])
            t_vals = misc["vals"].get(k, [])
            assert len(t_idxs) == len(t_vals) <= 1, (k, t_idxs, t_vals)
            idxs[k].extend(t_idxs)
            vals[k].extend(t_vals)
    return idxs, vals


def miscs_update_idxs_vals(miscs, idxs, vals, assert_all_vals_used=True, idxs_map=None):
    """Scatter aggregated {label: tids/vals} back into per-trial miscs."""
    if idxs_map is None:
        idxs_map = {}
    misc_by_id = {m["tid"]: m for m in miscs}
    for m in miscs:
        m["idxs"] = {k: [] for k in idxs}
        m["vals"] = {k: [] for k in idxs}
    n_used = 0
    for k, tids in idxs.items():
        for tid, val in zip(tids, vals[k]):
            tid = idxs_map.get(tid, tid)
            if tid in misc_by_id:
                misc_by_id[tid]["idxs"][k] = [tid]
                misc_by_id[tid]["vals"][k] = [val]
                n_used += 1
            elif assert_all_vals_used:
                raise ValueError(f"tid {tid} not found among miscs")
    return miscs


def spec_from_misc(misc):
    """Config dict {label: value} for one trial's sparse misc encoding."""
    spec = {}
    for k, v in misc["vals"].items():
        if len(v) == 0:
            continue
        if len(v) == 1:
            spec[k] = v[0]
        else:
            raise NotImplementedError(f"multiple values for label {k}: {v}")
    return spec


def validate_trial(trial):
    if not isinstance(trial, dict):
        raise InvalidTrial(f"trial should be a dict, got {type(trial)}")
    missing = TRIAL_KEYS - set(trial)
    if missing:
        raise InvalidTrial(f"trial missing keys {sorted(missing)}")
    if trial["state"] not in JOB_VALID_STATES:
        raise InvalidTrial(f"invalid state {trial['state']!r}")
    misc = trial["misc"]
    if not isinstance(misc, dict):
        raise InvalidTrial("trial['misc'] must be a dict")
    missing_misc = TRIAL_MISC_KEYS - set(misc)
    if missing_misc:
        raise InvalidTrial(f"trial['misc'] missing keys {sorted(missing_misc)}")
    if trial["tid"] != misc["tid"]:
        raise InvalidTrial(f"tid mismatch: {trial['tid']} != {misc['tid']}")
    return trial


class Trials:
    """In-memory experiment history: a list of trial documents.

    Synchronous, single-process store (reference ``base.Trials``).
    Subclasses override ``asynchronous`` / ``refresh`` to provide
    distributed stores (see :mod:`hyperopt_tpu.distributed`).
    """

    asynchronous = False

    def __init__(self, exp_key=None, refresh=True):
        self._ids = set()
        self._dynamic_trials = []
        self._exp_key = exp_key
        self.attachments = {}
        self._trials = []
        if refresh:
            self.refresh()

    # -- basics ------------------------------------------------------------
    def view(self, exp_key=None, refresh=True):
        rval = object.__new__(self.__class__)
        rval._exp_key = exp_key
        rval._ids = self._ids
        rval._dynamic_trials = self._dynamic_trials
        rval.attachments = self.attachments
        if refresh:
            rval.refresh()
        return rval

    def aname(self, trial, name):
        return f"ATTACH::{trial['tid']}::{name}"

    def trial_attachments(self, trial):
        """Mapping-like view over one trial's binary attachments."""
        store = self.attachments
        aname = self.aname

        class _View:
            def __contains__(self, name):
                return aname(trial, name) in store

            def __getitem__(self, name):
                return store[aname(trial, name)]

            def __setitem__(self, name, value):
                store[aname(trial, name)] = value

            def __delitem__(self, name):
                del store[aname(trial, name)]

        return _View()

    def __iter__(self):
        return iter(self._trials)

    def __len__(self):
        return len(self._trials)

    def __getitem__(self, item):
        return self._trials[item]

    def refresh(self):
        if self._exp_key is None:
            self._trials = list(self._dynamic_trials)
        else:
            self._trials = [
                t for t in self._dynamic_trials if t["exp_key"] == self._exp_key
            ]
        self._ids.update(t["tid"] for t in self._trials)

    @property
    def trials(self):
        return self._trials

    @property
    def tids(self):
        return [t["tid"] for t in self._trials]

    @property
    def specs(self):
        return [t["spec"] for t in self._trials]

    @property
    def results(self):
        return [t["result"] for t in self._trials]

    @property
    def miscs(self):
        return [t["misc"] for t in self._trials]

    @property
    def idxs_vals(self):
        return miscs_to_idxs_vals(self.miscs)

    @property
    def idxs(self):
        return self.idxs_vals[0]

    @property
    def vals(self):
        return self.idxs_vals[1]

    # -- ids / insertion ---------------------------------------------------
    def new_trial_ids(self, n):
        aa = len(self._ids)
        rval = list(range(aa, aa + n))
        self._ids.update(rval)
        return rval

    def new_trial_docs(self, tids, specs, results, miscs):
        rval = []
        for tid, spec, result, misc in zip(tids, specs, results, miscs):
            doc = {
                "state": JOB_STATE_NEW,
                "tid": tid,
                "spec": spec,
                "result": result,
                "misc": misc,
                "exp_key": self._exp_key,
                "owner": None,
                "version": 0,
                "book_time": None,
                "refresh_time": None,
            }
            rval.append(doc)
        return rval

    def source_trial_docs(self, tids, specs, results, miscs, sources):
        rval = self.new_trial_docs(tids, specs, results, miscs)
        for doc in rval:
            doc["misc"]["from_tid"] = [s["tid"] for s in sources]
        return rval

    def _insert_trial_docs(self, docs):
        self._dynamic_trials.extend(docs)
        return [d["tid"] for d in docs]

    def insert_trial_doc(self, doc):
        return self._insert_trial_docs([validate_trial(SONify(doc))])[0]

    def insert_trial_docs(self, docs):
        return self._insert_trial_docs([validate_trial(SONify(d)) for d in docs])

    def delete_all(self):
        self._dynamic_trials = []
        self._ids = set()
        self.attachments = {}
        self.refresh()

    # -- queries -----------------------------------------------------------
    def count_by_state_synced(self, arg, trials=None):
        """Number of *synced* (post-refresh) trials in the given state(s)."""
        if trials is None:
            trials = self._trials
        if isinstance(arg, int):
            queue = [t for t in trials if t["state"] == arg]
        else:
            states = set(arg)
            queue = [t for t in trials if t["state"] in states]
        return len(queue)

    def count_by_state_unsynced(self, arg):
        """Number of trials in state(s) counting unsynced dynamic docs."""
        if self._exp_key is not None:
            exp_trials = [
                t for t in self._dynamic_trials if t["exp_key"] == self._exp_key
            ]
        else:
            exp_trials = self._dynamic_trials
        return self.count_by_state_synced(arg, trials=exp_trials)

    def losses(self, bandit=None):
        if bandit is None:
            return [r.get("loss") for r in self.results]
        return [bandit.loss(r, s) for r, s in zip(self.results, self.specs)]

    def statuses(self, bandit=None):
        if bandit is None:
            return [r.get("status") for r in self.results]
        return [bandit.status(r, s) for r, s in zip(self.results, self.specs)]

    @property
    def best_trial(self):
        """Trial with lowest loss among status-ok completed trials."""
        candidates = [
            t
            for t in self._trials
            if t["state"] == JOB_STATE_DONE
            and t["result"].get("status") == STATUS_OK
            and t["result"].get("loss") is not None
        ]
        if not candidates:
            raise AllTrialsFailed()
        losses = np.array([float(t["result"]["loss"]) for t in candidates])
        if np.all(np.isnan(losses)):
            raise AllTrialsFailed()
        return candidates[int(np.nanargmin(losses))]

    @property
    def argmin(self):
        """Best config as {label: value} (choices are indices)."""
        return spec_from_misc(self.best_trial["misc"])

    def average_best_error(self, bandit=None):
        """Mean of true-losses of trials within 3 sigma of the best loss.

        Parity: reference ``Trials.average_best_error`` -- uses
        ``true_loss`` when provided, weighting by loss variance.
        """

        def fmap(f):
            rval = np.asarray(
                [
                    f(r, s)
                    for (r, s) in zip(self.results, self.specs)
                    if (bandit.status(r) if bandit else r.get("status")) == STATUS_OK
                ]
            ).astype(float)
            if not np.all(np.isfinite(rval)):
                raise ValueError("non-finite losses in average_best_error")
            return rval

        if bandit is None:
            def loss(r, s):
                return r.get("loss")

            def loss_v(r, s):
                return r.get("loss_variance", 0)

            def true_loss(r, s):
                return r.get("true_loss", r.get("loss"))
        else:
            loss, loss_v, true_loss = bandit.loss, bandit.loss_variance, bandit.true_loss

        loss3 = list(zip(fmap(loss), fmap(loss_v), fmap(true_loss)))
        if not loss3:
            raise AllTrialsFailed()
        loss3.sort()
        loss3 = np.asarray(loss3)
        if np.all(loss3[:, 1] == 0):
            best_idx = int(np.argmin(loss3[:, 0]))
            return loss3[best_idx, 2]
        cutoff = 0
        sigma = np.sqrt(loss3[0][1])
        while cutoff < len(loss3) and loss3[cutoff][0] < loss3[0][0] + 3 * sigma:
            cutoff += 1
        return np.mean(loss3[:cutoff, 2])

    # -- convenience -------------------------------------------------------
    def fmin(self, fn, space, algo=None, max_evals=None, **kwargs):
        """Minimize ``fn`` over ``space``, storing trials in self."""
        from .fmin import fmin as _fmin  # local import avoids cycle

        return _fmin(
            fn, space, algo=algo, max_evals=max_evals, trials=self, **kwargs
        )


def trials_from_docs(docs, validate=True, **kwargs):
    """Build a Trials object from a list of trial documents."""
    rval = Trials(**kwargs)
    if validate:
        rval.insert_trial_docs(docs)
    else:
        rval._insert_trial_docs(docs)
    rval.refresh()
    return rval


class Ctrl:
    """Job-control handle passed to objectives that ask for it.

    Parity: reference ``base.Ctrl`` (checkpoint / attachments /
    inject_results) -- SURVEY.md SS2.
    """

    info = logger.info
    warn = logger.warning
    error = logger.error
    debug = logger.debug

    def __init__(self, trials, current_trial=None):
        self.trials = trials
        self.current_trial = current_trial

    @property
    def attachments(self):
        """Attachment view scoped to the current trial."""
        return self.trials.trial_attachments(trial=self.current_trial)

    def checkpoint(self, result=None):
        """Persist a partial result for the running trial."""
        assert self.current_trial in self.trials._dynamic_trials
        if result is not None:
            self.current_trial["result"] = SONify(result)
            self.current_trial["refresh_time"] = coarse_utcnow()

    def inject_results(self, specs, results, miscs, new_tids=None):
        """Inject pre-evaluated trials (DONE) into the store from inside an
        objective -- used for population/batched evaluation strategies."""
        trial = self.current_trial
        assert trial is not None
        num = len(specs)
        if new_tids is not None:
            assert num == len(new_tids)
        else:
            new_tids = self.trials.new_trial_ids(num)
        docs = self.trials.source_trial_docs(
            tids=new_tids, specs=specs, results=results, miscs=miscs, sources=[trial]
        )
        for doc in docs:
            doc["state"] = JOB_STATE_DONE
        return self.trials.insert_trial_docs(docs)


class Domain:
    """Binds a user objective ``fn`` to a search space.

    Evaluation: ``memo_from_config`` substitutes sampled values at the
    labeled nodes, ``rec_eval`` materializes the (possibly nested) config,
    and ``fn`` is called on it (SURVEY.md SS3.1).
    """

    rec_eval_print_node_on_error = False

    def __init__(
        self,
        fn,
        expr,
        workdir=None,
        pass_expr_memo_ctrl=None,
        name=None,
        loss_target=None,
    ):
        self.fn = fn
        if pass_expr_memo_ctrl is None:
            self.pass_expr_memo_ctrl = getattr(fn, "fmin_pass_expr_memo_ctrl", False)
        else:
            self.pass_expr_memo_ctrl = pass_expr_memo_ctrl

        self.expr = as_apply(expr)
        self.workdir = workdir
        self.name = name
        self.loss_target = loss_target

        # label -> ParamInfo (validates labels, detects DuplicateLabel)
        self.hps = expr_to_config(self.expr)
        # label -> distribution node (memo substitution point)
        self.params = {label: info.node for label, info in self.hps.items()}

        self.cmd = ("domain_attachment", "FMinIter_Domain")

    # -- evaluation --------------------------------------------------------
    def memo_from_config(self, config):
        memo = {}
        for label, node in self.params.items():
            if label in config:
                memo[node] = config[label]
        return memo

    def evaluate(self, config, ctrl, attach_attachments=True):
        memo = self.memo_from_config(config)
        if self.pass_expr_memo_ctrl:
            rval = self.fn(expr=self.expr, memo=memo, ctrl=ctrl)
        else:
            pyll_rval = rec_eval(self.expr, memo=memo)
            rval = self.fn(pyll_rval)

        if isinstance(rval, (float, int, np.number)):
            loss = float(rval)
            if not np.isfinite(loss):
                # NaN/Inf quarantine: a non-finite loss is recorded as
                # a FAILED trial -- never as an "ok" observation that
                # would poison best_trial/loss_threshold and every
                # subsequent suggestion's above/below split
                result = {
                    "status": STATUS_FAIL,
                    "loss": None,
                    "failure": f"non-finite loss {loss!r}",
                }
            else:
                result = {"status": STATUS_OK, "loss": loss}
        elif isinstance(rval, dict):
            result = dict(rval)
            status = result.get("status")
            if status not in STATUS_STRINGS:
                raise InvalidResultStatus(
                    f"objective returned invalid status {status!r}"
                )
            if status == STATUS_OK:
                try:
                    result["loss"] = float(result["loss"])
                except (KeyError, TypeError, ValueError):
                    raise InvalidLoss(
                        f"objective with status 'ok' must return a float loss, "
                        f"got {result.get('loss')!r}"
                    )
                if not np.isfinite(result["loss"]):
                    # same quarantine for the dict-result path
                    result["failure"] = (
                        f"non-finite loss {result['loss']!r}"
                    )
                    result["status"] = STATUS_FAIL
                    result["loss"] = None
        else:
            raise InvalidResultStatus(
                f"objective must return float or dict, got {type(rval)}"
            )

        if attach_attachments:
            attachments = result.pop("attachments", {})
            for key, val in attachments.items():
                ctrl.attachments[key] = val
        return result

    def evaluate_async(self, config, ctrl, attach_attachments=True):
        """Deferred variant for backends that run objectives elsewhere."""
        return self.evaluate(config, ctrl, attach_attachments=attach_attachments)

    def short_str(self):
        return f"Domain{{{getattr(self.fn, '__name__', self.fn)!r}}}"

    # -- result accessors --------------------------------------------------
    def loss(self, result, config=None):
        return result.get("loss")

    def loss_variance(self, result, config=None):
        return result.get("loss_variance", 0.0)

    def true_loss(self, result, config=None):
        return result.get("true_loss", result.get("loss"))

    def true_loss_variance(self, config=None):
        raise NotImplementedError()

    def status(self, result, config=None):
        return result["status"]

    def new_result(self):
        return {"status": STATUS_NEW}
