"""Random search via the compiled space sampler (jitted prior draws).

TPU equivalent of :mod:`hyperopt_tpu.rand`: one XLA program draws the whole
batch (dense values + active masks) instead of interpreting the pyll graph
per trial (SURVEY.md SS3.3 -> SS7 stance #1).

``partial(rand_jax.suggest, speculative=k)`` serves k sequential asks
from one k-wide dispatch.  Unlike TPE, the prior never goes stale, so
the cached columns are exact (not an accepted staleness profile) -- the
only invalidation is cache drain or a different trials store.
"""

from __future__ import annotations

from .jax_trials import host_key, packed_space_for
from .rand import docs_from_idxs_vals
from .tpe_jax import _cast_vals
from .vectorize import dense_to_idxs_vals

__all__ = ["suggest", "suggest_batch"]


def _dense_draw(domain, seed, batch):
    import jax

    ps = packed_space_for(domain)
    key = host_key(int(seed) % (2**31 - 1))
    values, active = ps.sample_prior(key, batch)
    return jax.device_get((values, active))


def suggest_batch(new_ids, domain, trials, seed):
    ps = packed_space_for(domain)
    values, active = _dense_draw(domain, seed, len(new_ids))
    idxs, vals = dense_to_idxs_vals(new_ids, ps.labels, values, active)
    return _cast_vals(ps, idxs, vals)


def suggest(new_ids, domain, trials, seed, speculative=0):
    ps = packed_space_for(domain)
    if speculative and len(new_ids) == 1:
        from .tpe_jax import _speculative_cols

        params = ("rand", int(speculative), id(trials))
        values, active = _speculative_cols(
            domain, trials, seed, int(speculative),
            2**62,  # prior draws never go stale
            params,
            0,  # no startup regime: always 'warm'
            lambda s, k: _dense_draw(domain, s, k),
        )
        idxs, vals = dense_to_idxs_vals(new_ids, ps.labels, values, active)
        idxs, vals = _cast_vals(ps, idxs, vals)
    else:
        idxs, vals = suggest_batch(new_ids, domain, trials, seed)
    return docs_from_idxs_vals(new_ids, domain, trials, idxs, vals)
