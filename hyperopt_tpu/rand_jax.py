"""Random search via the compiled space sampler (jitted prior draws).

TPU equivalent of :mod:`hyperopt_tpu.rand`: one XLA program draws the whole
batch (dense values + active masks) instead of interpreting the pyll graph
per trial (SURVEY.md SS3.3 -> SS7 stance #1).
"""

from __future__ import annotations

from .jax_trials import host_key, packed_space_for
from .rand import docs_from_idxs_vals
from .tpe_jax import _cast_vals
from .vectorize import dense_to_idxs_vals

__all__ = ["suggest", "suggest_batch"]


def suggest_batch(new_ids, domain, trials, seed):
    import jax

    ps = packed_space_for(domain)
    key = host_key(int(seed) % (2**31 - 1))
    values, active = ps.sample_prior(key, len(new_ids))
    values, active = jax.device_get((values, active))
    idxs, vals = dense_to_idxs_vals(new_ids, ps.labels, values, active)
    return _cast_vals(ps, idxs, vals)


def suggest(new_ids, domain, trials, seed):
    idxs, vals = suggest_batch(new_ids, domain, trials, seed)
    return docs_from_idxs_vals(new_ids, domain, trials, idxs, vals)
