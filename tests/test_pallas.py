"""Pallas GMM-scoring kernel vs the XLA reference kernel (interpret mode
on CPU; the same kernel compiles for TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperopt_tpu.ops import kernels as K
from hyperopt_tpu.ops.pallas_kernels import (
    ei_scores,
    gmm_logpdf_rows,
    pad_components,
)


def make_row(rng, n_comp, spread=3.0):
    w = rng.uniform(0.1, 1.0, n_comp)
    w = w / w.sum()
    mu = rng.normal(0, spread, n_comp)
    sigma = rng.uniform(0.3, 2.0, n_comp)
    return w, mu, sigma


def test_pad_components():
    w = jnp.ones((2, 130))
    mu = jnp.zeros((2, 130))
    sig = jnp.ones((2, 130))
    lm = jnp.zeros((2, 130))
    pw, pm, ps, pl_ = pad_components(w, mu, sig, lm)
    assert pw.shape == (2, 256)
    assert float(pw[0, 130:].sum()) == 0.0
    assert float(ps[0, 200]) == 1.0  # padded sigma stays safe


def test_gmm_logpdf_rows_matches_xla_kernel():
    rng = np.random.default_rng(0)
    R, S, n_comp = 4, 128, 37
    xs, rows = [], []
    for _ in range(R):
        w, mu, sigma = make_row(rng, n_comp)
        rows.append((w, mu, sigma))
        xs.append(rng.normal(0, 3.0, S))
    x = jnp.asarray(np.stack(xs), jnp.float32)
    w = jnp.asarray(np.stack([r[0] for r in rows]), jnp.float32)
    mu = jnp.asarray(np.stack([r[1] for r in rows]), jnp.float32)
    sig = jnp.asarray(np.stack([r[2] for r in rows]), jnp.float32)
    lm = jnp.zeros((R, n_comp), jnp.float32)  # untruncated

    got = np.asarray(gmm_logpdf_rows(x, w, mu, sig, lm, interpret=True))

    for r in range(R):
        want = np.asarray(
            K.trunc_gmm_logpdf(
                x[r], w[r], mu[r], sig[r],
                jnp.float32(-jnp.inf), jnp.float32(jnp.inf),
                jnp.asarray(False), jnp.float32(0.0),
            )
        )
        np.testing.assert_allclose(got[r], want, rtol=2e-4, atol=2e-4)


def test_gmm_logpdf_rows_with_zero_weight_padding():
    """Components padded with w=0 must not perturb the density."""
    rng = np.random.default_rng(1)
    S = 128
    w, mu, sigma = make_row(rng, 129)  # pads to 256
    x = jnp.asarray(rng.normal(0, 2, S), jnp.float32)[None]
    lm = jnp.zeros((1, 129), jnp.float32)
    got = np.asarray(
        gmm_logpdf_rows(
            x, jnp.asarray(w, jnp.float32)[None],
            jnp.asarray(mu, jnp.float32)[None],
            jnp.asarray(sigma, jnp.float32)[None], lm, interpret=True,
        )
    )[0]
    want = np.asarray(
        K.trunc_gmm_logpdf(
            x[0], jnp.asarray(w, jnp.float32), jnp.asarray(mu, jnp.float32),
            jnp.asarray(sigma, jnp.float32),
            jnp.float32(-jnp.inf), jnp.float32(jnp.inf),
            jnp.asarray(False), jnp.float32(0.0),
        )
    )
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_ei_scores_consistency_with_parzen_pipeline():
    """Full-path check: pallas EI scores == XLA EI scores on real fits."""
    rng = np.random.default_rng(2)
    cap = 64
    obs = jnp.asarray(rng.normal(1.0, 2.0, cap), jnp.float32)
    below_mask = jnp.asarray(np.arange(cap) < 8)
    above_mask = jnp.asarray((np.arange(cap) >= 8) & (np.arange(cap) < 40))
    pm, psig, pw = jnp.float32(0.0), jnp.float32(8.0), jnp.float32(1.0)
    lf = jnp.float32(25.0)

    wb, mb, sb = K.parzen_fit(obs, below_mask, pm, psig, pw, lf)
    wa, ma, sa = K.parzen_fit(obs, above_mask, pm, psig, pw, lf)

    samples = K.trunc_gmm_sample(
        jax.random.key(0), wb, mb, sb, jnp.float32(-8.0), jnp.float32(10.0),
        jnp.asarray(False), jnp.float32(0.0), 128,
    )

    def lmass(mu, sig):
        from jax.scipy.special import ndtr

        return jnp.log(
            jnp.maximum(
                ndtr((10.0 - mu) / sig) - ndtr((-8.0 - mu) / sig), 1e-30
            )
        )

    below = (wb[None], mb[None], sb[None], lmass(mb, sb)[None])
    above = (wa[None], ma[None], sa[None], lmass(ma, sa)[None])
    got = np.asarray(ei_scores(samples[None], below, above, interpret=True))[0]

    ll_b = K.trunc_gmm_logpdf(
        samples, wb, mb, sb, jnp.float32(-8.0), jnp.float32(10.0),
        jnp.asarray(False), jnp.float32(0.0),
    )
    ll_a = K.trunc_gmm_logpdf(
        samples, wa, ma, sa, jnp.float32(-8.0), jnp.float32(10.0),
        jnp.asarray(False), jnp.float32(0.0),
    )
    want = np.asarray(ll_b - ll_a)
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)
    # and the argmax (the decision that matters) agrees
    assert int(np.argmax(got)) == int(np.argmax(want))


@pytest.mark.skipif(
    jax.devices()[0].platform != "tpu",
    reason="compiled (non-interpret) Mosaic path needs a real TPU",
)
def test_gmm_logpdf_rows_compiled_on_tpu():
    """The same kernel, compiled for the chip (validated manually in
    round 1 at [12, 524288] x K=513: max |diff| vs XLA ~2e-4)."""
    rng = np.random.default_rng(1)
    R, S, n_comp = 12, 256, 513
    w = np.stack([make_row(rng, n_comp)[0] for _ in range(R)])
    mu = rng.normal(0, 3.0, (R, n_comp))
    sig = rng.uniform(0.3, 2.0, (R, n_comp))
    x = jnp.asarray(rng.normal(0, 3.0, (R, S)), jnp.float32)
    lm = jnp.zeros((R, n_comp), jnp.float32)
    got = np.asarray(gmm_logpdf_rows(
        x, jnp.asarray(w, jnp.float32), jnp.asarray(mu, jnp.float32),
        jnp.asarray(sig, jnp.float32), lm,
    ))
    for r in range(0, R, 5):
        want = np.asarray(
            K.trunc_gmm_logpdf(
                x[r], jnp.asarray(w[r], jnp.float32),
                jnp.asarray(mu[r], jnp.float32),
                jnp.asarray(sig[r], jnp.float32),
                jnp.float32(-jnp.inf), jnp.float32(jnp.inf),
                jnp.asarray(False), jnp.float32(0.0),
            )
        )
        np.testing.assert_allclose(got[r], want, rtol=1e-3, atol=1e-3)
