"""The domain battery: every algo runs fmin end-to-end on canonical
synthetic objectives against best-loss thresholds (reference pattern:
tests/test_domains.py CasePerDomain, SURVEY.md SS4).

Statistics: thresholds are asserted on the MEDIAN over 5 fixed seeds
(deterministic given fixed code; strictly stronger than the old
best-of-2), and TPE-vs-random is additionally pinned by regression bars
set INSIDE the measured TPE-advantage gap plus a pooled paired win-rate
test -- calibration (10 seeds, 2026-07): hartmann6 tpe_med -2.54 /
rand_med -2.16, many_dists 0.38 / 0.88, surrogate 0.060 / 0.082,
gauss_wave2 -1.46 / -1.31; paired wins 39/40.  A TPE regression eating
~half its advantage over random trips the bars; smaller ones flip
paired wins."""

import numpy as np
import pytest

from hyperopt_tpu import Trials, anneal, fmin, rand, tpe
from hyperopt_tpu.models.synthetic import DOMAINS, battery

SEEDS = (0, 1, 2, 3, 4)


def run_domain(domain, algo, n_evals, seed=0):
    trials = Trials()
    fmin(
        domain.fn,
        domain.make_space(),
        algo=algo,
        max_evals=n_evals,
        trials=trials,
        rstate=np.random.default_rng(seed),
        show_progressbar=False,
        catch_eval_exceptions=False,
    )
    return trials.best_trial["result"]["loss"]


def median5(domain, algo, n_evals):
    return float(
        np.median([run_domain(domain, algo, n_evals, seed=s) for s in SEEDS])
    )


# battery subset for per-algo threshold tests (full battery in smoke test)
THRESHOLD_DOMAINS = ["quadratic1", "q1_choice", "n_arms", "branin", "gauss_wave2"]


@pytest.mark.parametrize("name", THRESHOLD_DOMAINS)
def test_tpe_hits_thresholds(name):
    domain = DOMAINS[name]
    n_evals, threshold = next(iter(domain.targets.items()))
    med = median5(domain, tpe.suggest, n_evals)
    assert med <= threshold, f"tpe on {name}: median5 {med} > {threshold}"


@pytest.mark.parametrize("name", THRESHOLD_DOMAINS)
def test_anneal_hits_thresholds(name):
    domain = DOMAINS[name]
    n_evals, threshold = next(iter(domain.targets.items()))
    med = median5(domain, anneal.suggest, n_evals)
    assert med <= threshold, f"anneal on {name}: median5 {med} > {threshold}"


# -- TPE-advantage regression bars ------------------------------------------
# (config, evals, median5 bar): bars sit between TPE's measured median and
# random's, ~half the gap in -- any regression that costs TPE half its
# advantage over random FAILS here, without being flaky at 5 fixed seeds.
SIGNAL_CONFIGS = [
    ("hartmann6", 150, -2.35),
    ("many_dists", 100, 0.55),
    ("gauss_wave2", 100, -1.40),
]


@pytest.mark.parametrize("name,n_evals,bar", SIGNAL_CONFIGS)
def test_tpe_advantage_regression_bar(name, n_evals, bar):
    med = median5(DOMAINS[name], tpe.suggest, n_evals)
    assert med <= bar, (
        f"tpe on {name}: median5 {med} > regression bar {bar} "
        f"(TPE has lost a large fraction of its advantage over random)"
    )


def test_tpe_beats_random_paired_win_rate():
    """Pooled paired comparison (same seed, same domain): the sensitive
    statistic -- small TPE regressions flip close pairs long before the
    median bars trip.  Measured 20/20 at calibration; 15 allows noise."""
    configs = [("hartmann6", 150), ("many_dists", 100), ("gauss_wave2", 100),
               ("surrogate", 100)]
    from hyperopt_tpu.models import surrogate as surrogate_mod

    wins = total = 0
    for name, n_evals in configs:
        if name == "surrogate":
            class _D:  # surrogate is in models/, not DOMAINS
                fn = staticmethod(surrogate_mod.objective)
                make_space = staticmethod(surrogate_mod.space)
            dom = _D()
        else:
            dom = DOMAINS[name]
        for s in SEEDS:
            t = run_domain(dom, tpe.suggest, n_evals, seed=s)
            r = run_domain(dom, rand.suggest, n_evals, seed=s)
            wins += t < r
            total += 1
    assert total == 20
    assert wins >= 15, f"TPE won only {wins}/{total} paired runs vs random"


@pytest.mark.parametrize("name", sorted(DOMAINS))
def test_rand_smoke_all_domains(name):
    """Random search must run end-to-end on every domain (no thresholds)."""
    domain = DOMAINS[name]
    best = run_domain(domain, rand.suggest, 20, seed=0)
    assert np.isfinite(best)


def test_tpe_smoke_many_dists():
    """TPE must handle the gnarly nested mixed-distribution space."""
    domain = DOMAINS["many_dists"]
    best = run_domain(domain, tpe.suggest, 35, seed=0)
    assert np.isfinite(best)


def test_battery_accessor():
    assert {d.name for d in battery()} == set(DOMAINS)
    assert [d.name for d in battery(["branin"])] == ["branin"]
