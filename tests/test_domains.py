"""The domain battery: every algo runs fmin end-to-end on canonical
synthetic objectives and must hit loose best-loss thresholds (reference
pattern: tests/test_domains.py CasePerDomain, SURVEY.md SS4)."""

import numpy as np
import pytest

from hyperopt_tpu import Trials, anneal, fmin, rand, tpe
from hyperopt_tpu.models.synthetic import DOMAINS, battery


def run_domain(domain, algo, n_evals, seed=0):
    trials = Trials()
    fmin(
        domain.fn,
        domain.make_space(),
        algo=algo,
        max_evals=n_evals,
        trials=trials,
        rstate=np.random.default_rng(seed),
        show_progressbar=False,
        catch_eval_exceptions=False,
    )
    return trials.best_trial["result"]["loss"]


# battery subset for per-algo threshold tests (full battery in smoke test)
THRESHOLD_DOMAINS = ["quadratic1", "q1_choice", "n_arms", "branin", "gauss_wave2"]


@pytest.mark.parametrize("name", THRESHOLD_DOMAINS)
def test_tpe_hits_thresholds(name):
    domain = DOMAINS[name]
    n_evals, threshold = next(iter(domain.targets.items()))
    best = min(run_domain(domain, tpe.suggest, n_evals, seed=s) for s in (0, 1))
    assert best <= threshold, f"tpe on {name}: {best} > {threshold}"


@pytest.mark.parametrize("name", THRESHOLD_DOMAINS)
def test_anneal_hits_thresholds(name):
    domain = DOMAINS[name]
    n_evals, threshold = next(iter(domain.targets.items()))
    best = min(run_domain(domain, anneal.suggest, n_evals, seed=s) for s in (0, 1))
    assert best <= threshold, f"anneal on {name}: {best} > {threshold}"


@pytest.mark.parametrize("name", sorted(DOMAINS))
def test_rand_smoke_all_domains(name):
    """Random search must run end-to-end on every domain (no thresholds)."""
    domain = DOMAINS[name]
    best = run_domain(domain, rand.suggest, 20, seed=0)
    assert np.isfinite(best)


def test_tpe_smoke_many_dists():
    """TPE must handle the gnarly nested mixed-distribution space."""
    domain = DOMAINS["many_dists"]
    best = run_domain(domain, tpe.suggest, 35, seed=0)
    assert np.isfinite(best)


def test_battery_accessor():
    assert {d.name for d in battery()} == set(DOMAINS)
    assert [d.name for d in battery(["branin"])] == ["branin"]
