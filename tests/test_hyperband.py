"""Successive halving + Hyperband (hyperopt_tpu.hyperband)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperopt_tpu import Trials, hp
from hyperopt_tpu.hyperband import compile_sha, hyperband, successive_halving


def budgeted_quad(cfg, budget):
    """Noisy-at-low-budget quadratic: the noise std shrinks with budget,
    so halving must promote genuinely good configs despite rung-0 noise."""
    rng = np.random.default_rng(int(1e6 * (cfg["x"] % 1)) % 2**31)
    return (cfg["x"] - 3.0) ** 2 + rng.normal(0, 1.0 / budget)


SPACE = {"x": hp.uniform("x", -10.0, 10.0)}


def test_successive_halving_promotes_and_records():
    trials = Trials()
    out = successive_halving(
        budgeted_quad, SPACE, max_budget=9, min_budget=1, eta=3,
        trials=trials, rstate=np.random.default_rng(0),
    )
    assert [r["budget"] for r in out["rungs"]] == [1, 3, 9]
    assert [r["n"] for r in out["rungs"]] == [9, 3, 1]
    assert out["best_loss"] < 4.0  # beats a typical random draw (~30)
    assert "x" in out["best"]
    # EVERY evaluation is its own recorded trial (promotions append, the
    # lower-rung learning-curve history survives): 9 + 3 + 1 = 13
    assert len(trials) == 13
    budgets = [t["result"]["budget"] for t in trials.trials]
    assert sorted(budgets) == [1] * 9 + [3] * 3 + [9]
    # a promoted config's rung-0 loss is still in the store alongside
    # its rung-1 loss (same x value, different budgets)
    x_of = lambda t: t["misc"]["vals"]["x"][0]
    promoted = [x_of(t) for t in trials.trials if t["result"]["budget"] == 3]
    rung0_x = [x_of(t) for t in trials.trials if t["result"]["budget"] == 1]
    assert all(any(np.isclose(p, x) for x in rung0_x) for p in promoted)


def test_successive_halving_exact_eta_power_reaches_max_budget():
    """Float-log regression: an exact eta-power budget span must count
    every rung (math.log(8, 2) = 2.9999... floors to 2 and silently
    drops the max-budget rung)."""
    out = successive_halving(
        lambda cfg, b: (cfg["x"] - 3.0) ** 2 / b, SPACE,
        max_budget=8, min_budget=1, eta=2,
        rstate=np.random.default_rng(0),
    )
    assert [r["budget"] for r in out["rungs"]] == [1, 2, 4, 8]
    assert [r["n"] for r in out["rungs"]] == [8, 4, 2, 1]


def test_successive_halving_reproducible():
    def run():
        out = successive_halving(
            budgeted_quad, SPACE, max_budget=9, eta=3,
            rstate=np.random.default_rng(5),
        )
        return out["best_loss"], out["best"]["x"]

    assert run() == run()


def test_hyperband_brackets_share_trials_and_find_optimum():
    out = hyperband(
        budgeted_quad, SPACE, max_budget=9, eta=3,
        rstate=np.random.default_rng(1),
    )
    assert len(out["brackets"]) == 3  # s = 2, 1, 0
    assert out["best_loss"] < 2.0
    # the shared store saw every bracket's evaluations
    assert len(out["trials"]) >= 9 + 5 + 3


def test_hyperband_with_tpe_rung0():
    """Rung-0 configurations can come from any suggest algo (the plugin
    seam): TPE-seeded halving runs end-to-end."""
    from hyperopt_tpu import tpe_jax

    out = successive_halving(
        budgeted_quad, SPACE, max_budget=4, eta=2, n_configs=8,
        algo=tpe_jax.suggest, rstate=np.random.default_rng(2),
    )
    assert np.isfinite(out["best_loss"])
    assert [r["n"] for r in out["rungs"]] == [8, 4, 2]


def test_hyperband_keeps_integral_budgets():
    """Integral-budget contract through hyperband: an int max_budget
    divisible by eta**s must reach fn as ints at every rung of every
    bracket (true division handed the objective 9.0 for epoch-count
    budgets; advisor finding r3)."""
    seen = []

    def int_checking(cfg, budget):
        seen.append(budget)
        assert isinstance(budget, int), budget
        return (cfg["x"] - 3.0) ** 2 / budget

    out = hyperband(
        int_checking, SPACE, max_budget=9, eta=3,
        rstate=np.random.default_rng(4),
    )
    assert np.isfinite(out["best_loss"])
    assert seen and all(isinstance(b, int) for b in seen)
    assert set(seen) == {1, 3, 9}


def test_budget_aware_filters_to_deepest_informative_rung():
    """BOHB model-fitting rule: the wrapped algo must see ONLY the
    highest budget with >= min_obs observations (cross-budget losses
    are not comparable), falling back to the most-populated budget
    while data is scarce."""
    from hyperopt_tpu import rand
    from hyperopt_tpu.base import Domain
    from hyperopt_tpu.hyperband import budget_aware

    seen = []

    def recording_algo(new_ids, domain, trials, seed, **kw):
        seen.append(sorted(
            t["result"]["budget"] for t in trials.trials if t.get("result")
        ))
        return rand.suggest(new_ids, domain, trials, seed)

    domain = Domain(lambda cfg: 0.0, SPACE)
    trials = Trials()
    docs = rand.suggest(trials.new_trial_ids(12), domain, trials, seed=0)
    for i, d in enumerate(docs):
        d["state"] = 2
        # 9 obs at budget 1, 3 at budget 3
        d["result"] = {"status": "ok", "loss": float(i),
                       "budget": 1 if i < 9 else 3}
    trials.insert_trial_docs(docs)
    trials.refresh()

    algo = budget_aware(recording_algo, min_obs=8)
    algo(trials.new_trial_ids(1), domain, trials, seed=1)
    assert seen[-1] == [1] * 9  # budget 3 has only 3 obs -> use budget 1

    # once the deeper rung accumulates min_obs, it takes over
    more = rand.suggest(trials.new_trial_ids(6), domain, trials, seed=2)
    for d in more:
        d["state"] = 2
        d["result"] = {"status": "ok", "loss": 1.0, "budget": 3}
    trials.insert_trial_docs(more)
    trials.refresh()
    algo(trials.new_trial_ids(1), domain, trials, seed=3)
    assert seen[-1] == [3] * 9

    # budget-free stores pass through untouched
    plain = Trials()
    algo(plain.new_trial_ids(1), domain, plain, seed=4)
    assert seen[-1] == []


def test_budget_aware_tpe_end_to_end():
    from hyperopt_tpu import tpe_jax
    from hyperopt_tpu.hyperband import budget_aware

    out = hyperband(
        budgeted_quad, SPACE, max_budget=9, eta=3,
        algo=budget_aware(tpe_jax.suggest, min_obs=4),
        rstate=np.random.default_rng(3),
    )
    assert np.isfinite(out["best_loss"])
    assert out["best_loss"] < 2.0


# ---------------------------------------------------------------------------
# fused on-device SHA
# ---------------------------------------------------------------------------


def linear_train_fn(state, hypers, key):
    """theta' = theta - lr*grad on (theta-0.7)^2; divergent for lr > 1."""
    theta = state["theta"] - hypers["lr"] * 2.0 * (state["theta"] - 0.7)
    return {"theta": theta}, (theta - 0.7) ** 2


def test_compile_sha_halves_and_continues_training():
    P = 8
    runner = compile_sha(
        linear_train_fn,
        {"theta": jnp.full((P,), 5.0)},
        {"lr": (1e-3, 5.0)},  # includes divergent lrs
        n_configs=P,
        eta=2,
        steps_per_rung=3,
    )
    out = runner(seed=0)
    assert [r["n"] for r in out["rungs"]] == [8, 4, 2, 1]
    assert [r["steps"] for r in out["rungs"]] == [3, 6, 12, 24]
    # survivors carried their trained theta: the final member has seen
    # 3+6+12+24 = 45 total steps; with a sane lr that converges
    assert out["best_loss"] < 1e-3
    assert np.isfinite(out["best_loss"])
    assert 1e-3 <= out["best_hypers"]["lr"] <= 5.0


def test_compile_sha_drops_divergent_members():
    """inf/NaN losses must rank LAST at every rung: with a log-uniform
    lr draw spanning stable (< 1) and violently divergent (up to 50)
    members, a stable member must win every seed."""
    P = 8

    def explosive(state, hypers, key):
        theta = state["theta"] - hypers["lr"] * 2.0 * (state["theta"] - 0.7)
        # lr > 1 explodes to inf within a few steps from theta=1e4
        return {"theta": theta}, (theta - 0.7) ** 2

    runner = compile_sha(
        explosive,
        {"theta": jnp.full((P,), 1e4)},
        {"lr": (0.01, 50.0)},
        n_configs=P,
        eta=2,
        steps_per_rung=4,
    )
    for seed in range(3):
        out = runner(seed=seed)
        assert np.isfinite(out["best_loss"])
        assert out["best_hypers"]["lr"] < 1.0  # a stable member won


def test_compile_sha_reproducible():
    runner = compile_sha(
        linear_train_fn, {"theta": jnp.full((4,), 2.0)},
        {"lr": (1e-3, 1.0)}, n_configs=4, eta=2, steps_per_rung=2,
    )
    a = runner(seed=7)
    b = runner(seed=7)
    assert a["best_loss"] == b["best_loss"]
    assert a["best_hypers"] == b["best_hypers"]


def test_compile_sha_validates():
    with pytest.raises(ValueError, match="power of eta"):
        compile_sha(linear_train_fn, {"theta": jnp.zeros((6,))},
                    {"lr": (1e-3, 1.0)}, n_configs=6, eta=2)
    with pytest.raises(ValueError, match="0 < low < high"):
        compile_sha(linear_train_fn, {"theta": jnp.zeros((4,))},
                    {"lr": (1.0, 0.5)}, n_configs=4)


def test_compile_sha_replicas_compose_with_mesh():
    """Bracket packing under a population mesh: the stacked K*P member
    axis shards over 'trial' and per-bracket ranking survives."""
    from hyperopt_tpu.parallel.mesh import mesh_from_spec

    mesh = mesh_from_spec((8,), ("trial",))
    P, K = 8, 2
    runner = compile_sha(
        linear_train_fn,
        {"theta": jnp.full((K * P,), 5.0)},
        {"lr": (1e-3, 1.0)},
        n_configs=P, eta=2, steps_per_rung=3, replicas=K, mesh=mesh,
    )
    out = runner(seed=0)
    assert [r["n"] for r in out["rungs"]] == [8, 4, 2, 1]
    assert len(out["replica_bests"]) == K
    assert out["best_loss"] < 1e-3


def test_compile_sha_mesh_sharded_rungs():
    """SHA under a population mesh: rung populations shrink below the
    axis size (8 -> 4 -> 2 -> 1 on an 8-device mesh) and GSPMD handles
    the uneven shards; results stay correct."""
    from hyperopt_tpu.parallel.mesh import mesh_from_spec

    mesh = mesh_from_spec((8,), ("trial",))
    runner = compile_sha(
        linear_train_fn,
        {"theta": jnp.full((8,), 5.0)},
        {"lr": (1e-3, 1.0)},
        n_configs=8,
        eta=2,
        steps_per_rung=3,
        mesh=mesh,
    )
    out = runner(seed=0)
    assert [r["n"] for r in out["rungs"]] == [8, 4, 2, 1]
    assert out["best_loss"] < 1e-3


def test_compile_sha_replicas_pack_brackets():
    """replicas=K packs K independent brackets into every rung program:
    promotion ranks WITHIN each bracket, results report per-bracket
    bests, and the overall best is their min."""
    P, K = 8, 3
    runner = compile_sha(
        linear_train_fn,
        {"theta": jnp.full((K * P,), 5.0)},
        {"lr": (1e-3, 5.0)},
        n_configs=P, eta=2, steps_per_rung=3, replicas=K,
    )
    out = runner(seed=0)
    assert [r["n"] for r in out["rungs"]] == [8, 4, 2, 1]
    assert len(out["replica_bests"]) == K
    assert np.isfinite(out["best_loss"])
    assert out["best_loss"] == min(out["replica_bests"])
    assert out["best_loss"] < 1e-3
    # (bracket independence is pinned by the lr-ranking test below --
    # here every bracket converges to exactly 0.0 on the toy objective)
    # deterministic across calls
    again = runner(seed=0)
    assert again["replica_bests"] == out["replica_bests"]


def test_compile_sha_replicas_rank_within_brackets():
    """A globally-better member in bracket 0 must not rescue bracket 1's
    members: ALL of bracket 0's members beat all of bracket 1's, so a
    global-argsort regression would promote only bracket-0 members and
    bracket 1's reported best could never be its true 1.0."""
    P, K = 4, 2
    # per-member static losses: bracket 0 = {0.0, .1, .2, .3},
    # bracket 1 = {1.0, 1.1, 1.2, 1.3}
    bias = jnp.asarray(
        [0.0, 0.1, 0.2, 0.3, 1.0, 1.1, 1.2, 1.3], dtype=jnp.float32
    )

    def loss_is_bias(state, hypers, key):
        return state, state["bias"]

    runner = compile_sha(
        loss_is_bias,
        {"bias": bias},
        {"lr": (1e-3, 1.0)},
        n_configs=P, eta=2, steps_per_rung=1, replicas=K,
    )
    out = runner(seed=1)
    np.testing.assert_allclose(out["replica_bests"], [0.0, 1.0], atol=1e-7)
    assert out["best_loss"] == 0.0
    # every rung's best is bracket 0's 0.0 (cross-bracket min)
    assert all(r["best_loss"] == 0.0 for r in out["rungs"])


def test_compile_sha_replicas_validates_leading_dim():
    with pytest.raises(ValueError, match="leading dim"):
        compile_sha(
            linear_train_fn, {"theta": jnp.zeros((8,))},
            {"lr": (1e-3, 1.0)}, n_configs=8, eta=2, replicas=2,
        )


def test_asha_promotes_and_records():
    """ASHA: workers never wait for a full rung; every evaluation lands
    in the store with its budget, promotions reuse rung-(r-1) configs,
    and the deepest survivor is a genuinely good one."""
    from hyperopt_tpu.hyperband import asha

    out = asha(
        budgeted_quad, SPACE, max_budget=9, eta=3, max_jobs=40,
        workers=4, rstate=np.random.default_rng(0),
    )
    trials = out["trials"]
    assert len(trials) == 40
    budgets = [t["result"]["budget"] for t in trials.trials]
    assert set(budgets) <= {1, 3, 9}
    # the ladder filled bottom-up: more cheap evals than deep ones
    assert budgets.count(1) > budgets.count(3) >= budgets.count(9) > 0
    # every promoted config was first evaluated at the previous rung
    x_at = lambda b: {
        round(t["misc"]["vals"]["x"][0], 9)
        for t in trials.trials if t["result"]["budget"] == b
    }
    assert x_at(3) <= x_at(1)
    assert x_at(9) <= x_at(3)
    # (the quality bound lives in the deterministic workers=1 test --
    # with 4 workers the fresh-draw count is schedule-dependent)
    assert np.isfinite(out["best_loss"])
    assert out["rungs"][0]["n"] >= out["rungs"][1]["n"]


def test_asha_single_worker_reproducible_and_converges():
    from hyperopt_tpu.hyperband import asha

    def run():
        out = asha(
            budgeted_quad, SPACE, max_budget=9, eta=3, max_jobs=40,
            workers=1, rstate=np.random.default_rng(3),
        )
        return out["best_loss"], out["best"]["x"]

    a = run()
    assert a == run()
    assert a[0] < 2.0  # deterministic: the deepest survivor is good


def test_asha_algo_sees_growing_history():
    """The rung-0 suggest algo must see every COMPLETED evaluation (a
    model-based algo otherwise degenerates to random search silently)."""
    from hyperopt_tpu import rand
    from hyperopt_tpu.hyperband import asha

    seen = []

    def probe(new_ids, domain, trials, seed):
        seen.append(len(trials.trials))
        return rand.suggest(new_ids, domain, trials, seed)

    asha(
        budgeted_quad, SPACE, max_budget=9, eta=3, max_jobs=20,
        workers=1, algo=probe, rstate=np.random.default_rng(0),
    )
    assert seen[0] == 0 and seen[-1] > 0
    assert seen == sorted(seen)  # history only grows


def test_asha_all_failed_raises():
    from hyperopt_tpu.exceptions import AllTrialsFailed
    from hyperopt_tpu.hyperband import asha

    def broken(cfg, budget):
        raise RuntimeError("no data")

    with pytest.raises(AllTrialsFailed, match="every asha evaluation"):
        asha(
            broken, SPACE, max_budget=4, eta=2, max_jobs=6, workers=2,
            rstate=np.random.default_rng(0),
        )


def test_asha_failed_evaluations_never_promote():
    """NaN/raising evaluations are recorded as failed trials and can
    never enter a rung's promotable set."""
    from hyperopt_tpu.hyperband import asha

    def sometimes_fails(cfg, budget):
        if cfg["x"] < 0:
            raise RuntimeError("boom")
        return (cfg["x"] - 3.0) ** 2 / budget

    out = asha(
        sometimes_fails, SPACE, max_budget=4, eta=2, max_jobs=30,
        workers=2, rstate=np.random.default_rng(1),
    )
    trials = out["trials"]
    assert len(trials) == 30
    failed = [t for t in trials.trials if t["result"]["status"] == "fail"]
    ok = [t for t in trials.trials if t["result"]["status"] == "ok"]
    assert failed and ok  # both outcomes occurred
    # no promoted (budget > min) trial has a failing x
    assert all(
        t["misc"]["vals"]["x"][0] >= 0
        for t in trials.trials if t["result"]["budget"] > 1
    )
    assert np.isfinite(out["best_loss"])


def test_asha_concurrency_fuzz():
    """Randomized evaluation durations x many workers: the scheduler's
    invariants hold under real interleavings -- exact job count, valid
    budget ladder, promotion chains intact (every rung-r config was
    evaluated at rung r-1 first)."""
    import time as _time

    from hyperopt_tpu.hyperband import asha

    def jittery(cfg, budget):
        # thread-safe jitter: derived from the inputs, no shared rng
        _time.sleep((hash((round(cfg["x"], 6), budget)) % 30) / 10_000.0)
        return (cfg["x"] - 3.0) ** 2 / budget

    for seed in range(3):
        out = asha(
            jittery, SPACE, max_budget=9, eta=3, max_jobs=60,
            workers=8, rstate=np.random.default_rng(seed),
        )
        trials = out["trials"]
        assert len(trials) == 60
        budgets = [t["result"]["budget"] for t in trials.trials]
        assert set(budgets) <= {1, 3, 9}
        x_at = lambda b: {
            round(t["misc"]["vals"]["x"][0], 9)
            for t in trials.trials if t["result"]["budget"] == b
        }
        assert x_at(3) <= x_at(1) and x_at(9) <= x_at(3)
        assert sum(r["n"] for r in out["rungs"]) == 60


class _KillableQuad:
    """budgeted_quad with an optional kill switch at call N.  A CLASS,
    not a per-test closure: the checkpoint guard fingerprints the
    objective's identity, so the killed run and the resumed run must
    present the SAME fn (kill_at=None) -- exactly how a real caller
    resumes with their unchanged objective."""

    def __init__(self, kill_at=None):
        self.kill_at = kill_at
        self.calls = 0

    def __call__(self, cfg, budget):
        self.calls += 1
        if self.kill_at is not None and self.calls == self.kill_at:
            raise KeyboardInterrupt
        return budgeted_quad(cfg, budget)


class _BlockerQuad:
    """budgeted_quad whose FIRST call (when armed) blocks until every
    other job drained, then dies -- so the last snapshot written holds
    the blocked job in ``pending``.  A CLASS for the same reason as
    :class:`_KillableQuad`: the asha guard fingerprints the objective,
    so the killed and resumed runs must present the same identity."""

    def __init__(self, arm=False):
        import threading

        self.arm = arm
        self.n_calls = 0
        self.blocked_x = []
        self.drained = threading.Event()
        self.call_lock = threading.Lock()

    def __call__(self, cfg, budget):
        with self.call_lock:
            i = self.n_calls
            self.n_calls += 1
            if self.n_calls >= 40:
                self.drained.set()
        if self.arm and i == 0:
            self.blocked_x.append(round(cfg["x"], 9))
            assert self.drained.wait(timeout=120)
            raise KeyboardInterrupt
        return budgeted_quad(cfg, budget)


def _sha_digest(out):
    return (
        out["best_loss"], out["best"]["x"], out["rungs"],
        [(d["tid"], d["result"]["budget"], d["result"]["loss"])
         for d in out["trials"].trials],
    )


def test_successive_halving_checkpoint_resume_bitwise(tmp_path):
    """The host SHA driver is a serial (rung, member) loop: kill it at
    any evaluation, resume from the per-evaluation snapshot, and the
    result is bitwise the uninterrupted run's -- completing the resume
    family for the HOST drivers too."""
    from hyperopt_tpu.hyperband import successive_halving

    kw = dict(max_budget=9, eta=3)
    ref = _sha_digest(successive_halving(
        _KillableQuad(), SPACE, rstate=np.random.default_rng(5), **kw
    ))
    # checkpoint_every > 1 exercises the snapshot-lags-evaluations
    # replay (several evaluations re-run deterministically on resume)
    for kill_at, every in ((4, 1), (11, 1), (11, 3)):
        path = str(tmp_path / f"sha-{kill_at}-{every}.ckpt")
        with pytest.raises(KeyboardInterrupt):
            successive_halving(
                _KillableQuad(kill_at), SPACE,
                rstate=np.random.default_rng(5),
                checkpoint=path, checkpoint_every=every, **kw
            )
        resumed = _sha_digest(successive_halving(
            _KillableQuad(), SPACE, rstate=np.random.default_rng(5),
            checkpoint=path, checkpoint_every=every, **kw
        ))
        assert resumed == ref, (kill_at, every)


def test_successive_halving_resume_bitwise_at_every_kill_point(tmp_path):
    """Exhaustive kill-point sweep: killing at EVERY evaluation of the
    bracket (including before the first snapshot exists -- resume then
    replays the seeded suggestion from scratch) resumes to the bitwise
    uninterrupted result."""
    from hyperopt_tpu.hyperband import successive_halving

    kw = dict(max_budget=9, eta=3)
    ref = _sha_digest(successive_halving(
        _KillableQuad(), SPACE, rstate=np.random.default_rng(5), **kw
    ))
    total_evals = len(ref[3])  # every recorded trial is one evaluation
    assert total_evals == 13  # 9 + 3 + 1
    for kill_at in range(1, total_evals + 1):
        path = str(tmp_path / f"sweep-{kill_at}.ckpt")
        with pytest.raises(KeyboardInterrupt):
            successive_halving(
                _KillableQuad(kill_at), SPACE,
                rstate=np.random.default_rng(5), checkpoint=path, **kw
            )
        resumed = _sha_digest(successive_halving(
            _KillableQuad(), SPACE, rstate=np.random.default_rng(5),
            checkpoint=path, **kw
        ))
        assert resumed == ref, kill_at


def test_successive_halving_checkpoint_guard(tmp_path):
    """A snapshot from a different ladder OR a different seed is
    refused -- a stale file must never silently resurrect an old run's
    results for a new request (same seed may resume: it would
    recompute the identical result)."""
    from hyperopt_tpu.hyperband import successive_halving

    path = str(tmp_path / "sha.ckpt")
    out = successive_halving(
        budgeted_quad, SPACE, max_budget=4, eta=2,
        rstate=np.random.default_rng(0), checkpoint=path,
    )
    with pytest.raises(ValueError, match="refusing to resume"):
        successive_halving(  # different ladder
            budgeted_quad, SPACE, max_budget=9, eta=3,
            rstate=np.random.default_rng(0), checkpoint=path,
        )
    with pytest.raises(ValueError, match="refusing to resume"):
        successive_halving(  # same ladder, DIFFERENT seed
            budgeted_quad, SPACE, max_budget=4, eta=2,
            rstate=np.random.default_rng(1), checkpoint=path,
        )
    with pytest.raises(ValueError, match="refusing to resume"):
        successive_halving(  # same ladder+seed, DIFFERENT objective
            _KillableQuad(), SPACE, max_budget=4, eta=2,
            rstate=np.random.default_rng(0), checkpoint=path,
        )
    again = successive_halving(  # same seed: sound to resume
        budgeted_quad, SPACE, max_budget=4, eta=2,
        rstate=np.random.default_rng(0), checkpoint=path,
    )
    assert again["best_loss"] == out["best_loss"]


def test_hyperband_checkpoint_resume_bitwise(tmp_path):
    """Kill the host Hyperband spread mid-bracket; the bracket-boundary
    snapshot plus the in-flight bracket's own snapshot resume to the
    uninterrupted result exactly (completed brackets are skipped, the
    shared rstate stream stays aligned)."""
    from hyperopt_tpu.hyperband import hyperband

    kw = dict(max_budget=9, eta=3)

    def digest(out):
        return (
            out["best_loss"], out["best"]["x"],
            [(b["s"], b["rungs"]) for b in out["brackets"]],
            [(d["tid"], d["result"]["budget"], d["result"]["loss"])
             for d in out["trials"].trials],
        )

    ref = digest(hyperband(
        _KillableQuad(), SPACE, rstate=np.random.default_rng(9), **kw
    ))
    path = str(tmp_path / "hb.ckpt")
    with pytest.raises(KeyboardInterrupt):
        hyperband(  # killed inside the second bracket
            _KillableQuad(15), SPACE, rstate=np.random.default_rng(9),
            checkpoint=path, **kw
        )
    resumed = digest(hyperband(
        _KillableQuad(), SPACE, rstate=np.random.default_rng(9),
        checkpoint=path, **kw
    ))
    assert resumed == ref
    # completed brackets' .s files were cleaned up: removing the main
    # snapshot leaves nothing stale to block a FRESH different-seed run
    import glob
    import os

    os.remove(path)
    assert not glob.glob(path + ".s*")
    fresh = hyperband(
        _KillableQuad(), SPACE, rstate=np.random.default_rng(10),
        checkpoint=path, **kw
    )
    assert np.isfinite(fresh["best_loss"])


def test_asha_checkpoint_resume_bitwise(tmp_path):
    """Kill mid-run, resume from the snapshot, and reproduce the
    uninterrupted run EXACTLY (workers=1: the snapshot's generator state
    predates the in-flight job's suggestion, so resume replays it) --
    the same contract the device_loop/pbt/sha resume tests pin."""
    from hyperopt_tpu.hyperband import asha

    kw = dict(max_budget=9, eta=3, max_jobs=40, workers=1)

    def digest(out):
        t = out["trials"].trials
        return (
            out["best_loss"], out["best"]["x"],
            [r["n"] for r in out["rungs"]],
            [(d["tid"], d["result"]["budget"], d["result"]["loss"])
             for d in t],
        )

    ref = digest(asha(
        _KillableQuad(), SPACE, rstate=np.random.default_rng(7), **kw
    ))

    path = str(tmp_path / "asha.ckpt")
    with pytest.raises(KeyboardInterrupt):
        # KeyboardInterrupt is a BaseException: not caught as a failed
        # eval; surfaces through the worker future like a kill.  A
        # _KillableQuad (stable class identity), not a closure: the
        # guard now fingerprints the objective like sha/hyperband do
        asha(
            _KillableQuad(13), SPACE, rstate=np.random.default_rng(7),
            checkpoint=path, **kw
        )
    resumed = digest(asha(
        _KillableQuad(), SPACE, rstate=np.random.default_rng(7),
        checkpoint=path, **kw
    ))
    assert resumed == ref


def test_asha_checkpoint_guard_and_multiworker_invariants(tmp_path):
    """A snapshot from a different ladder is refused; a multi-worker
    kill/resume preserves the scheduler invariants (exact job count,
    promotion chains) even though completion order is scheduling-
    dependent."""
    from hyperopt_tpu.hyperband import asha

    path = str(tmp_path / "asha.ckpt")

    with pytest.raises(KeyboardInterrupt):
        asha(
            _KillableQuad(17), SPACE, max_budget=9, eta=3, max_jobs=40,
            workers=4, rstate=np.random.default_rng(0), checkpoint=path,
        )
    with pytest.raises(ValueError, match="refusing to resume"):
        asha(
            _KillableQuad(), SPACE, max_budget=4, eta=2, max_jobs=40,
            workers=4, rstate=np.random.default_rng(0), checkpoint=path,
        )
    out = asha(
        _KillableQuad(), SPACE, max_budget=9, eta=3, max_jobs=40,
        workers=4, rstate=np.random.default_rng(0), checkpoint=path,
    )
    trials = out["trials"]
    assert len(trials) == 40  # total across kill + resume: exact budget
    budgets = [t["result"]["budget"] for t in trials.trials]
    assert set(budgets) <= {1, 3, 9}
    x_at = lambda b: {
        round(t["misc"]["vals"]["x"][0], 9)
        for t in trials.trials if t["result"]["budget"] == b
    }
    assert x_at(3) <= x_at(1) and x_at(9) <= x_at(3)
    assert np.isfinite(out["best_loss"])


def test_asha_checkpoint_requeues_in_flight_suggestion(tmp_path):
    """A rung-0 suggestion whose evaluation is in flight at kill time
    rides the snapshot (``pending``) and is RE-RUN on resume with its
    exact suggested config -- not silently dropped with an orphaned
    tid.  Two workers: the first call blocks until the other worker has
    drained every remaining job (so the last snapshot written contains
    the blocked job in ``pending``), then dies."""
    from hyperopt_tpu.hyperband import asha

    path = str(tmp_path / "asha.ckpt")
    armed = _BlockerQuad(arm=True)

    with pytest.raises(KeyboardInterrupt):
        asha(
            armed, SPACE, max_budget=9, eta=3, max_jobs=40, workers=2,
            rstate=np.random.default_rng(5), checkpoint=path,
        )
    blocked_x = armed.blocked_x
    out = asha(
        _BlockerQuad(), SPACE, max_budget=9, eta=3, max_jobs=40,
        workers=2, rstate=np.random.default_rng(5), checkpoint=path,
    )
    trials = out["trials"]
    assert len(trials) == 40  # the lost job's budget was re-spent
    xs = {
        round(t["misc"]["vals"]["x"][0], 9)
        for t in trials.trials if t["result"]["budget"] == 1
    }
    assert blocked_x[0] in xs  # the in-flight config itself was re-run
    # tid sequence stays contiguous: the pending doc's tid was reused
    tids = sorted(t["tid"] for t in trials.trials)
    assert tids == list(range(tids[0], tids[0] + 40))


def test_asha_space_fingerprint_stable_and_structural():
    """The checkpoint guard's space hash must survive a process restart
    (callable choice options print memory addresses via repr -- the
    fingerprint normalizes them) yet refuse structural edits like
    reordered options or changed bounds."""
    from hyperopt_tpu.base import Domain
    from hyperopt_tpu.hyperband import _space_fingerprint

    def build(opts, hi=1.0):
        # fresh lambdas each call: distinct object addresses, same
        # structure -- the in-process stand-in for a process restart
        space = {
            "act": hp.choice("act", [(o, (lambda z: z)) for o in opts]),
            "lr": hp.uniform("lr", 0.0, hi),
        }
        return Domain(lambda c: 0.0, space, pass_expr_memo_ctrl=False)

    a = _space_fingerprint(build(["tanh", "relu"]).expr)
    assert a == _space_fingerprint(build(["tanh", "relu"]).expr)
    assert a != _space_fingerprint(build(["relu", "tanh"]).expr)
    assert a != _space_fingerprint(build(["tanh", "relu"], hi=2.0).expr)

    # numpy-valued bounds/options are VALUES to the guard, not opaque
    # type names: changed contents must change the hash
    def build_np(hi, opts):
        space = {
            "k": hp.choice("k", list(opts)),
            "lr": hp.uniform("lr", 0.0, hi),
        }
        return Domain(lambda c: 0.0, space, pass_expr_memo_ctrl=False)

    b = _space_fingerprint(build_np(np.int64(1), 2 ** np.arange(3)).expr)
    assert b == _space_fingerprint(
        build_np(np.int64(1), 2 ** np.arange(3)).expr
    )
    assert b != _space_fingerprint(
        build_np(np.int64(5), 2 ** np.arange(3)).expr
    )
    assert b != _space_fingerprint(
        build_np(np.int64(1), 3 ** np.arange(3)).expr
    )


def test_asha_checkpoint_refuses_different_algo(tmp_path):
    """Resuming a model-driven run with the defaulted (random) algo is
    a silently different experiment -- the guard must refuse it; the
    same algo under functools.partial tuning still matches."""
    import functools

    from hyperopt_tpu import rand
    from hyperopt_tpu.hyperband import asha

    path = str(tmp_path / "asha.ckpt")

    def my_algo(new_ids, domain, trials, seed):
        return rand.suggest(new_ids, domain, trials, seed)

    kw = dict(max_budget=9, eta=3, max_jobs=12, workers=1)
    with pytest.raises(KeyboardInterrupt):
        asha(
            _KillableQuad(5), SPACE, algo=my_algo,
            rstate=np.random.default_rng(0), checkpoint=path, **kw
        )
    with pytest.raises(ValueError, match="refusing to resume"):
        asha(  # defaulted algo (rand.suggest) != my_algo
            _KillableQuad(), SPACE, rstate=np.random.default_rng(0),
            checkpoint=path, **kw
        )
    out = asha(  # partial of the SAME algo unwraps to a match
        _KillableQuad(), SPACE, algo=functools.partial(my_algo),
        rstate=np.random.default_rng(0), checkpoint=path, **kw
    )
    assert len(out["trials"]) == 12


def test_asha_checkpoint_refuses_different_objective(tmp_path):
    """ADVICE r5 medium: the asha guard must fingerprint the OBJECTIVE
    like the sha/hyperband guards already do -- resuming a snapshot with
    an edited objective would silently mix the old objective's recorded
    losses with new evaluations of the new one.  Same stable-class
    protocol as the sha tests: the unchanged objective resumes, a
    different class is refused."""
    from hyperopt_tpu.hyperband import asha

    path = str(tmp_path / "asha.ckpt")
    kw = dict(max_budget=9, eta=3, max_jobs=12, workers=1)
    with pytest.raises(KeyboardInterrupt):
        asha(
            _KillableQuad(5), SPACE, rstate=np.random.default_rng(3),
            checkpoint=path, **kw
        )
    with pytest.raises(ValueError, match="refusing to resume"):
        asha(  # a DIFFERENT objective class: refused
            _BlockerQuad(), SPACE, rstate=np.random.default_rng(3),
            checkpoint=path, **kw
        )
    out = asha(  # the unchanged objective (same class): resumes
        _KillableQuad(), SPACE, rstate=np.random.default_rng(3),
        checkpoint=path, **kw
    )
    assert len(out["trials"]) == 12


def test_asha_evaluator_arity_validated():
    """A mismatched evaluator (e.g. written against a 2-arg seam) must
    fail fast at entry, not burn every job as a failed trial inside the
    failure-tolerant worker."""
    from hyperopt_tpu.hyperband import asha

    with pytest.raises(TypeError, match="vals, cfg, budget"):
        asha(
            budgeted_quad, SPACE, max_budget=4, max_jobs=2, workers=1,
            evaluator=lambda vals, budget: 0.0,
        )


def test_evaluator_arity_check_accepts_uninspectable_builtins():
    """ADVICE r5: ``inspect.signature`` raises ValueError for some
    C-implemented callables (``min`` on this CPython) -- the pre-check
    must SKIP those, not crash the driver with an unrelated error, while
    still rejecting introspectable mismatches."""
    import inspect

    from hyperopt_tpu.hyperband import _check_evaluator_arity

    with pytest.raises(ValueError):
        inspect.signature(min)  # the premise: min is un-introspectable
    _check_evaluator_arity(min)  # must not raise
    _check_evaluator_arity(lambda vals, cfg, budget: 0.0)
    with pytest.raises(TypeError, match="vals, cfg, budget"):
        _check_evaluator_arity(lambda vals, budget: 0.0)


def test_asha_checkpoint_every_validated(tmp_path):
    from hyperopt_tpu.hyperband import asha

    with pytest.raises(ValueError, match="checkpoint_every"):
        asha(
            budgeted_quad, SPACE, max_budget=9, max_jobs=5, workers=1,
            checkpoint=str(tmp_path / "c"), checkpoint_every=0,
        )


def test_asha_ladder_shape_fuzz():
    """Property fuzz over ladder shapes: random (eta, min/max budget,
    max_jobs) -- integral and float budgets -- must all satisfy the
    order-independent scheduler invariants: exact job count, budgets
    drawn from the ladder, integral ladders staying integral, monotone
    rung occupancy with per-rung uniqueness, and promotion chains
    intact (the top-1/eta COUNT bound is deliberately not asserted --
    see the inline note on ASHA's moving promotion window)."""
    from hyperopt_tpu.hyperband import asha

    rng = np.random.default_rng(42)
    for trial in range(6):
        eta = int(rng.integers(2, 4))
        n_rungs = int(rng.integers(2, 4))
        min_budget = (
            int(rng.integers(1, 3)) if trial % 2 == 0
            else float(rng.uniform(0.5, 2.0))
        )
        max_budget = min_budget * eta ** (n_rungs - 1)
        max_jobs = int(rng.integers(10, 40))
        out = asha(
            budgeted_quad, SPACE, max_budget=max_budget, eta=eta,
            min_budget=min_budget, max_jobs=max_jobs, workers=2,
            rstate=np.random.default_rng(trial),
        )
        trials = out["trials"]
        assert len(trials) == max_jobs, (trial, eta, n_rungs)
        ladder = [
            int(round(min_budget * eta**r)) if trial % 2 == 0
            else min_budget * eta**r
            for r in range(n_rungs)
        ]
        budgets = [t["result"]["budget"] for t in trials.trials]
        assert set(budgets) <= set(ladder), (budgets, ladder)
        if trial % 2 == 0:  # integral ladders stay integral end-to-end
            assert all(isinstance(b, int) for b in budgets)
        counts = [budgets.count(b) for b in ladder]
        # occupancy decays up the ladder.  NOTE a tighter
        # counts[r+1] <= counts[r]//eta does NOT hold: the promotable
        # window is top-1/eta of COMPLETED results at decision time, and
        # as better results arrive new keys enter the (moving) window --
        # cumulative promotions legitimately exceed final_n//eta.  That
        # aggressiveness vs sync SHA is ASHA's documented trade, not a
        # bug; each promoted key WAS top-1/eta when promoted.
        assert counts == sorted(counts, reverse=True), counts
        # every promotion was unique per (key, rung): no config occupies
        # a rung twice, so rung occupancy counts distinct configs
        for b in ladder:
            xs = [
                round(t["misc"]["vals"]["x"][0], 9)
                for t in trials.trials if t["result"]["budget"] == b
            ]
            assert len(xs) == len(set(xs)), (b, xs)
        # promotion chains: every deeper-rung config was evaluated at
        # the rung below first
        def x_at(b):
            return {
                round(t["misc"]["vals"]["x"][0], 9)
                for t in trials.trials if t["result"]["budget"] == b
            }
        for r in range(n_rungs - 1):
            assert x_at(ladder[r + 1]) <= x_at(ladder[r])
        assert np.isfinite(out["best_loss"])


def test_compile_hyperband_on_device():
    """Full multi-bracket Hyperband as chained on-device ladders: the
    bracket spread (eta**s configs at rung-0 budget steps*eta**(s_max-s))
    is correct, every bracket reports, replicas compose, reproducible."""
    from hyperopt_tpu.hyperband import compile_hyperband

    runner = compile_hyperband(
        linear_train_fn, lambda key, n: {"theta": jnp.full((n,), 5.0)},
        {"lr": (1e-3, 5.0)}, s_max=3, eta=2, steps_per_rung=2,
    )
    out = runner(seed=0)
    assert [b["n_configs"] for b in out["brackets"]] == [8, 4, 2, 1]
    assert [
        [r["steps"] for r in b["rungs"]] for b in out["brackets"]
    ] == [[2, 4, 8, 16], [4, 8, 16], [8, 16], [16]]
    assert out["best_loss"] < 1e-2
    assert out["best_loss"] == min(b["best_loss"] for b in out["brackets"])
    assert runner(seed=0)["best_loss"] == out["best_loss"]

    packed = compile_hyperband(
        linear_train_fn, lambda key, n: {"theta": jnp.full((n,), 5.0)},
        {"lr": (1e-3, 5.0)}, s_max=2, eta=2, steps_per_rung=2, replicas=3,
    )(seed=1)
    assert all(len(b["replica_bests"]) == 3 for b in packed["brackets"])


@pytest.mark.slow
def test_compile_sha_transformer_rungs():
    """SHA over real LM training: rung budgets deepen survivors and the
    final loss improves on rung-0's best."""
    from hyperopt_tpu.models import transformer

    P = 8
    model = transformer.TinyLM(vocab=16, d_model=16, n_heads=2,
                               n_layers=1, max_len=16)
    params = transformer.init_population(
        model, P, jax.random.key(0), seq_len=16
    )
    momentum = jax.tree.map(jnp.zeros_like, params)
    train_fn = transformer.make_pbt_train_fn(
        model, batch_size=8, seq_len=16, vocab=16
    )
    runner = compile_sha(
        train_fn, (params, momentum),
        {"lr": (1e-3, 1.0), "wd": (1e-7, 1e-2)},
        n_configs=P, eta=2, steps_per_rung=3,
    )
    out = runner(seed=0)
    assert np.isfinite(out["best_loss"])
    assert out["best_loss"] <= out["rungs"][0]["best_loss"]


# ---------------------------------------------------------------------------
# round-5 advisor regressions
# ---------------------------------------------------------------------------


def test_budgets_integral_accepts_numpy_ints():
    """np.int64 max_budget is integral too (advisor r4): an epoch-count
    objective asserting ints must not see 9.0 because the budget came
    through numpy arithmetic."""
    seen = []

    def int_checking(cfg, budget):
        seen.append(budget)
        assert isinstance(budget, int), budget
        return (cfg["x"] - 3.0) ** 2 / budget

    out = successive_halving(
        int_checking, SPACE, max_budget=np.int64(9), eta=3,
        rstate=np.random.default_rng(0),
    )
    assert np.isfinite(out["best_loss"])
    assert set(seen) == {1, 3, 9}


def test_asha_tid_sequence_contiguous():
    """The rung-0 suggestion's tid is REUSED by its record (advisor r4):
    no orphaned tids, so the store's tid sequence is exactly 0..N-1."""
    from hyperopt_tpu.hyperband import asha

    out = asha(
        budgeted_quad, SPACE, max_budget=9, eta=3, max_jobs=30,
        workers=1, rstate=np.random.default_rng(2),
    )
    tids = sorted(t["tid"] for t in out["trials"].trials)
    assert tids == list(range(30))


def test_compile_sha_init_state_seed_arg():
    """A one-arg init_state callable receives the runner's seed, so seed
    sweeps can vary the initial population (advisor r4)."""
    got = []

    def init(seed):
        got.append(seed)
        return {"theta": jnp.full((4,), 2.0)}

    runner = compile_sha(
        linear_train_fn, init, {"lr": (1e-3, 1.0)},
        n_configs=4, eta=2, steps_per_rung=2,
    )
    runner(seed=3)
    runner(seed=11)
    assert got == [3, 11]


def test_compile_hyperband_seed_varies_initial_population():
    """runner(seed=...) folds into each bracket's init key: different
    seeds start every bracket from DIFFERENT initial populations, while
    the same seed reproduces bitwise (advisor r4 -- previously keyed by
    bracket id alone)."""
    from hyperopt_tpu.hyperband import compile_hyperband

    keys = []

    def init(key, n):
        keys.append(np.asarray(jax.random.key_data(key)).tolist())
        return {"theta": 2.0 + jax.random.uniform(key, (n,))}

    runner = compile_hyperband(
        linear_train_fn, init, {"lr": (1e-3, 1.0)},
        s_max=1, eta=2, steps_per_rung=2,
    )
    runner(seed=0)
    k_seed0 = list(keys)
    keys.clear()
    runner(seed=1)
    k_seed1 = list(keys)
    assert k_seed1 != k_seed0  # the seed reaches the init key
    keys.clear()
    runner(seed=1)
    assert keys == k_seed1  # and stays deterministic per seed


def test_compile_sha_zero_required_arg_callables_keep_zero_arg_call():
    """Default-valued / **kwargs callables are NOT seed-taking: passing
    the seed into a default-bound parameter would silently override the
    captured value (code-review r5)."""
    state = {"theta": jnp.full((4,), 2.0)}
    for init in (
        lambda s_=state: s_,            # default-capture idiom
        lambda **kw: state,             # kwargs-only
    ):
        runner = compile_sha(
            linear_train_fn, init, {"lr": (1e-3, 1.0)},
            n_configs=4, eta=2, steps_per_rung=2,
        )
        assert np.isfinite(runner(seed=3)["best_loss"])


# ---------------------------------------------------------------------------
# round-5: fused-scheduler checkpoint/resume (VERDICT r4 weak #3)
# ---------------------------------------------------------------------------


def _result_equal(a, b):
    """Bitwise result equality for compile_sha/compile_hyperband dicts."""
    assert a["best_loss"] == b["best_loss"]
    assert a["best_hypers"] == b["best_hypers"]
    if "rungs" in a:
        assert a["rungs"] == b["rungs"]
        assert a["replica_bests"] == b["replica_bests"]
        for la, lb in zip(
            jax.tree.leaves(a["state"]), jax.tree.leaves(b["state"])
        ):
            assert np.array_equal(np.asarray(la), np.asarray(lb))
    if "brackets" in a:
        assert a["brackets"] == b["brackets"]
        assert a["best_bracket"] == b["best_bracket"]


def _teed_saves(monkeypatch, copies):
    """Route snapshot writes through a tee that keeps every version --
    version k is exactly what a kill after rung k+1 would leave behind
    (writes are atomic)."""
    import shutil

    import hyperopt_tpu.utils.checkpoint as ckpt_mod

    orig = ckpt_mod.save_pytree

    def tee(tree, path):
        out = orig(tree, path)
        dst = f"{path}.v{len(copies)}"
        shutil.copyfile(path, dst)
        copies.append(dst)
        return out

    monkeypatch.setattr(ckpt_mod, "save_pytree", tee)


def test_compile_sha_checkpoint_resume_bitwise(tmp_path, monkeypatch):
    """Kill-mid-ladder resume: for EVERY rung boundary, resuming from
    that snapshot bitwise-reproduces the uninterrupted result; the
    durable run itself matches the non-durable one; a completed
    snapshot replays with no further writes."""
    import shutil

    def build():
        return compile_sha(
            linear_train_fn, {"theta": jnp.full((8,), 5.0)},
            {"lr": (1e-3, 5.0)}, n_configs=8, eta=2, steps_per_rung=3,
        )

    base = build()(seed=3)  # uninterrupted, non-durable
    copies = []
    _teed_saves(monkeypatch, copies)
    ck = str(tmp_path / "sha.npz")
    durable = build()(seed=3, checkpoint=ck)
    _result_equal(durable, base)
    assert len(copies) == 4  # one snapshot per rung

    # kill after each rung boundary, resume, compare bitwise
    for k, version in enumerate(copies[:-1]):
        ck_k = str(tmp_path / f"killed_{k}.npz")
        shutil.copyfile(version, ck_k)
        resumed = build()(seed=3, checkpoint=ck_k)
        _result_equal(resumed, base)

    # completed snapshot: pure host reassembly, no new rungs written
    n_before = len(copies)
    again = build()(seed=3, checkpoint=ck)
    _result_equal(again, base)
    assert len(copies) == n_before


def test_compile_sha_checkpoint_rejects_mismatch(tmp_path):
    ck = str(tmp_path / "sha.npz")
    runner = compile_sha(
        linear_train_fn, {"theta": jnp.full((4,), 2.0)},
        {"lr": (1e-3, 1.0)}, n_configs=4, eta=2, steps_per_rung=2,
    )
    runner(seed=5, checkpoint=ck)
    with pytest.raises(ValueError, match="refusing to resume"):
        runner(seed=6, checkpoint=ck)  # different seed
    other = compile_sha(
        linear_train_fn, {"theta": jnp.full((4,), 2.0)},
        {"lr": (1e-3, 1.0)}, n_configs=4, eta=2, steps_per_rung=3,
    )
    with pytest.raises(ValueError, match="refusing to resume"):
        other(seed=5, checkpoint=ck)  # different ladder schedule


def test_compile_hyperband_checkpoint_resume_bitwise(tmp_path, monkeypatch):
    """Kill-mid-SPREAD resume: later brackets absent, the interrupted
    bracket's ladder truncated to an intermediate rung -- the resumed
    spread bitwise-reproduces the uninterrupted result, replaying
    completed brackets from their snapshots alone."""
    import shutil

    from hyperopt_tpu.hyperband import compile_hyperband

    def build():
        return compile_hyperband(
            linear_train_fn,
            lambda key, n: {"theta": 5.0 + jax.random.uniform(key, (n,))},
            {"lr": (1e-3, 1.0)}, s_max=2, eta=2, steps_per_rung=2,
        )

    base = build()(seed=4)
    copies = []
    _teed_saves(monkeypatch, copies)
    ckdir = tmp_path / "hb"
    durable = build()(seed=4, checkpoint=str(ckdir))
    _result_equal(durable, base)

    # simulate a kill inside bracket s=1 (second of three): bracket_2
    # complete, bracket_1 truncated to its first rung snapshot,
    # bracket_0 never started
    killdir = tmp_path / "hb_killed"
    killdir.mkdir()
    shutil.copyfile(ckdir / "bracket_2.npz", killdir / "bracket_2.npz")
    first_b1 = next(
        c for c in copies if "bracket_1.npz.v" in c
    )
    shutil.copyfile(first_b1, killdir / "bracket_1.npz")
    resumed = build()(seed=4, checkpoint=str(killdir))
    _result_equal(resumed, base)


# ---------------------------------------------------------------------------
# round-5: ASHA over compiled device programs (VERDICT r4 weak #6)
# ---------------------------------------------------------------------------


def test_budget_objective_is_budget_aware_and_thread_safe():
    """transformer.budget_objective: one jitted program per distinct
    budget; deeper budgets genuinely train longer (loss improves for a
    sane lr); concurrent ASHA workers drive it without corruption."""
    from hyperopt_tpu.models import transformer
    from hyperopt_tpu.hyperband import asha

    fn = transformer.budget_objective()
    cfg = {"lr": 0.3, "wd": 1e-5}
    l1 = fn(cfg, 1)
    l9 = fn(cfg, 9)
    assert np.isfinite(l1) and np.isfinite(l9)
    assert l9 < l1  # budget really is SGD steps
    assert fn(cfg, 9) == l9  # deterministic, program cached

    out = asha(
        fn, transformer.hpo_space(), max_budget=9, eta=3, max_jobs=20,
        workers=4, rstate=np.random.default_rng(0),
    )
    assert np.isfinite(out["best_loss"])
    assert len(out["trials"]) == 20
    budgets = {t["result"]["budget"] for t in out["trials"].trials}
    assert budgets <= {1, 3, 9}
