"""graftpilot unit coverage (ISSUE 16): the autoscaler control loop
(hysteresis, cooldown, bounds, the down-backend veto), the probe
exponential-backoff schedule, jittered ``retry_after`` hints,
cross-host claim fencing between simulated hosts, ``fsck --serve``'s
cross-host artifact kinds, and the scale-out vs failover membership
race.

The chaos-grade scenarios (kill-during-scale under a storm, the PILOT
crash windows, record -> replay bitwise) live in
``tests/test_pilot_chaos.py``.
"""

import os
import threading

import pytest

from hyperopt_tpu import hp
from hyperopt_tpu.distributed.faults import REAL_FS, FaultPlan
from hyperopt_tpu.exceptions import Overloaded, OwnershipLost
from hyperopt_tpu.serve import FleetPilot, PilotConfig, SuggestService
from hyperopt_tpu.serve.fleet import Fleet, StudyClaim
from hyperopt_tpu.serve.pilot import PilotSample, summarize_rows
from hyperopt_tpu.serve.router import RouterServer, _Backend

SPACE = {
    "x": hp.uniform("x", -5, 5),
    "lr": hp.loguniform("lr", -5, 0),
    "c": hp.choice("c", [0, 1]),
}
ALGO_KW = dict(n_cand=16, n_cand_cat=8)
KW = dict(max_batch=8, n_startup_jobs=2, snapshot_cadence=4, **ALGO_KW)


# ---------------------------------------------------------------------------
# satellite: probe exponential backoff (pinned schedule)
# ---------------------------------------------------------------------------


def _stub_router(cap=8):
    """A RouterServer over one fake backend whose probe outcome is a
    flag -- ``_rpc`` is stubbed so the SCHEDULE (what the satellite
    pins) is exercised without socket noise; the socket path is
    end-to-end covered in test_obs.py."""
    router = RouterServer(
        [_Backend("b0", "127.0.0.1", 1)], salt="fp",
        probe_backoff_cap=cap,
    )
    state = {"ok": False}

    def rpc(conns, rid, req, timeout=30.0):
        if not state["ok"]:
            raise ConnectionError("down")
        return {"ok": True}

    router._rpc = rpc
    return router, state


def test_probe_backoff_schedule_pinned():
    """A persistently-down backend is probed on sweeps 0, 2, 5, 10,
    19, 28, ... : after the f-th consecutive failure the next
    ``min(2**(f-1), cap)`` sweeps skip it entirely (cap=8 -> steady
    state one probe per 9 sweeps, never rarer)."""
    router, _ = _stub_router(cap=8)
    probed_on = []
    for sweep in range(29):
        before = router._probes_total.value
        router.probe_backends()
        if router._probes_total.value > before:
            probed_on.append(sweep)
    assert probed_on == [0, 2, 5, 10, 19, 28]
    assert router._probe_failures.value == len(probed_on)
    assert "b0" in router._alive_excluded()


def test_probe_backoff_resets_on_rejoin():
    """A single successful probe clears the whole schedule: the
    backend rejoins within <= cap sweeps of coming back, and a LATER
    failure starts the backoff from scratch (probed again on the very
    next sweep, not after the old wait)."""
    router, state = _stub_router(cap=4)
    for _ in range(8):  # deep into backoff (fails=3, waits growing)
        router.probe_backends()
    assert "b0" in router._alive_excluded()
    state["ok"] = True
    for sweep in range(router.probe_backoff_cap + 1):
        router.probe_backends()
        if "b0" not in router._alive_excluded():
            break
    assert "b0" not in router._alive_excluded()
    assert sweep <= router.probe_backoff_cap
    assert router.metrics.counter(
        "router_backend_rejoins_total"
    ).value == 1
    assert router._probe_fails == {} and router._probe_wait == {}
    # fresh failure: no residual wait -- next sweep probes immediately
    state["ok"] = False
    before = router._probes_total.value
    router.probe_backends()
    assert router._probes_total.value == before + 1
    assert router._probe_wait["b0"] == 1


# ---------------------------------------------------------------------------
# satellite: seeded, bounded retry_after jitter at the reply seam
# ---------------------------------------------------------------------------


def _overflowing_service(**kw):
    svc = SuggestService(
        SPACE, background=False, max_batch=2, max_queue=2,
        n_startup_jobs=2, **ALGO_KW, **kw,
    )
    h = svc.create_study("jam", seed=3)
    for _ in range(2):  # fill the bounded queue exactly
        h.ask_async()
    return svc, h


def _refusals(h, n):
    hints = []
    for _ in range(n):
        with pytest.raises(Overloaded) as ei:
            h.ask_async()
        assert ei.value.reason == "queue_full"
        hints.append(ei.value.retry_after)
    return hints


def test_retry_after_jitter_spread_bounded_and_seeded():
    """Refused asks carry a JITTERED hint: spread over [base, base *
    (1 + retry_jitter)], deterministic per seed -- the shed herd stops
    retrying on one synchronized tick."""
    svc, h = _overflowing_service(retry_jitter_seed=7)
    base = svc.scheduler.retry_after()
    hints = _refusals(h, 16)
    assert len(set(hints)) > 1, "jitter produced a synchronized herd"
    assert all(base <= x <= round(base * 1.25, 6) for x in hints), (
        base, hints,
    )
    svc.shutdown()
    # seeded: the same seed re-derives the same hint sequence...
    svc2, h2 = _overflowing_service(retry_jitter_seed=7)
    assert _refusals(h2, 16) == hints
    svc2.shutdown()
    # ...and jitter off means the exact queue-drain estimate, always
    svc3, h3 = _overflowing_service(retry_jitter=0.0)
    assert set(_refusals(h3, 8)) == {base}
    svc3.shutdown()


def test_retry_jitter_never_touches_suggestion_streams():
    """The jitter rng lives at the REPLY seam, drawn only after an ask
    was refused: two services differing only in jitter config serve
    bitwise-identical suggestion streams, refusals interleaved or
    not."""
    streams = []
    for jitter_kw in (
        dict(retry_jitter=0.0),
        dict(retry_jitter=0.25, retry_jitter_seed=99),
    ):
        svc = SuggestService(
            SPACE, background=False, max_batch=2, max_queue=2,
            n_startup_jobs=2, **ALGO_KW, **jitter_kw,
        )
        h = svc.create_study("s", seed=11)
        got = []
        for tid in range(6):
            t, vals = h.ask()
            # jam the queue and eat a refusal between real asks
            f1, f2 = h.ask_async(), h.ask_async()
            with pytest.raises(Overloaded):
                h.ask_async()
            while svc.pump():  # drain the jam deterministically
                pass
            got.append((t, tuple(sorted(vals.items()))))
            h.tell(t, 0.5 + 0.1 * tid, vals=vals)
            del f1, f2
        streams.append(got)
        svc.shutdown()
    assert streams[0] == streams[1]


# ---------------------------------------------------------------------------
# the controller: summarize -> decide (hysteresis / cooldown / bounds)
# ---------------------------------------------------------------------------


def _rows(replicas, queue=0.0, shed=0.0, occ_sum=0.0, occ_count=0.0,
          down=0, lat_buckets=None):
    rows = []
    for i, rid in enumerate(sorted(replicas)):
        rows.append({
            "name": "serve_queue_depth", "labels": {"replica": rid},
            "value": queue / len(replicas),
        })
        rows.append({
            "name": "serve_shed_total", "labels": {"replica": rid},
            "value": shed / len(replicas),
        })
        rows.append({
            "name": "serve_batch_occupancy", "labels": {"replica": rid},
            "buckets": [], "sum": occ_sum / len(replicas),
            "count": occ_count / len(replicas),
        })
        if lat_buckets and i == 0:
            rows.append({
                "name": "serve_ask_latency_seconds",
                "labels": {"replica": rid},
                "buckets": [
                    {"le": le, "count": c} for le, c in lat_buckets
                ],
                "sum": 0.0,
                "count": sum(c for _, c in lat_buckets),
            })
    for j in range(down):
        rows.append({
            "name": "router_backend_up", "labels": {"backend": f"d{j}"},
            "value": 0,
        })
    return rows


def test_summarize_rows_distills_the_scrape():
    rows = _rows(
        ("r0", "r1"), queue=10.0, shed=4.0, occ_sum=1.5, occ_count=2.0,
        down=1,
        lat_buckets=[(0.005, 90), (0.05, 8), (float("inf"), 2)],
    )
    s = summarize_rows(rows)
    assert s.replicas == ("r0", "r1") and s.n_replicas == 2
    assert s.queue_depth == pytest.approx(10.0)
    assert s.shed_total == pytest.approx(4.0)
    assert s.occupancy_sum == pytest.approx(1.5)
    assert s.occupancy_count == pytest.approx(2.0)
    assert s.backends_down == 1
    # p99 upper bound: 99th of 100 falls in the +inf bucket -> the
    # largest FINITE boundary is the estimate
    assert s.ask_p99_s == pytest.approx(0.05)
    empty = summarize_rows([])
    assert empty.n_replicas == 0 and empty.ask_p99_s == 0.0


def _fleet_with_pilot(root, replica_ids, cfg, scrape):
    fleet = Fleet(
        SPACE, root, replica_ids=list(replica_ids),
        plans={}, **KW,
    )
    pilot = FleetPilot(fleet, config=cfg, scrape=scrape)
    return fleet, pilot


def test_pilot_scale_out_hysteresis_cooldown_and_max_bound(tmp_path):
    """Pressure must be SUSTAINED (breach_ticks) to scale out; the
    actuation starts a cooldown during which even hard pressure holds;
    max_replicas clamps everything."""
    root = str(tmp_path / "up")
    state = {"queue": 0.0}
    fleet, pilot = _fleet_with_pilot(
        root, ["r0"],
        PilotConfig(min_replicas=1, max_replicas=2, queue_high=8.0,
                    breach_ticks=2, clear_ticks=3, cooldown_ticks=2),
        lambda: _rows(sorted(fleet.replicas), queue=state["queue"]),
    )
    assert pilot.tick().action == "hold"  # quiet fleet
    state["queue"] = 20.0
    d1 = pilot.tick()
    assert d1.action == "hold", "one noisy scrape must never scale"
    d2 = pilot.tick()
    assert d2.action == "scale_out" and d2.rid == "p0"
    assert "queue_depth" in d2.reason
    assert set(fleet.replicas) == {"r0", "p0"}
    # cooldown: the migration's own spike cannot trigger the next move
    assert [pilot.tick().reason for _ in range(2)] == ["cooldown"] * 2
    # at max_replicas the breach is acknowledged but never actuated
    for _ in range(4):
        assert pilot.tick().action == "hold"
    assert set(fleet.replicas) == {"r0", "p0"}
    rows = {r["name"]: r for r in pilot.metrics_rows()
            if not r.get("labels")}
    assert rows["pilot_scale_outs_total"]["value"] == 1
    assert rows["pilot_scale_out_ms"]["value"] >= 0.0
    fleet.shutdown()


def test_pilot_scale_in_quiet_min_bound_and_down_veto(tmp_path):
    """Scale-in needs clear_ticks of quiet, drains the deterministic
    victim (lexicographically last scraped replica), never goes below
    min_replicas, and is VETOED while any backend is reported down --
    scale-out is not."""
    root = str(tmp_path / "down")
    state = {"queue": 0.0, "down": 0}
    fleet, pilot = _fleet_with_pilot(
        root, ["r0", "r1"],
        PilotConfig(min_replicas=1, max_replicas=3, queue_high=8.0,
                    queue_low=1.0, breach_ticks=2, clear_ticks=2,
                    cooldown_ticks=0),
        lambda: _rows(sorted(fleet.replicas), queue=state["queue"],
                      down=state["down"]),
    )
    # quiet but a backend is down: the veto holds capacity
    state["down"] = 1
    for _ in range(4):
        assert pilot.tick().action == "hold"
    assert set(fleet.replicas) == {"r0", "r1"}
    # the down backend vetoes scale-IN only -- pressure still scales out
    state["queue"] = 20.0
    pilot.tick()
    d = pilot.tick()
    assert d.action == "scale_out"
    assert set(fleet.replicas) == {"r0", "r1", "p0"}
    # recovered and quiet: drain back down to min_replicas, one
    # replica per quiet window, and stop there
    state.update(queue=0.0, down=0)
    drained = []
    for _ in range(10):
        d = pilot.tick()
        if d.action == "scale_in":
            drained.append(d.rid)
    assert drained == ["r1", "r0"]  # lexicographic max first
    assert set(fleet.replicas) == {"p0"}
    assert pilot.metrics.counter("pilot_scale_ins_total").value == 2
    fleet.shutdown()


def test_pilot_actuation_refusal_absorbed_not_retried(tmp_path):
    """A fleet that refuses the actuation (the rid joined by another
    path since the scrape) costs one counted error; the pilot moves
    its name counter past the contested rid and the next breach
    actuates cleanly."""
    root = str(tmp_path / "refuse")
    state = {"queue": 20.0}
    fleet, pilot = _fleet_with_pilot(
        root, ["r0"],
        PilotConfig(min_replicas=1, max_replicas=4, queue_high=8.0,
                    breach_ticks=1, cooldown_ticks=0),
        lambda: _rows(["r0"], queue=state["queue"]),
    )
    fleet.add_replica("p0", migrate=False)  # steal the pilot's name
    d = pilot.tick()
    assert d.action == "scale_out" and d.rid == "p0"
    assert pilot.metrics.counter(
        "pilot_actuation_errors_total"
    ).value == 1
    d2 = pilot.tick()  # re-derived from the (stale-by-design) scrape
    assert d2.action == "scale_out" and d2.rid == "p1"
    assert "p1" in fleet.replicas
    fleet.shutdown()


def test_pilot_crash_points_registered():
    from hyperopt_tpu.distributed.faults import (
        ALL_CRASH_POINTS,
        PILOT_CRASH_POINTS,
    )

    assert set(PILOT_CRASH_POINTS) <= set(ALL_CRASH_POINTS)
    assert set(PILOT_CRASH_POINTS) == {
        "pilot_after_decision_before_actuate",
        "pilot_mid_scale_out",
    }


# ---------------------------------------------------------------------------
# cross-host claim fencing: two simulated hosts, one NFS-shaped root
# ---------------------------------------------------------------------------


def _host_service(root, owner, seed):
    """One simulated host: its own fault-plan fs seam and owner id
    over the SHARED root."""
    return SuggestService(
        SPACE, root=root, owner=owner, background=False,
        fs=FaultPlan(seed=seed).fs(), max_batch=4, n_startup_jobs=2,
        **ALGO_KW,
    )


def test_two_hosts_epoch_fencing_over_shared_root(tmp_path):
    """hostA and hostB (distinct fs seams, distinct owner ids) fight
    over one study in a shared root: a live claim refuses the second
    host, ``takeover`` fences the first host out with a bumped epoch,
    and every op the fenced zombie attempts raises OwnershipLost --
    the epochs on disk stay strictly monotone throughout."""
    root = str(tmp_path / "nfs")
    a = _host_service(root, "hostA", seed=1)
    b = _host_service(root, "hostB", seed=2)
    ha = a.create_study("s", seed=5)
    e0 = StudyClaim.read(root, "s")["epoch"]
    tid, vals = ha.ask()
    ha.tell(tid, 0.5, vals=vals)

    # a live foreign claim refuses a plain acquire on the other host
    with pytest.raises(OwnershipLost):
        b.create_study("s")
    assert StudyClaim.read(root, "s")["epoch"] == e0

    # failover authority: hostB takes over; the epoch fence bumps
    hb = b.create_study("s", takeover=True)
    doc = StudyClaim.read(root, "s")
    assert doc["replica"] == "hostB" and doc["epoch"] > e0
    assert hb.n_tells == 1  # adopted WITH the shared-root history

    # hostA is now a zombie: every fenced op drops, double-serving
    # nothing
    with pytest.raises(OwnershipLost):
        ha.ask()
    with pytest.raises(OwnershipLost):
        ha.tell(99, 0.1, vals=vals)
    t2, v2 = hb.ask()
    hb.tell(t2, 0.25, vals=v2)
    assert hb.n_tells == 2
    a.shutdown()
    b.shutdown()


class _SkewedFS:
    """An fs seam whose clock runs ``skew`` seconds ahead -- the other
    host's NFS view of our mtimes."""

    def __init__(self, inner, skew):
        self._inner = inner
        self._skew = float(skew)

    def getmtime(self, path):
        return self._inner.getmtime(path) + self._skew

    def __getattr__(self, name):
        return getattr(self._inner, name)


def test_fsck_cross_host_kinds_repair_then_restorable(tmp_path):
    """The three cross-host artifacts a shared root accumulates --
    stale foreign claim, half-migrated handoff tombstone, divergent
    WAL/snap pair -- are each detected, repaired, and leave the root
    adoptable; ``claim_grace`` under a skewed remote clock suppresses
    the false positive."""
    from hyperopt_tpu.distributed.fsck import audit_serve, repair_serve

    root = str(tmp_path / "nfs")
    svc = _host_service(root, "hostA", seed=3)
    h = svc.create_study("a", seed=1)
    for tid in range(3):
        t, vals = h.ask()
        h.tell(t, 0.5 + tid, vals=vals)
    # hostA vanishes without releasing: its claim is now stale-foreign
    for st in svc.scheduler._studies.values():
        st.persist.wal.close()

    # a second family stranded mid-handoff: the migration source
    # released with the handoff marker, and no target ever adopted
    svc2 = _host_service(root, "hostB", seed=4)
    h2 = svc2.create_study("b", seed=2)
    t, vals = h2.ask()
    h2.tell(t, 1.0, vals=vals)
    svc2.handoff_study("b")

    # a third family whose WAL was replaced under the bundle: the
    # snapshot counts tells the fresh (empty) log never logged
    svc3 = _host_service(root, "hostC", seed=5)
    h3 = svc3.create_study("c", seed=3)
    for _ in range(3):
        t, vals = h3.ask()
        h3.tell(t, 2.0, vals=vals)
    svc3.close_study("c")  # final snapshot counts total_tells=3
    with open(os.path.join(root, "c.wal"), "wb"):
        pass  # the log a history-blind host re-created from nothing

    # no live-owner knowledge: the claim check stays quiet (operator
    # opt-in), the handoff + divergence still surface
    kinds = {i.kind for i in audit_serve(root)}
    assert kinds == {"study_half_migrated", "wal_snap_divergent"}

    # with the live-owner set, hostA's claim is stale-foreign...
    issues = audit_serve(root, live_owners={"hostB", "hostC"})
    kinds = {i.kind for i in issues}
    assert kinds == {
        "claim_stale_foreign", "study_half_migrated",
        "wal_snap_divergent",
    }, issues
    # ...unless the claim is too YOUNG to be trusted stale:
    # claim_grace absorbs an in-flight handoff from a host whose
    # clock runs AHEAD (its mtimes land in the auditor's future)
    young = audit_serve(
        root, live_owners={"hostB", "hostC"}, claim_grace=60.0,
        fs=_SkewedFS(REAL_FS, skew=120.0),
    )
    assert "claim_stale_foreign" not in {i.kind for i in young}
    # a claim old past the grace stays stale -- a BEHIND clock only
    # ages it further
    old = audit_serve(
        root, live_owners={"hostB", "hostC"}, claim_grace=60.0,
        fs=_SkewedFS(REAL_FS, skew=-120.0),
    )
    assert "claim_stale_foreign" in {i.kind for i in old}

    n = repair_serve(root, issues)
    assert n == len(issues)
    assert audit_serve(root, live_owners={"hostB", "hostC"}) == []
    # repaired-then-restorable: tombstoned claims adopt WITHOUT
    # takeover (the repair is the failover authority), and the
    # quarantined-WAL family restores from its bundle superset
    svc4 = _host_service(root, "hostD", seed=6)
    assert svc4.create_study("a").n_tells == 3
    assert svc4.create_study("b").n_tells == 1
    assert svc4.create_study("c", takeover=True).n_tells == 3
    svc4.shutdown()
    svc3.shutdown()


def test_fsck_serve_cli_cross_host_flags(tmp_path, capsys):
    """``hyperopt-tpu-fsck --serve --live-owner ... --claim-grace``
    end to end: report, repair, clean."""
    from hyperopt_tpu.distributed import fsck

    root = str(tmp_path / "nfs")
    svc = _host_service(root, "gone", seed=9)
    h = svc.create_study("a", seed=1)
    t, vals = h.ask()
    h.tell(t, 0.5, vals=vals)
    for st in svc.scheduler._studies.values():
        st.persist.wal.close()

    rc = fsck.main([
        "--serve", root, "--live-owner", "alive", "--claim-grace", "0",
    ])
    assert rc == 1
    assert "claim_stale_foreign" in capsys.readouterr().out
    rc = fsck.main([
        "--serve", root, "--live-owner", "alive", "--repair",
    ])
    assert rc == 0
    rc = fsck.main(["--serve", root, "--live-owner", "alive"])
    assert rc == 0
    assert "clean" in capsys.readouterr().out.lower()


# ---------------------------------------------------------------------------
# the membership race: scale-out vs failover (lockdep armed)
# ---------------------------------------------------------------------------


def test_add_replica_races_failover_no_double_adopt_no_strand(
    tmp_path, monkeypatch,
):
    """``Fleet.add_replica(migrate=True)`` on one thread races
    ``failover()`` on another: the membership lock serializes them, so
    no study ends up adopted by two replicas (the claim on disk names
    exactly its route target) and none is stranded ownerless -- every
    study still serves."""
    from hyperopt_tpu.analysis import lockdep

    dep = lockdep.arm_scheduler_class(monkeypatch)
    root = str(tmp_path / "race")
    fleet = Fleet(SPACE, root, replica_ids=["r0", "r1", "r2"], **KW)
    names = [f"s{i:02d}" for i in range(9)]
    for i, n in enumerate(names):
        fleet.register(n)
        fleet.replicas[fleet.route(n)].open_study(n, seed=40 + i)
    for n in names:
        rep = fleet.replicas[fleet.route(n)]
        t, vals = rep.ask(n)
        rep.tell(n, t, 0.5, vals=vals)

    victim = "r1"
    fleet.mark_dead(victim)
    errs = []

    def run(fn, *a, **kw):
        try:
            fn(*a, **kw)
        except Exception as e:  # surfaced after join, not swallowed
            errs.append(e)

    t1 = threading.Thread(target=run, args=(fleet.failover, victim))
    t2 = threading.Thread(
        target=run, args=(fleet.add_replica, "r9"),
        kwargs=dict(migrate=True),
    )
    t1.start()
    t2.start()
    t1.join(30)
    t2.join(30)
    assert not errs, errs
    assert victim not in fleet.ring.nodes and "r9" in fleet.ring.nodes

    # no strand: every study routes to a live replica and serves
    for n in names:
        rid = fleet.route(n)
        assert rid in fleet.replicas and not fleet.replicas[rid].dead
        rep = fleet.replicas[rid]
        t, vals = rep.ask(n)
        rep.tell(n, t, 0.25, vals=vals)
    # no double-adopt: the claim on disk names exactly the replica the
    # fleet routes to -- nobody else holds a live claim
    for n in names:
        doc = StudyClaim.read(root, n)
        assert not doc.get("released")
        assert doc["replica"] == fleet.route(n), (n, doc)
    # each study's tells landed exactly once
    for n in names:
        st = fleet.replicas[fleet.route(n)].service.scheduler.study(n)
        assert st.buf.count == 2, (n, st.buf.count)
        assert st.persist.wal.total_tells == 2
    fleet.shutdown()
    assert dep.inversions == 0, dep.errors


# ---------------------------------------------------------------------------
# satellite: graftlint + graftrace stay clean over the new modules
# ---------------------------------------------------------------------------


def test_pilot_modules_lint_and_trace_clean():
    """graftlint + graftrace over exactly the new pilot/replay modules
    (the whole-package zero-baseline gates cover them too; this pins
    the satellite explicitly)."""
    from hyperopt_tpu.analysis import lint_paths

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [
        os.path.join(repo, "hyperopt_tpu", "serve", "pilot.py"),
        os.path.join(repo, "hyperopt_tpu", "serve", "replay.py"),
    ]
    for pack in ("ast", "trace"):
        result = lint_paths(paths, pack=pack)
        assert not result.findings, (pack, result.findings)
