"""Distribution-shape tests for hp.* draws on both sampling paths
(reference: tests/test_pchoice.py / test_randint.py, SURVEY.md SS4)."""

import numpy as np
import pytest

import jax

from hyperopt_tpu import hp
from hyperopt_tpu.ops.compile import compile_space
from hyperopt_tpu.vectorize import VectorizeHelper


def host_draws(space, n, seed=0):
    helper = VectorizeHelper(space)
    rng = np.random.default_rng(seed)
    return [helper.sample_one(rng) for _ in range(n)]


def jax_draws(space, n, seed=0):
    ps = compile_space(space)
    v, a = ps.sample_prior(jax.random.key(seed), n)
    return ps, np.asarray(v), np.asarray(a)


# -- pchoice ----------------------------------------------------------------


def test_pchoice_host_frequencies():
    space = hp.pchoice("p", [(0.1, "a"), (0.6, "b"), (0.3, "c")])
    draws = [c["p"] for c in host_draws(space, 3000)]
    freq = np.bincount(draws, minlength=3) / len(draws)
    np.testing.assert_allclose(freq, [0.1, 0.6, 0.3], atol=0.035)


def test_pchoice_jax_frequencies():
    space = hp.pchoice("p", [(0.1, "a"), (0.6, "b"), (0.3, "c")])
    ps, v, a = jax_draws(space, 3000)
    freq = np.bincount(v[0].astype(int), minlength=3) / v.shape[1]
    np.testing.assert_allclose(freq, [0.1, 0.6, 0.3], atol=0.035)


def test_pchoice_normalizes_probs():
    space = hp.pchoice("p", [(2.0, "a"), (6.0, "b")])
    draws = [c["p"] for c in host_draws(space, 2000)]
    freq = np.mean(np.asarray(draws) == 1)
    assert 0.68 < freq < 0.82


def test_pchoice_invalid():
    from hyperopt_tpu.exceptions import InvalidAnnotatedParameter

    with pytest.raises(InvalidAnnotatedParameter):
        hp.pchoice("p", [])
    with pytest.raises(InvalidAnnotatedParameter):
        hp.pchoice("p", [(-1.0, "a"), (0.0, "b")])


# -- randint ----------------------------------------------------------------


def test_randint_host_uniform():
    space = hp.randint("r", 6)
    draws = np.array([c["r"] for c in host_draws(space, 3000)])
    assert draws.min() == 0 and draws.max() == 5
    freq = np.bincount(draws, minlength=6) / len(draws)
    np.testing.assert_allclose(freq, np.full(6, 1 / 6), atol=0.03)


def test_randint_low_high_host_and_jax():
    space = hp.randint("r", 3, 9)
    draws = np.array([c["r"] for c in host_draws(space, 2000)])
    assert draws.min() == 3 and draws.max() == 8
    ps, v, a = jax_draws(space, 2000)
    vals = v[0].astype(int)
    assert vals.min() == 3 and vals.max() == 8
    freq = np.bincount(vals - 3, minlength=6) / len(vals)
    np.testing.assert_allclose(freq, np.full(6, 1 / 6), atol=0.035)


def test_randint_bad_arity():
    from hyperopt_tpu.exceptions import InvalidAnnotatedParameter

    with pytest.raises(InvalidAnnotatedParameter):
        hp.randint("r")
    with pytest.raises(InvalidAnnotatedParameter):
        hp.randint("r", 1, 2, 3)


# -- continuous shapes ------------------------------------------------------


@pytest.mark.parametrize(
    "maker,check",
    [
        (lambda: hp.uniform("x", 2, 5),
         lambda d: 2 <= d.min() and d.max() <= 5 and abs(d.mean() - 3.5) < 0.2),
        (lambda: hp.loguniform("x", np.log(1e-3), np.log(1e3)),
         lambda d: abs(np.median(np.log(d))) < 0.9),
        (lambda: hp.normal("x", 4.0, 0.5),
         lambda d: abs(d.mean() - 4.0) < 0.1 and abs(d.std() - 0.5) < 0.1),
        (lambda: hp.lognormal("x", 1.0, 0.3),
         lambda d: abs(np.log(d).mean() - 1.0) < 0.1),
        (lambda: hp.qnormal("x", 0.0, 5.0, 2.0),
         lambda d: np.allclose(d, np.round(d / 2.0) * 2.0)),
    ],
)
def test_continuous_shapes_both_paths(maker, check):
    space = maker()
    host = np.array([c["x"] for c in host_draws(space, 1500)])
    assert check(host), f"host draws failed shape check: {host[:5]}"
    ps, v, a = jax_draws(space, 1500)
    assert check(v[0]), f"jax draws failed shape check: {v[0][:5]}"


def test_uniformint_inclusive_bounds_both_paths():
    space = hp.uniformint("x", 2, 7)
    host = np.array([c["x"] for c in host_draws(space, 1500)])
    assert set(np.unique(host)) <= set(range(2, 8))
    assert {2, 7} <= set(np.unique(host))


# -- checkpointing the dense history ---------------------------------------


def test_obs_buffer_checkpoint_roundtrip(tmp_path):
    from hyperopt_tpu.jax_trials import ObsBuffer
    from hyperopt_tpu.utils.checkpoint import load_obs_buffer, save_obs_buffer

    ps = compile_space({"x": hp.uniform("x", 0, 1)})
    buf = ObsBuffer(ps)
    for i in range(10):
        buf.add({"x": i / 10}, float(i))
    path = str(tmp_path / "obs.npz")
    save_obs_buffer(buf, path)
    buf2 = load_obs_buffer(ps, path)
    assert buf2.count == 10
    np.testing.assert_array_equal(buf2.losses, buf.losses)
    np.testing.assert_array_equal(buf2.values, buf.values)

    ps_other = compile_space({"y": hp.uniform("y", 0, 1)})
    with pytest.raises(ValueError):
        load_obs_buffer(ps_other, path)


def test_obs_buffer_orbax_roundtrip(tmp_path):
    """The orbax-native checkpoint path: same contract as the npz
    roundtrip (arrays, cursors, pending list, label validation)."""
    pytest.importorskip("orbax.checkpoint")
    from hyperopt_tpu.jax_trials import ObsBuffer
    from hyperopt_tpu.utils.checkpoint import (
        load_obs_buffer_orbax,
        save_obs_buffer_orbax,
    )

    ps = compile_space({"x": hp.uniform("x", 0, 1)})
    buf = ObsBuffer(ps)
    for i in range(10):
        buf.add({"x": i / 10}, float(i))
    # empty-pending (the common case) must roundtrip: orbax rejects
    # zero-size arrays, so the tree packs pending behind a sentinel
    d0 = str(tmp_path / "obs_orbax_empty")
    save_obs_buffer_orbax(buf, d0)
    assert load_obs_buffer_orbax(ps, d0)._pending == []
    buf._pending = [3, 7]
    d = str(tmp_path / "obs_orbax")
    save_obs_buffer_orbax(buf, d)
    buf2 = load_obs_buffer_orbax(ps, d)
    assert buf2.count == 10
    assert buf2._pending == [3, 7]
    np.testing.assert_array_equal(buf2.losses, buf.losses)
    np.testing.assert_array_equal(buf2.values, buf.values)
    np.testing.assert_array_equal(buf2.tids, buf.tids)

    ps_other = compile_space({"y": hp.uniform("y", 0, 1)})
    with pytest.raises(ValueError):
        load_obs_buffer_orbax(ps_other, d)
