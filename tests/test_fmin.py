"""Integration tests for the fmin driver (reference: tests/test_fmin.py,
SURVEY.md SS4: points_to_evaluate, early_stop_fn, timeout/loss_threshold,
save->resume, reproducibility, exception propagation)."""

import os
import pickle

import numpy as np
import pytest

from hyperopt_tpu import (
    STATUS_OK,
    Trials,
    fmin,
    fmin_pass_expr_memo_ctrl,
    generate_trials_to_calculate,
    hp,
    no_progress_loss,
    rand,
    space_eval,
    tpe,
)
from hyperopt_tpu.exceptions import AllTrialsFailed
from hyperopt_tpu.fmin import FMinIter, StopExperiment
from hyperopt_tpu.base import Domain


def quad(x):
    return (x - 3.0) ** 2


SPACE = hp.uniform("x", -10, 10)


def test_fmin_basic_rand():
    best = fmin(
        quad, SPACE, algo=rand.suggest, max_evals=30,
        rstate=np.random.default_rng(0), show_progressbar=False,
    )
    assert abs(best["x"] - 3.0) < 3.0


def test_fmin_reproducible_with_fixed_rstate():
    kw = dict(algo=rand.suggest, max_evals=20, show_progressbar=False)
    b1 = fmin(quad, SPACE, rstate=np.random.default_rng(123), **kw)
    b2 = fmin(quad, SPACE, rstate=np.random.default_rng(123), **kw)
    assert b1 == b2


def test_fmin_int_seed_accepted():
    b1 = fmin(quad, SPACE, algo=rand.suggest, max_evals=10, rstate=5,
              show_progressbar=False)
    b2 = fmin(quad, SPACE, algo=rand.suggest, max_evals=10, rstate=5,
              show_progressbar=False)
    assert b1 == b2


def test_fmin_points_to_evaluate():
    trials = Trials()
    best = fmin(
        quad, SPACE, algo=rand.suggest, max_evals=15,
        points_to_evaluate=[{"x": 3.0}, {"x": -4.0}],
        trials=trials, rstate=np.random.default_rng(0), show_progressbar=False,
    )
    # the seeded exact optimum must win
    assert best == {"x": 3.0}
    assert trials.trials[0]["misc"]["vals"]["x"] == [3.0]
    assert trials.trials[1]["misc"]["vals"]["x"] == [-4.0]


def test_generate_trials_to_calculate_structure():
    trials = generate_trials_to_calculate([{"x": 1.0}, {"x": 2.0}])
    assert len(trials._dynamic_trials) == 2
    assert trials._dynamic_trials[0]["state"] == 0


def test_fmin_early_stop():
    trials = Trials()
    fmin(
        quad, SPACE, algo=rand.suggest, max_evals=10_000,
        early_stop_fn=no_progress_loss(10), trials=trials,
        rstate=np.random.default_rng(0), show_progressbar=False,
    )
    assert len(trials) < 10_000


def test_fmin_loss_threshold():
    trials = Trials()
    fmin(
        quad, SPACE, algo=rand.suggest, max_evals=10_000,
        loss_threshold=5.0, trials=trials,
        rstate=np.random.default_rng(0), show_progressbar=False,
    )
    assert trials.best_trial["result"]["loss"] <= 5.0
    assert len(trials) < 10_000


def test_fmin_timeout():
    import time

    trials = Trials()

    def slow(x):
        time.sleep(0.05)
        return x**2

    fmin(
        slow, SPACE, algo=rand.suggest, max_evals=10_000, timeout=0.5,
        trials=trials, rstate=np.random.default_rng(0), show_progressbar=False,
    )
    assert 1 <= len(trials) < 100


def test_fmin_trials_save_file_resume(tmp_path):
    save_file = str(tmp_path / "trials.pkl")
    fmin(
        quad, SPACE, algo=rand.suggest, max_evals=10,
        trials_save_file=save_file, rstate=np.random.default_rng(0),
        show_progressbar=False,
    )
    assert os.path.exists(save_file)
    with open(save_file, "rb") as f:
        saved = pickle.load(f)
    assert len(saved) == 10
    # resume: max_evals=25 continues from the saved 10
    fmin(
        quad, SPACE, algo=rand.suggest, max_evals=25,
        trials_save_file=save_file, rstate=np.random.default_rng(1),
        show_progressbar=False,
    )
    with open(save_file, "rb") as f:
        resumed = pickle.load(f)
    assert len(resumed) == 25


def test_fmin_exception_propagates_by_default():
    class Boom(RuntimeError):
        pass

    def exploding(x):
        raise Boom("nope")

    with pytest.raises(Boom):
        fmin(
            exploding, SPACE, algo=rand.suggest, max_evals=3,
            rstate=np.random.default_rng(0), show_progressbar=False,
        )


def test_fmin_catch_eval_exceptions():
    calls = {"n": 0}

    def flaky(x):
        calls["n"] += 1
        if calls["n"] % 2:
            raise RuntimeError("flaky")
        return x**2

    trials = Trials()
    fmin(
        flaky, SPACE, algo=rand.suggest, max_evals=10,
        catch_eval_exceptions=True, trials=trials,
        rstate=np.random.default_rng(0), show_progressbar=False,
    )
    from hyperopt_tpu import JOB_STATE_DONE, JOB_STATE_ERROR

    states = [t["state"] for t in trials.trials]
    assert states.count(JOB_STATE_ERROR) > 0
    assert states.count(JOB_STATE_DONE) > 0


def test_fmin_all_failed_argmin_raises():
    def failing(x):
        return {"status": "fail"}

    trials = Trials()
    with pytest.raises(AllTrialsFailed):
        fmin(
            failing, SPACE, algo=rand.suggest, max_evals=3, trials=trials,
            rstate=np.random.default_rng(0), show_progressbar=False,
        )


def test_fmin_return_argmin_false():
    loss = fmin(
        quad, SPACE, algo=rand.suggest, max_evals=10, return_argmin=False,
        rstate=np.random.default_rng(0), show_progressbar=False,
    )
    assert isinstance(loss, float)


def test_space_eval_choice_resolution():
    space = hp.choice("c", [("a", hp.uniform("u", 0, 1)), ("b",)])
    out = space_eval(space, {"c": 0, "u": 0.25})
    assert out == ["a", 0.25]
    assert space_eval(space, {"c": 1}) == ["b"]


def test_fmin_pass_expr_memo_ctrl():
    seen = {}

    @fmin_pass_expr_memo_ctrl
    def raw_fn(expr, memo, ctrl):
        seen["expr"] = expr
        seen["memo"] = memo
        return {"status": STATUS_OK, "loss": 1.0}

    fmin(
        raw_fn, SPACE, algo=rand.suggest, max_evals=2,
        rstate=np.random.default_rng(0), show_progressbar=False,
    )
    assert "expr" in seen and "memo" in seen


def test_algo_can_stop_experiment():
    def stopping_algo(new_ids, domain, trials, seed):
        if len(trials.trials) >= 5:
            return StopExperiment
        return rand.suggest(new_ids, domain, trials, seed)

    trials = Trials()
    fmin(
        quad, SPACE, algo=stopping_algo, max_evals=100, trials=trials,
        rstate=np.random.default_rng(0), show_progressbar=False,
    )
    assert len(trials) == 5


def test_fminiter_stepwise():
    domain = Domain(quad, SPACE)
    trials = Trials()
    it = FMinIter(
        rand.suggest, domain, trials, rstate=np.random.default_rng(0),
        max_evals=7, show_progressbar=False,
    )
    it.run(3)
    assert len(trials) == 3
    it.exhaust()
    assert len(trials) == 7


def test_trials_fmin_method():
    trials = Trials()
    best = trials.fmin(
        quad, SPACE, algo=rand.suggest, max_evals=5,
        rstate=np.random.default_rng(0), show_progressbar=False,
    )
    assert "x" in best and len(trials) == 5


def test_max_queue_len_batching():
    seen_batches = []

    def batch_watcher(new_ids, domain, trials, seed):
        seen_batches.append(len(new_ids))
        return rand.suggest(new_ids, domain, trials, seed)

    fmin(
        quad, SPACE, algo=batch_watcher, max_evals=12, max_queue_len=4,
        rstate=np.random.default_rng(0), show_progressbar=False,
    )
    assert max(seen_batches) == 4


def test_scope_wrapped_hp_nodes_in_space():
    """The reference's ubiquitous idiom: pyll scope ops wrapping hp nodes
    inside a space (scope.int(hp.quniform(...)), arithmetic on draws).
    Every suggest path must evaluate the wrapping graph when building
    the trial's config."""
    from hyperopt_tpu import rand, tpe, tpe_jax
    from hyperopt_tpu.pyll import scope

    space = {
        "n_layers": scope.int(hp.quniform("n_layers", 1, 8, 1)),
        "lr_x2": hp.uniform("lr", 0.0, 1.0) * 2.0,
        "plain": hp.uniform("plain", -1, 1),
    }

    seen_types = []

    def obj(cfg):
        seen_types.append(type(cfg["n_layers"]))
        assert 0.0 <= cfg["lr_x2"] <= 2.0
        return (
            abs(cfg["n_layers"] - 4) * 0.1
            + (cfg["lr_x2"] - 1.0) ** 2
            + cfg["plain"] ** 2
        )

    for algo in (rand.suggest, tpe.suggest, tpe_jax.suggest):
        trials = Trials()
        fmin(obj, space, algo=algo, max_evals=25, trials=trials,
             rstate=np.random.default_rng(0), show_progressbar=False,
             return_argmin=False)
        assert len(trials) == 25
        assert np.isfinite(min(trials.losses()))
    assert all(issubclass(t, (int, np.integer)) for t in seen_types)


def test_container_shaped_spaces():
    """Reference parity: spaces may be arbitrary pytrees -- lists, tuple
    options inside hp.choice, bare scalars -- not just dicts."""
    from hyperopt_tpu import tpe_jax

    space_list = [hp.uniform("a", 0, 1), hp.uniform("b", -1, 0)]
    trials = Trials()
    fmin(lambda cfg: cfg[0] ** 2 + cfg[1] ** 2, space_list,
         algo=tpe_jax.suggest, max_evals=25, trials=trials,
         rstate=np.random.default_rng(0), show_progressbar=False,
         return_argmin=False)
    assert min(trials.losses()) < 0.5

    space_tup = hp.choice("c", [("conv", hp.uniform("k", 0, 1)), ("pool",)])
    trials = Trials()
    fmin(lambda cfg: cfg[1] if len(cfg) == 2 else 0.5, space_tup,
         algo=tpe_jax.suggest, max_evals=25, trials=trials,
         rstate=np.random.default_rng(1), show_progressbar=False,
         return_argmin=False)
    assert min(trials.losses()) < 0.5


def test_mix_suggest_end_to_end():
    """SURVEY SS2 algo mixer: probabilistic mixture over suggest fns at
    the plugin seam (reference hyperopt/mix.py shape)."""
    from functools import partial

    from hyperopt_tpu import mix, rand, tpe

    calls = {"tpe": 0, "rand": 0}

    def counting(name, inner):
        def algo(new_ids, domain, trials, seed):
            calls[name] += 1
            return inner(new_ids, domain, trials, seed)
        return algo

    algo = partial(mix.suggest, p_suggest=[
        (0.7, counting("tpe", tpe.suggest)),
        (0.3, counting("rand", rand.suggest)),
    ])
    trials = Trials()
    fmin(lambda x: (x - 3.0) ** 2, hp.uniform("x", -10, 10), algo=algo,
         max_evals=40, trials=trials, rstate=np.random.default_rng(0),
         show_progressbar=False, return_argmin=False)
    assert len(trials) == 40
    assert calls["tpe"] + calls["rand"] == 40
    assert calls["tpe"] > calls["rand"] > 0  # both arms exercised, 70/30
    assert min(trials.losses()) < 1.0

    with pytest.raises(ValueError):
        mix.suggest([0], None, trials, 0,
                    p_suggest=[(0.5, rand.suggest), (0.2, tpe.suggest)])
