"""Native (C++) host-math library: build + exact parity with the numpy
oracle implementations in hyperopt_tpu.tpe."""

import os

import numpy as np
import pytest

from hyperopt_tpu import native
from hyperopt_tpu.tpe import (
    GMM1_lpdf_numpy,
    LGMM1_lpdf_numpy,
    adaptive_parzen_normal_numpy,
)

# per-test (not module-level) skip: the strict-mode regression test
# below monkeypatches the build and must run EXACTLY on the
# no-toolchain machines a module-level skip would exclude
needs_native = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain / native build failed"
)


@needs_native
def test_build_produces_loadable_lib():
    assert os.path.exists(native.lib_path())
    assert native.available()


@needs_native
@pytest.mark.parametrize("n_obs", [0, 1, 2, 7, 40])
def test_adaptive_parzen_parity(n_obs):
    rng = np.random.default_rng(n_obs)
    obs = rng.normal(0.5, 2.0, n_obs)
    want = adaptive_parzen_normal_numpy(obs, 1.0, 0.0, 5.0, 25)
    got = native.adaptive_parzen(obs, 1.0, 0.0, 5.0, 25)
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=1e-12, atol=1e-12)


@needs_native
def test_adaptive_parzen_parity_no_forgetting():
    rng = np.random.default_rng(9)
    obs = rng.normal(0, 1, 30)
    want = adaptive_parzen_normal_numpy(obs, 0.5, 1.0, 3.0, 0)
    got = native.adaptive_parzen(obs, 0.5, 1.0, 3.0, 0)
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=1e-12, atol=1e-12)


@needs_native
@pytest.mark.parametrize(
    "low,high,q,logspace",
    [
        (None, None, None, False),
        (-2.0, 3.0, None, False),
        (0.0, 10.0, 1.0, False),
        (None, None, None, True),
        (-1.0, 1.0, None, True),
        (None, None, 0.5, True),
    ],
)
def test_gmm_lpdf_parity(low, high, q, logspace):
    rng = np.random.default_rng(0)
    K = 9
    w = rng.uniform(0.1, 1.0, K)
    w = w / w.sum()
    mu = rng.normal(0.5, 1.5, K)
    sigma = rng.uniform(0.2, 2.0, K)
    if logspace:
        x = rng.uniform(0.05, 6.0, 40)
        if q:
            x = np.maximum(np.round(x / q) * q, 0.0)
        want = LGMM1_lpdf_numpy(x, w, mu, sigma, low=low, high=high, q=q)
    else:
        x = rng.uniform(-3.0, 8.0, 40)
        if q:
            x = np.round(x / q) * q
            x = np.clip(x, low, high)
        want = GMM1_lpdf_numpy(x, w, mu, sigma, low=low, high=high, q=q)
    got = native.gmm_lpdf(x, w, mu, sigma, low=low, high=high, q=q,
                          logspace=logspace)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-9)


@needs_native
def test_dispatch_used_by_public_api():
    """The public GMM1_lpdf must agree with the numpy oracle regardless of
    which backend actually served it."""
    from hyperopt_tpu.tpe import GMM1_lpdf

    rng = np.random.default_rng(3)
    w = np.array([0.3, 0.7])
    mu = np.array([-1.0, 2.0])
    sigma = np.array([0.5, 1.5])
    x = rng.normal(0, 2, 16)
    np.testing.assert_allclose(
        GMM1_lpdf(x, w, mu, sigma, low=-4.0, high=4.0),
        GMM1_lpdf_numpy(x, w, mu, sigma, low=-4.0, high=4.0),
        rtol=1e-9,
    )


def test_strict_mode_raises_on_every_call(monkeypatch):
    """HYPEROPT_TPU_NATIVE=1 with a broken build must fail EVERY caller:
    the first failure used to latch tried=True and silently hand later
    callers the numpy fallback strict mode forbids (advisor finding r3)."""
    saved = dict(native._STATE)
    try:
        native._STATE.update(lib=None, tried=False, strict_error=None)
        monkeypatch.setenv("HYPEROPT_TPU_NATIVE", "1")
        boom = RuntimeError("no compiler")
        monkeypatch.setattr(
            native, "build", lambda force=False: (_ for _ in ()).throw(boom)
        )
        with pytest.raises(RuntimeError, match="no compiler"):
            native._load()
        # second call takes the lock-free tried fast path -- must still raise
        with pytest.raises(RuntimeError, match="no compiler"):
            native._load()
        with pytest.raises(RuntimeError, match="no compiler"):
            native.available()
        # flipping OFF strict mode after a strict failure must restore
        # the graceful numpy fallback (the cached error is strict-only)
        monkeypatch.setenv("HYPEROPT_TPU_NATIVE", "0")
        assert native._load() is None
        assert native.available() is False
    finally:
        native._STATE.clear()
        native._STATE.update(saved)


@needs_native
def test_native_speedup_sane():
    import time

    rng = np.random.default_rng(1)
    K, S = 500, 256
    w = np.full(K, 1.0 / K)
    mu = rng.normal(0, 3, K)
    sigma = rng.uniform(0.5, 1.5, K)
    x = rng.normal(0, 3, S)

    t0 = time.perf_counter()
    for _ in range(20):
        native.gmm_lpdf(x, w, mu, sigma, low=-8.0, high=8.0)
    native_dt = time.perf_counter() - t0
    assert native_dt < 5.0
