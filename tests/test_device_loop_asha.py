"""graftrung: rung-based early stopping fused inside the compiled scan.

The round-19 acceptance contracts:

* ``compile_fmin(asha=)`` turns each scan step into a full ASHA bracket
  -- every config trains a rung of epochs, promotions are computed
  ON-DEVICE, and survivors are COMPACTED (gathered) to train deeper
  inside the same compiled program; ``best`` ranks full-fidelity trials
  only and the result stream gains ``rung_of``/``asha`` metadata;
* the chunked ASHA scan (including a padded tail chunk) is BITWISE
  identical to the flat ASHA scan -- the per-bracket key folds the
  global step index, so chunk geometry changes nothing;
* a 1-device ``rung_submesh`` program is BITWISE the unsharded program
  (the graftmesh degenerate-anchor idiom); wider sub-meshes are
  structurally identical (same promotions, finite stream);
* kill-and-resume at EVERY device-loop crash point x chunk (= bracket)
  boundary is bitwise the uninterrupted run, with foreign-asha-geometry
  bundles refused (the guard pins eta/rung_epochs/n_rungs);
* ``artifact_callback`` streams each bracket's winner (slot, loss,
  TRAINED params) through the declared ``io_callback`` seam; cadence
  off compiles NO callback twin (zero extra dispatches, pinned on the
  compiled-function attribute);
* conditional spaces: the device loop masks inactive-branch dims to
  0.0 before ``init_fn``/``step_fn`` see them, matching the host
  driver's omit-inactive-labels semantics (allclose; bitwise pins are
  reserved for device-vs-device streams), and the masking is
  OBSERVABLE -- an unmasked host recompute diverges wherever the
  suggest kernels left other-branch garbage in inactive cells.
"""

import os

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin
from hyperopt_tpu.device_loop import compile_fmin
from hyperopt_tpu.distributed.faults import (
    DEVICE_LOOP_CRASH_POINTS,
    FaultPlan,
    SimulatedCrash,
)
from hyperopt_tpu.exceptions import CheckpointError
from hyperopt_tpu.hyperband import rung_schedule
from hyperopt_tpu.models.synthetic import (
    cond_tune_objective,
    cond_tune_space,
    mlp_tune_objective,
    mlp_tune_space,
)

N_EVALS = 24
BATCH = 8  # bracket population; 3 brackets of ladder (8,1)->(4,2)->(2,4)
ASHA = {"eta": 2, "rung_epochs": 1, "n_rungs": 3}
KW = dict(
    max_evals=N_EVALS, batch_size=BATCH, algo="tpe", n_startup_jobs=2,
    n_EI_candidates=8,
)
SEED = 5


def _mlp():
    return (
        mlp_tune_objective(n_epochs=1, n_train=32, in_dim=4, hidden=8),
        mlp_tune_space(),
    )


_RESULTS = {}


def _flat_asha():
    """The flat (unchunked, unsharded) ASHA run: the bitwise anchor."""
    if "flat" not in _RESULTS:
        obj, space = _mlp()
        _RESULTS["flat"] = compile_fmin(obj, space, asha=ASHA, **KW)(
            seed=SEED
        )
    return _RESULTS["flat"]


def _assert_stream_equal(a, b):
    """The FULL ASHA result stream, bitwise: every drawn value, every
    activity bit, every rung loss, every promotion decision, and the
    derived full-fidelity best."""
    for f in ("values", "active", "losses", "rung_of"):
        np.testing.assert_array_equal(a[f], b[f], err_msg=f)
    assert a["best_loss"] == b["best_loss"]
    assert a["best_index"] == b["best_index"]
    assert a["best"] == b["best"]


# ---------------------------------------------------------------------------
# ladder geometry
# ---------------------------------------------------------------------------


def test_rung_schedule_ladder_invariants():
    full = rung_schedule(8, 2, None, 1)
    assert full == [(8, 1, 0), (4, 2, 1), (2, 4, 3), (1, 8, 7)]
    for (w0, s0, o0), (w1, s1, o1) in zip(full, full[1:]):
        assert w1 * 2 == w0          # eta-fold survivor cut
        assert s1 == s0 * 2          # eta-fold fidelity growth
        assert o1 == o0 + s0         # cumulative epoch offsets
    assert rung_schedule(8, 2, 3, 1) == [(8, 1, 0), (4, 2, 1), (2, 4, 3)]
    with pytest.raises(ValueError, match="power of eta"):
        rung_schedule(6, 2)


# ---------------------------------------------------------------------------
# the fused bracket: flat, chunked, sharded
# ---------------------------------------------------------------------------


def test_flat_asha_rung_stream_and_full_fidelity_best():
    out = _flat_asha()
    assert out["n_evals"] == N_EVALS
    rung_of = out["rung_of"]
    assert rung_of.shape == (N_EVALS,)
    # 3 brackets of 8: each stops 4 at rung 0, 2 at rung 1, 2 at rung 2
    counts = np.bincount(rung_of + 1, minlength=4)
    assert list(counts) == [0, 12, 6, 6]
    assert np.isfinite(out["losses"]).all()
    # best ranks FULL-FIDELITY trials only
    full = rung_of == ASHA["n_rungs"] - 1
    assert full[out["best_index"]]
    assert out["best_loss"] == out["losses"][full].min()
    assert out["asha"]["ladder"] == [(8, 1, 0), (4, 2, 1), (2, 4, 3)]
    assert out["asha"]["eta"] == 2
    # seed-deterministic; a different seed draws a different stream
    obj, space = _mlp()
    runner = compile_fmin(obj, space, asha=ASHA, **KW)
    _assert_stream_equal(out, runner(seed=SEED))
    assert not np.array_equal(runner(seed=SEED + 1)["losses"], out["losses"])


def test_chunked_asha_bitwise_parity_with_flat():
    obj, space = _mlp()
    # chunk_size=8 -> 1 bracket per chunk, 3 chunks
    out = compile_fmin(obj, space, chunk_size=8, asha=ASHA, **KW)(seed=SEED)
    _assert_stream_equal(_flat_asha(), out)


def test_padded_tail_chunk_asha_bitwise_parity():
    obj, space = _mlp()
    # chunk_size=16 -> 2 brackets per chunk, 2 chunks; the tail chunk
    # runs one masked no-op bracket past n_steps
    out = compile_fmin(obj, space, chunk_size=16, asha=ASHA, **KW)(seed=SEED)
    _assert_stream_equal(_flat_asha(), out)


def test_one_device_submesh_bitwise_parity(cpu_mesh):
    """The graftmesh degenerate anchor: a 1-device sub-mesh must take
    the shard_map seam and still be bitwise the unsharded program."""
    obj, space = _mlp()
    runner = compile_fmin(
        obj, space, mesh=cpu_mesh(1, "trial"), trial_axis="trial",
        asha=ASHA, **KW,
    )
    assert runner._asha_submesh_devices == 1
    _assert_stream_equal(_flat_asha(), runner(seed=SEED))


def test_sharded_submesh_structural_parity(cpu_mesh):
    """Wider sub-meshes change vmap block widths (CPU libm vectorizes
    differently), so the pin is structural: the gcd sub-mesh covers the
    whole shrinking ladder, promotions match the ladder geometry, and
    the stream is finite and deterministic."""
    obj, space = _mlp()
    runner = compile_fmin(
        obj, space, mesh=cpu_mesh(4, "trial"), trial_axis="trial",
        asha=ASHA, **KW,
    )
    # gcd(smallest rung width 2, mesh axis 4) = 2
    assert runner._asha_submesh_devices == 2
    out = runner(seed=SEED)
    assert list(np.bincount(out["rung_of"] + 1, minlength=4)) == [0, 12, 6, 6]
    assert np.isfinite(out["losses"]).all()
    full = out["rung_of"] == ASHA["n_rungs"] - 1
    assert full[out["best_index"]]
    _assert_stream_equal(out, runner(seed=SEED))


# ---------------------------------------------------------------------------
# kill-and-resume at every crash point x bracket boundary
# ---------------------------------------------------------------------------


def test_kill_and_resume_every_crash_point_and_boundary_bitwise(tmp_path):
    """THE resume acceptance: arm each device-loop crash point at each
    chunk (= bracket/rung-ladder) boundary, kill, resume -- the
    completed stream including every promotion decision must be bitwise
    the uninterrupted run's, for every (point, boundary)."""
    obj, space = _mlp()
    path = str(tmp_path / "asha.ckpt")
    plan = FaultPlan(seed=0)
    runner = compile_fmin(
        obj, space, chunk_size=8, checkpoint_path=path,
        checkpoint_every=1, fs=plan.fs(), asha=ASHA, **KW,
    )
    ref = runner(seed=SEED)
    _assert_stream_equal(_flat_asha(), ref)  # durability changes nothing
    n_chunks = runner._chunk_geometry["n_chunks"]
    assert n_chunks == 3
    for point in DEVICE_LOOP_CRASH_POINTS:
        for at in range(1, n_chunks + 1):
            if os.path.exists(path):
                os.remove(path)
            plan.arm(point, at=at)
            with pytest.raises(SimulatedCrash):
                runner(seed=SEED)
            out = runner(seed=SEED, resume=True)
            _assert_stream_equal(ref, out)
    # resume of a COMPLETED run packages straight from the bundle
    out = runner(seed=SEED, resume=True)
    _assert_stream_equal(ref, out)


def test_resume_refuses_foreign_asha_geometry(tmp_path):
    obj, space = _mlp()
    path = str(tmp_path / "asha.ckpt")
    runner = compile_fmin(
        obj, space, chunk_size=8, checkpoint_path=path,
        checkpoint_every=1, asha=ASHA, **KW,
    )
    runner(seed=SEED)
    with pytest.raises(CheckpointError, match="seed"):
        runner(seed=SEED + 1, resume=True)
    # same experiment, different rung geometry -> different guard
    foreign = compile_fmin(
        obj, space, chunk_size=8, checkpoint_path=path,
        checkpoint_every=1, asha={"eta": 2, "rung_epochs": 2}, **KW,
    )
    with pytest.raises(CheckpointError, match="refusing to resume"):
        foreign(seed=SEED, resume=True)


# ---------------------------------------------------------------------------
# artifact streaming through the declared io_callback seam
# ---------------------------------------------------------------------------


def test_artifact_callback_streams_winners_and_changes_nothing():
    obj, space = _mlp()
    ref = _flat_asha()
    rows, prog = [], []
    runner = compile_fmin(
        obj, space, chunk_size=8, artifact_callback=rows.append,
        progress_callback=prog.append, asha=ASHA, **KW,
    )
    out = runner(seed=SEED)
    # observability changes NOTHING: bitwise the flat stream
    _assert_stream_equal(ref, out)
    # one winner per bracket, in bracket order, padded tail rows dropped
    assert [r["bracket"] for r in rows] == [0, 1, 2]
    for row in rows:
        assert set(row) == {"bracket", "slot", "loss", "params"}
        # the winner's loss IS its history entry, and the slot is a
        # full-fidelity survivor of its own bracket
        assert np.float32(row["loss"]) == np.float32(ref["losses"][row["slot"]])
        assert ref["rung_of"][row["slot"]] == ASHA["n_rungs"] - 1
        assert row["bracket"] * BATCH <= row["slot"] < (row["bracket"] + 1) * BATCH
        # TRAINED params crossed the seam as host numpy
        import jax

        leaves = jax.tree_util.tree_leaves(row["params"])
        assert leaves
        assert all(isinstance(l, (np.ndarray, np.generic)) for l in leaves)
    assert prog  # progress rows still ride the same chunk program
    # a second run re-fires the stream (no one-shot callback state)
    rows.clear()
    runner(seed=SEED)
    assert [r["bracket"] for r in rows] == [0, 1, 2]


def test_artifact_cadence_off_compiles_no_callback_twin():
    """Zero-extra-dispatch pin: with no callbacks requested, the chunk
    program has NO io_callback twin to dispatch through -- not a twin
    that happens to be skipped."""
    obj, space = _mlp()
    runner = compile_fmin(obj, space, chunk_size=8, asha=ASHA, **KW)
    assert runner._compiled_chunk_cb is None
    _assert_stream_equal(_flat_asha(), runner(seed=SEED))


# ---------------------------------------------------------------------------
# conditional spaces: masked init/step parity with the host driver
# ---------------------------------------------------------------------------


def test_conditional_space_masked_host_parity_and_observability():
    """Satellite contract: the device loop pins inactive-branch dims to
    0.0 before the trainable sees them -- exactly the host driver's
    omit-inactive-labels semantics -- and ``init_fn(..., active=)``
    receives the activity mask.  Proven two ways: a masked host
    recompute matches the device stream (allclose: vmap batching
    reorders fp ops by 1 ulp), and an UNMASKED recompute diverges on
    trials whose inactive cells carry other-branch garbage."""
    import jax
    import jax.numpy as jnp

    obj = cond_tune_objective(n_epochs=3, n_train=32, in_dim=4, hidden=8)
    space = cond_tune_space()
    B, seed, n = 4, 7, 12
    runner = compile_fmin(
        obj, space, max_evals=n, batch_size=B, algo="tpe",
        n_startup_jobs=2, n_EI_candidates=8,
    )
    out = runner(seed=seed)
    labels = list(runner._packed_space.labels)
    inact = ~out["active"][:, :n]
    assert inact.any(), "space never produced an inactive dim"
    # the suggest kernels really do leave garbage in inactive cells --
    # without masking there would be nothing to prove
    garbage = inact & (np.abs(out["values"][:, :n]) > 1e-9)
    assert garbage.any()

    base = jax.random.key(np.uint32(seed))

    def host_loss(t, masked):
        i, lane = t // B, t % B
        key = jax.random.fold_in(jax.random.fold_in(base, 0), i)
        ek = jax.random.split(jax.random.fold_in(key, 0x7EA1), B)[lane]
        vcol, acol = out["values"][:, t], out["active"][:, t]
        cfg = {
            lab: jnp.float32(vcol[d] if (acol[d] or not masked) else 0.0)
            for d, lab in enumerate(labels)
        }
        act = {lab: jnp.asarray(bool(acol[d])) for d, lab in enumerate(labels)}
        st = obj.init_fn(ek, cfg, active=act)
        for e in range(obj.n_epochs):
            st = obj.step_fn(st, cfg, e)
        return float(obj.loss_fn(st, cfg))

    masked = np.array([host_loss(t, True) for t in range(n)])
    unmasked = np.array([host_loss(t, False) for t in range(n)])
    dev = out["losses"][:n]
    assert np.allclose(masked, dev, rtol=1e-5, atol=1e-7)
    # observability: on trials carrying inactive garbage, training on
    # that garbage lands somewhere else
    garbage_trials = garbage.any(axis=0)
    diverged = np.abs(unmasked - dev) > 1e-4
    assert (diverged & garbage_trials).any()


def test_asha_on_conditional_space():
    obj = cond_tune_objective(n_epochs=3, n_train=32, in_dim=4, hidden=8)
    runner = compile_fmin(
        obj, cond_tune_space(), max_evals=16, batch_size=4, algo="tpe",
        n_startup_jobs=2, n_EI_candidates=8,
        asha={"eta": 2, "rung_epochs": 1},
    )
    out = runner(seed=7)
    # full ladder for B=4, eta=2: (4,1)->(2,2)->(1,4)
    assert list(np.bincount(out["rung_of"] + 1, minlength=4)) == [0, 8, 4, 4]
    assert np.isfinite(out["best_loss"])
    assert out["rung_of"][out["best_index"]] == 2


# ---------------------------------------------------------------------------
# option surface + fmin routing
# ---------------------------------------------------------------------------


def test_asha_option_validation(cpu_mesh):
    obj, space = _mlp()
    with pytest.raises(ValueError, match="dict of rung options"):
        compile_fmin(obj, space, asha=3, **KW)
    with pytest.raises(ValueError, match="unknown asha option"):
        compile_fmin(obj, space, asha={"eta": 2, "rungs": 3}, **KW)
    with pytest.raises(ValueError, match="TrainableObjective"):
        compile_fmin(lambda cfg: cfg["lr"], space, asha=ASHA, **KW)
    with pytest.raises(ValueError, match="power of eta"):
        compile_fmin(obj, space, asha=ASHA, **dict(KW, batch_size=6))
    with pytest.raises(ValueError, match="loss_threshold"):
        compile_fmin(obj, space, asha=ASHA, loss_threshold=0.1, **KW)
    with pytest.raises(ValueError, match="cand_axis"):
        compile_fmin(
            obj, space, asha=ASHA, mesh=cpu_mesh(2, "cand"),
            trial_axis=None, cand_axis="cand", **KW,
        )
    with pytest.raises(ValueError, match="requires asha="):
        compile_fmin(obj, space, chunk_size=8, artifact_callback=print, **KW)
    with pytest.raises(ValueError, match="chunk_size"):
        compile_fmin(obj, space, asha=ASHA, artifact_callback=print, **KW)
    runner = compile_fmin(obj, space, asha=ASHA, **KW)
    with pytest.raises(ValueError, match="seed sweep"):
        runner(seed=[0, 1])


def test_fmin_compiled_options_asha_routing():
    obj, space = _mlp()
    trials = Trials()
    best = fmin(
        obj, space, compiled=True, max_evals=16, trials=trials,
        rstate=np.random.default_rng(3),
        compiled_options=dict(
            batch_size=8, n_startup_jobs=2, n_EI_candidates=8,
            asha={"eta": 2, "rung_epochs": 1, "n_rungs": 3},
        ),
    )
    assert len(trials) == 16
    assert set(best) <= {"lr", "momentum", "wd", "init_scale"}
    losses = trials.losses()
    assert len(losses) == 16 and all(np.isfinite(losses))
