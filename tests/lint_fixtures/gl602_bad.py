"""GL602 true positives against contracts pinned from the good twin:
``ask`` renamed a reply field (vals -> values) and the ``best`` arm is
gone while the manifest still pins it (a stale row)."""


def _handle_request(service, req):
    op = req.get("op")
    if op == "ping":
        return {"ok": True, "pong": True}
    name = req.get("study")
    if op == "ask":
        return {"ok": True, "tid": 1, "values": {}}
    return {"ok": False, "error": "unknown"}


def drive(conn):
    conn.call({"op": "ping"})
    conn.call({"op": "ask", "study": "demo"})
