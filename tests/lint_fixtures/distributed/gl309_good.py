"""GL309 near-misses: the deadline-carrying shapes the rule must NOT
flag.  The blessed dial() seam; an explicit settimeout before the
blocking ops; create_connection with a timeout (positional or
keyword)."""

import socket

from hyperopt_tpu.serve.frames import dial


def fetch_status(host, port):
    # the graftstorm contract: dial() carries connect AND read
    # deadlines by construction
    sock, f = dial(host, port, connect_timeout=5.0, read_timeout=30.0)
    f.write(b'{"op": "status"}\n')
    f.flush()
    return f.readline()


def fetch_manual(host, port):
    sock = socket.create_connection((host, port), timeout=5.0)
    sock.settimeout(30.0)
    f = sock.makefile("rwb")  # deadline evidence in scope: settimeout
    return f.readline()


class Probe:
    def __init__(self, sock):
        self.sock = sock

    def pump(self, budget):
        self.sock.settimeout(budget)
        return self.sock.recv(4096)
