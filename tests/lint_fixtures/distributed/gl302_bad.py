"""GL302 true positive (fault-domain path): a broad except that eats
the error classes with_retries routes on, without re-raise or triage."""
import logging

logger = logging.getLogger(__name__)


def refresh(op):
    try:
        return op()
    except Exception as e:          # GL302: swallows OSError/transients
        logger.warning("refresh failed: %s", e)
        return None
