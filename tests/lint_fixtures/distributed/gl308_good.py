"""GL308 near-misses: the group-commit shapes the rule must NOT flag.
Flush per item with ONE fsync after the loop; a barrier-named helper
fsyncing inside its own retry loop (TellWAL.barrier's shape); and a
closure merely DEFINED inside a loop -- it runs later, once, not per
iteration."""

import os
import pickle


def durable_pickle(path, obj):
    with open(path, "wb") as f:
        pickle.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())


class RoundLog:
    def __init__(self, f):
        self.f = f

    def commit_round(self, records):
        # the sanctioned shape: kernel-visible per record, ONE storage
        # barrier per round
        for rec in records:
            self.f.write(rec)
            self.f.flush()
        os.fsync(self.f.fileno())

    def barrier_round(self, wals):
        # a barrier helper retrying each log's own barrier fsync IS
        # the group-commit fix -- exempt by name
        for w in wals:
            os.fsync(w.fileno())

    def arm(self, handles):
        flushers = []
        for h in handles:
            def flush_one(h=h):
                os.fsync(h.fileno())  # defined in the loop, runs once

            flushers.append(flush_one)
        return flushers
