"""GL302 near-misses: typed catches, a broad catch that consults
is_transient, and a broad catch that re-raises."""
import logging

logger = logging.getLogger(__name__)


def is_transient(exc):
    return False


def refresh(op):
    try:
        return op()
    except FileNotFoundError:       # typed: a protocol signal
        return None
    except Exception as e:
        if not is_transient(e):     # triaged: fatal errors surface
            raise
        logger.warning("transient refresh failure: %s", e)
        return None


def audit(op):
    try:
        return op()
    except Exception:
        logger.exception("audit failed")
        raise                       # re-raised: nothing swallowed
