"""GL309 true positives: socket ops with no deadline in scope in
fault-domain library code -- the hung-read shapes graftstorm retires.
A silent peer (black-hole partition, slow-loris writer, hung handler)
parks each of these threads forever."""

import socket


def fetch_status(host, port):
    # connect blocks for the OS default AND the socket inherits no
    # read deadline
    sock = socket.create_connection((host, port))  # GL309
    f = sock.makefile("rwb")  # GL309: no settimeout/dial in scope
    f.write(b'{"op": "status"}\n')
    f.flush()
    return f.readline()


class Probe:
    def __init__(self, sock):
        self.sock = sock

    def pump(self):
        return self.sock.recv(4096)  # GL309: bare blocking read
