"""GL307 near-misses: the migrated idioms -- registry descriptors
behind the historic attribute names, deltas computed en route to a
registry sink (observe/observe_since), deadline arithmetic that is
control flow rather than a metric, and private control state."""

import time

from hyperopt_tpu.obs.registry import (
    CounterAttr,
    HistogramAttr,
    MetricsRegistry,
)


class DispatchLoop:
    dispatches = CounterAttr("dispatch_total", "rounds dispatched")
    shed = CounterAttr("shed_total", "requests refused")
    latencies = HistogramAttr("dispatch_seconds", "round latency")

    def __init__(self, deadline=None):
        self.metrics = MetricsRegistry("loop")
        self.deadline = deadline
        self._rounds = 0  # private control state, not a metric

    def step(self, batch):
        t0 = time.perf_counter()
        self.dispatches += 1          # registry-backed descriptor
        self._rounds += 1
        if not batch:
            self.shed += 1            # registry-backed descriptor
        # the delta feeds a registry sink directly
        self.latencies.append(time.perf_counter() - t0)
        self.metrics.histogram("dispatch_seconds").observe_since(t0)
        return batch

    def time_left(self):
        if self.deadline is None:
            return None
        # comparison/budget arithmetic is control flow, not a metric
        return max(0.0, self.deadline - time.monotonic())
