"""GL307 true positives: ad-hoc timing/metric state in library code --
hand-rolled counter attributes accumulated outside the graftscope
registry, and inline time deltas that never land on a registry sink
(the pre-graftscope serve/scheduler idiom this rule retires)."""

import time


class DispatchLoop:
    def __init__(self):
        self.dispatches = 0          # counter-shaped: literal init...
        self.shed = 0
        self.last_latency = 0.0
        self._rounds = 0             # private control state: exempt

    def step(self, batch):
        t0 = time.perf_counter()
        self.dispatches += 1         # GL307: hand-rolled counter
        self._rounds += 1            # exempt (underscore)
        if not batch:
            self.shed += 1           # GL307: hand-rolled counter
        # GL307: the delta lives on a plain attribute, not a registry
        self.last_latency = time.perf_counter() - t0
        return batch
