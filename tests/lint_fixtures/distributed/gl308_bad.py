"""GL308 true positives: a storage barrier issued per item of a
batch/round loop in fault-domain library code -- the per-tell fsync
regime graftburst group-commit retires.  One fsync per record
serializes the whole round behind N disk barriers."""

import os
import pickle


def durable_pickle(path, obj):
    with open(path, "wb") as f:
        pickle.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())


class RoundLog:
    def __init__(self, f):
        self.f = f

    def commit_round(self, records):
        for rec in records:
            self.f.write(rec)
            self.f.flush()
            os.fsync(self.f.fileno())  # GL308: one barrier PER record

    def publish_all(self, paths, states):
        for p, s in zip(paths, states):
            durable_pickle(p, s)  # GL308: durable publish per item
