"""GL301 near-miss: fsync-before-rename (the PR 3 idiom), and a
read-then-rename function that never wrote the data it moves."""
import json
import os


def save(doc, path):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def promote(src, dst):
    with open(src) as f:            # read-only: nothing to sync
        json.load(f)
    os.rename(src, dst)
    return dst
