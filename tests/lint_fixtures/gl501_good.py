"""GL501 near miss: the same shape, every access under the guard."""
import threading


class Batcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []
        self.served = 0

    def submit(self, req):
        with self._lock:
            self._queue.append(req)

    def pick(self):
        with self._lock:
            if self._queue:
                self._queue.pop()
                self.served += 1

    def stats(self):
        with self._lock:
            return {"served": self.served, "depth": len(self._queue)}

    def reset_stats(self):
        with self._lock:
            self.served = 0

    def requeue(self, req):
        with self._lock:
            self._queue.append(req)
