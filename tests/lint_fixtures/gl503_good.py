"""GL503 near miss: block first, take the lock only to record."""
import threading
import time


class Poller:
    def __init__(self):
        self._lock = threading.Lock()
        self.ticks = 0
        self.results = []

    def tick(self, fut):
        time.sleep(0.01)
        out = fut.result()
        with self._lock:
            self.ticks += 1
            self.results.append(out)

    def snapshot(self):
        with self._lock:
            return list(self.results)
