"""GL202 near-miss: the product path stays dispatch-async; completion
is forced by the consumer's device_get, not an explicit barrier."""
import jax


def suggest(program, key, values):
    out = program(key, values)
    return jax.device_get(out)      # fetch forces completion implicitly
