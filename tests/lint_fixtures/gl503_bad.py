"""GL503 true positive: sleep and a Future fetch inside the guarded
region -- every contending thread stalls for the call's latency."""
import threading
import time


class Poller:
    def __init__(self):
        self._lock = threading.Lock()
        self.ticks = 0
        self.results = []

    def tick(self, fut):
        with self._lock:
            self.ticks += 1
            time.sleep(0.01)
            self.results.append(fut.result())

    def snapshot(self):
        with self._lock:
            return list(self.results)
