"""GL505 near miss: collect under the lock, resolve after release --
the drop_request idiom."""
import threading


class Acker:
    def __init__(self):
        self._lock = threading.Lock()
        self.pending = []

    def submit(self, fut):
        with self._lock:
            self.pending.append(fut)

    def fail_all(self, exc):
        with self._lock:
            stranded = list(self.pending)
            self.pending.clear()
        for fut in stranded:
            fut.set_exception(exc)

    def ack(self, fut, value):
        with self._lock:
            self.pending.remove(fut)
        fut.set_result(value)
