"""GL303 true positive: a hand-rolled retry loop -- sleep-on-error
inside a loop instead of _common.with_retries."""
import time


def fetch(op, attempts=5):
    for _ in range(attempts):
        try:
            return op()
        except OSError:
            time.sleep(0.05)        # GL303: hand-rolled backoff
    raise TimeoutError("gave up")
