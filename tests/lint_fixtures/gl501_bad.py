"""GL501 true positive: queue + counter written under the inferred
lock domain in the serve path, then touched lock-free elsewhere."""
import threading


class Batcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []
        self.served = 0

    def submit(self, req):
        with self._lock:
            self._queue.append(req)

    def pick(self):
        with self._lock:
            if self._queue:
                self._queue.pop()
                self.served += 1

    def stats(self):
        with self._lock:
            return {"served": self.served, "depth": len(self._queue)}

    def reset_stats(self):
        self.served = 0  # lock-free write to a guarded counter

    def requeue(self, req):
        self._queue.append(req)  # lock-free mutation of the queue
