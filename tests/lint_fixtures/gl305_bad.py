"""GL305 true positives: checkpoint-style state dumps with no fsync in
scope -- a crash mid-dump publishes a truncated file under the real
name (the fmin.py:285 latent bug class).  Two sites: an in-place
pickle checkpoint and an in-place npz snapshot."""

import pickle

import numpy as np


def save_trials_in_place(trials, path):
    with open(path, "wb") as f:
        pickle.dump(trials, f)  # no tmp, no fsync, no rename


def snapshot_arrays(values, losses, path):
    with open(path, "wb") as f:
        np.savez_compressed(f, values=values, losses=losses)
