"""GL502 true positive: two locks acquired in both orders (ABBA)."""
import threading


class Mover:
    def __init__(self):
        self._src = threading.Lock()
        self._dst = threading.Lock()
        self.moved = 0

    def push(self):
        with self._src:
            with self._dst:
                self.moved += 1

    def pull(self):
        with self._dst:
            with self._src:  # inverts push's src-then-dst order
                self.moved -= 1
