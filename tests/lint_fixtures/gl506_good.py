"""GL506 near miss: every attribute is assigned before the start."""
import threading


class Pump:
    def __init__(self, sink):
        self._stop = False
        self.sink = sink
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop:
            self.sink.put(1)
