"""GL606 true positive: a refusal reply carries a hand-built numeric
``retry_after`` outside the RETRY_AFTER_CAP/jitter path."""


def _handle_request(service, req):
    op = req.get("op")
    if op == "ping":
        return {"ok": True, "pong": True}
    return {
        "ok": False,
        "error": "server is draining",
        "retry_after": 0.25,
    }


def drive(conn):
    conn.call({"op": "ping"})
