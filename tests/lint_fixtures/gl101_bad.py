"""GL101 true positive: host sync on a traced value inside a jitted scope."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step(x):
    scale = x.item()            # GL101: .item() host-syncs the tracer
    return x * scale


def suggest(key, values):
    def body(v):
        host = np.asarray(v)    # GL101: materializes the tracer on host
        return jnp.sum(v) * float(host.mean())  # GL101: float() on traced
    program = jax.jit(body)
    return program(values)
