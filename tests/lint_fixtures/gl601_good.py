"""GL601 near miss: the same universe, symmetric -- every sent op has
a handler, every handler a caller, and the one global op both fronts
dispatch."""


def _handle_request(service, req):
    op = req.get("op")
    if op == "ping":
        return {"ok": True, "pong": True}
    if op == "stats":
        return {"ok": True, "stats": {}}
    name = req.get("study")
    if op == "ask":
        return {"ok": True, "tid": 1, "vals": {}}
    return {"ok": False, "error": "unknown"}


class RouterServer:
    def handle_request(self, req, conns):
        op = req.get("op")
        if op == "ping":
            return {"ok": True, "pong": True, "router": True}
        if op == "stats":
            return {"ok": True, "stats": {}}
        name = req.get("name") or req.get("study")
        if not name:
            return {"ok": False, "error": "needs a study name"}
        return self.forward(req)


def drive(conn):
    conn.call({"op": "ping"})
    conn.call({"op": "stats"})
    conn.call({"op": "ask", "study": "demo"})
