"""GL201 true positive: a donated buffer read after the dispatch that
consumed it -- XLA may already have aliased its memory."""
import jax


def apply_delta(values, vcol, idx):
    return values.at[:, idx].set(vcol)


step = jax.jit(apply_delta, donate_argnums=(0,))


def tell(values, vcol, idx):
    new_values = step(values, vcol, idx)
    checksum = values.sum()     # GL201: `values` was donated above
    return new_values, checksum
