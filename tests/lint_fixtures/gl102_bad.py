"""GL102 true positive: debug callback left inside a jitted program."""
import jax


@jax.jit
def hot(x):
    jax.debug.print("x = {}", x)    # GL102: host callback in the hot path
    return x * 2.0
