"""GL606 near miss: the same refusal with the hint capped through
RETRY_AFTER_CAP."""

RETRY_AFTER_CAP = 5.0


def _handle_request(service, req):
    op = req.get("op")
    if op == "ping":
        return {"ok": True, "pong": True}
    return {
        "ok": False,
        "error": "server is draining",
        "retry_after": min(0.25, RETRY_AFTER_CAP),
    }


def drive(conn):
    conn.call({"op": "ping"})
