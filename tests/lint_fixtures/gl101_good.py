"""GL101 near-miss: the same host syncs OUTSIDE any jitted scope, plus
literal-only scalar casts inside one (static config, not tracers)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step(x, n=4):
    return x * float(1e-3) + int(2)  # literals: no tracer involved


def fetch(program, key, values):
    out = program(key, values)
    host = np.asarray(out)     # outside the jitted scope: a real fetch
    return float(host.mean()), out.item() if out.ndim == 0 else None


def build(gamma):
    gamma_f = float(gamma)     # builder scope, never traced
    return jax.jit(lambda x: x * gamma_f)
