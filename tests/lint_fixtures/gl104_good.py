"""GL104 near-miss: the wrapper is hoisted out of the loop (or built
once behind a memo guard), so the family compiles once."""
import jax


def square(x):
    return x * x


_MEMO = []


def run(batches):
    f = jax.jit(square)             # hoisted: one family for all batches
    if not _MEMO:
        _MEMO.append(jax.jit(square))   # memo guard, not a loop
    outs = []
    for b in batches:
        outs.append(f(b))
    return outs
