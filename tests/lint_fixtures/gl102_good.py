"""GL102 near-miss: plain prints in host code, jax.debug outside jit."""
import jax


def diagnose(x):
    jax.debug.print("host-side inspection {}", x)   # not a jitted scope
    print("plain host print")
    return x


@jax.jit
def hot(x):
    return x * 2.0
