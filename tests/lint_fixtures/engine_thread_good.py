"""Engine near miss: the same thread-target shapes, but the entry
methods take the lock themselves -- resolution must NOT over-flag."""
import functools
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self._t1 = threading.Thread(target=self._drain)
        self._t2 = threading.Thread(target=functools.partial(self._bump, 2))

    def add(self, k):
        with self._lock:
            self.total += k

    def read(self):
        with self._lock:
            return self.total

    def snapshot(self):
        with self._lock:
            return {"total": self.total}

    def _drain(self):
        with self._lock:
            self.total = 0

    def _bump(self, k):
        with self._lock:
            self.total += k
