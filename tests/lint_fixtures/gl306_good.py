"""GL306 near-misses: the bounded idioms -- a maxlen ring buffer, a
popped work list, a slice-trimmed log -- and an append on a SHORT-lived
(non-service) object, which is a working buffer, not a leak."""
import collections


class RequestBatcher:
    def __init__(self):
        self.latencies = collections.deque(maxlen=1024)  # ring buffer
        self.trace = []
        self.queue = []

    def submit(self, req):
        self.queue.append(req)

    def step(self):
        while self.queue:
            req = self.queue.pop()               # bounded by pop
            self.latencies.append(req.age())     # deque, not a list attr
            self.trace.append(("served", req))
        self.trace[:-256] = []                   # bounded by slice trim
        return True

    def stop(self):
        return len(self.trace)


class ResultCollector:
    """No service-shaped method: a per-call accumulator is fine."""

    def __init__(self):
        self.results = []

    def collect(self, x):
        self.results.append(x)
