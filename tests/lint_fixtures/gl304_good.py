"""GL304 near-miss: explicit Generator streams (the rstate contract)."""
import numpy as np


def jitter(values, rstate=None):
    rng = rstate or np.random.default_rng(0)    # explicit stream: fine
    return values + rng.uniform(0, 1e-6, size=len(values))
