"""GL203 near-miss: the jitted callable is bound once and reused."""
import jax


def square(x):
    return x * x


square_fast = jax.jit(square)


def run(x):
    return square_fast(x)
