"""GL306 true positives: a long-lived service class whose plain-list
attributes grow by append with no bound anywhere in the class -- the
exact per-ask metrics leak the PR-8 review caught on the scheduler."""


class RequestBatcher:
    def __init__(self):
        self.latencies = []
        self.trace = []
        self.queue = []

    def submit(self, req):
        self.queue.append(req)

    def step(self):
        batch = self.queue
        self.queue = []                  # rebound: queue is fine
        for req in batch:
            self.latencies.append(req.age())     # GL306: never trimmed
            self.trace.append(("served", req))   # GL306: never trimmed
        return len(batch)

    def stop(self):
        return sum(self.latencies)
