"""Near miss: a partial-wrapped function that is never handed to a
trace wrapper stays host code -- no jitted scope, no GL101."""
import functools

import numpy as np


def scorer(cfg, x):
    return float(np.asarray(x).mean()) * cfg


bound = functools.partial(scorer, 2.0)
result = bound(np.ones(4))
