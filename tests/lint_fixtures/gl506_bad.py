"""GL506 true positive: the pump thread starts while __init__ is still
assigning -- the loop can observe a half-built object."""
import threading


class Pump:
    def __init__(self, sink):
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        self.sink = sink

    def _loop(self):
        while not self._stop:
            self.sink.put(1)
