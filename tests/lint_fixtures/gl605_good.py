"""GL605 near miss: the same publish with the window armed -- a chaos
suite can kill the process between fsync and rename."""
import json


def publish(fs, path, doc):
    tmp = path + ".tmp"
    with fs.open(tmp, "w") as f:
        f.write(json.dumps(doc))
        fs.fsync(f)
    fs.crashpoint("claim_tmp_before_rename")
    fs.rename(tmp, path)
