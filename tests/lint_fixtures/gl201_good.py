"""GL201 near-miss: the donated name is REBOUND to the program's output
(the resident-mirror swap), so every later read sees the live buffer."""
import jax


def apply_delta(values, vcol, idx):
    return values.at[:, idx].set(vcol)


step = jax.jit(apply_delta, donate_argnums=(0,))


def tell(values, vcol, idx):
    values = step(values, vcol, idx)    # rebind: the swap, not a read
    checksum = values.sum()             # reads the program's output
    return values, checksum
