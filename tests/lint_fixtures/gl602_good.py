"""GL602 near miss: the reply shapes this universe's manifest pins --
the bad twin drifts one field and drops one op against contracts built
from THIS file."""


def _handle_request(service, req):
    op = req.get("op")
    if op == "ping":
        return {"ok": True, "pong": True}
    name = req.get("study")
    if op == "ask":
        return {"ok": True, "tid": 1, "vals": {}}
    if op == "best":
        return {"ok": True, "best": None}
    return {"ok": False, "error": "unknown"}


def drive(conn):
    conn.call({"op": "ping"})
    conn.call({"op": "ask", "study": "demo"})
    conn.call({"op": "best", "study": "demo"})
