"""GL301 true positive: write-tmp-then-rename with no fsync -- a crash
shortly after the rename can publish an empty or truncated file."""
import json
import os


def save(doc, path):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)           # GL301: rename without fsync
    return path
