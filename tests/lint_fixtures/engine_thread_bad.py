"""Engine fixture (thread-entry resolution): bound-method and
functools.partial thread targets must resolve as analyzable ROOT
scopes.  ``_drain`` / ``_bump`` are also called from locked contexts,
so WITHOUT target resolution the entry fixpoint would conclude they
always run under the lock and GL501 would stay silent -- the findings
below exist only because ``Thread(target=self._drain)`` and
``Thread(target=functools.partial(self._bump, 2))`` force them to be
lock-free roots."""
import functools
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self._t1 = threading.Thread(target=self._drain)
        self._t2 = threading.Thread(target=functools.partial(self._bump, 2))

    def add(self, k):
        with self._lock:
            self.total += k

    def read(self):
        with self._lock:
            return self.total

    def snapshot(self):
        with self._lock:
            return {"total": self.total}

    def reset(self):
        with self._lock:
            self._drain()

    def kick(self):
        with self._lock:
            self._bump(1)

    def _drain(self):
        self.total = 0

    def _bump(self, k):
        self.total += k
