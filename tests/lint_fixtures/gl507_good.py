"""GL507 near miss: the flusher is a JOINED worker (not a daemon), so
shutdown waits for the in-flight durable write to finish."""
import threading


class Snapshotter:
    def __init__(self, persist):
        self.persist = persist
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=False)
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._thread.join()

    def _loop(self):
        while not self._stop.is_set():
            self._flush()

    def _flush(self):
        self.persist.log_tell(0, {}, 0.0)
