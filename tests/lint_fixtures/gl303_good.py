"""GL303 near-misses: the retry routed through with_retries, and an
idle-poll sleep in a loop with no error handling around it."""
import time


def with_retries(fn, attempts=5):
    return fn()


def fetch(op):
    return with_retries(op, attempts=5)     # the sanctioned scaffold


def poll(ready, interval=0.05):
    while not ready():
        time.sleep(interval)        # idle poll, not an error path
    return True
