"""GL103 true positive: a jitted closure defined in a loop captures the
loop variable -- every iteration bakes a new constant and retraces."""
import jax


def make_steps(learning_rates):
    steps = []
    for lr in learning_rates:
        @jax.jit
        def step(p, g):
            return p - lr * g       # GL103: captures loop-carried `lr`
        steps.append(step)
    return steps
