"""GL502 near miss: both paths honor one global order (src, then dst)."""
import threading


class Mover:
    def __init__(self):
        self._src = threading.Lock()
        self._dst = threading.Lock()
        self.moved = 0

    def push(self):
        with self._src:
            with self._dst:
                self.moved += 1

    def pull(self):
        with self._src:
            with self._dst:
                self.moved -= 1
