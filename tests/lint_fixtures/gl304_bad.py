"""GL304 true positive: numpy's process-global RNG in product code --
unseeded draws break the reproducibility contract."""
import numpy as np


def jitter(values):
    np.random.seed(0)                       # GL304: global-state seed
    return values + np.random.uniform(0, 1e-6, size=len(values))  # GL304
