"""GL507 true positive: a daemon thread reaches the durable WAL writer
through a same-class helper -- interpreter exit tears the log."""
import threading


class Snapshotter:
    def __init__(self, persist):
        self.persist = persist
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            self._flush()

    def _flush(self):
        self.persist.log_tell(0, {}, 0.0)
