"""GL104 true positive: trace wrappers constructed inside a loop -- a
fresh program family (and compile) per iteration."""
import jax


def square(x):
    return x * x


def run(batches):
    outs = []
    for b in batches:
        f = jax.jit(square)         # GL104: new program family each pass
        outs.append(f(b))
    return outs
