"""GL202 true positive: a product path serializing on device completion."""
import jax


def suggest(program, key, values):
    out = program(key, values)
    jax.block_until_ready(out)      # GL202: sync in a product path
    return out.block_until_ready()  # GL202: method form
