"""GL203 true positive: jit-wrap-then-call in one expression -- a fresh
callable (and cache entry lookup by a new id) per invocation."""
import jax


def square(x):
    return x * x


def run(x):
    return jax.jit(square)(x)       # GL203: per-call wrapping
