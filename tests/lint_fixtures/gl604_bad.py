"""GL604 true positives: a crash-point registry no test ever arms or
iterates (the fixture test passes NO test evidence alongside this
file) -- two dead fault windows."""

SERVE_CRASH_POINTS = (
    "serve_before_snapshot",
    "serve_after_snapshot",
)
