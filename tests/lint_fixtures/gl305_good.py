"""GL305 near-misses: the legal idioms closest to the bad fixture --
the durable tmp+fsync+rename shape, an in-memory BytesIO dump (nothing
on disk to make durable), and bytes-level serialization without a file
target at all."""

import io
import os
import pickle

import numpy as np


def save_trials_durably(trials, path):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        pickle.dump(trials, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def snapshot_arrays_to_bytes(values, losses):
    bio = io.BytesIO()
    np.savez_compressed(bio, values=values, losses=losses)
    return bio.getvalue()


def serialize_doc(doc):
    return pickle.dumps(doc)
