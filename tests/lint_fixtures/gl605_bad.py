"""GL605 true positive: the tmp + fsync + rename durable publish with
no crash point bracketing the torn-state window."""
import json


def publish(fs, path, doc):
    tmp = path + ".tmp"
    with fs.open(tmp, "w") as f:
        f.write(json.dumps(doc))
        fs.fsync(f)
    fs.rename(tmp, path)
