"""GL601 true positives: a client op nothing handles, a handler
nothing calls, and a global op only the service front dispatches (the
router would refuse it untyped).  One file plays server AND client."""


def _handle_request(service, req):
    op = req.get("op")
    if op == "ping":
        return {"ok": True, "pong": True}
    if op == "stats":
        return {"ok": True, "stats": {}}
    name = req.get("study")
    if op == "ask":
        return {"ok": True, "tid": 1, "vals": {}}
    return {"ok": False, "error": "unknown"}


class RouterServer:
    def handle_request(self, req, conns):
        op = req.get("op")
        if op == "ping":
            return {"ok": True, "pong": True, "router": True}
        name = req.get("name") or req.get("study")
        if not name:
            return {"ok": False, "error": "needs a study name"}
        return self.forward(req)


def drive(conn):
    conn.call({"op": "ping"})
    conn.call({"op": "ask", "study": "demo"})
    conn.call({"op": "frobnicate"})
