"""GL505 true positive: futures resolved while the scheduler lock is
held -- a done-callback that re-enters the class deadlocks."""
import threading


class Acker:
    def __init__(self):
        self._lock = threading.Lock()
        self.pending = []

    def submit(self, fut):
        with self._lock:
            self.pending.append(fut)

    def fail_all(self, exc):
        with self._lock:
            while self.pending:
                fut = self.pending.pop()
                fut.set_exception(exc)

    def ack(self, fut, value):
        with self._lock:
            self.pending.remove(fut)
            fut.set_result(value)
