"""GL504 true positive: if-then-wait loses the signal on a spurious
wakeup or a stolen predicate."""
import threading


class Mailbox:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._items = []

    def put(self, item):
        with self._cond:
            self._items.append(item)
            self._cond.notify()

    def get(self):
        with self._cond:
            if not self._items:
                self._cond.wait(timeout=1.0)
            return self._items.pop()
