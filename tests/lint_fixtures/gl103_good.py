"""GL103 near-miss: the loop value rides in as an ARGUMENT (one trace
serves every iteration), and a non-jitted closure may capture freely."""
import jax
import functools


@jax.jit
def step(p, g, lr):
    return p - lr * g


def make_steps(learning_rates):
    steps = []
    for lr in learning_rates:
        steps.append(functools.partial(step, lr=lr))  # partial, not a trace
        def host_log(msg):
            return f"{msg} @ {lr}"  # plain closure: no program involved
    return steps
