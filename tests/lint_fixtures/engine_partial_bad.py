"""Engine regression: scoped rules see through functools.partial.

A partial binds arguments -- the wrapped function's body is still what
traces, so GL1xx/GL2xx scope resolution must treat ``jit(partial(f,
...))`` (inline or via a one-level alias) exactly like ``jit(f)``.
"""
import functools

import jax
import numpy as np


def scorer(cfg, x):
    return float(np.asarray(x).mean()) * cfg  # GL101 x2 once jitted


def kernel(v):
    return v.item()  # GL101 once jitted


fast_scorer = jax.jit(functools.partial(scorer, 2.0))

bound = functools.partial(kernel)
fast_kernel = jax.jit(bound)
