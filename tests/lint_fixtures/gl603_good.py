"""GL603 near miss: every ServeError subclass is mapped by name at the
client reply seam."""


class ServeError(RuntimeError):
    pass


class Overloaded(ServeError):
    pass


class StudyPoisoned(ServeError):
    pass


_REPLY_ERRORS = {
    "Overloaded": Overloaded,
    "StudyPoisoned": StudyPoisoned,
}
