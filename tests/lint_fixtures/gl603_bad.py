"""GL603 true positive: a ServeError subclass the client reply seam
never maps -- its wire error_type would surface as a generic
RuntimeError instead of the typed class."""


class ServeError(RuntimeError):
    pass


class Overloaded(ServeError):
    pass


class StudyPoisoned(ServeError):
    pass


_REPLY_ERRORS = {
    "Overloaded": Overloaded,
}
