"""GL604 near miss: the registry rides with a test that arms its
point by name (this file plays both the faults and tests roles)."""

SERVE_CRASH_POINTS = (
    "serve_before_snapshot",
)


def test_crash_before_snapshot(plan):
    plan.arm("serve_before_snapshot", at=1)
