"""Chunked checkpointed device loops + stateful train-inside-the-scan.

The round-14 acceptance contracts:

* the chunked scan (``compile_fmin(chunk_size=)``) produces a result
  stream BITWISE identical to the flat scan -- including a padded tail
  chunk -- because the per-step key folds the global step index;
* the ``io_callback`` progress cadence changes NOTHING but
  observability: callback-on vs callback-off result streams are
  bitwise equal, and the rows themselves are consistent with the run;
* kill-and-resume at EVERY chunk boundary (both device-loop crash
  points, riding the PR-3/PR-6 fault-injection seam) is bitwise equal
  to the uninterrupted run, with foreign-experiment / foreign-seed
  bundles refused;
* ``TrainableObjective`` (per-trial params/opt-state trained by an
  inner ``fori_loop`` INSIDE the scan step) runs end to end,
  deterministically, and composes with chunking + resume;
* ``fmin(fn, compiled=True)`` routes through the device loop and
  returns the standard Trials/argmin contract.
"""

import os

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.device_loop import TrainableObjective, compile_fmin
from hyperopt_tpu.distributed.faults import (
    DEVICE_LOOP_CRASH_POINTS,
    FaultPlan,
    SimulatedCrash,
)
from hyperopt_tpu.exceptions import CheckpointError

SPACE = {"x": hp.uniform("x", -5.0, 5.0), "u": hp.choice("u", [0, 1, 2])}

N_EVALS = 24
BATCH = 2  # 12 steps; chunk_size=8 -> 4-step chunks, 3 chunks
KW = dict(
    max_evals=N_EVALS, batch_size=BATCH, n_startup_jobs=4,
    n_EI_candidates=8, n_EI_candidates_cat=4,
)
SEED = 5


def _objective(cfg):
    return (cfg["x"] - 1.0) ** 2 + 0.1 * cfg["u"]


_RESULTS = {}


def _flat_result():
    if "flat" not in _RESULTS:
        _RESULTS["flat"] = compile_fmin(_objective, SPACE, **KW)(seed=SEED)
    return _RESULTS["flat"]


def _assert_stream_equal(a, b):
    """The FULL result stream, bitwise: every loss, every drawn value,
    every activity bit, and the derived best."""
    for f in ("losses", "values", "active"):
        np.testing.assert_array_equal(a[f], b[f], err_msg=f)
    assert a["best_loss"] == b["best_loss"]
    assert a["best_index"] == b["best_index"]
    assert a["best"] == b["best"]


def test_chunked_bitwise_parity_with_flat():
    out = compile_fmin(_objective, SPACE, chunk_size=8, **KW)(seed=SEED)
    assert out["n_evals"] == N_EVALS
    _assert_stream_equal(_flat_result(), out)


def test_padded_tail_chunk_bitwise_parity():
    # chunk_size=5 -> 3-step chunks, 4 chunks covering 12 steps: the
    # tail chunk runs masked no-op steps past n_steps
    out = compile_fmin(_objective, SPACE, chunk_size=5, **KW)(seed=SEED)
    assert out["n_evals"] == N_EVALS
    _assert_stream_equal(_flat_result(), out)


def test_callback_cadence_on_off_bitwise_parity_and_rows():
    rows = []
    runner = compile_fmin(
        _objective, SPACE, chunk_size=8,
        progress_callback=rows.append, progress_every=2, **KW,
    )
    out = runner(seed=SEED)
    # ON vs OFF: bitwise the same stream (the flat run IS the
    # callback-off stream, proven equal to chunked-off above)
    _assert_stream_equal(_flat_result(), out)
    # cadence: every 2nd chunk plus the final one -> chunks 1 and 2
    assert [r["chunk"] for r in rows] == [1, 2]
    assert [r["trials_done"] for r in rows] == [16, 24]
    # best-so-far is monotone and lands on the run's best
    bests = [r["best_loss"] for r in rows]
    assert bests == sorted(bests, reverse=True)
    assert bests[-1] == out["best_loss"]
    # a second run re-fires the cadence (no one-shot callback state)
    rows.clear()
    runner(seed=SEED)
    assert [r["chunk"] for r in rows] == [1, 2]


def test_kill_and_resume_every_chunk_boundary_bitwise(tmp_path):
    """THE resume acceptance: arm each device-loop crash point at each
    chunk boundary, kill, resume -- the completed stream must be
    bitwise the uninterrupted run's, for every (point, boundary)."""
    path = str(tmp_path / "chunk.ckpt")
    plan = FaultPlan(seed=0)
    runner = compile_fmin(
        _objective, SPACE, chunk_size=8, checkpoint_path=path,
        checkpoint_every=1, fs=plan.fs(), **KW,
    )
    ref = runner(seed=SEED)
    _assert_stream_equal(_flat_result(), ref)  # durability changes nothing
    n_chunks = runner._chunk_geometry["n_chunks"]
    assert n_chunks == 3
    for point in DEVICE_LOOP_CRASH_POINTS:
        for at in range(1, n_chunks + 1):
            if os.path.exists(path):
                os.remove(path)
            plan.arm(point, at=at)
            with pytest.raises(SimulatedCrash):
                runner(seed=SEED)
            out = runner(seed=SEED, resume=True)
            _assert_stream_equal(ref, out)
    # resume of a COMPLETED run packages straight from the bundle
    # (no dispatch, same stream)
    out = runner(seed=SEED, resume=True)
    _assert_stream_equal(ref, out)


def test_resume_refuses_foreign_seed_and_foreign_experiment(tmp_path):
    path = str(tmp_path / "chunk.ckpt")
    runner = compile_fmin(
        _objective, SPACE, chunk_size=8, checkpoint_path=path,
        checkpoint_every=1, **KW,
    )
    runner(seed=SEED)
    with pytest.raises(CheckpointError, match="seed"):
        runner(seed=SEED + 1, resume=True)
    # a different experiment geometry writes a different guard
    foreign = compile_fmin(
        _objective, SPACE, max_evals=2 * N_EVALS, batch_size=BATCH,
        n_startup_jobs=4, n_EI_candidates=8, n_EI_candidates_cat=4,
        chunk_size=8, checkpoint_path=path, checkpoint_every=1,
    )
    with pytest.raises(CheckpointError, match="refusing to resume"):
        foreign(seed=SEED, resume=True)


def test_chunk_option_validation():
    with pytest.raises(ValueError, match="chunk_size"):
        compile_fmin(
            _objective, SPACE, progress_callback=print, **KW
        )
    with pytest.raises(ValueError, match="loss_threshold"):
        compile_fmin(
            _objective, SPACE, chunk_size=8, loss_threshold=0.1, **KW
        )
    with pytest.raises(ValueError, match="checkpoint_path"):
        compile_fmin(_objective, SPACE, chunk_size=8, resume=True, **KW)
    runner = compile_fmin(_objective, SPACE, chunk_size=8, **KW)
    with pytest.raises(ValueError, match="seed sweep"):
        runner(seed=[0, 1])
    with pytest.raises(ValueError, match="checkpoint_path"):
        runner(seed=0, resume=True)
    flat = compile_fmin(_objective, SPACE, **KW)
    with pytest.raises(ValueError, match="chunked"):
        flat(seed=0, resume=True)


# ---------------------------------------------------------------------------
# TrainableObjective: stateful training inside the scan
# ---------------------------------------------------------------------------


def _tiny_mlp():
    from hyperopt_tpu.models.synthetic import (
        mlp_tune_objective,
        mlp_tune_space,
    )

    return (
        mlp_tune_objective(n_epochs=4, n_train=64, in_dim=4, hidden=8),
        mlp_tune_space(),
    )


def test_trainable_objective_trains_deterministically():
    obj, space = _tiny_mlp()
    assert isinstance(obj, TrainableObjective)
    runner = compile_fmin(
        obj, space, max_evals=8, batch_size=4, n_startup_jobs=4,
        n_EI_candidates=4,
    )
    a = runner(seed=0)
    assert np.isfinite(a["losses"]).all()
    # a REAL training loop: different hyperparameters train to
    # different losses (a constant stream would mean the state never
    # actually trained)
    assert np.unique(a["losses"]).size > 1
    b = runner(seed=0)
    _assert_stream_equal(a, b)  # seed-deterministic
    c = runner(seed=1)
    assert not np.array_equal(a["losses"], c["losses"])


def test_trainable_objective_chunked_kill_resume_bitwise(tmp_path):
    """The tentpole combination: per-trial training INSIDE the scan,
    chunk boundaries streaming progress, a kill mid-experiment, and a
    bitwise-identical resume."""
    obj, space = _tiny_mlp()
    path = str(tmp_path / "mlp.ckpt")
    plan = FaultPlan(seed=0)
    rows = []
    runner = compile_fmin(
        obj, space, max_evals=16, batch_size=4, n_startup_jobs=4,
        n_EI_candidates=4, chunk_size=8, checkpoint_path=path,
        checkpoint_every=1, progress_callback=rows.append, fs=plan.fs(),
    )
    ref = runner(seed=3)
    rows.clear()
    plan.arm("device_loop_after_ckpt_before_next_chunk", at=1)
    with pytest.raises(SimulatedCrash):
        runner(seed=3)
    out = runner(seed=3, resume=True)
    _assert_stream_equal(ref, out)
    assert rows and rows[-1]["trials_done"] == 16


def test_trainable_objective_validation():
    with pytest.raises(ValueError, match="n_epochs"):
        TrainableObjective(lambda k, c: 0, lambda s, c, e: s,
                           lambda s, c: 0.0, n_epochs=0)


# ---------------------------------------------------------------------------
# fmin(compiled=True): the routed front
# ---------------------------------------------------------------------------


def test_fmin_compiled_returns_standard_trials_and_argmin():
    trials = Trials()
    best = fmin(
        _objective, SPACE, compiled=True, max_evals=16,
        rstate=np.random.default_rng(3), trials=trials,
        compiled_options=dict(
            batch_size=2, n_startup_jobs=4, n_EI_candidates=8,
        ),
    )
    assert len(trials) == 16
    assert set(best) <= {"x", "u"} and "x" in best
    losses = trials.losses()
    assert len(losses) == 16 and all(np.isfinite(losses))
    # argmin really is the best trial's config
    assert trials.argmin == best
    # return_argmin=False follows the fmin contract (best loss), and a
    # same-rstate rerun is deterministic
    loss = fmin(
        _objective, SPACE, compiled=True, max_evals=16,
        rstate=np.random.default_rng(3), return_argmin=False,
        compiled_options=dict(
            batch_size=2, n_startup_jobs=4, n_EI_candidates=8,
        ),
    )
    assert loss == min(losses)


def test_fmin_compiled_algo_mapping():
    import functools

    from hyperopt_tpu import anneal_jax, tpe, tpe_jax
    from hyperopt_tpu.fmin import _compiled_algo_name

    assert _compiled_algo_name(None) == "tpe"
    assert _compiled_algo_name("anneal") == "anneal"
    assert _compiled_algo_name(tpe.suggest) == "tpe"
    assert _compiled_algo_name(tpe_jax.suggest) == "tpe"
    assert _compiled_algo_name(
        functools.partial(anneal_jax.suggest, batch=4)
    ) == "anneal"
    with pytest.raises(ValueError, match="compiled"):
        _compiled_algo_name(lambda *a: None)
    with pytest.raises(ValueError, match="unknown compiled algo"):
        _compiled_algo_name("grid")


def test_fmin_compiled_rejects_host_driver_features():
    with pytest.raises(ValueError, match="trials_save_file"):
        fmin(_objective, SPACE, compiled=True, max_evals=8,
             trials_save_file="/tmp/x.ckpt")
    with pytest.raises(ValueError, match="trial_timeout"):
        fmin(_objective, SPACE, compiled=True, max_evals=8,
             trial_timeout=1.0)
    filled = Trials()
    fmin(
        _objective, SPACE, compiled=True, max_evals=4, trials=filled,
        rstate=np.random.default_rng(0),
        compiled_options=dict(
            batch_size=2, n_startup_jobs=2, n_EI_candidates=4,
        ),
    )
    with pytest.raises(ValueError, match="fresh experiment"):
        fmin(_objective, SPACE, compiled=True, max_evals=8,
             trials=filled)
