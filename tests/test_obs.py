"""graftscope (ISSUE 14): the unified observability subsystem.

The acceptance contract:

* BITWISE INVISIBILITY: the PR-8 multi-study parity scenario and the
  PR-13 fleet kill-mid-batch chaos scenario, run with a flight
  recorder armed at FULL cadence (and the device-metrics twin at
  cadence 1), produce suggestion streams identical to the untracked
  runs -- observability observes, it never perturbs;
* ZERO COST WHEN OFF: with device metrics disabled (the default), the
  dispatch count is exactly the untracked run's -- no extra programs;
* BOUNDED BY CONSTRUCTION: registries cap label cardinality at
  registration, histograms are fixed buckets + a maxlen ring, the
  flight recorder is a maxlen ring;
* RECOVERABLE EXPORT: a crash mid-span-export (the
  ``obs_flight_export_mid_append`` point) leaves a torn tail that
  ``hyperopt-tpu-fsck --obs`` truncates, with every span before the
  tear intact;
* BACK-COMPAT: every pre-graftscope attribute read path -- counters
  dicts, ``ask_latencies`` slicing, ObsBuffer traffic counters,
  ``fleet.recovery_ms`` -- reads exactly what it always did.
"""

import json
import pickle
import socket
import threading
import time

import numpy as np
import pytest

from hyperopt_tpu import hp
from hyperopt_tpu.distributed.faults import (
    OBS_CRASH_POINTS,
    ALL_CRASH_POINTS,
    FaultPlan,
    SimulatedCrash,
)
from hyperopt_tpu.obs import (
    NULL_RECORDER,
    FlightRecorder,
    MetricsRegistry,
    audit_flight_log,
    read_flight_log,
    render_prometheus,
)
from hyperopt_tpu.obs.registry import CounterAttr, HistogramAttr
from hyperopt_tpu.serve import SuggestService


@pytest.fixture(autouse=True)
def _lockdep_armed(monkeypatch):
    # every scheduler this suite builds runs under the graftrace
    # lockdep sanitizer -- tracing must not introduce an inversion
    from hyperopt_tpu.analysis import lockdep

    dep = lockdep.arm_scheduler_class(monkeypatch)
    yield dep
    assert dep.inversions == 0, dep.errors


# ---------------------------------------------------------------------------
# registry units
# ---------------------------------------------------------------------------


def test_registry_types_and_snapshot():
    r = MetricsRegistry("t", const_labels={"replica": "r9"})
    c = r.counter("ops_total", "ops")
    c.inc()
    c.inc(4)
    assert c.value == 5
    g = r.gauge("depth")
    assert g.value is None  # unambiguous "never set"
    g.set(3)
    g.inc()
    assert g.value == 4
    h = r.histogram("lat_seconds", buckets=(0.01, 0.1))
    h.observe(0.005)
    h.observe_since(time.perf_counter() - 0.05)
    rows = {row["name"]: row for row in r.collect()}
    assert rows["ops_total"]["value"] == 5
    assert rows["ops_total"]["labels"] == {"replica": "r9"}
    assert rows["lat_seconds"]["count"] == 2
    assert rows["lat_seconds"]["buckets"][0]["count"] == 1
    # get-or-create is type-checked, never a silent shadow
    with pytest.raises(TypeError):
        r.gauge("ops_total")


def test_registry_label_cardinality_capped():
    r = MetricsRegistry(label_cap=8)
    fam = r.gauge("up", labels=("backend",))
    for i in range(50):
        fam.labels(backend=f"b{i}").set(1)
    # 8 real children + the shared overflow series: bounded forever
    assert len(fam._children) <= 9
    names = {row["labels"]["backend"] for row in r.collect()}
    assert "_overflow" in names


def test_histogram_ring_bounded_and_back_compat_append():
    r = MetricsRegistry()
    h = r.histogram("w", buckets=(1.0,), window=16)
    for i in range(100):
        h.ring.append(0.5)  # the pre-graftscope deque write path
    assert len(h.ring) == 16  # ring bounded
    assert h.count == 100  # buckets saw every append
    assert sorted(h.ring)[0] == 0.5  # deque reads still work


def test_registry_pickles_and_heals_old_objects():
    r = MetricsRegistry("p")
    r.counter("a_total").inc(3)
    r.histogram("h").observe(1.0)
    r2 = pickle.loads(pickle.dumps(r))
    assert r2.counter("a_total").value == 3
    assert r2.histogram("h").count == 1
    r2.counter("a_total").inc()  # fresh lock works

    class Thing:
        n = CounterAttr("n_total")
        lats = HistogramAttr("lats")

    t = Thing()
    t.n += 2
    t.lats.append(0.5)
    assert t.n == 2
    # an object unpickled from a pre-graftscope artifact has no
    # .metrics attr: the descriptor heals it lazily
    t2 = Thing()
    assert t2.n == 0


def test_prometheus_rendering_shape():
    r = MetricsRegistry()
    r.counter("x_total", "things").inc(2)
    r.histogram("d_seconds", buckets=(0.1,)).observe(0.05)
    text = render_prometheus(r.collect())
    assert "# TYPE x_total counter" in text
    assert "x_total 2" in text
    assert 'd_seconds_bucket{le="0.1"} 1' in text
    assert 'd_seconds_bucket{le="+Inf"} 1' in text
    assert "d_seconds_count 1" in text


# ---------------------------------------------------------------------------
# flight recorder units + torn-export recovery
# ---------------------------------------------------------------------------


def test_recorder_ring_cadence_and_null():
    rec = FlightRecorder(capacity=4)
    for i in range(10):
        rec.record("e", tid=i)
    assert rec.recorded_total == 10
    assert [s["tid"] for s in rec.tail()] == [6, 7, 8, 9]  # bounded
    assert [s["tid"] for s in rec.tail(2)] == [8, 9]
    sampled = FlightRecorder(cadence=3)
    for i in range(9):
        sampled.record("e", tid=i)
    assert [s["tid"] for s in sampled.tail()] == [0, 3, 6]
    assert not NULL_RECORDER.enabled
    assert NULL_RECORDER.record("x") is None and NULL_RECORDER.tail() == []


def test_flight_export_roundtrip(tmp_path):
    path = str(tmp_path / "flight.wal")
    rec = FlightRecorder(path=path)
    t0 = time.perf_counter()
    rec.record("ask.delivered", t0, t0 + 0.001, study="s0", tid=1)
    rec.record("tell", study="s0", tid=1)
    rec.flush()
    rec.close()
    spans = read_flight_log(path)
    assert [s["name"] for s in spans] == ["ask.delivered", "tell"]
    assert spans[0]["study"] == "s0" and spans[0]["dur_ms"] > 0
    assert audit_flight_log(path) == []  # clean log, clean audit


def test_flight_export_torn_tail_recovered_via_fsck(tmp_path):
    """THE flight-recorder crash pin: die mid-export, prove the torn
    tail recoverable via fsck --obs with every prior span intact, and
    a restarted recorder appending onto the repaired prefix."""
    from hyperopt_tpu.distributed import fsck

    assert OBS_CRASH_POINTS[0] in ALL_CRASH_POINTS
    path = str(tmp_path / "flight.wal")
    plan = FaultPlan(seed=3)
    plan.arm("obs_flight_export_mid_append", at=4)
    rec = FlightRecorder(path=path, fs=plan.fs())
    with pytest.raises(SimulatedCrash):
        for i in range(10):
            rec.record("span", tid=i)
    issues = fsck.audit_obs(path)
    assert [i.kind for i in issues] == ["obs_torn_tail"]
    # the CLI contract: audit reports (rc 1), --repair heals (rc 0)
    assert fsck.main(["--obs", path]) == 1
    assert fsck.main(["--obs", path, "--repair"]) == 0
    assert fsck.audit_obs(path) == []
    spans = read_flight_log(path)
    assert [s["tid"] for s in spans] == [0, 1, 2]  # pre-crash intact
    # a restarted recorder appends cleanly onto the valid prefix
    rec2 = FlightRecorder(path=path)
    rec2.record("span", tid=99)
    rec2.close()
    assert [s["tid"] for s in read_flight_log(path)] == [0, 1, 2, 99]


def test_flight_reopen_self_heals_torn_tail(tmp_path):
    """A restarted recorder that reopens a torn log truncates the tail
    itself (the fsck-less crash-restart path)."""
    path = str(tmp_path / "flight.wal")
    plan = FaultPlan(seed=5)
    plan.arm("obs_flight_export_mid_append", at=2)
    rec = FlightRecorder(path=path, fs=plan.fs())
    with pytest.raises(SimulatedCrash):
        for i in range(5):
            rec.record("span", tid=i)
    rec2 = FlightRecorder(path=path)
    rec2.record("span", tid=7)
    rec2.close()
    assert audit_flight_log(path) == []
    assert [s["tid"] for s in read_flight_log(path)] == [0, 7]


# ---------------------------------------------------------------------------
# back-compat: the migrated counters read exactly as before
# ---------------------------------------------------------------------------

SPACE = {
    "x": hp.uniform("x", -5, 5),
    "lr": hp.loguniform("lr", -5, 0),
    "c": hp.choice("c", [0, 1]),
}
ALGO_KW = dict(n_cand=8, n_cand_cat=4)


def _loss(vals):
    return (vals["x"]) ** 2 / 10 + abs(float(np.log(vals["lr"])) + 2) / 3


def _drive(svc, handles, rounds, streams=None):
    for _ in range(rounds):
        futs = [(h, h.ask_async()) for h in handles]
        svc.pump()
        for h, f in futs:
            tid, vals = f.result(timeout=30)
            if streams is not None:
                streams.setdefault(h.name, []).append(vals)
            h.tell(tid, _loss(vals))


def test_scheduler_counters_back_compat_and_exposition():
    svc = SuggestService(
        SPACE, max_batch=4, background=False, n_startup_jobs=2, **ALGO_KW
    )
    handles = [svc.create_study(f"s{i}", seed=i) for i in range(3)]
    _drive(svc, handles, 3)
    s = svc.scheduler
    # the historic read paths: plain ints, sliceable deques, the
    # counters dict -- all now registry-backed
    assert s.dispatch_count == 3
    assert isinstance(s.dispatch_count, int)
    assert svc.counters["dispatch_count"] == 3
    assert len(list(s.ask_latencies)) == 9
    assert sorted(s.ask_latencies)[0] >= 0
    assert list(s.occupancy) == [0.75] * 3
    # and the same numbers come out of the registry, typed
    rows = {r["name"]: r for r in svc.metrics_rows()}
    assert rows["serve_dispatch_total"]["value"] == 3
    assert rows["serve_ask_latency_seconds"]["count"] == 9
    assert rows["serve_studies"]["value"] == 3
    text = svc.metrics_text()
    assert "serve_dispatch_total 3" in text
    svc.shutdown()


def test_obs_buffer_counters_back_compat_and_pickle():
    from hyperopt_tpu.jax_trials import ObsBuffer
    from hyperopt_tpu.ops.compile import compile_space

    ps = compile_space(SPACE)
    buf = ObsBuffer(ps, resident=True)
    for i in range(4):
        buf.add({"x": 0.5, "lr": 0.1, "c": 0}, 0.1 * i)
    buf.device_arrays()
    assert buf.full_uploads == 1
    assert buf.transfer_bytes_total > 0
    before = (buf.transfer_bytes_total, buf.delta_tells, buf.full_uploads)
    buf2 = pickle.loads(pickle.dumps(buf))
    assert (
        buf2.transfer_bytes_total, buf2.delta_tells, buf2.full_uploads
    ) == before
    rows = {r["name"]: r for r in buf.metrics.collect()}
    assert rows["obs_full_uploads_total"]["value"] == 1


# ---------------------------------------------------------------------------
# THE invisibility pins
# ---------------------------------------------------------------------------


def test_invisibility_64_study_parity_with_tracing_armed():
    """The PR-8 64-study bitwise-parity scenario with a flight
    recorder at FULL cadence and the device-metrics twin at cadence 1:
    every stream identical to the untracked run AND to its solo
    fused-path reference; the untracked run dispatches exactly zero
    extra programs."""
    import test_serve

    def run(recorder=None, device_metrics_every=0):
        svc = SuggestService(
            test_serve.SPACE, max_batch=64, background=False,
            n_startup_jobs=test_serve.N_STARTUP, recorder=recorder,
            device_metrics_every=device_metrics_every,
            **test_serve.ALGO_KW,
        )
        handles = [
            svc.create_study(f"s{i:02d}", seed=100 + i) for i in range(64)
        ]
        streams = {}
        test_serve.drive_rounds(svc, handles, streams, 3)
        counts = (
            svc.scheduler.dispatch_count,
            svc.scheduler.device_metric_dispatches,
        )
        ps = svc.ps
        svc.shutdown()
        return streams, counts, ps

    plain_streams, plain_counts, ps = run()
    rec = FlightRecorder(capacity=65536)
    traced_streams, traced_counts, _ = run(
        recorder=rec, device_metrics_every=1
    )
    # bitwise invisibility: tracing changed NOTHING in any stream
    assert traced_streams == plain_streams
    # and both match the solo fused-path references
    for i in range(0, 64, 16):
        assert plain_streams[f"s{i:02d}"] == test_serve.solo_stream(
            ps, 100 + i, 3
        )
    # the armed run really traced at full cadence...
    names = {s["name"] for s in rec.tail()}
    assert {
        "ask.submit", "ask.queued", "serve.dispatch", "ask.delivered",
        "tell.wal_append", "tell.applied", "tell",
    } <= names
    assert rec.recorded_total > 64 * 3 * 4
    # ...dispatched its twin every round, while the untracked run
    # dispatched exactly zero extra programs (the off-cost pin)
    assert traced_counts == (plain_counts[0], plain_counts[0])
    assert plain_counts[1] == 0


@pytest.mark.chaos
def test_invisibility_fleet_kill_mid_batch_with_tracing_armed(tmp_path):
    """The PR-13 fleet failover chaos shape -- replica killed
    mid-batch under a 10% transient storm -- with a fleet-shared
    flight recorder at full cadence: zero lost / zero duplicate tells,
    and every stream (including the killed replica's) bitwise the
    untracked same-seed run's."""
    import test_fleet_chaos as tfc
    from hyperopt_tpu.serve import Fleet

    names = tfc.NAMES[:6]
    rounds = 3

    def run(root, recorder=None):
        plans = {
            rid: FaultPlan(seed=700 + i, rate=0.10)
            for i, rid in enumerate(tfc.REPLICAS)
        }
        plans[tfc.victim_rid()].arm("serve_mid_batch", at=2)
        kw = dict(tfc.KW)
        if recorder is not None:
            kw["recorder"] = recorder
            kw["device_metrics_every"] = 1
        fleet = Fleet(
            tfc.SPACE, str(root), replica_ids=list(tfc.REPLICAS),
            plans=plans, fs=FaultPlan(seed=7).fs(), **kw,
        )
        client = tfc.Client(fleet)
        for i, n in enumerate(names):
            client.create(n, seed=100 + i)
        streams = {n: [] for n in names}
        tfc.drive(client, streams, rounds, names=names)
        assert fleet.replicas[tfc.victim_rid()].dead
        assert fleet.recovery_ms is not None and fleet.recovery_ms > 0
        state = {
            n: tfc.final_state(fleet, [n])[n] for n in names
        }
        fleet.shutdown()
        return streams, state

    plain_streams, plain_state = run(tmp_path / "plain")
    rec = FlightRecorder(capacity=65536)
    traced_streams, traced_state = run(tmp_path / "traced", recorder=rec)

    # bitwise invisibility under failover chaos
    assert traced_streams == plain_streams
    for n in names:
        assert traced_state[n]["tids"] == plain_state[n]["tids"]
        np.testing.assert_array_equal(
            traced_state[n]["values"], plain_state[n]["values"]
        )
        # zero lost / zero duplicate (live counters)
        assert traced_state[n]["count"] == rounds
        assert len(set(traced_state[n]["tids"])) == rounds
        assert traced_state[n]["wal_total_tells"] == rounds
    # spans carry the fleet correlation ids end to end
    delivered = [
        s for s in rec.tail() if s["name"] == "ask.delivered"
    ]
    assert delivered and all("replica" in s for s in delivered)
    assert {s["replica"] for s in delivered} <= set(tfc.REPLICAS)


# ---------------------------------------------------------------------------
# fleet-wide scrape: router aggregation, probes, the scope CLI
# ---------------------------------------------------------------------------


def _start_replica(owner, root=None):
    from hyperopt_tpu.serve.service import serve_forever

    svc = SuggestService(
        SPACE, background=True, max_wait_ms=1.0, n_startup_jobs=2,
        owner=owner, root=root, recorder=FlightRecorder(), **ALGO_KW,
    )
    server = serve_forever(svc, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return svc, server, server.server_address[1]


def test_fleet_wide_scrape_probes_and_scope_cli(tmp_path, capsys):
    from hyperopt_tpu.obs import cli as scope_cli
    from hyperopt_tpu.serve.router import RouterServer, _Backend

    root = str(tmp_path / "root")
    svcs, servers, ports = {}, {}, {}
    for rid in ("r0", "r1"):
        svcs[rid], servers[rid], ports[rid] = _start_replica(
            rid, root=root
        )
    router = RouterServer([
        _Backend("r0", "127.0.0.1", ports["r0"]),
        _Backend("r1", "127.0.0.1", ports["r1"]),
    ])
    rserver = router.serve_forever(port=0)
    threading.Thread(target=rserver.serve_forever, daemon=True).start()
    rport = rserver.server_address[1]
    try:
        with socket.create_connection(("127.0.0.1", rport), 10) as sock:
            f = sock.makefile("rw")

            def rpc(**req):
                f.write(json.dumps(req) + "\n")
                f.flush()
                return json.loads(f.readline())

            r = rpc(op="create_study", name="demo", seed=3)
            assert r["ok"], r
            for _ in range(2):
                a = rpc(op="ask", study="demo", name="demo")
                assert a["ok"], a
                assert rpc(
                    op="tell", study="demo", name="demo",
                    tid=a["tid"], loss=_loss(a["vals"]),
                )["ok"]

            # ONE call scrapes the whole fleet: both replicas' series,
            # replica-tagged, plus the router's own
            m = rpc(op="metrics")
            assert m["ok"] and sorted(m["replicas"]) == ["r0", "r1"]
            by_replica = {
                row["labels"].get("replica")
                for row in m["metrics"]
                if row["name"] == "serve_dispatch_total"
            }
            assert by_replica == {"r0", "r1"}
            assert "serve_dispatch_total" in m["text"]
            assert 'replica="r0"' in m["text"]
            # fleet-wide span tail, replica-tagged
            t = rpc(op="trace", tail=200)
            assert t["ok"]
            assert any(
                s["name"] == "ask.delivered" for s in t["spans"]
            )
            assert {s.get("replica") for s in t["spans"]} <= {"r0", "r1"}

        # the console script against the live router
        assert scope_cli.main(
            ["metrics", "--port", str(rport)]
        ) == 0
        out = capsys.readouterr().out
        assert "serve_dispatch_total" in out and 'replica="r1"' in out
        assert scope_cli.main(
            ["trace", "--port", str(rport), "--tail", "5", "--json"]
        ) == 0
        spans = json.loads(capsys.readouterr().out)
        assert isinstance(spans, list)

        # health probing: kill the backend that OWNS the study -- the
        # probe marks it suspect BEFORE any client ask eats the
        # connection failure...
        victim = router.ring.owner("demo")
        other = "r0" if victim == "r1" else "r1"
        servers[victim].shutdown()
        servers[victim].server_close()
        router.probe_backends()
        assert victim in router._alive_excluded()
        rows = {
            (r["name"], r["labels"].get("backend")): r
            for r in router.metrics.collect()
        }
        assert rows[("router_backend_up", other)]["value"] == 1
        assert rows[("router_backend_up", victim)]["value"] == 0
        assert router.metrics.histogram("router_probe_seconds").count >= 2

        def ask_ok():
            with socket.create_connection(
                ("127.0.0.1", rport), 10
            ) as sock:
                f = sock.makefile("rw")
                f.write(json.dumps(
                    {"op": "ask", "study": "demo", "name": "demo"}
                ) + "\n")
                f.flush()
                return json.loads(f.readline())

        # ...asks fail over to the survivor (shared-root adoption),
        # with no client-visible error
        a = ask_ok()
        assert a["ok"], a

        # ...and a probe-recovered backend rejoins the ring: the next
        # ask routed to it re-adopts the study past its stale claim
        # (OwnershipLost -> takeover -> retry), again with no
        # client-visible error
        from hyperopt_tpu.serve.service import serve_forever

        revived = serve_forever(
            svcs[victim], host="127.0.0.1", port=ports[victim]
        )
        threading.Thread(
            target=revived.serve_forever, daemon=True
        ).start()
        servers[victim] = revived
        # the dead backend accumulated probe-backoff while down, so the
        # revival is noticed within <= probe_backoff_cap sweeps (PR-16
        # satellite: failed probes back off exponentially, capped)
        for _ in range(router.probe_backoff_cap + 1):
            router.probe_backends()
            if victim not in router._alive_excluded():
                break
        assert victim not in router._alive_excluded()
        rows = {
            (r["name"], r["labels"].get("backend")): r
            for r in router.metrics.collect()
        }
        assert rows[("router_backend_up", victim)]["value"] == 1
        assert router.metrics.counter(
            "router_backend_rejoins_total"
        ).value == 1
        a = ask_ok()
        assert a["ok"], a
    finally:
        router.stop_probes()
        rserver.shutdown()
        rserver.server_close()
        for rid in ("r0", "r1"):
            servers[rid].shutdown()
            servers[rid].server_close()
            svcs[rid].shutdown()


def test_scope_cli_flight_file(tmp_path, capsys):
    from hyperopt_tpu.obs import cli as scope_cli

    path = str(tmp_path / "f.wal")
    rec = FlightRecorder(path=path)
    for i in range(5):
        rec.record("e", tid=i)
    rec.close()
    assert scope_cli.main(["flight", path, "--tail", "3"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 3 and "tid=4" in out[-1]
    assert scope_cli.main(["flight", path, "--json"]) == 0
    assert len(json.loads(capsys.readouterr().out)) == 5


# ---------------------------------------------------------------------------
# device-side streaming (the declared io_callback twin)
# ---------------------------------------------------------------------------


def test_device_metrics_twin_cadence_and_zero_when_off():
    def run(every):
        svc = SuggestService(
            SPACE, max_batch=4, background=False, n_startup_jobs=2,
            device_metrics_every=every, **ALGO_KW,
        )
        handles = [svc.create_study(f"s{i}", seed=i) for i in range(3)]
        _drive(svc, handles, 4)
        s = svc.scheduler
        out = (
            s.dispatch_count, s.device_metric_dispatches,
            {r["name"]: r for r in svc.metrics_rows()},
        )
        svc.shutdown()
        return out

    d_off, twin_off, rows_off = run(0)
    assert (d_off, twin_off) == (4, 0)  # off = zero extra dispatches
    assert "serve_device_best_loss" not in rows_off
    d_on, twin_on, rows = run(2)
    assert d_on == 4 and twin_on == 2  # cadence 2: rounds 2 and 4
    assert rows["obs_device_events_total"]["value"] == 2
    assert rows["serve_device_active_slots"]["value"] == 3
    assert rows["serve_device_trials_done"]["value"] > 0
    assert np.isfinite(rows["serve_device_best_loss"]["value"])


def test_device_loop_metrics_registry_adapter():
    from hyperopt_tpu.device_loop import compile_fmin

    space = {"x": hp.uniform("x", -5.0, 5.0)}
    reg = MetricsRegistry("dl")
    runner = compile_fmin(
        lambda cfg: (cfg["x"] - 1.0) ** 2, space, max_evals=16,
        batch_size=4, n_startup_jobs=2, n_EI_candidates=4,
        chunk_size=8, metrics_registry=reg,
    )
    out = runner(seed=3)
    rows = {r["name"]: r for r in reg.collect()}
    # 16 evals / batch 4 = 4 steps; chunk_size 8 -> 2-step chunks -> 2
    # declared io_callback rows landed on the registry
    assert rows["obs_device_events_total"]["value"] == 2
    assert rows["device_loop_trials_done"]["value"] == 16
    assert rows["device_loop_best_loss"]["value"] == pytest.approx(
        float(np.min(out["losses"]))
    )
    assert rows["device_loop_trials_per_sec"]["value"] > 0


def test_fmin_driver_recorder_invisible():
    from hyperopt_tpu import Trials, fmin, tpe

    space = {"x": hp.uniform("x", -3, 3)}

    def run(recorder=None):
        trials = Trials()
        fmin(
            lambda cfg: (cfg["x"] - 1) ** 2, space, algo=tpe.suggest,
            max_evals=8, trials=trials,
            rstate=np.random.default_rng(7), show_progressbar=False,
            recorder=recorder,
        )
        return trials.losses()

    plain = run()
    rec = FlightRecorder()
    traced = run(recorder=rec)
    assert traced == plain  # invisibility on the host driver too
    spans = [s for s in rec.tail() if s["name"] == "driver.trial"]
    assert len(spans) == 8
    assert all(s["study"] == "driver" for s in spans)
