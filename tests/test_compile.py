"""Space-compiler tests: the jitted sampler must agree (statistically and
structurally) with the host interpreter (SURVEY.md SS7 stance #1)."""

import numpy as np
import pytest

import jax

from hyperopt_tpu import hp
from hyperopt_tpu.exceptions import CompileError
from hyperopt_tpu.ops.compile import compile_space
from hyperopt_tpu.vectorize import VectorizeHelper, dense_to_idxs_vals


def test_compile_flat_mixed_space():
    space = {
        "u": hp.uniform("u", -2, 3),
        "lu": hp.loguniform("lu", -3, 1),
        "qu": hp.quniform("qu", 0, 10, 0.5),
        "n": hp.normal("n", 1.0, 2.0),
        "ri": hp.randint("ri", 7),
        "ch": hp.choice("ch", [0, 1, 2]),
        "pc": hp.pchoice("pc", [(0.2, "a"), (0.8, "b")]),
    }
    ps = compile_space(space)
    assert ps.n_dims == 7
    assert ps.unconditional
    v, a = ps.sample_prior(jax.random.key(0), 512)
    v, a = np.asarray(v), np.asarray(a)
    assert a.all()  # flat space: everything active
    lbl = {l: i for i, l in enumerate(ps.labels)}
    u = v[lbl["u"]]
    assert u.min() >= -2 and u.max() <= 3
    assert abs(u.mean() - 0.5) < 0.3
    lu = v[lbl["lu"]]
    assert lu.min() >= np.exp(-3) - 1e-6 and lu.max() <= np.exp(1) + 1e-5
    qu = v[lbl["qu"]]
    np.testing.assert_allclose(qu, np.round(qu / 0.5) * 0.5, atol=1e-5)
    ri = v[lbl["ri"]]
    assert set(np.unique(ri)).issubset(set(range(7)))
    pc = v[lbl["pc"]]
    frac_b = (pc == 1).mean()
    assert 0.7 < frac_b < 0.9  # pchoice respects probabilities


def test_compile_randint_low_high_offset():
    ps = compile_space({"r": hp.randint("r", 5, 9)})
    v, _ = ps.sample_prior(jax.random.key(1), 256)
    vals = np.asarray(v)[0]
    assert set(np.unique(vals)) <= {5.0, 6.0, 7.0, 8.0}
    assert len(np.unique(vals)) == 4


def test_compile_conditional_activity_matches_host_sampler():
    space = hp.choice(
        "root",
        [
            {"b": "flat", "x": hp.uniform("x_flat", 0, 1)},
            {
                "b": "deep",
                "y": hp.loguniform("y_deep", -3, 0),
                "sub": hp.choice("sub", [hp.normal("n0", 0, 1), hp.randint("r1", 4)]),
            },
        ],
    )
    ps = compile_space(space)
    v, a = ps.sample_prior(jax.random.key(2), 2000)
    v, a = np.asarray(v), np.asarray(a)
    lbl = {l: i for i, l in enumerate(ps.labels)}
    root = v[lbl["root"]]
    # activity must follow the drawn choices exactly
    np.testing.assert_array_equal(a[lbl["x_flat"]], root == 0)
    np.testing.assert_array_equal(a[lbl["y_deep"]], root == 1)
    np.testing.assert_array_equal(a[lbl["sub"]], root == 1)
    sub = v[lbl["sub"]]
    np.testing.assert_array_equal(a[lbl["n0"]], (root == 1) & (sub == 0))
    np.testing.assert_array_equal(a[lbl["r1"]], (root == 1) & (sub == 1))
    # branch rates ~ uniform prior
    assert 0.45 < (root == 0).mean() < 0.55

    # statistical parity with the host interpreter on a shared label
    helper = VectorizeHelper(space)
    host_draws = [helper.sample_one(np.random.default_rng(i)) for i in range(500)]
    host_y = np.array([c["y_deep"] for c in host_draws if "y_deep" in c])
    jax_y = v[lbl["y_deep"]][a[lbl["y_deep"]]]
    # same support and similar medians (loguniform -3..0)
    assert np.exp(-3) <= jax_y.min() and jax_y.max() <= 1.0 + 1e-6
    assert abs(np.median(np.log(jax_y)) - np.median(np.log(host_y))) < 0.35


def test_compile_shared_param_across_branches():
    shared = hp.uniform("shared", 0, 1)
    space = hp.choice("c", [{"a": shared}, {"b": shared, "z": hp.normal("z", 0, 1)}])
    ps = compile_space(space)
    v, a = ps.sample_prior(jax.random.key(3), 500)
    a = np.asarray(a)
    lbl = {l: i for i, l in enumerate(ps.labels)}
    # shared is active on both branches -> always active
    assert a[lbl["shared"]].all()
    np.testing.assert_array_equal(
        a[lbl["z"]], np.asarray(v)[lbl["c"]] == 1
    )


def test_compile_empty_space_raises():
    with pytest.raises(CompileError):
        compile_space({"const": 3})


def test_dense_to_sparse_bridge_with_compiled_sampler():
    space = hp.choice("c", [hp.uniform("x", 0, 1), hp.uniform("y", 5, 6)])
    ps = compile_space(space)
    v, a = ps.sample_prior(jax.random.key(4), 8)
    idxs, vals = dense_to_idxs_vals(range(8), ps.labels, np.asarray(v), np.asarray(a))
    assert idxs["c"] == list(range(8))
    assert sorted(idxs["x"] + idxs["y"]) == list(range(8))


def test_sample_prior_deterministic():
    ps = compile_space({"u": hp.uniform("u", 0, 1)})
    v1, _ = ps.sample_prior(jax.random.key(9), 16)
    v2, _ = ps.sample_prior(jax.random.key(9), 16)
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))


@pytest.mark.slow
def test_compile_deeply_nested_choice_stress():
    """Three levels of hp.choice nesting (the NAS-style stress case,
    SURVEY.md SS7 'hard parts'): activity masks must reflect the full
    conjunction of ancestor choices on every path, on the compiled
    sampler, the host sampler, tpe_jax, and the device loop."""
    import jax
    import numpy as np

    from hyperopt_tpu import Domain, Trials, fmin, hp, tpe_jax
    from hyperopt_tpu.ops.compile import compile_space

    space = hp.choice("l1", [
        {"arm": 0, "a": hp.uniform("a", 0, 1)},
        {"arm": 1, "sub": hp.choice("l2", [
            {"k": 0, "b": hp.uniform("b", 0, 1)},
            {"k": 1, "deep": hp.choice("l3", [
                {"z": 0, "c": hp.quniform("c", 0, 10, 1)},
                {"z": 1, "d": hp.randint("d", 3)},
            ])},
        ])},
    ])

    ps = compile_space(space)
    assert not ps.unconditional
    values, active = ps.sample_prior(jax.random.key(0), 256)
    values, active = np.asarray(values), np.asarray(active)
    lbl = {l: i for i, l in enumerate(ps.labels)}
    l1, l2, l3 = values[lbl["l1"]], values[lbl["l2"]], values[lbl["l3"]]
    # conjunction of ancestors, per level
    np.testing.assert_array_equal(active[lbl["a"]], l1 == 0)
    np.testing.assert_array_equal(active[lbl["l2"]], l1 == 1)
    np.testing.assert_array_equal(active[lbl["b"]], (l1 == 1) & (l2 == 0))
    np.testing.assert_array_equal(active[lbl["l3"]], (l1 == 1) & (l2 == 1))
    np.testing.assert_array_equal(
        active[lbl["c"]], (l1 == 1) & (l2 == 1) & (l3 == 0)
    )
    np.testing.assert_array_equal(
        active[lbl["d"]], (l1 == 1) & (l2 == 1) & (l3 == 1)
    )

    def obj(cfg):
        if cfg["arm"] == 0:
            return cfg["a"]
        sub = cfg["sub"]
        if sub["k"] == 0:
            return 1.0 + sub["b"]
        deep = sub["deep"]
        return (2.0 + deep["c"] / 10.0) if deep["z"] == 0 else 2.0 + deep["d"]

    trials = Trials()
    fmin(obj, space, algo=tpe_jax.suggest, max_evals=60, trials=trials,
         rstate=np.random.default_rng(0), show_progressbar=False)
    for t in trials.trials:
        vals = t["misc"]["vals"]
        arm = vals["l1"][0]
        assert (len(vals["a"]) == 1) == (arm == 0)
        assert (len(vals["l2"]) == 1) == (arm == 1)
        if arm == 1 and vals["l2"][0] == 1:
            assert len(vals["l3"]) == 1
            z = vals["l3"][0]
            assert (len(vals["c"]) == 1) == (z == 0)
            assert (len(vals["d"]) == 1) == (z == 1)
    assert min(trials.losses()) < 1.0  # found the best (arm 0) branch

    # device loop over the same nested space
    from hyperopt_tpu.device_loop import fmin_on_device
    import jax.numpy as jnp

    def dev_obj(cfg, active):
        return jnp.where(
            active["a"], cfg["a"],
            jnp.where(active["b"], 1.0 + cfg["b"],
                      jnp.where(active["c"], 2.0 + cfg["c"] / 10.0,
                                2.0 + cfg["d"])),
        )

    out = fmin_on_device(dev_obj, space, max_evals=64, batch_size=8, seed=0)
    assert out["best_loss"] < 1.0
