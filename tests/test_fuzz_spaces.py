"""Seeded fuzz over randomly generated nested spaces.

SURVEY.md SS7 names conditional spaces under jit as the hard part; the
hand-written cases in test_compile/test_vectorize pin known shapes, and
this file sweeps a generator over the whole constructor surface --
every hp.* family, nested hp.choice up to depth 3, shared-label-free
random trees -- asserting the structural invariants that every drawn
batch must satisfy:

  * the emitted active mask equals ``ps.active_fn(values)`` (conditional
    routing is self-consistent),
  * active values respect each family's bounds / log-space domain /
    quantization grid / integer range,
  * the dense->sparse bridge emits values exactly for active labels,
  * ``space_eval`` resolves a drawn assignment to a concrete config,
  * ``tpe_jax.suggest`` runs end-to-end on the space and keeps the same
    structural integrity in its trial docs.
"""

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp, tpe_jax
from hyperopt_tpu.fmin import space_eval
from hyperopt_tpu.ops.compile import compile_space
from hyperopt_tpu.vectorize import dense_to_idxs_vals


def make_random_space(rng, max_labels=10):
    """A random space tree touching every constructor family."""
    counter = [0]

    def fresh(kind):
        counter[0] += 1
        return f"{kind}{counter[0]}"

    def leaf():
        k = rng.integers(0, 11)
        lbl = fresh("p")
        if k == 0:
            return hp.uniform(lbl, -5, 5)
        if k == 1:
            return hp.loguniform(lbl, -4, 2)
        if k == 2:
            return hp.quniform(lbl, 0, 10, float(rng.choice([0.5, 1, 2])))
        if k == 3:
            return hp.qloguniform(lbl, 0, 3, 1)
        if k == 4:
            return hp.normal(lbl, 0, 2)
        if k == 5:
            return hp.qnormal(lbl, 0, 4, 1)
        if k == 6:
            return hp.lognormal(lbl, 0, 1)
        if k == 7:
            return hp.qlognormal(lbl, 0, 1, 1)
        if k == 8:
            return hp.randint(lbl, int(rng.integers(2, 9)))
        if k == 9:
            return hp.uniformint(lbl, 1, int(rng.integers(3, 12)))
        return hp.pchoice(lbl, [
            (p / 100.0, i)
            for i, p in enumerate([20, 30, 50])
        ])

    def node(d):
        if d < 2 and rng.uniform() < 0.35:
            n_opts = int(rng.integers(2, 4))
            return hp.choice(fresh("c"), [
                {"which": i, "inner": node(d + 1)} for i in range(n_opts)
            ])
        return leaf()

    n_top = int(rng.integers(2, max_labels // 2 + 1))
    return {f"top{i}": node(0) for i in range(n_top)}


def check_batch(ps, values, active):
    values = np.asarray(values)
    active = np.asarray(active)
    # conditional routing self-consistency
    np.testing.assert_array_equal(active, np.asarray(ps.active_fn(values)))
    # family-wise domain checks on ACTIVE entries only
    for i, d in enumerate(ps.cont_idx):
        v = values[d][active[d]]
        if v.size == 0:
            continue
        if np.isfinite(ps.low[i]):
            # compare in NATURAL space so the quantization slack (a
            # natural-space half-step) shares units with the bound
            if ps.logspace[i]:
                nlo, nhi = np.exp(ps.low[i]), np.exp(ps.high[i])
            else:
                nlo, nhi = float(ps.low[i]), float(ps.high[i])
            qslack = ps.q[i] / 2.0 if ps.q[i] > 0 else 0.0
            tol = 1e-3 * max(1.0, abs(nhi))
            assert v.min() >= nlo - qslack - tol
            assert v.max() <= nhi + qslack + tol
        if ps.q[i] > 0:
            ratio = v / ps.q[i]
            assert np.allclose(ratio, np.round(ratio), atol=1e-3)
        if ps.logspace[i]:
            if ps.q[i] > 0:
                # qlognormal legitimately rounds small draws to 0
                # (reference semantics)
                assert (v >= 0).all()
            else:
                assert (v > 0).all()
    for i, d in enumerate(ps.cat_idx):
        v = values[d][active[d]]
        if v.size == 0:
            continue
        assert np.allclose(v, np.round(v))
        assert v.min() >= ps.int_low[i]
        assert v.max() < ps.int_low[i] + ps.n_options[i]


@pytest.mark.parametrize("seed", range(10))
def test_fuzzed_space_prior_batch_invariants(seed):
    import jax

    rng = np.random.default_rng(seed)
    space = make_random_space(rng)
    ps = compile_space(space)
    values, active = ps.sample_prior(jax.random.key(seed), 64)
    values, active = np.asarray(values), np.asarray(active)
    check_batch(ps, values, active)

    # dense -> sparse bridge: values exactly where active
    ids = list(range(64))
    idxs, vals = dense_to_idxs_vals(ids, ps.labels, values, active)
    for d, label in enumerate(ps.labels):
        got = set(idxs[label])
        expect = {ids[j] for j in range(64) if active[d, j]}
        assert got == expect, label
        assert len(vals[label]) == len(idxs[label])

    # a drawn assignment resolves to a concrete config via space_eval
    j = 0
    cat = set(ps.cat_idx.tolist())
    assign = {
        label: (int(round(values[d, j].item())) if d in cat
                else values[d, j].item())
        for d, label in enumerate(ps.labels)
        if active[d, j]
    }
    cfg = space_eval(space, assign)
    assert isinstance(cfg, dict) and len(cfg) >= 1


@pytest.mark.parametrize("seed", (3, 7))
def test_fuzzed_space_tpe_jax_end_to_end(seed):
    rng = np.random.default_rng(seed)
    space = make_random_space(rng)
    ps = compile_space(space)

    def objective(cfg):
        # deterministic scalar from an arbitrary nested config
        total = 0.0
        stack = [cfg]
        while stack:
            x = stack.pop()
            if isinstance(x, dict):
                stack.extend(x.values())
            elif isinstance(x, (int, float)):
                total += float(np.tanh(float(x)))
        return total

    trials = Trials()
    fmin(
        objective, space, algo=tpe_jax.suggest, max_evals=35,
        trials=trials, rstate=np.random.default_rng(seed),
        show_progressbar=False, return_argmin=False,
    )
    assert len(trials) == 35
    lbl_to_dim = {label: d for d, label in enumerate(ps.labels)}
    cat = set(ps.cat_idx.tolist())
    n = len(trials.trials)
    dense = np.zeros((ps.n_dims, n), dtype=np.float32)
    act = np.zeros((ps.n_dims, n), dtype=bool)
    for j, t in enumerate(trials.trials):
        vals = t["misc"]["vals"]
        for label, vlist in vals.items():
            assert len(vlist) in (0, 1), label
            d = lbl_to_dim[label]
            if vlist:
                dense[d, j] = float(vlist[0])
                act[d, j] = True
                if d in cat:
                    assert isinstance(vlist[0], int)
    # TPE-suggested values (the EI sweep path, not just the prior) must
    # satisfy the same routing/bounds/quantization invariants
    check_batch(ps, dense, act)


@pytest.mark.slow
@pytest.mark.parametrize("seed,algo", [(1, "tpe"), (4, "tpe"), (6, "anneal")])
def test_fuzzed_space_device_loop(seed, algo):
    """The flagship on-device loop must run fuzzed conditional spaces end
    to end: jnp objective over dense values + active masks, finite best,
    history obeying the same structural invariants."""
    import jax.numpy as jnp

    from hyperopt_tpu.device_loop import compile_fmin
    from hyperopt_tpu.fmin import space_eval

    rng = np.random.default_rng(seed)
    space = make_random_space(rng)
    ps = compile_space(space)

    def obj(cfg, active):
        t = 0.0
        for k, v in cfg.items():
            t = t + jnp.tanh(v) * active[k]
        return t

    runner = compile_fmin(
        obj, space, max_evals=96, batch_size=8, algo=algo,
        n_startup_jobs=16,
    )
    out = runner(seed=seed)
    assert np.isfinite(out["best_loss"])
    assert out["n_evals"] == 96
    check_batch(ps, out["values"], out["active"])
    cfg = space_eval(space, out["best"])  # index-form best resolves
    assert isinstance(cfg, dict)


EXTREME_SPACES = {
    "tiny_range": lambda: {"x": hp.uniform("x", 0.0, 1e-8)},
    "huge_range": lambda: {"x": hp.uniform("x", -1e12, 1e12)},
    "wide_log": lambda: {"x": hp.loguniform("x", -30.0, 30.0)},
    "big_normal": lambda: {"x": hp.normal("x", 0.0, 1e9)},
    "tiny_q": lambda: {"x": hp.quniform("x", 0.0, 1e-4, 1e-6)},
    "huge_q": lambda: {"x": hp.quniform("x", 0.0, 1e12, 1e9)},
}


@pytest.mark.parametrize("name", sorted(EXTREME_SPACES))
def test_extreme_bounds_stay_finite(name):
    """f32 numerics at parameter extremes: both TPE paths must keep every
    draw finite and inside the declared range (truncation masses, bin
    masses, and the inverse-CDF sampler all stress-underflow here)."""
    from hyperopt_tpu import tpe

    space = EXTREME_SPACES[name]()
    lo_hi = {
        "tiny_range": (0.0, 1e-8), "huge_range": (-1e12, 1e12),
        "wide_log": (0.0, np.exp(30.0) * 1.001), "big_normal": (-np.inf, np.inf),
        "tiny_q": (-5e-7, 1e-4 + 5e-7), "huge_q": (-5e8, 1e12 + 5e8),
    }[name]
    for algo in (tpe.suggest, tpe_jax.suggest):
        trials = Trials()
        fmin(lambda cfg: float(np.tanh(cfg["x"] * 1e-6)), space, algo=algo,
             max_evals=30, trials=trials, rstate=np.random.default_rng(0),
             show_progressbar=False, return_argmin=False)
        xs = np.array(
            [t["misc"]["vals"]["x"][0] for t in trials.trials], dtype=float
        )
        assert np.isfinite(xs).all()
        assert xs.min() >= lo_hi[0] and xs.max() <= lo_hi[1]
