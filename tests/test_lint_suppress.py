"""Suppression semantics, the engine's meta rules (GL001/GL002), the
baseline's content-hash keying, and the CLI's exit-code contract."""

import json
import textwrap

import pytest

from hyperopt_tpu.analysis.baseline import (
    apply_baseline,
    load_baseline,
    write_baseline,
)
from hyperopt_tpu.analysis.cli import main
from hyperopt_tpu.analysis.engine import lint_source

BAD_SLEEP = textwrap.dedent(
    """\
    import time


    def fetch(op):
        for _ in range(3):
            try:
                return op()
            except OSError:
                time.sleep(0.05)
    """
)


def _findings(source, path="pkg/mod.py"):
    fs, _ = lint_source(source, path=path)
    return fs


# -- pragma placement --------------------------------------------------------

def test_pragma_on_violating_line_suppresses():
    src = BAD_SLEEP.replace(
        "time.sleep(0.05)",
        "time.sleep(0.05)  # graftlint: disable=GL303 supervisor backoff",
    )
    assert _findings(src) == []
    _, n = lint_source(src, path="pkg/mod.py")
    assert n == 1  # counted as suppressed, not silently dropped


def test_pragma_on_enclosing_def_suppresses_scope():
    src = BAD_SLEEP.replace(
        "def fetch(op):",
        "def fetch(op):  # graftlint: disable=GL303 hand-rolled by design",
    )
    assert _findings(src) == []


def test_pragma_on_unrelated_line_does_not_suppress():
    # one line ABOVE the violation is neither the line nor a scope header
    src = BAD_SLEEP.replace(
        "except OSError:",
        "except OSError:  # graftlint: disable=GL303 wrong line",
    )
    fs = _findings(src)
    assert [f.rule for f in fs] == ["GL303"]


def test_pragma_for_different_rule_does_not_suppress():
    src = BAD_SLEEP.replace(
        "time.sleep(0.05)",
        "time.sleep(0.05)  # graftlint: disable=GL304 wrong rule",
    )
    assert [f.rule for f in _findings(src)] == ["GL303"]


def test_multi_rule_pragma():
    src = BAD_SLEEP.replace(
        "time.sleep(0.05)",
        "time.sleep(0.05)  # graftlint: disable=GL304,GL303 both named",
    )
    assert _findings(src) == []


# -- GL001 / GL002 -----------------------------------------------------------

def test_unknown_rule_id_in_pragma_is_itself_a_finding():
    src = "x = 1  # graftlint: disable=GL999 no such rule\n"
    fs = _findings(src)
    assert [f.rule for f in fs] == ["GL001"]
    assert "GL999" in fs[0].message


def test_valid_pragma_with_reason_is_not_gl001():
    src = BAD_SLEEP.replace(
        "time.sleep(0.05)",
        "time.sleep(0.05)  # graftlint: disable=GL303 reason text here",
    )
    assert _findings(src) == []


def test_syntax_error_is_gl002():
    fs = _findings("def broken(:\n")
    assert [f.rule for f in fs] == ["GL002"]


# -- baseline: content-hash keying ------------------------------------------

def test_baseline_survives_unrelated_line_shift(tmp_path):
    fs = _findings(BAD_SLEEP)
    assert [f.rule for f in fs] == ["GL303"]
    bl_path = tmp_path / "baseline.json"
    write_baseline(str(bl_path), fs)

    shifted = "import os\n\nUNRELATED = os.sep  # new code above\n" + BAD_SLEEP
    shifted_fs = _findings(shifted)
    assert shifted_fs[0].line != fs[0].line  # the shift really happened
    kept, matched = apply_baseline(shifted_fs, load_baseline(str(bl_path)))
    assert kept == [] and matched == 1


def test_baseline_entry_dies_when_violating_line_changes(tmp_path):
    fs = _findings(BAD_SLEEP)
    bl_path = tmp_path / "baseline.json"
    write_baseline(str(bl_path), fs)

    edited = BAD_SLEEP.replace("time.sleep(0.05)", "time.sleep(0.25)")
    kept, matched = apply_baseline(
        _findings(edited), load_baseline(str(bl_path))
    )
    assert matched == 0 and [f.rule for f in kept] == ["GL303"]


def test_baseline_is_keyed_by_path_too(tmp_path):
    fs = _findings(BAD_SLEEP, path="pkg/a.py")
    bl_path = tmp_path / "baseline.json"
    write_baseline(str(bl_path), fs)
    kept, matched = apply_baseline(
        _findings(BAD_SLEEP, path="pkg/b.py"), load_baseline(str(bl_path))
    )
    assert matched == 0 and len(kept) == 1


def test_baseline_is_a_multiset(tmp_path):
    # two identical violating lines need two entries; one entry only
    # absorbs one of them
    double = BAD_SLEEP.replace(
        "time.sleep(0.05)", "time.sleep(0.05)\n            time.sleep(0.05)"
    )
    fs = _findings(double)
    assert len(fs) == 2
    bl_path = tmp_path / "baseline.json"
    write_baseline(str(bl_path), fs[:1])
    kept, matched = apply_baseline(fs, load_baseline(str(bl_path)))
    assert matched == 1 and len(kept) == 1


# -- CLI contract ------------------------------------------------------------

@pytest.fixture
def bad_tree(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(BAD_SLEEP)
    (pkg / "clean.py").write_text("x = 1\n")
    return pkg


def test_cli_exit_1_on_findings(bad_tree, capsys):
    assert main([str(bad_tree)]) == 1
    out = capsys.readouterr().out
    assert "GL303" in out and "1 finding(s)" in out


def test_cli_exit_0_on_clean(tmp_path, capsys):
    (tmp_path / "ok.py").write_text("x = 1\n")
    assert main([str(tmp_path)]) == 0


def test_cli_exit_2_on_bad_path(tmp_path, capsys):
    assert main([str(tmp_path / "does_not_exist")]) == 2
    assert "error" in capsys.readouterr().err


def test_cli_exit_2_on_unreadable_baseline(bad_tree, tmp_path, capsys):
    bl = tmp_path / "corrupt.json"
    bl.write_text("{not json")
    assert main([str(bad_tree), "--baseline", str(bl)]) == 2


def test_cli_json_format(bad_tree, capsys):
    assert main([str(bad_tree), "--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["summary"]["total"] == 1
    (finding,) = payload["findings"]
    assert finding["rule"] == "GL303" and finding["content_hash"]


def test_cli_write_baseline_roundtrip(bad_tree, tmp_path, capsys):
    bl = tmp_path / "bl.json"
    assert main(
        [str(bad_tree), "--baseline", str(bl), "--write-baseline"]
    ) == 0
    assert main([str(bad_tree), "--baseline", str(bl)]) == 0  # grandfathered
    assert main([str(bad_tree), "--baseline", str(bl), "--no-baseline"]) == 1


def test_cli_list_rules(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("GL001", "GL101", "GL201", "GL301", "GL304"):
        assert rid in out
