"""JAX kernel unit tests: parity against the numpy oracle in
hyperopt_tpu.tpe (SURVEY.md SS7 'parity tests vs numpy oracle')."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperopt_tpu import tpe
from hyperopt_tpu.ops import kernels as K


def f32(x):
    return jnp.asarray(x, dtype=jnp.float32)


# -- forgetting weights -----------------------------------------------------


@pytest.mark.parametrize("n,lf", [(10, 25), (40, 25), (25, 25), (26, 25)])
def test_forgetting_weights_match_oracle(n, lf):
    mask = np.zeros(64, dtype=bool)
    mask[:n] = True
    got = np.asarray(K.forgetting_weights(jnp.asarray(mask), float(lf)))
    want = tpe.linear_forgetting_weights(n, lf)
    np.testing.assert_allclose(got[:n], want, rtol=1e-5)
    np.testing.assert_array_equal(got[n:], 0.0)


def test_forgetting_weights_masked_slots_skipped():
    # valid slots interleaved with invalid: ranks follow valid order
    mask = np.array([True, False, True, True, False])
    got = np.asarray(K.forgetting_weights(jnp.asarray(mask), 25.0))
    assert got[1] == 0.0 and got[4] == 0.0
    np.testing.assert_allclose(got[[0, 2, 3]], np.ones(3), rtol=1e-6)


# -- parzen fit -------------------------------------------------------------


def parzen_oracle(obs, prior_mu, prior_sigma, prior_weight=1.0, lf=25):
    return tpe.adaptive_parzen_normal(obs, prior_weight, prior_mu, prior_sigma, lf)


def run_parzen_kernel(obs, prior_mu, prior_sigma, prior_weight=1.0, lf=25, cap=32):
    buf = np.zeros(cap, dtype=np.float32)
    mask = np.zeros(cap, dtype=bool)
    buf[: len(obs)] = obs
    mask[: len(obs)] = True
    w, m, s = K.parzen_fit(
        f32(buf), jnp.asarray(mask), f32(prior_mu), f32(prior_sigma),
        f32(prior_weight), f32(lf),
    )
    w, m, s = np.asarray(w), np.asarray(m), np.asarray(s)
    keep = w > 0
    return w[keep], m[keep], s[keep]


@pytest.mark.parametrize(
    "obs",
    [
        [],
        [0.5],
        [0.5, -1.0],
        [0.1, 0.2, 0.3, 5.0, -3.0],
        list(np.random.default_rng(0).uniform(-4, 4, size=30)),
    ],
)
def test_parzen_fit_matches_oracle(obs):
    prior_mu, prior_sigma = 0.0, 8.0
    ww, wm, ws = parzen_oracle(obs, prior_mu, prior_sigma)
    gw, gm, gs = run_parzen_kernel(obs, prior_mu, prior_sigma)
    assert len(gw) == len(ww)
    np.testing.assert_allclose(gm, wm, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(gw, ww, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(gs, ws, rtol=1e-4, atol=1e-5)


def test_parzen_fit_with_forgetting_matches_oracle():
    rng = np.random.default_rng(1)
    obs = list(rng.normal(0, 2, size=40))
    ww, wm, ws = parzen_oracle(obs, 0.0, 5.0, lf=25)
    gw, gm, gs = run_parzen_kernel(obs, 0.0, 5.0, lf=25, cap=64)
    np.testing.assert_allclose(gm, wm, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(gw, ww, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(gs, ws, rtol=1e-4, atol=1e-4)


# -- truncated GMM sampling -------------------------------------------------


def test_trunc_gmm_sample_bounds_and_distribution():
    w = f32([0.4, 0.6, 0.0])
    mu = f32([0.0, 5.0, 0.0])
    sigma = f32([1.0, 0.7, 1.0])
    key = jax.random.key(0)
    draws = np.asarray(
        K.trunc_gmm_sample(
            key, w, mu, sigma, f32(-2.0), f32(7.0), jnp.asarray(False),
            f32(0.0), 20000,
        )
    )
    assert draws.min() >= -2.0 and draws.max() <= 7.0
    # compare against numpy-oracle draws via KS-ish histogram distance
    oracle = tpe.GMM1(
        np.array([0.4, 0.6]), np.array([0.0, 5.0]), np.array([1.0, 0.7]),
        low=-2.0, high=7.0, rng=np.random.default_rng(0), size=(20000,),
    )
    h1, edges = np.histogram(draws, bins=30, range=(-2, 7), density=True)
    h2, _ = np.histogram(oracle, bins=edges, density=True)
    assert np.abs(h1 - h2).max() < 0.06


def test_trunc_gmm_sample_logspace_quantized():
    w = f32([1.0])
    mu = f32([0.0])
    sigma = f32([1.0])
    draws = np.asarray(
        K.trunc_gmm_sample(
            jax.random.key(1), w, mu, sigma, f32(-1.0), f32(1.0),
            jnp.asarray(True), f32(0.5), 2000,
        )
    )
    np.testing.assert_allclose(draws, np.round(draws / 0.5) * 0.5, atol=1e-5)
    assert draws.min() >= 0.0  # rounded exp(-1)=0.368 -> 0.5 grid
    assert draws.max() <= np.round(np.exp(1.0) / 0.5) * 0.5 + 1e-6


# -- GMM lpdf ---------------------------------------------------------------


def test_trunc_gmm_logpdf_matches_oracle_continuous():
    w = np.array([0.3, 0.7])
    mu = np.array([-1.0, 2.0])
    sigma = np.array([0.5, 1.5])
    x = np.linspace(-3, 4, 51)
    got = np.asarray(
        K.trunc_gmm_logpdf(
            f32(x), f32(w), f32(mu), f32(sigma), f32(-jnp.inf), f32(jnp.inf),
            jnp.asarray(False), f32(0.0),
        )
    )
    want = tpe.GMM1_lpdf(x, w, mu, sigma)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_trunc_gmm_logpdf_matches_oracle_truncated_quantized():
    w = np.array([0.5, 0.5])
    mu = np.array([1.0, 8.0])
    sigma = np.array([2.0, 1.0])
    x = np.arange(0.0, 11.0, 1.0)
    got = np.asarray(
        K.trunc_gmm_logpdf(
            f32(x), f32(w), f32(mu), f32(sigma), f32(0.0), f32(10.0),
            jnp.asarray(False), f32(1.0),
        )
    )
    want = tpe.GMM1_lpdf(x, w, mu, sigma, low=0.0, high=10.0, q=1.0)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)
    assert np.exp(got).sum() == pytest.approx(1.0, abs=1e-4)


def test_trunc_gmm_logpdf_matches_oracle_lognormal():
    w = np.array([0.6, 0.4])
    mu = np.array([0.0, 1.0])
    sigma = np.array([0.5, 0.3])
    x = np.linspace(0.1, 10.0, 40)
    got = np.asarray(
        K.trunc_gmm_logpdf(
            f32(x), f32(w), f32(mu), f32(sigma), f32(-jnp.inf), f32(jnp.inf),
            jnp.asarray(True), f32(0.0),
        )
    )
    want = tpe.LGMM1_lpdf(x, w, mu, sigma)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


# -- categorical fit --------------------------------------------------------


def test_categorical_fit_matches_oracle():
    obs = [2, 2, 0, 1, 2, 2]
    prior = np.array([0.25, 0.25, 0.5])
    cap = 16
    buf = np.zeros(cap, dtype=np.float32)
    mask = np.zeros(cap, dtype=bool)
    buf[: len(obs)] = obs
    mask[: len(obs)] = True
    got = np.asarray(
        K.categorical_fit(f32(buf), jnp.asarray(mask), f32(prior), f32(1.0), f32(25.0))
    )
    want = tpe.categorical_posterior(obs, prior, 1.0, 25)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_categorical_fit_padded_options_zero():
    prior = np.array([0.5, 0.5, 0.0, 0.0])  # 2 real options, 2 padded
    got = np.asarray(
        K.categorical_fit(
            f32(np.zeros(8)), jnp.asarray(np.zeros(8, bool)), f32(prior),
            f32(1.0), f32(25.0),
        )
    )
    assert got[2] == 0.0 and got[3] == 0.0
    np.testing.assert_allclose(got[:2], [0.5, 0.5], rtol=1e-6)


# -- below/above split ------------------------------------------------------


def test_split_below_above_counts_and_membership():
    losses = np.array([5.0, 1.0, 3.0, 2.0, 4.0, np.nan, 9.0, 0.5], np.float32)
    valid = np.array([True, True, True, True, True, True, True, False])
    below, above, n_below = K.split_below_above(
        jnp.asarray(losses), jnp.asarray(valid), 0.25, 25.0
    )
    below, above = np.asarray(below), np.asarray(above)
    n_ok = 6  # nan and invalid excluded
    want_n_below = min(int(np.ceil(0.25 * np.sqrt(n_ok))), 25)
    assert below.sum() == want_n_below
    assert not below[5] and not above[5]  # nan masked
    assert not below[7] and not above[7]  # invalid masked
    assert below[1]  # loss 1.0 is the best valid
    assert below.sum() + above.sum() == n_ok


def test_split_matches_numpy_filter():
    rng = np.random.default_rng(0)
    losses = rng.uniform(0, 1, 30).astype(np.float32)
    valid = np.ones(30, dtype=bool)
    below, above, _ = K.split_below_above(
        jnp.asarray(losses), jnp.asarray(valid), 0.25, 25.0
    )
    n_below = int(np.asarray(below).sum())
    want_below_idx = set(np.argsort(losses, kind="stable")[:n_below])
    assert set(np.nonzero(np.asarray(below))[0]) == want_below_idx


def test_below_pad_one_slot_slack():
    """Regression (ADVICE r1): split_below_above computes
    ceil(gamma*sqrt(n_ok)) in float32 on device; _below_pad bounds it on
    the host in float64.  The pad must keep >= 1 slot of slack above the
    device count wherever the lf cap doesn't apply, so a float32 ceil
    landing one above the float64 ceil at an exact integer boundary can
    never overflow the buffer -- including when the float64 bound is a
    multiple of 8 and the sublane round-up would otherwise add no slack."""
    import math

    for cap in (64, 256, 512, 1024, 2048, 4096):
        for gamma in (0.25, 0.2, 0.5):
            lf = 1000  # never the binding constraint
            pad = K._below_pad(lf, cap=cap, gamma=gamma)
            dev_ceil = int(
                np.ceil(np.float32(gamma) * np.sqrt(np.float32(cap)))
            )
            assert pad >= dev_ceil + 1, (cap, gamma, pad, dev_ceil)
    # the case where the round-up alone adds no slack: bound is exactly a
    # multiple of 8 (cap=1024, gamma=.25 -> ceil(8.0)=8); without the +1
    # the pad would be 8 with zero slack
    assert K._below_pad(1000, cap=1024, gamma=0.25) >= 9
    # lf-capped regime needs no slack: device mins with the same lf float
    assert K._below_pad(25, cap=10**6, gamma=0.25) >= 25


def test_check_prior_weight_guard():
    """Regression (ADVICE r1): every suggest builder must reject
    prior_weight <= 0 at build time."""
    from hyperopt_tpu import hp, tpe_jax
    from hyperopt_tpu.ops.compile import compile_space
    from hyperopt_tpu.parallel.mesh import default_mesh
    from hyperopt_tpu.parallel.sharded import build_sharded_suggest_fn

    ps = compile_space({"x": hp.uniform("x", 0, 1)})
    with pytest.raises(ValueError, match="prior_weight must be > 0"):
        tpe_jax.build_suggest_fn(ps, 16, 0.25, 25.0, 0.0)
    with pytest.raises(ValueError, match="prior_weight must be > 0"):
        build_sharded_suggest_fn(ps, default_mesh(), 16, 0.25, 25.0, 0.0)


def test_ei_sweep_fused_b1_matches_grouped():
    """Round-5 B=1 optimization: when a space has BOTH q and non-q
    continuous dims, the B=1 sweep runs as ONE fused traced-q group
    (fewer kernels) -- its draws and scores must be bitwise identical
    to the q-partitioned form, which still runs at B > 1.  Row 0 of a
    B=2 grouped call uses the same per-dim keys as the B=1 fused call,
    so the two must agree exactly."""
    import jax

    from hyperopt_tpu import hp
    from hyperopt_tpu.ops.compile import compile_space

    space = {
        "u": hp.uniform("u", -5.0, 5.0),
        "qu": hp.quniform("qu", 0.0, 20.0, 1.0),
        "lu": hp.loguniform("lu", -4.0, 2.0),
    }
    ps = compile_space(space)
    c = ps._consts
    dc = len(ps.cont_idx)
    cap = 128
    rng = np.random.default_rng(0)
    values, active = jax.device_get(ps.sample_prior(jax.random.key(0), cap))
    losses = jnp.asarray(rng.uniform(0, 10, cap).astype(np.float32))
    valid = jnp.ones((cap,), bool)
    fits = K.fit_all_dims(
        c, jnp.asarray(values), jnp.asarray(active), losses, valid,
        0.25, 25.0, 1.0,
    )
    keys = jax.random.split(jax.random.key(1), 2 * dc).reshape(2, dc)

    v1, s1 = K.ei_sweep_cont(ps.q, c, keys[:1], fits["cont"], 16)  # fused
    v2, s2 = K.ei_sweep_cont(ps.q, c, keys, fits["cont"], 16)  # grouped
    assert np.array_equal(np.asarray(v1[0]), np.asarray(v2[0]))
    assert np.array_equal(np.asarray(s1[0]), np.asarray(s2[0]))


# -- above-model compaction (round 6) ---------------------------------------


def _wide_parzen_fit(n_live, width, seed=0, spread=2.0):
    """One parzen_fit row with ``n_live - 1`` observations (+ prior) in a
    ``width - 1``-slot buffer -- the raw material compact_gmm consumes."""
    rng = np.random.default_rng(seed)
    obs = np.zeros(width - 1, np.float32)
    mask = np.zeros(width - 1, bool)
    obs[: n_live - 1] = rng.normal(0, spread, n_live - 1)
    mask[: n_live - 1] = True
    return K.parzen_fit(
        f32(obs), jnp.asarray(mask), f32(0.0), f32(8.0), f32(1.0), f32(25.0)
    )


def test_compact_gmm_identity_below_cap_bitwise():
    """PARITY CONTRACT: while the live component count fits under the
    cap, compaction is the identity -- the output slots are BITWISE the
    input's first ``cap`` slots (live prefix + zero-weight padding), so
    every downstream score reduction sees the same live terms."""
    for n_live, width, cap in ((50, 257, 64), (64, 1025, 64), (2, 129, 8)):
        w, m, s = _wide_parzen_fit(n_live, width, seed=n_live)
        wo, mo, so = K.compact_gmm(w, m, s, cap)
        assert np.array_equal(np.asarray(wo), np.asarray(w)[:cap])
        assert np.array_equal(np.asarray(mo), np.asarray(m)[:cap])
        assert np.array_equal(np.asarray(so), np.asarray(s)[:cap])


def test_compact_gmm_preserves_mixture_moments():
    """Above the cap, moment-matched merging preserves the mixture's
    total mass, mean, and second moment -- the compacted above model is
    the same density coarse-grained, not a reweighted one."""
    w, m, s = _wide_parzen_fit(801, 1025, seed=3)
    wo, mo, so = K.compact_gmm(w, m, s, 64)
    w_, m_, s_ = (np.asarray(a) for a in (w, m, s))
    wo_, mo_, so_ = (np.asarray(a) for a in (wo, mo, so))
    assert (wo_ > 0).sum() == 64  # full cap utilized
    np.testing.assert_allclose(wo_.sum(), w_.sum(), rtol=1e-6)
    np.testing.assert_allclose(
        (wo_ * mo_).sum(), (w_ * m_).sum(), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        (wo_ * (mo_**2 + so_**2)).sum(),
        (w_ * (m_**2 + s_**2)).sum(), rtol=1e-5,
    )
    # zero-weight output slots carry the padded-slot convention
    # (mu 0, sigma 1) every consumer already handles
    w2, m2, s2 = _wide_parzen_fit(5, 257, seed=4)
    wo2, mo2, so2 = K.compact_gmm(w2, m2, s2, 64)
    pad = np.asarray(wo2) == 0
    assert pad.any()
    assert np.array_equal(np.asarray(mo2)[pad], np.zeros(pad.sum()))
    assert np.array_equal(np.asarray(so2)[pad], np.ones(pad.sum()))


def test_compact_gmm_density_stays_close():
    """The compacted mixture must score like the full one: its density
    is a locally-averaged version of the full density (adjacent-in-mu
    merges), so pointwise agreement should be tight relative to the
    density scale even at a ~12x merge ratio."""
    w, m, s = _wide_parzen_fit(801, 1025, seed=5)
    wo, mo, so = K.compact_gmm(w, m, s, 64)
    x = f32(np.linspace(-8, 8, 201))
    args = (f32(-jnp.inf), f32(jnp.inf), jnp.asarray(False), f32(0.0))
    full = np.exp(np.asarray(K.trunc_gmm_logpdf(x, w, m, s, *args)))
    comp = np.exp(np.asarray(K.trunc_gmm_logpdf(x, wo, mo, so, *args)))
    assert np.abs(full - comp).max() < 0.35 * full.max()
    assert np.abs(full - comp).mean() < 0.02 * full.max()


def test_fit_all_dims_above_cap_scoring_parity():
    """ACCEPTANCE PIN (round 6): whenever the live above-model component
    count is <= the compaction cap, compacted scoring must match
    full-width scoring -- the compacted fit is bitwise the full fit
    (identity grouping) and the EI sweep's drawn candidates are bitwise
    identical.  The per-candidate float scores agree to the reduction's
    last ulp (XLA associates the sum differently across widths; the live
    terms and the padded exact-zero terms are identical either way)."""
    from hyperopt_tpu import hp
    from hyperopt_tpu.ops.compile import compile_space

    space = {
        "u": hp.uniform("u", -5.0, 5.0),
        "qu": hp.quniform("qu", 0.0, 20.0, 1.0),
        "lu": hp.loguniform("lu", -4.0, 2.0),
    }
    ps = compile_space(space)
    c = ps._consts
    cap = 512
    rng = np.random.default_rng(0)
    values, active = jax.device_get(ps.sample_prior(jax.random.key(0), cap))
    valid = np.zeros(cap, bool)
    valid[:50] = True  # ~47 above obs + prior: far under the cap of 64
    losses = rng.uniform(0, 10, cap).astype(np.float32)
    args = (
        c, jnp.asarray(values), jnp.asarray(active), jnp.asarray(losses),
        jnp.asarray(valid), 0.25, 25.0, 1.0,
    )
    f_full = K.fit_all_dims(*args)
    f_comp = K.fit_all_dims(*args, above_cap=64)
    assert f_full["cont"][3].shape[1] == cap + 1
    assert f_comp["cont"][3].shape[1] == 64
    for full_a, comp_a in zip(f_full["cont"][3:], f_comp["cont"][3:]):
        assert np.array_equal(np.asarray(full_a)[:, :64], np.asarray(comp_a))
    # below-model fits are untouched by the above cap
    for full_b, comp_b in zip(f_full["cont"][:3], f_comp["cont"][:3]):
        assert np.array_equal(np.asarray(full_b), np.asarray(comp_b))

    dc = len(ps.cont_idx)
    keys = jax.random.split(jax.random.key(1), 3 * dc).reshape(3, dc)
    v1, s1 = K.ei_sweep_cont(ps.q, c, keys, f_full["cont"], 16)
    v2, s2 = K.ei_sweep_cont(ps.q, c, keys, f_comp["cont"], 16)
    assert np.array_equal(np.asarray(v1), np.asarray(v2))
    np.testing.assert_allclose(
        np.asarray(s1), np.asarray(s2), rtol=0, atol=1e-5
    )


def test_fit_all_dims_above_cap_engages_past_cap():
    """Past the cap the above model really is capped (width AND live
    count), the below split is untouched, and the sweep still returns
    in-bounds draws."""
    from hyperopt_tpu import hp
    from hyperopt_tpu.ops.compile import compile_space

    ps = compile_space({"x": hp.uniform("x", -5.0, 5.0)})
    c = ps._consts
    cap = 1024
    rng = np.random.default_rng(1)
    values = rng.uniform(-5, 5, (1, cap)).astype(np.float32)
    active = np.ones((1, cap), bool)
    losses = rng.uniform(0, 10, cap).astype(np.float32)
    valid = np.ones(cap, bool)
    fits = K.fit_all_dims(
        c, jnp.asarray(values), jnp.asarray(active), jnp.asarray(losses),
        jnp.asarray(valid), 0.25, 25.0, 1.0, above_cap=128,
    )
    wa = np.asarray(fits["cont"][3])
    assert wa.shape == (1, 128)
    assert (wa > 0).sum() == 128
    np.testing.assert_allclose(wa.sum(), 1.0, rtol=1e-5)
    keys = jax.random.split(jax.random.key(2), 1).reshape(1, 1)
    v, s = K.ei_sweep_cont(ps.q, c, keys, fits["cont"], 32)
    v = np.asarray(v)
    assert np.isfinite(v).all() and (v >= -5).all() and (v <= 5).all()
    assert np.isfinite(np.asarray(s)).all()


def test_ei_sweep_single_group_batch_rows_independent():
    """Regression (round 5): the identity-group fast path must never
    collapse a B > 1 batch onto row 0's keys -- every row draws with its
    own keys, so rows differ (an all-non-q space is a single group)."""
    import jax

    from hyperopt_tpu import hp
    from hyperopt_tpu.ops.compile import compile_space

    ps = compile_space({
        "x": hp.uniform("x", -5.0, 5.0),
        "y": hp.uniform("y", -5.0, 5.0),
    })
    c = ps._consts
    cap = 128
    rng = np.random.default_rng(1)
    values, active = jax.device_get(ps.sample_prior(jax.random.key(0), cap))
    losses = jnp.asarray(rng.uniform(0, 10, cap).astype(np.float32))
    valid = jnp.ones((cap,), bool)
    fits = K.fit_all_dims(
        c, jnp.asarray(values), jnp.asarray(active), losses, valid,
        0.25, 25.0, 1.0,
    )
    keys = jax.random.split(jax.random.key(2), 3 * 2).reshape(3, 2)
    v, s = K.ei_sweep_cont(ps.q, c, keys, fits["cont"], 16)
    assert not np.array_equal(np.asarray(v[0]), np.asarray(v[1]))
    assert not np.array_equal(np.asarray(v[1]), np.asarray(v[2]))
