"""graftserve: the multi-tenant suggestion service (ISSUE 8).

The acceptance contract, pinned deterministically:

* PER-STUDY BITWISE PARITY: every study served out of a batched run --
  across join/leave churn and two slot capacities -- produces exactly
  the suggestion stream its SOLO fused-path run produces (same seed
  stream, same tell cadence);
* DISPATCH BOUND: a full 64-study run serves all asks in
  ``ceil(total_asks / batch) + joins`` device dispatches (counted, not
  timed);
* BUCKET-BOUNDARY GUARD: a study crossing its pow2 obs bucket
  re-buckets the shared state without disturbing sibling slots (their
  streams stay bitwise solo-equal even though the shared width grew).
"""

import json
import math
import socket
import threading

import numpy as np
import pytest

from hyperopt_tpu import hp, tpe_jax
from hyperopt_tpu.jax_trials import MIN_CAPACITY, ObsBuffer, host_key
from hyperopt_tpu.ops.compile import compile_space
from hyperopt_tpu.serve import SuggestService
from hyperopt_tpu.serve.batched import slot_capacity
from hyperopt_tpu.serve.scheduler import dense_to_vals

@pytest.fixture(autouse=True)
def _lockdep_armed(monkeypatch):
    # graftrace's runtime half: every BatchScheduler this suite builds
    # runs with its lock/condition wrapped by the lockdep sanitizer --
    # an observed acquisition-order inversion raises at the point of
    # acquisition, and the teardown assert catches the non-raising
    # (Condition re-acquire) path
    from hyperopt_tpu.analysis import lockdep

    dep = lockdep.arm_scheduler_class(monkeypatch)
    yield dep
    assert dep.inversions == 0, dep.errors


SPACE = {
    "x": hp.uniform("x", -5, 5),
    "lr": hp.loguniform("lr", -5, 0),
    "q": hp.quniform("q", 0, 10, 1),
    "c": hp.choice("c", [0, 1, 2]),
}

ALGO_KW = dict(n_cand=16, n_cand_cat=8)
N_STARTUP = 3


def loss_fn(vals):
    return (
        (vals["x"] - 1) ** 2 / 10
        + abs(float(np.log(vals["lr"])) + 2) / 3
        + abs(vals["q"] - 4) / 5
        + 0.1 * vals["c"]
    )


_SOLO_FNS = {}


def _solo_fns(ps):
    """The solo fused-path programs at the serve algo parameters
    (shared across all reference streams -- one compile)."""
    key = id(ps)
    if key not in _SOLO_FNS:
        plain = tpe_jax.build_suggest_fn(
            ps, ALGO_KW["n_cand"], 0.25, 25.0, 1.0,
            n_cand_cat=ALGO_KW["n_cand_cat"],
        )
        fused = tpe_jax.build_suggest_fn(
            ps, ALGO_KW["n_cand"], 0.25, 25.0, 1.0,
            n_cand_cat=ALGO_KW["n_cand_cat"], state_io=True,
        )
        _SOLO_FNS[key] = (plain, fused)
    return _SOLO_FNS[key]


def solo_stream(ps, seed, n_asks, prefill=()):
    """The SOLO fused-path reference for one study: per-ask seeds from
    the study's own rstate stream, one tell per ask, resident mirror
    with the fused tell+ask program -- exactly the PR-4 sequential
    driver a lone tenant would run."""
    import jax

    plain, fused = _solo_fns(ps)
    a_cap = tpe_jax._resolve_above_cap(None)
    buf = ObsBuffer(ps, resident=True)
    for vals, loss in prefill:
        buf.add(dict(vals), float(loss))
    rstate = np.random.default_rng(seed)
    stream = []
    for _ in range(n_asks):
        s = int(rstate.integers(2**31 - 1))
        key = host_key(s % (2**31 - 1))
        if buf.count < N_STARTUP:
            buf.dispatch_count += 1
            out = ps.sample_prior(key, 1)
        else:
            out = tpe_jax._state_dispatch(buf, key, 1, a_cap, plain, fused)
        v, a = jax.device_get(out)
        vals = dense_to_vals(ps, np.asarray(v)[:, 0], np.asarray(a)[:, 0])
        stream.append(vals)
        buf.add(dict(vals), loss_fn(vals))
    return stream


def drive_rounds(svc, handles, streams, n_rounds):
    """n_rounds of (ask every open handle, tell its loss)."""
    for _ in range(n_rounds):
        futs = [(h, h.ask_async()) for h in handles]
        svc.pump()
        for h, f in futs:
            tid, vals = f.result(timeout=10)
            streams.setdefault(h.name, []).append(vals)
            h.tell(tid, loss_fn(vals))


# ---------------------------------------------------------------------------
# the acceptance pins
# ---------------------------------------------------------------------------


def test_64_study_parity_and_dispatch_bound():
    """64 studies, 6 asks each, one slotted batch: every per-study
    stream bitwise solo-equal, all 384 asks served in 6 dispatches
    (``ceil(total_asks / batch) + joins`` with zero drain), occupancy
    pinned at 1.0."""
    svc = SuggestService(
        SPACE, max_batch=64, background=False,
        n_startup_jobs=N_STARTUP, **ALGO_KW,
    )
    ps = svc.ps
    handles = [svc.create_study(f"s{i:02d}", seed=100 + i)
               for i in range(64)]
    streams = {}
    n_rounds = 6
    drive_rounds(svc, handles, streams, n_rounds)

    for i, h in enumerate(handles):
        assert streams[h.name] == solo_stream(
            ps, 100 + i, n_rounds
        ), f"study {h.name} diverged from its solo fused-path stream"

    total_asks = 64 * n_rounds
    c = svc.counters
    assert c["dispatch_count"] <= math.ceil(total_asks / 64) + c["joins"]
    assert c["dispatch_count"] == n_rounds  # tight: every round full
    assert c["delta_drain_dispatches"] == 0
    assert c["upload_events"] == 1  # one materialization at first round
    assert list(svc.scheduler.occupancy) == [1.0] * n_rounds


@pytest.mark.parametrize("max_batch", [16, 64])
def test_churn_parity_two_capacities(max_batch):
    """Join/leave churn at two slot capacities: studies join mid-run,
    leave mid-run, slots get reused -- and every study's stream stays
    bitwise equal to its solo fused-path run (per-study rstate streams
    make batching order irrelevant)."""
    svc = SuggestService(
        SPACE, max_batch=max_batch, background=False,
        n_startup_jobs=N_STARTUP, **ALGO_KW,
    )
    ps = svc.ps
    streams = {}
    seeds = {}

    def open_wave(tag, n, base_seed):
        hs = []
        for i in range(n):
            name = f"{tag}{i:02d}"
            seeds[name] = base_seed + i
            hs.append(svc.create_study(name, seed=base_seed + i))
        return hs

    wave_a = open_wave("a", max_batch // 2, 500)
    drive_rounds(svc, wave_a, streams, 2)
    wave_b = open_wave("b", max_batch // 2, 700)  # join mid-run
    drive_rounds(svc, wave_a + wave_b, streams, 2)
    for h in wave_a[: max_batch // 4]:  # leave mid-run
        h.close()
    survivors = wave_a[max_batch // 4:] + wave_b
    drive_rounds(svc, survivors, streams, 2)
    wave_c = open_wave("c", max_batch // 4, 900)  # reuse freed slots
    drive_rounds(svc, survivors + wave_c, streams, 2)

    n_asks = {h.name: len(streams[h.name])
              for h in wave_a + wave_b + wave_c}
    for name, stream in streams.items():
        assert stream == solo_stream(ps, seeds[name], n_asks[name]), (
            f"study {name} diverged under churn (max_batch={max_batch})"
        )
    # the freed slots really were reused (join/leave exercised slots)
    assert svc.counters["joins"] == max_batch + max_batch // 4


def test_churn_before_first_dispatch_keeps_high_slots():
    """REGRESSION: closing a study BEFORE the first dispatch leaves a
    survivor on a slot index >= len(studies) (the freed low slot sits
    in the free list); the batch must be sized from the highest
    OCCUPIED slot, not the study count, or stack_states under-
    allocates and the high-slot ask indexes past the study axis."""
    svc = SuggestService(
        SPACE, max_batch=8, background=False,
        n_startup_jobs=N_STARTUP, **ALGO_KW,
    )
    ps = svc.ps
    handles = [svc.create_study(f"r{i}", seed=200 + i) for i in range(5)]
    handles[0].close()  # frees slot 0; a survivor still holds slot 4
    survivors = handles[1:]
    assert max(st.slot for st in svc.scheduler._slots.values()) == 4
    streams = {}
    drive_rounds(svc, survivors, streams, 3)
    for i, h in enumerate(survivors, start=1):
        assert streams[h.name] == solo_stream(ps, 200 + i, 3), (
            f"study {h.name} diverged after churn before first dispatch"
        )


def test_failed_dispatch_fails_picked_futures():
    """REGRESSION: a dispatch that dies mid-batch must fail the
    round's PICKED futures (already popped off the queue), not leave
    their clients blocked in ask() until the full timeout."""
    from hyperopt_tpu.distributed.faults import FaultPlan, SimulatedCrash

    plan = FaultPlan(seed=0).arm("serve_mid_batch", at=1)
    svc = SuggestService(
        SPACE, max_batch=4, background=False, fs=plan.fs(),
        n_startup_jobs=N_STARTUP, **ALGO_KW,
    )
    h = svc.create_study("f", seed=1)
    fut = h.ask_async()
    with pytest.raises(SimulatedCrash):
        svc.pump()
    assert fut.done(), "picked future stranded by a dying dispatch"
    with pytest.raises(SimulatedCrash):
        fut.result(timeout=0)


def test_stop_fails_queued_asks_promptly():
    """REGRESSION: shutdown must promptly fail every queued ask future
    and refuse later submits, not strand blocked clients until their
    timeout."""
    svc = SuggestService(
        SPACE, max_batch=4, background=False,
        n_startup_jobs=N_STARTUP, **ALGO_KW,
    )
    h = svc.create_study("z", seed=3)
    fut = h.ask_async()
    svc.shutdown()
    assert fut.done(), "queued future stranded by shutdown"
    with pytest.raises(RuntimeError, match="shutting down"):
        fut.result(timeout=0)
    with pytest.raises(RuntimeError, match="shutting down"):
        h.ask_async()


def test_bench_metrics_are_bounded():
    """REGRESSION: the timing metrics are ring buffers -- a long-
    running service must not leak one entry per ask forever."""
    from hyperopt_tpu.serve.scheduler import METRICS_WINDOW

    svc = SuggestService(SPACE, max_batch=4, background=False)
    assert svc.scheduler.ask_latencies.maxlen == METRICS_WINDOW
    assert svc.scheduler.occupancy.maxlen == METRICS_WINDOW
    svc.shutdown()


def test_bucket_boundary_rebucket_keeps_siblings_bitwise():
    """The satellite guard: a study crossing the pow2 obs bucket
    (count 128 -> bucket 256) re-buckets the WHOLE stacked state; the
    sibling -- still tiny, solo-bucketed at 128 -- must see a stream
    bitwise identical to its solo run across the crossing."""
    svc = SuggestService(
        SPACE, max_batch=4, background=False,
        n_startup_jobs=N_STARTUP, **ALGO_KW,
    )
    ps = svc.ps
    big = svc.create_study("big", seed=11)
    small = svc.create_study("small", seed=22)

    # pre-fill `big` to just under the bucket boundary with explicit
    # tells (no asks): deterministic synthetic history
    rng = np.random.default_rng(5)
    prefill = []
    for _ in range(MIN_CAPACITY - 2):
        vals = {
            "x": float(rng.uniform(-5, 5)),
            "lr": float(np.exp(rng.uniform(-5, 0))),
            "q": float(rng.integers(0, 11)),
            "c": int(rng.integers(0, 3)),
        }
        prefill.append((vals, loss_fn(vals)))
    for tid, (vals, loss) in enumerate(prefill):
        big.tell(tid, loss, vals=vals)
    assert svc.scheduler.study("big").buf.count == MIN_CAPACITY - 2

    streams = {}
    drive_rounds(svc, [big, small], streams, 6)  # crosses 128 at ask 3

    assert svc.scheduler.study("big").buf.count > MIN_CAPACITY
    assert svc.counters["rebuckets"] >= 1  # the boundary really crossed
    assert streams["small"] == solo_stream(ps, 22, 6), (
        "sibling stream disturbed by a neighbor's bucket growth"
    )
    assert streams["big"] == solo_stream(ps, 11, 6, prefill=prefill)


def test_multi_tell_backlog_drains_and_stays_bitwise():
    """A study telling several times between asks: the backlog drains
    through the batched masked-delta program (counted) and the next
    ask still matches the solo stream (solo replays the same deltas
    through its resident mirror)."""
    svc = SuggestService(
        SPACE, max_batch=4, background=False,
        n_startup_jobs=N_STARTUP, **ALGO_KW,
    )
    ps = svc.ps
    h = svc.create_study("m", seed=77)
    streams = {}
    drive_rounds(svc, [h], streams, 4)  # warm the study + mirror
    extra = [
        ({"x": 0.5, "lr": 0.1, "q": 2.0, "c": 1}, 0.9),
        ({"x": -1.5, "lr": 0.05, "q": 7.0, "c": 0}, 1.7),
        ({"x": 2.5, "lr": 0.3, "q": 1.0, "c": 2}, 0.4),
    ]
    base_tid = svc.scheduler.study("m").next_tid
    for k, (vals, loss) in enumerate(extra):
        h.tell(base_tid + k, loss, vals=vals)
    svc.scheduler.study("m").next_tid = base_tid + len(extra)
    drive_rounds(svc, [h], streams, 2)
    # 4 staged at the next ask (round-4's own tell + the 3 extras):
    # three drain dispatches, the last delta fuses into the ask
    assert svc.counters["delta_drain_dispatches"] == 3

    solo = solo_stream(ps, 77, 4)
    # replay the same interleaving on the solo reference
    import jax

    plain, fused = _solo_fns(ps)
    a_cap = tpe_jax._resolve_above_cap(None)
    buf = ObsBuffer(ps, resident=True)
    rstate = np.random.default_rng(77)
    solo_all = []
    for i in range(6):
        if i == 4:
            for vals, loss in extra:
                buf.add(dict(vals), loss)
        s = int(rstate.integers(2**31 - 1))
        key = host_key(s % (2**31 - 1))
        if buf.count < N_STARTUP:
            out = ps.sample_prior(key, 1)
        else:
            out = tpe_jax._state_dispatch(buf, key, 1, a_cap, plain, fused)
        v, a = jax.device_get(out)
        vals = dense_to_vals(ps, np.asarray(v)[:, 0], np.asarray(a)[:, 0])
        solo_all.append(vals)
        buf.add(dict(vals), loss_fn(vals))
    assert streams["m"] == solo_all
    assert solo_all[:4] == solo  # sanity: the prefix is the plain run


def test_anneal_serve_parity():
    """The anneal batched body: per-study streams bitwise equal to the
    solo anneal programs (prior below one observation, anneal after)."""
    import jax

    from hyperopt_tpu import anneal_jax

    svc = SuggestService(
        SPACE, algo="anneal", max_batch=4, background=False,
    )
    ps = svc.ps
    handles = [svc.create_study(f"an{i}", seed=40 + i) for i in range(3)]
    streams = {}
    drive_rounds(svc, handles, streams, 5)

    plain = anneal_jax.build_anneal_fn(ps, 2.0, 0.1)
    fused = anneal_jax.build_anneal_fn(ps, 2.0, 0.1, state_io=True)
    for i, h in enumerate(handles):
        buf = ObsBuffer(ps, resident=True)
        rstate = np.random.default_rng(40 + i)
        for vals in streams[h.name]:
            s = int(rstate.integers(2**31 - 1))
            key = host_key(s % (2**31 - 1))
            if buf.count == 0:
                out = ps.sample_prior(key, 1)
            else:
                out = tpe_jax._state_dispatch(
                    buf, key, 1, None, plain, fused
                )
            v, a = jax.device_get(out)
            got = dense_to_vals(
                ps, np.asarray(v)[:, 0], np.asarray(a)[:, 0]
            )
            assert got == vals
            buf.add(dict(got), loss_fn(got))


# ---------------------------------------------------------------------------
# engine units
# ---------------------------------------------------------------------------


def test_slot_capacity_schedule():
    assert slot_capacity(1, 64) == 4
    assert slot_capacity(4, 64) == 4
    assert slot_capacity(5, 64) == 8
    assert slot_capacity(33, 64) == 64
    assert slot_capacity(100, 64) == 64
    assert slot_capacity(3, 2) == 2


def test_dense_to_vals_types_match_cast_vals():
    ps = compile_space(SPACE)
    col_v = np.zeros(ps.n_dims, np.float32)
    col_a = np.ones(ps.n_dims, bool)
    for i, d in enumerate(ps.cont_idx):
        col_v[d] = 1.25
    for d in ps.cat_idx:
        col_v[d] = 2.0
    vals = dense_to_vals(ps, col_v, col_a)
    for d in ps.cat_idx:
        assert isinstance(vals[ps.labels[d]], int)
    for d in ps.cont_idx:
        assert isinstance(vals[ps.labels[d]], float)
    # inactive dims are omitted (conditional-branch contract)
    col_a[:] = False
    assert dense_to_vals(ps, col_v, col_a) == {}


def test_apply_delta_masked_is_apply_or_identity():
    import jax
    import jax.numpy as jnp

    from hyperopt_tpu.ops.kernels import apply_delta, apply_delta_masked

    D, cap = 3, 8
    rng = np.random.default_rng(0)
    state = (
        jnp.asarray(rng.normal(size=(D, cap)).astype(np.float32)),
        jnp.asarray(rng.random((D, cap)) > 0.5),
        jnp.asarray(rng.normal(size=cap).astype(np.float32)),
        jnp.asarray(np.arange(cap) < 5),
    )
    vcol = jnp.asarray(rng.normal(size=D).astype(np.float32))
    acol = jnp.ones(D, bool)
    loss, idx = jnp.float32(0.5), jnp.int32(5)

    on = apply_delta_masked(*state, vcol, acol, loss, idx, True)
    ref = apply_delta(*state, vcol, acol, loss, idx)
    for a, b in zip(jax.device_get(on), jax.device_get(ref)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    off = apply_delta_masked(*state, vcol, acol, loss, idx, False)
    for a, b in zip(jax.device_get(off), jax.device_get(state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_tell_is_idempotent_by_tid():
    svc = SuggestService(SPACE, max_batch=4, background=False, **ALGO_KW)
    h = svc.create_study("idem", seed=1)
    vals = {"x": 0.1, "lr": 0.2, "q": 3.0, "c": 0}
    h.tell(0, 1.0, vals=vals)
    h.tell(0, 1.0, vals=vals)  # re-told (lost ack); absorbed once
    st = svc.scheduler.study("idem")
    assert st.buf.count == 1
    assert st.n_tells == 1


def test_serve_package_lints_clean():
    """The CI/tooling satellite: the serve subsystem is graftlint-clean
    on its own (no baseline, no suppressions needed)."""
    import os

    from hyperopt_tpu.analysis import lint_paths

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    result = lint_paths(
        [os.path.join(repo, "hyperopt_tpu", "serve")], root=repo
    )
    assert not result.findings, [
        f"{f.path}:{f.line} {f.rule} {f.message}" for f in result.findings
    ]


def test_serve_registered_in_ir_manifest():
    """The CI/tooling satellite: the batched program families are
    registered and pinned in the committed contracts manifest."""
    import os

    from hyperopt_tpu.analysis.ir import load_contracts
    from hyperopt_tpu.ops.compile import registered_programs

    specs = registered_programs()
    for name in ("serve.batched_step", "serve.batched_anneal_step",
                 "serve.batched_apply_delta"):
        assert name in specs, name
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    manifest = load_contracts(
        os.path.join(repo, "program_contracts.json")
    )["programs"]
    assert manifest["serve.batched_step"]["donation"] == [1, 2, 3, 4]
    assert manifest["serve.batched_anneal_step"]["donation"] == [1, 2, 3, 4]
    assert manifest["serve.batched_apply_delta"]["donation"] == [0, 1, 2, 3]


# ---------------------------------------------------------------------------
# the socket transport
# ---------------------------------------------------------------------------


def test_socket_transport_roundtrip():
    from hyperopt_tpu.serve.service import serve_forever

    svc = SuggestService(
        SPACE, background=True, max_wait_ms=1.0,
        n_startup_jobs=2, **ALGO_KW,
    )
    server = serve_forever(svc, port=0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        host, port = server.server_address[:2]
        with socket.create_connection((host, port), timeout=10) as sock:
            f = sock.makefile("rw")

            def rpc(**req):
                f.write(json.dumps(req) + "\n")
                f.flush()
                return json.loads(f.readline())

            assert rpc(op="ping")["pong"]
            assert rpc(op="create_study", name="demo", seed=3)["ok"]
            assert rpc(op="studies")["studies"] == ["demo"]
            for _ in range(3):
                r = rpc(op="ask", study="demo")
                assert r["ok"], r
                assert rpc(
                    op="tell", study="demo", tid=r["tid"],
                    loss=loss_fn(r["vals"]),
                )["ok"]
            best = rpc(op="best", study="demo")
            assert best["ok"] and best["best"]["loss"] >= 0
            assert not rpc(op="ask", study="nope")["ok"]
            assert not rpc(op="frobnicate")["ok"]
            assert rpc(op="close_study", study="demo")["ok"]
            # the migration wire op: handoff evicts the local handle,
            # so a follow-up ask is a typed UnknownStudy -- the
            # router's cue to lazily re-adopt on the ring owner
            assert rpc(op="create_study", name="mig", seed=5)["ok"]
            ho = rpc(op="handoff_study", study="mig")
            assert ho["ok"] and ho["handed_off"] == "mig"
            gone = rpc(op="ask", study="mig")
            assert not gone["ok"]
            assert gone["error_type"] == "UnknownStudy"
    finally:
        server.shutdown()
        server.server_close()
        svc.shutdown()


def test_console_script_space_loader():
    from hyperopt_tpu.serve.service import _load_space

    space = _load_space("hyperopt_tpu.models.synthetic:mixed_space")
    ps = compile_space(space)
    assert ps.n_dims > 0
    with pytest.raises(SystemExit):
        _load_space("no_colon_here")
