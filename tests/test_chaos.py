"""Deterministic chaos suite for the distributed layer.

Seeded :class:`FaultPlan` replays against LIVE queue+worker stacks
(real directories, real rename CAS, real heartbeat threads -- no
mocks): transient ESTALE/EIO storms, torn writes, latency, and
simulated process death at every named crash point of the protocol.
The invariants under test are the distributed tier's two promises
(FAILURES.md): **no job is ever lost** and **no job is ever
double-completed**.

Everything here is deterministic by construction -- fixed plan seeds,
burst-bounded injection (so retries always converge), no real sleeps
above 50 ms -- and runs in the fast tier under the wall-clock pin.
"""

import collections
import errno
import json
import os
import pickle
import signal
import threading
import time
import types

import pytest

from hyperopt_tpu import hp, rand
from hyperopt_tpu.base import Domain, JOB_STATE_DONE
from hyperopt_tpu.distributed import FileJobQueue, FileTrials
from hyperopt_tpu.distributed import _common
from hyperopt_tpu.distributed import fsck
from hyperopt_tpu.distributed.faults import (
    CRASH_POINTS,
    FaultPlan,
    FaultyFS,
    SimulatedCrash,
)
from hyperopt_tpu.distributed.filequeue import worker_owner
from hyperopt_tpu.distributed.worker import (
    GracefulDrain,
    main_worker_helper,
    run_one,
)
from hyperopt_tpu.exceptions import (
    FatalBackendError,
    TransientBackendError,
)

pytestmark = pytest.mark.chaos


# ---------------------------------------------------------------------------
# harness pieces
# ---------------------------------------------------------------------------


def _chaos_objective(x):
    return float(x)


def make_doc(tid, exp_key=None):
    return {
        "tid": tid,
        "state": 0,
        "spec": None,
        "result": {"status": "new"},
        "misc": {"tid": tid, "cmd": None, "idxs": {"x": [tid]},
                 "vals": {"x": [0.5]}},
        "exp_key": exp_key,
        "owner": None,
        "version": 0,
        "book_time": None,
        "refresh_time": None,
    }


class CountingFS(FaultyFS):
    """FaultyFS that additionally counts successful renames into done/
    -- the duplicate-DONE detector: a tid renamed into done/ more than
    once across the whole run means a stale worker double-published."""

    def __init__(self, plan, done_counter):
        super().__init__(plan)
        self.done_counter = done_counter

    def rename(self, src, dst):
        super().rename(src, dst)  # only counts if the rename happened
        if (
            os.path.basename(os.path.dirname(dst)) == "done"
            and dst.endswith(".json")
        ):
            self.done_counter[os.path.basename(dst)] += 1


def _drain_worker(dirpath, fs, name, stop, reserve_timeout=0.3):
    """One simulated worker process: reap + run_one in a loop, treating
    SimulatedCrash as process death + supervisor restart (fresh queue
    object, claims left for the reaper) and transient-exhausted OSErrors
    as a mount outage to back off from."""
    queue = FileJobQueue(dirpath, fs=fs)
    owner = f"{worker_owner()}/{name}"
    bad_tids = _common.TTLSet(ttl=0.3)
    while not stop.is_set():
        try:
            queue.reap(reserve_timeout)
            ran = run_one(
                queue, owner, heartbeat=reserve_timeout / 3.0,
                exclude_tids=bad_tids.current(),
            )
        except SimulatedCrash:
            queue = FileJobQueue(dirpath, fs=fs)  # the restart
            continue
        except OSError:
            time.sleep(0.01)
            continue
        except Exception as e:
            tid = getattr(e, "failed_tid", None)
            if tid is None:
                raise
            bad_tids.add(tid)
            time.sleep(0.005)
            continue
        if not ran:
            time.sleep(0.005)


def _publish_with_driver_restarts(publish, docs, dirpath):
    """Drive the publish loop like a crash-looping driver: a
    SimulatedCrash mid-publish is followed by a 'restart' that
    re-publishes exactly the docs that never made it into the queue."""
    try:
        publish(docs)
    except SimulatedCrash:
        for doc in docs:
            name = f"{doc['tid']}.json"
            if not any(
                os.path.exists(os.path.join(dirpath, sub, name))
                for sub in ("new", "running", "done")
            ):
                _publish_with_driver_restarts(publish, [doc], dirpath)


# ---------------------------------------------------------------------------
# THE acceptance scenario: driver + 2 workers, 50 jobs, faults at every
# named crash point plus a 10% transient-error rate -- zero lost jobs,
# zero duplicate DONE docs, on both of two same-seeded runs
# ---------------------------------------------------------------------------


def _run_chaos_scenario(tmp_path, seed, tag, n_jobs=50):
    dirpath = str(tmp_path / f"q-{tag}")
    root_plan = FaultPlan(
        seed=seed, rate=0.10, errors=(errno.ESTALE, errno.EIO),
        latency=0.001, partial_rate=0.05, burst=2,
    )
    done_counter = collections.Counter()

    driver_plan = root_plan.split("driver")
    driver_plan.arm("after_publish_tmp_before_rename", at=7)
    # hit 1 is the initial Domain publish; hit 2 the late attachment
    driver_plan.arm("after_attach_fsync_before_rename", at=2)
    driver_fs = CountingFS(driver_plan, done_counter)

    worker_plans = [root_plan.split(f"worker{i}") for i in range(2)]
    for p in worker_plans:
        # every worker-side crash point, one-shot per worker
        p.arm("after_claim_utime_before_rename")
        p.arm("after_claim_rename_before_write")
        p.arm("after_done_tmp_before_rename")
        p.arm("after_done_rename_before_unlink")
        p.arm("before_complete")
        p.arm("after_unreserve_utime_before_rename")
        p.arm("after_reap_utime_before_rename")

    trials = FileTrials(dirpath, reserve_timeout=0.5, refresh=False,
                        fs=driver_fs)
    space = hp.uniform("x", 0, 1)
    domain = Domain(_chaos_objective, space)

    def set_attachment_with_restarts(key, blob):
        while True:  # a crash mid-write is followed by a retry: the
            try:     # one-shot point fires at most once, so this ends
                trials.attachments[key] = blob
                return
            except SimulatedCrash:
                continue

    set_attachment_with_restarts("FMinIter_Domain", pickle.dumps(domain))
    docs = rand.suggest(trials.new_trial_ids(n_jobs), domain, trials,
                        seed=seed)
    # tid 0 names a Domain attachment that does not exist yet: every
    # worker that claims it must give it back (the unreserve path, and
    # the armed after_unreserve crash) until the driver publishes it
    docs[0]["misc"]["cmd"] = ("domain_attachment", "FMinIter_Domain.late")
    try:
        trials.insert_trial_docs(docs)
    except SimulatedCrash:
        # the restarted driver's memory store is intact (docs are
        # recorded before transport publish); re-publish at the
        # transport level exactly the docs that never reached the queue
        from hyperopt_tpu.base import SONify

        _publish_with_driver_restarts(
            lambda ds: [trials.queue.publish(SONify(d)) for d in ds],
            [d for d in docs if not any(
                os.path.exists(os.path.join(dirpath, sub, f"{d['tid']}.json"))
                for sub in ("new", "running", "done")
            )],
            dirpath,
        )

    stop = threading.Event()
    workers = [
        threading.Thread(
            target=_drain_worker,
            args=(dirpath, CountingFS(worker_plans[i], done_counter),
                  f"w{i}", stop),
            daemon=True,
        )
        for i in range(2)
    ]
    for w in workers:
        w.start()
    try:
        time.sleep(0.3)
        # the late Domain lands (through the armed attach crash + retry)
        set_attachment_with_restarts(
            "FMinIter_Domain.late",
            pickle.dumps(Domain(_chaos_objective, space)),
        )

        check = FileJobQueue(dirpath)  # invariant observer, real fs
        deadline = time.time() + 120
        while time.time() < deadline:
            counts = check.counts()
            if counts["done"] >= n_jobs and counts["running"] == 0 \
                    and counts["new"] == 0:
                break
            time.sleep(0.05)
    finally:
        stop.set()
        for w in workers:
            w.join(timeout=30)

    # ---- the invariants -------------------------------------------------
    done = check.done_docs()
    assert set(done) == {d["tid"] for d in docs}, "a job was lost"
    assert check.counts() == {"new": 0, "running": 0, "done": n_jobs}
    assert all(d["state"] == JOB_STATE_DONE for d in done.values())
    # zero duplicate DONE publishes: every done file was renamed into
    # done/ exactly once across driver + both workers + all restarts
    assert done_counter == {f"{tid}.json": 1 for tid in done}, (
        "duplicate DONE publish detected"
    )
    # the driver's own refresh converges under the same fault rate
    trials.refresh()
    assert sum(t["state"] == JOB_STATE_DONE for t in trials.trials) == n_jobs
    # every named crash point fired somewhere in the run
    fired = collections.Counter()
    for p in [driver_plan] + worker_plans:
        for k, v in p.stats.items():
            if k.startswith("crash:"):
                fired[k.split(":", 1)[1]] += v
    for point in CRASH_POINTS:
        assert fired[point] >= 1, f"crash point {point} never exercised"
    return {
        "done_tids": set(done),
        "done_counter": dict(done_counter),
        "driver_log_head": driver_plan.log[:50],
    }


def test_chaos_50_jobs_two_workers_every_crash_point(tmp_path):
    """Acceptance: faults at every named crash point + 10% transient
    rate; driver + 2 workers; 50 jobs; zero lost, zero duplicated --
    and the same holds on a second run with the same seed (the plans
    re-issue the same schedule)."""
    r1 = _run_chaos_scenario(tmp_path, seed=1234, tag="run1")
    r2 = _run_chaos_scenario(tmp_path, seed=1234, tag="run2")
    assert r1["done_tids"] == r2["done_tids"]
    assert r1["done_counter"] == r2["done_counter"]
    # the single-threaded driver phase is bitwise-deterministic: the
    # same seed produced the same fault schedule
    assert r1["driver_log_head"] == r2["driver_log_head"]


def test_chaos_smoke_12_jobs_two_workers(tmp_path):
    """Fast-tier twin of the acceptance scenario (12 jobs): the same
    crash-point coverage and invariants on a budget."""
    _run_chaos_scenario(tmp_path, seed=99, tag="smoke", n_jobs=12)


# ---------------------------------------------------------------------------
# per-crash-point recovery, single worker
# ---------------------------------------------------------------------------

_WORKER_POINTS = [
    "after_publish_tmp_before_rename",
    "after_claim_utime_before_rename",
    "after_claim_rename_before_write",
    "after_done_tmp_before_rename",
    "after_done_rename_before_unlink",
    "after_reap_utime_before_rename",
    "before_complete",
]


@pytest.mark.parametrize("point", _WORKER_POINTS)
def test_crash_point_recovery_exactly_once(tmp_path, point):
    """A worker killed at ``point`` loses nothing: after reaping, a
    restarted worker completes every job exactly once."""
    dirpath = str(tmp_path / "q")
    plan = FaultPlan(seed=5)  # no random faults: isolate the crash
    plan.arm(point)
    done_counter = collections.Counter()
    fs = CountingFS(plan, done_counter)
    queue = FileJobQueue(dirpath, fs=fs)
    queue.attachments["FMinIter_Domain"] = pickle.dumps(
        Domain(_chaos_objective, hp.uniform("x", 0, 1))
    )
    docs = [make_doc(0), make_doc(1)]
    _publish_with_driver_restarts(
        lambda ds: [queue.publish(d) for d in ds], docs, dirpath
    )
    if point == "after_reap_utime_before_rename":
        # the reap crash needs a stale claim to recycle: claim one and
        # abandon it (a heartbeat-less dead worker)
        assert queue.reserve("abandoner") is not None

    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            # tiny reap timeout + a beat-free run_one: a claim left by
            # the crash ages past 50 ms and is recycled on the next pass
            time.sleep(0.06)
            queue.reap(0.05)
            if not run_one(queue, worker_owner()):
                counts = queue.counts()
                if counts["done"] == 2 and counts["running"] == 0:
                    break
        except SimulatedCrash:
            queue = FileJobQueue(dirpath, fs=fs)  # the restart

    assert plan.stats[f"crash:{point}"] == 1, "the armed point never fired"
    done = queue.done_docs()
    assert set(done) == {0, 1}
    assert {k: v for k, v in done_counter.items()} == {
        "0.json": 1, "1.json": 1,
    }, "a DONE doc was published more than once"
    assert queue.counts() == {"new": 0, "running": 0, "done": 2}


def test_crash_point_unreserve_recovery(tmp_path):
    """Death mid-unreserve (giving back a job whose Domain would not
    load) strands the claim at worst -- the reaper recycles it and the
    job still completes exactly once."""
    dirpath = str(tmp_path / "q")
    plan = FaultPlan(seed=6)
    plan.arm("after_unreserve_utime_before_rename")
    done_counter = collections.Counter()
    queue = FileJobQueue(dirpath, fs=CountingFS(plan, done_counter))
    doc = make_doc(0)
    doc["misc"]["cmd"] = ("domain_attachment", "FMinIter_Domain.late")
    queue.publish(doc)

    with pytest.raises(SimulatedCrash):  # claim, fail to load, die giving back
        run_one(queue, worker_owner())
    assert queue.counts()["running"] == 1  # stranded claim, not lost
    # the attachment appears, the claim ages out, a fresh worker drains
    queue.attachments["FMinIter_Domain.late"] = pickle.dumps(
        Domain(_chaos_objective, hp.uniform("x", 0, 1))
    )
    time.sleep(0.06)
    assert queue.reap(0.05) == 1
    assert run_one(queue, worker_owner())
    assert dict(done_counter) == {"0.json": 1}
    assert queue.counts() == {"new": 0, "running": 0, "done": 1}


def test_attachment_write_is_crash_consistent(tmp_path):
    """The FileAttachments satellite: the blob write fsyncs BEFORE the
    rename (torn-publish protection), and a crash between the two
    leaves the previous value fully intact -- never a truncated pickle."""
    plan = FaultPlan(seed=7)
    fs = plan.fs()
    queue = FileJobQueue(str(tmp_path / "q"), fs=fs)
    queue.attachments["blob"] = b"v1" * 100

    # protocol order: the fsync of the tmp file precedes its rename
    ops = [(op, key) for op, key, _ in plan.log if op in ("fsync", "rename")]
    fsyncs = [i for i, (op, k) in enumerate(ops) if op == "fsync"]
    renames = [i for i, (op, k) in enumerate(ops) if op == "rename"]
    assert fsyncs and renames and fsyncs[0] < renames[0]

    plan.arm("after_attach_fsync_before_rename")
    with pytest.raises(SimulatedCrash):
        queue.attachments["blob"] = b"v2" * 100
    # the crash left the OLD value complete -- not empty, not truncated
    assert queue.attachments["blob"] == b"v1" * 100
    queue.attachments["blob"] = b"v2" * 100  # the retry lands
    assert queue.attachments["blob"] == b"v2" * 100


# ---------------------------------------------------------------------------
# heartbeat loss / lost-claim detection (satellite)
# ---------------------------------------------------------------------------

_GATE = threading.Event()
_STARTED = threading.Event()


def _gated_objective(x):
    _STARTED.set()
    assert _GATE.wait(10), "test gate never opened"
    return float(x)


def test_heartbeat_loss_mid_eval_yields_exactly_one_done(tmp_path, caplog):
    """The claim file vanishes mid-evaluation (a reap): the beat thread
    stops cleanly, the stale worker DROPS its result at completion
    time, and the job's eventual state is exactly one DONE doc -- from
    the re-run."""
    _GATE.clear()
    _STARTED.clear()
    dirpath = str(tmp_path / "q")
    queue = FileJobQueue(dirpath)
    queue.attachments["FMinIter_Domain"] = pickle.dumps(
        Domain(_gated_objective, hp.uniform("x", 0, 1))
    )
    queue.publish(make_doc(0))

    n_threads = threading.active_count()
    worker = threading.Thread(
        target=run_one, args=(queue, "stale-worker"),
        kwargs={"heartbeat": 0.02}, daemon=True,
    )
    worker.start()
    assert _STARTED.wait(10)
    # the reap transition happens under the evaluating worker: its
    # claim moves back to new/ (heartbeat lost on the next tick)
    os.utime(os.path.join(dirpath, "running", "0.json"))
    os.rename(
        os.path.join(dirpath, "running", "0.json"),
        os.path.join(dirpath, "new", "0.json"),
    )
    time.sleep(0.08)  # a few beat intervals: the thread notices and stops
    with caplog.at_level("WARNING", logger="hyperopt_tpu.distributed.worker"):
        _GATE.set()
        worker.join(timeout=10)
    assert not worker.is_alive()
    # the stale worker published NOTHING
    assert queue.counts()["done"] == 0
    assert any("claim lost" in r.message for r in caplog.records)
    # the heartbeat thread is gone (stopped cleanly, not leaked)
    assert threading.active_count() <= n_threads
    # the re-run (a healthy worker) produces the one and only DONE doc
    assert run_one(queue, "healthy-worker")
    done = queue.done_docs()
    assert set(done) == {0}
    assert done[0]["state"] == JOB_STATE_DONE
    assert done[0]["owner"] == "healthy-worker"
    assert queue.counts() == {"new": 0, "running": 0, "done": 1}


def test_reap_releases_completed_claim_instead_of_recycling(tmp_path):
    """A worker dead between DONE publish and claim release must not
    cause a re-evaluation: reap() releases the claim when the DONE doc
    already exists."""
    dirpath = str(tmp_path / "q")
    plan = FaultPlan(seed=8)
    plan.arm("after_done_rename_before_unlink")
    queue = FileJobQueue(dirpath, fs=plan.fs())
    queue.attachments["FMinIter_Domain"] = pickle.dumps(
        Domain(_chaos_objective, hp.uniform("x", 0, 1))
    )
    queue.publish(make_doc(0))
    with pytest.raises(SimulatedCrash):
        run_one(queue, worker_owner())
    # DONE is published AND the claim is still held by the dead worker
    assert queue.counts() == {"new": 0, "running": 1, "done": 1}
    time.sleep(0.06)
    assert queue.reap(0.05) == 0  # released, NOT recycled into new/
    assert queue.counts() == {"new": 0, "running": 0, "done": 1}


# ---------------------------------------------------------------------------
# retry scaffold units
# ---------------------------------------------------------------------------


def test_with_retries_transient_errno_converges():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError(errno.ESTALE, "stale handle")
        return "ok"

    delays = []
    assert _common.with_retries(flaky, sleep=delays.append) == "ok"
    assert len(calls) == 3
    assert all(d <= 0.05 for d in delays)
    assert delays == sorted(delays)  # exponential, capped


def test_with_retries_gives_up_after_attempts():
    calls = []

    def always():
        calls.append(1)
        raise OSError(errno.EIO, "io error")

    with pytest.raises(OSError):
        _common.with_retries(always, attempts=4, sleep=lambda _: None)
    assert len(calls) == 4


def test_with_retries_protocol_signals_not_retried():
    for exc in (FileNotFoundError("gone"), json.JSONDecodeError("x", "", 0),
                FatalBackendError("corrupt"), KeyError("k")):
        calls = []

        def once(exc=exc):
            calls.append(1)
            raise exc

        with pytest.raises(type(exc)):
            _common.with_retries(once, sleep=lambda _: None)
        assert len(calls) == 1, f"{type(exc).__name__} was retried"


def test_with_retries_typed_transient_and_mongo_names():
    assert _common.is_transient(TransientBackendError("blip"))
    assert not _common.is_transient(FatalBackendError("dead"))
    AutoReconnect = type("AutoReconnect", (Exception,), {})
    assert _common.is_transient(AutoReconnect("primary stepped down"))
    NetworkTimeout = type("NetworkTimeout", (AutoReconnect,), {})
    assert _common.is_transient(NetworkTimeout("slow"))
    assert not _common.is_transient(RuntimeError("bug"))
    assert not _common.is_transient(OSError(errno.EPERM, "denied"))


# ---------------------------------------------------------------------------
# FaultPlan determinism
# ---------------------------------------------------------------------------


def _exercise_plan(tmp_path, plan, tag):
    fs = plan.fs()
    # same BASENAME in different parents: decision keys are basenames,
    # so the two runs must present identical keys
    d = tmp_path / f"det-{tag}" / "det"
    os.makedirs(d, exist_ok=True)
    for i in range(40):
        path = str(d / f"f{i}.json")
        try:
            with fs.open(path, "w") as f:
                f.write("{}")
            fs.utime(path)
            fs.stat(path)
            fs.rename(path, path + ".moved")
            fs.listdir(str(d))
            fs.unlink(path + ".moved")
        except OSError:
            pass
    return list(plan.log)


def test_fault_plan_same_seed_same_schedule(tmp_path):
    p1 = FaultPlan(seed=42, rate=0.3, partial_rate=0.2, burst=3)
    p2 = FaultPlan(seed=42, rate=0.3, partial_rate=0.2, burst=3)
    p3 = FaultPlan(seed=43, rate=0.3, partial_rate=0.2, burst=3)
    log1 = _exercise_plan(tmp_path, p1, "a")
    log2 = _exercise_plan(tmp_path, p2, "b")
    log3 = _exercise_plan(tmp_path, p3, "c")
    assert log1 == log2
    assert log1 != log3
    assert any(d.startswith("errno=") for _, _, d in log1)


def test_fault_plan_split_is_stable_and_independent():
    p = FaultPlan(seed=9, rate=0.5)
    a1, a2 = p.split("workerA"), p.split("workerA")
    b = p.split("workerB")
    assert a1.seed == a2.seed != b.seed
    # derived seeds are crc-stable, not hash()-salted
    assert a1.seed == FaultPlan(seed=9).split("workerA").seed


def test_fault_plan_burst_bounds_consecutive_failures(tmp_path):
    """rate=1.0 with burst=2 still converges: at most 2 consecutive
    injected failures per (op, file), so attempt 3 of the retry
    scaffold always lands."""
    plan = FaultPlan(seed=1, rate=1.0, burst=2)
    fs = plan.fs()
    path = str(tmp_path / "x")
    with open(path, "w") as f:
        f.write("hi")
    failures = 0
    for _ in range(2):
        with pytest.raises(OSError):
            fs.stat(path)
        failures += 1
    fs.stat(path)  # the third consecutive call MUST succeed
    assert failures == 2


def test_single_worker_drain_is_trace_deterministic(tmp_path):
    """End-to-end determinism: the same seed against the same job
    sequence produces the identical injection trace and outcome."""

    def one_run(tag):
        plan = FaultPlan(seed=77, rate=0.2, latency=0.0, burst=2)
        queue = FileJobQueue(str(tmp_path / f"q-{tag}"), fs=plan.fs())
        queue.attachments["FMinIter_Domain"] = pickle.dumps(
            Domain(_chaos_objective, hp.uniform("x", 0, 1))
        )
        for tid in range(6):
            queue.publish(make_doc(tid))
        drained = 0
        deadline = time.time() + 30
        while drained < 6 and time.time() < deadline:
            try:
                if run_one(queue, "det-worker"):
                    drained += 1
            except OSError:
                pass
        return list(plan.log), set(queue.done_docs())

    log1, done1 = one_run("a")
    log2, done2 = one_run("b")
    assert done1 == done2 == set(range(6))
    assert log1 == log2


# ---------------------------------------------------------------------------
# worker CLI hardening: SIGTERM drain + crash-loop guard
# ---------------------------------------------------------------------------

_SIGTERM_SENT = threading.Event()


def _self_sigterm_objective(x):
    if not _SIGTERM_SENT.is_set():
        _SIGTERM_SENT.set()
        os.kill(os.getpid(), signal.SIGTERM)
    return float(x)


def test_sigterm_drains_gracefully(tmp_path):
    """SIGTERM mid-evaluation: the in-flight job FINISHES and is
    published, then the loop exits 0 leaving the remaining queue
    intact -- nothing stranded in running/, nothing half-written."""
    _SIGTERM_SENT.clear()
    dirpath = str(tmp_path / "q")
    queue = FileJobQueue(dirpath)
    queue.attachments["FMinIter_Domain"] = pickle.dumps(
        Domain(_self_sigterm_objective, hp.uniform("x", 0, 1))
    )
    for tid in range(3):
        queue.publish(make_doc(tid))
    options = types.SimpleNamespace(
        dir=dirpath, exp_key=None, max_jobs=None, poll_interval=0.01,
        reserve_timeout=5.0, last_job_timeout=10.0, workdir=None,
        max_crash_loop=5,
    )
    prev = signal.getsignal(signal.SIGTERM)
    try:
        rc = main_worker_helper(options)
    finally:
        signal.signal(signal.SIGTERM, prev)
    assert rc == 0
    assert _SIGTERM_SENT.is_set()
    counts = queue.counts()
    assert counts["done"] == 1  # the in-flight job finished
    assert counts["running"] == 0  # nothing stranded
    assert counts["new"] == 2  # the rest left for other workers


def test_crash_loop_guard_exits_loudly(tmp_path):
    """Persistent NON-transient failure: the worker backs off a bounded
    number of times, then exits with rc 2 instead of spinning (or dying
    on attempt one and getting supervisor-restarted forever)."""
    dirpath = str(tmp_path / "q")
    FileJobQueue(dirpath)  # create the layout with a healthy fs
    plan = FaultPlan(seed=1, rate=1.0, errors=(errno.EPERM,), burst=None,
                     ops=("listdir",))
    options = types.SimpleNamespace(
        dir=dirpath, exp_key=None, max_jobs=None, poll_interval=0.002,
        reserve_timeout=None, last_job_timeout=10.0, workdir=None,
        max_crash_loop=3, fs=plan.fs(),
    )
    prev = signal.getsignal(signal.SIGTERM)
    try:
        rc = main_worker_helper(options)
    finally:
        signal.signal(signal.SIGTERM, prev)
    assert rc == 2
    assert plan.stats["error:listdir"] >= 3


def test_transient_outage_backs_off_then_recovers(tmp_path):
    """A transient burst that outlives the per-op retries costs the
    loop backoff, not the process: once the mount 'heals', the worker
    drains normally and exits via last_job_timeout with rc 0."""
    dirpath = str(tmp_path / "q")
    queue = FileJobQueue(dirpath)
    queue.attachments["FMinIter_Domain"] = pickle.dumps(
        Domain(_chaos_objective, hp.uniform("x", 0, 1))
    )
    queue.publish(make_doc(0))
    # 12 guaranteed consecutive ESTALEs on the reserve scan (> the 5
    # retry attempts), then a healthy mount
    outage = {"left": 12}

    class HealingFS(FaultyFS):
        def listdir(self, path):
            if outage["left"] > 0:
                outage["left"] -= 1
                raise OSError(errno.ESTALE, "injected outage")
            return super().listdir(path)

    options = types.SimpleNamespace(
        dir=dirpath, exp_key=None, max_jobs=1, poll_interval=0.002,
        reserve_timeout=None, last_job_timeout=5.0, workdir=None,
        max_crash_loop=10, fs=HealingFS(FaultPlan(seed=1)),
    )
    prev = signal.getsignal(signal.SIGTERM)
    try:
        rc = main_worker_helper(options)
    finally:
        signal.signal(signal.SIGTERM, prev)
    assert rc == 0
    assert outage["left"] == 0
    assert queue.counts()["done"] == 1


# ---------------------------------------------------------------------------
# fsck: audit + repair
# ---------------------------------------------------------------------------


def _corrupt_queue(tmp_path):
    """Hand-built corruption covering every issue kind."""
    dirpath = str(tmp_path / "q")
    queue = FileJobQueue(dirpath)
    queue.attachments["FMinIter_Domain"] = pickle.dumps(
        Domain(_chaos_objective, hp.uniform("x", 0, 1))
    )
    for tid in range(4):
        queue.publish(make_doc(tid))
    # job 0 completed normally...
    assert run_one(queue, worker_owner())
    done0 = os.path.join(dirpath, "done", "0.json")
    # job 1: orphaned claim (dead worker, stale mtime) -- reserved
    # BEFORE the duplicate fixture below, or reserve's done-check
    # self-healing would retire the planted duplicate first
    claimed = queue.reserve("dead-worker")
    assert claimed["tid"] == 1
    old = time.time() - 3600
    os.utime(os.path.join(dirpath, "running", "1.json"), (old, old))
    # job 0 "recycled" into new/ (duplicate_tid) and re-claimed into
    # running/ (completed_claim)
    import shutil
    shutil.copy(done0, os.path.join(dirpath, "new", "0.json"))
    shutil.copy(done0, os.path.join(dirpath, "running", "0.json"))
    # job 2: half-written doc (torn write on a non-atomic FS)
    with open(os.path.join(dirpath, "new", "2.json"), "w") as f:
        f.write('{"tid": 2, "state"')
    # stale tmp residue
    tmp = os.path.join(dirpath, "done", "9.json.tmp.123")
    with open(tmp, "w") as f:
        f.write("{}")
    os.utime(tmp, (old, old))
    return dirpath, queue


def test_fsck_audit_detects_every_corruption_kind(tmp_path):
    dirpath, _ = _corrupt_queue(tmp_path)
    issues = fsck.audit(dirpath, reserve_timeout=60.0, tmp_grace=60.0)
    kinds = {i.kind for i in issues}
    assert kinds == {
        "stale_tmp", "half_written", "orphaned_claim", "completed_claim",
        "duplicate_tid",
    }
    assert fsck.main(["--dir", dirpath]) == 1  # issues, no repair


def test_fsck_repair_then_fresh_worker_drains(tmp_path, capsys):
    dirpath, queue = _corrupt_queue(tmp_path)
    rc = fsck.main([
        "--dir", dirpath, "--repair", "--reserve-timeout", "60",
        "--tmp-grace", "60",
    ])
    assert rc == 0
    capsys.readouterr()
    # post-repair: audit is clean, the completed job was NOT resurrected
    assert fsck.audit(dirpath, reserve_timeout=60.0, tmp_grace=60.0) == []
    done_before = queue.done_docs()
    assert set(done_before) == {0}
    # a fresh worker drains what remains (jobs 1 and 3; job 2 was
    # quarantined as unrecoverable, job 0 must not re-run)
    while run_one(queue, "fresh-worker"):
        pass
    done = queue.done_docs()
    assert set(done) == {0, 1, 3}
    assert queue.counts() == {"new": 0, "running": 0, "done": 3}
    assert done[0]["owner"] != "fresh-worker"  # not re-evaluated
    assert os.path.exists(os.path.join(dirpath, "quarantine"))


def test_fsck_repairs_crash_fixture_corruption(tmp_path):
    """Acceptance: a queue directory corrupted by the crash-point
    fixtures is restored by ``fsck --repair`` to a state a fresh worker
    drains completely -- every job exactly one DONE doc."""
    dirpath = str(tmp_path / "q")
    seed_queue = FileJobQueue(dirpath)
    seed_queue.attachments["FMinIter_Domain"] = pickle.dumps(
        Domain(_chaos_objective, hp.uniform("x", 0, 1))
    )
    crash_points = [
        "after_publish_tmp_before_rename",
        "after_claim_rename_before_write",
        "after_done_tmp_before_rename",
        "after_done_rename_before_unlink",
        "before_complete",
    ]
    done_counter = collections.Counter()
    for tid, point in enumerate(crash_points):
        plan = FaultPlan(seed=tid).arm(point)
        queue = FileJobQueue(dirpath, fs=CountingFS(plan, done_counter))
        try:
            queue.publish(make_doc(tid))
            run_one(queue, f"doomed-{tid}")
        except SimulatedCrash:
            pass
        assert plan.stats[f"crash:{point}"] == 1
    time.sleep(0.06)  # age the stranded claims past the orphan bound

    rc = fsck.main([
        "--dir", dirpath, "--repair", "--reserve-timeout", "0.05",
        "--tmp-grace", "0",
    ])
    assert rc == 0
    # a fresh, fault-free worker drains the repaired directory
    fresh = FileJobQueue(dirpath, fs=CountingFS(FaultPlan(0), done_counter))
    while run_one(fresh, "fresh-worker"):
        pass
    done = fresh.done_docs()
    # the publish-crash job (tid 0) never entered the queue -- its
    # driver must re-publish; every job that WAS enqueued completes
    # exactly once, nothing is stranded
    assert set(done) == set(range(1, len(crash_points)))
    assert all(done_counter[f"{tid}.json"] == 1 for tid in done)
    assert fresh.counts()["new"] == 0 and fresh.counts()["running"] == 0
    assert fsck.audit(dirpath, reserve_timeout=60.0, tmp_grace=60.0) == []


# ---------------------------------------------------------------------------
# mongo backend: lost-claim CAS + AutoReconnect retries (doubles)
# ---------------------------------------------------------------------------


@pytest.fixture
def fake_mongo(monkeypatch):
    from fake_backends import install_fake_mongo

    return install_fake_mongo(monkeypatch)


def _mongo_jobs():
    from hyperopt_tpu.distributed.mongo import MongoJobs

    return MongoJobs.new_from_connection_str("localhost:27017/chaosdb")


def test_mongo_complete_require_claim_drops_reaped(fake_mongo):
    from hyperopt_tpu.base import JOB_STATE_NEW, JOB_STATE_RUNNING

    jobs = _mongo_jobs()
    jobs.publish(make_doc(0))
    doc = jobs.reserve("w1")
    assert doc["state"] == JOB_STATE_RUNNING and doc.get("claim")
    # the claim is reaped mid-evaluation...
    time.sleep(0.02)
    assert jobs.reap(0.01) == 1
    # ...so the stale worker's CAS writeback matches nothing
    assert jobs.complete(
        doc, result={"status": "ok", "loss": 0.5}, require_claim=True
    ) is False
    current = jobs.coll.find_one({"tid": 0})
    assert current["state"] == JOB_STATE_NEW  # still queued for the re-run
    assert current.get("result", {}).get("loss") != 0.5
    # the re-run holds a FRESH claim token and ITS writeback lands
    doc2 = jobs.reserve("w2")
    assert doc2["claim"] != doc["claim"]
    assert jobs.complete(
        doc2, result={"status": "ok", "loss": 0.7}, require_claim=True
    ) is True
    assert jobs.coll.find_one({"tid": 0})["result"]["loss"] == 0.7


def test_mongo_reserve_retries_autoreconnect(fake_mongo):
    AutoReconnect = type("AutoReconnect", (Exception,), {})
    jobs = _mongo_jobs()
    jobs.publish(make_doc(0))
    real_coll = jobs.coll
    blips = {"left": 2, "seen": 0}

    class FlakyColl:
        def __getattr__(self, name):
            real = getattr(real_coll, name)
            if name != "find_one_and_update":
                return real

            def flaky(*a, **k):
                if blips["left"] > 0:
                    blips["left"] -= 1
                    blips["seen"] += 1
                    raise AutoReconnect("primary stepped down")
                return real(*a, **k)

            return flaky

    jobs.coll = FlakyColl()
    doc = jobs.reserve("w1")  # survives two reconnect blips
    assert doc is not None and doc["tid"] == 0
    assert blips["seen"] == 2
