"""graftstorm acceptance (ISSUE 18): the fleet survives a HOSTILE
network, not just a dead replica.

THE scenario: a three-replica serve fleet behind the TCP router, real
sockets end to end, under a seeded storm -- 10% connection resets
mid-frame, injected latency, truncate-then-close, a slow-loris client,
and a black-hole partition of one backend (partitioned-but-ALIVE: the
replica process keeps running and is fenced by claim epochs, distinct
from ``die()``).  The workload must complete with

* ZERO lost / ZERO duplicate tells -- asserted live on the replicas'
  buffers AND by a cold WAL audit from nothing but the shared root;
* every suggestion stream bitwise identical to the same-seed NO-FAULT
  run through the identical topology;
* only typed errors client-visible (the retry/dedup machinery absorbs
  every transport fault; the driver never catches anything raw);
* the whole scenario replaying bitwise across two same-seed runs,
  injected-fault schedule included.

Plus the socket-hygiene satellites: typed ``NetworkTimeout`` /
``PeerUnreachable`` at the dial seam, connection-cap refusal and idle
reaping on both TCP fronts, and the ``NET_CRASH_POINTS`` send/ack
windows proving the exactly-once resubmission discipline.
"""

import json
import socket
import threading
import time

import pytest

from hyperopt_tpu import hp
from hyperopt_tpu.client import RemoteStudy
from hyperopt_tpu.distributed.faults import (
    NET_CRASH_POINTS,
    NetFaultPlan,
    SimulatedCrash,
)
from hyperopt_tpu.exceptions import (
    NetworkTimeout, Overloaded, PeerUnreachable,
)
from hyperopt_tpu.serve import SuggestService
from hyperopt_tpu.serve.frames import FrameConn, dial
from hyperopt_tpu.serve.router import RouterServer, _Backend
from hyperopt_tpu.serve.service import serve_forever

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _lockdep_armed(monkeypatch):
    from hyperopt_tpu.analysis import lockdep

    dep = lockdep.arm_scheduler_class(monkeypatch)
    yield dep
    assert dep.inversions == 0, dep.errors


SPACE = {
    "x": hp.uniform("x", -5, 5),
    "c": hp.choice("c", [0, 1, 2]),
}
ALGO_KW = dict(n_cand=8, n_cand_cat=4)
RIDS = ("r0", "r1", "r2")
NAMES = ("s00", "s01", "s02")
R = 4  # ask+tell rounds per study the workload must end with, exactly


def loss_fn(vals):
    return (vals["x"] - 1) ** 2 / 10 + 0.1 * vals["c"]


def _spawn(server):
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return t


# ---------------------------------------------------------------------------
# THE storm acceptance scenario
# ---------------------------------------------------------------------------


def _storm_fleet(root, router_plan=None):
    """Three replica serve processes (shared root, claim-fenced) behind
    real TCP fronts, one RouterServer front over them."""
    services, servers, backends = {}, {}, []
    for rid in RIDS:
        svc = SuggestService(
            SPACE, root=root, owner=rid, background=True, max_batch=8,
            n_startup_jobs=2, **ALGO_KW,
        )
        srv = serve_forever(svc, port=0)
        _spawn(srv)
        services[rid] = svc
        servers[rid] = srv
        host, port = srv.server_address[:2]
        backends.append(_Backend(rid, host, port))
    router = RouterServer(
        backends, salt="storm", read_timeout=5.0, probe_timeout=2.0,
        net_plan=router_plan,
    )
    rsrv = router.serve_forever(port=0)
    _spawn(rsrv)
    return services, servers, router, rsrv


def _teardown_fleet(services, servers, rsrv):
    rsrv.shutdown()
    rsrv.server_close()
    for rid in RIDS:
        servers[rid].shutdown()
        servers[rid].server_close()
        services[rid].shutdown()


def _run_scenario(root, client_plan=None, router_plan=None):
    """Drive the R-round workload; with plans armed, round 2 runs
    against a partitioned backend (failover) and rounds 3..R against
    the healed rejoiner (OwnershipLost adoption).  Returns (streams,
    final live state, summed client stats, victim rid)."""
    storm = router_plan is not None
    services, servers, router, rsrv = _storm_fleet(
        root, router_plan=router_plan
    )
    host, port = rsrv.server_address[:2]
    victim = router.ring.owner(NAMES[0])
    if client_plan is not None:
        # one client writes slow-loris style on top of the shared rates
        client_plan.slow_loris(f"client/{NAMES[-1]}")
    clients = {}
    streams = {n: [] for n in NAMES}
    try:
        for i, n in enumerate(NAMES):
            clients[n] = RemoteStudy(
                host, port, n, seed=100 + i, net_plan=client_plan,
                read_timeout=5.0,
            )

        def round_():
            for n in NAMES:
                c = clients[n]
                tid, vals = c.ask(timeout=30)
                c.tell(tid, loss_fn(vals), vals)
                streams[n].append((tid, json.dumps(vals, sort_keys=True)))

        round_()  # round 1: the storm rates alone
        if storm:
            router_plan.partition(victim)
        round_()  # round 2: black-holed backend -> NetworkTimeout -> failover
        if storm:
            assert victim in router._alive_excluded(), (
                "the partition never tripped the failover path"
            )
            assert router_plan.stats["net:blackhole_read"] > 0
            router_plan.heal(victim)
            router.probe_backends()  # probe-recovered: rejoins the ring
            assert victim not in router._alive_excluded()
        for _ in range(R - 2):
            round_()  # the healed zombie re-claims via takeover adoption

        state = {}
        for n in NAMES:
            rid = router.ring.owner(n, exclude=router._alive_excluded())
            st = services[rid].scheduler.study(n)
            state[n] = {
                "owner": rid,
                "count": int(st.buf.count),
                "tids": st.buf.tids[: st.buf.count].tolist(),
                "losses": st.buf.losses[: st.buf.count].tolist(),
                "wal_total_tells": st.persist.wal.total_tells,
            }
        stats = {}
        for c in clients.values():
            for k, v in c.stats.items():
                stats[k] = stats.get(k, 0) + v
    finally:
        for c in clients.values():
            c.close()
        _teardown_fleet(services, servers, rsrv)
    return streams, state, stats, victim


def _cold_audit(root):
    """Re-materialize every study from nothing but its WAL+bundle pair
    in the shared root: the independent zero-lost/zero-dup proof."""
    audit = SuggestService(
        SPACE, root=root, owner="audit", background=False, max_batch=16,
        n_startup_jobs=2, **ALGO_KW,
    )
    cold = {}
    for n in NAMES:
        h = audit.create_study(n, takeover=True)
        assert h.n_tells == R, (n, h.n_tells)
        cold[n] = audit.scheduler.study(n).buf.tids[:R].tolist()
    audit.shutdown()
    return cold


def _assert_zero_lost_zero_duplicate(state):
    for n, d in state.items():
        assert d["count"] == R, (n, d)
        assert len(set(d["tids"])) == R, f"{n}: duplicate tid absorbed"
        assert d["wal_total_tells"] == R, (
            f"{n}: WAL logged {d['wal_total_tells']} tells for {R} "
            "applied -- lost or duplicated"
        )


def _storm_plans(rep):
    """Same seeds every rep: the schedule must replay bitwise."""
    client_plan = NetFaultPlan(
        seed=18, reset_rate=0.10, latency=0.002, truncate_rate=0.05,
        burst=2,
    )
    router_plan = NetFaultPlan(seed=180)  # the partition/heal switch
    return client_plan, router_plan


def test_fleet_storm_acceptance(tmp_path):
    """THE graftstorm acceptance scenario (see module docstring)."""
    clean_streams, clean_state, clean_stats, _ = _run_scenario(
        str(tmp_path / "clean")
    )
    assert clean_stats.get("transport_errors", 0) == 0
    _assert_zero_lost_zero_duplicate(clean_state)

    runs = []
    for rep in range(2):
        root = str(tmp_path / f"storm-{rep}")
        client_plan, router_plan = _storm_plans(rep)
        streams, state, stats, victim = _run_scenario(
            root, client_plan=client_plan, router_plan=router_plan
        )
        # the storm actually stormed, and the client absorbed it
        assert client_plan.stats["net:reset"] > 0
        assert stats["transport_errors"] > 0
        assert stats["retries"] > 0
        # only typed errors client-visible: nothing raw escaped the
        # retry loop (the drive completing proves it), and the only
        # typed refusal a client may surface mid-storm is backpressure
        surfaced = {
            k for k in stats if k.startswith("typed:")
        } - {"typed:Overloaded"}
        assert not surfaced, surfaced
        _assert_zero_lost_zero_duplicate(state)
        # cold WAL audit agrees with the live counters, tid for tid
        cold = _cold_audit(root)
        for n in NAMES:
            assert cold[n] == state[n]["tids"], n
        runs.append((streams, state, list(client_plan.log), victim))

    for streams, state, _log, victim in runs:
        # the partitioned replica was the placement's, not an accident
        assert victim == RIDS[0] or victim in RIDS
        # bitwise the same-seed no-fault run: resets, failover, heal,
        # and rejoin all stream-invisible
        assert streams == clean_streams
        for n in NAMES:
            assert state[n]["tids"] == clean_state[n]["tids"], n
            assert state[n]["losses"] == clean_state[n]["losses"], n
    # and the whole scenario -- injected-fault schedule included --
    # replays bitwise across two same-seed runs
    assert runs[0][0] == runs[1][0]
    assert runs[0][2] == runs[1][2], "the fault schedule diverged"
    assert runs[0][3] == runs[1][3]


# ---------------------------------------------------------------------------
# the NET crash points: lost-ack exactly-once on a single serve front
# ---------------------------------------------------------------------------


def _tcp_service(root=None, **kw):
    svc = SuggestService(
        SPACE, root=root, background=True, max_batch=8, n_startup_jobs=2,
        **ALGO_KW, **kw,
    )
    srv = serve_forever(svc, port=0)
    _spawn(srv)
    return svc, srv


def _teardown(svc, srv):
    srv.shutdown()
    srv.server_close()
    svc.shutdown()


def test_net_crash_points_registered():
    from hyperopt_tpu.distributed.faults import ALL_CRASH_POINTS

    assert set(NET_CRASH_POINTS) <= set(ALL_CRASH_POINTS)
    assert set(NET_CRASH_POINTS) == {
        "net_client_after_send_before_reply",
        "net_client_after_reply_before_deliver",
    }
    with pytest.raises(ValueError):
        NetFaultPlan().arm("not_a_point")


def test_lost_reply_ask_recovers_exactly_once(tmp_path):
    """``net_client_after_reply_before_deliver`` on an ask: the reply
    arrived -- the service committed tid N -- but the client died
    before acting on it.  A restarted client's ``recover=True`` ask
    re-delivers tid N bitwise instead of burning a fresh seed."""
    svc, srv = _tcp_service(root=str(tmp_path / "ask"))
    host, port = srv.server_address[:2]
    plan = NetFaultPlan(seed=0)
    try:
        c1 = RemoteStudy(host, port, "s", seed=7, net_plan=plan)
        tid0, vals0 = c1.ask(timeout=30)
        c1.tell(tid0, loss_fn(vals0), vals0)
        plan.arm("net_client_after_reply_before_deliver", at=1)
        with pytest.raises(SimulatedCrash):
            c1.ask(timeout=30)  # the reply window: served, never seen
        assert plan.stats[
            "crash:net_client_after_reply_before_deliver"
        ] == 1
        # the "restarted" client process
        c2 = RemoteStudy(host, port, "s", create=False)
        reply = c2.call({
            "op": "ask", "study": "s", "timeout": 30, "recover": True,
        })
        assert reply["tid"] == tid0 + 1  # the crashed ask's tid, re-served
        c2.tell(reply["tid"], loss_fn(reply["vals"]), reply["vals"])
        st = svc.scheduler.study("s")
        assert st.persist.wal.total_tells == 2
        assert st.buf.tids[:2].tolist() == [tid0, tid0 + 1]
        c2.close()
    finally:
        _teardown(svc, srv)


def test_lost_ack_tell_resubmission_dedups_exactly_once(tmp_path):
    """``net_client_after_send_before_reply`` on a tell: the bytes hit
    the wire -- the service applies the tell -- but the ack never came
    back.  The restarted client's re-tell (explicit vals, same tid) is
    absorbed exactly once by the WAL tid-dedup."""
    svc, srv = _tcp_service(root=str(tmp_path / "tell"))
    host, port = srv.server_address[:2]
    plan = NetFaultPlan(seed=1)
    try:
        c1 = RemoteStudy(host, port, "s", seed=7, net_plan=plan)
        tid, vals = c1.ask(timeout=30)
        plan.arm("net_client_after_send_before_reply", at=1)
        with pytest.raises(SimulatedCrash):
            c1.tell(tid, loss_fn(vals), vals)  # sent, applied, unacked
        # wait for the server to absorb the already-sent tell before
        # the resubmission races it
        st = svc.scheduler.study("s")
        deadline = time.perf_counter() + 10
        while st.persist.wal.total_tells < 1:
            assert time.perf_counter() < deadline
            time.sleep(0.005)
        c2 = RemoteStudy(host, port, "s", create=False)
        c2.tell(tid, loss_fn(vals), vals)  # the lost-ack resubmission
        assert st.persist.wal.total_tells == 1  # absorbed exactly once
        assert int(st.buf.count) == 1
        c2.close()
    finally:
        _teardown(svc, srv)


# ---------------------------------------------------------------------------
# socket hygiene: typed deadlines and bounded fronts
# ---------------------------------------------------------------------------


def test_hung_peer_surfaces_network_timeout():
    """An accepting-but-silent peer: the read misses its deadline and
    surfaces typed NetworkTimeout, never a stranded thread."""
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    host, port = lsock.getsockname()
    try:
        sock, f = dial(host, port, read_timeout=0.2)
        f.write(b'{"op": "ping"}\n')
        f.flush()
        with pytest.raises(NetworkTimeout):
            f.readline()
        f.close()
        sock.close()
    finally:
        lsock.close()


def test_refused_connect_surfaces_peer_unreachable():
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    host, port = lsock.getsockname()
    lsock.close()  # nobody listens here now
    with pytest.raises(PeerUnreachable):
        dial(host, port, connect_timeout=0.5)


def test_serve_front_connection_cap_typed_refusal():
    """One past ``max_conns`` gets a typed Overloaded refusal on the
    hello line, not an unbounded accept; a freed slot serves again."""
    svc = SuggestService(
        SPACE, background=True, max_batch=8, n_startup_jobs=2, **ALGO_KW,
    )
    srv = serve_forever(svc, port=0, max_conns=1)
    _spawn(srv)
    addr = srv.server_address[:2]
    try:
        s1 = socket.create_connection(addr, timeout=10)
        c1 = FrameConn(s1.makefile("rwb"))  # holds the only slot
        assert c1.call({"op": "ping"})["pong"] is True
        s2 = socket.create_connection(addr, timeout=10)
        with pytest.raises(Overloaded) as ei:
            FrameConn(s2.makefile("rwb"))
        assert ei.value.reason == "max_connections"
        assert ei.value.retry_after is not None
        s2.close()
        c1.close()
        s1.close()
        # the slot frees (handler teardown is async): a retrying
        # client gets back in
        deadline = time.perf_counter() + 10
        while True:
            s3 = socket.create_connection(addr, timeout=10)
            try:
                c3 = FrameConn(s3.makefile("rwb"))
            except Overloaded:
                s3.close()
                assert time.perf_counter() < deadline
                time.sleep(0.01)
                continue
            assert c3.call({"op": "ping"})["pong"] is True
            c3.close()
            s3.close()
            break
    finally:
        _teardown(svc, srv)


def test_router_front_connection_cap_typed_refusal():
    router = RouterServer(
        [_Backend("b0", "127.0.0.1", 1)], max_conns=1
    )
    rsrv = router.serve_forever(port=0)
    _spawn(rsrv)
    addr = rsrv.server_address[:2]
    try:
        s1 = socket.create_connection(addr, timeout=10)
        f1 = s1.makefile("rwb")
        f1.write(b'{"op": "ping"}\n')
        f1.flush()
        assert json.loads(f1.readline())["pong"] is True
        s2 = socket.create_connection(addr, timeout=10)
        f2 = s2.makefile("rwb")
        refusal = json.loads(f2.readline())
        assert refusal["error_type"] == "Overloaded"
        assert refusal["reason"] == "max_connections"
        f2.close()
        s2.close()
        f1.close()
        s1.close()
    finally:
        rsrv.shutdown()
        rsrv.server_close()


def test_idle_timeout_reaps_half_open_client():
    """A connected-but-silent client is reaped at the idle deadline:
    the handler thread returns instead of blocking forever on a
    half-open socket."""
    svc = SuggestService(
        SPACE, background=True, max_batch=8, n_startup_jobs=2, **ALGO_KW,
    )
    srv = serve_forever(svc, port=0, idle_timeout=0.3)
    _spawn(srv)
    try:
        sock = socket.create_connection(srv.server_address[:2], timeout=10)
        sock.settimeout(10.0)
        # say nothing: the server must hang up on US
        assert sock.recv(64) == b""
        sock.close()
    finally:
        _teardown(svc, srv)
