"""bench.py is the driver's measurement contract: it must always print
exactly one valid JSON line with the expected schema. Run it small, on
the hermetic CPU platform, as a real subprocess."""

import json
import os

import pytest
import subprocess
import sys


@pytest.mark.slow
def test_bench_prints_one_json_line():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_BATCH"] = "16"
    env["BENCH_N_CAND"] = "16"
    env["BENCH_N_OBS"] = "60"
    env["BENCH_N_TRIALS"] = "40"
    env["BENCH_OBS_SWEEP"] = "60,120"  # CI-sized obs-scaling sweep
    env["BENCH_SERVE_STUDIES"] = "8"  # CI-sized serve batch
    env["BENCH_SERVE_ROUNDS"] = "3"
    env["BENCH_BURST_CLIENTS"] = "32"  # CI-sized concurrent-client burst
    env["BENCH_ASHA_FLAT"] = "32"  # CI-sized graftrung sweep pair
    env["BENCH_ASHA_EVALS"] = "64"
    env["BENCH_ASHA_BATCH"] = "8"
    env["BENCH_STORM_REPLICAS"] = "2"  # CI-sized hostile-network fleet
    env["BENCH_STORM_STUDIES"] = "3"
    env["BENCH_STORM_ROUNDS"] = "4"
    out = subprocess.run(
        [sys.executable, "bench.py"],
        capture_output=True, text=True, timeout=1200, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, out.stdout
    d = json.loads(lines[0])
    for k in ("metric", "value", "unit", "vs_baseline", "platform", "batch"):
        assert k in d, d
    assert d["metric"] == "tpe_suggestions_per_sec_20dim_mixed"
    assert d["value"] > 0 and d["vs_baseline"] > 0
    assert d["unit"] == "suggestions/s"
    # the second headline metric (BASELINE.json): wall-clock to best @ 1k
    assert d["seconds_to_best_at_1k"] > 0
    assert d["best_loss_at_1k"] >= 0
    assert d["seconds_to_best_at_1k_spec8"] > 0
    assert d["n_trials_1k"] == 40
    assert d["speculative_suggest_per_sec"] > 0
    # round-20 graftclient rows: fmin-as-serve-client replaces the
    # retired solo sync regime (single_suggest_sync_per_sec is GONE),
    # and the client stream is bitwise the solo driver's -- same seed,
    # same experiment, so the quality row must MATCH exactly
    assert "single_suggest_sync_per_sec" not in d
    assert d["seconds_to_best_at_1k_client"] > 0
    assert d["fmin_client_asks_per_sec"] > 0
    assert d["fmin_ask_ahead_depth"] == 4
    assert d["best_loss_at_1k_client"] == d["best_loss_at_1k"]
    # round-14: the device-loop family is stamped on EVERY backend,
    # keyed by backend so rounds stay comparable within one
    assert d["device_loop_trials_per_sec"] > 0
    assert d["device_loop_config"]["backend"] == "cpu"
    assert d["device_loop_seconds_at_1k"] > 0
    assert d["device_loop_seq_seconds_at_1k"] > 0
    # round-14 compiled-objective rows: fmin(compiled=True) wall-clock
    # on the same experiment as the host sequential headline, HPO over
    # a real vmapped training loop (TrainableObjective), and the
    # io_callback observability cost
    assert d["seconds_to_best_at_1k_compiled"] > 0
    assert d["best_loss_at_1k_compiled"] >= 0
    assert d["compiled_vs_host_speedup_x"] > 0
    assert d["mlp_tune_trials_per_sec"] > 0
    assert d["mlp_tune_config"]["backend"] == "cpu"
    assert d["device_loop_callback_overhead_frac"] >= 0
    # round-24 graftrung rows (compile_fmin(asha=)): the fused-ASHA
    # time-to-quality pair is stamped on every backend -- both
    # wall-clocks measured, the ratio defined whenever both sweeps hit
    # the shared quality target, and the config keyed by backend so
    # rounds stay comparable
    assert d["compiled_asha_seconds_to_quality"] > 0
    assert d["compiled_flat_seconds_to_quality"] > 0
    assert d["compiled_asha_vs_flat_speedup_x"] is None or (
        d["compiled_asha_vs_flat_speedup_x"] > 0
    )
    assert d["compiled_asha_best_loss"] >= 0
    assert d["compiled_asha_reached_flat_best"] in (True, False)
    assert d["compiled_asha_config"]["backend"] == "cpu"
    assert d["compiled_asha_config"]["n_evals_asha"] == 64
    assert d["compiled_asha_config"]["eta"] == 2
    # round-5 fields: cache stamp always present; asha-on-device keys
    # exist (None off-accelerator)
    assert d["compilation_cache"] in (True, False)
    assert "asha_device_seconds" in d and "asha_device_speedup_x" in d
    # round-6: the obs-scaling sweep stamps compacted + full-width
    # throughput per history size, plus the active compaction cap
    assert d["above_cap"] > 0
    assert [r["n_obs"] for r in d["obs_scaling"]] == [60, 120]
    for r in d["obs_scaling"]:
        assert r["suggestions_per_sec"] > 0
        assert r["full_width_suggestions_per_sec"] > 0
        assert r["compaction_speedup_x"] > 0
    # round-7: resident-history traffic/dispatch contract rows, counted
    # deterministically (BENCH_r06 comparable to r01-r05 plus these)
    assert d["single_suggest_fused_sync_per_sec"] > 0
    assert d["dispatches_per_trial"] == 1.0
    rows = d["host_to_device_bytes_per_ask"]
    assert [r["n_obs"] for r in rows] == [60, 120]
    for r in rows:
        assert r["resident_bytes_per_ask"] > 0
        # the delta tell is O(D); a full re-upload is O(bucket * D)
        assert (
            r["full_reupload_bytes_per_ask"] > r["resident_bytes_per_ask"]
        )
    # flat in n_obs: the acceptance contract (within 2x across sizes)
    res = [r["resident_bytes_per_ask"] for r in rows]
    assert max(res) <= 2 * min(res)
    # round-9: graftlint trend rows -- a healthy tree has zero
    # unbaselined findings; the grandfathered baseline was burned to
    # zero in round 11 and must stay there
    assert d["lint_findings_total"] == 0
    assert d["lint_baseline_size"] == 0
    # round-11: graftir contract rows -- every registered
    # dispatch-critical program family IR-checked, zero drift against
    # the committed program_contracts.json
    assert d["ir_programs_checked"] >= 10
    assert d["ir_contract_drift"] == 0
    # round-16: graftrace concurrency rows -- the GL5xx pack over the
    # whole package reports zero unbaselined findings, all seven rules
    # ran, and the lockdep probe caught exactly its one deliberate
    # inversion (proof the runtime sanitizer is armed and detecting)
    assert d["trace_findings_total"] == 0
    assert d["trace_rules_checked"] == 7
    assert d["lockdep_inversions_observed"] == 1
    # round-20: graftwire protocol rows -- both fronts' op surfaces
    # checked, zero drift against the committed wire_contracts.json,
    # and EVERY registered crash point armed by some test (the GL604
    # no-dead-fault-windows satellite, pinned at exactly 1.0)
    assert d["wire_ops_checked"] >= 15
    assert d["wire_contract_drift"] == 0
    assert d["crash_points_armed_frac"] == 1.0
    # round-10: crash-recovery cost rows -- the per-trial durability
    # overhead is measured (WAL append + amortized bundle publish) and
    # stamped both raw and relative to the fused dispatch time
    assert d["resume_overhead_per_trial"] >= 0
    assert d["resume_overhead_frac_of_fused"] >= 0
    # round-12: multi-tenant serve rows -- studies/sec out of one
    # slotted batch, latency percentiles, occupancy, and the
    # continuous-batching speedup over the one-tenant rate
    assert d["serve_studies_per_sec"] > 0
    assert d["serve_ask_p99_ms"] >= d["serve_ask_p50_ms"] > 0
    assert 0 < d["serve_batch_occupancy"] <= 1.0
    assert d["serve_vs_solo_speedup_x"] > 0
    assert d["serve_batch"] == 8
    # round-13: graftguard rows -- overload shedding really shed, the
    # NaN tenant really accrued its K trips, the watchdog really timed
    # a hung dispatch out and recovered
    assert 0 < d["serve_shed_rate"] < 1
    assert d["serve_quarantine_count"] == 3
    assert d["serve_watchdog_recovery_ms"] > 0
    # round-18 graftfleet rows: router-aggregated throughput, the
    # replica-kill window's p99, and measured failover recovery
    assert d["fleet_studies_per_sec"] > 0
    assert d["fleet_ask_p99_ms_failover"] > 0
    assert d["fleet_recovery_ms"] > 0
    assert d["fleet_replicas"] == 3
    # round-21 graftpilot rows: the autoscaler's actuation latencies
    # really measured, aggregate throughput while the fleet runs under
    # the control loop, and the recorded flight log replaying to
    # bitwise-identical suggestion streams
    assert d["pilot_scale_out_ms"] > 0
    assert d["pilot_scale_in_ms"] > 0
    assert d["fleet_studies_per_sec_autoscaled"] > 0
    assert d["replay_fidelity"] == 1.0
    # round-22 graftburst rows: concurrent binary-frame clients on one
    # served engine -- aggregate throughput, the group-commit fsync
    # amortization (per-tell fsync would stamp >= 1.0; group commit
    # must stay well under it), and co-batched round occupancy. The
    # graftclient sequential headline must not regress under the
    # shared-service regime: fmin_client_asks_per_sec stays a
    # positive stamped row (asserted > 0 above) on every round.
    assert d["fleet_asks_per_sec_concurrent"] > 0
    assert 0 <= d["wal_fsyncs_per_tell"] < 0.9
    assert 0 < d["client_cobatch_occupancy"] <= 1.0
    assert d["burst_config"]["n_clients"] == 32
    # round-23 graftstorm rows: the routed fleet under the seeded
    # client-wire storm plus a mid-run partition+heal -- throughput
    # stays positive with faults armed, faulted-op recovery is a real
    # measurement (0.0 only when the storm injected nothing), and the
    # absorption rate is a sane per-op fraction
    assert d["fleet_asks_per_sec_under_storm"] > 0
    assert d["net_fault_recovery_ms"] >= 0
    assert 0 <= d["net_typed_error_rate"] < 1
    assert d["storm_config"]["n_replicas"] == 2
    # round-19 graftscope rows: tracing-armed overhead fractions
    # (deterministic zero-extra-dispatch half pinned in test_obs.py;
    # these are the measured wall-clock halves), span throughput, and
    # one fleet-wide /metrics scrape through a live TCP router
    assert d["obs_overhead_frac_serve"] >= 0
    assert d["obs_overhead_frac_fused"] >= 0
    assert d["obs_events_per_sec"] > 0
    assert d["metrics_scrape_ms_fleet"] > 0
    # round-17: graftmesh rows -- per-mesh-shape throughput of the
    # study-sharded serve engine and the shard_map PBT schedule, keyed
    # by mesh shape, plus the scaling-efficiency diagnostic per family
    serve_mesh = d["serve_studies_per_sec_mesh"]
    assert set(serve_mesh) == {"study=1", "study=2", "study=4"}
    assert all(v > 0 for v in serve_mesh.values())
    pbt_mesh = d["pbt_member_steps_per_sec_mesh"]
    assert set(pbt_mesh) == {"trial=1", "trial=2", "trial=4"}
    assert all(v > 0 for v in pbt_mesh.values())
    eff = d["mesh_scaling_efficiency"]
    assert set(eff) == {"serve", "pbt"}
    assert set(eff["serve"]) == {"study=2", "study=4"}
    assert set(eff["pbt"]) == {"trial=2", "trial=4"}
    assert all(v > 0 for fam in eff.values() for v in fam.values())
