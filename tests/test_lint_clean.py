"""Tier-1 gate: graftlint over the whole package must be clean against
the committed baseline -- and fast enough to live in the fast tier.

This is the static half of the invariant story: the retrace guard, the
chaos suite, and the wallclock pin catch violations at RUN time; this
test catches them at DIFF time, before any program ever compiles.
"""

import os
import time

import pytest

from hyperopt_tpu.analysis import (
    RULES,
    format_text,
    lint_paths,
    load_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "hyperopt_tpu")
BASELINE = os.path.join(REPO, "lint_baseline.json")

# the baseline is grandfathered debt: it may shrink, it must not grow.
# Raising this number in a diff is the signal to stop and fix instead.
# Burned to ZERO in PR 7 (the 4 GL303 worker-CLI sleeps now route
# through _common.retry_delay): the whole package lints clean with no
# grandfathered findings, and it stays that way.
MAX_BASELINE_ENTRIES = 0


@pytest.fixture
def repo_cwd(monkeypatch):
    # finding paths are cwd-relative; pin cwd so they match the
    # committed baseline's repo-root-relative keys
    monkeypatch.chdir(REPO)


def test_package_lints_clean_against_baseline(repo_cwd):
    baseline = load_baseline(BASELINE)
    t0 = time.perf_counter()
    result = lint_paths(["hyperopt_tpu"], baseline=baseline)
    elapsed = time.perf_counter() - t0
    assert result.clean, "\n" + format_text(result)
    # engine speed is part of the contract: the fast tier runs under a
    # 9-minute wallclock pin and the lint pass must be noise inside it
    assert elapsed < 5.0, f"lint took {elapsed:.2f}s (budget 5s)"
    assert result.n_files > 50  # the whole package, not a subset


def test_package_trace_clean_against_baseline(repo_cwd):
    # the graftrace concurrency gate (hyperopt-tpu-lint --trace): the
    # whole package must be GL5xx-clean against the committed baseline
    # -- every deliberate pattern carries an inline reasoned pragma,
    # and the baseline holds zero grandfathered concurrency findings
    baseline = load_baseline(BASELINE)
    t0 = time.perf_counter()
    result = lint_paths(["hyperopt_tpu"], baseline=baseline, pack="trace")
    elapsed = time.perf_counter() - t0
    assert result.clean, "\n" + format_text(result)
    # fast-tier budget: the concurrency pass must stay cheap noise
    # inside the 9-minute session pin
    assert elapsed < 10.0, f"trace lint took {elapsed:.2f}s (budget 10s)"
    assert result.n_files > 50  # the whole package, not a subset


def test_package_wire_clean_against_contracts(repo_cwd):
    # the graftwire protocol gate (hyperopt-tpu-lint --wire): the wire
    # surfaces must match the committed wire_contracts.json, every
    # ServeError subclass must be mapped at the client seam, and EVERY
    # registered crash point must be armed by some test -- with zero
    # grandfathered findings
    from hyperopt_tpu.analysis.wire import check_wire

    baseline = load_baseline(BASELINE)
    t0 = time.perf_counter()
    result = check_wire(baseline=baseline)
    elapsed = time.perf_counter() - t0
    assert result.clean, result.findings
    assert elapsed < 5.0, f"wire lint took {elapsed:.2f}s (budget 5s)"
    assert result.ops_checked >= 15  # both fronts, not a subset
    assert result.crash_points_total > 0
    assert result.crash_points_armed == result.crash_points_total


def test_baseline_is_small_and_shrinking(repo_cwd):
    baseline = load_baseline(BASELINE)
    assert sum(baseline.values()) <= MAX_BASELINE_ENTRIES, (
        "the findings baseline grew -- fix the new finding or suppress "
        "it inline with a reason; the baseline is not a dumping ground"
    )


def test_every_pack_rule_has_a_fixture_pair():
    fixture_dir = os.path.join(REPO, "tests", "lint_fixtures")
    names = set()
    for root, _dirs, files in os.walk(fixture_dir):
        names.update(files)
    for rule_id in RULES:
        if rule_id in ("GL001", "GL002"):
            continue  # engine rules: pinned in test_lint_suppress.py
        if rule_id.startswith("GL4"):
            # graftir IR rules check traced programs, not source text;
            # their bad/good pairs are in-memory program captures pinned
            # by tests/test_graftir.py
            continue
        stem = rule_id.lower()
        assert f"{stem}_bad.py" in names, f"missing TP fixture for {rule_id}"
        assert f"{stem}_good.py" in names, f"missing FP fixture for {rule_id}"
