"""graftir (hyperopt-tpu-lint --ir): the jaxpr-level contract gate.

Three layers, mirroring the AST pack's tests:

* the tier-1 GATE: every registered program family checks clean against
  the committed ``program_contracts.json``, inside a 10 s CPU budget;
* registry COMPLETENESS: every jit-wrapped program family reachable
  from the dispatch-critical entry points (``suggest(fused=True)``,
  ``device_loop``, the sharded suite, resident delta tells) is claimed
  by a registered program -- an unregistered callsite fails by name;
* per-rule bad/good capture pairs with exact-count pins (the IR twin of
  ``tests/lint_fixtures/``), plus the CLI exit-code/format/cwd
  contracts.
"""

import functools
import json
import os
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONTRACTS = os.path.join(REPO, "program_contracts.json")

_CACHED_RESULT = []


def _checked():
    """One full check_programs() run shared by the gate + CLI tests
    (tracing every family costs seconds; pay once per session)."""
    if not _CACHED_RESULT:
        from hyperopt_tpu.analysis.ir import check_programs

        t0 = time.perf_counter()
        res = check_programs(contracts_path=CONTRACTS)
        _CACHED_RESULT.append((res, time.perf_counter() - t0))
    return _CACHED_RESULT[0]


# ---------------------------------------------------------------------------
# the tier-1 gate
# ---------------------------------------------------------------------------


def test_ir_gate_clean_and_fast():
    from hyperopt_tpu.analysis.report import format_ir_text

    res, elapsed = _checked()
    assert res.clean, "\n" + format_ir_text(res)
    assert res.contract_drift == 0
    # the whole registry, not a subset: every dispatch-critical family
    # the issue names (fused tell+ask x2, apply_delta, device-loop scan,
    # speculative redraw, sharded, pallas, prior, plain asks)
    assert res.programs_checked >= 10
    # fast-tier budget: tracing + lowering every family on CPU must be
    # noise inside the 9-minute wallclock pin (raised 10 -> 15 s when
    # the serve-batched families grew the registry 11 -> 14 programs,
    # 15 -> 25 s when the chunked/trainable device-loop families grew
    # it 14 -> 18 -- the train_step trace runs grad through an MLP --
    # and 25 -> 40 s when the graftmesh shard_map families grew it
    # 18 -> 22: each traces AND lowers over the forced 4-device mesh,
    # and 40 -> 55 s when the graftrung asha families grew it 23 -> 26:
    # each traces the unrolled rung ladder's full training pyramid)
    assert elapsed < 55.0, f"--ir took {elapsed:.2f}s (budget 55s)"


def test_manifest_covers_every_registered_program():
    from hyperopt_tpu.analysis.ir import load_contracts
    from hyperopt_tpu.ops.compile import registered_programs

    manifest = load_contracts(CONTRACTS)["programs"]
    specs = registered_programs()
    assert set(manifest) == set(specs), (
        "program_contracts.json out of sync with the registry: "
        f"missing {sorted(set(specs) - set(manifest))}, "
        f"stale {sorted(set(manifest) - set(specs))}"
    )
    for name, row in manifest.items():
        assert row["outputs"], name
        assert isinstance(row["flops"], int), name
        assert isinstance(row["bytes_accessed"], int), name
        assert row["const_bytes"] < (1 << 20), (
            f"{name}: baked constants within a dispatch of the GL404 "
            "threshold -- the manifest itself says re-upload hazard"
        )
    # the donated state families really pin their donation in the manifest
    for fused in ("tpe_jax.fused_tell_ask", "anneal_jax.fused_tell_ask"):
        assert manifest[fused]["donation"] == [1, 2, 3, 4], fused
    assert manifest["jax_trials.apply_delta"]["donation"] == [0, 1, 2, 3]


# ---------------------------------------------------------------------------
# registry completeness: exercised flows vs registered families
# ---------------------------------------------------------------------------


def _record_jits(monkeypatch, recorded):
    import jax

    from hyperopt_tpu.ops.compile import program_family

    real_jit = jax.jit

    def recording_jit(fun, *args, **kwargs):
        fam = program_family(fun)
        if fam.startswith("hyperopt_tpu."):
            recorded.add(fam)
        return real_jit(fun, *args, **kwargs)

    monkeypatch.setattr(jax, "jit", recording_jit)


def test_registry_covers_every_reachable_program_family(monkeypatch):
    """Drive the real dispatch-critical entry points while recording
    which hyperopt_tpu-owned callables get jit-wrapped; every recorded
    family must be claimed by a registered program, with the offender
    named in the failure."""
    import jax

    from hyperopt_tpu import fmin, hp, tpe_jax
    from hyperopt_tpu.jax_trials import JaxTrials, ObsBuffer
    from hyperopt_tpu.device_loop import compile_fmin
    from hyperopt_tpu.ops.compile import compile_space, registered_programs

    recorded = set()
    _record_jits(monkeypatch, recorded)
    # lazily-built process globals would skip their (recorded) jit wrap
    # if an earlier test already built them -- reset so this test is
    # order-independent
    from hyperopt_tpu import jax_trials as _jt
    from hyperopt_tpu.serve import batched as _sb

    monkeypatch.setattr(_jt, "_APPLY_DELTA", None)
    monkeypatch.setattr(_sb, "_BATCHED_DELTA_FN", None)
    monkeypatch.setattr(_sb, "_FINITE_CHECK_FN", None)

    space = {"a": hp.uniform("a", -2.0, 2.0), "b": hp.choice("b", [0, 1])}

    def objective(cfg):
        return float(cfg["a"]) ** 2 + float(cfg["b"])

    # 1. the fused sequential driver (suggest(fused=True) end to end)
    fmin(
        objective, space,
        algo=functools.partial(tpe_jax.suggest, fused=True,
                               n_startup_jobs=2, n_EI_candidates=8),
        max_evals=5, trials=JaxTrials(resident=True),
        rstate=np.random.default_rng(0), show_progressbar=False,
    )

    # 2. the resident delta-tell program (multi-tell backlog path)
    ps = compile_space({"a": hp.uniform("a", -1.0, 1.0)})
    buf = ObsBuffer(ps, resident=True)
    for i in range(3):
        buf.add({"a": 0.1 * i}, float(i))
    buf.device_arrays()  # materialize the mirror
    buf.add({"a": 0.5}, 3.0)
    buf.add({"a": 0.6}, 4.0)
    buf.device_arrays()  # two staged deltas -> jitted apply_delta

    # 3. every device-loop algo family (traced, not executed: tracing
    # is what constructs the nested suggest programs)
    import jax.numpy as jnp

    def dl_obj(cfg):
        t = jnp.zeros((), jnp.float32)
        for k in sorted(cfg):
            t = t + (cfg[k] - 0.5) ** 2
        return t

    for algo in ("tpe", "anneal", "atpe", "rand"):
        runner = compile_fmin(
            dl_obj, {"a": hp.uniform("a", -2.0, 2.0),
                     "b": hp.choice("b", [0, 1])},
            max_evals=4, batch_size=1, algo=algo, n_startup_jobs=2,
            n_EI_candidates=8,
        )
        cap = runner._history_capacity
        runner._compiled_run.trace(
            jax.ShapeDtypeStruct((), np.uint32),
            jax.ShapeDtypeStruct((2, cap), jnp.float32),
            jax.ShapeDtypeStruct((2, cap), jnp.bool_),
            jax.ShapeDtypeStruct((cap,), jnp.float32),
            jax.ShapeDtypeStruct((cap,), jnp.bool_),
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.float32),
        )

    # 4. the sharded suite (single-device mesh; the family is the same)
    from hyperopt_tpu.base import Domain, Trials
    from hyperopt_tpu.parallel import sharded
    from hyperopt_tpu.parallel.mesh import default_mesh

    domain = Domain(objective, space)
    trials = Trials()
    mesh = default_mesh(devices=jax.local_devices()[:1])
    fn = sharded.build_sharded_suggest_fn(
        tpe_jax.packed_space_for(domain), mesh, 8, 0.25, 25.0, 1.0,
    )
    from hyperopt_tpu.jax_trials import host_key

    fn.trace(
        host_key(0),
        jax.ShapeDtypeStruct((2, 128), jnp.float32),
        jax.ShapeDtypeStruct((2, 128), jnp.bool_),
        jax.ShapeDtypeStruct((128,), jnp.float32),
        jax.ShapeDtypeStruct((128,), jnp.bool_),
        batch=1,
    )

    registered = set()
    for spec in registered_programs().values():
        registered.update(spec.families)

    unclaimed = sorted(recorded - registered)
    assert not unclaimed, (
        "program families constructed by the dispatch-critical entry "
        "points but NOT claimed by any registered graftir program "
        f"(register them in their owning module): {unclaimed}"
    )
    # and the exercise really reached the core families (a silently
    # skipped flow must not turn this test into a tautology)
    for fam in (
        "hyperopt_tpu.tpe_jax:build_suggest_fn",
        "hyperopt_tpu.ops.kernels:apply_delta",
        "hyperopt_tpu.ops.compile:PackedSpace.sample_prior_fn",
        "hyperopt_tpu.anneal_jax:build_anneal_fn",
        "hyperopt_tpu.atpe_jax:build_atpe_device_fn",
        "hyperopt_tpu.device_loop:compile_fmin",
        "hyperopt_tpu.parallel.sharded:build_sharded_suggest_fn",
    ):
        assert fam in recorded, f"flow never constructed {fam}"


# ---------------------------------------------------------------------------
# per-rule bad/good capture pairs (exact-count pins)
# ---------------------------------------------------------------------------


def _capture(fn, *args, donate=(), static=(), allowed=(), **kwargs):
    import jax

    from hyperopt_tpu.ops.compile import ProgramCapture

    jitted = jax.jit(
        fn,
        static_argnames=static or None,
        donate_argnums=donate or None,
    )
    return ProgramCapture(
        fn=jitted, args=args, kwargs=kwargs, donate_argnums=donate,
        allowed_callbacks=allowed,
    )


def _spec(name):
    from hyperopt_tpu.ops.compile import ProgramSpec

    return ProgramSpec(
        name=name, build=None, families=(),
        path="tests/test_graftir.py", line=1,
    )


def _check(name, capture, stored=None):
    from hyperopt_tpu.analysis.ir import check_capture

    return check_capture(_spec(name), capture, stored=stored)


def _vec():
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct((8,), jnp.float32)


def test_gl401_host_callback_bad_and_good():
    import jax
    import jax.numpy as jnp

    def bad(x):
        from jax.experimental import io_callback

        jax.debug.callback(lambda v: None, x)
        y = jax.pure_callback(
            lambda v: np.asarray(v), jax.ShapeDtypeStruct(x.shape, x.dtype), x
        )
        return io_callback(
            lambda v: np.asarray(v),
            jax.ShapeDtypeStruct(x.shape, x.dtype), y,
        )

    findings, _ = _check("fixture.gl401_bad", _capture(bad, _vec()))
    assert [f.rule for f in findings] == ["GL401"] * 3  # one per primitive
    assert {"io_callback", "pure_callback", "debug_callback"} == {
        f.message.split("'")[1] for f in findings
    }

    def good(x):
        return jnp.sum(x * 2.0)

    findings, _ = _check("fixture.gl401_good", _capture(good, _vec()))
    assert findings == []


def test_gl401_declared_callback_allowlist():
    """The round-14 escape hatch: a program may DECLARE a deliberate
    host callback (allowed_callbacks) -- the chunked device loop's
    progress io_callback.  Undeclared still fails, a stale declaration
    fails, and the callback set is pinned in the contract."""
    import jax
    from jax.experimental import io_callback

    def prog(x):
        io_callback(lambda v: None, None, x.sum(), ordered=True)
        return x * 2.0

    # BAD: undeclared -> exactly one GL401, pointing at the allowlist
    findings, contract = _check(
        "fixture.gl401_allow_bad", _capture(prog, _vec())
    )
    assert [f.rule for f in findings] == ["GL401"]
    assert "allowed_callbacks" in findings[0].message
    assert contract["callbacks"] == ["io_callback"]

    # GOOD: declared -> clean, and the contract pins what was declared
    findings, contract = _check(
        "fixture.gl401_allow_good",
        _capture(prog, _vec(), allowed=("io_callback",)),
    )
    assert findings == []
    assert contract["callbacks"] == ["io_callback"]

    # STALE: a declaration the traced program no longer contains ->
    # exactly one GL401 (the allowlist is a contract, not a mute
    # button)
    def clean(x):
        return x * 2.0

    findings, contract = _check(
        "fixture.gl401_allow_stale",
        _capture(clean, _vec(), allowed=("io_callback",)),
    )
    assert [f.rule for f in findings] == ["GL401"]
    assert "stale" in findings[0].message
    assert contract["callbacks"] == []

    # and a declaration naming a non-callback primitive is itself bad
    findings, _ = _check(
        "fixture.gl401_allow_unknown",
        _capture(clean, _vec(), allowed=("device_put",)),
    )
    assert [f.rule for f in findings] == ["GL401"]
    assert "unknown" in findings[0].message

    # GL406 drift: a grown callback set against a pinned contract fails
    # with a field-level diff naming 'callbacks'
    _, fresh = _check(
        "fixture.gl401_allow_drift",
        _capture(prog, _vec(), allowed=("io_callback",)),
    )
    stale_row = dict(fresh, callbacks=[])
    findings, _ = _check(
        "fixture.gl401_allow_drift",
        _capture(prog, _vec(), allowed=("io_callback",)),
        stored=stale_row,
    )
    assert [f.rule for f in findings] == ["GL406"]
    assert "callbacks" in findings[0].message


def test_gl402_f64_promotion_bad_and_good():
    import jax.numpy as jnp

    def bad(x):
        wide = x.astype(jnp.float64)  # the silent widening under x64
        return (wide * 2.0).sum()

    findings, _ = _check("fixture.gl402_bad", _capture(bad, _vec()))
    rules = [f.rule for f in findings]
    # one finding per offending primitive: convert_element_type, mul,
    # reduce_sum all carry strong f64 avals
    assert set(rules) == {"GL402"} and len(findings) == 3

    def good(x):
        # python-scalar weak promotion is NOT a finding: 2.0 stays weak
        # and the strong f32 array wins the binop
        return (x * 2.0).sum()

    findings, _ = _check("fixture.gl402_good", _capture(good, _vec()))
    assert findings == []


def test_gl403_donation_bad_and_good():
    import jax.numpy as jnp

    def step(state, d):
        return state + d

    # BAD: the registry contract declares donation but the jit lost it
    from hyperopt_tpu.ops.compile import ProgramCapture
    import jax

    cap = ProgramCapture(
        fn=jax.jit(step), args=(_vec(), _vec()), donate_argnums=(0,),
    )
    findings, _ = _check("fixture.gl403_bad", cap)
    assert [f.rule for f in findings] == ["GL403"]
    assert "[0]" in findings[0].message and "[]" in findings[0].message

    # GOOD: declared donation present in the lowered aliasing
    findings, contract = _check(
        "fixture.gl403_good", _capture(step, _vec(), _vec(), donate=(0,))
    )
    assert findings == []
    assert contract["donation"] == [0]


def test_gl404_oversized_constant_bad_and_good():
    import jax.numpy as jnp

    big = jnp.zeros((512, 600), jnp.float32)  # ~1.2 MB baked constant

    def bad(x):
        return x.sum() + big.sum()

    findings, contract = _check("fixture.gl404_bad", _capture(bad, _vec()))
    assert [f.rule for f in findings] == ["GL404"]
    assert "float32[512,600]" in findings[0].message
    assert contract["const_bytes"] >= big.size * 4

    small = jnp.zeros((8,), jnp.float32)

    def good(x):
        return x.sum() + small.sum()

    findings, _ = _check("fixture.gl404_good", _capture(good, _vec()))
    assert findings == []


def test_gl405_mid_program_transfer_bad_and_good():
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]

    def bad(x):
        pinned = jax.device_put(x, dev)  # explicit mid-program placement
        return pinned * 2.0

    findings, _ = _check("fixture.gl405_bad", _capture(bad, _vec()))
    assert [f.rule for f in findings] == ["GL405"]

    def good(x):
        # jnp.asarray emits a target-less device_put (alias semantics,
        # no transfer) -- must NOT be flagged
        return jnp.asarray([1.0, 2.0], jnp.float32).sum() + x.sum()

    findings, _ = _check("fixture.gl405_good", _capture(good, _vec()))
    assert findings == []


def test_gl406_contract_drift_bad_and_good():
    import jax.numpy as jnp

    def prog(x):
        return jnp.stack([x, x * 2.0])

    _, fresh = _check("fixture.gl406", _capture(prog, _vec()))

    # GOOD: identical stored contract -> no drift
    findings, _ = _check("fixture.gl406", _capture(prog, _vec()),
                         stored=dict(fresh))
    assert findings == []

    # BAD: a stored contract from "before the shape change"
    stale = dict(fresh)
    stale["outputs"] = ["float32[3,8]"]
    stale["flops"] = (fresh["flops"] or 0) + 7
    findings, _ = _check("fixture.gl406", _capture(prog, _vec()),
                         stored=stale)
    rules = sorted(f.rule for f in findings)
    assert rules == ["GL406", "GL406"]
    drifted = {f.message.split("'")[1] for f in findings}
    assert drifted == {"outputs", "flops"}
    # the diff is readable: names the program, the field, both values
    assert all("fixture.gl406" in f.message for f in findings)
    assert any("float32[3,8]" in f.message for f in findings)


# ---------------------------------------------------------------------------
# CLI: exit codes, formats, --update-contracts, cwd-independence
# ---------------------------------------------------------------------------


def test_cli_ir_exit_codes_and_json(tmp_path, monkeypatch, capsys):
    from hyperopt_tpu.analysis.cli import main

    # clean tree against the committed manifest -> 0
    assert main(["--ir", "--contracts", CONTRACTS]) == 0
    capsys.readouterr()

    # --format json carries the bench-stamped summary fields
    assert main(["--ir", "--contracts", CONTRACTS, "--format", "json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["summary"]["programs_checked"] >= 10
    assert payload["summary"]["contract_drift"] == 0
    assert payload["findings"] == []

    # doctored manifest -> drift findings, exit 1, diff names the field
    doctored = json.loads(open(CONTRACTS).read())
    doctored["programs"]["tpe_jax.fused_tell_ask"]["flops"] += 1
    bad = tmp_path / "contracts.json"
    bad.write_text(json.dumps(doctored))
    assert main(["--ir", "--contracts", str(bad)]) == 1
    out = capsys.readouterr().out
    assert "GL406" in out and "tpe_jax.fused_tell_ask" in out
    assert "flops" in out

    # unreadable manifest -> usage error 2
    garbage = tmp_path / "garbage.json"
    garbage.write_text("{not json")
    assert main(["--ir", "--contracts", str(garbage)]) == 2
    capsys.readouterr()

    # missing manifest -> every program unpinned (exit 1), then
    # --update-contracts pins it and the check goes green (exit 0)
    fresh = tmp_path / "fresh.json"
    assert main(["--ir", "--contracts", str(fresh)]) == 1
    out = capsys.readouterr().out
    assert "no committed contract" in out
    assert main(["--ir", "--contracts", str(fresh),
                 "--update-contracts"]) == 0
    capsys.readouterr()
    assert main(["--ir", "--contracts", str(fresh)]) == 0
    capsys.readouterr()

    # --update-contracts without --ir is a usage error
    assert main(["--update-contracts"]) == 2
    capsys.readouterr()


def test_cli_findings_identical_from_any_cwd(tmp_path, monkeypatch, capsys):
    """The satellite bugfix: both the AST CLI and --ir must report the
    exact same findings whether invoked from / or from the repo root."""
    from hyperopt_tpu.analysis.cli import main

    pkg = os.path.join(REPO, "hyperopt_tpu")
    baseline = os.path.join(REPO, "lint_baseline.json")

    outputs = {}
    for cwd in ("/", REPO):
        monkeypatch.chdir(cwd)
        rc = main([pkg, "--baseline", baseline, "--format", "json"])
        assert rc == 0
        outputs[cwd] = json.loads(capsys.readouterr().out)
    assert outputs["/"] == outputs[REPO]

    ir_outputs = {}
    for cwd in ("/", REPO):
        monkeypatch.chdir(cwd)
        rc = main(["--ir", "--format", "json"])
        assert rc == 0
        ir_outputs[cwd] = json.loads(capsys.readouterr().out)
    assert ir_outputs["/"] == ir_outputs[REPO]
