"""graftrace (GL5xx) fixture corpus + mutation kill-checks + the
runtime lockdep sanitizer's own contract.

Same discipline as test_lint_rules.py: every rule's true-positive and
near-miss behavior is pinned by a bad/good fixture pair, with exact
finding counts for the multi-site fixtures.  The mutation kill-checks
prove -- with ZERO test execution, pure lint_source -- that the three
canonical concurrency mutations on a scheduler-shaped class are each
caught: a deleted ``with self._lock:`` guard (GL501), two swapped
acquisition sites (GL502), a dispatch moved under the lock (GL503).
"""

import os
import textwrap
import threading

import pytest

from hyperopt_tpu.analysis.engine import lint_source

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_fixtures")

TRACE_RULES = [
    "GL501", "GL502", "GL503", "GL504", "GL505", "GL506", "GL507",
]

#: exact finding counts for every bad fixture -- a rule that silently
#: stops seeing one of the sites regresses here
EXPECTED_COUNTS = {
    "GL501": 2, "GL502": 2, "GL503": 2, "GL504": 1,
    "GL505": 2, "GL506": 1, "GL507": 1,
}


def _lint_file(path):
    with open(path, encoding="utf-8") as f:
        source = f.read()
    findings, _ = lint_source(
        source, path=os.path.relpath(path), pack="trace"
    )
    return findings


def _trace(source, path="pkg/mod.py"):
    findings, _ = lint_source(source, path=path, pack="trace")
    return findings


@pytest.mark.parametrize("rule_id", TRACE_RULES)
def test_bad_fixture_trips_exactly_its_rule(rule_id):
    path = os.path.join(FIXTURES, f"{rule_id.lower()}_bad.py")
    findings = _lint_file(path)
    assert findings, f"{rule_id}: bad fixture produced no findings"
    assert {f.rule for f in findings} == {rule_id}, (
        f"{rule_id}: bad fixture tripped "
        f"{sorted({f.rule for f in findings})}"
    )
    assert len(findings) == EXPECTED_COUNTS[rule_id], (
        f"{rule_id}: expected {EXPECTED_COUNTS[rule_id]} finding(s), "
        f"got {[(f.line, f.message) for f in findings]}"
    )


@pytest.mark.parametrize("rule_id", TRACE_RULES)
def test_good_fixture_is_clean(rule_id):
    path = os.path.join(FIXTURES, f"{rule_id.lower()}_good.py")
    findings = _lint_file(path)
    assert not findings, (
        f"{rule_id}: near-miss fixture produced "
        f"{[(f.rule, f.line, f.message) for f in findings]}"
    )


# -- engine satellite: bound-method / partial thread-target resolution ------


def test_bound_method_thread_targets_resolve_as_roots():
    # engine regression (this PR): Thread(target=self._drain) and
    # Thread(target=functools.partial(self._bump, 2)) must resolve the
    # BOUND METHOD as an analyzable root scope; without it the entry
    # fixpoint concludes both always run under the lock (their only
    # in-class callers hold it) and GL501 stays silent
    findings = _lint_file(os.path.join(FIXTURES, "engine_thread_bad.py"))
    assert {f.rule for f in findings} == {"GL501"}
    assert len(findings) == 2  # _drain's store + _bump's aug-store
    assert not _lint_file(os.path.join(FIXTURES, "engine_thread_good.py"))


def test_pragma_suppresses_trace_findings():
    src = textwrap.dedent(
        """\
        import threading
        import time


        class P:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def tick(self):
                with self._lock:
                    self.n += 1
                    time.sleep(0.01)  # graftlint: disable=GL503 deliberate
        """
    )
    assert _trace(src) == []
    _, n = lint_source(src, path="pkg/mod.py", pack="trace")
    assert n == 1  # counted as suppressed, not silently dropped


# -- mutation kill-checks ----------------------------------------------------
# A scheduler-shaped class that lints CLEAN; each mutation below is the
# exact concurrency bug class the rollout protects against, proven
# caught statically (lint_source only -- nothing executes).

SCHED = textwrap.dedent(
    """\
    import threading
    from jax import jit


    class MiniScheduler:
        def __init__(self, step_fn):
            self._lock = threading.Lock()
            self._gate = threading.Lock()
            self._step_fn = jit(step_fn)
            self._asks = []
            self.dispatch_count = 0

        def submit(self, req):
            with self._lock:
                with self._gate:
                    self._asks.append(req)

        def counters(self):
            with self._lock:
                return {"dispatched": self.dispatch_count}

        def step(self):
            with self._lock:
                with self._gate:
                    picked = list(self._asks)
                    self._asks.clear()
                self.dispatch_count += 1
            out = self._step_fn(picked)
            return out
    """
)


def test_mutation_base_is_clean():
    assert _trace(SCHED) == []


def test_mutation_deleted_lock_guard_trips_gl501():
    mutant = SCHED.replace(
        "    def submit(self, req):\n"
        "        with self._lock:\n"
        "            with self._gate:\n"
        "                self._asks.append(req)",
        "    def submit(self, req):\n"
        "        self._asks.append(req)",
    )
    assert mutant != SCHED
    findings = _trace(mutant)
    assert "GL501" in {f.rule for f in findings}
    assert any("_asks" in f.message for f in findings)


def test_mutation_swapped_acquisition_sites_trips_gl502():
    mutant = SCHED.replace(
        "    def step(self):\n"
        "        with self._lock:\n"
        "            with self._gate:",
        "    def step(self):\n"
        "        with self._gate:\n"
        "            with self._lock:",
    )
    assert mutant != SCHED
    findings = _trace(mutant)
    assert "GL502" in {f.rule for f in findings}


def test_mutation_dispatch_moved_under_lock_trips_gl503():
    mutant = SCHED.replace(
        "            self.dispatch_count += 1\n"
        "        out = self._step_fn(picked)\n",
        "            self.dispatch_count += 1\n"
        "            out = self._step_fn(picked)\n",
    )
    assert mutant != SCHED
    findings = _trace(mutant)
    assert {f.rule for f in findings} == {"GL503"}
    assert "jitted dispatch" in findings[0].message


# -- the runtime lockdep sanitizer ------------------------------------------


def test_lockdep_consistent_order_is_silent():
    from hyperopt_tpu.analysis.lockdep import LockDep

    dep = LockDep()
    a = dep.wrap(threading.Lock(), "a")
    b = dep.wrap(threading.Lock(), "b")
    for _ in range(3):
        with a:
            with b:
                pass
    assert dep.inversions == 0 and not dep.errors


def test_lockdep_inversion_raises_and_releases():
    from hyperopt_tpu.analysis.lockdep import LockDep, LockOrderError

    dep = LockDep()
    a = dep.wrap(threading.Lock(), "a")
    b = dep.wrap(threading.Lock(), "b")
    with a:
        with b:
            pass
    with pytest.raises(LockOrderError):
        with b:
            with a:
                pass
    assert dep.inversions == 1
    # the failed acquisition must NOT leak the lock: both reacquire
    with a:
        pass
    with b:
        pass


def test_lockdep_rlock_reentrancy_records_once():
    from hyperopt_tpu.analysis.lockdep import LockDep

    dep = LockDep()
    r = dep.wrap(threading.RLock(), "r")
    with r:
        with r:  # re-entrant: no self-edge, no double bookkeeping
            pass
        assert dep._stack() == ["r"]
    assert dep._stack() == []


def test_lockdep_condition_wait_keeps_stack_exact():
    from hyperopt_tpu.analysis.lockdep import LockDep

    dep = LockDep()
    traced = dep.wrap(threading.RLock(), "sched")
    cond = threading.Condition(traced)
    done = []

    def waiter():
        with cond:
            while not done:
                cond.wait(timeout=5.0)

    t = threading.Thread(target=waiter)
    t.start()
    with cond:
        done.append(True)
        cond.notify_all()
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert dep.inversions == 0 and not dep.errors
