"""graftwire (GL6xx) rule-level pins: a bad/good fixture pair per rule
with exact finding counts, pragma-suppression counting, the CLI pack
selection/exit contract, and the three zero-test-execution mutation
kill-checks over the REAL repo sources.

The fixtures are single-file miniature universes fed straight to
:func:`~hyperopt_tpu.analysis.wire.analyze` wearing whatever role hats
the rule needs (server, client, seam, faults, durable, tests); the
mutation checks feed :func:`check_wire` the real files with ONE seam
textually broken and assert the named finding appears -- no server is
started, no test is executed."""

import json
import os

import pytest

from hyperopt_tpu.analysis.wire import analyze, check_wire

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")


def _read(name):
    with open(os.path.join(FIXTURES, name)) as f:
        return f.read()


def _real(rel):
    with open(os.path.join(REPO, rel)) as f:
        return f.read()


def _roles_for(rule, src, kind):
    """The role hats each fixture universe wears.  GL604's good twin
    doubles as its own arming test; its bad twin deliberately ships
    with NO test evidence (that absence IS the finding)."""
    path = "fixture.py"
    if rule == "GL603":
        return {"exceptions": {path: src}, "reply_seam": {path: src}}
    if rule == "GL604":
        roles = {"faults": {path: src}}
        if kind == "good":
            roles["tests"] = {"test_fixture.py": src}
        return roles
    if rule == "GL605":
        return {"durable": {path: src}}
    return {"server": {path: src}, "clients": {path: src}}


# rule -> exact finding count its bad fixture must trip (GL602 needs a
# two-step manifest build and has its own test below)
EXPECTED_COUNTS = {
    "GL601": 3,
    "GL603": 1,
    "GL604": 2,
    "GL605": 1,
    "GL606": 1,
}


@pytest.mark.parametrize("rule", sorted(EXPECTED_COUNTS))
def test_bad_fixture_trips_exactly_its_rule(rule):
    src = _read(f"{rule.lower()}_bad.py")
    findings, _, _ = analyze(**_roles_for(rule, src, "bad"))
    assert [f.rule for f in findings] == [rule] * EXPECTED_COUNTS[rule], (
        findings
    )


@pytest.mark.parametrize("rule", sorted(EXPECTED_COUNTS) + ["GL602"])
def test_good_fixture_is_clean(rule):
    src = _read(f"{rule.lower()}_good.py")
    findings, _, _ = analyze(**_roles_for(rule, src, "good"))
    assert findings == [], findings


def test_gl601_names_each_asymmetry():
    findings, _, _ = analyze(
        **_roles_for("GL601", _read("gl601_bad.py"), "bad")
    )
    msgs = " | ".join(f.message for f in findings)
    assert "'frobnicate'" in msgs  # client op nothing handles
    assert "no client or test caller" in msgs  # handler nothing calls
    assert "not by the router front" in msgs  # global-op asymmetry


def test_gl602_contract_drift_pair():
    """Drift is measured against a manifest pinned from the GOOD twin:
    the bad twin renames ask's ``vals`` field and drops the ``best``
    arm the manifest still pins (a stale row) -- both field-level."""
    good = _roles_for("GL602", _read("gl602_good.py"), "good")
    bad = _roles_for("GL602", _read("gl602_bad.py"), "bad")
    _, _, contracts = analyze(**good)
    findings, stats, _ = analyze(contracts=contracts, **good)
    assert findings == [] and stats["contract_drift"] == 0
    findings, stats, _ = analyze(contracts=contracts, **bad)
    assert [f.rule for f in findings] == ["GL602", "GL602"], findings
    msgs = " | ".join(f.message for f in findings)
    assert "'ask'" in msgs and "'vals'" in msgs and "'values'" in msgs
    assert "'best'" in msgs and "no longer dispatches" in msgs
    assert stats["contract_drift"] == 2


def test_pragma_suppresses_wire_findings():
    src = _read("gl606_bad.py").replace(
        "def _handle_request(service, req):",
        "def _handle_request(service, req):  "
        "# graftlint: disable=GL606 fixture-only refusal hint",
    )
    findings, stats, _ = analyze(
        server={"fixture.py": src}, clients={"fixture.py": src}
    )
    assert findings == []
    assert stats["n_suppressed"] == 1


# ---------------------------------------------------------------------------
# mutation kill-checks: break ONE real seam textually, run only the
# static checker, and the named finding must appear -- zero test
# execution, the whole point of the pack
# ---------------------------------------------------------------------------


def test_mutation_deleted_tell_handler_trips_gl601():
    rel = "hyperopt_tpu/serve/service.py"
    src = _real(rel)
    mutant = src.replace('if op == "tell":', 'if op == "tell_disabled":')
    assert mutant != src
    res = check_wire(root=REPO, sources={rel: mutant})
    hits = [
        f for f in res.findings
        if f.rule == "GL601" and "'tell'" in f.message
    ]
    assert hits, res.findings


def test_mutation_renamed_reply_field_trips_gl602():
    rel = "hyperopt_tpu/serve/service.py"
    src = _real(rel)
    mutant = src.replace(
        'return {"ok": True, "tid": tid, "vals": vals}',
        'return {"ok": True, "tid": tid, "values": vals}',
    )
    assert mutant != src
    res = check_wire(root=REPO, sources={rel: mutant})
    hits = [
        f for f in res.findings
        if f.rule == "GL602" and "'ask'" in f.message
        and "'vals'" in f.message and "'values'" in f.message
    ]
    assert hits, res.findings


def test_mutation_dropped_reply_error_trips_gl603():
    rel = "hyperopt_tpu/client.py"
    src = _real(rel)
    mutant = src.replace('    "StudyPoisoned": StudyPoisoned,\n', '')
    assert mutant != src
    res = check_wire(root=REPO, sources={rel: mutant})
    hits = [
        f for f in res.findings
        if f.rule == "GL603" and "StudyPoisoned" in f.message
    ]
    assert hits, res.findings


def test_unmutated_repo_is_wire_clean():
    res = check_wire(root=REPO)
    assert res.clean, res.findings
    assert res.crash_points_total > 0
    assert res.crash_points_armed == res.crash_points_total


# ---------------------------------------------------------------------------
# the CLI contract: pack selection, exit codes, cwd-independence
# ---------------------------------------------------------------------------


def test_cli_wire_exit_codes(tmp_path, monkeypatch, capsys):
    from hyperopt_tpu.analysis import wire as wire_mod
    from hyperopt_tpu.analysis.cli import main

    monkeypatch.chdir(REPO)
    assert main(["--wire"]) == 0
    assert main(["--ir", "--wire"]) == 2
    assert main(["--trace", "--wire"]) == 2
    assert main(["--update-contracts"]) == 2  # needs --ir or --wire
    capsys.readouterr()
    # an unreadable manifest is a usage error, never a traceback
    garbage = tmp_path / "wire_contracts.json"
    garbage.write_text("{not json")
    assert main(["--wire", "--contracts", str(garbage)]) == 2
    # a drifted manifest is findings
    payload = wire_mod.load_contracts(
        os.path.join(REPO, "wire_contracts.json")
    )
    payload["fronts"]["service"]["ask"] = ["ok"]
    drifted = tmp_path / "drifted.json"
    drifted.write_text(json.dumps(payload))
    assert main(["--wire", "--contracts", str(drifted)]) == 1
    capsys.readouterr()


def test_cli_wire_findings_identical_from_any_cwd(monkeypatch, capsys):
    from hyperopt_tpu.analysis.cli import main

    monkeypatch.chdir(REPO)
    assert main(["--wire", "--format", "json"]) == 0
    here = json.loads(capsys.readouterr().out)
    monkeypatch.chdir("/")
    assert main(["--wire", "--format", "json", "--root", REPO]) == 0
    there = json.loads(capsys.readouterr().out)
    assert here == there
